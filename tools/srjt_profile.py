#!/usr/bin/env python
"""Query-profile store CLI: list / show / diff persisted query profiles.

The reading half of ``utils/profile.py`` (docs/OBSERVABILITY.md): the
engine writes one compact JSON profile per query into ``SRJT_PROFILE_DIR``;
this tool renders the store without touching devices — pure JSON over the
on-disk ring, safe to run anywhere the directory is mounted.

Usage::

    python tools/srjt_profile.py list      [--dir DIR]
    python tools/srjt_profile.py show      [--dir DIR] [PATH|-1]
    python tools/srjt_profile.py diff      [--dir DIR] [BASE CAND]
    python tools/srjt_profile.py decisions [--dir DIR] [PATH|-1]
    python tools/srjt_profile.py slo       [--dir DIR] [--slo-ms SPEC]

``diff`` with no positional arguments picks the two newest profiles
sharing a plan fingerprint (the cross-run EXPLAIN ANALYZE comparison);
with explicit paths it diffs exactly those.  ``slo`` renders per-source-
fingerprint burn rates against the ``SRJT_SLO_MS`` objectives (override
with ``--slo-ms``), evaluated from the stored history by
``utils/blackbox.py``.  Exit code 0 on success, 2 on usage errors (empty
store, no fingerprint pair, no objectives declared).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from spark_rapids_jni_tpu.utils import profile  # noqa: E402


def _dir_of(args) -> str:
    d = args.dir or profile.config.profile_dir
    if not d:
        print("profile store dir not set (use --dir or SRJT_PROFILE_DIR)",
              file=sys.stderr)
        raise SystemExit(2)
    return d


def cmd_list(args) -> int:
    d = _dir_of(args)
    paths = profile.list_profiles(d)
    for p in paths:
        try:
            prof = profile.read(p)
        except (OSError, ValueError) as e:
            print(f"{os.path.basename(p)}  <unreadable: {e}>")
            continue
        nex = len(prof.get("exchanges", ()))
        print(f"{os.path.basename(p)}  name={prof.get('name', '')!r} "
              f"wall={prof.get('wall_s')}s nodes={len(prof.get('nodes', ()))} "
              f"exchanges={nex}")
    summ = profile.store_summary(d)
    print(f"-- {summ['profiles']} profiles, "
          f"top_exchange_skew={summ['top_exchange_skew']}, "
          f"chunk_latency_p99_s={summ['chunk_latency_p99_s']}")
    return 0


def _resolve(d: str, spec: str | None) -> str:
    """A path, or a negative index into the chronological store (-1 =
    newest); default newest."""
    if spec and not spec.lstrip("-").isdigit():
        return spec if os.path.sep in spec else os.path.join(d, spec)
    paths = profile.list_profiles(d)
    if not paths:
        print(f"no profiles in {d}", file=sys.stderr)
        raise SystemExit(2)
    idx = int(spec) if spec else -1
    try:
        return paths[idx]
    except IndexError:
        print(f"index {idx} out of range ({len(paths)} profiles)",
              file=sys.stderr)
        raise SystemExit(2)


def cmd_show(args) -> int:
    path = _resolve(_dir_of(args), args.path)
    print(json.dumps(profile.read(path), indent=2, sort_keys=True))
    return 0


def cmd_diff(args) -> int:
    d = _dir_of(args)
    if args.base and args.cand:
        base = _resolve(d, args.base)
        cand = _resolve(d, args.cand)
    else:
        # newest pair sharing a fingerprint: the cross-run comparison
        paths = profile.list_profiles(d)
        by_fp: dict[str, list] = {}
        for p in paths:
            try:
                fp = profile.read(p).get("fingerprint", "")
            except (OSError, ValueError):
                continue
            by_fp.setdefault(fp, []).append(p)
        pair = None
        for p in reversed(paths):  # newest fingerprint with >= 2 runs wins
            fp = next((f for f, ps in by_fp.items() if p in ps), "")
            if len(by_fp.get(fp, ())) >= 2:
                pair = by_fp[fp][-2:]
                break
        if pair is None:
            print("no two profiles share a fingerprint; pass BASE CAND "
                  "explicitly", file=sys.stderr)
            return 2
        base, cand = pair
    d_out = profile.diff(base, cand)
    if args.json:
        print(json.dumps(d_out, indent=2, sort_keys=True))
    else:
        print(profile.render_diff(d_out))
    return 0


def cmd_decisions(args) -> int:
    """Render one profile's optimizer decision ledger, scored against the
    run's actuals: the EXPLAIN footer, replayable after the fact."""
    path = _resolve(_dir_of(args), args.path)
    prof = profile.read(path)
    dec = prof.get("decisions") or []
    print(f"{os.path.basename(path)}  name={prof.get('name', '')!r} "
          f"decisions={len(dec)}")
    if not dec:
        print("  (no decisions recorded — pre-ledger profile or "
              "single-device plan with no rewrites)")
        return 0
    for d in dec:
        bits = [d.get("kind", "?")]
        if d.get("path"):
            bits.append(f"path={d['path']}")
        if "triggered" in d:
            # adaptive (runtime) entry: show the verdict and the measured
            # value that fired or declined it, then before -> after
            bits.append("triggered=yes" if d.get("triggered")
                        else "triggered=no")
        for k in ("side", "how", "exchange", "inner", "n"):
            if d.get(k) is not None:
                bits.append(f"{k}={d[k]}")
        if d.get("keys"):
            bits.append("keys=" + ",".join(map(str, d["keys"])))
        if d.get("aggs"):
            bits.append("aggs=" + ",".join(map(str, d["aggs"])))
        if d.get("before") is not None and d.get("after") is not None:
            bits.append(f"{d['before']}->{d['after']}")
        if "measured_rows" in d:
            bits.append(f"measured_rows={d['measured_rows']}")
        if "measured_skew" in d:
            bits.append(f"measured_skew={d['measured_skew']:.2f}")
        if d.get("post_skew") is not None:
            bits.append(f"post_skew={d['post_skew']:.2f}")
        if d.get("hot_devices"):
            bits.append("hot_devices=" + ",".join(map(str,
                                                      d["hot_devices"])))
        if d.get("combined_rows") is not None:
            bits.append(f"combined_rows={d['combined_rows']}")
        if "est_before" in d:
            bits.append(f"est_before={d['est_before']}")
        if "est_rows" in d:
            bits.append(f"est={d['est_rows'] if d['est_rows'] is not None else '?'}")
        if d.get("choice"):
            bits.append(f"choice={d['choice']}")
        if d.get("prior_kind"):
            bits.append(f"prior_kind={d['prior_kind']}")
        if d.get("threshold") is not None:
            bits.append(f"threshold={d['threshold']}")
        if "actual_rows" in d:
            bits.append(f"actual={d['actual_rows']}")
        if d.get("q_error") is not None:
            bits.append(f"q_error={d['q_error']:.2f}")
        flag = "  ! MISESTIMATE" if d.get("misestimate") else ""
        if d.get("verify_rejected"):
            flag += "  ! VERIFY_REJECTED"
        print("  " + " ".join(bits) + flag)
    return 0


def cmd_slo(args) -> int:
    """Per-source-fingerprint SLO burn table from profile-store history."""
    d = _dir_of(args)
    from spark_rapids_jni_tpu.utils import blackbox
    from spark_rapids_jni_tpu.utils.config import config
    if args.slo_ms is not None:
        config.slo_ms = args.slo_ms  # session-local; config.refresh resets
    rep = blackbox.slo_report(d)
    if not rep["enabled"]:
        print("no SLO objectives declared (set SRJT_SLO_MS or --slo-ms, "
              "e.g. '500' or '500,ab12cd34ef56=200')", file=sys.stderr)
        return 2
    print(f"SLO objectives: default={rep['default_ms']}ms "
          f"({len(rep['entries'])} fingerprint(s) with history)")
    for e in rep["entries"]:
        print(f"  {e['fingerprint']}  objective={e['objective_ms']}ms "
              f"runs={e['runs']} breaches={e['breaches']} "
              f"(errors={e['errors']}) worst={e['worst_ms']}ms "
              f"burn_rate={e['burn_rate']}")
    if not rep["entries"]:
        print("  (no stored runs match the objectives)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srjt_profile", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--dir", default=None,
                    help="profile store directory (default SRJT_PROFILE_DIR)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="one line per stored profile + store summary")
    p_show = sub.add_parser("show", help="pretty-print one profile")
    p_show.add_argument("path", nargs="?", default=None,
                        help="path, filename, or negative index (-1 = newest)")
    p_diff = sub.add_parser("diff",
                            help="per-node deltas between two runs")
    p_diff.add_argument("base", nargs="?", default=None)
    p_diff.add_argument("cand", nargs="?", default=None)
    p_diff.add_argument("--json", action="store_true",
                        help="emit the structured diff instead of the table")
    p_dec = sub.add_parser(
        "decisions", help="optimizer decision ledger of one profile, "
                          "scored against the run's actuals")
    p_dec.add_argument("path", nargs="?", default=None,
                       help="path, filename, or negative index (-1 = newest)")
    p_slo = sub.add_parser(
        "slo", help="per-fingerprint SLO burn rates from stored history")
    p_slo.add_argument("--slo-ms", default=None,
                       help="objectives spec overriding SRJT_SLO_MS "
                            "(default_ms[,fp_prefix=ms,...])")
    args = ap.parse_args(argv)
    return {"list": cmd_list, "show": cmd_show, "diff": cmd_diff,
            "decisions": cmd_decisions, "slo": cmd_slo}[args.cmd](args)


if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:
        # downstream pager/head closed the pipe mid-print: normal exit,
        # but devnull stdout first so interpreter teardown can't re-raise
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)
