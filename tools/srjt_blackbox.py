#!/usr/bin/env python
"""Flight-recorder bundle CLI: list / show / grep post-mortem bundles.

The reading half of ``utils/blackbox.py`` (docs/OBSERVABILITY.md): on a
classified error, timeout, cancel, or degradation the engine writes one
post-mortem bundle into ``SRJT_BLACKBOX_DIR``; this tool renders the
bundle ring without touching devices — pure JSON over the on-disk files,
safe to run anywhere the directory is mounted.

Usage::

    python tools/srjt_blackbox.py list  [--dir DIR]
    python tools/srjt_blackbox.py show  [--dir DIR] [PATH|-1] [--ring]
    python tools/srjt_blackbox.py grep  [--dir DIR] TRACE_ID

``show`` defaults to the newest bundle; ``--ring`` appends the captured
flight-recorder tail as one event per line.  ``grep`` matches bundles
whose trace_id starts with the given hex prefix (the id a failed client
call carries as ``e.trace_id``).  Exit code 0 on success (grep: at least
one match), 1 on no match, 2 on usage errors (no directory, empty ring,
bad index).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from spark_rapids_jni_tpu.utils import blackbox  # noqa: E402
from spark_rapids_jni_tpu.utils.config import config  # noqa: E402


def _dir_of(args) -> str:
    d = args.dir or config.blackbox_dir
    if not d:
        print("bundle dir not set (use --dir or SRJT_BLACKBOX_DIR)",
              file=sys.stderr)
        raise SystemExit(2)
    return d


def _describe(path: str) -> str:
    try:
        doc = blackbox.read_bundle(path)
    except (OSError, ValueError) as e:
        return f"{os.path.basename(path)}  <unreadable: {e}>"
    err = doc.get("error") or {}
    q = doc.get("query") or {}
    bits = [os.path.basename(path),
            f"trace={doc.get('trace_id', '')[:12] or '?'}",
            f"reason={doc.get('reason', '?')}"]
    if err:
        bits.append(f"error={err.get('type', '?')}/{err.get('kind', '?')}")
    if q:
        bits.append(f"query={q.get('name', '')!r} wall={q.get('wall_s')}s")
    bits.append(f"ring={len(doc.get('ring') or ())}ev")
    return "  ".join(bits)


def cmd_list(args) -> int:
    d = _dir_of(args)
    paths = blackbox.list_bundles(d)
    for p in paths:
        print(_describe(p))
    print(f"-- {len(paths)} bundle(s) in {d}")
    return 0


def _resolve(d: str, spec: str | None) -> str:
    """A path, or a negative index into the chronological ring (-1 =
    newest); default newest."""
    if spec and not spec.lstrip("-").isdigit():
        return spec if os.path.sep in spec else os.path.join(d, spec)
    paths = blackbox.list_bundles(d)
    if not paths:
        print(f"no bundles in {d}", file=sys.stderr)
        raise SystemExit(2)
    idx = int(spec) if spec else -1
    try:
        return paths[idx]
    except IndexError:
        print(f"index {idx} out of range ({len(paths)} bundles)",
              file=sys.stderr)
        raise SystemExit(2)


def cmd_show(args) -> int:
    path = _resolve(_dir_of(args), args.path)
    doc = blackbox.read_bundle(path)
    ring = doc.pop("ring", [])
    print(json.dumps(doc, indent=2, sort_keys=True, default=str))
    if args.ring:
        print(f"-- flight-recorder tail ({len(ring)} events):")
        for ev in ring:
            print("  " + json.dumps(ev, sort_keys=True, default=str))
    return 0


def cmd_grep(args) -> int:
    """Bundles whose trace_id starts with the given hex prefix — the
    client-to-server join: paste ``e.trace_id`` from a failed call."""
    d = _dir_of(args)
    want = args.trace_id.strip().lower()
    if not want:
        print("empty trace id", file=sys.stderr)
        return 2
    hits = 0
    for p in blackbox.list_bundles(d):
        try:
            doc = blackbox.read_bundle(p)
        except (OSError, ValueError):
            continue
        if str(doc.get("trace_id", "")).lower().startswith(want):
            hits += 1
            print(_describe(p))
    if not hits:
        print(f"no bundle matches trace {want!r}", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srjt_blackbox", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--dir", default=None,
                    help="bundle directory (default SRJT_BLACKBOX_DIR)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="one line per stored bundle")
    p_show = sub.add_parser("show", help="pretty-print one bundle")
    p_show.add_argument("path", nargs="?", default=None,
                        help="path, filename, or negative index "
                             "(-1 = newest)")
    p_show.add_argument("--ring", action="store_true",
                        help="append the flight-recorder tail, one event "
                             "per line")
    p_grep = sub.add_parser("grep",
                            help="bundles matching a trace-id prefix")
    p_grep.add_argument("trace_id", help="hex trace id (prefix ok)")
    args = ap.parse_args(argv)
    return {"list": cmd_list, "show": cmd_show,
            "grep": cmd_grep}[args.cmd](args)


if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:
        # downstream pager/head closed the pipe mid-print: normal exit,
        # but devnull stdout first so interpreter teardown can't re-raise
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)
