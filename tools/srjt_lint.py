#!/usr/bin/env python
"""Repo lint for the engine's static invariants (docs/ANALYSIS.md pass 3).

Four stdlib-``ast`` rules over ``spark_rapids_jni_tpu/``:

- **traced-host-op** — no ``.item()`` / ``float()`` / ``bool()`` / ``int()``
  / ``np.asarray`` / ``.tolist()`` / ``jax.device_get`` /
  ``.block_until_ready()`` inside the segment-traced code paths
  (``segment._build_fn`` / ``segment._probe_join_node`` /
  ``executor._eval_expr``): any of these concretizes a tracer, turning the
  zero-sync fused chunk program into a per-chunk host round-trip.
- **config-env-read** — ``os.environ`` / ``os.getenv`` only in
  ``utils/config.py``; everything else reads the ``config`` singleton so
  ``refresh()`` stays the one switchboard.  Pre-existing sites are
  grandfathered in ``ci/lint-baseline.json``.
- **host-sync-site** — every ``metrics.host_sync(...)`` call site must
  carry a ``label=`` that is a literal member of ``verify.SYNC_WHITELIST``:
  adding a fourth deliberate sync means adding it to the whitelist, in
  one reviewable diff.
- **bare-except** — no bare ``except:`` under ``bridge/`` / ``engine/`` /
  ``parallel/``: the recovery layer (engine/recovery.py) dispatches on the
  ``utils/errors`` taxonomy, and a bare catch swallows cancellation and
  resource exhaustion indistinguishably.

Plus two import-time passes:

- **dispatch exhaustiveness** — every class in ``plan._NODE_TYPES`` must be
  registered in ``executor._EXEC_DISPATCH``, ``explain._DESCRIBE``, and
  ``verify._INFER`` (a new plan node can't silently miss a layer).
- **``--segments``** — build the bench smoke warehouse in a tempdir, lower
  the optimized q5-lite + chunked plans' fused segments to jaxprs
  (``verify.lint_plan_artifacts``, nothing executes) and assert the static
  sync budget is EXACTLY the three whitelisted host syncs.  ``--full``
  extends the plan set with the bench join + top-k shapes (nightly).

Usage::

    python tools/srjt_lint.py --baseline ci/lint-baseline.json
    python tools/srjt_lint.py --segments --baseline ci/lint-baseline.json
    python tools/srjt_lint.py --write-baseline   # regenerate the baseline

Violations not covered by the baseline exit nonzero.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "spark_rapids_jni_tpu"

#: file (repo-relative) -> function names whose bodies are jax-traced
TRACED_FUNCS = {
    f"{PKG}/engine/segment.py": {"_build_fn", "_probe_join_node"},
    f"{PKG}/engine/executor.py": {"_eval_expr"},
}

#: attribute calls that concretize a tracer / pull data to host
#: subtrees where a bare `except:` is a lint violation — the failure-domain
#: hardening (engine/recovery.py) depends on every catch being classifiable
_NO_BARE_EXCEPT = (f"{PKG}/bridge/", f"{PKG}/engine/", f"{PKG}/parallel/")

_HOST_ATTR_CALLS = {"item", "tolist", "block_until_ready"}
#: builtin casts that concretize when applied to a traced array
_HOST_NAME_CALLS = {"float", "int", "bool"}


def _violation(code: str, path: str, line: int, detail: str) -> dict:
    return {"code": code, "file": path, "line": line, "detail": detail}


def baseline_key(v: dict) -> str:
    # line numbers excluded so unrelated edits above a grandfathered
    # site don't churn the baseline
    return f"{v['code']}|{v['file']}|{v['detail']}"


class _FileLint(ast.NodeVisitor):
    def __init__(self, relpath: str, whitelist: tuple):
        self.relpath = relpath
        self.traced = TRACED_FUNCS.get(relpath, set())
        self.whitelist = whitelist
        self.out: list = []
        self._traced_depth = 0

    def visit_FunctionDef(self, node):
        entered = node.name in self.traced
        if entered:
            self._traced_depth += 1
        self.generic_visit(node)
        if entered:
            self._traced_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_traced_call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _HOST_ATTR_CALLS:
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    f".{fn.attr}() in traced code"))
            elif fn.attr in ("asarray", "array") and \
                    isinstance(fn.value, ast.Name) and fn.value.id == "np":
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    f"np.{fn.attr}() in traced code"))
            elif fn.attr == "device_get":
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    "jax.device_get() in traced code"))
        elif isinstance(fn, ast.Name) and fn.id in _HOST_NAME_CALLS:
            if not (node.args and isinstance(node.args[0], ast.Constant)):
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    f"{fn.id}() cast in traced code"))

    def _check_host_sync(self, node: ast.Call) -> None:
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "host_sync"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "metrics"):
            return
        labels = [kw.value.value for kw in node.keywords
                  if kw.arg == "label"
                  and isinstance(kw.value, ast.Constant)]
        if not labels or labels[0] not in self.whitelist:
            self.out.append(_violation(
                "host-sync-site", self.relpath, node.lineno,
                f"metrics.host_sync label {labels[0]!r} not in "
                f"SYNC_WHITELIST" if labels else
                "metrics.host_sync without a whitelisted literal label="))

    def visit_Call(self, node: ast.Call) -> None:
        if self._traced_depth:
            self._check_traced_call(node)
        self._check_host_sync(node)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.relpath != f"{PKG}/utils/config.py" and \
                isinstance(node.value, ast.Name) and node.value.id == "os" \
                and node.attr in ("environ", "getenv"):
            self.out.append(_violation(
                "config-env-read", self.relpath, node.lineno,
                f"os.{node.attr} outside utils/config.py"))
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        # failure-domain code must classify what it catches (utils/errors
        # taxonomy): a bare `except:` swallows cancellation and OOM alike,
        # so none are allowed in the recovery-bearing subtrees
        if node.type is None and self.relpath.startswith(_NO_BARE_EXCEPT):
            self.out.append(_violation(
                "bare-except", self.relpath, node.lineno,
                "bare `except:` in failure-domain code (catch a type; "
                "see utils/errors taxonomy)"))
        self.generic_visit(node)


def ast_pass(whitelist: tuple) -> list:
    violations: list = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, PKG)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, REPO)
            with open(full) as f:
                tree = ast.parse(f.read(), filename=rel)
            lint = _FileLint(rel, whitelist)
            lint.visit(tree)
            violations += lint.out
    return violations


def dispatch_pass() -> list:
    import importlib

    from spark_rapids_jni_tpu.engine import executor, explain, plan

    # engine/__init__ re-exports the verify() function under the submodule's
    # name, so resolve the module through importlib
    verify_mod = importlib.import_module("spark_rapids_jni_tpu.engine.verify")
    tables = (("executor._EXEC_DISPATCH", executor._EXEC_DISPATCH),
              ("explain._DESCRIBE", explain._DESCRIBE),
              ("verify._INFER", verify_mod._INFER))
    out: list = []
    for cls in plan._NODE_TYPES.values():
        for name, table in tables:
            if cls not in table:
                out.append(_violation(
                    "dispatch-missing", f"{PKG}/engine/plan.py", 0,
                    f"{cls.__name__} not registered in {name}"))
    for name, table in tables:
        for cls in table:
            if cls not in plan._NODE_TYPES.values():
                out.append(_violation(
                    "dispatch-missing", f"{PKG}/engine/plan.py", 0,
                    f"{name} entry {cls.__name__} is not a plan node"))
    return out


#: the smoke pair's exact budget: q5's one fused map segment + the chunked
#: plan's streamed agg (sizing + compaction) — 3 syncs, one per whitelisted
#: site (docs/OBSERVABILITY.md's "3 deliberate host syncs")
SMOKE_EXPECTED_SYNCS = 3


def _full_plans(tmp: str):
    """The nightly extension: bench-shaped join + top-k plans."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.engine import (Aggregate, Filter, Join, Limit,
                                             Scan, Sort, col, lit)
    rng = np.random.default_rng(11)
    n = 4000
    fact = os.path.join(tmp, "lint_fact.parquet")
    dim = os.path.join(tmp, "lint_dim.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 2000, n).astype(np.int64)),
        "v": pa.array(rng.uniform(-5, 50, n)),
    }), fact, row_group_size=n // 8)
    pq.write_table(pa.table({
        "dk": pa.array(np.arange(2000, dtype=np.int64)),
        "grp": pa.array((np.arange(2000) % 7).astype(np.int64)),
    }), dim)
    fscan = Scan(fact, chunk_bytes=24_000)
    join_agg = Aggregate(
        Join(Filter(fscan, (">", col("v"), lit(0.0))), Scan(dim),
             ("k",), ("dk",), "inner"),
        ("grp",), (("v", "sum"), ("v", "count")), ("total", "n"))
    topk = Limit(Sort(Scan(fact, chunk_bytes=24_000),
                      (("v", False), ("k", True))), 32)
    return {"join_agg": join_agg, "topk": topk}


def segments_pass(full: bool = False) -> list:
    import tempfile

    import numpy as np

    sys.path.insert(0, REPO)
    import bench
    from spark_rapids_jni_tpu.engine import optimize
    from spark_rapids_jni_tpu.engine.verify import (check_sync_budget,
                                                    lint_plan_artifacts)
    out: list = []
    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.default_rng(7)
        bench._pipeline_warehouse(tmp, 4000, rng)
        q5, chunked = bench._pipeline_plans(tmp, 48_000)
        plans = {"q5": optimize(q5), "chunked": optimize(chunked)}
        entries, bad = check_sync_budget(list(plans.values()))
        smoke_syncs = sum(e["count"] for e in entries)
        for e in bad:
            out.append(_violation("unwhitelisted-host-sync", "<smoke>", 0,
                                  f"{e['site']} at {e['path']}"))
        if smoke_syncs != SMOKE_EXPECTED_SYNCS:
            out.append(_violation(
                "sync-budget-mismatch", "<smoke>", 0,
                f"smoke plans budget {smoke_syncs} syncs, expected "
                f"{SMOKE_EXPECTED_SYNCS} "
                f"({[(e['site'], e['count']) for e in entries]})"))
        if full:
            plans.update({k: optimize(p)
                          for k, p in _full_plans(tmp).items()})
        for name, plan in plans.items():
            rep = lint_plan_artifacts(plan)
            for v in rep["violations"]:
                out.append(_violation(v["code"], f"<plan:{name}>", 0,
                                      f"{v.get('path', '?')}: "
                                      f"{v.get('detail', '')}"))
            nseg = sum(1 for s in rep["segments"] if "skipped" not in s)
            print(f"srjt-lint: {name}: {nseg} segment artifact(s) linted, "
                  f"{len(rep['violations'])} violation(s)")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of grandfathered violation keys")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline (default ci/lint-baseline.json)"
                         " from the current violations")
    ap.add_argument("--segments", action="store_true",
                    help="also jaxpr-lint the smoke plans' fused segments")
    ap.add_argument("--full", action="store_true",
                    help="with --segments: extend to the bench join/top-k "
                         "plan shapes")
    args = ap.parse_args(argv)

    # import-time passes need the engine importable without a device
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, REPO)
    from spark_rapids_jni_tpu.engine.verify import SYNC_WHITELIST

    violations = ast_pass(tuple(SYNC_WHITELIST))
    violations += dispatch_pass()
    if args.segments or args.full:
        violations += segments_pass(full=args.full)

    baseline_path = args.baseline or os.path.join(REPO, "ci",
                                                  "lint-baseline.json")
    if args.write_baseline:
        keys = sorted({baseline_key(v) for v in violations})
        with open(baseline_path, "w") as f:
            json.dump({"grandfathered": keys}, f, indent=2)
            f.write("\n")
        print(f"srjt-lint: wrote {len(keys)} baseline key(s) to "
              f"{baseline_path}")
        return 0

    grandfathered: set = set()
    if args.baseline and os.path.exists(args.baseline):
        with open(args.baseline) as f:
            grandfathered = set(json.load(f).get("grandfathered", []))

    fresh = [v for v in violations if baseline_key(v) not in grandfathered]
    old = len(violations) - len(fresh)
    for v in fresh:
        print(f"srjt-lint: {v['code']}: {v['file']}:{v['line']}: "
              f"{v['detail']}")
    print(f"srjt-lint: {len(fresh)} new violation(s), {old} grandfathered")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
