#!/usr/bin/env python
"""Repo lint for the engine's static invariants (docs/ANALYSIS.md pass 3).

Six stdlib-``ast`` rules over ``spark_rapids_jni_tpu/`` + ``tools/``:

- **traced-host-op** — no ``.item()`` / ``float()`` / ``bool()`` / ``int()``
  / ``np.asarray`` / ``.tolist()`` / ``jax.device_get`` /
  ``.block_until_ready()`` inside the segment-traced code paths
  (``segment._build_fn`` / ``segment._probe_join_node`` /
  ``executor._eval_expr``): any of these concretizes a tracer, turning the
  zero-sync fused chunk program into a per-chunk host round-trip.
- **config-env-read** — ``os.environ`` / ``os.getenv`` only in
  ``utils/config.py``; everything else reads the ``config`` singleton so
  ``refresh()`` stays the one switchboard.  Env *writes*
  (``os.environ.setdefault``/``os.environ[k] = v`` — how the CLI tools pin
  ``JAX_PLATFORMS`` before the first jax import) are exempt.  Pre-existing
  read sites are grandfathered in ``ci/lint-baseline.json``.
- **unlocked-global-write** — ahead of AQE's runtime re-planning (a second
  thread touching planner state), any write to a module-level mutable
  container (dict/list/set/deque assignments at module scope) from inside a
  function must sit under a ``with <lock>:`` block — mutating method calls
  (``append``/``update``/``setdefault``/...), subscript stores, ``del``,
  augmented assigns, and rebinds via ``global``.  Two exemptions: writes at
  module scope (import-time is single-threaded) and functions whose
  docstring carries the ``(lock held)`` convention (see faults._arm),
  which asserts the caller already owns the lock.
- **host-sync-site** — every ``metrics.host_sync(...)`` call site must
  carry a ``label=`` that is a literal member of ``verify.SYNC_WHITELIST``:
  adding a fourth deliberate sync means adding it to the whitelist, in
  one reviewable diff.
- **bare-except** — no bare ``except:`` under ``bridge/`` / ``engine/`` /
  ``parallel/`` / ``utils/`` / ``tools/``: the recovery layer
  (engine/recovery.py) dispatches on the ``utils/errors`` taxonomy, and a
  bare catch swallows cancellation and resource exhaustion
  indistinguishably.
- **unregistered-metric** — every literal metric name recorded through
  ``metrics.count/observe/gauge_set/gauge_max/time_add`` /
  ``tracing.count`` (and every literal ``node_set`` span label) must
  appear in the generated catalog ``docs/METRICS.md``; f-string names
  catalog with ``<var>`` placeholders.  A name in the catalog with no
  remaining call site flags ``stale-metric``.  Regenerate with
  ``--write-metrics`` — the catalog diff IS the metric-rename review.

Plus two import-time passes:

- **dispatch exhaustiveness** — every class in ``plan._NODE_TYPES`` must be
  registered in ``executor._EXEC_DISPATCH``, ``explain._DESCRIBE``,
  ``verify._INFER``, ``verify._NULLS`` (nullability lattice), and
  ``fuzz._ORACLE`` (pandas differential oracle) — a new plan node can't
  silently miss a layer.
- **``--segments``** — build the bench smoke warehouse in a tempdir, lower
  the optimized q5-lite + chunked plans' fused segments to jaxprs
  (``verify.lint_plan_artifacts``, nothing executes) and assert the static
  sync budget is EXACTLY the three whitelisted host syncs.  ``--full``
  extends the plan set with the bench join + top-k shapes (nightly).

Usage::

    python tools/srjt_lint.py --baseline ci/lint-baseline.json
    python tools/srjt_lint.py --segments --baseline ci/lint-baseline.json
    python tools/srjt_lint.py --write-baseline   # regenerate the baseline
    python tools/srjt_lint.py --write-metrics    # regenerate docs/METRICS.md

Violations not covered by the baseline exit nonzero.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "spark_rapids_jni_tpu"

#: file (repo-relative) -> function names whose bodies are jax-traced
TRACED_FUNCS = {
    f"{PKG}/engine/segment.py": {"_build_fn", "_probe_join_node",
                                 "_build_fused_fn", "_build_decode_fn"},
    f"{PKG}/engine/executor.py": {"_eval_expr"},
}

#: attribute calls that concretize a tracer / pull data to host
#: subtrees where a bare `except:` is a lint violation — the failure-domain
#: hardening (engine/recovery.py) depends on every catch being classifiable
_NO_BARE_EXCEPT = (f"{PKG}/bridge/", f"{PKG}/engine/", f"{PKG}/parallel/",
                   f"{PKG}/utils/", "tools/")

_HOST_ATTR_CALLS = {"item", "tolist", "block_until_ready"}
#: builtin casts that concretize when applied to a traced array
_HOST_NAME_CALLS = {"float", "int", "bool"}

#: constructors whose module-level assignment marks a name as shared
#: mutable state for the unlocked-global-write rule
_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                  "OrderedDict", "Counter", "WeakValueDictionary"}
#: method calls that mutate a container in place
_MUTATING_METHODS = {"append", "appendleft", "add", "update", "setdefault",
                     "pop", "popitem", "popleft", "clear", "extend",
                     "insert", "remove", "discard"}
#: identifier substrings that mark a `with` context as a mutual-exclusion
#: guard (threading.Lock/RLock/Condition naming conventions in this repo)
_LOCKISH = ("lock", "cond", "mutex", "_cv")
#: docstring marker asserting the caller already holds the guarding lock
_LOCK_HELD_DOC = "(lock held)"

#: registry entry points whose first argument is a metric name, and the
#: catalog kind each registers under (docs/METRICS.md)
_METRIC_FNS = {"count": "counter", "observe": "histogram",
               "gauge_set": "gauge", "gauge_max": "gauge",
               "time_add": "timer"}
#: receiver names that denote the metrics/tracing registries at call sites
#: (bridge/server.py imports the module as `_metrics`)
_METRIC_BASES = {"metrics", "_metrics", "tracing"}
#: repo-relative path of the generated metric-name catalog
METRICS_DOC = os.path.join("docs", "METRICS.md")


def _literal_metric_name(arg) -> "str | None":
    """A metric-name argument as a catalogable string: literal strings
    verbatim, f-strings with each interpolation normalized to a ``<var>``
    placeholder (so ``f"engine.errors.{kind}"`` catalogs once as
    ``engine.errors.<kind>``), fully dynamic expressions -> None
    (plumbing forwarders like ``tracing.count(name, n)`` are not call
    sites)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                inner = v.value
                if isinstance(inner, ast.Name):
                    parts.append(f"<{inner.id}>")
                elif isinstance(inner, ast.Attribute):
                    parts.append(f"<{inner.attr}>")
                else:
                    parts.append("<?>")
        return "".join(parts)
    return None


def _module_mutable_globals(tree: ast.Module) -> set:
    """Names bound at module scope to a mutable container literal/ctor."""
    names: set = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                     ast.ListComp, ast.SetComp,
                                     ast.DictComp)) or (
            isinstance(value, ast.Call) and (
                (isinstance(value.func, ast.Name)
                 and value.func.id in _MUTABLE_CTORS) or
                (isinstance(value.func, ast.Attribute)
                 and value.func.attr in _MUTABLE_CTORS)))
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and \
                    not any(s in t.id.lower() for s in _LOCKISH):
                names.add(t.id)
    return names


def _is_os_environ(node) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


def _mentions_lock(expr) -> bool:
    for n in ast.walk(expr):
        ident = n.id if isinstance(n, ast.Name) else \
            n.attr if isinstance(n, ast.Attribute) else None
        if ident is not None and \
                any(s in ident.lower() for s in _LOCKISH):
            return True
    return False


def _violation(code: str, path: str, line: int, detail: str) -> dict:
    return {"code": code, "file": path, "line": line, "detail": detail}


def baseline_key(v: dict) -> str:
    # line numbers excluded so unrelated edits above a grandfathered
    # site don't churn the baseline
    return f"{v['code']}|{v['file']}|{v['detail']}"


class _FileLint(ast.NodeVisitor):
    def __init__(self, relpath: str, whitelist: tuple,
                 mutable_globals: set = frozenset()):
        self.relpath = relpath
        self.traced = TRACED_FUNCS.get(relpath, set())
        self.whitelist = whitelist
        self.mutable_globals = mutable_globals
        self.out: list = []
        self.metric_sites: list = []  # (name, kind, relpath, line)
        self._traced_depth = 0
        self._func_depth = 0
        self._lock_depth = 0
        self._global_decls: set = set()
        self._env_writes: set = set()  # id()s of exempt os.environ nodes

    def visit_FunctionDef(self, node):
        entered = node.name in self.traced
        if entered:
            self._traced_depth += 1
        doc = ast.get_docstring(node)
        held = doc is not None and _LOCK_HELD_DOC in doc
        if held:
            self._lock_depth += 1
        self._func_depth += 1
        saved_decls = self._global_decls
        self._global_decls = set(saved_decls)
        self.generic_visit(node)
        self._global_decls = saved_decls
        self._func_depth -= 1
        if held:
            self._lock_depth -= 1
        if entered:
            self._traced_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        locked = any(_mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self._lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._lock_depth -= 1

    visit_AsyncWith = visit_With

    def visit_Global(self, node: ast.Global) -> None:
        self._global_decls.update(node.names)

    # -- unlocked-global-write ---------------------------------------------

    def _flag_global_write(self, name: str, lineno: int, how: str) -> None:
        if name not in self.mutable_globals:
            return
        if self._func_depth == 0 or self._lock_depth > 0:
            return  # import-time init / guarded by a lock context
        self.out.append(_violation(
            "unlocked-global-write", self.relpath, lineno,
            f"{how} of module global {name!r} outside a lock context "
            f"(wrap in `with <lock>:` or document `(lock held)`)"))

    def _check_store_target(self, target, lineno: int) -> None:
        if isinstance(target, ast.Subscript) and \
                isinstance(target.value, ast.Name):
            self._flag_global_write(target.value.id, lineno,
                                    "subscript store")
        elif isinstance(target, ast.Name) and \
                target.id in self._global_decls:
            self._flag_global_write(target.id, lineno, "rebind")
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_store_target(elt, lineno)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript) and _is_os_environ(t.value):
                self._env_writes.add(id(t.value))  # env WRITE: exempt
            self._check_store_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name):
                self._flag_global_write(t.value.id, node.lineno,
                                        "subscript delete")
            if isinstance(t, ast.Subscript) and _is_os_environ(t.value):
                self._env_writes.add(id(t.value))
        self.generic_visit(node)

    def _check_traced_call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _HOST_ATTR_CALLS:
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    f".{fn.attr}() in traced code"))
            elif fn.attr in ("asarray", "array") and \
                    isinstance(fn.value, ast.Name) and fn.value.id == "np":
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    f"np.{fn.attr}() in traced code"))
            elif fn.attr == "device_get":
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    "jax.device_get() in traced code"))
        elif isinstance(fn, ast.Name) and fn.id in _HOST_NAME_CALLS:
            if not (node.args and isinstance(node.args[0], ast.Constant)):
                self.out.append(_violation(
                    "traced-host-op", self.relpath, node.lineno,
                    f"{fn.id}() cast in traced code"))

    def _check_host_sync(self, node: ast.Call) -> None:
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "host_sync"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "metrics"):
            return
        labels = [kw.value.value for kw in node.keywords
                  if kw.arg == "label"
                  and isinstance(kw.value, ast.Constant)]
        if not labels or labels[0] not in self.whitelist:
            self.out.append(_violation(
                "host-sync-site", self.relpath, node.lineno,
                f"metrics.host_sync label {labels[0]!r} not in "
                f"SYNC_WHITELIST" if labels else
                "metrics.host_sync without a whitelisted literal label="))

    # -- unregistered-metric -----------------------------------------------

    def _collect_metric(self, node: ast.Call) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        if fn.attr in _METRIC_FNS and isinstance(fn.value, ast.Name) \
                and fn.value.id in _METRIC_BASES and node.args:
            name = _literal_metric_name(node.args[0])
            if name is not None:
                self.metric_sites.append(
                    (name, _METRIC_FNS[fn.attr], self.relpath, node.lineno))
        elif fn.attr == "node_set" and len(node.args) >= 2:
            label = _literal_metric_name(node.args[1])
            if label is not None:
                self.metric_sites.append(
                    (label, "span", self.relpath, node.lineno))

    def visit_Call(self, node: ast.Call) -> None:
        if self._traced_depth:
            self._check_traced_call(node)
        self._check_host_sync(node)
        self._collect_metric(node)
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and \
                    fn.attr in _MUTATING_METHODS:
                self._flag_global_write(fn.value.id, node.lineno,
                                        f".{fn.attr}() call")
            if fn.attr == "setdefault" and _is_os_environ(fn.value):
                self._env_writes.add(id(fn.value))  # env WRITE: exempt
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.relpath != f"{PKG}/utils/config.py" and \
                isinstance(node.value, ast.Name) and node.value.id == "os" \
                and node.attr in ("environ", "getenv") \
                and id(node) not in self._env_writes:
            self.out.append(_violation(
                "config-env-read", self.relpath, node.lineno,
                f"os.{node.attr} outside utils/config.py"))
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        # failure-domain code must classify what it catches (utils/errors
        # taxonomy): a bare `except:` swallows cancellation and OOM alike,
        # so none are allowed in the recovery-bearing subtrees
        if node.type is None and self.relpath.startswith(_NO_BARE_EXCEPT):
            self.out.append(_violation(
                "bare-except", self.relpath, node.lineno,
                "bare `except:` in failure-domain code (catch a type; "
                "see utils/errors taxonomy)"))
        self.generic_visit(node)


def _metric_catalog(sites: list) -> dict:
    """Aggregate (name, kind, file, line) sites into
    name -> {"kinds": set, "files": set}."""
    cat: dict = {}
    for name, kind, relpath, _line in sites:
        e = cat.setdefault(name, {"kinds": set(), "files": set()})
        e["kinds"].add(kind)
        e["files"].add(relpath)
    return cat


def _registered_metrics(doc_path: str) -> set:
    """Names from the catalog's table rows (first backticked token of
    each ``| `name` | ...`` line); prose backticks don't register."""
    names: set = set()
    if not os.path.exists(doc_path):
        return names
    with open(doc_path) as f:
        for line in f:
            if line.startswith("| `") and line.count("`") >= 2:
                names.add(line.split("`", 2)[1])
    return names


def render_metrics_doc(catalog: dict) -> str:
    lines = [
        "# Metric-name catalog",
        "",
        "Generated by `python tools/srjt_lint.py --write-metrics` from the",
        "literal names at `metrics.count` / `observe` / `gauge_set` /",
        "`gauge_max` / `time_add` / `tracing.count` / `node_set` call",
        "sites; `<var>` marks an f-string interpolation (one row per",
        "template, however many concrete names it expands to).  Do not",
        "edit by hand: a call site recording a name missing here fails",
        "the lint (`unregistered-metric`), and a row with no remaining",
        "call site fails it too (`stale-metric`) — every metric rename is",
        "one reviewable catalog diff.",
        "",
        "| name | kind | call sites |",
        "|---|---|---|",
    ]
    for name in sorted(catalog):
        e = catalog[name]
        lines.append(f"| `{name}` | {', '.join(sorted(e['kinds']))} | "
                     f"{', '.join(sorted(e['files']))} |")
    lines += ["", f"{len(catalog)} names."]
    return "\n".join(lines) + "\n"


def metrics_doc_pass(catalog: dict, doc_path: str) -> list:
    """Two-way diff of the call-site catalog against docs/METRICS.md."""
    registered = _registered_metrics(doc_path)
    rel = os.path.relpath(doc_path, REPO)
    out: list = []
    for name in sorted(set(catalog) - registered):
        site = sorted(catalog[name]["files"])[0]
        out.append(_violation(
            "unregistered-metric", site, 0,
            f"metric name `{name}` not in {rel} "
            f"(regenerate: tools/srjt_lint.py --write-metrics)"))
    for name in sorted(registered - set(catalog)):
        out.append(_violation(
            "stale-metric", rel, 0,
            f"catalog entry `{name}` has no remaining call site "
            f"(regenerate: tools/srjt_lint.py --write-metrics)"))
    return out


def ast_pass(whitelist: tuple, roots: tuple = (PKG, "tools"),
             sites_out: "list | None" = None) -> list:
    violations: list = []
    sites: list = []
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(REPO, root)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, REPO)
                with open(full) as f:
                    tree = ast.parse(f.read(), filename=rel)
                lint = _FileLint(rel, whitelist,
                                 _module_mutable_globals(tree))
                lint.visit(tree)
                violations += lint.out
                sites += lint.metric_sites
    if sites_out is not None:
        sites_out.extend(sites)
    violations += metrics_doc_pass(_metric_catalog(sites),
                                   os.path.join(REPO, METRICS_DOC))
    return violations


def dispatch_pass() -> list:
    import importlib

    from spark_rapids_jni_tpu.engine import executor, explain, plan

    # engine/__init__ re-exports the verify() function under the submodule's
    # name, so resolve the module through importlib
    verify_mod = importlib.import_module("spark_rapids_jni_tpu.engine.verify")
    fuzz_mod = importlib.import_module("spark_rapids_jni_tpu.engine.fuzz")
    tables = (("executor._EXEC_DISPATCH", executor._EXEC_DISPATCH),
              ("explain._DESCRIBE", explain._DESCRIBE),
              ("verify._INFER", verify_mod._INFER),
              ("verify._NULLS", verify_mod._NULLS),
              ("fuzz._ORACLE", fuzz_mod._ORACLE))
    out: list = []
    for cls in plan._NODE_TYPES.values():
        for name, table in tables:
            if cls not in table:
                out.append(_violation(
                    "dispatch-missing", f"{PKG}/engine/plan.py", 0,
                    f"{cls.__name__} not registered in {name}"))
    for name, table in tables:
        for cls in table:
            if cls not in plan._NODE_TYPES.values():
                out.append(_violation(
                    "dispatch-missing", f"{PKG}/engine/plan.py", 0,
                    f"{name} entry {cls.__name__} is not a plan node"))
    return out


#: the smoke pair's exact budget: q5's one fused map segment + the chunked
#: plan's streamed agg (sizing + compaction) — 3 syncs, one per whitelisted
#: site (docs/OBSERVABILITY.md's "3 deliberate host syncs")
SMOKE_EXPECTED_SYNCS = 3

#: the fused dist smoke sandwich's exact budget: the whole partial-agg ->
#: hash-exchange -> final-agg stage is ONE shard_map program paying ONE
#: groupby-compaction boundary sync (the host-orchestrated path pays 4)
FUSED_SMOKE_EXPECTED_SYNCS = 1


def _fused_plan(tmp: str):
    """The dist smoke sandwich for the fused-exchange jaxpr lint."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.engine import Aggregate, Scan
    rng = np.random.default_rng(13)
    n = 4000
    fact = os.path.join(tmp, "lint_fused.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 512, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 400, n) * 0.25),
    }), fact)
    return Aggregate(Scan(fact), ("k",),
                     (("v", "sum"), ("v", "count")), ("total", "n"))


def _full_plans(tmp: str):
    """The nightly extension: bench-shaped join + top-k plans."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.engine import (Aggregate, Filter, Join, Limit,
                                             Scan, Sort, col, lit)
    rng = np.random.default_rng(11)
    n = 4000
    fact = os.path.join(tmp, "lint_fact.parquet")
    dim = os.path.join(tmp, "lint_dim.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 2000, n).astype(np.int64)),
        "v": pa.array(rng.uniform(-5, 50, n)),
    }), fact, row_group_size=n // 8)
    pq.write_table(pa.table({
        "dk": pa.array(np.arange(2000, dtype=np.int64)),
        "grp": pa.array((np.arange(2000) % 7).astype(np.int64)),
    }), dim)
    fscan = Scan(fact, chunk_bytes=24_000)
    join_agg = Aggregate(
        Join(Filter(fscan, (">", col("v"), lit(0.0))), Scan(dim),
             ("k",), ("dk",), "inner"),
        ("grp",), (("v", "sum"), ("v", "count")), ("total", "n"))
    topk = Limit(Sort(Scan(fact, chunk_bytes=24_000),
                      (("v", False), ("k", True))), 32)
    return {"join_agg": join_agg, "topk": topk}


def segments_pass(full: bool = False) -> list:
    import tempfile

    import numpy as np

    sys.path.insert(0, REPO)
    import bench
    from spark_rapids_jni_tpu.engine import optimize
    from spark_rapids_jni_tpu.engine.verify import (check_sync_budget,
                                                    lint_plan_artifacts)
    out: list = []
    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.default_rng(7)
        bench._pipeline_warehouse(tmp, 4000, rng)
        q5, chunked = bench._pipeline_plans(tmp, 48_000)
        plans = {"q5": optimize(q5), "chunked": optimize(chunked)}
        entries, bad = check_sync_budget(list(plans.values()))
        smoke_syncs = sum(e["count"] for e in entries)
        for e in bad:
            out.append(_violation("unwhitelisted-host-sync", "<smoke>", 0,
                                  f"{e['site']} at {e['path']}"))
        if smoke_syncs != SMOKE_EXPECTED_SYNCS:
            out.append(_violation(
                "sync-budget-mismatch", "<smoke>", 0,
                f"smoke plans budget {smoke_syncs} syncs, expected "
                f"{SMOKE_EXPECTED_SYNCS} "
                f"({[(e['site'], e['count']) for e in entries]})"))
        if full:
            plans.update({k: optimize(p)
                          for k, p in _full_plans(tmp).items()})
        for name, plan in plans.items():
            rep = lint_plan_artifacts(plan)
            for v in rep["violations"]:
                out.append(_violation(v["code"], f"<plan:{name}>", 0,
                                      f"{v.get('path', '?')}: "
                                      f"{v.get('detail', '')}"))
            nseg = sum(1 for s in rep["segments"] if "skipped" not in s)
            print(f"srjt-lint: {name}: {nseg} segment artifact(s) linted, "
                  f"{len(rep['violations'])} violation(s)")

        # the fused-exchange artifact: optimize the dist smoke sandwich
        # under SRJT_FUSE_EXCHANGE and lint the whole jit(shard_map)
        # program (verify.lint_fused_stage: no callbacks, no host
        # concretization inside the collectives, all_to_all present) plus
        # its exact one-sync budget
        import jax
        from spark_rapids_jni_tpu.utils.config import config as _cfg
        saved = _cfg.fuse_exchange
        _cfg.fuse_exchange = True
        try:
            fused_opt = optimize(_fused_plan(tmp), distribute=True)
            entries, bad = check_sync_budget([fused_opt])
            for e in bad:
                out.append(_violation(
                    "unwhitelisted-host-sync", "<dist-fused>", 0,
                    f"{e['site']} at {e['path']}"))
            fused_syncs = sum(e["count"] for e in entries)
            ndev = len(jax.devices())
            if ndev > 1 and fused_syncs != FUSED_SMOKE_EXPECTED_SYNCS:
                out.append(_violation(
                    "sync-budget-mismatch", "<dist-fused>", 0,
                    f"fused smoke budget {fused_syncs} syncs, expected "
                    f"{FUSED_SMOKE_EXPECTED_SYNCS} "
                    f"({[(e['site'], e['count']) for e in entries]})"))
            rep = lint_plan_artifacts(fused_opt)
            for v in rep["violations"]:
                out.append(_violation(v["code"], "<plan:dist-fused>", 0,
                                      f"{v.get('path', '?')}: "
                                      f"{v.get('detail', '')}"))
            fused_arts = [s for s in rep["segments"]
                          if s.get("kind") == "fused-stage"]
            if ndev > 1 and not any("skipped" not in s for s in fused_arts):
                out.append(_violation(
                    "missing-fused-artifact", "<plan:dist-fused>", 0,
                    "no fused-stage jaxpr linted on a multi-device mesh"))
            print(f"srjt-lint: dist-fused: "
                  f"{len(fused_arts)} fused-stage artifact(s), budget "
                  f"{fused_syncs} sync(s) on {ndev} device(s)")
        finally:
            _cfg.fuse_exchange = saved

        # the device-decode artifact: plan real page geometry off the
        # warehouse fact file and lint the fused scan+decode program
        # (verify.lint_decode_segment) — the decode prefix must splice
        # into the scan segment with ZERO added host syncs or callbacks
        from spark_rapids_jni_tpu.engine import segment as _sg
        from spark_rapids_jni_tpu.engine.plan import Scan as _Scan
        from spark_rapids_jni_tpu.engine.plan import topo_nodes as _topo
        from spark_rapids_jni_tpu.engine.verify import lint_decode_segment
        from spark_rapids_jni_tpu.io.parquet import (ParquetFile,
                                                     plan_device_group)
        copt = plans["chunked"]
        sn = next(n for n in _topo(copt) if isinstance(n, _Scan))
        seg = _sg.build_stream_segment(copt, sn, _sg.parent_counts(copt))
        chunk, reason = plan_device_group(
            ParquetFile(os.path.join(tmp, "store_sales.parquet")), 0,
            None, 1 << 30)
        if seg is None or chunk is None:
            out.append(_violation(
                "missing-decode-artifact", "<plan:chunked>", 0,
                f"no fused scan+decode jaxpr to lint "
                f"(segment={seg is not None}, plan reason={reason})"))
        else:
            rep = lint_decode_segment(seg, chunk.geom)
            for v in rep["violations"]:
                out.append(_violation(v["code"], "<decode:chunked>", 0,
                                      v.get("detail", "")))
            print(f"srjt-lint: device-decode: fused scan+decode jaxpr, "
                  f"{rep['primitives']} primitive(s), "
                  f"{len(rep['violations'])} violation(s)")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of grandfathered violation keys")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline (default ci/lint-baseline.json)"
                         " from the current violations")
    ap.add_argument("--write-metrics", action="store_true",
                    help="regenerate docs/METRICS.md from the metric-name "
                         "call sites")
    ap.add_argument("--segments", action="store_true",
                    help="also jaxpr-lint the smoke plans' fused segments")
    ap.add_argument("--full", action="store_true",
                    help="with --segments: extend to the bench join/top-k "
                         "plan shapes")
    args = ap.parse_args(argv)

    # import-time passes need the engine importable without a device
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.segments or args.full:
        # the fused-exchange artifact needs a multi-device mesh to lower
        # its shard_map program; must be set before jax initializes
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
    sys.path.insert(0, REPO)
    from spark_rapids_jni_tpu.engine.verify import SYNC_WHITELIST

    sites: list = []
    violations = ast_pass(tuple(SYNC_WHITELIST), sites_out=sites)
    if args.write_metrics:
        doc_path = os.path.join(REPO, METRICS_DOC)
        catalog = _metric_catalog(sites)
        os.makedirs(os.path.dirname(doc_path), exist_ok=True)
        with open(doc_path, "w") as f:
            f.write(render_metrics_doc(catalog))
        print(f"srjt-lint: wrote {len(catalog)} metric name(s) to "
              f"{os.path.relpath(doc_path, REPO)}")
        return 0
    violations += dispatch_pass()
    if args.segments or args.full:
        violations += segments_pass(full=args.full)

    baseline_path = args.baseline or os.path.join(REPO, "ci",
                                                  "lint-baseline.json")
    if args.write_baseline:
        keys = sorted({baseline_key(v) for v in violations})
        with open(baseline_path, "w") as f:
            json.dump({"grandfathered": keys}, f, indent=2)
            f.write("\n")
        print(f"srjt-lint: wrote {len(keys)} baseline key(s) to "
              f"{baseline_path}")
        return 0

    grandfathered: set = set()
    if args.baseline and os.path.exists(args.baseline):
        with open(args.baseline) as f:
            grandfathered = set(json.load(f).get("grandfathered", []))

    fresh = [v for v in violations if baseline_key(v) not in grandfathered]
    old = len(violations) - len(fresh)
    for v in fresh:
        print(f"srjt-lint: {v['code']}: {v['file']}:{v['line']}: "
              f"{v['detail']}")
    print(f"srjt-lint: {len(fresh)} new violation(s), {old} grandfathered")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
