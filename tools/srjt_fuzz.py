#!/usr/bin/env python
"""Seeded plan-space fuzzer CLI (the generative half of docs/ANALYSIS.md).

Drives ``engine/fuzz.py``: synthesize random valid plans over a seeded
parquet warehouse, sweep each through the executor flag matrix
(``SRJT_FUSE``/``SRJT_DIST``/``SRJT_TOPK``/``SRJT_BROADCAST_ROWS``),
and assert the rewrite-soundness invariants (verify-after-rewrite,
ledger==census, exchange census==executed counter, sync whitelist,
bit-exact executor parity, pandas-oracle parity).  Any failure is
shrunk to a minimal plan and reported as ``seed + case + plan JSON`` —
a one-line deterministic repro.

Gate usage:

    python tools/srjt_fuzz.py --smoke            # premerge: fixed seed
    python tools/srjt_fuzz.py --seed N --count M --full \
        --out target/fuzz-repro.json             # nightly sweep

Exit status 0 = zero soundness violations; 1 = failures (repro JSON on
stdout and, with ``--out``, persisted as the CI artifact).
"""

import argparse
import json
import os
import sys
import tempfile

# must precede the first jax import: the differential matrix needs the
# 8-device virtual CPU mesh the engine's distributed tests use
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the premerge smoke contract: fixed seed, ~50 plans, core matrix
SMOKE_SEED = 20260805
SMOKE_COUNT = 50


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help=f"fixed-seed gate corpus (seed {SMOKE_SEED}, "
                         f"{SMOKE_COUNT} plans, core variant matrix)")
    ap.add_argument("--seed", type=int, default=SMOKE_SEED)
    ap.add_argument("--count", type=int, default=SMOKE_COUNT)
    ap.add_argument("--full", action="store_true",
                    help="sweep the extended variant matrix "
                         "(adds dist-nofuse and interp-notopk)")
    ap.add_argument("--out", default=None,
                    help="write the failure report (seed + shrunk "
                         "minimal plan JSON) to this path on failure")
    ap.add_argument("--no-shrink", action="store_true",
                    help="report raw failing plans without minimizing")
    args = ap.parse_args(argv)

    from pathlib import Path

    from spark_rapids_jni_tpu.engine import fuzz

    if args.smoke:
        seed, count, variants = SMOKE_SEED, SMOKE_COUNT, fuzz.VARIANTS
    else:
        seed, count = args.seed, args.count
        variants = fuzz.FULL_VARIANTS if args.full else fuzz.VARIANTS

    with tempfile.TemporaryDirectory(prefix="srjt-fuzz-") as tmp:
        report = fuzz.run_corpus(
            seed, count, Path(tmp), variants=variants,
            log=lambda m: print(f"srjt_fuzz: {m}", file=sys.stderr),
            shrink_failures=not args.no_shrink)

    report["variants"] = [v["name"] for v in variants]
    if report["failures"]:
        print(json.dumps(report, indent=2, default=str))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2, default=str)
            print(f"srjt_fuzz: repro artifact at {args.out}",
                  file=sys.stderr)
        print(f"srjt_fuzz: {len(report['failures'])} soundness "
              f"violation(s) in {count} plans (seed {seed})",
              file=sys.stderr)
        return 1
    print(f"srjt_fuzz: OK — {count} plans x {len(variants)} variants, "
          f"0 soundness violations (seed {seed})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
