#!/usr/bin/env python
"""Prometheus text-exposition exporter for the engine metrics registries.

One scrape = one dump of the counter/gauge/histogram registries (plus the
live-query progress gauges and, when ``SRJT_SLO_MS`` declares objectives,
the per-fingerprint ``srjt_slo_*`` burn-rate gauges — evaluated by the
server for ``--socket`` scrapes, locally otherwise) in Prometheus text
exposition format v0.0.4 —
pipe it into a node_exporter textfile collector, a pushgateway, or curl's
stdin.  Two sources:

- ``--socket PATH``: scrape a *running bridge server* over ``OP_METRICS``
  (second connection; does not disturb in-flight queries).  ``--prefix``
  narrows the blocks server-side before they cross the wire.
- no socket: dump this process's own registries.  That is only useful
  after something ran in-process, so ``--warm`` first executes a tiny
  generated query to populate them — the CI smoke path that validates the
  exposition format end to end.

Usage::

    python tools/srjt_export.py --socket /tmp/bridge.sock [--prefix engine.]
    python tools/srjt_export.py --warm [--prefix engine.stream]

Exit code 0 on success, 2 on usage errors (dead socket, empty registry
without --warm).
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from spark_rapids_jni_tpu.utils import metrics  # noqa: E402


def _warm_query() -> None:
    """Run one tiny in-process aggregate so the registries have content —
    scan + groupby over a generated parquet file, a few KB of work."""
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.engine import Aggregate, Scan, execute, optimize

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "warm.parquet")
        rng = np.random.default_rng(11)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 8, 512).astype(np.int64)),
            "v": pa.array(rng.uniform(0.0, 1.0, 512)),
        }), path, row_group_size=128)
        plan = Aggregate(Scan(path, chunk_bytes=2_048), ["k"],
                         [("v", "sum")], names=["s"])
        with metrics.query("export:warm"):
            execute(optimize(plan))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srjt_export", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--socket", default=None,
                    help="bridge server unix socket to scrape over "
                         "OP_METRICS (default: this process's registries)")
    ap.add_argument("--prefix", default="",
                    help="metric-name prefix filter (e.g. engine.stream)")
    ap.add_argument("--warm", action="store_true",
                    help="no-socket mode: run a tiny query first so the "
                         "local registries have content")
    args = ap.parse_args(argv)

    if args.socket:
        from spark_rapids_jni_tpu.bridge import BridgeClient
        try:
            client = BridgeClient(args.socket)
        except OSError as e:
            print(f"cannot connect to {args.socket}: {e}", file=sys.stderr)
            return 2
        try:
            snap = client.metrics(prefix=args.prefix)
        finally:
            client.close()
        # the server already applied the prefix; render its snapshot
        sys.stdout.write(metrics.prometheus_text(snap=snap))
        return 0

    if args.warm:
        _warm_query()
    text = metrics.prometheus_text(prefix=args.prefix)
    if not text.strip():
        print("local registries are empty (run under a query, or pass "
              "--warm / --socket)", file=sys.stderr)
        return 2
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:
        # downstream pager/head closed the pipe mid-print: normal exit,
        # but devnull stdout first so interpreter teardown can't re-raise
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)
