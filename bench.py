"""Benchmarks over the BASELINE.md north-star configs.

Prints ONE JSON line.  Headline metric: RowConversion device throughput
(BASELINE configs[0]); ``extras`` carries CastStrings, HashAggregate and
Parquet-scan so the artifact records >=3 metrics per round.

Timing methodology (tunneled TPU): a value fetch costs ~50-90 ms and
``block_until_ready`` returns before execution, so every device metric runs
K iterations inside one jitted ``fori_loop`` with a per-iteration salt
(defeats loop-invariant hoisting), reduced to one scalar fetch.  Rates are
fitted from two K values to cancel the fixed dispatch+fetch cost.  Where the
loop must materialize full-size output each iteration (RowConversion), the
carry xors in the output matrix — this *overstates* traffic by one
read+write of the carry per iteration, so reported GB/s is a lower bound on
the kernel's standalone rate.
"""

import json
import os
import sys
import time

import numpy as np

# Pinned baseline constants (VERDICT r3 #7): vs_baseline is measured/pinned,
# never measured/measured — see BENCH_BASELINES.json for provenance.
with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_BASELINES.json")) as f:
    _PINS = json.load(f)


def pinned(metric: str) -> float:
    return _PINS[metric]["pinned_baseline"]


def fit_per_iter(make_loop, args, k1=16, k2=64):
    """min-of-3 wall times at two K values -> steady per-iteration seconds."""
    import jax
    ts = {}
    for k in (k1, k2):
        jf = jax.jit(make_loop(k))
        int(jf(*args))  # compile + warm
        best = min(_timed(jf, args) for _ in range(3))
        ts[k] = best
    per = (ts[k2] - ts[k1]) / (k2 - k1)
    if per <= 0:  # tunnel jitter; fall back to the conservative bound
        per = ts[k2] / k2
    return per


def _timed(jf, args):
    t0 = time.perf_counter()
    int(jf(*args))
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# 1. RowConversion (headline, BASELINE configs[0])
# ---------------------------------------------------------------------------

def build_host_table(n, rng):
    return [
        ("i64", rng.integers(-2**62, 2**62, n).astype(np.int64), None),
        ("f64", rng.standard_normal(n), rng.random(n) > 0.1),
        ("i32", rng.integers(-2**31, 2**31 - 1, n).astype(np.int32), None),
        ("f32", rng.standard_normal(n).astype(np.float32), None),
        ("i16", rng.integers(-2**15, 2**15 - 1, n).astype(np.int16),
         rng.random(n) > 0.5),
        ("i8", rng.integers(-128, 128, n).astype(np.int8), None),
        ("bool", (rng.random(n) > 0.5), None),
        ("dec64", rng.integers(-10**15, 10**15, n).astype(np.int64), None),
    ]


def numpy_pack(cols, layout):
    """CPU Arrow-style row packer: strided assignment per column + validity."""
    n = len(cols[0][1])
    out = np.zeros((n, layout.row_size), np.uint8)
    for (name, data, valid), off in zip(cols, layout.offsets):
        if data.dtype == np.bool_:
            data = data.astype(np.uint8)
        b = data.view(np.uint8).reshape(n, data.dtype.itemsize)
        out[:, off:off + data.dtype.itemsize] = b
    vbytes = np.zeros((n, layout.num_validity_bytes), np.uint8)
    for i, (name, data, valid) in enumerate(cols):
        bit = np.uint8(1 << (i % 8))
        if valid is None:
            vbytes[:, i // 8] |= bit
        else:  # full-vector or, not boolean fancy indexing (4x faster)
            vbytes[:, i // 8] |= np.where(valid, bit, np.uint8(0))
    out[:, layout.validity_offset:layout.validity_offset
        + layout.num_validity_bytes] = vbytes
    return out


def bench_row_conversion(n=2_000_000):
    import jax
    import jax.numpy as jnp
    from spark_rapids_jni_tpu import dtypes as dt
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.ops.row_conversion import (
        fixed_width_layout, _to_rows_bytes, _to_rows_wire)

    rng = np.random.default_rng(0)
    host_cols = build_host_table(n, rng)
    schema = [dt.INT64, dt.FLOAT64, dt.INT32, dt.FLOAT32, dt.INT16, dt.INT8,
              dt.BOOL8, dt.decimal64(-4)]
    layout = fixed_width_layout(schema)
    table = Table([Column.from_numpy(data, validity=valid, dtype=d)
                   for (name, data, valid), d in zip(host_cols, schema)])
    datas = tuple(c.data for c in table.columns)
    masks = tuple(c.validity for c in table.columns)
    nw = layout.row_size // 4

    def make_loop(K):
        def loop(d, m, acc):
            def body(i, acc):
                di = d[:2] + (d[2] ^ i.astype(jnp.int32),) + d[3:]
                return acc ^ _to_rows_wire(layout, di, m)
            out = jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, acc)
            return out.sum(dtype=jnp.uint32)
        return loop

    acc0 = jnp.zeros((n * nw,), jnp.uint32)
    per = fit_per_iter(make_loop, (datas, masks, acc0))
    dev_gbps = n * layout.row_size / per / 1e9

    # Honest measured ceiling (r4's planes-only "ceiling" measured BELOW the
    # shipped op — a bound an op can beat is mis-measured).  This one is a
    # pure HBM stream under the SAME acc-xor harness (strictly simpler than
    # any op formulation: zero compute, perfectly coalesced), scaled by the
    # op's minimum-traffic ratio.  Per iteration the stream moves 3R bytes
    # (read x, read acc, write acc; R = output bytes); any to-rows
    # formulation must move >= I + 2R (read every input byte, read+write
    # acc), so its processed-bytes rate cannot exceed
    # stream_rate * 3R / (I + 2R).
    def make_ceiling(K):
        def loop(x, acc):
            def body(i, acc):
                # roll makes each iteration depend on the fully
                # materialized previous carry, so XLA can neither cancel
                # xor pairs nor fuse the K iterations into one read of x
                return jnp.roll(acc, 1) ^ x
            out = jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, acc)
            return out.sum(dtype=jnp.uint32)
        return loop

    x0 = jnp.arange(n * nw, dtype=jnp.uint32)
    per_s = fit_per_iter(make_ceiling, (x0, acc0))
    stream_gbps = n * layout.row_size / per_s / 1e9
    in_bytes = sum(int(np.asarray(d).nbytes) for d in datas) + \
        sum(0 if m is None else n for m in masks)
    R = n * layout.row_size
    ceiling_gbps = stream_gbps * 3 * R / (in_bytes + 2 * R)

    # CPU Arrow-style baseline (best of 3)
    cpu_s = min(
        (lambda t0: (numpy_pack(host_cols, layout),
                     time.perf_counter() - t0))(time.perf_counter())[1]
        for _ in range(3))
    cpu_gbps = n * layout.row_size / cpu_s / 1e9

    # wire-bytes cross-check on a 100k slice against the numpy oracle
    ncheck = 100_000
    check = jax.jit(lambda d, m: _to_rows_bytes(layout, d, m))
    got = np.asarray(check(
        tuple(d[:ncheck] for d in datas),
        tuple(None if m is None else m[:ncheck] for m in masks)))
    ref = numpy_pack([(nm, d0[:ncheck], None if v0 is None else v0[:ncheck])
                      for nm, d0, v0 in host_cols], layout).reshape(-1)
    ok = bool((got == ref).all())
    return dev_gbps, cpu_gbps, ok, ceiling_gbps


def numpy_pack_var(i64, chars, lens, vlay):
    """CPU Arrow-style variable-width row packer (vectorized numpy): the
    long+string half of the configs[0] baseline."""
    base = vlay.base
    pad = (lens.astype(np.int64) + 7) // 8 * 8
    row_sizes = base.row_size + pad
    row_ends = np.cumsum(row_sizes)
    row_starts = row_ends - row_sizes
    out = np.zeros(int(row_ends[-1]), np.uint8)
    n = i64.shape[0]
    fixed_idx = row_starts[:, None] + np.arange(8)
    out[fixed_idx] = i64.view(np.uint8).reshape(n, 8)
    slot = np.empty((n, 8), np.uint8)
    slot[:, :4] = np.full((n,), base.row_size, np.uint32)[:, None].view(
        np.uint8).reshape(n, 4)
    slot[:, 4:] = lens.astype(np.uint32)[:, None].view(np.uint8).reshape(n, 4)
    out[row_starts[:, None] + np.arange(8, 16)] = slot
    out[row_starts + base.validity_offset] = 0x3  # both columns valid
    coff = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=coff[1:])
    within = np.arange(coff[-1]) - np.repeat(coff[:-1], lens)
    out[np.repeat(row_starts + base.row_size, lens) + within] = chars
    return out


def bench_row_conversion_strings(n=1_000_000):
    # 1M rows (not the fixed path's 2M): the wire-sort program's REMOTE
    # compile scales with the lane count and dominated bench wall time at
    # 2M (~10 min); GB/s is intensive in n (measured 0.140 vs 0.146)
    """BASELINE configs[0] at its specified shape: long + string columns."""
    import jax.numpy as jnp
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.ops.row_conversion import (
        convert_to_rows, variable_width_layout)
    from spark_rapids_jni_tpu import dtypes as dt

    rng = np.random.default_rng(5)
    i64 = rng.integers(-2**62, 2**62, n).astype(np.int64)
    lens = rng.integers(4, 21, n).astype(np.int32)
    coff = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=coff[1:])
    chars = rng.integers(97, 123, int(coff[-1])).astype(np.uint8)
    table = Table([Column.from_numpy(i64),
                   Column.string(jnp.asarray(chars),
                                 jnp.asarray(coff.astype(np.int32)))],
                  ["l", "s"])
    blobs = convert_to_rows(table)  # compile + warm
    total = sum(int(np.asarray(b.offsets)[-1]) for b in blobs)

    # steady-state device rate, same fori_loop methodology as the fixed
    # headline (salt the long column; lengths are untouched so shapes and
    # the wire sort stay identical)
    import jax
    from spark_rapids_jni_tpu.ops.row_conversion import _to_rows_var_fused
    vlay = variable_width_layout(table.dtypes())
    soffs = (jnp.asarray(table.columns[1].offsets, jnp.int32),)
    schars = (jnp.asarray(table.columns[1].data, jnp.uint8),)
    masks = (None, None)
    total_words = total // 4

    def make_loop(K):
        def loop(d, acc):
            def body(i, acc):
                wire, _ = _to_rows_var_fused(
                    vlay, (max(8, (int(lens.max()) + 7) // 8 * 8),),
                    total_words,
                    (d ^ i.astype(jnp.int64), None), masks, soffs, schars)
                return acc ^ wire
            out = jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, acc)
            return out.sum(dtype=jnp.uint32)
        return loop

    # ONE compiled loop (a second K would double the minutes-long remote
    # compile of the ~12M-lane wire sort); K=8 amortizes dispatch+fetch to <10%, and
    # dividing the whole wall time by K under-counts nothing — conservative
    acc0 = jnp.zeros((total_words,), jnp.uint32)
    K = 8
    jf = jax.jit(make_loop(K))
    args = (table.columns[0].data, acc0)
    int(jf(*args))  # compile + warm
    per = min(_timed(jf, args) for _ in range(3)) / K
    dev_gbps = total / per / 1e9

    t0 = time.perf_counter()
    ref = numpy_pack_var(i64, chars, lens, vlay)
    cpu_s = time.perf_counter() - t0
    cpu_gbps = total / cpu_s / 1e9
    # byte-exactness cross-check on a slice against the numpy oracle
    got = np.asarray(blobs[0].children[0].data).view(np.uint8)
    ok = bool((got[:1 << 16] == ref[:1 << 16]).all())
    return dev_gbps, cpu_gbps, ok


# ---------------------------------------------------------------------------
# 2. CastStrings: string -> int64 (north-star op)
# ---------------------------------------------------------------------------

def bench_cast_strings(n=2_000_000):
    import jax
    import jax.numpy as jnp
    from spark_rapids_jni_tpu.ops.cast_strings import _parse_number

    rng = np.random.default_rng(1)
    width = 18
    digits = rng.integers(0, 10, (n, width)).astype(np.uint8) + ord("0")
    mat = jnp.asarray(digits)
    lengths = jnp.full((n,), width, jnp.int32)

    def make_loop(K):
        def loop(mat, lengths):
            def body(i, acc):
                m = mat.at[:, -1].set((48 + i % 10).astype(jnp.uint8))
                p = _parse_number(m, lengths, True, False, False)
                return acc + p["digits"].sum(dtype=jnp.uint64).astype(
                    jnp.uint32) + p["syntax_ok"].sum(dtype=jnp.uint32)
            return jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body,
                                     jnp.uint32(0))
        return loop

    per = fit_per_iter(make_loop, (mat, lengths))
    dev_mrows = n / per / 1e6

    # CPU baseline: pandas vectorized string->int64 on the same strings
    import pandas as pd
    ser = pd.Series(digits.view(f"S{width}").ravel())
    t0 = time.perf_counter()
    ser.astype(np.int64)
    cpu_mrows = n / (time.perf_counter() - t0) / 1e6
    return dev_mrows, cpu_mrows


# ---------------------------------------------------------------------------
# 3. HashAggregate: groupby(sum, count) (BASELINE configs[2] shape, scaled)
# ---------------------------------------------------------------------------

def bench_hash_aggregate(n=2_000_000, nkeys=100_000):
    import jax
    import jax.numpy as jnp
    from spark_rapids_jni_tpu import dtypes as dt
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.ops.aggregate import groupby_padded

    rng = np.random.default_rng(2)
    k = jnp.asarray(rng.integers(0, nkeys, n).astype(np.int64))
    v = jnp.asarray(rng.integers(-1000, 1000, n).astype(np.int64))

    def make_loop(K):
        def loop(k, v):
            def body(i, acc):
                tbl = Table([Column(dt.INT64, data=k ^ (i & 7)),
                             Column(dt.INT64, data=v)], ["k", "v"])
                _, aggs, ng = groupby_padded(
                    tbl, ["k"], [("v", "sum"), ("v", "count")])
                return acc + ng.astype(jnp.uint32) + \
                    aggs[0].data.sum(dtype=jnp.int64).astype(jnp.uint32)
            return jax.lax.fori_loop(jnp.int64(0), jnp.int64(K), body,
                                     jnp.uint32(0))
        return loop

    per = fit_per_iter(make_loop, (k, v), k1=8, k2=32)
    dev_mrows = n / per / 1e6

    import pandas as pd
    df = pd.DataFrame({"k": np.asarray(k), "v": np.asarray(v)})
    t0 = time.perf_counter()
    df.groupby("k").v.agg(["sum", "count"])
    cpu_mrows = n / (time.perf_counter() - t0) / 1e6
    return dev_mrows, cpu_mrows


# ---------------------------------------------------------------------------
# 4. Parquet scan (ParquetChunked north star)
# ---------------------------------------------------------------------------

def bench_parquet_scan(n=2_000_000):
    import shutil, tempfile, os
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.io import read_parquet

    rng = np.random.default_rng(3)
    tbl = pa.table({
        "a": pa.array(rng.integers(0, 10**9, n).astype(np.int64)),
        "b": pa.array(rng.standard_normal(n)),
        "c": pa.array(rng.integers(0, 100, n).astype(np.int32)),
    })
    d = tempfile.mkdtemp()
    path = os.path.join(d, "bench.parquet")
    pq.write_table(tbl, path, compression="snappy", row_group_size=250_000)
    nbytes = n * (8 + 8 + 4)
    from spark_rapids_jni_tpu.io import ParquetFile

    # host decode (the engine's own work; page decode + dict gather), using
    # the same threaded row-group fan-out ParquetFile.read uses
    from concurrent.futures import ThreadPoolExecutor
    f = ParquetFile(path)
    list(map(f._decode_group, range(1)))  # warm imports/mmap
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=min(f.num_row_groups,
                                            os.cpu_count() or 4)) as ex:
        list(ex.map(f._decode_group, range(f.num_row_groups)))
    decode = nbytes / (time.perf_counter() - t0) / 1e6

    # measured host->device link rate (NOT assumed — VERDICT r3 weak #4:
    # the e2e number only means something next to the link it rides)
    import jax
    probe = np.random.default_rng(9).integers(0, 255, 24 << 20,
                                              dtype=np.uint8)
    x = jax.device_put(probe); float(x[0])  # warm
    link = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        x = jax.device_put(probe); float(x[0])
        link = max(link, probe.nbytes / (time.perf_counter() - t0) / 1e6)

    # end-to-end into device columns; on tunneled devices this is bounded by
    # the host->device link, measured above and reported alongside
    t0 = time.perf_counter()
    out = read_parquet(path)
    float(out.columns[0].data.sum())  # wait for device residency
    e2e = nbytes / (time.perf_counter() - t0) / 1e6

    # repeated-scan rate through the staged single-transfer path: the
    # jitted unpack compiles on the first call (cached per schema), so a
    # warm scan is the NDS steady-state number.  Best-of-3: the tunnel's
    # throughput swings run to run, and a single sample has recorded a
    # stall as the steady state
    read_parquet(path, staged=True)  # compile + first transfer
    e2e_staged = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        out = read_parquet(path, staged=True)
        float(out.columns[0].data.sum())
        e2e_staged = max(e2e_staged,
                         nbytes / (time.perf_counter() - t0) / 1e6)

    t0 = time.perf_counter()
    pq.read_table(path)
    arrow = nbytes / (time.perf_counter() - t0) / 1e6
    shutil.rmtree(d)
    return decode, e2e, e2e_staged, arrow, link


def bench_window(n=2_000_000):
    """Window rank + running sum (RANGE frame) vs single-threaded pandas."""
    import jax
    import jax.numpy as jnp
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.dtypes import INT64
    from spark_rapids_jni_tpu.ops.window import window

    rng = np.random.default_rng(4)
    p = rng.integers(0, 10_000, n).astype(np.int64)
    o = rng.integers(0, 1_000_000, n).astype(np.int64)
    v = rng.integers(-1000, 1000, n).astype(np.int64)
    pj, oj, vj = jnp.asarray(p), jnp.asarray(o), jnp.asarray(v)

    def make_loop(k):
        def body(i, carry):
            t = Table([Column(INT64, data=pj),
                       Column(INT64, data=oj + i),  # salt defeats hoisting
                       Column(INT64, data=vj)], ["p", "o", "v"])
            out = window(t, ["p"], ["o"], [(None, "rank"), ("v", "sum")])
            return carry + out["rank"].data[0] + out["sum_v"].data[-1]

        return lambda: jax.lax.fori_loop(0, k, body, jnp.int64(0))

    per = fit_per_iter(make_loop, ())
    dev_mrows = n / per / 1e6

    import pandas as pd
    df = pd.DataFrame({"p": p, "o": o, "v": v})
    t0 = time.perf_counter()
    s = df.sort_values(["p", "o"], kind="stable")
    s.groupby("p")["o"].rank(method="min")
    s.groupby("p")["v"].cumsum()
    cpu_mrows = n / (time.perf_counter() - t0) / 1e6
    return dev_mrows, cpu_mrows


def bench_distributed_join(n_left=1_000_000, n_right=250_000):
    """Shuffle + distributed SortMergeJoin, BASELINE configs[3].

    The deployment has one physical chip, so the 8-device exchange runs in
    a subprocess on the virtual CPU mesh (the same path dryrun_multichip
    validates); the single-chip metrics above stay on the TPU.  Reports
    Mrows/s of left-side input through shuffle+join, and the local
    single-device join rate on the same host for scale context.
    """
    import subprocess
    import os
    import sys as _sys
    script = f"""
import json, time
import numpy as np
import spark_rapids_jni_tpu
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.join import inner_join
from spark_rapids_jni_tpu.parallel import make_mesh, distributed_join
from spark_rapids_jni_tpu.parallel.mesh import shard_table
from spark_rapids_jni_tpu.parallel.shuffle import shuffle_table_padded
rng = np.random.default_rng(3)
nl, nr = {n_left}, {n_right}
left = Table([Column.from_numpy(rng.integers(0, nr, nl).astype(np.int64)),
              Column.from_numpy(rng.integers(-100, 100, nl).astype(np.int64))],
             ["k", "v"])
right = Table([Column.from_numpy(rng.permutation(nr).astype(np.int64)),
               Column.from_numpy(np.arange(nr, dtype=np.int64))],
              ["k", "rv"])
mesh = make_mesh(8)
out = distributed_join(left, right, mesh, ["k"])   # warm (compile)
t0 = time.perf_counter(); out = distributed_join(left, right, mesh, ["k"])
drows = out.num_rows; dt_d = time.perf_counter() - t0
out2 = inner_join(left, right, ["k"])              # warm
t0 = time.perf_counter(); out2 = inner_join(left, right, ["k"])
dt_l = time.perf_counter() - t0
assert out.num_rows == out2.num_rows
# stage breakdown (VERDICT r3 #8): exchange-only cost on the same data,
# measured as the standalone shuffle of each side; join = total - exchange
lt = shard_table(left, mesh); rt = shard_table(right, mesh)
for t in (lt, rt): shuffle_table_padded(t, mesh, ["k"])  # warm
t0 = time.perf_counter()
sl, okl, _ = shuffle_table_padded(lt, mesh, ["k"])
sr, okr, _ = shuffle_table_padded(rt, mesh, ["k"])
float(np.asarray(okl)[0]); float(np.asarray(okr)[0])
dt_x = time.perf_counter() - t0
xbytes = sum(int(np.asarray(c.data).nbytes) for c in sl.columns) + \
         sum(int(np.asarray(c.data).nbytes) for c in sr.columns)
# padding efficiency: live rows over padded exchange slots (VERDICT r4 #7)
pad_eff = (nl + nr) / (sl.num_rows + sr.num_rows)
print(json.dumps({{"dist_mrows_s": nl / dt_d / 1e6,
                   "local_mrows_s": nl / dt_l / 1e6,
                   "exchange_s": dt_x, "total_s": dt_d,
                   "exchange_MB": xbytes / 1e6,
                   "padding_efficiency": pad_eff,
                   "rows_out": drows}}))
"""
    # hand the bench run's trace to the child (SRJT_TRACE_ID): its flight
    # recorder, timeline, and any post-mortem bundle join the parent's id
    from spark_rapids_jni_tpu.utils import blackbox
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               SRJT_TRACE_ID=(blackbox.current_trace()
                              or blackbox.new_trace_id()),
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"),
               JAX_ENABLE_X64="1")
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run([_sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=900)
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            print(f"distributed-join bench failed (rc={r.returncode}):\n"
                  f"{r.stderr[-2000:]}", file=_sys.stderr)
            return None
        return json.loads(lines[-1])
    except Exception as e:
        print(f"distributed-join bench failed: {e!r}", file=_sys.stderr)
        return None


def bench_engine_q5(n=200_000):
    """Whole-plan bridge dispatch vs per-op dispatch on a q5-lite shape.

    The engine's reason to exist (docs/ENGINE.md): on an RTT-dominated link
    every per-op call pays a round trip, so submitting the serialized plan
    in ONE ``PLAN_EXECUTE`` message amortizes the link out of the plan walk.
    Builds a tmpdir warehouse, runs scan+semi-join+agg+join+agg+sort both
    ways against one server, and reports cold (plan-cache miss: optimize +
    execute) vs warm (cache hit) plan dispatch plus the round-trip counts.
    No pinned baseline yet: first round with the engine in the tree.
    """
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.bridge import BridgeClient, spawn_server
    from spark_rapids_jni_tpu.bridge import protocol as P
    from spark_rapids_jni_tpu.engine import Aggregate, Join, Scan, Sort

    rng = np.random.default_rng(11)
    with tempfile.TemporaryDirectory() as tmp:
        root = os.path.join(tmp, "wh")
        os.mkdir(root)
        pq.write_table(pa.table({
            "ss_sold_date_sk": pa.array(
                np.sort(rng.integers(0, 400, n)).astype(np.int64)),
            "ss_store_sk": pa.array(rng.integers(1, 13, n).astype(np.int64)),
            "ss_ext_sales_price": pa.array(rng.uniform(0.5, 300.0, n)),
        }), os.path.join(root, "store_sales.parquet"), row_group_size=20_000)
        # the date filter is pre-applied at write time: the bridge's per-op
        # surface has no comparison op, so both paths scan the kept range
        pq.write_table(pa.table({
            "d_date_sk": pa.array(np.arange(100, 300, dtype=np.int64)),
        }), os.path.join(root, "date_dim.parquet"))
        pq.write_table(pa.table({
            "s_store_sk": pa.array(np.arange(1, 13, dtype=np.int64)),
            "s_mgr": pa.array(np.arange(1, 13, dtype=np.int64) % 4),
        }), os.path.join(root, "store.parquet"))

        kept = Join(Scan(os.path.join(root, "store_sales.parquet")),
                    Scan(os.path.join(root, "date_dim.parquet")),
                    ["ss_sold_date_sk"], ["d_date_sk"], how="semi")
        totals = Aggregate(kept, ["ss_store_sk"],
                           [("ss_ext_sales_price", "sum"),
                            ("ss_ext_sales_price", "count")],
                           names=["sales", "n"])
        joined = Join(totals, Scan(os.path.join(root, "store.parquet")),
                      ["ss_store_sk"], ["s_store_sk"], how="inner")
        plan = Sort(Aggregate(joined, ["s_mgr"],
                              [("sales", "sum"), ("n", "sum")],
                              names=["sales", "n"]),
                    (("s_mgr", True),))

        sock = os.path.join(tmp, "tpub.sock")
        proc = spawn_server(sock)
        try:
            c = BridgeClient(sock)
            t0 = time.perf_counter()
            h_cold = c.execute_plan(plan)
            t_cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            h_warm = c.execute_plan(plan)
            t_warm = time.perf_counter() - t0
            plan_trips = 1  # each execute_plan was one _call

            before = c.round_trips
            t0 = time.perf_counter()
            sh = c.read_parquet(os.path.join(root, "store_sales.parquet"))
            dh = c.read_parquet(os.path.join(root, "date_dim.parquet"))
            th = c.read_parquet(os.path.join(root, "store.parquet"))
            kh = c.join(sh, dh, [0], [0], "semi")
            gh = c.groupby(kh, [1], [(2, P.AGG_SUM), (2, P.AGG_COUNT)])
            jh = c.join(gh, th, [0], [0], "inner")
            g2 = c.groupby(jh, [3], [(1, P.AGG_SUM), (2, P.AGG_SUM)])
            oh = c.sort(g2, [(0, True, None)])
            t_perop = time.perf_counter() - t0
            perop_trips = c.round_trips - before

            got = c.export_table(h_warm[0])
            want = c.export_table(oh)
            same = got.num_rows == want.num_rows and all(
                np.allclose(np.asarray(a.data), np.asarray(b.data))
                for a, b in zip(got.columns, want.columns))
            # prefix narrows the counter/hist/gauge blocks server-side;
            # the plan_cache block rides along regardless
            cache = c.metrics(prefix="bridge.")["plan_cache"]
            c.shutdown_server()
        except Exception as e:
            print(f"engine bench failed: {e!r}", file=sys.stderr)
            proc.kill()
            return None
        finally:
            proc.wait(timeout=30)
    return {"cold_ms": t_cold * 1e3, "warm_ms": t_warm * 1e3,
            "per_op_ms": t_perop * 1e3, "plan_round_trips": plan_trips,
            "per_op_round_trips": perop_trips, "results_match": same,
            "cache_hits": cache["hits"], "cache_misses": cache["misses"]}


def _pipeline_warehouse(root, n, rng):
    """q5-lite warehouse for the local-executor pipeline bench."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    pq.write_table(pa.table({
        "ss_sold_date_sk": pa.array(
            np.sort(rng.integers(0, 400, n)).astype(np.int64)),
        "ss_store_sk": pa.array(rng.integers(1, 13, n).astype(np.int64)),
        "ss_ext_sales_price": pa.array(rng.uniform(0.5, 300.0, n)),
        "ss_net_profit": pa.array(rng.uniform(-50.0, 120.0, n)),
    }), os.path.join(root, "store_sales.parquet"),
        row_group_size=max(1, n // 8))
    pq.write_table(pa.table({
        "d_date_sk": pa.array(np.arange(100, 300, dtype=np.int64)),
    }), os.path.join(root, "date_dim.parquet"))
    pq.write_table(pa.table({
        "s_store_sk": pa.array(np.arange(1, 13, dtype=np.int64)),
        "s_mgr": pa.array(np.arange(1, 13, dtype=np.int64) % 4),
    }), os.path.join(root, "store.parquet"))


def _pipeline_plans(root, chunk_bytes):
    """(q5-lite plan, chunked-scan aggregate plan) over the warehouse.

    The q5 filters survive optimization as real Filter nodes (the scan
    predicate only prunes row groups), so the fused executor has chains to
    compile; the chunked aggregate feeds the scan straight into a fused
    partial-groupby segment — the double-buffered streaming shape.
    """
    from spark_rapids_jni_tpu.engine import (Aggregate, Filter, Join, Scan,
                                             Sort, col, lit)
    dates_f = Filter(Scan(os.path.join(root, "date_dim.parquet")),
                     ("&", (">=", col("d_date_sk"), lit(100)),
                      ("<", col("d_date_sk"), lit(300))))
    sales = Scan(os.path.join(root, "store_sales.parquet"))
    kept = Filter(Join(sales, dates_f, ["ss_sold_date_sk"], ["d_date_sk"],
                       how="semi"),
                  ("&", (">", col("ss_net_profit"), lit(0.0)),
                   (">=", col("ss_sold_date_sk"), lit(100))))
    totals = Aggregate(kept, ["ss_store_sk"],
                       [("ss_ext_sales_price", "sum"),
                        ("ss_net_profit", "sum"),
                        ("ss_ext_sales_price", "count")],
                       names=["sales", "profit", "n"])
    joined = Join(totals, Scan(os.path.join(root, "store.parquet")),
                  ["ss_store_sk"], ["s_store_sk"], how="inner")
    q5 = Sort(Aggregate(joined, ["s_mgr"],
                        [("sales", "sum"), ("profit", "sum"), ("n", "sum")],
                        names=["sales", "profit", "n"]),
              (("s_mgr", True),))

    chunked = Aggregate(
        Filter(Scan(os.path.join(root, "store_sales.parquet"),
                    chunk_bytes=chunk_bytes),
               (">", col("ss_ext_sales_price"), lit(1.0))),
        ["ss_store_sk"],
        [("ss_ext_sales_price", "sum"), ("ss_net_profit", "sum"),
         ("ss_net_profit", "min"), ("ss_net_profit", "max"),
         ("ss_ext_sales_price", "count")],
        names=["sales", "profit", "lo", "hi", "n"])
    return q5, chunked


def _run_plan(opt, fused, prefetch):
    """One timed local execute; blocks until the result is ready."""
    import jax
    from spark_rapids_jni_tpu.engine import execute, new_stats
    stats = new_stats()
    t0 = time.perf_counter()
    out = execute(opt, stats, fused=fused, prefetch=prefetch)
    jax.block_until_ready([c.data for c in out.columns
                           if c.data is not None])
    return time.perf_counter() - t0, out, stats


def _tables_match(a, b) -> bool:
    if a.num_rows != b.num_rows or a.num_columns != b.num_columns:
        return False
    for ca, cb in zip(a.columns, b.columns):
        if not np.allclose(np.asarray(ca.data, np.float64),
                           np.asarray(cb.data, np.float64)):
            return False
    return True


def bench_engine_pipeline(n=600_000, chunk_bytes=512_000, smoke=False):
    """Fused-segment compilation + double-buffered streaming vs PR 1.

    Two comparisons on the LOCAL executor (no bridge — this measures the
    execution engine itself):

    - q5-lite, warm: node-by-node interpreter (``fused=False``, the PR 1
      executor) vs fused segments (Filter/Project/Aggregate chains as one
      jitted program each).  Cold fused time is reported too: it pays the
      segment trace+compile the ``engine.segment_cache`` then amortizes.
    - chunked-scan aggregate: serial chunk streaming (``prefetch=0``) vs
      double-buffered (``prefetch=2``) on the same fused plan, plus the
      interpreted loop both ways — overlap hides host decode behind device
      compute; the interpreted loop ALSO syncs per chunk, so it shows the
      overlap even when device compute is cheap.

    ``smoke=True``: tiny shapes, correctness cross-checks only, no timing
    claims — the CI hook that keeps the perf paths importable+runnable.
    """
    import tempfile

    from spark_rapids_jni_tpu.engine import optimize
    from spark_rapids_jni_tpu.engine.segment import SEGMENT_CACHE
    from spark_rapids_jni_tpu.ops.selection import sort_table
    from spark_rapids_jni_tpu.ops.order import SortKey

    rng = np.random.default_rng(13)
    with tempfile.TemporaryDirectory() as tmp:
        root = os.path.join(tmp, "wh")
        os.mkdir(root)
        _pipeline_warehouse(root, n, rng)
        q5, chunked = _pipeline_plans(root, chunk_bytes)
        q5_opt, ch_opt = optimize(q5), optimize(chunked)

        def sorted_by_key(t):
            return sort_table(t, [SortKey(t[t.names[0]], ascending=True)])

        # q5-lite: cold fused (segment trace+compile), then warm both ways
        t_cold, out_f, _ = _run_plan(q5_opt, fused=True, prefetch=0)
        t_fused = min(_run_plan(q5_opt, fused=True, prefetch=0)[0]
                      for _ in range(1 if smoke else 3))
        _run_plan(q5_opt, fused=False, prefetch=0)  # warm interp caches too
        t_interp, out_i, _ = _run_plan(q5_opt, fused=False, prefetch=0)
        if not smoke:
            t_interp = min(t_interp, *(
                _run_plan(q5_opt, fused=False, prefetch=0)[0]
                for _ in range(2)))
        q5_match = _tables_match(out_f, out_i)

        # chunked streaming aggregate: serial vs double-buffered.
        # A/B pairs interleaved and min-taken — on a saturated host the
        # run-to-run noise is the same order as the overlap win, and
        # alternating keeps cache/thermal drift out of the ratio.
        reps = 1 if smoke else 3
        _run_plan(ch_opt, fused=True, prefetch=0)   # compile warm-up
        _run_plan(ch_opt, fused=False, prefetch=0)  # warm interp loop
        t_serial = t_overlap = t_iserial = t_ioverlap = float("inf")
        out_s = st_s = out_o = st_o = out_is = out_io = None
        for _ in range(reps):
            dt, out_s, st_s = _run_plan(ch_opt, fused=True, prefetch=0)
            t_serial = min(t_serial, dt)
            dt, out_o, st_o = _run_plan(ch_opt, fused=True, prefetch=2)
            t_overlap = min(t_overlap, dt)
            dt, out_is, _ = _run_plan(ch_opt, fused=False, prefetch=0)
            t_iserial = min(t_iserial, dt)
            dt, out_io, _ = _run_plan(ch_opt, fused=False, prefetch=2)
            t_ioverlap = min(t_ioverlap, dt)
        stream_match = (_tables_match(sorted_by_key(out_s),
                                      sorted_by_key(out_o))
                        and _tables_match(sorted_by_key(out_s),
                                          sorted_by_key(out_is))
                        and _tables_match(sorted_by_key(out_is),
                                          sorted_by_key(out_io)))

    seg = SEGMENT_CACHE.stats()
    return {
        "q5_cold_fused_ms": t_cold * 1e3,
        "q5_warm_fused_ms": t_fused * 1e3,
        "q5_warm_interp_ms": t_interp * 1e3,
        "fused_vs_interp": t_interp / t_fused if t_fused else None,
        # headline overlap ratio: the per-chunk-sync streaming loop (PR 1's
        # serial streaming aggregate) — the consumer blocks on every chunk's
        # groupby sync, which is exactly the idle time double-buffered decode
        # hides.  The fused loop's consumer never blocks (async dispatch, one
        # sync at the combine), so on a single-core CPU host its A/B is a
        # wash — reported separately; on a tunneled TPU the fused consumer
        # DOES block on transfers, which is the deploy case for prefetch.
        "stream_serial_ms": t_iserial * 1e3,
        "stream_overlap_ms": t_ioverlap * 1e3,
        "overlap_vs_serial": t_iserial / t_ioverlap if t_ioverlap else None,
        "fused_stream_serial_ms": t_serial * 1e3,
        "fused_stream_overlap_ms": t_overlap * 1e3,
        "fused_overlap_vs_serial": (t_serial / t_overlap
                                    if t_overlap else None),
        "chunks": st_s["chunks"],
        "fused_streamed": bool(st_o["fused_segments"]),
        "results_match": bool(q5_match and stream_match),
        "segment_cache": {"hits": seg["hits"], "misses": seg["misses"],
                          "evictions": seg["evictions"]},
    }


def bench_engine_join(n=400_000, chunk_bytes=512_000, smoke=False):
    """Streamed probe join + streaming top-k vs their PR 2 fallbacks.

    Two A/B pairs on the LOCAL executor, interleaved min-of-reps like
    ``bench_engine_pipeline``:

    - chunked probe join: the fused path prepares the build side (hash +
      stable sort) ONCE via ``BUILD_CACHE`` and probes every chunk inside
      one jitted program, vs the interpreted per-chunk loop that re-runs
      the whole ``inner_join`` — build sort included — on every chunk.
      The cold-cache counter contract (``hits == chunks - 1``) is asserted
      here, not just in tests, so the bench can't silently measure the
      wrong path.
    - ORDER BY ... LIMIT k: the streamed ``TopK`` (capacity-k device
      buffer merged per chunk) vs materializing + fully sorting the table
      (``SRJT_TOPK=0`` semantics), same optimized plan.
    """
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.engine import (Aggregate, BUILD_CACHE, Filter,
                                             Join, Limit, Scan, Sort, col,
                                             lit, optimize)
    from spark_rapids_jni_tpu.ops.order import SortKey
    from spark_rapids_jni_tpu.ops.selection import sort_table
    from spark_rapids_jni_tpu.utils.config import config as cfg
    from spark_rapids_jni_tpu.utils.config import refresh

    rng = np.random.default_rng(17)
    with tempfile.TemporaryDirectory() as tmp:
        root = os.path.join(tmp, "wh")
        os.mkdir(root)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 2_000, n).astype(np.int64)),
            "v": pa.array(rng.uniform(-5.0, 50.0, n)),
        }), os.path.join(root, "fact.parquet"),
            row_group_size=max(1, n // 8))
        pq.write_table(pa.table({
            "dk": pa.array(np.arange(0, 2_000, dtype=np.int64)),
            "dv": pa.array((np.arange(0, 2_000) % 16).astype(np.int64)),
        }), os.path.join(root, "dim.parquet"))

        def fact_scan():
            return Filter(Scan(os.path.join(root, "fact.parquet"),
                               chunk_bytes=chunk_bytes),
                          (">", col("v"), lit(0.0)))

        j_opt = optimize(Aggregate(
            Join(fact_scan(), Scan(os.path.join(root, "dim.parquet")),
                 ["k"], ["dk"], how="inner"),
            ["dv"], [("v", "sum"), ("v", "count")], names=["s", "c"]))
        t_opt = optimize(Limit(Sort(fact_scan(), (("v", False),)), 32))

        def sorted_by_key(t):
            return sort_table(t, [SortKey(t[t.names[0]], ascending=True)])

        reps = 1 if smoke else 3
        _run_plan(j_opt, fused=True, prefetch=0)   # compile warm-up
        _run_plan(j_opt, fused=False, prefetch=0)  # warm interp loop
        t_cached = t_perchunk = float("inf")
        out_c = out_p = st_c = None
        for _ in range(reps):
            dt, out_c, st_c = _run_plan(j_opt, fused=True, prefetch=0)
            t_cached = min(t_cached, dt)
            dt, out_p, _ = _run_plan(j_opt, fused=False, prefetch=0)
            t_perchunk = min(t_perchunk, dt)
        join_match = _tables_match(sorted_by_key(out_c), sorted_by_key(out_p))

        # cold-cache counter contract: exactly one miss, then a hit per
        # remaining chunk
        BUILD_CACHE.clear()
        h0, m0 = BUILD_CACHE.hits, BUILD_CACHE.misses
        _, _, st_cold = _run_plan(j_opt, fused=True, prefetch=0)
        counters_ok = (st_cold["fused_segments"] == 1
                       and BUILD_CACHE.misses - m0 == 1
                       and BUILD_CACHE.hits - h0 == st_cold["chunks"] - 1)

        _run_plan(t_opt, fused=True, prefetch=0)  # warm-up
        t_stream = t_full = float("inf")
        out_ts = out_tf = st_ts = None
        for _ in range(reps):
            dt, out_ts, st_ts = _run_plan(t_opt, fused=True, prefetch=0)
            t_stream = min(t_stream, dt)
            cfg.topk = False
            try:
                dt, out_tf, _ = _run_plan(t_opt, fused=True, prefetch=0)
            finally:
                refresh()
            t_full = min(t_full, dt)
        # ordered compare: tie order is part of the top-k contract
        topk_match = _tables_match(out_ts, out_tf)

    return {
        "join_cached_build_ms": t_cached * 1e3,
        "join_per_chunk_build_ms": t_perchunk * 1e3,
        "cached_vs_per_chunk": (t_perchunk / t_cached
                                if t_cached else None),
        "topk_stream_ms": t_stream * 1e3,
        "topk_full_sort_ms": t_full * 1e3,
        "topk_vs_full_sort": t_full / t_stream if t_stream else None,
        "chunks": st_cold["chunks"],
        "join_streamed_fused": bool(st_c["fused_segments"]),
        "topk_streamed": bool(st_ts["topk"]),
        "build_cache_counters_ok": bool(counters_ok),
        "results_match": bool(join_match and topk_match),
        "build_cache": {k: v for k, v in BUILD_CACHE.stats().items()
                        if k != "maxsize"},
    }


def bench_engine_dist(n_fact=240_000, n_dim=2_000, smoke=False):
    """Partitioning-aware distributed planning: broadcast vs shuffle join.

    The deployment has one physical chip, so (like the SMJ bench above)
    the 8-device plans run in a subprocess on the virtual CPU mesh.  Four
    configurations of the same join+aggregate plan:

    - **broadcast**: dim under ``SRJT_BROADCAST_ROWS`` — the planner
      replicates the build side, probe chunks stream through the fused
      probe-join segment with zero probe-side exchange.
    - **exchange**: ``SRJT_BROADCAST_ROWS=0`` forces hash exchanges on
      both join sides (the partial agg still pushes below its exchange).
    - **smj**: the r5 shuffle+SortMergeJoin comparator
      (``distributed_join``) on the same data, join stage only —
      ``broadcast_vs_smj8`` is the stage-for-stage A/B against the
      broadcast-hash join stage the planner picks (replicate the build +
      shard-local hash probe) on the same in-memory tables.
    - **co-partitioned**: scans declared partitioned on the join keys,
      aggregate grouped on the partition key — must plan ZERO exchanges
      (verified, and the static census must match the executed count).

    Reports wall times, the broadcast_vs_smj8 / broadcast_vs_exchange
    ratios, exchange counts (static and executed), and result parity.
    """
    import subprocess
    import os
    import sys as _sys
    script = f"""
import json, os, tempfile, time
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import spark_rapids_jni_tpu
import jax
root = tempfile.mkdtemp()
rng = np.random.default_rng(9)
nf, nd = {n_fact}, {n_dim}
# a wide fact: the shuffle pays wire for every payload column, the
# broadcast join pays none of them (the representative star-schema case)
k = rng.integers(0, nd, nf)
v = np.round(rng.uniform(0, 100, nf), 3)
v2 = rng.integers(-100, 100, nf)
v3 = rng.integers(0, 1000, nf)
pq.write_table(pa.table({{"k": pa.array(k, pa.int64()),
                          "v": pa.array(v, pa.float64()),
                          "v2": pa.array(v2, pa.int64()),
                          "v3": pa.array(v3, pa.int64())}}),
               os.path.join(root, "fact.parquet"), row_group_size=32_000)
dk = np.arange(nd, dtype=np.int64)
pq.write_table(pa.table({{"dk": pa.array(dk), "grp": pa.array(dk % 7)}}),
               os.path.join(root, "dim.parquet"))

from spark_rapids_jni_tpu.engine import (Aggregate, Join, Scan, execute,
                                         new_stats, optimize)
from spark_rapids_jni_tpu.engine.verify import (check_partitioning,
                                                plan_exchanges, verify)
from spark_rapids_jni_tpu.utils.config import refresh
fact, dim = os.path.join(root, "fact.parquet"), os.path.join(root,
                                                             "dim.parquet")

def mkplan(**scan_kw):
    j = Join(Scan(fact, chunk_bytes=192_000, **scan_kw.get("f", {{}})),
             Scan(dim, **scan_kw.get("d", {{}})), ("k",), ("dk",), "inner")
    return Aggregate(j, ("grp",),
                     (("v", "sum"), ("v2", "sum"), ("v3", "sum"),
                      ("v", "count")),
                     ("total", "t2", "t3", "n"))

def timed(opt):
    stats = new_stats()
    execute(opt, new_stats())                       # warm (compile)
    t0 = time.perf_counter()
    out = execute(opt, stats)
    jax.block_until_ready([c.data for c in out.columns])
    return time.perf_counter() - t0, out, stats

def norm(t):
    cols = sorted(zip(t.names, (c.to_numpy() for c in t.columns)))
    order = np.argsort(cols[0][1], kind="stable")
    return [(n, np.round(a[order], 4).tolist()) for n, a in cols]

base_t, base, _ = timed(optimize(mkplan()))

optA = optimize(mkplan(), distribute=True)
exA = plan_exchanges(optA)
tA, outA, stA = timed(optA)

os.environ["SRJT_BROADCAST_ROWS"] = "0"
refresh()
optB = optimize(mkplan(), distribute=True)
exB = plan_exchanges(optB)
tB, outB, stB = timed(optB)

# per-device exchange attribution of the hash-exchange run just timed:
# the per-(src, dest) wire matrix must sum EXACTLY to the query's
# engine.exchange.wire_bytes counter (the invariant premerge asserts)
from spark_rapids_jni_tpu.utils import metrics as _m
dev_attrib = {{"matrix_matches": None, "skew": None, "max_dev_rows": None,
               "wire_matrix_sum": None, "wire_bytes_counter": None,
               "exchange_nodes": 0, "explain_skew_rendered": None}}
if _m.enabled():
    summ = _m.recent_summaries()[-1]
    ex_nodes = [n for n in summ["nodes"] if n.get("wire_matrix")]
    mat_sum = sum(sum(r) for n in ex_nodes for r in n["wire_matrix"])
    ctr = summ["counters"].get("engine.exchange.wire_bytes", 0)
    dev_attrib.update(
        exchange_nodes=len(ex_nodes),
        wire_matrix_sum=mat_sum, wire_bytes_counter=ctr,
        matrix_matches=bool(ex_nodes) and mat_sum == ctr,
        skew=max(n.get("skew") or 0.0 for n in ex_nodes)
        if ex_nodes else None,
        max_dev_rows=max(n.get("max_dev_rows") or 0 for n in ex_nodes)
        if ex_nodes else None)
    # and the rendered EXPLAIN ANALYZE must carry the skew columns on the
    # same forced-exchange plan shape (SRJT_DIST routes optimize())
    os.environ["SRJT_DIST"] = "1"
    refresh()
    from spark_rapids_jni_tpu.engine.explain import explain_analyze
    rep = explain_analyze(mkplan())
    dev_attrib["explain_skew_rendered"] = "skew=" in rep.text
    # the AQE evidence plane on the same report: every plan-node line must
    # carry the cardinality columns, and the decision footer's structural
    # entry count must equal the static census of the optimized plan
    from spark_rapids_jni_tpu.engine.verify import decision_census
    node_lines = [ln for ln in rep.text.splitlines()
                  if ln.strip() and not ln.lstrip().startswith("--")]
    cen = decision_census(optimize(mkplan(), distribute=True), dist=True)
    # runtime (adaptive:*) entries carry a path too but are deliberately
    # outside the static census — census counts PLANNED structure only
    pathed = sum(1 for d in rep.decisions
                 if "path" in d and not d.get("runtime"))
    dev_attrib["evidence"] = {{
        "node_lines_annotated": all("est_rows=" in ln and "q_error=" in ln
                                    for ln in node_lines),
        "decisions": len(rep.decisions),
        "decisions_pathed": pathed,
        "census": len(cen),
        "census_matches": pathed == len(cen),
        "footer_rendered":
            ("-- decisions (" + str(len(rep.decisions)) + "):") in rep.text,
    }}
    del os.environ["SRJT_DIST"]

del os.environ["SRJT_BROADCAST_ROWS"]
refresh()

# join-stage A/B on the same in-memory tables: the r5 comparator
# (shuffle both sides + SortMergeJoin) vs the broadcast-hash stage the
# planner picks (replicate the build, probe shard-locally)
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.join import inner_join
from spark_rapids_jni_tpu.parallel import distributed_join, make_mesh
from spark_rapids_jni_tpu.parallel.mesh import broadcast_table
mesh = make_mesh(8)
lt = Table([Column.from_numpy(k.astype(np.int64)),
            Column.from_numpy(np.arange(nf, dtype=np.int64)),
            Column.from_numpy(v2.astype(np.int64)),
            Column.from_numpy(v3.astype(np.int64))],
           ["k", "v", "v2", "v3"])
rt = Table([Column.from_numpy(dk), Column.from_numpy(dk % 7)],
           ["k", "grp"])
distributed_join(lt, rt, mesh, ["k"])   # warm
t0 = time.perf_counter()
smj = distributed_join(lt, rt, mesh, ["k"])
tC = time.perf_counter() - t0
inner_join(lt, broadcast_table(rt, mesh), ["k"])   # warm
t0 = time.perf_counter()
bj = inner_join(lt, broadcast_table(rt, mesh), ["k"])
jax.block_until_ready([c.data for c in bj.columns])
tJ = time.perf_counter() - t0
assert bj.num_rows == smj.num_rows

optD = optimize(Aggregate(
    Join(Scan(fact, partitioned_by=("k",)),
         Scan(dim, partitioned_by=("dk",)), ("k",), ("dk",), "inner"),
    ("k",), (("v", "sum"),), ("total",)), distribute=True)
verify(optD)
check_partitioning(optD)
exD = plan_exchanges(optD)
stD = new_stats()
execute(optD, stD)

print(json.dumps({{
    "local_s": base_t, "broadcast_s": tA, "exchange_s": tB, "smj_s": tC,
    "bjoin_s": tJ,
    "ratios": {{"broadcast_vs_smj8": tC / tJ if tJ else None,
                "broadcast_vs_exchange": tB / tA if tA else None}},
    "exchanges": {{"broadcast_static": len(exA),
                   "broadcast_executed": stA["exchanges"],
                   "exchange_static": len(exB),
                   "exchange_executed": stB["exchanges"],
                   "copartitioned_static": len(exD),
                   "copartitioned_executed": stD["exchanges"]}},
    "smj_rows": smj.num_rows,
    "device_attrib": dev_attrib,
    "results_match": bool(norm(outA) == norm(base)
                          and norm(outB) == norm(base))}}))
"""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"),
               JAX_ENABLE_X64="1")
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run([_sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=900)
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            print(f"engine-dist bench failed (rc={r.returncode}):\n"
                  f"{r.stderr[-2000:]}", file=_sys.stderr)
            return None
        return json.loads(lines[-1])
    except Exception as e:
        print(f"engine-dist bench failed: {e!r}", file=_sys.stderr)
        return None


def bench_engine_fused_stage(n_fact=240_000, n_keys=2_000, smoke=False):
    """Whole-stage fusion across the exchange (SRJT_FUSE_EXCHANGE): the
    ``partial-agg -> hash Exchange -> final-agg`` sandwich lowered into ONE
    ``jax.jit(shard_map(...))`` program vs the host-orchestrated exchange
    path on the same plan (8-device virtual CPU mesh, subprocess like the
    other dist benches).

    The plan is the dist smoke shape: a chunked scan feeding the grouped
    aggregate (the host path streams the partial agg chunk-by-chunk and
    then orchestrates the exchange with two deliberate syncs; the fused
    path runs the whole stage as one program).  ``SRJT_FUSE_GROUPS`` is
    sized at 2x the workload's distinct-key count — the documented
    operator sizing for the static in-program exchange.

    Both paths are compile-warmed, then timed (min of 3).  A scan-only
    plan (same file, same chunking) is timed the same way and subtracted
    from both walls: the two paths pay an identical chunked parquet scan,
    so ``vs_host_exchange`` compares the exchange STAGE (partial agg ->
    exchange -> final agg) the fusion actually replaces; the raw
    end-to-end walls and their ratio (``vs_host_e2e``) are reported
    alongside.  Also reports the host-sync counter deltas of each timed
    run (the fused run must pay exactly its static ``verify.sync_budget``),
    the exchange census (static == executed on both paths), and bit-exact
    result parity.
    """
    import subprocess
    import os
    import sys as _sys
    script = f"""
import json, os, tempfile, time
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import spark_rapids_jni_tpu
import jax
root = tempfile.mkdtemp()
rng = np.random.default_rng(17)
nf, nk = {n_fact}, {n_keys}
k = rng.integers(0, nk, nf)
# quarter-grid floats: partial-then-combine sums are exactly representable,
# so fused-vs-host parity is bit-exact despite reduction-order differences
v = (rng.integers(0, 400, nf) * 0.25).astype(np.float64)
v2 = rng.integers(-100, 100, nf)
pq.write_table(pa.table({{"k": pa.array(k, pa.int64()),
                          "v": pa.array(v, pa.float64()),
                          "v2": pa.array(v2, pa.int64())}}),
               os.path.join(root, "fact.parquet"), row_group_size=32_000)
fact = os.path.join(root, "fact.parquet")

from spark_rapids_jni_tpu.engine import (Aggregate, Scan, execute,
                                         new_stats, optimize)
from spark_rapids_jni_tpu.engine.verify import plan_exchanges, sync_budget
from spark_rapids_jni_tpu.utils import tracing
from spark_rapids_jni_tpu.utils.config import config, refresh

def mkplan():
    return Aggregate(Scan(fact, chunk_bytes=192_000), ("k",),
                     (("v", "sum"), ("v2", "sum"), ("v", "count")),
                     ("total", "t2", "n"))

def syncs():
    return tracing.counters_snapshot("engine.host_sync") \\
        .get("engine.host_sync", 0)

def timed(opt):
    execute(opt, new_stats())                       # warm (compile)
    best, out, stats, dsync = None, None, None, None
    for _ in range(3):
        st = new_stats()
        s0 = syncs()
        t0 = time.perf_counter()
        o = execute(opt, st)
        jax.block_until_ready([c.data for c in o.columns])
        dt = time.perf_counter() - t0
        if best is None or dt < best:
            best, out, stats, dsync = dt, o, st, syncs() - s0
    return best, out, stats, dsync

def norm(t):
    cols = sorted(zip(t.names, (c.to_numpy() for c in t.columns)))
    order = np.argsort(cols[0][1], kind="stable")
    return [(n, np.asarray(a)[order].tolist()) for n, a in cols]

# scan-only baseline: both paths pay this identical chunked scan, so the
# exchange-stage comparison subtracts it from both walls (raw walls are
# reported too — nothing rides on the subtraction being hidden)
optS = optimize(Scan(fact, chunk_bytes=192_000), distribute=True)
tS, _, _, _ = timed(optS)

# host-orchestrated exchange (the pre-fusion distributed path)
optH = optimize(mkplan(), distribute=True)
exH = plan_exchanges(optH)
tH, outH, stH, syH = timed(optH)

# fused whole-stage program; the static group budget sized at 2x the
# workload's distinct keys (the documented operator sizing — overflow
# would fall back to the host path, which the dispatch counter catches)
os.environ["SRJT_FUSE_EXCHANGE"] = "1"
os.environ["SRJT_FUSE_GROUPS"] = str(2 * nk)
refresh()
optF = optimize(mkplan(), distribute=True)
exF = plan_exchanges(optF)
budget = sync_budget(optF, cfg=config)
d0 = tracing.counters_snapshot("engine.fused_stage.dispatches") \\
    .get("engine.fused_stage.dispatches", 0)
tF, outF, stF, syF = timed(optF)
dispatches = tracing.counters_snapshot("engine.fused_stage.dispatches") \\
    .get("engine.fused_stage.dispatches", 0) - d0
del os.environ["SRJT_FUSE_EXCHANGE"]
del os.environ["SRJT_FUSE_GROUPS"]
refresh()

print(json.dumps({{
    "host_s": tH, "fused_s": tF, "scan_s": tS,
    "vs_host_exchange": (tH - tS) / max(tF - tS, 1e-9),
    "vs_host_e2e": tH / tF if tF else None,
    "host_syncs": {{"host": syH, "fused": syF,
                    "fused_budget": sum(e["count"] for e in budget)}},
    "dispatches": dispatches,
    "exchanges": {{"host_static": len(exH),
                   "host_executed": stH["exchanges"],
                   "fused_static": len(exF),
                   "fused_executed": stF["exchanges"]}},
    "results_match": bool(norm(outF) == norm(outH))}}))
"""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"),
               JAX_ENABLE_X64="1")
    env.pop("SRJT_FUSE_EXCHANGE", None)
    env.pop("SRJT_FUSE_GROUPS", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run([_sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=900)
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            print(f"engine-fused-stage bench failed (rc={r.returncode}):\n"
                  f"{r.stderr[-2000:]}", file=_sys.stderr)
            return None
        return json.loads(lines[-1])
    except Exception as e:
        print(f"engine-fused-stage bench failed: {e!r}", file=_sys.stderr)
        return None


def bench_engine_aqe(n_fact=240_000, n_keys=2_000, smoke=False):
    """Adaptive execution (SRJT_AQE) A/Bs on the virtual 8-device mesh.

    Two experiments, both with runtime rewrites verified and parity
    asserted against the AQE-off single-device plan:

    - **skewed vs balanced twin**: the same groupby-mean plan over two
      facts that differ only in key distribution (half the skewed fact
      sits on ONE key).  mean is non-decomposable, so the FULL input
      crosses the exchange on the group key — without AQE the hot
      destination inflates the padded all_to_all capacity for every
      device.  With ``SRJT_AQE=1`` the skew-split rule re-deals the hot
      destinations' rows round-robin; ``skew_ratio`` (skewed / balanced
      wall time, both AQE-on) is the headline, with the applied
      ``adaptive:skew_split`` ledger entry and the post-split
      ``engine.exchange.skew`` gauge as the structural evidence.
    - **repeat-query cold vs warmed**: a join whose build side is a
      selective Filter — the footer estimate (the UN-filtered row count)
      sits above the broadcast threshold so run 1 plans a shuffle join,
      but the measured actual sits below it.  Run 2 of the same source
      fingerprint reads run 1's profile (``SRJT_PROFILE_DIR``) and plans
      the broadcast join outright (``adaptive:history_warmed``);
      ``rerun_vs_first`` is warmed / cold wall time.

    Wall-clock ratios are gated report-only (BENCH_BASELINES.json —
    machine noise at smoke scale); the structural evidence on this line
    is what ci/premerge.sh asserts.
    """
    import subprocess
    import os
    import sys as _sys
    script = f"""
import json, os, tempfile, time
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import spark_rapids_jni_tpu
import jax
root = tempfile.mkdtemp()
rng = np.random.default_rng(21)
nf, nk = {n_fact}, {n_keys}

from spark_rapids_jni_tpu.engine import (Aggregate, Filter, Join, Scan, col,
                                         execute, lit, new_stats, optimize)
from spark_rapids_jni_tpu.engine.plan import Exchange, topo_nodes
from spark_rapids_jni_tpu.utils import metrics as _m
from spark_rapids_jni_tpu.utils.config import refresh

v = np.round(rng.uniform(0, 100, nf), 3)
k_bal = rng.integers(0, nk, nf)
k_skew = k_bal.copy()
k_skew[: nf // 2] = 3      # one hot key: half the fact routes to one device
for name, kk in (("bal", k_bal), ("skew", k_skew)):
    pq.write_table(pa.table({{"k": pa.array(kk, pa.int64()),
                              "v": pa.array(v, pa.float64())}}),
                   os.path.join(root, name + ".parquet"),
                   row_group_size=32_000)

def meanplan(path):
    # mean is non-decomposable: no partial pushes below the exchange, the
    # full input crosses the wire keyed on k — a hot key is a genuinely
    # hot destination device, the shape the skew-split rule exists for
    return Aggregate(Scan(path), ("k",), (("v", "mean"),), ("m",))

def timed(opt):
    stats = new_stats()
    execute(opt, new_stats())                       # warm (compile)
    t0 = time.perf_counter()
    out = execute(opt, stats)
    jax.block_until_ready([c.data for c in out.columns])
    return time.perf_counter() - t0, out, stats

def norm(t):
    cols = sorted(zip(t.names, (c.to_numpy() for c in t.columns)))
    order = np.argsort(cols[0][1], kind="stable")
    return [(n, np.round(a[order], 4).tolist()) for n, a in cols]

# -- skewed vs balanced twin, both under AQE --------------------------------
SKEW_THRESHOLD = 2.0
os.environ["SRJT_AQE"] = "1"
os.environ["SRJT_AQE_SKEW"] = str(SKEW_THRESHOLD)
refresh()
t_bal, out_bal, st_bal = timed(optimize(
    meanplan(os.path.join(root, "bal.parquet")), distribute=True))
opt_skew = optimize(meanplan(os.path.join(root, "skew.parquet")),
                    distribute=True)
t_skew, out_skew, st_skew = timed(opt_skew)
splits = [d for d in getattr(opt_skew, "_decisions", ())
          if d.get("kind") == "adaptive:skew_split" and d.get("triggered")]
# the gauge holds the LAST exchange's post-placement skew — the skewed
# run's split exchange, read before anything else executes
gauge_skew = (_m.gauges_snapshot("engine.exchange.skew")
              .get("engine.exchange.skew") if _m.enabled() else None)

os.environ["SRJT_AQE"] = "0"
refresh()
base_skew = execute(optimize(meanplan(os.path.join(root, "skew.parquet"))),
                    new_stats())
base_bal = execute(optimize(meanplan(os.path.join(root, "bal.parquet"))),
                   new_stats())
skew_parity = bool(norm(out_skew) == norm(base_skew)
                   and norm(out_bal) == norm(base_bal))

# -- repeat-query cold vs history-warmed ------------------------------------
# fresh store: the newest-profile-by-fingerprint lookup must see exactly
# run 1, not whatever the inherited smoke store holds
os.environ["SRJT_PROFILE_DIR"] = tempfile.mkdtemp(prefix="srjt-aqe-warm-")
os.environ["SRJT_AQE"] = "1"
os.environ["SRJT_BROADCAST_ROWS"] = "100"
refresh()
nd = 500
dk = np.arange(nd, dtype=np.int64)
pq.write_table(pa.table({{"dk": pa.array(dk), "grp": pa.array(dk % 7)}}),
               os.path.join(root, "dim.parquet"))
# a WIDE fact for the repeat-query A/B: the cold shuffle join pays wire
# for every payload column, the warmed broadcast join pays none of them —
# the same asymmetry the dist bench measures, here it is what makes run 2
# strictly faster rather than noise-level
pq.write_table(pa.table({{"k": pa.array(k_bal, pa.int64()),
                          "v": pa.array(v, pa.float64()),
                          "v2": pa.array(rng.integers(-100, 100, nf),
                                         pa.int64()),
                          "v3": pa.array(rng.integers(0, 1000, nf),
                                         pa.int64())}}),
               os.path.join(root, "warm.parquet"), row_group_size=32_000)

def joinplan():
    # the Filter keeps 50 of 500 dim rows; the footer estimate is the
    # UN-filtered 500 (> broadcast threshold 100) so the cold run plans a
    # shuffle join — the measured actual (50, under the threshold) is
    # what run 2 warms from
    dim = Filter(Scan(os.path.join(root, "dim.parquet")),
                 ("<", col("dk"), lit(50)))
    # unchunked probe: both plans materialize the fact once, so the A/B
    # isolates the planned exchange (what warming removes) instead of
    # mixing in per-chunk dispatch overhead on the shared-core mesh
    j = Join(Scan(os.path.join(root, "warm.parquet")),
             dim, ("k",), ("dk",), "inner")
    return Aggregate(j, ("grp",),
                     (("v", "sum"), ("v2", "sum"), ("v3", "sum"),
                      ("v", "count")),
                     ("total", "t2", "t3", "n"))

def kinds(opt):
    return sorted(e.kind for e in topo_nodes(opt) if isinstance(e, Exchange))

opt1 = optimize(joinplan(), distribute=True)
t1, out1, st1 = timed(opt1)
opt2 = optimize(joinplan(), distribute=True)    # reads run 1's profile
t2, out2, st2 = timed(opt2)
warmed = [d for d in getattr(opt2, "_decisions", ())
          if d.get("kind") == "adaptive:history_warmed"]
warm_parity = bool(norm(out1) == norm(out2))

print(json.dumps({{
    "balanced_s": t_bal, "skewed_s": t_skew,
    "skew_ratio": t_skew / t_bal if t_bal else None,
    "skew": {{"splits_applied": len(splits),
              "aqe_splits": st_skew["aqe_splits"],
              "pre_skew": splits[0].get("measured_skew") if splits else None,
              "post_skew": splits[0].get("post_skew") if splits else None,
              "gauge_skew": gauge_skew,
              "threshold": SKEW_THRESHOLD,
              "parity": skew_parity}},
    "first_s": t1, "rerun_s": t2,
    "rerun_vs_first": t2 / t1 if t1 else None,
    "warm": {{"warmed_entries": len(warmed),
              "choice": warmed[0].get("choice") if warmed else None,
              "run1_kinds": kinds(opt1), "run2_kinds": kinds(opt2),
              "run1_flips": st1["aqe_flips"],
              "run2_broadcast_planned": bool(
                  "broadcast" in kinds(opt2)
                  and "broadcast" not in kinds(opt1)),
              "faster": bool(t2 < t1),
              "parity": warm_parity}}}}))
"""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"),
               JAX_ENABLE_X64="1",
               # gauge + profile evidence need the metrics layer on even
               # when the parent runs bare
               SRJT_METRICS="1")
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run([_sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=900)
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            print(f"engine-aqe bench failed (rc={r.returncode}):\n"
                  f"{r.stderr[-2000:]}", file=_sys.stderr)
            return None
        return json.loads(lines[-1])
    except Exception as e:
        print(f"engine-aqe bench failed: {e!r}", file=_sys.stderr)
        return None


def _serving_plans(root, chunk_bytes, k, base=1.0):
    """k distinct-fingerprint chunked aggregates over the warehouse.

    Same shape (filter + partial groupby, the fused streaming segment),
    different filter literal per plan — so every plan is its own plan-cache
    / result-cache entry and its own scheduler fingerprint, like k tenants
    running k different queries of the same family.
    """
    from spark_rapids_jni_tpu.engine import Aggregate, Filter, Scan, col, lit
    sales = os.path.join(root, "store_sales.parquet")
    return [Aggregate(
        Filter(Scan(sales, chunk_bytes=chunk_bytes),
               (">", col("ss_ext_sales_price"), lit(base + 0.25 * i))),
        ["ss_store_sk"],
        [("ss_ext_sales_price", "sum"), ("ss_net_profit", "sum"),
         ("ss_ext_sales_price", "count")],
        names=["sales", "profit", "n"]) for i in range(k)]


def bench_engine_serving(n=240_000, clients=8, smoke=False):
    """Multi-tenant serving: N concurrent sessions vs the same N queries
    serial, plus the admission controller's shed path and the result-set
    cache, all against real subprocess servers (engine/scheduler.py,
    docs/SERVING.md).

    Server A (scheduler on, result cache OFF so every pass really
    executes): warm all plans once, then time a serial pass (one client,
    N queries back-to-back) vs a concurrent pass (N clients, one query
    each) of the SAME plans — per-trace results must be bit-exact across
    the two passes.  Reports per-query p50/p99 under contention, aggregate
    throughput, and the concurrent-vs-serial throughput ratio.

    Server B (1 session slot, SRJT_SLO_MS=1 so every run burns its error
    budget, profile store on, result cache on): a repeat plan over
    unchanged inputs must serve from the result cache (speedup = cold /
    warm), and while a long holder query occupies the only slot, a
    fingerprint with burn >= SRJT_ADMISSION_BURN must be shed immediately
    with the typed ``AdmissionRejectedError`` carrying trace_id + bundle
    pointer — the client-side contract for load-shedding.
    """
    import tempfile
    import threading

    from spark_rapids_jni_tpu.bridge import BridgeClient, spawn_server
    from spark_rapids_jni_tpu.utils.errors import AdmissionRejectedError

    rng = np.random.default_rng(29)
    out: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        root = os.path.join(tmp, "wh")
        os.mkdir(root)
        _pipeline_warehouse(root, n, rng)
        chunk = 64_000 if smoke else 512_000
        plans = _serving_plans(root, chunk, clients)

        # --- server A: serial vs concurrent on the same warm plans -------
        sock = os.path.join(tmp, "srv.sock")
        proc = spawn_server(sock, env={
            "SRJT_MAX_SESSIONS": str(clients),
            "SRJT_RESULT_CACHE": "0",   # measure execution, not the cache
        })
        try:
            warm = BridgeClient(sock)
            for p in plans:   # compile + warm jit caches once per plan
                for h in warm.execute_plan(p):
                    warm.release(h)

            serial_tabs = {}
            t0 = time.perf_counter()
            for i, p in enumerate(plans):
                hs = warm.execute_plan(p)
                serial_tabs[i] = warm.export_table(hs[0])
                for h in hs:
                    warm.release(h)
            serial_s = time.perf_counter() - t0
            warm.close()

            lat: dict = {}
            conc_tabs: dict = {}
            errs: list = []
            start = threading.Barrier(clients + 1)

            def one(i):
                try:
                    c = BridgeClient(sock)
                    start.wait()
                    q0 = time.perf_counter()
                    hs = c.execute_plan(plans[i])
                    conc_tabs[i] = c.export_table(hs[0])
                    lat[i] = time.perf_counter() - q0
                    for h in hs:
                        c.release(h)
                    c.close()
                except Exception as e:  # noqa: BLE001 — reported below
                    errs.append((i, repr(e)))

            ts = [threading.Thread(target=one, args=(i,), daemon=True)
                  for i in range(clients)]
            for t in ts:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in ts:
                t.join(timeout=300)
            concurrent_s = time.perf_counter() - t0

            parity = (not errs and len(conc_tabs) == clients and all(
                _tables_match(conc_tabs[i], serial_tabs[i])
                for i in range(clients)))
            c2 = BridgeClient(sock)
            sched = c2.serving_stats()["scheduler"]
            c2.shutdown_server()
        except Exception as e:
            print(f"engine-serving bench failed: {e!r}", file=sys.stderr)
            proc.kill()
            return None
        finally:
            proc.wait(timeout=30)

        samples = sorted(lat.values())
        p50 = samples[len(samples) // 2] if samples else 0.0
        p99 = samples[min(len(samples) - 1,
                          int(len(samples) * 0.99))] if samples else 0.0
        throughput = clients / concurrent_s if concurrent_s else 0.0
        serial_tp = clients / serial_s if serial_s else 0.0
        out.update({
            "clients": clients, "errors": errs,
            "parity": parity,
            "serial_s": serial_s, "concurrent_s": concurrent_s,
            "p50_ms": p50 * 1e3, "p99_ms": p99 * 1e3,
            "throughput_qps": throughput,
            "throughput_ratio": (throughput / serial_tp
                                 if serial_tp else None),
            "admitted": sched.get("admitted", 0),
            "rounds": sched.get("rounds", 0),
        })

        # --- server B: result cache + SLO-burn shed ----------------------
        prof_dir = os.path.join(tmp, "profiles")
        os.mkdir(prof_dir)
        sock2 = os.path.join(tmp, "srv2.sock")
        proc2 = spawn_server(sock2, env={
            "SRJT_MAX_SESSIONS": "1",
            "SRJT_ADMISSION_QUEUE_S": "2.0",
            "SRJT_RESULT_CACHE": "16",
            "SRJT_SLO_MS": "1",          # everything breaches: burn = 1.0
            "SRJT_PROFILE_DIR": prof_dir,
            # bundle dir so the typed shed error carries a post-mortem
            # pointer (the client-side contract: trace_id + bundle)
            "SRJT_BLACKBOX_DIR": os.path.join(tmp, "bb"),
        })
        try:
            c = BridgeClient(sock2)
            rc_plan = plans[0]
            t0 = time.perf_counter()
            for h in c.execute_plan(rc_plan):   # cold: executes + caches
                c.release(h)
            rc_cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            for h in c.execute_plan(rc_plan):   # warm: result-cache hit
                c.release(h)
            rc_warm = time.perf_counter() - t0
            rc_hits = c.serving_stats()["result_cache"]["hits"]

            # burn plan: one profiled run (wall >> 1ms => burn 1.0), then
            # an mtime bump so the repeat MISSES the result cache and has
            # to face admission while the holder owns the only slot
            burn_plan = plans[1] if clients > 1 else plans[0]
            for h in c.execute_plan(burn_plan):
                c.release(h)
            sales = os.path.join(root, "store_sales.parquet")
            os.utime(sales)

            holder_plans = _serving_plans(root, 4_096, 3, base=100.0)
            holder_done = threading.Event()

            def hold(p):
                try:
                    hc = BridgeClient(sock2)
                    for h in hc.execute_plan(p):
                        hc.release(h)
                    hc.close()
                finally:
                    holder_done.set()

            shed = None
            for attempt, hp in enumerate(holder_plans):
                holder_done.clear()
                ht = threading.Thread(target=hold, args=(hp,), daemon=True)
                ht.start()
                time.sleep(0.4)   # let the holder take the slot
                if holder_done.is_set():
                    continue      # holder too fast: try a fresh one
                try:
                    hs = c.execute_plan(burn_plan)
                    for h in hs:
                        c.release(h)
                except AdmissionRejectedError as e:
                    shed = {"kind": e.kind, "retryable": e.retryable,
                            "trace_id": e.trace_id or "",
                            "bundle": getattr(e, "bundle_path", "") or "",
                            "message": str(e)[:120]}
                ht.join(timeout=300)
                if shed is not None:
                    break
            stats2 = c.serving_stats()
            c.shutdown_server()
        except Exception as e:
            print(f"engine-serving bench failed: {e!r}", file=sys.stderr)
            proc2.kill()
            return None
        finally:
            proc2.wait(timeout=30)

        out.update({
            "result_cache_cold_ms": rc_cold * 1e3,
            "result_cache_warm_ms": rc_warm * 1e3,
            "result_cache_speedup": (rc_cold / rc_warm) if rc_warm else None,
            "result_cache_hits": rc_hits,
            "shed": shed,
            "shed_count": stats2["scheduler"].get("shed", 0),
        })
    return out


def bench_parquet_device_decode(n=400_000, smoke=False):
    """Device-side Parquet decode (SRJT_DEVICE_DECODE): raw compressed
    pages shipped over the link and decoded in-kernel (ops/parquet_decode)
    vs the staged host path (pyarrow decode + pad + ship) on the same
    snappy+PLAIN int64 file.

    Three measurements:
      - kernel A/B on an incompressible file (random int64): a jitted
        ``decode_table`` over planned page chunks vs a warm
        ``ParquetChunkedReader.iter_staged`` pass, pyarrow alongside for
        scale; parity is bit-exact per row group against pyarrow's own
        decode.  The MB/s ratio is machine- and backend-dependent (on the
        CPU backend XLA's per-element gathers lose to pyarrow's SIMD
        decode shuffles), so it is gated report-only; correctness +
        engagement are the hard signal.
      - link bytes on a COMPRESSIBLE twin: compressed page bytes shipped
        (sum of ``DevicePageChunk.comp_bytes``) vs the uncompressed bytes
        the host path must move — the transfer-volume win the device path
        exists for.  The twin's snappy stream carries back-references, so
        this also runs the copy-resolution kernel at bench scale with
        bit-exact parity.
      - engine E2E: the same aggregate plan with the flag off vs on —
        bit-exact results, a ``scan:device_decode choice=device`` ledger
        entry covering every chunk with zero host fallbacks, and
        ``decode=device`` rendered on the EXPLAIN ANALYZE scan line.
    """
    import tempfile
    import time
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    import jax
    import spark_rapids_jni_tpu.utils.config as cfgmod
    from spark_rapids_jni_tpu.io import ParquetChunkedReader
    from spark_rapids_jni_tpu.io import parquet as pqio
    from spark_rapids_jni_tpu.ops import parquet_decode as pqd

    root = tempfile.mkdtemp(prefix="srjt-devdec-")
    rng = np.random.default_rng(5)
    path = os.path.join(root, "rand.parquet")
    pq.write_table(pa.table({
        "a": pa.array(rng.integers(0, 1 << 62, n), type=pa.int64()),
        "b": pa.array(rng.integers(0, 1 << 62, n), type=pa.int64()),
    }), path, row_group_size=max(n // 4, 1_000), compression="snappy",
        use_dictionary=False)

    def plan_all(pf):
        chunks = []
        for gi in range(pf.num_row_groups):
            c, reason = pqio.plan_device_group(pf, gi, None, 1 << 30)
            if c is None:
                raise RuntimeError(f"device plan rejected group {gi}: "
                                   f"{reason}")
            chunks.append(c)
        return chunks

    jfn = jax.jit(pqd.decode_table, static_argnums=1)

    def parity_all(path, chunks):
        pf_ref = pq.ParquetFile(path)
        for gi, c in enumerate(chunks):
            out = jfn(c.to_device(), c.geom)
            ref = pf_ref.read_row_group(gi)
            for nm, col in zip(out.names, out.columns):
                dev = np.asarray(col.data)[:c.nrows]
                if not np.array_equal(dev, ref[nm].to_numpy()):
                    return False
        return True

    pf = pqio.ParquetFile(path)
    chunks = plan_all(pf)
    parity = parity_all(path, chunks)

    # device timing: planes staged ahead (the engine's prefetch does the
    # same), the jitted decode is what's on the clock
    staged = [(c.to_device(), c.geom) for c in chunks]
    out = jfn(*staged[0])
    jax.block_until_ready([c.data for c in out.columns])  # warm compile
    t0 = time.perf_counter()
    for planes, geom in staged:
        out = jfn(planes, geom)
    jax.block_until_ready([c.data for c in out.columns])
    dev_s = time.perf_counter() - t0
    unc = sum(c.unc_bytes for c in chunks)

    def host_pass():
        rd = ParquetChunkedReader(path, pass_read_limit=1 << 30)
        last = None
        for tbl, _nv in rd.iter_staged():
            last = tbl
        jax.block_until_ready([c.data for c in last.columns])
        rd.close()

    host_pass()  # warm the unpack compile
    t0 = time.perf_counter()
    host_pass()
    host_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    pq.read_table(path)
    arrow_s = time.perf_counter() - t0

    # compressible twin: small repeating values -> snappy back-references
    cpath = os.path.join(root, "comp.parquet")
    pq.write_table(pa.table({
        "a": pa.array((np.arange(n) % 97).astype(np.int64)),
        "b": pa.array(np.repeat(np.arange(n // 100 + 1), 100)[:n]
                      .astype(np.int64)),
    }), cpath, row_group_size=max(n // 4, 1_000), compression="snappy",
        use_dictionary=False)
    cchunks = plan_all(pqio.ParquetFile(cpath))
    cparity = parity_all(cpath, cchunks)
    link_bytes = sum(c.comp_bytes for c in cchunks)
    host_bytes = sum(c.unc_bytes for c in cchunks)

    # engine E2E: flag off vs on, same plan, ledgered device engagement
    from spark_rapids_jni_tpu.engine import (Aggregate, Scan, execute,
                                             new_stats, optimize)
    from spark_rapids_jni_tpu.engine.explain import explain_analyze
    eplan = Aggregate(Scan(path, chunk_bytes=1 << 20), ["a"],
                      [("b", "max"), (None, "count_all")], names=["m", "c"])
    host_out = execute(optimize(eplan), new_stats())
    prev = os.environ.get("SRJT_DEVICE_DECODE")
    try:
        os.environ["SRJT_DEVICE_DECODE"] = "1"
        cfgmod.refresh()
        rep = explain_analyze(eplan, distribute=False)
    finally:
        if prev is None:
            os.environ.pop("SRJT_DEVICE_DECODE", None)
        else:
            os.environ["SRJT_DEVICE_DECODE"] = prev
        cfgmod.refresh()

    def norm(t):
        cols = {nm: np.asarray(c.data) for nm, c in zip(t.names, t.columns)}
        order = np.argsort(cols["a"], kind="stable")
        return [(nm, cols[nm][order].tolist()) for nm in sorted(cols)]

    dd = next((d for d in rep.decisions
               if d["kind"] == "scan:device_decode" and d.get("runtime")),
              {})
    return {
        "device_s": dev_s, "host_s": host_s, "arrow_s": arrow_s,
        "device_MBps": unc / dev_s / 1e6,
        "host_MBps": unc / host_s / 1e6,
        "arrow_MBps": unc / arrow_s / 1e6,
        "device_vs_host": host_s / dev_s if dev_s else None,
        "parity": bool(parity), "compressible_parity": bool(cparity),
        "link_bytes": link_bytes, "host_bytes": host_bytes,
        "link_ratio": link_bytes / host_bytes if host_bytes else None,
        "e2e_match": bool(norm(rep.result) == norm(host_out)),
        "ledger_choice": dd.get("choice"),
        "device_chunks": dd.get("device_chunks", 0),
        "host_fallbacks": dd.get("host_chunks", 0),
        "explain_decode": "decode=device" in rep.text,
    }


def smoke():
    """``bench.py --smoke``: tiny shapes through the fused + pipelined
    paths end-to-end, correctness-only (no timing assertions) — wired into
    ci/premerge.sh so perf-path exceptions fail fast in tier-1 budget."""
    import spark_rapids_jni_tpu  # noqa: F401  (enables x64)
    # profile store for the whole smoke run, BEFORE any bench executes: the
    # dist bench's subprocess inherits the env, so its exchange profiles
    # land in the same ring and the sixth line can report their skew
    if not os.environ.get("SRJT_PROFILE_DIR"):
        import tempfile
        os.environ["SRJT_PROFILE_DIR"] = tempfile.mkdtemp(
            prefix="srjt-smoke-profiles-")
        from spark_rapids_jni_tpu.utils.config import refresh
        refresh()
    res = bench_engine_pipeline(n=20_000, chunk_bytes=48_000, smoke=True)
    ok = bool(res and res["results_match"] and res["fused_streamed"]
              and res["chunks"] > 1)
    print(json.dumps({"metric": "engine_pipeline_smoke",
                      "ok": ok,
                      "chunks": res["chunks"] if res else None,
                      "segment_cache": res["segment_cache"] if res else None,
                      # absolute latencies (machine-dependent, gate with
                      # loose tolerance only) and dimensionless ratios
                      # (the portable signal) for ci/bench_gate.py
                      "latency_ms": {} if not res else {
                          "q5_warm_fused": round(res["q5_warm_fused_ms"], 3),
                          "q5_warm_interp": round(res["q5_warm_interp_ms"], 3),
                          "stream_serial": round(res["stream_serial_ms"], 3),
                          "stream_overlap": round(res["stream_overlap_ms"], 3),
                      },
                      "ratios": {} if not res else {
                          "fused_vs_interp": round(res["fused_vs_interp"], 4)
                          if res["fused_vs_interp"] else None,
                          "overlap_vs_serial":
                          round(res["overlap_vs_serial"], 4)
                          if res["overlap_vs_serial"] else None,
                      }}))
    jres = bench_engine_join(n=20_000, chunk_bytes=48_000, smoke=True)
    jok = bool(jres and jres["results_match"] and jres["join_streamed_fused"]
               and jres["topk_streamed"] and jres["build_cache_counters_ok"]
               and jres["chunks"] > 1)
    print(json.dumps({"metric": "engine_join_smoke",
                      "ok": jok,
                      "chunks": jres["chunks"] if jres else None,
                      "build_cache": jres["build_cache"] if jres else None,
                      "latency_ms": {} if not jres else {
                          "join_cached_build":
                          round(jres["join_cached_build_ms"], 3),
                          "topk_stream": round(jres["topk_stream_ms"], 3),
                      },
                      "ratios": {} if not jres else {
                          "cached_vs_per_chunk":
                          round(jres["cached_vs_per_chunk"], 4)
                          if jres["cached_vs_per_chunk"] else None,
                          "topk_vs_full_sort":
                          round(jres["topk_vs_full_sort"], 4)
                          if jres["topk_vs_full_sort"] else None,
                      }}))
    # third line: the observability layer itself — every execute() above ran
    # under a QueryMetrics, so with SRJT_METRICS on the snapshot must carry
    # per-query summaries (premerge greps this line for the block)
    from spark_rapids_jni_tpu.utils import metrics, timeline
    snap = metrics.snapshot()
    mok = (not metrics.enabled()) or bool(snap["queries"])
    print(json.dumps({"metric": "metrics_snapshot",
                      "ok": mok,
                      "enabled": metrics.enabled(),
                      **snap}))
    # fourth line: the timeline layer — with SRJT_TIMELINE on, the smoke
    # queries above must have produced trace events, and the dump (to
    # SRJT_TIMELINE_OUT, or a tempfile) must be valid Chrome trace JSON
    tok, tpath, tevents = True, None, 0
    if timeline.enabled():
        import tempfile
        tpath = os.environ.get("SRJT_TIMELINE_OUT")
        if not tpath:
            tpath = os.path.join(tempfile.gettempdir(),
                                 f"srjt-smoke-timeline-{os.getpid()}.json")
        trace = timeline.export()
        tevents = sum(1 for e in trace["traceEvents"] if e["ph"] != "M")
        timeline.dump(tpath)
        try:
            with open(tpath) as f:
                reloaded = json.load(f)
            tok = bool(tevents > 0 and reloaded["traceEvents"])
        except Exception:
            tok = False
    print(json.dumps({"metric": "timeline",
                      "ok": tok,
                      "enabled": timeline.enabled(),
                      "path": tpath,
                      "events": tevents}))
    # fifth line: the distributed planner — broadcast and hash-exchange
    # plans must match the single-device result, the static exchange
    # census must equal the executed count, and the co-partitioned plan
    # must carry ZERO exchanges (premerge asserts all three on this line)
    dres = bench_engine_dist(n_fact=60_000, n_dim=500, smoke=True)
    dattr = (dres or {}).get("device_attrib") or {}
    dok = bool(dres and dres["results_match"]
               and dres["exchanges"]["broadcast_static"]
               == dres["exchanges"]["broadcast_executed"]
               and dres["exchanges"]["exchange_static"]
               == dres["exchanges"]["exchange_executed"]
               and dres["exchanges"]["copartitioned_static"]
               == dres["exchanges"]["copartitioned_executed"] == 0
               # per-device attribution invariants (False fails; None =
               # metrics off, nothing to check)
               and dattr.get("matrix_matches") is not False
               and dattr.get("explain_skew_rendered") is not False
               # AQE evidence plane: cardinality columns on every node
               # line, decision footer count == static census (absent =
               # metrics off, nothing to check)
               and (dattr.get("evidence") or {}).get(
                   "node_lines_annotated") is not False
               and (dattr.get("evidence") or {}).get(
                   "census_matches") is not False)
    print(json.dumps({"metric": "engine_dist_smoke",
                      "ok": dok,
                      "exchanges": dres["exchanges"] if dres else None,
                      "device_attrib": dattr or None,
                      "latency_ms": {} if not dres else {
                          "broadcast": round(dres["broadcast_s"] * 1e3, 3),
                          "exchange": round(dres["exchange_s"] * 1e3, 3),
                          "smj8": round(dres["smj_s"] * 1e3, 3),
                      },
                      "ratios": {} if not dres else {
                          "broadcast_vs_smj8":
                          round(dres["ratios"]["broadcast_vs_smj8"], 4)
                          if dres["ratios"]["broadcast_vs_smj8"] else None,
                          "broadcast_vs_exchange":
                          round(dres["ratios"]["broadcast_vs_exchange"], 4)
                          if dres["ratios"]["broadcast_vs_exchange"]
                          else None,
                      }}))
    # fused whole-stage line: the partial-agg -> exchange -> final-agg
    # sandwich as ONE shard_map program (SRJT_FUSE_EXCHANGE) vs the
    # host-orchestrated exchange path — parity must be bit-exact, the
    # fused run must pay exactly its static sync_budget (and well under
    # the host path's count; premerge asserts < 5), and the exchange
    # census must stay static==executed on BOTH paths.  vs_host_exchange
    # is the report-only fused_stage.* gate key (BENCH_BASELINES.json)
    fres = bench_engine_fused_stage(n_fact=60_000, n_keys=500, smoke=True)
    fsync = (fres or {}).get("host_syncs") or {}
    fok = bool(fres and fres["results_match"]
               and fres.get("dispatches", 0) >= 1
               and fsync.get("fused") == fsync.get("fused_budget")
               and fres["exchanges"]["host_static"]
               == fres["exchanges"]["host_executed"]
               and fres["exchanges"]["fused_static"]
               == fres["exchanges"]["fused_executed"])
    print(json.dumps({"metric": "fused_stage",
                      "ok": fok,
                      "vs_host_exchange": round(fres["vs_host_exchange"], 4)
                      if fres and fres.get("vs_host_exchange") else None,
                      "host_syncs": fsync or None,
                      "dispatches": (fres or {}).get("dispatches"),
                      "exchanges": (fres or {}).get("exchanges"),
                      "results_match": (fres or {}).get("results_match"),
                      "vs_host_e2e": round(fres["vs_host_e2e"], 4)
                      if fres and fres.get("vs_host_e2e") else None,
                      "latency_ms": {} if not fres else {
                          "host_exchange": round(fres["host_s"] * 1e3, 3),
                          "fused": round(fres["fused_s"] * 1e3, 3),
                          "scan_baseline": round(fres["scan_s"] * 1e3, 3),
                      }}))
    # device-decode line (metric name "parquet" so the gate key flattens
    # to parquet.device_vs_host): compressed pages decoded in-kernel vs
    # the staged host path.  ok gates on what is machine-independent —
    # bit-exact parity (both datasets + engine E2E), every chunk decoded
    # on-device with zero fallbacks, decode=device rendered in EXPLAIN —
    # while the MB/s ratio and link ratio are report-only gate keys
    # (device_vs_host is backend-dependent: the CPU backend loses to
    # pyarrow's SIMD shuffles; the number exists to track drift, not to
    # assert the accelerator win at smoke scale)
    pdres = bench_parquet_device_decode(n=48_000, smoke=True)
    pdok = bool(pdres and pdres["parity"] and pdres["compressible_parity"]
                and pdres["e2e_match"]
                and pdres["ledger_choice"] == "device"
                and pdres["device_chunks"] >= 1
                and pdres["host_fallbacks"] == 0
                and pdres["explain_decode"]
                and pdres["link_ratio"] and pdres["link_ratio"] < 1.0)
    print(json.dumps({"metric": "parquet",
                      "ok": pdok,
                      "device_vs_host": round(pdres["device_vs_host"], 4)
                      if pdres and pdres.get("device_vs_host") else None,
                      "link_ratio": round(pdres["link_ratio"], 4)
                      if pdres and pdres.get("link_ratio") else None,
                      "device_chunks": (pdres or {}).get("device_chunks"),
                      "host_fallbacks": (pdres or {}).get("host_fallbacks"),
                      "parity": (pdres or {}).get("parity"),
                      "e2e_match": (pdres or {}).get("e2e_match"),
                      "latency_ms": {} if not pdres else {
                          "device": round(pdres["device_s"] * 1e3, 3),
                          "host": round(pdres["host_s"] * 1e3, 3),
                          "pyarrow": round(pdres["arrow_s"] * 1e3, 3),
                      },
                      "MBps": {} if not pdres else {
                          "device": round(pdres["device_MBps"], 1),
                          "host": round(pdres["host_MBps"], 1),
                          "pyarrow": round(pdres["arrow_MBps"], 1),
                      }}))
    # sixth line: adaptive execution — the skewed twin must apply at least
    # one verified skew split (post-split skew gauge under the threshold)
    # and the repeat query must plan run 2 from run 1's measured actuals,
    # with bit-parity everywhere.  skew_ratio / rerun_vs_first are the
    # report-only gate keys (aqe.* in BENCH_BASELINES.json)
    ares = bench_engine_aqe(n_fact=60_000, n_keys=500, smoke=True)
    askew = (ares or {}).get("skew") or {}
    awarm = (ares or {}).get("warm") or {}
    aok = bool(ares and askew.get("parity") and awarm.get("parity")
               and askew.get("splits_applied", 0) >= 1
               # gauge absent = metrics off in subprocess, nothing to check
               and (askew.get("gauge_skew") is None
                    or askew["gauge_skew"] < askew["threshold"])
               and awarm.get("warmed_entries", 0) >= 1
               and awarm.get("run2_broadcast_planned")
               and awarm.get("faster"))
    print(json.dumps({"metric": "aqe",
                      "ok": aok,
                      "skew_ratio": round(ares["skew_ratio"], 4)
                      if ares and ares.get("skew_ratio") else None,
                      "rerun_vs_first": round(ares["rerun_vs_first"], 4)
                      if ares and ares.get("rerun_vs_first") else None,
                      "latency_ms": {} if not ares else {
                          "balanced": round(ares["balanced_s"] * 1e3, 3),
                          "skewed": round(ares["skewed_s"] * 1e3, 3),
                          "first": round(ares["first_s"] * 1e3, 3),
                          "rerun": round(ares["rerun_s"] * 1e3, 3),
                      },
                      "skew": askew or None,
                      "warm": awarm or None}))
    # seventh line: multi-tenant serving — N concurrent bridge sessions
    # must return bit-exact per-trace results vs the serial pass, at least
    # one query must be shed with the typed admission error (trace +
    # bundle attached), and a repeat plan must serve from the result cache
    # well under its cold wall.  p99/throughput/shed_count are the
    # report-only serving.* gate keys (BENCH_BASELINES.json)
    sres = bench_engine_serving(n=24_000, clients=8, smoke=True)
    sshed = (sres or {}).get("shed") or {}
    sspeed = (sres or {}).get("result_cache_speedup")
    sok = bool(sres and sres.get("parity") and not sres.get("errors")
               and sres.get("admitted", 0) >= sres.get("clients", 8)
               and sshed.get("kind") == "resource"
               and sshed.get("retryable") is False
               and sshed.get("trace_id") and sshed.get("bundle")
               and sres.get("result_cache_hits", 0) >= 1
               and sspeed is not None and sspeed > 10.0)
    print(json.dumps({"metric": "serving",
                      "ok": sok,
                      "clients": (sres or {}).get("clients"),
                      "p50_ms": round(sres["p50_ms"], 3) if sres else None,
                      "p99_ms": round(sres["p99_ms"], 3) if sres else None,
                      "throughput": round(sres["throughput_qps"], 4)
                      if sres else None,
                      "throughput_ratio": round(sres["throughput_ratio"], 4)
                      if sres and sres.get("throughput_ratio") else None,
                      "shed_count": (sres or {}).get("shed_count"),
                      "result_cache_speedup": round(sspeed, 2)
                      if sspeed else None,
                      "latency_ms": {} if not sres else {
                          "serial_pass": round(sres["serial_s"] * 1e3, 3),
                          "concurrent_pass":
                              round(sres["concurrent_s"] * 1e3, 3),
                          "result_cache_cold":
                              round(sres["result_cache_cold_ms"], 3),
                          "result_cache_warm":
                              round(sres["result_cache_warm_ms"], 3),
                      },
                      "shed": sshed or None}))
    # roofline line: the fused row-conversion pipeline against the measured
    # stream ceiling at smoke scale — roofline_frac = achieved / ceiling is
    # dimensionless, so it tracks formulation regressions (extra passes,
    # lost fusion) without retuning for machine speed.  Report-only gate
    # key row_conversion.roofline_frac (BENCH_BASELINES.json); the r5
    # full-scale value was 0.071
    rc_dev, rc_cpu, rc_ok, rc_ceiling = bench_row_conversion(n=200_000)
    print(json.dumps({"metric": "row_conversion",
                      "ok": bool(rc_ok),
                      "GBps": round(rc_dev, 3),
                      "ceiling_GBps": round(rc_ceiling, 2),
                      "roofline_frac": round(rc_dev / rc_ceiling, 4)
                      if rc_ceiling else None,
                      "cpu_GBps": round(rc_cpu, 3)}))
    # profile-store line: every query above (this process AND the dist +
    # aqe subprocesses, via the inherited env) persisted a profile; the
    # store summary must carry the dist exchanges' skew
    from spark_rapids_jni_tpu.utils import profile
    psumm = profile.store_summary()
    pok = (not profile.enabled()) or (
        psumm["profiles"] > 0 and psumm["top_exchange_skew"] is not None)
    print(json.dumps({"metric": "profile_store",
                      "ok": pok,
                      "enabled": profile.enabled(),
                      **psumm}))
    # overhead line: the observability layer's own price — the same tiny
    # aggregate timed under SRJT_METRICS=0 and =1.  The on/off ratio is
    # gated report-only (machine noise dwarfs the per-chunk dict writes
    # at smoke scale); the line exists so a pathological regression in
    # the metrics hot path shows up in the bench artifact immediately.
    import tempfile
    import time as _time
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.engine import (Aggregate, Scan, execute,
                                             new_stats, optimize)
    from spark_rapids_jni_tpu.utils.config import refresh as _refresh
    ov_dir = tempfile.mkdtemp(prefix="srjt-ov-")
    ov_path = os.path.join(ov_dir, "ov.parquet")
    rng = np.random.default_rng(3)
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 50, 20_000).astype(np.int64)),
        "v": pa.array(rng.uniform(0.0, 1.0, 20_000)),
    }), ov_path, row_group_size=2_000)
    ov_plan = Aggregate(Scan(ov_path, chunk_bytes=32_000), ["k"],
                        [("v", "sum")], names=["s"])
    ov_opt = optimize(ov_plan)
    prev_flag = os.environ.get("SRJT_METRICS")
    ov_ms = {}
    try:
        for flag in ("0", "1"):
            os.environ["SRJT_METRICS"] = flag
            _refresh()
            execute(ov_opt, new_stats())  # warm (compile)
            t0 = _time.perf_counter()
            for _ in range(3):
                with metrics.query("overhead"):
                    execute(ov_opt, new_stats())
            ov_ms[flag] = (_time.perf_counter() - t0) * 1e3 / 3
    finally:
        if prev_flag is None:
            os.environ.pop("SRJT_METRICS", None)
        else:
            os.environ["SRJT_METRICS"] = prev_flag
        _refresh()
    ov_ratio = (ov_ms["1"] / ov_ms["0"]) if ov_ms.get("0") else None
    vok = bool(ov_ratio and ov_ratio > 0)
    print(json.dumps({"metric": "metrics_overhead",
                      "ok": vok,
                      "latency_ms": {
                          "metrics_off": round(ov_ms.get("0", 0.0), 3),
                          "metrics_on": round(ov_ms.get("1", 0.0), 3),
                      },
                      "ratios": {"on_vs_off": round(ov_ratio, 4)
                                 if ov_ratio else None}}))
    # flight-recorder overhead line: the always-on blackbox ring's price —
    # the same aggregate timed under SRJT_BLACKBOX=0 and =1 (happy path:
    # ring appends only, no bundle is ever cut).  Report-only like
    # metrics_overhead; the line exists so a regression in the record()
    # fast path (utils/blackbox.py) shows up in the bench artifact.
    prev_bb = os.environ.get("SRJT_BLACKBOX")
    bb_ms = {}
    try:
        for flag in ("0", "1"):
            os.environ["SRJT_BLACKBOX"] = flag
            _refresh()
            execute(ov_opt, new_stats())  # warm (compile)
            t0 = _time.perf_counter()
            for _ in range(3):
                with metrics.query("bb_overhead"):
                    execute(ov_opt, new_stats())
            bb_ms[flag] = (_time.perf_counter() - t0) * 1e3 / 3
    finally:
        if prev_bb is None:
            os.environ.pop("SRJT_BLACKBOX", None)
        else:
            os.environ["SRJT_BLACKBOX"] = prev_bb
        _refresh()
    bb_ratio = (bb_ms["1"] / bb_ms["0"]) if bb_ms.get("0") else None
    bok = bool(bb_ratio and bb_ratio > 0)
    print(json.dumps({"metric": "blackbox_overhead",
                      "ok": bok,
                      "latency_ms": {
                          "blackbox_off": round(bb_ms.get("0", 0.0), 3),
                          "blackbox_on": round(bb_ms.get("1", 0.0), 3),
                      },
                      "ratios": {"on_vs_off": round(bb_ratio, 4)
                                 if bb_ratio else None}}))
    return 0 if (ok and jok and mok and tok and dok and fok and pdok
                 and aok and sok and rc_ok and pok and vok and bok) else 1


def main():
    import spark_rapids_jni_tpu  # noqa: F401  (enables x64)

    dev_gbps, cpu_gbps, ok, ceiling = bench_row_conversion()
    vs_dev, vs_cpu, vs_ok = bench_row_conversion_strings()
    cast_dev, cast_cpu = bench_cast_strings()
    agg_dev, agg_cpu = bench_hash_aggregate()
    scan_decode, scan_e2e, scan_staged, scan_arrow, link = \
        bench_parquet_scan()
    win_dev, win_cpu = bench_window()
    smj = bench_distributed_join()
    eng = bench_engine_q5()
    pipe = bench_engine_pipeline()
    ejoin = bench_engine_join()
    edist = bench_engine_dist()
    efused = bench_engine_fused_stage()
    eaqe = bench_engine_aqe()
    eserv = bench_engine_serving()

    # vs_baseline is measured/PINNED (BENCH_BASELINES.json), so the ratio is
    # comparable across rounds; the live re-measure of each baseline is
    # reported as *_measured_now for drift visibility only.
    print(json.dumps({
        "metric": "row_conversion_to_rows_GBps" + ("" if ok else "_MISMATCH"),
        "value": round(dev_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(
            dev_gbps / pinned("row_conversion_to_rows_GBps"), 3),
        "pinned_baseline": pinned("row_conversion_to_rows_GBps"),
        "roofline_frac": round(dev_gbps / ceiling, 3),
        "extras": {
            "row_conversion_ceiling_GBps": {
                "value": round(ceiling, 2),
                "note": "measured HBM stream (same harness) scaled by the "
                        "op's minimum-traffic ratio 3R/(I+2R): an upper "
                        "bound no formulation can beat (it cannot move "
                        "fewer bytes)"},
            "cpu_numpy_pack_measured_now_GBps": {"value": round(cpu_gbps, 3)},
            "row_conversion_long_string_1M_GBps" + ("" if vs_ok
                                                 else "_MISMATCH"): {
                "value": round(vs_dev, 3),
                "pinned_baseline": pinned("row_conversion_long_string_1M_GBps"),
                "vs_baseline": round(
                    vs_dev / pinned("row_conversion_long_string_1M_GBps"), 2),
                "cpu_measured_now": round(vs_cpu, 3),
                "note": "BASELINE configs[0] at its specified long+string "
                        "shape (variable-width UnsafeRow-style rows)"},
            "cast_strings_to_int64_Mrows_s": {
                "value": round(cast_dev, 2),
                "pinned_baseline": pinned("cast_strings_to_int64_Mrows_s"),
                "vs_baseline": round(
                    cast_dev / pinned("cast_strings_to_int64_Mrows_s"), 2),
                "cpu_measured_now": round(cast_cpu, 2)},
            "hash_aggregate_Mrows_s": {
                "value": round(agg_dev, 2),
                "pinned_baseline": pinned("hash_aggregate_Mrows_s"),
                "vs_baseline": round(
                    agg_dev / pinned("hash_aggregate_Mrows_s"), 2),
                "cpu_measured_now": round(agg_cpu, 2)},
            "parquet_scan_decode_MBps": {
                "value": round(scan_decode, 1),
                "pinned_baseline": pinned("parquet_scan_decode_MBps"),
                "vs_baseline": round(
                    scan_decode / pinned("parquet_scan_decode_MBps"), 3),
                "pyarrow_measured_now": round(scan_arrow, 1)},
            "parquet_scan_to_device_MBps": {
                "value": round(scan_e2e, 1),
                "link_MBps_measured": round(link, 1),
                "frac_of_link": round(scan_e2e / link, 3) if link else None},
            "parquet_scan_to_device_staged_warm_MBps": {
                "value": round(scan_staged, 1),
                "frac_of_link": round(scan_staged / link, 3) if link
                else None,
                "note": "repeated-scan steady state: one packed transfer "
                        "+ cached jitted unpack (io/staging.py)"},
            "window_rank_sum_Mrows_s": {
                "value": round(win_dev, 2),
                "pinned_baseline": pinned("window_rank_sum_Mrows_s"),
                "vs_baseline": round(
                    win_dev / pinned("window_rank_sum_Mrows_s"), 2),
                "cpu_measured_now": round(win_cpu, 2)},
            **({"shuffle_smj_8dev_cpu_mesh_Mrows_s": {
                "value": round(smj["dist_mrows_s"], 2),
                "pinned_baseline": pinned(
                    "shuffle_smj_8dev_cpu_mesh_Mrows_s"),
                "vs_baseline": round(
                    smj["dist_mrows_s"] / pinned(
                        "shuffle_smj_8dev_cpu_mesh_Mrows_s"), 3),
                "local_measured_now": round(smj["local_mrows_s"], 3),
                "breakdown_s": {
                    "exchange": round(smj["exchange_s"], 3),
                    "join": round(smj["total_s"] - smj["exchange_s"], 3),
                    "total": round(smj["total_s"], 3)},
                "exchange_MB": round(smj["exchange_MB"], 1),
                "padding_efficiency": {
                    "value": round(smj["padding_efficiency"], 3),
                    "note": "live rows / padded exchange slots (sent "
                            "bytes over live bytes inverse)"}}}
               if smj else {}),
            **({"engine_q5_plan_execute": {
                "cold_ms": round(eng["cold_ms"], 1),
                "warm_ms": round(eng["warm_ms"], 1),
                "per_op_dispatch_ms": round(eng["per_op_ms"], 1),
                "round_trips": {"plan": eng["plan_round_trips"],
                                "per_op": eng["per_op_round_trips"]},
                "plan_cache": {"hits": eng["cache_hits"],
                               "misses": eng["cache_misses"]},
                "results_match": eng["results_match"],
                "note": "q5-lite via ONE PLAN_EXECUTE message (cold = "
                        "plan-cache miss: optimize+execute; warm = cache "
                        "hit) vs the same query as per-op bridge calls; "
                        "no pinned baseline yet (first round with the "
                        "engine in the tree)"}}
               if eng else {}),
            **({"engine_pipeline": {
                "q5_cold_fused_ms": round(pipe["q5_cold_fused_ms"], 1),
                "q5_warm_fused_ms": round(pipe["q5_warm_fused_ms"], 1),
                "q5_warm_interp_ms": round(pipe["q5_warm_interp_ms"], 1),
                "fused_vs_interp": round(pipe["fused_vs_interp"], 3),
                "stream_serial_ms": round(pipe["stream_serial_ms"], 1),
                "stream_overlap_ms": round(pipe["stream_overlap_ms"], 1),
                "overlap_vs_serial": round(pipe["overlap_vs_serial"], 3),
                "fused_stream_serial_ms": round(
                    pipe["fused_stream_serial_ms"], 1),
                "fused_stream_overlap_ms": round(
                    pipe["fused_stream_overlap_ms"], 1),
                "fused_overlap_vs_serial": round(
                    pipe["fused_overlap_vs_serial"], 3),
                "chunks": pipe["chunks"],
                "results_match": pipe["results_match"],
                "segment_cache": pipe["segment_cache"],
                "note": "LOCAL executor. fused_vs_interp: warm fused "
                        "segments vs the PR 1 node-by-node interpreter on "
                        "the q5-lite shape (>1 means fused wins). "
                        "overlap_vs_serial: double-buffered (prefetch=2) "
                        "vs serial (prefetch=0) chunk streaming on the "
                        "chunked-scan aggregate's per-chunk-sync loop, "
                        "min of interleaved A/B pairs (>1 means overlap "
                        "wins); fused_* is the same A/B on the fused "
                        "streaming loop, whose consumer never blocks "
                        "per chunk — on a 1-core CPU host there is no "
                        "idle wait for the producer to hide behind, so "
                        "~1.0 is expected there until a real accelerator "
                        "link is in the loop"}}
               if pipe else {}),
            **({"engine_join": {
                "join_cached_build_ms": round(
                    ejoin["join_cached_build_ms"], 1),
                "join_per_chunk_build_ms": round(
                    ejoin["join_per_chunk_build_ms"], 1),
                "cached_vs_per_chunk": round(
                    ejoin["cached_vs_per_chunk"], 3),
                "topk_stream_ms": round(ejoin["topk_stream_ms"], 1),
                "topk_full_sort_ms": round(ejoin["topk_full_sort_ms"], 1),
                "topk_vs_full_sort": round(ejoin["topk_vs_full_sort"], 3),
                "chunks": ejoin["chunks"],
                "build_cache_counters_ok":
                    ejoin["build_cache_counters_ok"],
                "results_match": ejoin["results_match"],
                "build_cache": ejoin["build_cache"],
                "note": "LOCAL executor. cached_vs_per_chunk: streamed "
                        "inner join with the build side prepared once "
                        "(BUILD_CACHE, fused probe per chunk) vs the "
                        "interpreted loop re-hashing + re-sorting the "
                        "build every chunk (>1 means cached wins). "
                        "topk_vs_full_sort: streamed capacity-k TopK vs "
                        "materialize + full sort + slice on the same "
                        "optimized plan (>1 means streaming wins)"}}
               if ejoin else {}),
            **({"engine_dist": {
                "broadcast_s": round(edist["broadcast_s"], 3),
                "exchange_s": round(edist["exchange_s"], 3),
                "smj8_s": round(edist["smj_s"], 3),
                "broadcast_join_stage_s": round(edist["bjoin_s"], 3),
                "local_s": round(edist["local_s"], 3),
                "broadcast_vs_smj8": round(
                    edist["ratios"]["broadcast_vs_smj8"], 3),
                "broadcast_vs_exchange": round(
                    edist["ratios"]["broadcast_vs_exchange"], 3),
                "exchanges": edist["exchanges"],
                "results_match": edist["results_match"],
                "note": "partitioning-aware planner on the 8-device CPU "
                        "mesh: the same join+agg plan as a broadcast-hash "
                        "join (build replicated, probe streamed through "
                        "the fused segment) vs forced hash exchanges vs "
                        "the r5 shuffle+SMJ comparator (join stage only); "
                        "co-partitioned scans must plan zero exchanges"}}
               if edist else {}),
            **({"engine_fused_stage": {
                "host_exchange_s": round(efused["host_s"], 3),
                "fused_s": round(efused["fused_s"], 3),
                "scan_baseline_s": round(efused["scan_s"], 3),
                "vs_host_exchange": round(
                    efused["vs_host_exchange"], 3)
                if efused["vs_host_exchange"] else None,
                "vs_host_e2e": round(efused["vs_host_e2e"], 3)
                if efused["vs_host_e2e"] else None,
                "host_syncs": efused["host_syncs"],
                "dispatches": efused["dispatches"],
                "exchanges": efused["exchanges"],
                "results_match": efused["results_match"],
                "note": "SRJT_FUSE_EXCHANGE: the partial-agg -> hash "
                        "Exchange -> final-agg sandwich lowered into ONE "
                        "jit(shard_map) program (device-side murmur3 "
                        "placement, bucket scatter, all_to_all, combine) "
                        "vs the host-orchestrated exchange on the same "
                        "plan.  The fused run pays exactly its static "
                        "verify.sync_budget (one boundary sync), the "
                        "host path pays per-device gathers + a host "
                        "bucket sort + re-uploads; parity is bit-exact.  "
                        "vs_host_exchange isolates the exchange stage by "
                        "subtracting the separately-timed scan-only "
                        "baseline both paths share; vs_host_e2e is the "
                        "raw end-to-end wall ratio"}}
               if efused else {}),
            **({"engine_aqe": {
                "balanced_s": round(eaqe["balanced_s"], 3),
                "skewed_s": round(eaqe["skewed_s"], 3),
                "skew_ratio": round(eaqe["skew_ratio"], 3)
                if eaqe["skew_ratio"] else None,
                "first_s": round(eaqe["first_s"], 3),
                "rerun_s": round(eaqe["rerun_s"], 3),
                "rerun_vs_first": round(eaqe["rerun_vs_first"], 3)
                if eaqe["rerun_vs_first"] else None,
                "skew": eaqe["skew"],
                "warm": eaqe["warm"],
                "note": "SRJT_AQE=1 runtime rewrites on the 8-device CPU "
                        "mesh: skew_ratio is the skewed twin vs its "
                        "balanced twin (hot keys split + re-dealt at the "
                        "exchange, ~1.0 means the split erased the hot "
                        "device); rerun_vs_first is run 2 of the same "
                        "source fingerprint planned from run 1's measured "
                        "build actuals (profile history) vs the cold run "
                        "(<1.0 means warming won)"}}
               if eaqe else {}),
            **({"engine_serving": {
                "clients": eserv["clients"],
                "p50_ms": round(eserv["p50_ms"], 1),
                "p99_ms": round(eserv["p99_ms"], 1),
                "throughput_qps": round(eserv["throughput_qps"], 3),
                "throughput_ratio": round(eserv["throughput_ratio"], 3)
                if eserv["throughput_ratio"] else None,
                "serial_s": round(eserv["serial_s"], 3),
                "concurrent_s": round(eserv["concurrent_s"], 3),
                "parity": eserv["parity"],
                "admitted": eserv["admitted"],
                "shed_count": eserv["shed_count"],
                "result_cache_speedup": round(
                    eserv["result_cache_speedup"], 1)
                if eserv["result_cache_speedup"] else None,
                "note": "N concurrent bridge sessions (one PLAN_EXECUTE "
                        "each, distinct fingerprints) vs the same N "
                        "queries serial on one connection, warm jit "
                        "caches, result cache off — parity is bit-exact "
                        "per-trace results.  shed_count / "
                        "result_cache_speedup come from a second 1-slot "
                        "server with SRJT_SLO_MS=1: a burning fingerprint "
                        "is shed at admission with the typed error, and "
                        "a repeat plan over unchanged files serves from "
                        "the result-set cache (engine/scheduler.py, "
                        "docs/SERVING.md).  throughput_ratio ~1.0 (or "
                        "below) is expected on a CPU-only host: XLA's "
                        "intra-op threadpool already spends every core "
                        "on one query, so concurrency has no idle "
                        "device time to reclaim until a real "
                        "accelerator link is in the loop"}}
               if eserv else {}),
            "metrics_snapshot": _metrics_snapshot(),
        },
    }))


def _metrics_snapshot() -> dict:
    """The SRJT_METRICS layer's view of everything the bench just ran:
    flat counters, histograms/gauges, and the most recent per-query
    summaries (bounded — the full deque holds 32)."""
    from spark_rapids_jni_tpu.utils import metrics
    snap = metrics.snapshot()
    snap["enabled"] = metrics.enabled()
    snap["queries"] = metrics.recent_summaries(limit=8)
    return snap


if __name__ == "__main__":
    if "--smoke" in sys.argv[1:]:
        sys.exit(smoke())
    sys.exit(main())
