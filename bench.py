"""Benchmark: RowConversion throughput on the device vs a CPU Arrow-style packer.

BASELINE.json configs[0] ("RowConversion round-trip ... CPU Arrow baseline").
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

- device path: the jitted u32-row-word kernel (ops/row_conversion)
- baseline: vectorized numpy packing of the same table into the identical
  wire format (the honest CPU columnar->row cost an Arrow-based row writer
  pays; all strided copies, no python loops)
"""

import json
import sys
import time

import numpy as np


def build_host_table(n: int):
    rng = np.random.default_rng(0)
    cols = [
        ("i64", rng.integers(-2**62, 2**62, n).astype(np.int64), None),
        ("f64", rng.standard_normal(n), rng.random(n) > 0.1),
        ("i32", rng.integers(-2**31, 2**31 - 1, n).astype(np.int32), None),
        ("f32", rng.standard_normal(n).astype(np.float32), None),
        ("i16", rng.integers(-2**15, 2**15 - 1, n).astype(np.int16),
         rng.random(n) > 0.5),
        ("i8", rng.integers(-128, 128, n).astype(np.int8), None),
        ("bool", (rng.random(n) > 0.5), None),
        ("dec64", rng.integers(-10**15, 10**15, n).astype(np.int64), None),
    ]
    return cols


def numpy_pack(cols, layout):
    """CPU Arrow-style row packer: strided assignment per column + validity."""
    n = len(cols[0][1])
    out = np.zeros((n, layout.row_size), np.uint8)
    for (name, data, valid), off in zip(cols, layout.offsets):
        if data.dtype == np.bool_:
            data = data.astype(np.uint8)
        b = data.view(np.uint8).reshape(n, data.dtype.itemsize)
        out[:, off:off + data.dtype.itemsize] = b
    vbytes = np.zeros((n, layout.num_validity_bytes), np.uint8)
    for i, (name, data, valid) in enumerate(cols):
        bit = np.uint8(1 << (i % 8))
        if valid is None:
            vbytes[:, i // 8] |= bit
        else:
            vbytes[valid, i // 8] |= bit
    out[:, layout.validity_offset:layout.validity_offset
        + layout.num_validity_bytes] = vbytes
    return out


def main():
    import spark_rapids_jni_tpu  # x64 on
    import jax
    import jax.numpy as jnp
    from spark_rapids_jni_tpu import dtypes as dt
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.ops.row_conversion import (
        fixed_width_layout, _to_rows_bytes)

    n = 2_000_000  # 4M+ exceeds the remote AOT compile helper's limits
    host_cols = build_host_table(n)
    schema = [dt.INT64, dt.FLOAT64, dt.INT32, dt.FLOAT32, dt.INT16, dt.INT8,
              dt.BOOL8, dt.decimal64(-4)]
    layout = fixed_width_layout(schema)

    table = Table([
        Column.from_numpy(data, validity=valid, dtype=d)
        for (name, data, valid), d in zip(host_cols, schema)
    ])
    datas = tuple(c.data for c in table.columns)
    masks = tuple(c.validity for c in table.columns)

    # Timing on the axon tunnel needs care (measured here):
    #  - block_until_ready returns before execution; only a value fetch waits
    #  - a fetch round-trip costs ~90 ms, dwarfing a single ~2 ms conversion
    # So: chain K salted conversions inside one jitted fori_loop (the salt on
    # an i32 column defeats result caching), reduce each to a u32 checksum,
    # and fetch one scalar.  Aggregate bytes / wall time -> true device rate.
    K = 32

    def run(d, m):
        def body(i, acc):
            di = d[:2] + (d[2] ^ i, ) + d[3:]
            return acc + _to_rows_bytes(layout, di, m).sum(dtype=jnp.uint32)
        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body,
                                 jnp.uint32(0))

    fn = jax.jit(run)
    int(fn(datas, masks))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(fn(datas, masks))
        times.append(time.perf_counter() - t0)
    dev_s = min(times)
    nbytes = K * n * layout.row_size
    dev_gbps = nbytes / dev_s / 1e9

    # CPU Arrow-style baseline (best of 3)
    cpu_s = min(
        (lambda: (lambda t: (numpy_pack(host_cols, layout),
                             time.perf_counter() - t))(time.perf_counter()))()[1]
        for _ in range(3))
    cpu_gbps = nbytes / cpu_s / 1e9

    # cross-check on a 100k-row slice: device bytes == numpy wire bytes
    ncheck = 100_000
    check = jax.jit(lambda d, m: _to_rows_bytes(layout, d, m))
    got = np.asarray(check(tuple(d[:ncheck] for d in datas),
                           tuple(None if m is None else m[:ncheck]
                                 for m in masks)))
    ref = numpy_pack([(nm, d0[:ncheck], None if v0 is None else v0[:ncheck])
                      for nm, d0, v0 in host_cols], layout).reshape(-1)
    ok = bool((got == ref).all())

    print(json.dumps({
        "metric": "row_conversion_to_rows_GBps"
                  + ("" if ok else "_MISMATCH"),
        "value": round(dev_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(dev_gbps / cpu_gbps, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
