#!/usr/bin/env python3
"""Bench regression gate: diff a bench.py artifact against the ``_gate``
references in BENCH_BASELINES.json.

A bench artifact is the stdout of ``bench.py`` or ``bench.py --smoke``:
one JSON object per line, each carrying a ``metric`` name.  This gate
flattens every line into dotted keys (``<metric>.<path.to.value>``),
looks up each key in the ``_gate.metrics`` table, and classifies it:

- ``ok``          within tolerance of the reference
- ``improved``    better than the reference by more than the tolerance
- ``regression``  worse than the reference by more than the tolerance
- ``missing``     a gated key the artifact did not produce (treated as a
                  regression: the bench silently dropped a metric)

Keys present in the artifact but not in ``_gate.metrics`` are ignored —
the gate only watches what was deliberately enrolled.  References are
NOT the pinned ``vs_baseline`` denominators (those are measured once and
never touched); ``_gate`` is a separate, freely retunable table.

Report-only by default: always prints the table and a JSON summary line,
exits 0.  ``--enforce`` makes regressions (and missing gated keys) exit
non-zero; ``--enforce-keys a,b,c`` narrows enforcement to an allowlist so
soaked keys gate hard while newer keys stay report-only — the flip is
per-key, not all-or-nothing.

``--profiles DIR`` additionally aggregates the query-profile store
(utils/profile.py) into profile-derived keys — ``profile.exchange.skew``
(worst skew across stored profiles), ``profile.exchange.straggler_share``,
``profile.chunk_latency.p99`` — so the gate can catch *why* a headline
number regressed (the exchange skewed, the latency tail grew), not just
that it did.  Pure JSON reads; no engine import.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINES = os.path.join(_REPO_ROOT, "BENCH_BASELINES.json")


def flatten(obj, prefix=""):
    """{'a': {'b': 1}, 'c': 2} -> {'a.b': 1, 'c': 2}; lists are skipped
    (no gated metric is a list, and histogram buckets should not be)."""
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, dict):
                out.update(flatten(v, key))
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                out[key] = float(v)
    return out


def parse_artifact(text: str) -> dict:
    """Flatten every JSON line of a bench run into one dotted-key map,
    rooted at each line's ``metric`` name.  Non-JSON lines (warnings,
    progress chatter) are skipped."""
    flat: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(obj, dict):
            continue
        metric = obj.get("metric")
        root = str(metric) if metric else ""
        flat.update(flatten(obj, root))
        # the headline value lives at "<metric>.value"; flatten() already
        # produces that, so nothing special to do
    return flat


def profile_keys(profiles_dir: str) -> dict:
    """Aggregate the profile store into gateable dotted keys.

    Worst-case aggregation across every stored profile (a gate should
    catch the worst run in the artifact, not the average): max exchange
    skew / straggler share, max chunk-latency p99.  Unreadable files are
    skipped — a torn profile must not fail the gate by itself."""
    out: dict[str, float] = {}
    try:
        names = sorted(os.listdir(profiles_dir))
    except OSError:
        return out

    def fold(key, v):
        if v is not None and (key not in out or v > out[key]):
            out[key] = float(v)

    for name in names:
        if not (name.startswith("profile-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(profiles_dir, name)) as f:
                prof = json.load(f)
        except (OSError, ValueError):
            continue
        for ex in prof.get("exchanges", ()):
            fold("profile.exchange.skew", ex.get("skew"))
            fold("profile.exchange.straggler_share",
                 ex.get("straggler_share"))
        h = prof.get("histograms", {}).get("engine.stream.chunk_latency_s")
        if h:
            fold("profile.chunk_latency.p99", h.get("p99"))
    return out


def load_gate(path: str) -> tuple[dict, float]:
    with open(path) as f:
        pins = json.load(f)
    gate = pins.get("_gate", {})
    return (gate.get("metrics", {}),
            float(gate.get("tolerance_default", 0.25)))


def classify(value, spec: dict, default_tol: float) -> dict:
    tol = float(spec.get("tolerance", default_tol))
    ref = float(spec["reference"])
    higher = spec.get("direction", "higher") == "higher"
    row = {"reference": ref, "tolerance": tol,
           "direction": "higher" if higher else "lower"}
    if value is None:
        row.update(status="missing", value=None, ratio=None)
        return row
    ratio = (value / ref) if ref else None
    row.update(value=value, ratio=round(ratio, 4) if ratio else None)
    if higher:
        if value < ref * (1 - tol):
            row["status"] = "regression"
        elif value > ref * (1 + tol):
            row["status"] = "improved"
        else:
            row["status"] = "ok"
    else:
        if value > ref * (1 + tol):
            row["status"] = "regression"
        elif value < ref * (1 - tol):
            row["status"] = "improved"
        else:
            row["status"] = "ok"
    return row


def run_gate(artifact_text: str, baselines_path: str,
             tolerance: float | None = None,
             enforce_keys: list | None = None,
             profiles_dir: str | None = None) -> dict:
    flat = parse_artifact(artifact_text)
    if profiles_dir:
        flat.update(profile_keys(profiles_dir))
    specs, default_tol = load_gate(baselines_path)
    if tolerance is not None:
        default_tol = tolerance
    rows = {key: classify(flat.get(key), spec, default_tol)
            for key, spec in specs.items()}
    statuses = [r["status"] for r in rows.values()]
    # failures that count under --enforce: all bad rows, or just the
    # allowlisted subset when --enforce-keys narrows the flip
    bad = [k for k, r in rows.items()
           if r["status"] in ("regression", "missing")]
    if enforce_keys is not None:
        allow = set(enforce_keys)
        bad = [k for k in bad if k in allow]
    return {
        "rows": rows,
        "checked": len(rows),
        "ok": statuses.count("ok"),
        "improved": statuses.count("improved"),
        "regressions": statuses.count("regression"),
        "missing": statuses.count("missing"),
        "enforced_failures": sorted(bad),
    }


def render(summary: dict) -> str:
    lines = [f"{'status':<11} {'key':<68} {'value':>12} {'ref':>12} "
             f"{'ratio':>8} {'tol':>5}"]
    for key, r in sorted(summary["rows"].items(),
                         key=lambda kv: kv[1]["status"] != "regression"):
        val = "-" if r["value"] is None else f"{r['value']:.4g}"
        ratio = "-" if r["ratio"] is None else f"{r['ratio']:.3f}"
        lines.append(f"{r['status']:<11} {key:<68} {val:>12} "
                     f"{r['reference']:>12.4g} {ratio:>8} "
                     f"{r['tolerance']:>5.2f}")
    lines.append(f"-- gate: {summary['checked']} checked, "
                 f"{summary['ok']} ok, {summary['improved']} improved, "
                 f"{summary['regressions']} regressions, "
                 f"{summary['missing']} missing")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--artifact", required=True,
                    help="bench output file (JSON lines), or - for stdin")
    ap.add_argument("--baselines", default=DEFAULT_BASELINES,
                    help="BENCH_BASELINES.json carrying the _gate section")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override _gate.tolerance_default for keys "
                         "without a per-key tolerance")
    ap.add_argument("--profiles", default=None, metavar="DIR",
                    help="query-profile store dir; aggregates "
                         "profile.* keys into the artifact")
    ap.add_argument("--enforce-keys", default=None, metavar="K1,K2",
                    help="comma allowlist: with --enforce, only these "
                         "keys' regressions fail the gate (all keys are "
                         "still reported)")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--report-only", action="store_true", default=True,
                      help="print the report, always exit 0 (default)")
    mode.add_argument("--enforce", action="store_true",
                      help="exit 1 on regressions or missing gated keys")
    args = ap.parse_args(argv)

    if args.artifact == "-":
        text = sys.stdin.read()
    else:
        with open(args.artifact) as f:
            text = f.read()

    enforce_keys = None
    if args.enforce_keys is not None:
        enforce_keys = [k.strip() for k in args.enforce_keys.split(",")
                        if k.strip()]
    summary = run_gate(text, args.baselines, args.tolerance,
                       enforce_keys=enforce_keys,
                       profiles_dir=args.profiles)
    print(render(summary))
    print(json.dumps({"metric": "bench_gate",
                      "enforced": bool(args.enforce),
                      "checked": summary["checked"],
                      "ok": summary["ok"],
                      "improved": summary["improved"],
                      "regressions": summary["regressions"],
                      "missing": summary["missing"],
                      "enforced_failures": summary["enforced_failures"]}))
    if args.enforce and summary["enforced_failures"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
