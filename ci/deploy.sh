#!/bin/bash
#
# Release deploy (analog of the reference's ci/deploy.sh:33-81, which
# deploys the jar + per-classifier jars + sources/javadoc with optional GPG
# signing to a maven repository).  TPU build artifacts:
#
#   * the Python wheel + sdist (the primary deliverable)
#   * the Java bridge jar when a JDK exists (classifier-free; the native
#     .so rides inside at ${os.arch}/${os.name}/ like the reference jar)
#
# Env (mirroring the reference's SIGN_FILE / SERVER_URL knobs):
#   SIGN_FILE=1          gpg-detach-sign every artifact (requires gpg key)
#   DEPLOY_REPO_URL=...  twine upload target (pypi-style); unset = dry run
#   TWINE_* creds        consumed by twine as usual
#
# Without DEPLOY_REPO_URL this stages + (optionally) signs into
# target/deploy/ and stops — a dry run a release engineer can inspect,
# the same way the reference splits deploy from premerge.

set -ex
cd "$(dirname "$0")/.."

OUT=target/deploy
rm -rf "$OUT"
mkdir -p "$OUT"

# provenance must be fresh at deploy time (reference bakes build-info into
# the jar at pom.xml:313-343)
build/build-info

python -m pip wheel --no-deps --no-build-isolation -w "$OUT" . \
    || python -m pip wheel --no-deps -w "$OUT" .
# sdist when the `build` frontend is installed; wheels alone are deployable
python -m build --sdist -o "$OUT" . 2>/dev/null \
    || echo "deploy: sdist skipped (python -m build not installed)"

if command -v javac >/dev/null 2>&1 && command -v mvn >/dev/null 2>&1; then
    mvn -B -DskipTests package
    cp target/spark-rapids-jni-tpu-*.jar "$OUT"/ 2>/dev/null || true
fi

if [ "${SIGN_FILE:-0}" = "1" ]; then
    for f in "$OUT"/*; do
        gpg --armor --detach-sign --batch --yes "$f"
    done
fi

if [ -n "${DEPLOY_REPO_URL:-}" ]; then
    if command -v twine >/dev/null 2>&1; then
        # wheels + sdists (twine ships sibling .asc signatures when
        # present); the jar deploys to a maven repo, not pypi — it stays
        # staged for the release engineer like the reference's classifier
        # jars
        twine upload --repository-url "$DEPLOY_REPO_URL" \
            "$OUT"/*.whl $(ls "$OUT"/*.tar.gz 2>/dev/null || true)
    else
        echo "deploy: DEPLOY_REPO_URL set but twine missing" >&2
        exit 1
    fi
else
    echo "deploy: dry run complete; artifacts staged in $OUT:"
    ls -l "$OUT"
fi

echo "deploy: OK"
