#!/usr/bin/env python
"""End-to-end trace-join check for premerge (docs/OBSERVABILITY.md).

Proves the serving path's observability contract across a REAL process
boundary — a client in this process, the bridge server in a subprocess —
twice:

- **clean query**: the client-minted trace id rides the v2 frame into the
  server, shows up on the server's ``OP_METRICS`` per-query summary AND
  in the stored profile, and no post-mortem bundle is cut;
- **fault-injected query** (every parquet chunk read raises ``io_error``
  until retries exhaust): the typed client exception carries the same
  trace id as (a) the server's post-mortem bundle, (b) the wire error
  doc's bundle pointer (``e.bundle_path`` names that exact file), and
  (c) the profile-store entry for the failed run.

Run directly::

    JAX_PLATFORMS=cpu python ci/trace_join_check.py
"""

from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.bridge.client import BridgeClient, spawn_server
    from spark_rapids_jni_tpu.engine import Aggregate, Scan
    from spark_rapids_jni_tpu.utils import blackbox, errors, profile

    root = tempfile.mkdtemp(prefix="srjt-tracejoin-")
    bb_dir = os.path.join(root, "bundles")
    prof_dir = os.path.join(root, "profiles")
    os.makedirs(bb_dir)
    os.makedirs(prof_dir)

    path = os.path.join(root, "join.parquet")
    rng = np.random.default_rng(5)
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 16, 4_000).astype(np.int64)),
        "v": pa.array(rng.uniform(0.0, 1.0, 4_000)),
    }), path, row_group_size=500)
    plan = Aggregate(Scan(path, chunk_bytes=1 << 16), ["k"],
                     [("v", "sum")], names=["s"])

    env = {"SRJT_BLACKBOX_DIR": bb_dir, "SRJT_PROFILE_DIR": prof_dir,
           "SRJT_METRICS": "1"}
    failures: list[str] = []

    # -- phase 1: clean query, trace joins client -> server summary/profile
    sock = os.path.join(root, "bridge.sock")
    proc = spawn_server(sock, env=env)
    client = BridgeClient(sock)
    clean_tid = client.trace_id
    try:
        for h in client.execute_plan(plan):
            client.release(h)
        queries = (client.metrics() or {}).get("queries") or []
        hits = [q for q in queries if q.get("trace_id") == clean_tid]
        if not hits:
            failures.append(
                f"no OP_METRICS summary carries client trace {clean_tid!r}: "
                f"{[q.get('trace_id') for q in queries]}")
        client.shutdown_server()
    finally:
        client.close()
        proc.wait(timeout=30)
    if os.listdir(bb_dir):
        failures.append(
            f"clean query cut bundle(s): {os.listdir(bb_dir)}")
    profs = []
    for p in profile.list_profiles(prof_dir):
        try:
            profs.append(profile.read(p))
        except (OSError, ValueError):
            continue
    if not any(pr.get("trace_id") == clean_tid for pr in profs):
        failures.append(
            f"no stored profile carries client trace {clean_tid!r}")
    print(f"trace join (clean): summary+profile matched {clean_tid[:12]}, "
          f"0 bundles")

    # -- phase 2: injected fault -> typed error + bundle + profile, one id
    sock2 = os.path.join(root, "bridge2.sock")
    proc2 = spawn_server(sock2, env={
        **env, "SRJT_FAULTS": "parquet.chunk:*:io_error",
        "SRJT_RETRY_BACKOFF_S": "0.001"})
    client2 = BridgeClient(sock2)
    fault_tid = client2.trace_id
    err = None
    try:
        try:
            client2.execute_plan(plan)
            failures.append("fault-injected plan unexpectedly succeeded")
        except Exception as e:  # noqa: BLE001 — classified below
            err = e
        client2.shutdown_server()
    finally:
        client2.close()
        proc2.wait(timeout=30)
    if err is not None:
        kind, _ = errors.classify(err)
        if kind == errors.KIND_FATAL:
            failures.append(f"fault surfaced unclassified: "
                            f"{type(err).__name__}: {err}")
        tid = getattr(err, "trace_id", "")
        if tid != fault_tid:
            failures.append(f"exception trace {tid!r} != client-minted "
                            f"{fault_tid!r}")
        matching = []
        for p in blackbox.list_bundles(bb_dir):
            try:
                if blackbox.read_bundle(p).get("trace_id") == fault_tid:
                    matching.append(p)
            except (OSError, ValueError):
                continue
        if len(matching) != 1:
            failures.append(f"want exactly 1 bundle for {fault_tid!r}, "
                            f"got {len(matching)}")
        bp = getattr(err, "bundle_path", "")
        if not bp or not matching or \
                os.path.basename(bp) != os.path.basename(matching[0]):
            failures.append(f"wire bundle pointer {bp!r} does not name the "
                            f"matching bundle {matching!r}")
        fprofs = []
        for p in profile.list_profiles(prof_dir):
            try:
                fprofs.append(profile.read(p))
            except (OSError, ValueError):
                continue
        fhit = [pr for pr in fprofs if pr.get("trace_id") == fault_tid]
        if not fhit:
            failures.append(
                f"no stored profile carries fault trace {fault_tid!r}")
        elif (fhit[0].get("outcome") or {}).get("status") != "error":
            failures.append(f"fault profile outcome not error: "
                            f"{fhit[0].get('outcome')!r}")
        print(f"trace join (fault): {type(err).__name__} ({kind}) "
              f"exception==bundle==profile trace {fault_tid[:12]}")

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print("trace join check: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
