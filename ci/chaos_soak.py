#!/usr/bin/env python
"""Chaos soak: the fault-injection matrix against the real pipeline.

The robustness acceptance test (docs/ROBUSTNESS.md): build the bench
warehouse, compute oracle results with no faults armed, then re-run the
same plans under a rotating ``SRJT_FAULTS`` schedule covering every
injection site x kind.  Each run must end one of exactly two ways:

- **parity** — the recovery layer absorbed the fault (retry, interpreted
  fallback, exchange degradation ladder) and the result matches the
  oracle bit-for-bit after key-sorting; or
- **typed error** — a classified, non-fatal ``utils.errors`` kind
  (transient / resource / cancelled) surfaced within the deadline.

Anything else fails the soak: a fatal/unclassified error, a hang (the
whole script runs under ``timeout`` in ci/nightly.sh), a result mismatch,
a leaked prefetch thread (``io.prefetch.reap_timeouts`` must stay 0), or
an orphaned spill file.

The flight recorder (utils/blackbox.py) is held to the same oracle: a
typed error must cut EXACTLY one post-mortem bundle whose trace_id
matches the one the raised exception carries (``e.trace_id``), a parity
run cuts at most one (the degradation ladder bundles too), the clean
oracle runs cut none, and the bundle directory stays bounded.

A concurrent-clients scenario repeats the contract under multi-tenant
contention: four bridge clients run distinct plans at once against a
subprocess server with faults armed in ITS env — an absorbed fault must
leave every client's result bit-exact (zero cross-session leakage), and
an unabsorbable fault must hand every client a typed error joined 1:1
to a fresh server-side bundle by trace id.

Run directly::

    JAX_PLATFORMS=cpu python ci/chaos_soak.py
    python ci/chaos_soak.py --rounds 2 --devices 2   # more soak, exchange on
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the soak schedule: every site, both deterministic-nth and every-time
# rules, all three kinds.  timeout-kind sleeps are tiny (faults.HANG_S)
# so the soak stays fast; the point is that deadline plumbing engages.
SCHEDULE = [
    "parquet.chunk:1:io_error",
    "parquet.chunk:*:io_error",
    "parquet.chunk:2:oom",
    "parquet.prefetch:1:io_error",
    "parquet.prefetch:*:io_error",
    "staging.transfer:1:oom",
    "staging.transfer:2:io_error",
    "exchange.dispatch:1:oom",
    "exchange.dispatch:*:oom",
    "spill.write:1:io_error",
    "bridge.op:1:io_error",
    "parquet.chunk:1:timeout",
    "parquet.chunk:3:io_error,staging.transfer:1:oom",
]


def _sorted_columns(table, key):
    import numpy as np
    a = np.asarray(table.column(key).data)
    order = np.argsort(a, kind="stable")
    return [np.asarray(c.data)[order] for c in table.columns]


def _parity(base, out, key) -> bool:
    import numpy as np
    if base.num_rows != out.num_rows or base.num_columns != out.num_columns:
        return False
    for x, y in zip(_sorted_columns(base, key), _sorted_columns(out, key)):
        if not np.allclose(np.asarray(x, np.float64),
                           np.asarray(y, np.float64)):
            return False
    return True


def _parity_by_index(base, out, idx=0) -> bool:
    """Like ``_parity`` but key-sorts by column INDEX: tables exported
    over the bridge carry data only, no column names."""
    import numpy as np
    if base.num_rows != out.num_rows or base.num_columns != out.num_columns:
        return False

    def cols(t):
        order = np.argsort(np.asarray(t.columns[idx].data), kind="stable")
        return [np.asarray(c.data)[order] for c in t.columns]
    for x, y in zip(cols(base), cols(out)):
        if not np.allclose(np.asarray(x, np.float64),
                           np.asarray(y, np.float64)):
            return False
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=1,
                    help="full passes over the fault schedule")
    ap.add_argument("--rows", type=int, default=120_000)
    ap.add_argument("--devices", type=int, default=0,
                    help="virtual CPU device count (0 = leave as-is)")
    args = ap.parse_args(argv)

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("SRJT_FAULTS", None)
    # chunk-boundary deadline: generous enough for cold jit compiles, small
    # enough that a real hang converts to a typed timeout well before the
    # nightly `timeout` wrapper SIGKILLs the soak
    os.environ["SRJT_QUERY_TIMEOUT_S"] = "120"
    os.environ["SRJT_RETRY_BACKOFF_S"] = "0.001"
    # post-mortem bundles: every typed error below must cut exactly one,
    # joined to the run by trace id (docs/OBSERVABILITY.md)
    bb_dir = tempfile.mkdtemp(prefix="srjt-chaos-bb-")
    os.environ["SRJT_BLACKBOX_DIR"] = bb_dir

    import numpy as np

    import bench
    from spark_rapids_jni_tpu.engine import execute, optimize
    from spark_rapids_jni_tpu.utils import blackbox, errors, faults, tracing
    from spark_rapids_jni_tpu.utils.config import refresh

    refresh()
    rng = np.random.default_rng(7)
    root = tempfile.mkdtemp(prefix="srjt-chaos-")
    bench._pipeline_warehouse(root, args.rows, rng)
    q5, chunked = bench._pipeline_plans(root, chunk_bytes=256_000)
    plans = [("q5", optimize(q5), "s_mgr"),
             ("chunked", optimize(chunked), "ss_store_sk")]

    oracle = {name: execute(opt) for name, opt, _ in plans}
    thread_floor = threading.active_count()

    failures: list[str] = []
    # fault-free runs must not post-mortem anything
    if os.listdir(bb_dir):
        failures.append(
            f"clean oracle runs cut bundle(s): {os.listdir(bb_dir)}")
    runs = outcomes_parity = outcomes_typed = 0
    t_start = time.monotonic()
    for rnd in range(args.rounds):
        for spec in SCHEDULE:
            os.environ["SRJT_FAULTS"] = spec
            refresh()
            for name, opt, key in plans:
                faults.reset()
                runs += 1
                tag = f"round{rnd} [{spec}] {name}"
                before = set(os.listdir(bb_dir))
                try:
                    out = execute(opt)
                except Exception as e:  # noqa: BLE001 — the soak classifies
                    kind, _ = errors.classify(e)
                    fresh = sorted(set(os.listdir(bb_dir)) - before)
                    if kind == errors.KIND_FATAL:
                        failures.append(
                            f"{tag}: FATAL {type(e).__name__}: {e}")
                    else:
                        outcomes_typed += 1
                        tid = getattr(e, "trace_id", "")
                        if len(fresh) != 1:
                            failures.append(
                                f"{tag}: typed error cut {len(fresh)} "
                                f"bundle(s), want exactly 1: {fresh}")
                        else:
                            doc = blackbox.read_bundle(
                                os.path.join(bb_dir, fresh[0]))
                            if not tid or doc.get("trace_id") != tid:
                                failures.append(
                                    f"{tag}: bundle trace "
                                    f"{doc.get('trace_id')!r} != "
                                    f"client-observed {tid!r}")
                        print(f"  {tag}: typed error "
                              f"({kind}) {type(e).__name__} "
                              f"trace={tid[:12] or '?'}")
                    continue
                fresh = sorted(set(os.listdir(bb_dir)) - before)
                if len(fresh) > 1:  # 0 ok; 1 = degradation post-mortem
                    failures.append(
                        f"{tag}: parity run cut {len(fresh)} bundles: "
                        f"{fresh}")
                if _parity(oracle[name], out, key):
                    outcomes_parity += 1
                else:
                    failures.append(f"{tag}: result diverged from oracle")
    os.environ.pop("SRJT_FAULTS", None)
    refresh()
    faults.reset()

    # spill path under injection, with a real spill_dir: the sweep plus
    # finalizers must leave the directory empty
    from spark_rapids_jni_tpu.columnar import Column, Table
    from spark_rapids_jni_tpu.parallel.mesh import make_mesh
    from spark_rapids_jni_tpu.parallel.spill import shuffle_table_spilled
    sd = tempfile.mkdtemp(prefix="srjt-chaos-spill-")
    st = Table([Column.from_numpy(
                    rng.integers(0, 64, 50_000).astype("int64")),
                Column.from_numpy(
                    rng.integers(-99, 99, 50_000).astype("int64"))],
               ["k", "v"])
    os.environ["SRJT_FAULTS"] = "spill.write:1:io_error"
    refresh()
    faults.reset()
    spilled = shuffle_table_spilled(st, make_mesh(), ["k"],
                                    hbm_budget_bytes=1 << 18, spill_dir=sd)
    if spilled.num_rows != st.num_rows:
        failures.append("spill: row count diverged under injection")
    del spilled  # finalizers unlink the memmaps
    import gc
    gc.collect()
    left = [n for n in os.listdir(sd) if n.startswith("spill-")]
    if left:
        failures.append(f"spill: {len(left)} file(s) left in {sd}: {left}")
    os.environ.pop("SRJT_FAULTS", None)
    refresh()

    # device-decode path under injection (SRJT_DEVICE_DECODE=1): the
    # chunked plan with the parquet.device_decode transfer seam faulted.
    # A one-shot transient is absorbed by the retry ladder; a persistent
    # fault and an OOM must re-plan the chunk onto the host decoder —
    # every case ends in bit-exact parity with the fault-free oracle,
    # never a FATAL, and the device path must prove it actually engaged
    # (counter delta > 0) so the scenario can't silently soak nothing
    from spark_rapids_jni_tpu.utils import metrics as _metrics
    os.environ["SRJT_DEVICE_DECODE"] = "1"
    dd0 = _metrics.snapshot()["counters"].get("io.device_decode.chunks", 0)
    for spec in ("parquet.device_decode:1:io_error",
                 "parquet.device_decode:*:io_error",
                 "parquet.device_decode:1:oom"):
        os.environ["SRJT_FAULTS"] = spec
        refresh()
        faults.reset()
        runs += 1
        tag = f"device-decode [{spec}]"
        try:
            out = execute(plans[1][1])
        except Exception as e:  # noqa: BLE001 — the soak classifies
            kind, _ = errors.classify(e)
            if kind == errors.KIND_FATAL:
                failures.append(f"{tag}: FATAL {type(e).__name__}: {e}")
            else:
                outcomes_typed += 1
                print(f"  {tag}: typed error ({kind}) {type(e).__name__}")
        else:
            if _parity(oracle["chunked"], out, "ss_store_sk"):
                outcomes_parity += 1
                print(f"  {tag}: parity under injection")
            else:
                failures.append(f"{tag}: result diverged from oracle")
    dd1 = _metrics.snapshot()["counters"].get("io.device_decode.chunks", 0)
    if _metrics.enabled() and dd1 <= dd0:
        failures.append("device-decode: scenario never engaged the device "
                        "path (io.device_decode.chunks did not move)")
    os.environ.pop("SRJT_DEVICE_DECODE", None)
    os.environ.pop("SRJT_FAULTS", None)
    refresh()
    faults.reset()

    # concurrent-clients scenario: the fault matrix under multi-tenant
    # contention (engine/scheduler.py).  Four bridge clients run four
    # distinct-fingerprint plans at once against a real subprocess server
    # with faults armed in the SERVER env.  Two sub-scenarios:
    #  - an nth-shot fault the recovery layer absorbs: every client must
    #    still get ITS OWN plan's result bit-exact (zero cross-session
    #    leakage — a retried chunk must never land in a neighbor's
    #    accumulator);
    #  - an every-time fault no ladder can absorb: every client must get
    #    a typed, classified error carrying its own trace id, and the
    #    server must cut EXACTLY one trace-joined bundle per typed error.
    from spark_rapids_jni_tpu.bridge import BridgeClient, spawn_server
    n_clients = 4
    conc_plans = bench._serving_plans(root, 64_000, n_clients)
    conc_oracle = [execute(optimize(p)) for p in conc_plans]

    def _concurrent_pass(tag, fault_spec, bb):
        sock = os.path.join(tempfile.mkdtemp(prefix="srjt-chaos-srv-"),
                            "srv.sock")
        proc = spawn_server(sock, env={
            "SRJT_FAULTS": fault_spec,
            "SRJT_BLACKBOX_DIR": bb,
            "SRJT_RETRY_BACKOFF_S": "0.001",
            "SRJT_QUERY_TIMEOUT_S": "120",
        })
        results: dict = {}
        errs: dict = {}
        barrier = threading.Barrier(n_clients)

        def one(i):
            try:
                c = BridgeClient(sock)
                barrier.wait()
                hs = c.execute_plan(conc_plans[i])
                results[i] = c.export_table(hs[0])
                for h in hs:
                    c.release(h)
                c.close()
            except Exception as e:  # noqa: BLE001 — classified below
                errs[i] = e
        ts = [threading.Thread(target=one, args=(i,))
              for i in range(n_clients)]
        try:
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            ctl = BridgeClient(sock)
            ctl.shutdown_server()
        except Exception as e:  # noqa: BLE001 — the soak classifies
            failures.append(f"{tag}: harness error {e!r}")
            proc.kill()
        finally:
            proc.wait(timeout=30)
        return results, errs

    bb_absorb = tempfile.mkdtemp(prefix="srjt-chaos-bb-conc1-")
    res, errs = _concurrent_pass("concurrent/absorbed",
                                 "parquet.chunk:2:io_error", bb_absorb)
    runs += n_clients
    for i in range(n_clients):
        if i in errs:
            failures.append(f"concurrent/absorbed: client {i} errored "
                            f"({errs[i]!r}), want recovery parity")
        elif not _parity_by_index(conc_oracle[i], res[i]):
            failures.append(f"concurrent/absorbed: client {i} result "
                            "diverged from its oracle (cross-session "
                            "leakage or lost chunk)")
        else:
            outcomes_parity += 1
    print(f"  concurrent/absorbed: {len(res)}/{n_clients} parity under "
          f"nth-shot fault, {len(errs)} error(s)")

    bb_hard = tempfile.mkdtemp(prefix="srjt-chaos-bb-conc2-")
    res, errs = _concurrent_pass("concurrent/typed",
                                 "parquet.chunk:*:io_error", bb_hard)
    runs += n_clients
    bundles = {blackbox.read_bundle(os.path.join(bb_hard, f))
               .get("trace_id"): f for f in blackbox.list_bundles(bb_hard)}
    for i in range(n_clients):
        e = errs.get(i)
        if e is None:
            failures.append("concurrent/typed: client "
                            f"{i} succeeded under an every-time fault")
            continue
        kind, _ = errors.classify(e)
        if kind == errors.KIND_FATAL:
            failures.append(f"concurrent/typed: client {i} got FATAL "
                            f"{type(e).__name__}: {e}")
            continue
        outcomes_typed += 1
        tid = getattr(e, "trace_id", "")
        if not tid or tid not in bundles:
            failures.append(f"concurrent/typed: client {i} trace "
                            f"{tid!r} has no joined bundle "
                            f"(bundles: {sorted(bundles)})")
    if len(blackbox.list_bundles(bb_hard)) != len(errs):
        failures.append(
            f"concurrent/typed: {len(blackbox.list_bundles(bb_hard))} "
            f"bundle(s) for {len(errs)} typed error(s), want 1:1")
    print(f"  concurrent/typed: {len(errs)}/{n_clients} typed errors, "
          f"{len(bundles)} trace-joined bundle(s)")

    # leak checks: every prefetch producer must have been reaped inside
    # its join window, and no soak run may leave a live worker behind
    reaps = tracing.counters_snapshot("io.prefetch.reap_timeouts")
    if any(reaps.values()):
        failures.append(f"prefetch reap timeouts: {reaps}")
    time.sleep(0.2)  # producers parked on a full queue exit on drain/close
    leaked = threading.active_count() - thread_floor
    if leaked > 0:
        names = [t.name for t in threading.enumerate()]
        failures.append(f"{leaked} leaked thread(s): {names}")

    # bundle-dir bound: the writer prunes to its on-disk ring size
    n_bundles = len(blackbox.list_bundles(bb_dir))
    if n_bundles > blackbox._DIR_KEEP:
        failures.append(f"bundle dir unbounded: {n_bundles} files "
                        f"(cap {blackbox._DIR_KEEP})")

    wall = time.monotonic() - t_start
    print(f"chaos soak: {runs} runs in {wall:.1f}s — "
          f"{outcomes_parity} parity, {outcomes_typed} typed errors, "
          f"{n_bundles} bundle(s), {len(failures)} failure(s)")
    counters = tracing.counters_snapshot("engine.")
    for k in sorted(counters):
        if k.startswith(("engine.retries", "engine.degraded",
                         "engine.errors")):
            print(f"  {k} = {counters[k]}")
    for f in failures:
        print(f"  FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
