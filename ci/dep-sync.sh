#!/bin/bash
#
# Dependency-bump bot (analog of the reference's ci/submodule-sync.sh:34-117,
# which advances the cudf submodule nightly, runs `mvn verify`, and opens an
# auto-merge PR).  Here: refresh build/deps.pin to the installed jax/jaxlib,
# run the premerge gate, and commit on green to a bot branch.  PR opening /
# auto-merge is deployment-specific and left to the hosting CI.

set -ex
cd "$(dirname "$0")/.."

BRANCH=${BRANCH:-bot-dep-sync}

python - <<'PY'
import importlib.metadata as m
lines = []
for line in open("build/deps.pin"):
    s = line.strip()
    if not s or s.startswith("#"):
        lines.append(line.rstrip("\n"))
        continue
    pkg = s.split("==")[0]
    lines.append(f"{pkg}=={m.version(pkg)}")
open("build/deps.pin", "w").write("\n".join(lines) + "\n")
PY

if git diff --quiet build/deps.pin; then
    echo "dep-sync: pins already current"
    exit 0
fi

ci/premerge.sh

git checkout -B "$BRANCH"
git add build/deps.pin
git commit -m "Bump accelerator-stack pins to installed versions"
echo "dep-sync: committed to $BRANCH (open a PR from here)"
