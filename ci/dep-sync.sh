#!/bin/bash
#
# Dependency-bump bot (analog of the reference's ci/submodule-sync.sh:34-117,
# which advances the cudf submodule nightly, runs `mvn verify`, and opens an
# auto-merge PR).  Here: refresh build/deps.pin to the installed jax/jaxlib,
# run the premerge gate, and commit on green to a bot branch.  PR opening /
# auto-merge is deployment-specific and left to the hosting CI.

set -ex
cd "$(dirname "$0")/.."

BRANCH=${BRANCH:-bot-dep-sync}

python - <<'PY'
import importlib.metadata as m
lines = []
for line in open("build/deps.pin"):
    s = line.strip()
    if not s or s.startswith("#"):
        lines.append(line.rstrip("\n"))
        continue
    pkg = s.split("==")[0]
    lines.append(f"{pkg}=={m.version(pkg)}")
open("build/deps.pin", "w").write("\n".join(lines) + "\n")
PY

if git diff --quiet build/deps.pin; then
    echo "dep-sync: pins already current"
    exit 0
fi

# reviewable PR artifact (the PR half of the reference's
# ci/submodule-sync.sh:66-117, which posts the bump + CI verdict to a PR
# and auto-merges on green): the pin diff plus the gate result, staged
# under target/ for whatever forge hosts the bot branch
mkdir -p target
{
    echo "## dep-sync $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    echo
    echo '```diff'
    git diff build/deps.pin
    echo '```'
} > target/dep-sync-pr.md

if ci/premerge.sh; then
    echo -e "\npremerge: GREEN — safe to auto-merge" >> target/dep-sync-pr.md
else
    echo -e "\npremerge: RED — pin bump held (see CI log)" >> target/dep-sync-pr.md
    git checkout -- build/deps.pin
    echo "dep-sync: premerge failed; pins reverted, PR body in target/dep-sync-pr.md"
    exit 1
fi

git checkout -B "$BRANCH"
git add build/deps.pin
# bot identity: CI runners have no configured author (reference bot
# pattern, ci/submodule-sync.sh)
git -c user.name="dep-sync-bot" -c user.email="dep-sync-bot@invalid" \
    commit -m "Bump accelerator-stack pins to installed versions" \
    -m "$(cat target/dep-sync-pr.md)"
echo "dep-sync: committed to $BRANCH (PR body: target/dep-sync-pr.md)"
