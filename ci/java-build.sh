#!/bin/bash
#
# Toolchain-gated Java build (the pattern the reference uses for
# hardware-gated tests, ci/premerge-build.sh:28): compile + test the Java
# surface when a JDK and maven exist, skip cleanly otherwise.  The JUnit
# round-trip test additionally needs a running device server:
#
#   python -m spark_rapids_jni_tpu.bridge.server /tmp/tpubridge.sock &
#   TPU_BRIDGE_SOCKET=/tmp/tpubridge.sock ci/java-build.sh

set -e
cd "$(dirname "$0")/.."

if ! command -v javac >/dev/null || ! command -v mvn >/dev/null; then
    echo "java-build: SKIPPED (no JDK/maven on this machine)"
    exit 0
fi

mvn -B verify
echo "java-build: OK"
