#!/bin/bash
#
# Toolchain-gated Java build (the pattern the reference uses for
# hardware-gated tests, ci/premerge-build.sh:28): compile + test the Java
# surface when a JDK and maven exist, skip cleanly otherwise.  The JUnit
# round-trip test additionally needs a running device server:
#
#   python -m spark_rapids_jni_tpu.bridge.server /tmp/tpubridge.sock &
#   TPU_BRIDGE_SOCKET=/tmp/tpubridge.sock ci/java-build.sh

set -e
cd "$(dirname "$0")/.."

if ! command -v javac >/dev/null || ! command -v mvn >/dev/null; then
    echo "java-build: SKIPPED (no JDK/maven on this machine)"
    exit 0
fi

mvn -B verify

# NativeDepsLoader contract (reference pom.xml:362-391): the jar must carry
# the native bridge at ${os.arch}/${os.name}/ so the loader can extract and
# System.load it.  Fail the build if packaging silently dropped the .so.
JAR=$(ls target/spark-rapids-jni-tpu-*.jar 2>/dev/null | grep -v sources | head -1)
if [ -z "$JAR" ]; then
    echo "java-build: FAIL (no jar produced)" >&2
    exit 1
fi
if ! jar tf "$JAR" | grep -q 'libtpubridge.*\.so$'; then
    echo "java-build: FAIL (jar lacks libtpubridge*.so under arch/os path)" >&2
    jar tf "$JAR" >&2
    exit 1
fi
echo "java-build: OK ($(jar tf "$JAR" | grep -c '\.so$') native libs in jar)"
