#!/bin/bash
#
# Toolchain-gated Java build (the pattern the reference uses for
# hardware-gated tests, ci/premerge-build.sh:28): compile + test the Java
# surface when a JDK and maven exist, skip cleanly otherwise.  The JUnit
# round-trip test additionally needs a running device server:
#
#   python -m spark_rapids_jni_tpu.bridge.server /tmp/tpubridge.sock &
#   TPU_BRIDGE_SOCKET=/tmp/tpubridge.sock ci/java-build.sh

set -e
cd "$(dirname "$0")/.."

if ! command -v javac >/dev/null || ! command -v mvn >/dev/null; then
    echo "java-build: SKIPPED (no JDK/maven on this machine)"
    exit 0
fi

mvn -B verify

# NativeDepsLoader contract (reference pom.xml:362-391): the jar must carry
# the native bridge at ${os.arch}/${os.name}/ so the loader can extract and
# System.load it.  Fail the build if packaging silently dropped the .so.
JAR=$(ls target/spark-rapids-jni-tpu-*.jar 2>/dev/null | grep -v sources | head -1)
if [ -z "$JAR" ]; then
    echo "java-build: FAIL (no jar produced)" >&2
    exit 1
fi
if ! jar tf "$JAR" | grep -q 'libtpubridge.*\.so$'; then
    echo "java-build: FAIL (jar lacks libtpubridge*.so under arch/os path)" >&2
    jar tf "$JAR" >&2
    exit 1
fi

# Persist the JUnit evidence as a named artifact (the "Java mile ran"
# proof a JDK-less bench environment cannot produce): surefire XML +
# build provenance land in target/java-mile/ for CI to upload.
ART=target/java-mile
rm -rf "$ART"   # stale XMLs must never pass as current evidence
mkdir -p "$ART"
cp -r target/surefire-reports "$ART"/ 2>/dev/null || true
{
    echo "date: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    echo "jdk: $(javac -version 2>&1)"
    echo "jar: $(basename "$JAR")"
    echo "bridge_socket: ${TPU_BRIDGE_SOCKET:-<unset: JUnit bridge tests skipped>}"
    grep -h -o 'tests="[0-9]*"[^>]*' "$ART"/surefire-reports/*.xml \
        2>/dev/null || true
} > "$ART"/SUMMARY.txt
echo "java-build: OK ($(jar tf "$JAR" | grep -c '\.so$') native libs in jar;" \
     "JUnit evidence in $ART)"
