#!/bin/bash
#
# Nightly build (analog of ci/nightly-build.sh): premerge + benchmarks +
# wheel packaging with baked provenance.

set -ex
cd "$(dirname "$0")/.."

ci/premerge.sh

# nightly lint: premerge covers the smoke plans; --full extends the jaxpr
# sync-lint over the bench join + top-k plan shapes
JAX_PLATFORMS=cpu python tools/srjt_lint.py --segments --full \
    --baseline ci/lint-baseline.json

# nightly fuzz sweep: a bigger corpus on a fresh seed over the EXTENDED
# variant matrix (adds dist-nofuse + interp-notopk).  The shrunk repro
# artifact lands in target/fuzz-repro.json on failure — re-run with the
# printed seed to reproduce deterministically.
JAX_PLATFORMS=cpu python tools/srjt_fuzz.py \
    --seed "$(date +%Y%m%d)" --count 150 --full \
    --out target/fuzz-repro.json

# chaos soak: the fault-injection matrix against the pipeline plans
# (docs/ROBUSTNESS.md).  `timeout` is the outermost hang detector — a soak
# that can't finish inside 15 minutes IS a robustness failure.
JAX_PLATFORMS=cpu timeout 900 python ci/chaos_soak.py --devices 2

# benchmarks (runs on whatever backend jax selects; TPU when present)
python bench.py | tee target/bench-nightly.json

# regression gate over the full artifact — report-only until the _gate
# tolerances have soaked; flip to --enforce to make regressions fail
python ci/bench_gate.py --artifact target/bench-nightly.json --report-only

# wheel with provenance baked in (build/build-info ran in premerge)
python -m pip wheel --no-deps --no-build-isolation -w target/dist . \
    || python -m pip wheel --no-deps -w target/dist .

echo "nightly: OK"
