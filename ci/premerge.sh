#!/bin/bash
#
# Premerge gate (analog of the reference's ci/premerge-build.sh): dep pin
# check, native bridge build, full test suite on the 8-device virtual CPU
# mesh, multi-chip dryrun.  Hardware-gated tests are excluded the way the
# reference excludes CuFileTest (`-Dtest=*,!CuFileTest`): pytest marks them
# `requires_tpu` and conftest skips them off-hardware.

set -ex
cd "$(dirname "$0")/.."

build/dep-pin-check
build/build-info

# native bridge (C ABI client + optional JNI adapter when a JDK exists)
cmake -S src/main/cpp -B target/cpp-build -G Ninja \
      -DCMAKE_BUILD_TYPE=Release
cmake --build target/cpp-build

# static analysis (docs/ANALYSIS.md): repo AST lint (traced-host-op,
# config-env-read, host-sync-site), dispatch-table exhaustiveness, and the
# jaxpr sync-lint over the smoke plans' fused segments — exactly the 3
# whitelisted host syncs, no host callbacks, static output shapes.  New
# violations (anything not in ci/lint-baseline.json) fail the gate.
JAX_PLATFORMS=cpu python tools/srjt_lint.py --segments \
    --baseline ci/lint-baseline.json

# rewrite-soundness fuzz smoke (docs/ANALYSIS.md): 50 seeded plans swept
# through the flag matrix (interp/fused/dist-shuffle/dist-broadcast) with
# verify-after-rewrite, ledger==census, exchange census, sync whitelist,
# bit-exact executor parity, and pandas-oracle parity asserted per plan.
# Zero soundness violations required; failures print a shrunk minimal
# repro (seed + plan JSON).
JAX_PLATFORMS=cpu python tools/srjt_fuzz.py --smoke

# full suite on the virtual 8-device CPU mesh (includes bridge round trip)
python -m pytest tests/ -q

# engine perf-path smoke: tiny shapes through the fused-segment and
# double-buffered streaming paths end-to-end (correctness cross-checks,
# no timing assertions) — keeps the bench's perf paths runnable without
# paying full bench time in the gate.  Runs with tracing, the metrics
# layer, AND the timeline forced on so the instrumented paths (spans,
# histograms, Perfetto annotations, trace events) are exercised in-gate;
# the snapshot line must carry the per-query summary block and the
# timeline line must point at a loadable Chrome trace-event JSON
# (docs/OBSERVABILITY.md).
mkdir -p target
rm -rf target/smoke-profiles
SMOKE_OUT=$(JAX_PLATFORMS=cpu SRJT_TRACE=1 SRJT_METRICS=1 \
    SRJT_TIMELINE=1 SRJT_TIMELINE_OUT=target/smoke-timeline.json \
    SRJT_PROFILE_DIR=target/smoke-profiles \
    python bench.py --smoke)
echo "$SMOKE_OUT"
echo "$SMOKE_OUT" > target/smoke-artifact.json
echo "$SMOKE_OUT" | python -c '
import json, sys
snaps = [json.loads(l) for l in sys.stdin if l.strip()]
snap = [s for s in snaps if s.get("metric") == "metrics_snapshot"]
assert snap, "bench.py --smoke emitted no metrics_snapshot line"
assert snap[0].get("queries"), "metrics snapshot missing per-query summaries"
assert snap[0]["ok"], "metrics snapshot not ok"
print("metrics snapshot: %d per-query summaries" % len(snap[0]["queries"]))
tl = [s for s in snaps if s.get("metric") == "timeline"]
assert tl, "bench.py --smoke emitted no timeline line"
assert tl[0]["enabled"] and tl[0]["ok"], "timeline line not ok: %r" % tl[0]
trace = json.load(open(tl[0]["path"]))
evs = trace["traceEvents"]
assert evs and all("ph" in e and "name" in e for e in evs), \
    "timeline dump is not Chrome trace-event JSON"
assert any(e["ph"] == "X" for e in evs), "timeline has no complete spans"
print("timeline: %d trace events at %s" % (tl[0]["events"], tl[0]["path"]))
dist = [s for s in snaps if s.get("metric") == "engine_dist_smoke"]
assert dist, "bench.py --smoke emitted no engine_dist_smoke line"
assert dist[0]["ok"], "engine_dist_smoke not ok: %r" % dist[0]
ex = dist[0]["exchanges"]
# the static exchange census (verify.plan_exchanges) must equal what the
# executor actually ran, and co-partitioned plans must carry none
assert ex["broadcast_static"] == ex["broadcast_executed"], ex
assert ex["exchange_static"] == ex["exchange_executed"], ex
assert ex["copartitioned_static"] == ex["copartitioned_executed"] == 0, ex
print("engine dist: exchanges static==executed (%d broadcast-plan, %d "
      "exchange-plan), co-partitioned 0" % (ex["broadcast_executed"],
                                            ex["exchange_executed"]))
# per-device exchange attribution (docs/OBSERVABILITY.md): the per-(src,
# dest) wire matrix must sum to engine.exchange.wire_bytes, and the dist
# smoke plan must render skew in EXPLAIN ANALYZE
da = dist[0].get("device_attrib") or {}
assert da.get("matrix_matches") is True, \
    "exchange wire matrix != wire_bytes counter: %r" % da
assert da.get("explain_skew_rendered") is True, da
assert da.get("skew") is not None and da["skew"] >= 1.0, da
print("device attrib: %d exchange nodes, matrix sum %d == counter, "
      "skew %.2f" % (da["exchange_nodes"], da["wire_matrix_sum"],
                     da["skew"]))
prof = [s for s in snaps if s.get("metric") == "profile_store"]
assert prof, "bench.py --smoke emitted no profile_store line"
assert prof[0]["enabled"] and prof[0]["ok"], \
    "profile_store line not ok: %r" % prof[0]
assert prof[0]["profiles"] > 0, prof[0]
assert prof[0]["top_exchange_skew"] is not None, \
    "no exchange skew reached the profile store"
print("profile store: %d profiles at %s, top skew %s" %
      (prof[0]["profiles"], prof[0]["dir"], prof[0]["top_exchange_skew"]))
# AQE evidence plane (docs/OBSERVABILITY.md): the dist smoke report must
# carry the cardinality columns on every node line and a decision footer
# whose structural entry count equals the static census
ev = da.get("evidence") or {}
assert ev.get("node_lines_annotated") is True, \
    "EXPLAIN node lines missing est_rows/q_error: %r" % ev
assert ev.get("footer_rendered") is True and ev.get("decisions", 0) > 0, ev
assert ev.get("census_matches") is True, \
    "decision ledger count != static census: %r" % ev
print("evidence: %d decisions (%d pathed == census %d)" %
      (ev["decisions"], ev["decisions_pathed"], ev["census"]))
# the profile store carries the scored ledger + per-node q_error: some
# stored profile (the dist subprocess queries ran distributed plans)
# must have a decisions block, and some node must carry a q_error score
import glob
profs = [json.load(open(p))
         for p in glob.glob(prof[0]["dir"] + "/profile-*.json")]
assert any(p.get("decisions") for p in profs), \
    "no stored profile carries a decision ledger"
assert any(n.get("q_error") is not None
           for p in profs for n in p.get("nodes", ())), \
    "no stored profile node carries q_error"
ndec = sum(len(p.get("decisions") or ()) for p in profs)
print("profile evidence: %d decision entries across %d profiles" %
      (ndec, len(profs)))
mo = [s for s in snaps if s.get("metric") == "metrics_overhead"]
assert mo and mo[0]["ok"], "metrics_overhead line missing or not ok"
print("metrics overhead: on/off ratio %s (report-only gate key)" %
      mo[0]["ratios"]["on_vs_off"])
bo = [s for s in snaps if s.get("metric") == "blackbox_overhead"]
assert bo and bo[0]["ok"], "blackbox_overhead line missing or not ok"
print("blackbox overhead: on/off ratio %s (report-only gate key)" %
      bo[0]["ratios"]["on_vs_off"])
# adaptive execution (docs/ENGINE.md "Adaptive execution"): the skewed
# smoke run must have APPLIED at least one verified skew split, the
# post-split engine.exchange.skew gauge must sit under the trigger
# threshold (the re-deal provably flattened the hot device), and the
# repeat query must have planned run 2 from run 1s measured actuals
# (adaptive:history_warmed -> broadcast) and beaten the cold run — all
# with bit-parity against the AQE-off plans.  The wall-clock ratios
# (aqe.skew_ratio / aqe.rerun_vs_first) stay report-only in the gate
# below; this block asserts the structure.
aqe = [s for s in snaps if s.get("metric") == "aqe"]
assert aqe, "bench.py --smoke emitted no aqe line"
assert aqe[0]["ok"], "aqe line not ok: %r" % aqe[0]
sk, wm = aqe[0]["skew"], aqe[0]["warm"]
assert sk["splits_applied"] >= 1, "no adaptive:skew_split applied: %r" % sk
assert sk["gauge_skew"] is not None \
    and sk["gauge_skew"] < sk["threshold"], \
    "post-split skew gauge not under threshold: %r" % sk
assert sk["parity"] and wm["parity"], "AQE parity failed: %r" % aqe[0]
assert wm["warmed_entries"] >= 1 and wm["run2_broadcast_planned"], \
    "history warming did not replan run 2: %r" % wm
print("aqe: %d skew split(s) applied, skew %.2f -> gauge %.2f "
      "(threshold %.1f); warmed rerun planned broadcast, "
      "rerun_vs_first %s" % (sk["splits_applied"], sk["pre_skew"],
                             sk["gauge_skew"], sk["threshold"],
                             aqe[0]["rerun_vs_first"]))
# whole-stage fusion (docs/ENGINE.md): the fused run must pay exactly
# its static sync budget — and stay far under the host-orchestrated
# sync count — with bit-exact parity and a matching exchange census.
# The wall-clock ratio (fused_stage.vs_host_exchange) is report-only in
# the gate below while it soaks; this block asserts the structure.
fs = [s for s in snaps if s.get("metric") == "fused_stage"]
assert fs, "bench.py --smoke emitted no fused_stage line"
assert fs[0]["ok"], "fused_stage line not ok: %r" % fs[0]
fsy = fs[0]["host_syncs"]
assert fsy["fused"] == fsy["fused_budget"], \
    "fused syncs != static budget: %r" % fsy
assert fsy["fused"] < 5, \
    "fused stage paying host-path-order sync counts: %r" % fsy
assert fs[0]["results_match"], "fused vs host parity failed: %r" % fs[0]
print("fused_stage: %d sync(s) (== static budget, host path pays %d), "
      "%d dispatch(es), vs_host_exchange %s, bit-exact"
      % (fsy["fused"], fsy["host"], fs[0]["dispatches"],
         fs[0]["vs_host_exchange"]))
# row-conversion roofline: the smoke line must pass its numpy-oracle
# wire check; roofline_frac is the report-only gate key
rc = [s for s in snaps if s.get("metric") == "row_conversion"]
assert rc, "bench.py --smoke emitted no row_conversion line"
assert rc[0]["ok"], "row_conversion wire check failed: %r" % rc[0]
print("row_conversion: %.2f GB/s of %.2f ceiling (roofline_frac %s)"
      % (rc[0]["GBps"], rc[0]["ceiling_GBps"], rc[0]["roofline_frac"]))
# multi-tenant serving (docs/SERVING.md): the concurrent pass must be
# bit-exact per trace vs the serial pass, the forced-low-SLO scenario
# must shed at least once with the typed admission error carrying
# trace id + bundle pointer, and the repeat plan must serve from the
# result cache far under its cold wall.  The wall-clock keys
# (serving.p99_ms / serving.throughput / serving.shed_count) are
# ENFORCED in the gate below (promoted r7 after the r6 report-only
# soak); this block asserts the structure.
srv = [s for s in snaps if s.get("metric") == "serving"]
assert srv, "bench.py --smoke emitted no serving line"
assert srv[0]["ok"], "serving line not ok: %r" % srv[0]
shed = srv[0]["shed"]
assert shed and shed["kind"] == "resource" and shed["retryable"] is False, \
    "shed not the typed admission error: %r" % shed
assert shed["trace_id"] and shed["bundle"], \
    "shed error missing trace/bundle join: %r" % shed
assert srv[0]["shed_count"] >= 1, "no shed counted: %r" % srv[0]
assert srv[0]["result_cache_speedup"] > 10, \
    "result-cache repeat not well under cold wall: %r" % srv[0]
print("serving: %d clients bit-exact, p99 %.0fms, %d shed (typed, "
      "trace-joined), result-cache speedup %.0fx"
      % (srv[0]["clients"], srv[0]["p99_ms"], srv[0]["shed_count"],
         srv[0]["result_cache_speedup"]))
'

# Prometheus exposition: one local scrape through tools/srjt_export.py,
# parsed line-by-line as text exposition format (every line a comment or
# a srjt_-prefixed sample; histogram buckets cumulative)
JAX_PLATFORMS=cpu python tools/srjt_export.py --warm \
    > target/smoke-scrape.prom
python -c '
lines = [l.rstrip("\n") for l in open("target/smoke-scrape.prom") if l.strip()]
assert lines, "empty Prometheus scrape"
samples = 0
for l in lines:
    if l.startswith("# TYPE "):
        parts = l.split()
        assert len(parts) == 4 and parts[3] in ("counter", "gauge",
                                                "histogram"), l
        continue
    assert l.startswith("srjt_"), "non-exposition line: %r" % l
    name_labels, value = l.rsplit(" ", 1)
    float(value)  # every sample value parses as a number
    samples += 1
assert samples > 0
assert any("_bucket{le=" in l for l in lines), "no histogram buckets"
print("prometheus scrape: %d samples parse as text exposition" % samples)
'

# bench regression gate: ENFORCED for the smoke-line ratio keys that have
# soaked since PR 5, the serving keys promoted r7 after their r6
# report-only soak, and fused_stage.vs_host_exchange promoted r8 after
# its r7 soak (--enforce-keys allowlist — a regression or a silently
# dropped key among them fails premerge); every other enrolled key,
# including the PR-8 dist ratios, the profile-derived keys,
# row_conversion.roofline_frac, and the new r8 device-decode keys
# (parquet.device_vs_host, parquet.link_ratio — backend-dependent, see
# BENCH_BASELINES.json), stays report-only in the same run.  --profiles
# folds the query-profile store into the artifact
# (profile.exchange.skew, profile.chunk_latency.p99).
python ci/bench_gate.py --artifact target/smoke-artifact.json \
    --profiles target/smoke-profiles \
    --enforce \
    --enforce-keys engine_pipeline_smoke.ratios.fused_vs_interp,engine_join_smoke.ratios.cached_vs_per_chunk,serving.p99_ms,serving.throughput,serving.shed_count,fused_stage.vs_host_exchange

# end-to-end trace join (docs/OBSERVABILITY.md): a clean query's
# client-minted trace id must reach the server's OP_METRICS summary and
# the stored profile with zero bundles cut; a fault-injected failing
# PLAN_EXECUTE must surface a typed client exception whose trace id
# matches the server's post-mortem bundle (named by the wire error doc)
# AND the profile-store entry for the failed run — one id across the
# whole serving path, proven over a real process boundary.
JAX_PLATFORMS=cpu python ci/trace_join_check.py

# the driver's multi-chip entry must keep compiling + executing
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# Java mile (VERDICT r3 #4): when a JDK+maven exist (always true in the
# ci/Dockerfile container), run the full Java build — JNI adapter compile,
# jar packaging with the .so at ${os.arch}/${os.name}/, and the JUnit
# round-trip + engine-ops tests against a LIVE bridge server.
# ci/java-build.sh skips cleanly on machines without a JDK (the
# reference's hardware-gate pattern, ci/premerge-build.sh:28) and, when
# it runs, leaves the JUnit XML + provenance in target/java-mile/ — the
# uploadable proof that the Java mile executed.  Environments without a
# JDK (this bench image has none) still exercise the identical native
# call path through the C-ABI harness (bridge_roundtrip_test), which the
# python step above runs unconditionally.
if command -v javac >/dev/null 2>&1 && command -v mvn >/dev/null 2>&1; then
    BRIDGE_SOCK=$(mktemp -u /tmp/tpubridge.XXXXXX.sock)
    JAX_PLATFORMS=cpu python -m spark_rapids_jni_tpu.bridge.server \
        --socket "$BRIDGE_SOCK" &
    BRIDGE_PID=$!
    trap 'kill $BRIDGE_PID 2>/dev/null || true' EXIT
    for _ in $(seq 60); do [ -S "$BRIDGE_SOCK" ] && break; sleep 1; done
    [ -S "$BRIDGE_SOCK" ]  # server must be up
    TPU_BRIDGE_SOCKET="$BRIDGE_SOCK" ci/java-build.sh
    kill $BRIDGE_PID 2>/dev/null || true
    trap - EXIT
else
    ci/java-build.sh   # prints its SKIPPED line
fi

echo "premerge: OK"
