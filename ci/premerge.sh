#!/bin/bash
#
# Premerge gate (analog of the reference's ci/premerge-build.sh): dep pin
# check, native bridge build, full test suite on the 8-device virtual CPU
# mesh, multi-chip dryrun.  Hardware-gated tests are excluded the way the
# reference excludes CuFileTest (`-Dtest=*,!CuFileTest`): pytest marks them
# `requires_tpu` and conftest skips them off-hardware.

set -ex
cd "$(dirname "$0")/.."

build/dep-pin-check
build/build-info

# native bridge (C ABI client + optional JNI adapter when a JDK exists)
cmake -S src/main/cpp -B target/cpp-build -G Ninja \
      -DCMAKE_BUILD_TYPE=Release
cmake --build target/cpp-build

# full suite on the virtual 8-device CPU mesh (includes bridge round trip)
python -m pytest tests/ -q

# the driver's multi-chip entry must keep compiling + executing
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "premerge: OK"
