"""Deterministic fault injection (``SRJT_FAULTS``).

Every recovery path in the engine — retry, OOM degradation, cancellation —
must be testable on CPU without real hardware faults.  This module plants
that capability: ``check(site)`` seams sit at the engine's real failure
domains, and the ``SRJT_FAULTS`` spec arms them deterministically.

Spec grammar (comma-separated entries)::

    SRJT_FAULTS = site:nth[:kind][,site:nth[:kind]...]

- ``site``  — one of :data:`SITES` (a seam location).
- ``nth``   — 1-based occurrence to fault, or ``*`` for every occurrence.
- ``kind``  — ``io_error`` (default) | ``oom`` | ``timeout``.

Examples::

    SRJT_FAULTS=parquet.chunk:3:io_error          # 3rd chunk decode fails
    SRJT_FAULTS=exchange.dispatch:1:oom           # 1st exchange chunk OOMs
    SRJT_FAULTS=parquet.chunk:*:io_error          # every decode fails
    SRJT_FAULTS=spill.write:2,staging.transfer:1:oom

Kinds map to the taxonomy (utils/errors.py): ``io_error`` raises
:class:`InjectedIOError` (transient, retryable), ``oom`` raises
:class:`InjectedResourceExhausted` (resource — triggers the degradation
ladder), ``timeout`` sleeps :data:`HANG_S` so deadline tokens trip at the
next boundary.  Each injection ticks ``faults.injected.<site>.<kind>``.

Zero-overhead contract: with ``SRJT_FAULTS`` unset, ``check`` is one falsy
attribute test and an immediate return — safe on per-chunk hot paths.
Occurrence counters key off the live config string, so tests flipping
``SRJT_FAULTS`` + ``config.refresh()`` re-arm automatically; ``reset()``
re-arms the counters for a fresh run under the same spec.
"""

from __future__ import annotations

import threading
import time

from . import errors
from .config import config, logger

#: the planted seams (one per engine failure domain)
SITES = (
    "parquet.chunk",      # io/parquet.py: per-row-group host decode
    "parquet.prefetch",   # io/parquet.py: prefetch producer thread
    "parquet.device_decode",  # io/parquet.py: device page-plane transfer
    "staging.transfer",   # io/staging.py: host->device staging
    "exchange.dispatch",  # parallel/shuffle.py: per-chunk shuffle dispatch
    "spill.write",        # parallel/spill.py: spill-pass buffer write
    "bridge.op",          # bridge/server.py: op dispatch
)

KIND_IO_ERROR = "io_error"
KIND_OOM = "oom"
KIND_TIMEOUT = "timeout"
KINDS = (KIND_IO_ERROR, KIND_OOM, KIND_TIMEOUT)

#: how long a ``timeout`` injection stalls (long enough for a sub-second
#: SRJT_QUERY_TIMEOUT_S deadline to expire before the next boundary check)
HANG_S = 0.05


class InjectedIOError(errors.TransientError, OSError):
    """A fault-injected transient I/O failure."""


class InjectedResourceExhausted(errors.ResourceExhaustedError):
    """A fault-injected allocation failure (device RESOURCE_EXHAUSTED)."""

    def __str__(self) -> str:  # carry the real runtime's marker so code
        # matching on the XLA status string treats injections identically
        return f"RESOURCE_EXHAUSTED (injected): {super().__str__()}"


class FaultSpecError(ValueError):
    """SRJT_FAULTS failed to parse."""


_lock = threading.Lock()
_armed_for: str | None = None              # spec string the state matches
_rules: dict[str, list] = {}               # site -> [(nth|None, kind), ...]
_hits: dict[str, int] = {}                 # site -> occurrences so far


def parse(spec: str) -> dict:
    """Parse a spec string into ``{site: [(nth|None, kind), ...]}``."""
    rules: dict[str, list] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3):
            raise FaultSpecError(
                f"bad SRJT_FAULTS entry {entry!r} (want site:nth[:kind])")
        site, nth_s = parts[0].strip(), parts[1].strip()
        kind = parts[2].strip() if len(parts) == 3 else KIND_IO_ERROR
        if site not in SITES:
            raise FaultSpecError(
                f"unknown fault site {site!r} (known: {', '.join(SITES)})")
        if kind not in KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} (known: {', '.join(KINDS)})")
        if nth_s == "*":
            nth = None
        else:
            try:
                nth = int(nth_s)
            except ValueError:
                raise FaultSpecError(
                    f"bad occurrence {nth_s!r} in {entry!r} "
                    "(want a 1-based integer or '*')") from None
            if nth < 1:
                raise FaultSpecError(
                    f"occurrence must be >= 1 in {entry!r}")
        rules.setdefault(site, []).append((nth, kind))
    return rules


def _arm(spec: str) -> None:
    """(Re)build rules + zero the hit counters for ``spec`` (lock held)."""
    global _armed_for, _rules
    _rules = parse(spec)
    _hits.clear()
    _armed_for = spec


def reset() -> None:
    """Zero the occurrence counters (tests re-arm between runs)."""
    with _lock:
        _hits.clear()


def active() -> bool:
    return bool(config.faults)


def check(site: str) -> None:
    """Fault seam: count this occurrence of ``site`` and inject if armed.

    First line is the zero-overhead gate — with ``SRJT_FAULTS`` unset this
    is a falsy attribute test and a return.
    """
    spec = config.faults
    if not spec:
        return
    with _lock:
        if spec != _armed_for:
            _arm(spec)
        rules = _rules.get(site)
        if not rules:
            return
        n = _hits.get(site, 0) + 1
        _hits[site] = n
        kind = None
        for nth, k in rules:
            if nth is None or nth == n:
                kind = k
                break
        if kind is None:
            return
    _inject(site, n, kind)


def _inject(site: str, n: int, kind: str) -> None:
    from . import metrics
    metrics.count(f"faults.injected.{site}.{kind}")
    logger().info("fault injected at %s#%d: %s", site, n, kind)
    if kind == KIND_IO_ERROR:
        raise InjectedIOError(f"injected io_error at {site}#{n}")
    if kind == KIND_OOM:
        raise InjectedResourceExhausted(f"injected oom at {site}#{n}")
    # timeout: stall so a deadline token expires before the next boundary
    time.sleep(HANG_S)
