"""In-process event timeline: Chrome trace-event export without jax.profiler.

``SRJT_TRACE`` gives Perfetto spans *through* ``jax.profiler`` — heavyweight,
platform-dependent, and unavailable in plenty of deployment shells.  This
module is the always-available fallback the bridge and bench can ship: a
bounded ring buffer of events recorded with nothing but ``perf_counter`` and
a deque append, exported as Chrome trace-event JSON that loads directly in
Perfetto (ui.perfetto.dev) or ``chrome://tracing``.

Gated by ``SRJT_TIMELINE`` (default off); with the flag off every entry
point returns immediately — no contexts, no allocation — so the streaming
fast paths stay uninstrumented.  Like the metrics layer, recording is pure
host-side bookkeeping: no device syncs anywhere.

Event vocabulary (Chrome trace-event ``ph`` codes):

- **Spans** — ``span(name)`` / ``complete(name, t0, dur)`` record one
  ``"X"`` complete event per finished span (begin/end collapsed into ts +
  dur).  A still-open span holds no buffer slot, so ring-buffer overflow
  can only ever drop *finished* history — open spans cannot be corrupted.
- **Instants** — ``instant(name)``: ``"i"`` events marking the engine's
  deliberate host syncs (``metrics.host_sync`` calls through here).
- **Flows** — ``flow_start``/``flow_finish``: ``"s"``/``"f"`` arrows
  linking the prefetch producer's staging of chunk N to the consumer's
  dispatch of chunk N across threads.
- **Counters** — ``counter(name, value)``: ``"C"`` tracks (device
  live-bytes over time, fed by ``metrics.mem_checkpoint``).

Events carry the active query name (``metrics.current()``) as an arg when
one is bound, so timeline slices correlate with per-query summaries.

Export: ``export()`` -> ``{"traceEvents": [...]}`` with thread-name
metadata records; ``dump(path)`` writes it as JSON.  Timestamps are
``perf_counter`` microseconds (monotonic within the process, which is all
the trace viewer needs).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque

from .config import config

_lock = threading.Lock()
_buf: deque | None = None      # created lazily at first record / reset()
_buf_cap = 0
_thread_names: dict[int, str] = {}
_flow_seq = itertools.count(1)
_dropped = 0                   # events evicted by ring overflow
_warned: set[str] = set()      # query names already warned about overflow

_PID = os.getpid()

#: synthetic tid base for per-device lanes — far above any OS thread id,
#: so device lanes render as their own named rows next to real threads
_DEV_TID_BASE = 1 << 48


def device_lane(dev: int) -> int:
    """Synthetic tid of device ``dev``'s timeline lane."""
    return _DEV_TID_BASE + int(dev)


def enabled() -> bool:
    """Live SRJT_TIMELINE gate (config singleton, refresh()-tunable)."""
    return config.timeline


def _now_us() -> float:
    return time.perf_counter() * 1e6


def _qname() -> str | None:
    # lazy import: metrics imports this module at load time (host_sync
    # instants), so the reverse edge must resolve at call time
    from . import metrics
    q = metrics.current()
    return q.name if q is not None else None


def _buffer() -> deque:
    """The ring buffer at the configured capacity (SRJT_TIMELINE_CAP).

    ``deque(maxlen=cap)`` IS the ring: appends past capacity drop the
    oldest event.  Only finished events ever occupy a slot, so overflow
    discards old history and nothing else."""
    global _buf, _buf_cap
    cap = max(16, int(config.timeline_cap))
    if _buf is None or _buf_cap != cap:
        old = list(_buf) if _buf is not None else []
        _buf = deque(old[-cap:], maxlen=cap)
        _buf_cap = cap
    return _buf


def _append(ev: dict, dev: int | None = None) -> None:
    global _dropped
    if dev is None:
        tid, tname = threading.get_ident(), None
    else:
        tid, tname = device_lane(dev), f"device:{int(dev)}"
    ev["pid"] = _PID
    ev["tid"] = tid
    q = _qname()
    if q is not None:
        ev.setdefault("args", {})["query"] = q
    # end-to-end trace id (utils/blackbox.py): ties timeline slices to
    # bridge spans and post-mortem bundles across processes
    from . import blackbox
    trace = blackbox.current_trace()
    if trace:
        ev.setdefault("args", {})["trace"] = trace
    dropped_now = warn = False
    with _lock:
        if tid not in _thread_names:
            _thread_names[tid] = tname if tname is not None \
                else threading.current_thread().name
        buf = _buffer()
        if buf.maxlen is not None and len(buf) == buf.maxlen:
            _dropped += 1
            dropped_now = True
            qkey = q or ""
            if qkey not in _warned:
                _warned.add(qkey)
                warn = True
        buf.append(ev)
    if dropped_now:
        # overflow gauge lives in the metrics layer; lazy import breaks
        # the metrics -> timeline load-time edge
        from . import metrics
        metrics.gauge_set("timeline.dropped_events", float(_dropped))
    if warn:
        from .config import logger
        logger().warning(
            "timeline ring overflow%s: oldest events dropped "
            "(raise SRJT_TIMELINE_CAP, currently %d)",
            f" in query {q!r}" if q else "", config.timeline_cap)


# -- recording ---------------------------------------------------------------

@contextlib.contextmanager
def span(name: str, args: dict | None = None):
    """Record one complete ("X") event for the enclosed region.

    No-op context when SRJT_TIMELINE=0 (checked once at entry)."""
    if not config.timeline:
        yield
        return
    t0 = _now_us()
    try:
        yield
    finally:
        ev = {"name": name, "ph": "X", "ts": t0, "dur": _now_us() - t0}
        if args:
            ev["args"] = dict(args)
        _append(ev)


def complete(name: str, t0_s: float, dur_s: float,
             args: dict | None = None, dev: int | None = None) -> None:
    """Record an already-measured span (perf_counter seconds), for call
    sites that timed the region themselves (segment compile/replay).
    ``dev`` routes the slice onto that device's lane instead of the
    calling thread's row (per-device exchange receipt)."""
    if not config.timeline:
        return
    ev = {"name": name, "ph": "X", "ts": t0_s * 1e6, "dur": dur_s * 1e6}
    if args:
        ev["args"] = dict(args)
    _append(ev, dev=dev)


def instant(name: str, args: dict | None = None,
            dev: int | None = None) -> None:
    """Thread-scoped instant ("i") event — the host-sync markers."""
    if not config.timeline:
        return
    ev = {"name": name, "ph": "i", "ts": _now_us(), "s": "t"}
    if args:
        ev["args"] = dict(args)
    _append(ev, dev=dev)


def counter(name: str, value: float, dev: int | None = None) -> None:
    """Counter-track ("C") sample, e.g. device live-bytes over time; with
    ``dev``, a per-device track (cumulative exchange rows per device)."""
    if not config.timeline:
        return
    _append({"name": name, "ph": "C", "ts": _now_us(),
             "args": {"value": float(value)}}, dev=dev)


def new_flow_base() -> int:
    """A fresh id block for one flow stream: ids ``base + n`` are unique
    across streams as long as a stream emits < 2^32 flows."""
    return next(_flow_seq) << 32


def flow_start(name: str, flow_id: int, args: dict | None = None) -> None:
    """Flow arrow tail ("s"): the producer side of a chunk handoff."""
    if not config.timeline:
        return
    ev = {"name": name, "ph": "s", "ts": _now_us(), "id": int(flow_id),
          "cat": "flow"}
    if args:
        ev["args"] = dict(args)
    _append(ev)


def flow_finish(name: str, flow_id: int, args: dict | None = None,
                dev: int | None = None) -> None:
    """Flow arrow head ("f", binding to the enclosing slice): the consumer
    side of the handoff recorded by ``flow_start`` with the same id.
    ``dev`` lands the arrow head on that device's lane (exchange dispatch
    -> per-device receipt)."""
    if not config.timeline:
        return
    ev = {"name": name, "ph": "f", "ts": _now_us(), "id": int(flow_id),
          "cat": "flow", "bp": "e"}
    if args:
        ev["args"] = dict(args)
    _append(ev, dev=dev)


# -- export / lifecycle ------------------------------------------------------

def events_snapshot() -> list:
    """Copy of the buffered events (oldest first), no metadata records."""
    with _lock:
        return [dict(e) for e in (_buf or ())]


def export() -> dict:
    """Chrome trace-event document: thread-name metadata + buffered events.

    Loadable as-is at ui.perfetto.dev / chrome://tracing."""
    with _lock:
        events = [dict(e) for e in (_buf or ())]
        names = dict(_thread_names)
        dropped = _dropped
    meta = [{"name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
             "args": {"name": "spark_rapids_jni_tpu"}}]
    for tid, tname in sorted(names.items()):
        meta.append({"name": "thread_name", "ph": "M", "pid": _PID,
                     "tid": tid, "args": {"name": tname}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": {"dropped_events": dropped}}


def dump(path: str) -> str:
    """Write ``export()`` to ``path`` (dirs created); returns the path."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(export(), f)
    return path


def dropped_events() -> int:
    """Events evicted by ring overflow since the last ``reset()``."""
    with _lock:
        return _dropped


def reset() -> None:
    """Drop all buffered events (tests; also picks up a changed cap)."""
    global _buf, _buf_cap, _dropped
    with _lock:
        _buf = None
        _buf_cap = 0
        _dropped = 0
        _thread_names.clear()
        _warned.clear()
