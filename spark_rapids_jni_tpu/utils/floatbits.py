"""float64 <-> IEEE-754 bit-pattern conversion that works on TPU.

The TPU X64-emulation pass cannot lower ``bitcast-convert`` on f64 operands,
and ``jnp.signbit`` / ``frexp`` / ``ldexp`` all reduce to such bitcasts
(verified on v5e: each fails to compile, while 64-bit integer arithmetic and
<=32-bit bitcasts work; f64 ``exp2`` compiles but evaluates at f32 precision).
The row wire format (reference src/main/cpp/src/row_conversion.cu:432-456
packs raw column bytes into rows) needs FLOAT64 bit patterns, so:

- On backends with native f64 bitcast (cpu), we bitcast: bit-exact for every
  pattern including subnormals and NaN payloads.
- Elsewhere (tpu) we compute the pattern with pure f64 arithmetic — binary
  exponent-reduction ladders built from comparisons and exact power-of-two
  multiplications:
  * normals and +/-0 and +/-inf are exact;
  * subnormals map to +/-0 — XLA on these backends runs f64 in DAZ/FTZ mode
    (verified: ``5e-324 * 2.0 == 0``), so subnormal values are unobservable by
    any on-device compute anyway;
  * NaNs canonicalize to the quiet NaN 0x7ff8000000000000 (Spark treats all
    NaNs as equal, so payload loss is observationally safe in SQL semantics).

The arithmetic path is itself tested on CPU (same DAZ behavior, representative
of TPU) against the bitcast ground truth — tests/test_floatbits.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_CANON_NAN = jnp.uint64(0x7FF8000000000000)
_INF_BITS = jnp.uint64(0x7FF0000000000000)
_MANT_MASK = jnp.uint64((1 << 52) - 1)
_TWO52 = 2.0**52

# 512 appears twice so the ladders cover the full exponent range (|e| <= 1074:
# two 512-steps leave a residual < 512, which the descending powers-of-two then
# decompose exactly).  Every multiplication is by a power of two with a normal
# result, hence exact.
_LADDER = (512, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)


def _sign_mask(x: jnp.ndarray) -> jnp.ndarray:
    """signbit without bitcast: catches -0.0 via the sign of 1/x."""
    neg_zero = (x == 0.0) & (1.0 / x < 0.0)
    return ((x < 0.0) | neg_zero).astype(jnp.uint64) << jnp.uint64(63)


def _f64_to_bits_arith(x: jnp.ndarray) -> jnp.ndarray:
    x = jnp.asarray(x, jnp.float64)
    sign = _sign_mask(x)
    ax = jnp.abs(x)
    # normalize ax = m * 2^e with m in [0.5, 1) by exponent binary search
    m, e = ax, jnp.zeros(x.shape, jnp.int32)
    for k in _LADDER:  # reduce m >= 1 downward
        c = m >= 2.0**k
        m = jnp.where(c, m * 2.0**-k, m)
        e = jnp.where(c, e + k, e)
    for k in _LADDER:  # raise m < 0.5 upward
        c = m < 2.0**-k
        m = jnp.where(c, m * 2.0**k, m)
        e = jnp.where(c, e - k, e)
    c = m >= 1.0
    m = jnp.where(c, m * 0.5, m)
    e = jnp.where(c, e + 1, e)
    # mantissa: (2m - 1) * 2^52 is exact (m carries <= 53 significant bits).
    # clamp before the uint cast: for x == 0 the ladder leaves m == 0, and
    # float->uint64 of the resulting -2^52 wraps to 0xFFF0000000000000 on TPU
    mant = (jnp.maximum(m * 2.0 - 1.0, 0.0) * _TWO52).astype(jnp.uint64)
    bexp = jnp.clip(e + 1022, 0, 2046).astype(jnp.uint64)
    bits = (bexp << jnp.uint64(52)) | mant
    # below the normal range: DAZ semantics, flush to zero (see module doc).
    # The explicit == 0 term does not rely on the 2^-1022 constant surviving
    # the backend's f64 emulation; the threshold term catches true subnormals
    # whether or not the compare itself flushes.
    bits = jnp.where((ax == 0.0) | (ax < 2.0**-1022), jnp.uint64(0), bits)
    bits = jnp.where(jnp.isinf(x), _INF_BITS, bits)
    return jnp.where(jnp.isnan(x), _CANON_NAN, sign | bits)


def _bits_to_f64_arith(b: jnp.ndarray) -> jnp.ndarray:
    b = jnp.asarray(b, jnp.uint64)
    sign = (b >> jnp.uint64(63)).astype(jnp.bool_)
    bexp = ((b >> jnp.uint64(52)) & jnp.uint64(0x7FF)).astype(jnp.int32)
    mant_u = b & _MANT_MASK
    # val = (mant + 2^52) * 2^(bexp - 1075), scaling via the exact ladder;
    # intermediates stay monotone toward the (normal) result, so no spurious
    # overflow/underflow.
    val = mant_u.astype(jnp.float64) + _TWO52  # exact: < 2^53
    e = bexp - 1075
    for k in _LADDER:
        up = e >= k
        val = jnp.where(up, val * 2.0**k, val)
        e = jnp.where(up, e - k, e)
        down = e <= -k
        val = jnp.where(down, val * 2.0**-k, val)
        e = jnp.where(down, e + k, e)
    val = jnp.where(bexp == 0, 0.0, val)  # subnormal patterns flush (DAZ/FTZ)
    val = jnp.where(
        bexp == 0x7FF,
        jnp.where(mant_u == 0, jnp.float64(jnp.inf), jnp.float64(jnp.nan)),
        val,
    )
    return jnp.where(sign, -val, val)


def _native_f64_bitcast() -> bool:
    return jax.default_backend() == "cpu"


def f64_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """IEEE-754 bit pattern of float64 values as uint64."""
    if _native_f64_bitcast():
        return jax.lax.bitcast_convert_type(jnp.asarray(x, jnp.float64), jnp.uint64)
    return _f64_to_bits_arith(x)


def bits_to_f64(b: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`f64_to_bits`."""
    if _native_f64_bitcast():
        return jax.lax.bitcast_convert_type(jnp.asarray(b, jnp.uint64), jnp.float64)
    return _bits_to_f64_arith(b)


def f64_to_u32_pair(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(lo, hi) little-endian uint32 halves of float64 bit patterns."""
    bits = f64_to_bits(x)
    lo = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (bits >> jnp.uint64(32)).astype(jnp.uint32)
    return lo, hi


def u32_pair_to_f64(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    bits = lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << jnp.uint64(32))
    return bits_to_f64(bits)
