from . import bitmask
from . import config
from . import tracing

__all__ = ["bitmask", "config", "tracing"]
