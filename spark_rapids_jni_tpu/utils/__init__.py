from . import bitmask
from . import config
from . import memory
from . import timeline
from . import tracing

__all__ = ["bitmask", "config", "memory", "timeline", "tracing"]
