from . import bitmask
from . import config
from . import memory
from . import tracing

__all__ = ["bitmask", "config", "memory", "tracing"]
