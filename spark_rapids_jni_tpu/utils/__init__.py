from . import bitmask

__all__ = ["bitmask"]
