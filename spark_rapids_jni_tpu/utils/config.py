"""Runtime flag system (analog of the reference's config pass-through).

The reference threads Maven ``-D`` properties through ant/cmake into compile
definitions and JVM sysprops (reference pom.xml:79-103, 404-408;
CONTRIBUTING.md:64-78 documents the table).  A jax library's equivalent is
environment flags read once at import:

| flag | default | reference analog |
|---|---|---|
| ``SRJT_TRACE``        | ``0``   | ``ai.rapids.cudf.nvtx.enabled`` (pom.xml:84,407) |
| ``SRJT_PALLAS``       | ``auto``| ``GPU_ARCHS`` (kernel backend selection) |
| ``SRJT_LOG_LEVEL``    | ``WARNING`` | ``RMM_LOGGING_LEVEL`` (pom.xml:81) |
| ``SRJT_LEAK_DEBUG``   | ``0``   | ``ai.rapids.refcount.debug`` (pom.xml:85,406) |
| ``SRJT_FUSE``         | ``1``   | whole-stage codegen toggle (engine segment fusion) |
| ``SRJT_PREFETCH``     | ``1``   | chunked-scan pipeline depth (0 = serial) |
| ``SRJT_PLAN_CACHE``   | ``128`` | plan-cache capacity (spark.sql plan-cache size) |
| ``SRJT_SEGMENT_CACHE``| ``256`` | compiled-segment cache capacity |
| ``SRJT_FUSE_JOIN``    | ``1``   | fuse scan-independent-build joins into streamed chunk programs |
| ``SRJT_TOPK``         | ``1``   | streaming top-k for ORDER BY ... LIMIT (TopK plans) |
| ``SRJT_BUILD_CACHE``  | ``32``  | prepared-join-build cache capacity (entries) |
| ``SRJT_METRICS``      | ``1``   | query-scoped metrics collection (spans/histograms/gauges, utils/metrics.py) |
| ``SRJT_TIMELINE``     | ``0``   | in-process trace-event timeline (utils/timeline.py, Perfetto-loadable JSON) |
| ``SRJT_TIMELINE_CAP`` | ``16384`` | timeline ring-buffer capacity (events; oldest dropped) |
| ``SRJT_LOG_FORMAT``   | ``text``| ``json`` emits one JSON object per log line (ts/level/logger/msg + active query) |
| ``SRJT_VERIFY``       | ``1``   | static plan verification in optimize()/PLAN_EXECUTE (engine/verify.py) |
| ``SRJT_DIST``         | ``0``   | partitioning-aware distributed planning (Exchange placement rules) |
| ``SRJT_BROADCAST_ROWS`` | ``100000`` | broadcast-join threshold: estimated build rows at or under this replicate instead of shuffling |
| ``SRJT_AQE``          | ``0``   | adaptive query execution (engine/adaptive.py): runtime broadcast flip, hot-key skew split, profile-warmed planning |
| ``SRJT_FUSE_EXCHANGE`` | ``0``  | whole-stage exchange fusion: lower the partial-agg -> hash Exchange -> final-agg sandwich into ONE pjit/shard_map program (engine/segment.py fused stage) |
| ``SRJT_FUSE_GROUPS`` | ``4096`` | fused stage's static per-shard live-group budget: sizes the in-program exchange (prefix + per-dest capacity); a shard aggregating more groups trips the device-side overflow counter and the stage re-plans on the host path |
| ``SRJT_AQE_SKEW``     | ``4.0`` | skew (max/mean device load) above which a hash exchange splits its hot keys round-robin |
| ``SRJT_AQE_BROADCAST_ROWS`` | ``-1`` | measured-rows threshold for the runtime broadcast flip (``-1`` = follow ``SRJT_BROADCAST_ROWS``) |
| ``SRJT_PROFILE_DIR``  | *(unset)* | persist one compact query profile JSON per query into this dir (utils/profile.py; empty = off) |
| ``SRJT_PROFILE_CAP``  | ``512`` | on-disk profile ring capacity (oldest profiles pruned past this) |
| ``SRJT_FAULTS``       | *(unset)* | deterministic fault injection spec ``site:nth[:kind],...`` (utils/faults.py; empty = all seams no-op) |
| ``SRJT_RETRY_MAX``    | ``3``   | max per-site retries of transient failures (engine/recovery.py) |
| ``SRJT_RETRY_BACKOFF_S`` | ``0.01`` | base retry backoff seconds (doubles per attempt, ±25% jitter) |
| ``SRJT_QUERY_TIMEOUT_S`` | ``0`` | cooperative per-query deadline in seconds (0 = none; checked at chunk boundaries) |
| ``SRJT_BRIDGE_TIMEOUT_S`` | ``60`` | per-op socket deadline on bridge client+server (0 = block forever, the pre-hardening behavior) |
| ``SRJT_MEM_DEBUG``    | ``0``   | live-buffer census checkpoints + MemoryScope exit report (io chunked reader, utils/memory.py) |
| ``SRJT_BLACKBOX``     | ``1``   | always-on flight recorder (utils/blackbox.py): bounded ring of coarse events, independent of SRJT_METRICS/SRJT_TIMELINE |
| ``SRJT_BLACKBOX_DIR`` | *(unset)* | post-mortem bundle directory (empty = ring only, no disk writes) |
| ``SRJT_BLACKBOX_CAP`` | ``512`` | flight-recorder ring capacity (events; oldest dropped) |
| ``SRJT_SLO_MS``       | *(unset)* | latency objectives: ``default_ms[,fp12=ms,...]`` per source fingerprint, evaluated from the profile store (utils/blackbox.py slo_report) |
| ``SRJT_TRACE_ID``     | *(unset)* | inherited trace context for helper processes (bench dist subprocess); minted per client/query when empty |
| ``SRJT_ROOFLINE_GBPS`` | ``0`` | device-bandwidth ceiling override for explain-analyze roofline fractions (0 = use BENCH_BASELINES.json pin) |
| ``SRJT_SCHED``        | ``1``   | multi-tenant scheduler (engine/scheduler.py): SLO-aware admission + fair-share chunk interleaving on the bridge PLAN_EXECUTE path |
| ``SRJT_MAX_SESSIONS`` | ``8``   | concurrent admitted PLAN_EXECUTE sessions; arrivals past this queue at admission |
| ``SRJT_ADMISSION_QUEUE_S`` | ``5.0`` | max seconds a query waits in the admission queue before it is shed (AdmissionRejectedError) |
| ``SRJT_ADMISSION_BURN`` | ``0.9`` | SLO burn rate (breaches/runs from the profile store) at or above which a saturated server sheds the fingerprint immediately instead of queueing |
| ``SRJT_SESSION_BUDGET_BYTES`` | ``0`` | per-session device-memory budget charged at chunk boundaries (0 = unlimited; bounds the spill ladder and gates the OOM retry-first path) |
| ``SRJT_RESULT_CACHE`` | ``0``   | result-set cache capacity (entries) keyed (plan fingerprint, data version); 0 = off |
| ``SRJT_DEVICE_DECODE`` | ``0``  | device-side parquet page decode (ops/parquet_decode.py): ship compressed pages, decompress + decode in the fused scan segment; ineligible chunks re-plan to the host decoder per chunk |
| ``JAX_PLATFORMS``     | *(unset)* | jax platform list honored by the bridge server before its first jax touch |

``refresh()`` re-reads the environment (tests use it); everything else
reads the module-level singleton.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from dataclasses import dataclass, fields


def _bool_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _int_flag(name: str, default: int, minimum: int = 0) -> int:
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return max(minimum, int(v.strip()))
    except ValueError:
        return default


def _float_flag(name: str, default: float, minimum: float = 0.0) -> float:
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return max(minimum, float(v.strip()))
    except ValueError:
        return default


@dataclass
class Config:
    trace: bool = False          # profiler annotations around ops
    pallas: str = "auto"         # "auto" | "on" | "off"
    log_level: str = "WARNING"
    leak_debug: bool = False     # bridge handle-leak tracking verbosity
    fuse: bool = True            # engine whole-stage segment fusion
    prefetch: int = 1            # chunked-scan pipeline depth (0 = serial)
    plan_cache: int = 128        # PlanCache capacity (entries)
    segment_cache: int = 256     # compiled-segment cache capacity (entries)
    fuse_join: bool = True       # probe-join fusion on the streamed path
    topk: bool = True            # streaming top-k execution of TopK plans
    build_cache: int = 32        # prepared-build cache capacity (entries)
    metrics: bool = True         # query-scoped metrics (utils/metrics.py)
    timeline: bool = False       # trace-event timeline (utils/timeline.py)
    timeline_cap: int = 16384    # timeline ring-buffer capacity (events)
    log_format: str = "text"     # "text" | "json" (structured log lines)
    verify: bool = True          # static plan verification (engine/verify.py)
    distribute: bool = False     # Exchange-placement distributed planning
    broadcast_rows: int = 100_000  # broadcast-join build-size threshold (rows)
    aqe: bool = False            # adaptive execution (engine/adaptive.py)
    fuse_exchange: bool = False  # in-program exchange (fused dist stage)
    fuse_groups: int = 4096      # fused stage's static per-shard group cap
    aqe_skew: float = 4.0        # skew threshold for the hot-key split
    aqe_broadcast_rows: int = -1  # runtime flip threshold (-1 = follow
    #                               broadcast_rows)
    profile_dir: str = ""        # query-profile store dir (empty = off)
    profile_cap: int = 512       # profile-store ring capacity (files)
    faults: str = ""             # fault-injection spec (utils/faults.py)
    retry_max: int = 3           # transient-failure retry bound per site
    retry_backoff_s: float = 0.01  # base retry backoff (doubles/attempt)
    query_timeout_s: float = 0.0   # cooperative query deadline (0 = none)
    bridge_timeout_s: float = 60.0  # bridge per-op socket deadline (0=off)
    mem_debug: bool = False      # live-buffer census + MemoryScope reports
    blackbox: bool = True        # flight recorder ring (utils/blackbox.py)
    blackbox_dir: str = ""       # post-mortem bundle dir (empty = no disk)
    blackbox_cap: int = 512      # flight-recorder ring capacity (events)
    slo_ms: str = ""             # latency objectives spec (default[,fp=ms])
    trace_id: str = ""           # inherited trace context (subprocesses)
    roofline_gbps: float = 0.0   # explain-analyze ceiling override (0=pin)
    jax_platforms: str = ""      # jax platform list ("" = jax's default)
    sched: bool = True           # multi-tenant scheduler (engine/scheduler)
    max_sessions: int = 8        # concurrent admitted PLAN_EXECUTE sessions
    admission_queue_s: float = 5.0  # admission-queue wait bound (seconds)
    admission_burn: float = 0.9  # burn rate that sheds when saturated
    session_budget_bytes: int = 0  # per-session memory budget (0=unlimited)
    result_cache: int = 0        # result-set cache capacity (0 = off)
    device_decode: bool = False  # device-side parquet page decode

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            trace=_bool_flag("SRJT_TRACE", False),
            pallas=os.environ.get("SRJT_PALLAS", "auto").strip().lower(),
            log_level=os.environ.get("SRJT_LOG_LEVEL", "WARNING").upper(),
            leak_debug=_bool_flag("SRJT_LEAK_DEBUG", False),
            fuse=_bool_flag("SRJT_FUSE", True),
            prefetch=_int_flag("SRJT_PREFETCH", 1),
            plan_cache=_int_flag("SRJT_PLAN_CACHE", 128, minimum=1),
            segment_cache=_int_flag("SRJT_SEGMENT_CACHE", 256, minimum=1),
            fuse_join=_bool_flag("SRJT_FUSE_JOIN", True),
            topk=_bool_flag("SRJT_TOPK", True),
            build_cache=_int_flag("SRJT_BUILD_CACHE", 32, minimum=1),
            metrics=_bool_flag("SRJT_METRICS", True),
            timeline=_bool_flag("SRJT_TIMELINE", False),
            timeline_cap=_int_flag("SRJT_TIMELINE_CAP", 16384, minimum=16),
            log_format=os.environ.get("SRJT_LOG_FORMAT",
                                      "text").strip().lower(),
            verify=_bool_flag("SRJT_VERIFY", True),
            distribute=_bool_flag("SRJT_DIST", False),
            broadcast_rows=_int_flag("SRJT_BROADCAST_ROWS", 100_000),
            aqe=_bool_flag("SRJT_AQE", False),
            fuse_exchange=_bool_flag("SRJT_FUSE_EXCHANGE", False),
            fuse_groups=_int_flag("SRJT_FUSE_GROUPS", 4096, minimum=1),
            aqe_skew=_float_flag("SRJT_AQE_SKEW", 4.0, minimum=1.0),
            aqe_broadcast_rows=_int_flag("SRJT_AQE_BROADCAST_ROWS", -1,
                                         minimum=-1),
            profile_dir=os.environ.get("SRJT_PROFILE_DIR", "").strip(),
            profile_cap=_int_flag("SRJT_PROFILE_CAP", 512, minimum=1),
            faults=os.environ.get("SRJT_FAULTS", "").strip(),
            retry_max=_int_flag("SRJT_RETRY_MAX", 3),
            retry_backoff_s=_float_flag("SRJT_RETRY_BACKOFF_S", 0.01),
            query_timeout_s=_float_flag("SRJT_QUERY_TIMEOUT_S", 0.0),
            bridge_timeout_s=_float_flag("SRJT_BRIDGE_TIMEOUT_S", 60.0),
            mem_debug=_bool_flag("SRJT_MEM_DEBUG", False),
            blackbox=_bool_flag("SRJT_BLACKBOX", True),
            blackbox_dir=os.environ.get("SRJT_BLACKBOX_DIR", "").strip(),
            blackbox_cap=_int_flag("SRJT_BLACKBOX_CAP", 512, minimum=16),
            slo_ms=os.environ.get("SRJT_SLO_MS", "").strip(),
            trace_id=os.environ.get("SRJT_TRACE_ID", "").strip(),
            roofline_gbps=_float_flag("SRJT_ROOFLINE_GBPS", 0.0),
            jax_platforms=os.environ.get("JAX_PLATFORMS", "").strip(),
            sched=_bool_flag("SRJT_SCHED", True),
            max_sessions=_int_flag("SRJT_MAX_SESSIONS", 8, minimum=1),
            admission_queue_s=_float_flag("SRJT_ADMISSION_QUEUE_S", 5.0),
            admission_burn=_float_flag("SRJT_ADMISSION_BURN", 0.9),
            session_budget_bytes=_int_flag("SRJT_SESSION_BUDGET_BYTES", 0),
            result_cache=_int_flag("SRJT_RESULT_CACHE", 0),
            device_decode=_bool_flag("SRJT_DEVICE_DECODE", False),
        )


config = Config.from_env()


def child_environ(default_platform: str = "cpu") -> dict:
    """Environment for a spawned helper process.

    A copy of ours with the package importable regardless of the child's
    cwd (PYTHONPATH) and the jax platform defaulted — a second process
    contending for a one-tenant TPU tunnel hangs at backend init, so
    children land on CPU unless the caller overrides.  Lives here so
    ``os.environ`` stays confined to this module (the config-env-read
    lint); callers layer their own overrides on the returned dict.
    """
    e = dict(os.environ)
    e.setdefault("JAX_PLATFORMS", default_platform)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    e["PYTHONPATH"] = pkg_root + os.pathsep + e.get("PYTHONPATH", "")
    return e


def refresh() -> Config:
    """Re-read flags from the environment (returns the live singleton).

    Copies every dataclass field, so a flag added to ``Config`` is
    refresh-visible automatically instead of needing a hand-maintained
    assignment here (where ``SRJT_METRICS`` would have been dropped).
    """
    new = Config.from_env()
    for f in fields(Config):
        setattr(config, f.name, getattr(new, f.name))
    logger()  # re-applies the (possibly changed) level
    return config


class _JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts/level/logger/msg plus the active query
    name from the metrics layer when one is bound on the emitting thread —
    bridge-server log lines correlate with per-query summaries by name."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {"ts": round(record.created, 6),
               "level": record.levelname,
               "logger": record.name,
               "msg": record.getMessage()}
        try:
            from . import metrics
            q = metrics.current()
            if q is not None:
                doc["query"] = q.name
        except Exception:
            pass
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def logger() -> logging.Logger:
    """The package logger (analog of the reference's slf4j-api single dep).

    A ``NullHandler`` keeps library log records from falling through to
    lastResort when the host app never configured logging, and the level
    is applied on EVERY call — a host app that configures root logging
    before importing us must not freeze our level at the import-time
    default.

    ``SRJT_LOG_FORMAT=json`` attaches a stderr handler with
    ``_JsonLogFormatter`` (and stops propagation so lines emit exactly
    once); switching back to ``text`` detaches it and restores the
    host-app-owned path.
    """
    log = logging.getLogger("spark_rapids_jni_tpu")
    if not any(isinstance(h, logging.NullHandler) for h in log.handlers):
        log.addHandler(logging.NullHandler())
    json_handlers = [h for h in log.handlers
                     if getattr(h, "_srjt_json", False)]
    if config.log_format == "json":
        if not json_handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(_JsonLogFormatter())
            h._srjt_json = True
            log.addHandler(h)
        log.propagate = False
    else:
        for h in json_handlers:
            log.removeHandler(h)
        log.propagate = True
    log.setLevel(config.log_level)
    return log
