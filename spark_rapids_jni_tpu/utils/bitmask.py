"""Validity bitmask packing utilities.

The engine stores validity as a ``bool[n]`` jax.Array (compute-friendly on the VPU);
these helpers convert to/from the cudf wire format — 1 bit per row, LSB-first within
32-bit words (reference row_conversion.cu:158-165 writes whole 32-bit validity words
per warp ballot; :255-272 packs bits with aligned atomics).  Packing only happens at
wire/host boundaries (row blobs, IPC bridge), never in the hot compute path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def pack_bits(valid: jnp.ndarray, word_bits: int = 32) -> jnp.ndarray:
    """Pack bool[n] -> uint{word_bits}[ceil(n/word_bits)], LSB-first.

    Rows beyond n are padded with 0 (invalid), matching cudf's convention that
    trailing mask bits are undefined-but-zeroed in fresh allocations.
    """
    if word_bits not in (8, 32):
        raise ValueError(f"word_bits must be 8 or 32, got {word_bits}")
    n = valid.shape[0]
    nwords = (n + word_bits - 1) // word_bits
    padded = jnp.zeros((nwords * word_bits,), jnp.bool_).at[:n].set(valid)
    bits = padded.reshape(nwords, word_bits).astype(jnp.uint32)
    shifts = jnp.arange(word_bits, dtype=jnp.uint32)
    words = jnp.sum(bits << shifts, axis=1, dtype=jnp.uint32)
    if word_bits == 8:
        return words.astype(jnp.uint8)
    return words


def unpack_bits(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """Unpack LSB-first packed words -> bool[n]."""
    word_bits = words.dtype.itemsize * 8
    shifts = jnp.arange(word_bits, dtype=words.dtype)
    bits = (words[:, None] >> shifts[None, :]) & words.dtype.type(1)
    return bits.reshape(-1)[:n].astype(jnp.bool_)


def pack_bits_np(valid: np.ndarray, word_bits: int = 32) -> np.ndarray:
    """Host-side (numpy) packing, same layout as :func:`pack_bits`."""
    if word_bits not in (8, 32):
        raise ValueError(f"word_bits must be 8 or 32, got {word_bits}")
    n = valid.shape[0]
    nwords = (n + word_bits - 1) // word_bits
    padded = np.zeros((nwords * word_bits,), np.bool_)
    padded[:n] = valid
    le_bytes = np.packbits(padded, bitorder="little")
    dt = {8: np.uint8, 32: np.uint32}[word_bits]
    return le_bytes.view(dt) if word_bits == 8 else le_bytes.view("<u4")


def unpack_bits_np(words: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(words.view(np.uint8), bitorder="little")[:n].astype(np.bool_)
