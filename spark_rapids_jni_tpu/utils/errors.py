"""Error taxonomy, typed failures, and cooperative cancellation.

The serving regime (ROADMAP item 3) needs every failure classified before
anything can decide what to do with it: retry, degrade, or surface.  This
module is the dependency-free bottom layer both sides of the bridge share —
``engine/recovery.py`` builds retry/degradation policy on top, and the
bridge carries ``to_wire()`` documents in ``_error_body`` the way
``plan_verification`` already travels.

Taxonomy (one ``kind`` per exception + a retryable bit):

- ``transient``  — I/O hiccups, timeouts on a single op; same operation may
  succeed if repeated (retryable).
- ``resource``   — allocation failure (device ``RESOURCE_EXHAUSTED``, host
  OOM); repeating at the same footprint fails the same way, so NOT blind-
  retryable — the executor degrades capacity instead (engine/recovery.py).
- ``cancelled``  — cooperative cancellation or deadline expiry; never
  retried, never degraded.
- ``fatal``      — everything else (bugs, bad plans, corrupt data).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Optional, Tuple

KIND_TRANSIENT = "transient"
KIND_RESOURCE = "resource"
KIND_CANCELLED = "cancelled"
KIND_FATAL = "fatal"

KINDS = (KIND_TRANSIENT, KIND_RESOURCE, KIND_CANCELLED, KIND_FATAL)


class EngineError(RuntimeError):
    """Base of the typed engine failures; subclasses pin kind/retryable."""

    kind = KIND_FATAL
    retryable = False


class TransientError(EngineError):
    kind = KIND_TRANSIENT
    retryable = True


class ResourceExhaustedError(EngineError):
    kind = KIND_RESOURCE
    retryable = False  # blind retry repeats the allocation; degrade instead


class AdmissionRejectedError(EngineError):
    """The scheduler shed this query at admission (engine/scheduler.py).

    ``resource`` kind — the server is saturated, not broken — but NOT
    retryable by the blind in-op retry loop: re-submitting immediately
    would re-enter the same overloaded admission queue.  Clients decide
    when (and whether) to come back; the wire doc carries trace_id and
    the shed bundle pointer like every other typed failure."""

    kind = KIND_RESOURCE
    retryable = False


class QueryCancelledError(EngineError):
    kind = KIND_CANCELLED
    retryable = False


class QueryTimeoutError(QueryCancelledError):
    """Deadline expiry — a cancellation the clock requested."""


class BridgeTimeoutError(TransientError, TimeoutError):
    """A bridge socket op exceeded its deadline (SRJT_BRIDGE_TIMEOUT_S)."""


#: substrings that mark a runtime allocation failure (jax raises
#: XlaRuntimeError with a RESOURCE_EXHAUSTED status; host numpy raises
#: MemoryError directly)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def classify(exc: BaseException) -> Tuple[str, bool]:
    """``(kind, retryable)`` for any exception.

    Typed ``EngineError``s carry their own class attributes; foreign
    exceptions map by type and message: allocation failures are
    ``resource``, I/O and socket errors ``transient``, the rest ``fatal``.
    """
    if isinstance(exc, EngineError):
        return exc.kind, exc.retryable
    if isinstance(exc, MemoryError):
        return KIND_RESOURCE, False
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return KIND_RESOURCE, False
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return KIND_TRANSIENT, True
    if isinstance(exc, OSError):
        return KIND_TRANSIENT, True
    return KIND_FATAL, False


def is_resource_exhausted(exc: BaseException) -> bool:
    return classify(exc)[0] == KIND_RESOURCE


def is_cancellation(exc: BaseException) -> bool:
    return classify(exc)[0] == KIND_CANCELLED


# -- wire format (bridge _error_body / client re-raise) ----------------------

_WIRE_TYPES = {
    "TransientError": TransientError,
    "ResourceExhaustedError": ResourceExhaustedError,
    "AdmissionRejectedError": AdmissionRejectedError,
    "QueryCancelledError": QueryCancelledError,
    "QueryTimeoutError": QueryTimeoutError,
    "BridgeTimeoutError": BridgeTimeoutError,
}

_KIND_FALLBACK = {
    KIND_TRANSIENT: TransientError,
    KIND_RESOURCE: ResourceExhaustedError,
    KIND_CANCELLED: QueryCancelledError,
}


def to_wire(exc: BaseException) -> dict:
    """Structured error document (bridge ``_error_body`` payload).

    Carries the trace context when the exception has one (stamped by
    ``utils.blackbox.post_mortem`` on the way out of the executor) so a
    round-tripped error keeps its join key."""
    kind, retryable = classify(exc)
    doc = {"error": "taxonomy", "kind": kind, "retryable": retryable,
           "type": type(exc).__name__, "msg": str(exc)}
    tid = getattr(exc, "trace_id", "")
    if tid:
        doc["trace_id"] = tid
    bundle = getattr(exc, "bundle_path", "")
    if bundle:
        doc["bundle"] = bundle
    return doc


def from_wire(doc: dict) -> Exception:
    """Reconstruct a typed exception from a ``to_wire`` document.

    Known engine types rebuild exactly; anything else lands on the
    kind-matched ``EngineError`` subclass (or a plain ``RuntimeError``
    for ``fatal``) with the original type name preserved in the message.
    The trace context rides along: ``e.trace_id`` joins the failure to
    the server's spans/profile entry, ``e.bundle_path`` points at its
    post-mortem bundle (utils/blackbox.py) when one was written.
    """
    kind = doc.get("kind", KIND_FATAL)
    tname = doc.get("type", "")
    msg = doc.get("msg", "")
    cls = _WIRE_TYPES.get(tname)
    if cls is not None:
        exc: Exception = cls(msg)
    else:
        text = f"{tname}: {msg}" if tname else msg
        fb = _KIND_FALLBACK.get(kind)
        exc = fb(text) if fb is not None \
            else RuntimeError(f"bridge error: {text}")
    tid = doc.get("trace_id", "")
    if tid:
        exc.trace_id = tid
    bundle = doc.get("bundle", "")
    if bundle:
        exc.bundle_path = bundle
    return exc


# -- cooperative cancellation ------------------------------------------------

class CancelToken:
    """Cancellation flag + optional monotonic deadline, checked at chunk
    boundaries (executor streaming loops, exchange chunk loop, prefetch
    producer).  Cooperative: nothing is interrupted mid-dispatch — the next
    boundary raises, and the existing ``close()`` machinery releases reader
    threads and device buffers on the way out."""

    __slots__ = ("_event", "_deadline", "_reason")

    def __init__(self, timeout_s: Optional[float] = None):
        self._event = threading.Event()
        self._deadline = (time.monotonic() + timeout_s
                          if timeout_s and timeout_s > 0 else None)
        self._reason = ""

    def cancel(self, reason: str = "cancelled") -> None:
        self._reason = reason
        self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    @property
    def expired(self) -> bool:
        return self._deadline is not None \
            and time.monotonic() > self._deadline

    def should_stop(self) -> bool:
        """Non-raising poll (producer threads break their loop on this)."""
        return self.cancelled or self.expired

    def remaining_s(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def check(self) -> None:
        """Raise the typed cancellation if the token has tripped."""
        if self.cancelled:
            raise QueryCancelledError(
                f"query cancelled: {self._reason or 'cancelled'}")
        if self.expired:
            raise QueryTimeoutError(
                "query deadline exceeded (SRJT_QUERY_TIMEOUT_S)")


# -- bounded retry -----------------------------------------------------------

def retry_call(fn: Callable, site: str,
               retry_max: Optional[int] = None,
               backoff_s: Optional[float] = None,
               cancel: Optional[CancelToken] = None):
    """Run ``fn`` with bounded exponential backoff on *retryable* failures.

    Only exceptions classifying retryable (transient I/O) are retried —
    resource exhaustion propagates to the degradation ladder, cancellation
    propagates immediately.  Backoff doubles per attempt from
    ``SRJT_RETRY_BACKOFF_S`` with deterministic ±25% jitter derived from the
    attempt index (no RNG state: reproducible under SRJT_FAULTS).  Each
    retry ticks ``engine.retries`` and ``engine.retries.<site>``.
    """
    from . import metrics
    from .config import config, logger
    limit = config.retry_max if retry_max is None else int(retry_max)
    base = config.retry_backoff_s if backoff_s is None else float(backoff_s)
    attempt = 0
    while True:
        if cancel is not None:
            cancel.check()
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            kind, retryable = classify(e)
            if not retryable or attempt >= limit:
                raise
            attempt += 1
            metrics.count("engine.retries")
            metrics.count(f"engine.retries.{site}")
            from . import blackbox
            blackbox.record("retry", site=site, attempt=attempt, kind=kind)
            # deterministic jitter in [-25%, +25%]: crc32 of site:attempt —
            # stable across processes, unlike hash() under PYTHONHASHSEED
            j = (zlib.crc32(f"{site}:{attempt}".encode()) % 1000) / 1000.0
            delay = base * (2.0 ** (attempt - 1)) * (0.75 + 0.5 * j)
            if cancel is not None and cancel.remaining_s() is not None:
                delay = min(delay, cancel.remaining_s())
            logger().warning(
                "retry %d/%d at %s after %s: %s (backoff %.3fs)",
                attempt, limit, site, kind, e, delay)
            if delay > 0:
                time.sleep(delay)
