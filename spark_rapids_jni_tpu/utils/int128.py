"""Unsigned 128-bit limb arithmetic as elementwise XLA integer programs.

The device backbone of DECIMAL128 casts (the cudf fixed_point<__int128>
role): values travel as (lo, hi) uint64 pairs, and every operation stays
in 64-bit lanes — multiplication and division work over 32-bit limbs so no
intermediate exceeds uint64 (TPU has no 128-bit, and no 64-bit bitcasts;
see utils/floatbits.py for the same constraint on floats).

All helpers are magnitude (unsigned) ops; callers split sign via
``split_sign``/``apply_sign`` (two's-complement negate with carry).
"""

from __future__ import annotations

import jax.numpy as jnp

_U64 = jnp.uint64
_M32 = _U64(0xFFFFFFFF)


def split_sign(lo_i64, hi_i64):
    """int128 limb pairs -> (|x| lo, |x| hi, negative mask)."""
    lo = lo_i64.astype(jnp.uint64)
    hi = hi_i64.astype(jnp.uint64)
    neg = hi_i64 < 0
    nlo = (~lo) + _U64(1)
    nhi = (~hi) + jnp.where(nlo == 0, _U64(1), _U64(0))
    return jnp.where(neg, nlo, lo), jnp.where(neg, nhi, hi), neg


def apply_sign(lo, hi, neg):
    """(magnitude, neg) -> signed int64 limb pairs (two's complement)."""
    nlo = (~lo) + _U64(1)
    nhi = (~hi) + jnp.where(nlo == 0, _U64(1), _U64(0))
    slo = jnp.where(neg, nlo, lo)
    shi = jnp.where(neg, nhi, hi)
    return slo.astype(jnp.int64), shi.astype(jnp.int64)


def mul_small(lo, hi, c: int):
    """(lo, hi) * c for 0 < c <= 2^30; returns (lo, hi, overflow)."""
    assert 0 < c <= 1 << 30
    cc = _U64(c)
    limbs = [lo & _M32, lo >> _U64(32), hi & _M32, hi >> _U64(32)]
    out = []
    carry = jnp.zeros(lo.shape, _U64)
    for d in limbs:
        t = d * cc + carry          # < 2^32 * 2^30 + 2^62 < 2^63
        out.append(t & _M32)
        carry = t >> _U64(32)
    nlo = out[0] | (out[1] << _U64(32))
    nhi = out[2] | (out[3] << _U64(32))
    return nlo, nhi, carry != 0


def divmod_small(lo, hi, c: int):
    """(lo, hi) // c and remainder, for 0 < c <= 2^30."""
    assert 0 < c <= 1 << 30
    cc = _U64(c)
    limbs = [hi >> _U64(32), hi & _M32, lo >> _U64(32), lo & _M32]
    q = []
    r = jnp.zeros(lo.shape, _U64)
    for d in limbs:                  # r < c <= 2^30, so cur < 2^62
        cur = (r << _U64(32)) | d
        q.append(cur // cc)
        r = cur % cc
    qhi = (q[0] << _U64(32)) | q[1]
    qlo = (q[2] << _U64(32)) | q[3]
    return qlo, qhi, r


def mul_pow10(lo, hi, k: int):
    """(lo, hi) * 10^k (k >= 0 static); returns (lo, hi, overflow)."""
    ovf = jnp.zeros(lo.shape, jnp.bool_)
    while k > 0:
        step = min(k, 9)
        lo, hi, o = mul_small(lo, hi, 10 ** step)
        ovf = ovf | o
        k -= step
    return lo, hi, ovf


def div_pow10(lo, hi, k: int, half_up: bool):
    """(lo, hi) // 10^k (k > 0 static), truncating or HALF_UP (away from
    zero on the magnitude); returns (lo, hi, exact)."""
    exact = jnp.ones(lo.shape, jnp.bool_)
    kk = k - 1 if half_up else k
    while kk > 0:
        step = min(kk, 9)
        lo, hi, r = divmod_small(lo, hi, 10 ** step)
        exact = exact & (r == 0)
        kk -= step
    if half_up:
        lo, hi, d = divmod_small(lo, hi, 10)
        exact = exact & (d == 0)
        bump = d >= 5
        nlo = lo + jnp.where(bump, _U64(1), _U64(0))
        hi = hi + jnp.where(bump & (nlo == 0), _U64(1), _U64(0))
        lo = nlo
    return lo, hi, exact


def fits_bits(lo, hi, bits: int):
    """Magnitude < 2^bits (bits in (0, 128])."""
    if bits >= 128:
        return jnp.ones(lo.shape, jnp.bool_)
    if bits > 64:
        return hi < (_U64(1) << _U64(bits - 64))
    if bits == 64:
        return hi == 0
    return (hi == 0) & (lo < (_U64(1) << _U64(bits)))


def le_u64(lo, hi, bound: int):
    """Magnitude <= bound (bound < 2^64)."""
    return (hi == 0) & (lo <= _U64(bound))


def to_f64(lo, hi):
    """Magnitude as float64 (rounded — 128 bits exceed the mantissa)."""
    return hi.astype(jnp.float64) * jnp.float64(2.0**64) + \
        lo.astype(jnp.float64)


def from_u64(mag_u64):
    """uint64 magnitude -> (lo, hi)."""
    return mag_u64, jnp.zeros(mag_u64.shape, _U64)


def mul_pow10_dyn(lo, hi, k, kmax: int):
    """(lo, hi) * 10^k with PER-ROW k in [0, kmax] (static bound):
    kmax masked multiply-by-ten steps; returns (lo, hi, overflow)."""
    ovf = jnp.zeros(lo.shape, jnp.bool_)
    for t in range(kmax):
        nlo, nhi, o = mul_small(lo, hi, 10)
        act = t < k
        lo = jnp.where(act, nlo, lo)
        hi = jnp.where(act, nhi, hi)
        ovf = ovf | (act & o)
    return lo, hi, ovf


def div_pow10_dyn(lo, hi, k, kmax: int, half_up: bool):
    """(lo, hi) // 10^k with PER-ROW k in [0, kmax]; HALF_UP uses the most
    significant dropped digit (the remainder of the final step)."""
    last = jnp.zeros(lo.shape, jnp.uint64)
    for t in range(kmax):
        nlo, nhi, r = divmod_small(lo, hi, 10)
        act = t < k
        last = jnp.where(act, r, last)
        lo = jnp.where(act, nlo, lo)
        hi = jnp.where(act, nhi, hi)
    if half_up:
        bump = (last >= 5) & (k > 0)
        nlo = lo + jnp.where(bump, _U64(1), _U64(0))
        hi = hi + jnp.where(bump & (nlo == 0), _U64(1), _U64(0))
        lo = nlo
    return lo, hi
