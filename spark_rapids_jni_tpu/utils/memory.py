"""Device-memory observability and budgets (the RMM role).

The reference threads an ``rmm::mr::device_memory_resource*`` through
every op (reference src/main/cpp/src/row_conversion.hpp:30-36) so callers
control and observe allocation.  Under XLA the allocator belongs to the
runtime, so the TPU-native analog is split the way the rest of the design
splits host/device responsibilities:

- *control* lives in the size-bounded entry points that already exist
  (``convert_to_rows`` max_batch_bytes, ``ParquetChunkedReader``
  pass_read_limit, shuffle capacities) — the working set is bounded by
  construction, not by a custom allocator;
- *observability* lives here: a live-buffer census over ``jax.live_arrays``
  plus scoped high-water tracking, and an optional budget guard that turns
  "the working set grew past X" into an exception at the checkpoints the
  engine already passes through.

Env: ``SRJT_MEM_DEBUG=1`` logs every scope's high-water mark to stderr
(the RMM_LOGGING_LEVEL analog, reference pom.xml:81).
"""

from __future__ import annotations

import sys
from contextlib import contextmanager
from dataclasses import dataclass

import jax

from .config import config


def _array_nbytes(a) -> int:
    try:
        return a.nbytes
    except Exception:
        return 0


def device_memory_stats(platform: str | None = None) -> dict:
    """Census of live device buffers: {live_bytes, live_arrays}.

    ``platform`` filters to one backend (e.g. "tpu"); default counts every
    live jax.Array in the process."""
    total = 0
    count = 0
    for a in jax.live_arrays(platform):
        total += _array_nbytes(a)
        count += 1
    return {"live_bytes": total, "live_arrays": count}


def runtime_memory_stats(platform: str | None = None) -> dict | None:
    """Allocator-level stats from ``Device.memory_stats()`` where the
    backend exposes them (TPU/GPU runtimes do, CPU returns None):
    {bytes_in_use, peak_bytes_in_use} summed across local devices.
    Returns None when no device reports — callers fall back to the
    live-array census (the byte-accounting path)."""
    try:
        devices = jax.local_devices(backend=platform) if platform \
            else jax.local_devices()
    except Exception:
        return None
    in_use = peak = 0
    seen = False
    for d in devices:
        ms = getattr(d, "memory_stats", None)
        try:
            stats = ms() if callable(ms) else None
        except Exception:
            stats = None
        if not stats:
            continue
        seen = True
        b = int(stats.get("bytes_in_use", 0))
        in_use += b
        peak += int(stats.get("peak_bytes_in_use", b))
    if not seen:
        return None
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak}


def telemetry_snapshot(platform: str | None = None) -> dict:
    """The per-query device-memory sample the metrics layer records:
    runtime allocator stats when available (``source: "runtime"``),
    otherwise the live-array byte census (``source: "census"``).
    Always carries ``live_bytes``; ``peak_bytes`` only on the runtime
    path (the census has no allocator high-water to read)."""
    rt = runtime_memory_stats(platform)
    if rt is not None:
        return {"source": "runtime",
                "live_bytes": rt["bytes_in_use"],
                "peak_bytes": rt["peak_bytes_in_use"]}
    c = device_memory_stats(platform)
    return {"source": "census",
            "live_bytes": c["live_bytes"],
            "live_arrays": c["live_arrays"],
            "peak_bytes": None}


def column_nbytes(col) -> int:
    """Buffer bytes of one column (data + validity + offsets + children).

    Pure metadata reads (``.nbytes`` on device or host arrays) — never
    forces a transfer or sync, so the executor can account bytes per node
    on the streaming paths for free."""
    total = 0
    for buf in (col.data, col.validity, col.offsets):
        if buf is not None:
            total += _array_nbytes(buf)
    for child in col.children:
        total += column_nbytes(child)
    return total


def table_nbytes(table) -> int:
    """Buffer bytes of a Table — the ``bytes_moved`` unit the roofline
    attribution in ``engine.explain_analyze`` divides by wall time."""
    return sum(column_nbytes(c) for c in table.columns)


@dataclass
class ScopeStats:
    name: str
    start_bytes: int = 0
    high_water_bytes: int = 0
    end_bytes: int = 0

    @property
    def delta_bytes(self) -> int:
        return self.end_bytes - self.start_bytes


class BudgetExceeded(RuntimeError):
    """Working set grew past the scope's budget at a checkpoint."""


class MemoryScope:
    """Scoped live-byte tracking with optional budget enforcement.

    The engine's long-running paths call ``checkpoint()`` at their natural
    batch boundaries (the places the reference would consult its memory
    resource); a checkpoint refreshes the high-water mark and raises
    ``BudgetExceeded`` when a budget is set and breached.
    """

    def __init__(self, name: str = "scope", budget_bytes: int | None = None,
                 platform: str | None = None):
        self.stats = ScopeStats(name)
        self.budget = budget_bytes
        self.platform = platform

    def __enter__(self) -> "MemoryScope":
        self.stats.start_bytes = device_memory_stats(
            self.platform)["live_bytes"]
        self.stats.high_water_bytes = self.stats.start_bytes
        return self

    def checkpoint(self) -> int:
        live = device_memory_stats(self.platform)["live_bytes"]
        if live > self.stats.high_water_bytes:
            self.stats.high_water_bytes = live
        if self.budget is not None and live > self.budget:
            raise BudgetExceeded(
                f"{self.stats.name}: live device bytes {live} exceed "
                f"budget {self.budget}")
        return live

    def __exit__(self, *exc):
        self.stats.end_bytes = device_memory_stats(
            self.platform)["live_bytes"]
        if self.stats.end_bytes > self.stats.high_water_bytes:
            self.stats.high_water_bytes = self.stats.end_bytes
        if config.mem_debug:
            s = self.stats
            print(f"[mem] {s.name}: start={s.start_bytes} "
                  f"high={s.high_water_bytes} end={s.end_bytes} "
                  f"delta={s.delta_bytes}", file=sys.stderr, flush=True)
        return False


@contextmanager
def track(name: str = "scope", budget_bytes: int | None = None):
    """``with memory.track("join") as scope: ...`` — scoped census."""
    with MemoryScope(name, budget_bytes) as scope:
        yield scope
