"""Persistent query-profile store: one compact JSON per query, on disk.

The Flare / Presto-on-GPUs observation (PAPERS.md) is that per-stage
profiles only pay off when they survive the process: regression hunting,
plan-choice feedback, and multi-tenant accounting all compare *runs*, not
live counters.  This module is that persistence layer — ``metrics.query()``
calls ``write(summary)`` on exit when ``SRJT_PROFILE_DIR`` is set, storing
a compact derivative of the query summary (plan fingerprint, per-node
wall/rows/bytes/GB/s/roofline_frac, exchange skew + straggler share, cache
and host-sync counters, histogram percentiles) into a bounded on-disk ring.

Layout: ``<dir>/profile-<epoch_ns>-<fp12>.json`` — zero-padded nanosecond
timestamp first, so lexical filename order IS chronological order, and the
first 12 hex chars of the plan fingerprint second, so same-plan runs are
greppable.  The ring is bounded by ``SRJT_PROFILE_CAP`` (oldest pruned).

Consumers:

- ``tools/srjt_profile.py`` — list/show/diff CLI; ``diff`` renders
  per-node deltas between two runs of the same fingerprint and flags
  regression attribution (node slowed, cache stopped hitting, exchange
  skewed, latency tail grew).
- ``ci/bench_gate.py --profiles DIR`` — gates on profile-derived keys
  (``profile.exchange.skew``, ``profile.chunk_latency.p99``).
- The bridge's ``OP_METRICS`` reply embeds ``store_summary()``.

All writes are best-effort (the metrics layer swallows profile IO errors);
reads raise normally so tools see real failures.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from .config import config

#: schema version stamped into every profile (bump on breaking change)
VERSION = 1

#: histogram fields carried into the compact profile (percentiles are the
#: point; full bucket arrays stay in the live snapshot only)
_HIST_FIELDS = ("count", "sum", "mean", "min", "max", "p50", "p90", "p99")

#: counter prefixes worth keeping per profile — cache attribution, sync
#: counts, exchange/shuffle traffic, bridge health, recovery activity
_COUNTER_KEEP = ("engine.exchange", "parallel.shuffle", "bridge.",
                 "engine.errors", "engine.retries", "engine.degraded",
                 "engine.estimate", "faults.injected")

#: decision/node q-error at or above this is a misestimate — the planner's
#: input was off by >= 4x, enough to flip a broadcast-vs-shuffle choice
#: (same module-constant convention as the diff thresholds below)
_QERR_FLAG = 4.0


def enabled() -> bool:
    """Live SRJT_PROFILE_DIR gate (config singleton, refresh()-tunable)."""
    return bool(config.profile_dir)


def _keep_counter(name: str) -> bool:
    # "cache." catches every cache family (engine.build_cache.hit/miss,
    # engine.segment_cache.*) regardless of the separator before "cache"
    return ("cache." in name or name == "engine.host_sync"
            or name.startswith(_COUNTER_KEEP))


def _ceiling() -> Optional[float]:
    try:
        from ..engine.explain import roofline_ceiling_gbps
        return roofline_ceiling_gbps()
    except Exception:
        return None


def compact(summary: dict) -> dict:
    """Derive the compact profile document from a ``QueryMetrics.summary()``.

    Pure function of the summary (plus the pinned roofline ceiling) — the
    round-trip tests rely on every gated key surviving write -> read."""
    ceiling = _ceiling()
    nodes = []
    exchanges = []
    for r in summary.get("nodes", ()):
        wall = float(r.get("wall_s") or 0.0)
        moved = int(r.get("bytes_in") or 0) + int(r.get("bytes_out") or 0)
        gbps = (moved / wall / 1e9) if (moved and wall > 0) else None
        node = {"label": r.get("label", ""),
                "path": r.get("path"),
                "calls": int(r.get("calls") or 0),
                "wall_s": round(wall, 6),
                "rows_in": int(r.get("rows_in") or 0),
                "rows_out": int(r.get("rows_out") or 0),
                "chunks": int(r.get("chunks") or 0),
                "host_syncs": int(r.get("host_syncs") or 0),
                "est_rows": r.get("est_rows"),
                "q_error": r.get("q_error"),
                "bytes_moved": moved,
                "GBps": round(gbps, 3) if gbps is not None else None,
                "roofline_frac": (round(gbps / ceiling, 6)
                                  if gbps is not None and ceiling else None)}
        nodes.append(node)
        if r.get("wire_bytes") or r.get("skew") is not None:
            exchanges.append({
                "label": r.get("label", ""),
                "wire_bytes": int(r.get("wire_bytes") or 0),
                "skew": r.get("skew"),
                "straggler_share": r.get("straggler_share"),
                "max_dev_rows": r.get("max_dev_rows"),
                "dev_rows": list(r.get("dev_rows") or ()),
                # broadcast exchanges are structurally balanced (skew 1.0)
                # but pay ndev-1 replicas of the build — the AQE rules
                # read the replication cost from here
                "replica_bytes": r.get("replica_bytes")})
    prof = {"version": VERSION,
            "fingerprint": summary.get("fingerprint", ""),
            "source_fingerprint": summary.get("source_fingerprint", ""),
            "trace_id": summary.get("trace_id", ""),
            "qid": summary.get("qid"),
            "name": summary.get("name", ""),
            "wall_s": summary.get("wall_s"),
            "stats": dict(summary.get("stats") or {}),
            "nodes": nodes,
            "exchanges": exchanges,
            "counters": {k: v for k, v in
                         (summary.get("counters") or {}).items()
                         if _keep_counter(k)},
            "histograms": {k: {f: h.get(f) for f in _HIST_FIELDS}
                           for k, h in
                           (summary.get("histograms") or {}).items()}}
    if summary.get("memory"):
        prof["memory"] = dict(summary["memory"])
    # recovery attribution: how the query ended and what capacity it gave
    # up on the way (srjt_profile diff flags degradation regressions)
    if summary.get("outcome"):
        prof["outcome"] = dict(summary["outcome"])
    if summary.get("degradations"):
        prof["degradations"] = [dict(d) for d in summary["degradations"]]
    if summary.get("decisions"):
        by_path = {n["path"]: n for n in nodes if n.get("path")}
        prof["decisions"] = [_score_decision(d, by_path)
                             for d in summary["decisions"]]
    return prof


def _score_decision(d: dict, by_path: dict) -> dict:
    """Score one optimizer-ledger entry against the run's actuals: the
    node at the decision's path supplies ``actual_rows``; the entry's own
    ``est_rows`` supplies the estimate; q-error >= ``_QERR_FLAG`` marks a
    misestimate (the broadcast-chosen-on-est=50k-that-saw-5M case the
    diff flags and ``srjt_profile decisions`` browses)."""
    from . import metrics
    out = dict(d)
    node = by_path.get(d.get("path"))
    if node is not None:
        out["actual_rows"] = node.get("rows_out")
        qe = metrics.q_error(d.get("est_rows"), node.get("rows_out"))
        if qe is not None:
            out["q_error"] = qe
            out["misestimate"] = qe >= _QERR_FLAG
    return out


def write(summary: dict, dir_path: str | None = None) -> str | None:
    """Persist one profile for ``summary``; returns its path (None = off).

    Atomic (tmp + rename) so a concurrent reader never sees a torn JSON,
    then prunes the ring past ``SRJT_PROFILE_CAP``."""
    d = dir_path or config.profile_dir
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    prof = compact(summary)
    fp12 = (prof["fingerprint"] or "noplan")[:12]
    path = os.path.join(d, f"profile-{time.time_ns():020d}-{fp12}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(prof, f, separators=(",", ":"))
    os.replace(tmp, path)
    _prune(d)
    return path


def _prune(d: str) -> None:
    paths = list_profiles(d)
    for p in paths[:max(0, len(paths) - config.profile_cap)]:
        try:
            os.remove(p)
        except OSError:
            pass  # concurrent pruner got it first


def list_profiles(dir_path: str | None = None) -> list:
    """Profile paths in the store, oldest first (lexical = chronological)."""
    d = dir_path or config.profile_dir
    if not d or not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, n) for n in os.listdir(d)
                  if n.startswith("profile-") and n.endswith(".json"))


def read(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def latest(fingerprint: str | None = None,
           dir_path: str | None = None) -> dict | None:
    """Newest profile (optionally restricted to one plan fingerprint)."""
    for p in reversed(list_profiles(dir_path)):
        prof = read(p)
        if fingerprint is None or prof.get("fingerprint") == fingerprint:
            return prof
    return None


def history(source_fingerprint: str | None,
            dir_path: str | None = None) -> dict | None:
    """Measured history for one SOURCE plan fingerprint — the AQE
    profile-warming lookup (``optimize()`` consults this on every run
    when SRJT_AQE is on).

    Matches on the ``source_fingerprint`` stamped by the optimizer (the
    pre-rewrite plan), not the optimized fingerprint: warming changes the
    optimized shape, so only the source is stable across runs.  Returns
    the NEWEST matching run's scored decision ledger and exchange
    attribution plus how many stored runs matched, or None when the store
    holds no prior run (torn/unreadable profiles are skipped, exactly
    like the pruner's concurrent-reader tolerance).
    """
    if not source_fingerprint:
        return None
    runs = 0
    newest = None
    for p in list_profiles(dir_path):
        try:
            prof = read(p)
        except (OSError, ValueError):
            continue
        if prof.get("source_fingerprint") == source_fingerprint:
            runs += 1
            newest = prof  # list_profiles is oldest-first
    if newest is None:
        return None
    return {"source_fingerprint": source_fingerprint,
            "fingerprint": newest.get("fingerprint", ""),
            "runs": runs,
            "wall_s": newest.get("wall_s"),
            "decisions": list(newest.get("decisions") or ()),
            "exchanges": list(newest.get("exchanges") or ())}


def store_summary(dir_path: str | None = None) -> dict:
    """Aggregate view of the store — the bench smoke line / OP_METRICS
    block: profile count, worst exchange skew seen, and the latest
    chunk-latency p99 across stored profiles."""
    paths = list_profiles(dir_path)
    top_skew = None
    p99 = None
    for p in paths:
        try:
            prof = read(p)
        except (OSError, ValueError):
            continue
        for ex in prof.get("exchanges", ()):
            s = ex.get("skew")
            if s is not None and (top_skew is None or s > top_skew):
                top_skew = s
        h = prof.get("histograms", {}).get("engine.stream.chunk_latency_s")
        if h and h.get("p99") is not None:
            p99 = h["p99"]  # newest wins (paths are chronological)
    return {"dir": dir_path or config.profile_dir,
            "profiles": len(paths),
            "top_exchange_skew": top_skew,
            "chunk_latency_p99_s": p99}


# -- cross-run diff -----------------------------------------------------------

#: relative wall-time growth on a node that counts as "slowed"
_SLOW_FRAC = 0.25
#: absolute wall-time growth floor (ignore sub-ms jitter on tiny nodes)
_SLOW_ABS_S = 0.002
#: skew growth that counts as "exchange skewed"
_SKEW_DELTA = 0.25


def _by_label(rows) -> dict:
    out = {}
    for r in rows:
        # duplicate labels (shared subtrees) fold together: sum wall
        prev = out.get(r["label"])
        if prev is None:
            out[r["label"]] = dict(r)
        else:
            prev["wall_s"] = prev.get("wall_s", 0) + r.get("wall_s", 0)
    return out


def diff(base: dict | str, cand: dict | str) -> dict:
    """Per-node / per-counter / per-histogram deltas ``cand - base``.

    Accepts profile dicts or paths.  The ``flags`` list is the regression
    attribution: which node slowed, which cache stopped hitting, which
    exchange skewed, which latency tail grew."""
    a = read(base) if isinstance(base, str) else base
    b = read(cand) if isinstance(cand, str) else cand
    an, bn = _by_label(a.get("nodes", ())), _by_label(b.get("nodes", ()))
    nodes = []
    flags = []
    for label in sorted(set(an) | set(bn)):
        wa = (an.get(label) or {}).get("wall_s") or 0.0
        wb = (bn.get(label) or {}).get("wall_s") or 0.0
        d = {"label": label, "wall_s_base": wa, "wall_s_cand": wb,
             "wall_s_delta": round(wb - wa, 6),
             "q_error_base": (an.get(label) or {}).get("q_error"),
             "q_error_cand": (bn.get(label) or {}).get("q_error")}
        nodes.append(d)
        if wb - wa > _SLOW_ABS_S and (wa == 0 or wb / wa > 1 + _SLOW_FRAC):
            flags.append(f"node-slowed: {label} "
                         f"{wa * 1e3:.2f}ms -> {wb * 1e3:.2f}ms")
    counters = {}
    ac, bc = a.get("counters") or {}, b.get("counters") or {}
    for k in sorted(set(ac) | set(bc)):
        da = int(ac.get(k) or 0)
        db = int(bc.get(k) or 0)
        if da != db:
            counters[k] = {"base": da, "cand": db, "delta": db - da}
        if "cache." in k and (k.endswith(".hit") or k.endswith(".hits")):
            if db < da:
                flags.append(f"cache-hits-dropped: {k} {da} -> {db}")
    exchanges = []
    ae = _by_label(a.get("exchanges", ()))
    be = _by_label(b.get("exchanges", ()))
    for label in sorted(set(ae) | set(be)):
        sa = (ae.get(label) or {}).get("skew")
        sb = (be.get(label) or {}).get("skew")
        exchanges.append({"label": label, "skew_base": sa, "skew_cand": sb})
        if sa is not None and sb is not None and sb - sa > _SKEW_DELTA:
            flags.append(f"exchange-skew-up: {label} {sa:.2f} -> {sb:.2f}")
    hists = {}
    ah, bh = a.get("histograms") or {}, b.get("histograms") or {}
    for k in sorted(set(ah) | set(bh)):
        pa = (ah.get(k) or {}).get("p99")
        pb = (bh.get(k) or {}).get("p99")
        if pa is None and pb is None:
            continue
        hists[k] = {"p99_base": pa, "p99_cand": pb}
        if pa and pb and pb / pa > 1 + _SLOW_FRAC:
            flags.append(f"p99-up: {k} {pa:.6g} -> {pb:.6g}")
    # degradation attribution: a candidate run that gave up capacity
    # (interpreted fallback, halved/spilled/passthrough exchange) is a
    # regression even when its wall time looks fine
    base_steps = [d.get("step", "?") for d in a.get("degradations", ())]
    cand_steps = [d.get("step", "?") for d in b.get("degradations", ())]
    for step in cand_steps:
        if step not in base_steps:
            flags.append(f"degraded: {step}")
    ob, oc = a.get("outcome") or {}, b.get("outcome") or {}
    if oc.get("status") == "error" and ob.get("status") != "error":
        flags.append(f"outcome-error: kind={oc.get('kind', '?')}")
    # misestimate attribution: a candidate decision whose planner input was
    # off by >= _QERR_FLAG when the base run's wasn't means the cardinality
    # feed regressed (stats drifted, estimate path broke) — flag it even if
    # the plan happened to stay fast on this data
    base_mis = {(d.get("kind"), d.get("path"))
                for d in a.get("decisions", ()) if d.get("misestimate")}
    for d in b.get("decisions", ()):
        if d.get("misestimate") and \
                (d.get("kind"), d.get("path")) not in base_mis:
            flags.append(
                f"misestimate: {d.get('kind', '?')} at {d.get('path', '?')} "
                f"est={d.get('est_rows')} actual={d.get('actual_rows')} "
                f"q_error={d.get('q_error')}")
    return {"fingerprint": a.get("fingerprint", ""),
            "fingerprint_match":
                a.get("fingerprint", "") == b.get("fingerprint", ""),
            "base_name": a.get("name", ""), "cand_name": b.get("name", ""),
            "wall_s_base": a.get("wall_s"), "wall_s_cand": b.get("wall_s"),
            "nodes": nodes, "counters": counters,
            "exchanges": exchanges, "histograms": hists, "flags": flags}


def render_diff(d: dict) -> str:
    """Human-readable diff table (the ``srjt_profile diff`` output)."""
    lines = [f"profile diff: {d['base_name']} -> {d['cand_name']} "
             f"(fingerprint {'match' if d['fingerprint_match'] else 'MISMATCH'})",
             f"  wall: {d['wall_s_base']}s -> {d['wall_s_cand']}s"]
    for n in d["nodes"]:
        lines.append(f"  node {n['label']}: "
                     f"{n['wall_s_base'] * 1e3:.2f}ms -> "
                     f"{n['wall_s_cand'] * 1e3:.2f}ms "
                     f"({n['wall_s_delta'] * 1e3:+.2f}ms)")
    for e in d["exchanges"]:
        lines.append(f"  exchange {e['label']}: skew "
                     f"{e['skew_base']} -> {e['skew_cand']}")
    for k, v in d["counters"].items():
        lines.append(f"  counter {k}: {v['base']} -> {v['cand']} "
                     f"({v['delta']:+d})")
    for k, v in d["histograms"].items():
        lines.append(f"  hist {k}: p99 {v['p99_base']} -> {v['p99_cand']}")
    if d["flags"]:
        lines.append("  flags:")
        lines.extend(f"    ! {f}" for f in d["flags"])
    else:
        lines.append("  flags: none")
    return "\n".join(lines)
