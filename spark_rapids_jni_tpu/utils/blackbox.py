"""Always-on flight recorder, post-mortem bundles, and SLO evaluation.

The metrics/timeline/profile layers are opt-in and in-process: with
``SRJT_METRICS=0`` a crashed query leaves nothing behind, and nothing ties
a client's call to the server's spans.  This module is the serving-grade
floor under all of them (docs/OBSERVABILITY.md):

- **Flight recorder** — a bounded ring of recent coarse events (query
  begin/end, exchange, degradation rung, retry, host sync, error),
  recorded even with ``SRJT_METRICS=0``/``SRJT_TIMELINE=0``.  Gated only
  by ``SRJT_BLACKBOX`` (default on); capacity ``SRJT_BLACKBOX_CAP``.
  Every entry point is dict work under one lock — no device syncs.
- **Trace context** — ``query_scope()`` binds a ``trace_id`` (minted, or
  carried in from the bridge frame / ``SRJT_TRACE_ID``) to the executing
  thread, so client spans, server spans, and subprocesses share one ID.
- **Post-mortem bundles** — on a classified error, timeout, cancel, or
  degradation, ``post_mortem()`` writes one JSON bundle atomically to
  ``SRJT_BLACKBOX_DIR`` (empty = ring only): trace_id, ring tail, error
  taxonomy doc + server-side traceback, query summary, plan + decision
  ledger, live progress, config + faults spec.  Exactly one bundle per
  query execution (dedup by execution scope / exception identity); the
  directory is a bounded ring like the profile store.  Browse with
  ``tools/srjt_blackbox.py`` (list / show / grep-by-trace).
- **SLO layer** — ``SRJT_SLO_MS`` declares latency objectives (a default
  plus per-source-fingerprint overrides, ``500,ab12cd34ef56=200``);
  ``slo_report()`` evaluates burn rates from profile-store history and
  ``metrics.prometheus_text()`` exposes them as gauges.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import traceback
from collections import deque
from dataclasses import fields as _dc_fields

from . import errors
from .config import config

#: bundle schema version (bump on breaking change)
VERSION = 1

#: on-disk bundle ring bound (oldest pruned), like SRJT_PROFILE_CAP
_DIR_KEEP = 256

#: in-memory dedup registries stay bounded regardless of uptime
_REG_KEEP = 512

_lock = threading.Lock()
_ring: deque | None = None
_drops = 0
_seq = itertools.count(1)
_exec_ids = itertools.count(1)
#: execution-scope key -> bundle path (one bundle per query execution)
_bundled: dict[str, str] = {}
#: trace_id -> newest bundle path (the bridge error reply's pointer)
_last_by_trace: dict[str, str] = {}

_tls = threading.local()


def enabled() -> bool:
    """Live SRJT_BLACKBOX gate (config singleton, refresh()-tunable)."""
    return config.blackbox


def new_trace_id() -> str:
    """128-bit random trace id, 32 hex chars (W3C traceparent width)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit random span id, 16 hex chars."""
    return os.urandom(8).hex()


class _Scope:
    """One query execution's trace binding on the executing thread."""

    __slots__ = ("trace_id", "exec_id")

    def __init__(self, trace_id: str, exec_id: int):
        self.trace_id = trace_id
        self.exec_id = exec_id


def current_trace() -> str:
    """The trace id bound to this thread ("" outside any scope).

    Falls back to the active query's stamped trace (helper threads that
    re-enter with ``metrics.bind``) and then to ``SRJT_TRACE_ID`` (a
    parent process handing its trace to a subprocess)."""
    s = getattr(_tls, "scope", None)
    if s is not None:
        return s.trace_id
    from . import metrics
    q = metrics.current()
    if q is not None and getattr(q, "trace_id", ""):
        return q.trace_id
    return config.trace_id


@contextlib.contextmanager
def query_scope(trace_id: str = "", label: str = ""):
    """Bind a trace to this thread for one query execution.

    Re-entrant like ``metrics.maybe_query``: a nested scope joins the
    enclosing one (adopting ``trace_id`` into it if the outer scope was
    minted without one) so one top-level execute means one exec_id — the
    post-mortem dedup key.  With no inherited id, one is minted."""
    prev = getattr(_tls, "scope", None)
    if prev is not None:
        if trace_id and not prev.trace_id:
            prev.trace_id = trace_id
        yield prev
        return
    s = _Scope(trace_id or config.trace_id or new_trace_id(),
               next(_exec_ids))
    _tls.scope = s
    record("query.begin", trace=s.trace_id, label=label)
    try:
        yield s
    except BaseException as e:
        record("error", trace=s.trace_id, etype=type(e).__name__,
               kind=errors.classify(e)[0], msg=str(e)[:200])
        raise
    finally:
        _tls.scope = None
        record("query.end", trace=s.trace_id, label=label)


# -- the ring -----------------------------------------------------------------

def _buffer() -> deque:
    """(lock held) ring matching the live cap, rebuilt keeping newest."""
    global _ring
    cap = max(16, int(config.blackbox_cap))
    if _ring is None or _ring.maxlen != cap:
        old = list(_ring) if _ring is not None else []
        _ring = deque(old[-cap:], maxlen=cap)
    return _ring


def record(event: str, **fields) -> None:
    """Append one coarse event to the flight-recorder ring.

    Always on (independent of SRJT_METRICS/SRJT_TIMELINE) unless
    ``SRJT_BLACKBOX=0``.  Pure host-side dict work under one lock.  The
    event type lands under ``ev`` so fields named ``kind`` (error kinds,
    exchange kinds, degradation kinds) pass through untouched."""
    if not config.blackbox:
        return
    ev = {"seq": next(_seq), "t": round(time.time(), 6), "ev": event}
    tid = fields.pop("trace", "") or current_trace()
    if tid:
        ev["trace"] = tid
    from . import metrics
    q = metrics.current()
    if q is not None:
        ev["qid"] = q.qid
        ev["query"] = q.name
    th = threading.current_thread().name
    if th != "MainThread":
        ev["thread"] = th
    ev.update(fields)
    global _drops
    with _lock:
        buf = _buffer()
        if len(buf) == buf.maxlen:
            _drops += 1
        buf.append(ev)


def tail(n: int | None = None) -> list:
    """Newest-last copy of the ring (all of it, or the last ``n``)."""
    with _lock:
        evs = list(_buffer())
    return evs if n is None else evs[-n:]


def ring_stats() -> dict:
    with _lock:
        buf = _buffer()
        return {"events": len(buf), "cap": buf.maxlen, "drops": _drops}


def reset() -> None:
    """Drop the ring and bundle registries (test isolation)."""
    global _ring, _drops
    with _lock:
        _ring = None
        _drops = 0
        _bundled.clear()
        _last_by_trace.clear()


# -- post-mortem bundles ------------------------------------------------------

def post_mortem(reason: str, exc: BaseException | None = None,
                qm=None, trace_id: str = "",
                dir_path: str | None = None,
                extra: dict | None = None) -> str | None:
    """Write one post-mortem bundle; returns its path (None = not written).

    Best-effort end to end: stamps ``exc.trace_id`` so callers can join
    the exception to telemetry even when no bundle lands on disk, dedups
    to one bundle per query execution (a degradation followed by the
    final error reuses the first bundle), writes atomically (tmp +
    rename, a failed write leaves nothing torn behind), and prunes the
    directory past ``_DIR_KEEP``."""
    if not config.blackbox:
        return None
    tid = trace_id or current_trace()
    if exc is not None:
        if tid and not getattr(exc, "trace_id", ""):
            try:
                exc.trace_id = tid
            except (AttributeError, TypeError):
                pass  # __slots__ exception without the attribute
        prev = getattr(exc, "bundle_path", "")
        if prev:
            return prev  # this failure already has its bundle
    d = dir_path or config.blackbox_dir
    if not d:
        record("post_mortem", reason=reason, trace=tid, written=False)
        return None
    s = getattr(_tls, "scope", None)
    key = (f"exec:{s.exec_id}" if s is not None
           else f"trace:{tid}" if tid else "")
    with _lock:
        existing = _bundled.get(key) if key else None
    if existing:
        if exc is not None:
            try:
                exc.bundle_path = existing
            except (AttributeError, TypeError):
                pass
        return existing
    from . import metrics
    cq = qm if qm is not None else metrics.current()
    summary = cq.summary() if cq is not None else None
    doc = {"version": VERSION, "reason": reason, "trace_id": tid,
           "ts": round(time.time(), 6),
           "ring": tail(), "ring_stats": ring_stats(),
           "progress": metrics.progress_snapshot(),
           "config": {f.name: getattr(config, f.name)
                      for f in _dc_fields(type(config))},
           "faults": config.faults}
    if exc is not None:
        edoc = errors.to_wire(exc)
        # the server-side stack context the wire error doc cannot carry:
        # it lives here, and the wire doc points here (bundle path)
        edoc["traceback"] = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))[-8000:]
        doc["error"] = edoc
    if summary:
        doc["query"] = summary
        doc["plan"] = {"fingerprint": summary.get("fingerprint", ""),
                       "source_fingerprint":
                           summary.get("source_fingerprint", ""),
                       "decisions": summary.get("decisions") or [],
                       "degradations": summary.get("degradations") or []}
    if extra:
        doc["extra"] = dict(extra)
    tmp = ""
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"blackbox-{time.time_ns():020d}-{(tid or 'notrace')[:12]}"
               ".json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"), default=str)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        # a failed bundle write must never mask the error it describes,
        # and a torn .tmp must never look like a bundle
        if tmp:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return None
    with _lock:
        if key:
            _bundled[key] = path
            while len(_bundled) > _REG_KEEP:
                _bundled.pop(next(iter(_bundled)))
        if tid:
            _last_by_trace[tid] = path
            while len(_last_by_trace) > _REG_KEEP:
                _last_by_trace.pop(next(iter(_last_by_trace)))
    _prune_dir(d)
    record("post_mortem", reason=reason, trace=tid,
           bundle=os.path.basename(path))
    if exc is not None:
        try:
            exc.bundle_path = path
        except (AttributeError, TypeError):
            pass
    return path


def last_bundle(trace_id: str = "") -> str | None:
    """Newest bundle written for ``trace_id`` in this process (None = no
    bundle for that trace — the wire error doc then carries no pointer)."""
    if not trace_id:
        return None
    with _lock:
        return _last_by_trace.get(trace_id)


def list_bundles(dir_path: str | None = None) -> list:
    """Bundle paths, oldest first (lexical = chronological, like the
    profile store).  ``.tmp`` leftovers never match."""
    d = dir_path or config.blackbox_dir
    if not d or not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, n) for n in os.listdir(d)
                  if n.startswith("blackbox-") and n.endswith(".json"))


def read_bundle(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _prune_dir(d: str) -> None:
    paths = list_bundles(d)
    for p in paths[:max(0, len(paths) - _DIR_KEEP)]:
        try:
            os.remove(p)
        except OSError:
            pass  # concurrent pruner got it first


# -- SLO evaluation -----------------------------------------------------------

def slo_targets() -> tuple:
    """Parse ``SRJT_SLO_MS`` into ``(default_ms | None, {fp_prefix: ms})``.

    Grammar: comma-separated terms; a bare number is the default
    objective, ``<fp_prefix>=<ms>`` overrides it for source fingerprints
    starting with that prefix.  Malformed terms are skipped (flag
    hygiene, like _int_flag's fallback)."""
    default_ms = None
    per: dict[str, float] = {}
    for part in config.slo_ms.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            fp, _, ms = part.partition("=")
            try:
                per[fp.strip()] = float(ms)
            except ValueError:
                continue
        else:
            try:
                default_ms = float(part)
            except ValueError:
                continue
    return default_ms, per


def slo_enabled() -> bool:
    default_ms, per = slo_targets()
    return default_ms is not None or bool(per)


def _objective_for(fp: str, default_ms, per: dict):
    for ov, ms in per.items():
        if ov and fp.startswith(ov):
            return ms
    return default_ms


def slo_objective_for(fp: str):
    """The latency objective (ms) that applies to source fingerprint
    ``fp``, or ``None`` when no SLO covers it.  The scheduler derives a
    session's fair-share weight from this (tighter objective -> more
    chunks per round)."""
    default_ms, per = slo_targets()
    return _objective_for(fp[:12], default_ms, per)


def slo_burn_for(fp: str, dir_path: str | None = None):
    """Windowed burn rate for source fingerprint ``fp`` from the profile
    store, or ``None`` when SLOs are off or the fingerprint has no
    history.  This is the admission controller's shed signal
    (engine/scheduler.py): a fingerprint already burning its error
    budget is shed when the server saturates, instead of queueing
    behind queries that still have budget to protect."""
    rep = slo_report(dir_path)
    if not rep.get("enabled"):
        return None
    p = fp[:12]
    for e in rep["entries"]:
        if e["fingerprint"] == p:
            return e["burn_rate"]
    return None


def slo_report(dir_path: str | None = None) -> dict:
    """Per-source-fingerprint SLO burn from profile-store history.

    A run breaches its objective when its wall time exceeds the
    objective OR it ended in a classified error (an error consumes
    budget exactly like a slow success).  ``burn_rate`` is
    breaches/runs over the stored window — the profile store is already
    a bounded recent ring, so this IS a windowed burn rate."""
    default_ms, per = slo_targets()
    if default_ms is None and not per:
        return {"enabled": False, "default_ms": None, "entries": []}
    from . import profile
    groups: dict[str, dict] = {}
    for p in profile.list_profiles(dir_path):
        try:
            prof = profile.read(p)
        except (OSError, ValueError):
            continue  # torn/pruned profile: skip, like profile.history
        fp = (prof.get("source_fingerprint")
              or prof.get("fingerprint") or "")[:12] or "(none)"
        objective = _objective_for(fp, default_ms, per)
        if objective is None:
            continue  # override-only spec: unlisted fingerprints opt out
        g = groups.setdefault(fp, {"fingerprint": fp,
                                   "objective_ms": objective,
                                   "runs": 0, "breaches": 0, "errors": 0,
                                   "worst_ms": 0.0})
        g["runs"] += 1
        wall_ms = float(prof.get("wall_s") or 0.0) * 1000.0
        g["worst_ms"] = max(g["worst_ms"], wall_ms)
        err = (prof.get("outcome") or {}).get("status") == "error"
        if err:
            g["errors"] += 1
        if err or wall_ms > objective:
            g["breaches"] += 1
    entries = []
    for g in groups.values():
        g["burn_rate"] = (round(g["breaches"] / g["runs"], 4)
                          if g["runs"] else 0.0)
        g["worst_ms"] = round(g["worst_ms"], 3)
        entries.append(g)
    entries.sort(key=lambda g: (-g["burn_rate"], g["fingerprint"]))
    return {"enabled": True, "default_ms": default_ms, "entries": entries}
