"""Profiling scopes: the NVTX-range analog (SURVEY §5).

The reference wraps ops in NVTX ranges toggled by
``ai.rapids.cudf.nvtx.enabled`` (reference pom.xml:84,407) so Nsight shows
per-op spans.  The TPU equivalents:

- ``jax.named_scope`` — always on: names the HLO ops an op emits, so XLA
  dumps and profiler traces attribute work to engine ops (compile-time
  metadata, zero runtime cost).
- ``jax.profiler.TraceAnnotation`` — runtime spans on the host timeline,
  enabled by ``SRJT_TRACE=1`` (visible in Perfetto via ``profile()``).
- ``profile(logdir)`` — capture a full device trace
  (``jax.profiler.trace``), the Nsight-session analog.
- ``count(name)`` / ``counters_snapshot()`` — lightweight named event
  counters (the metrics-registry analog); the engine plan cache reports
  hits/misses through these.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

import jax

from . import timeline
from .config import config


@contextlib.contextmanager
def op_scope(name: str):
    """Named scope + (when SRJT_TRACE=1) a host profiler annotation +
    (when SRJT_TIMELINE=1) a span in the in-process event timeline —
    one call site, three observability sinks on the same name."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(jax.named_scope(name))
        if config.trace:
            stack.enter_context(jax.profiler.TraceAnnotation(name))
        if config.timeline:
            stack.enter_context(timeline.span(name))
        yield


def traced(name: str):
    """Decorator form of ``op_scope`` for op entry points."""
    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with op_scope(name):
                return fn(*args, **kwargs)
        return inner
    return wrap


@contextlib.contextmanager
def profile(logdir: str):
    """Device+host trace capture; view in Perfetto/TensorBoard.

    Usage::

        with tracing.profile("/tmp/trace"):
            run_query(...)

    Creates ``logdir`` if missing, and degrades to a warning no-op when
    ``jax.profiler`` is unavailable or fails to start on this platform —
    the docs/OBSERVABILITY.md recipe must work on a clean checkout, not
    raise (the SRJT_TIMELINE path exists for exactly those shells).
    """
    from .config import logger
    os.makedirs(logdir, exist_ok=True)
    try:
        cm = jax.profiler.trace(logdir)
        cm.__enter__()
    except Exception as e:
        logger().warning(
            "jax.profiler unavailable (%s); profile(%r) is a no-op — "
            "use SRJT_TIMELINE=1 for the in-process timeline", e, logdir)
        yield
        return
    try:
        yield
    finally:
        cm.__exit__(None, None, None)


# -- named event counters --------------------------------------------------
#
# Process-wide monotonic counters keyed by dotted name (e.g.
# "engine.plan_cache.hit").  Cheap enough to leave on unconditionally;
# thread-safe because the bridge server increments from its serve thread
# while tests read snapshots from the main thread.

_counters: dict[str, int] = {}
_counters_lock = threading.Lock()


def count(name: str, n: int = 1) -> int:
    """Increment counter ``name`` by ``n``; returns the new value."""
    with _counters_lock:
        v = _counters.get(name, 0) + n
        _counters[name] = v
        return v


def counter_value(name: str) -> int:
    with _counters_lock:
        return _counters.get(name, 0)


def counters_snapshot(prefix: str = "") -> dict:
    """Copy of all counters whose name starts with ``prefix``."""
    with _counters_lock:
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def reset_counters(prefix: str = "") -> None:
    """Zero counters under ``prefix`` (tests isolate themselves with this)."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


def restore_counters(snapshot: dict, prefix: str = "") -> None:
    """Put back a ``counters_snapshot(prefix)`` taken before a reset (the
    tail half of the ``metrics_isolation`` test fixture)."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]
        _counters.update(snapshot)
