"""Query-scoped metrics: spans, histograms, gauges over the flat counters.

``utils.tracing`` gives the process flat monotonic counters (the
metrics-registry analog of the reference's NVTX-range toggles); this module
adds the attribution layer the Spark RAPIDS plugin gets from per-operator
SQLMetrics: a ``QueryMetrics`` context that collects per-plan-node spans
(wall time, rows in/out, chunk count, padded-vs-live row waste, host-sync
count), per-query counter attribution, and lock-protected histograms and
gauges keyed by dotted name so concurrent queries never collide.

Three consumers sit on top (docs/OBSERVABILITY.md):

- ``engine.explain_analyze(plan)`` renders the optimized DAG annotated
  with the spans recorded here (the EXPLAIN ANALYZE analog).
- The bridge's ``OP_METRICS`` reply embeds ``snapshot()`` so JNI-side
  callers can poll counters + histograms + per-query summaries.
- ``bench.py`` embeds ``snapshot()`` into its emitted JSON so BENCH_*.json
  carries attribution, not just totals.

Collection is gated by ``SRJT_METRICS`` (default on): every entry point is
cheap dict/``perf_counter`` work — no device syncs — and with the flag off
each returns immediately, restoring the uninstrumented fast path.  The
pre-existing flat counters (``tracing.count``) stay on unconditionally, as
they always were.  ``SRJT_TRACE=1`` layers Perfetto ``TraceAnnotation``s
(``tracing.op_scope``) on top of the same span names.

Threading: the active query context is a thread-local; code that fans work
out to helper threads (the chunked reader's prefetch producer) captures
``current()`` and re-enters it with ``bind(qm)`` so producer-side metrics
still attribute to the query that spawned them.  ``QueryMetrics`` carries
its own lock, so attribution from any bound thread is safe.
"""

from __future__ import annotations

import contextlib
import itertools
import math
import threading
import time
from collections import deque

from . import timeline, tracing
from .config import config

# -- registries -------------------------------------------------------------
#
# Histograms and gauges mirror the tracing counter registry: process-wide,
# dotted-name keyed, one lock.  Histogram values bucket into powers of two
# (the chunk-row-bucket convention io/staging.py already uses), which keeps
# the bucket set tiny without pre-declaring ranges per metric.

_lock = threading.Lock()
_hists: dict[str, dict] = {}
_gauges: dict[str, float] = {}

#: live-query progress registry: qid -> the QueryMetrics itself.  Entries
#: register at QueryMetrics construction and leave at ``finish()``, so the
#: registry IS the set of in-flight queries — the bridge's OP_QUERY_STATUS
#: and ``progress_snapshot()`` read it from any thread while the query
#: runs.  Writes ride the per-query lock; no device work anywhere.
_progress: dict[int, "QueryMetrics"] = {}

#: completed-query summaries, newest last (the bridge/bench export window)
_RECENT_LIMIT = 32
_recent: "deque[dict]" = deque(maxlen=_RECENT_LIMIT)

_tls = threading.local()
_qids = itertools.count(1)


def enabled() -> bool:
    """Live SRJT_METRICS gate (config singleton, refresh()-tunable)."""
    return config.metrics


def _bucket_le(value: float) -> float:
    """Smallest power-of-two upper bound for ``value`` (0.0 for <= 0)."""
    v = float(value)
    if v <= 0.0:
        return 0.0
    return 2.0 ** math.ceil(math.log2(v))


def _hist_add(hists: dict, name: str, value: float) -> None:
    h = hists.get(name)
    if h is None:
        h = hists[name] = {"count": 0, "sum": 0.0,
                           "min": None, "max": None, "buckets": {}}
    v = float(value)
    h["count"] += 1
    h["sum"] += v
    h["min"] = v if h["min"] is None else min(h["min"], v)
    h["max"] = v if h["max"] is None else max(h["max"], v)
    le = _bucket_le(v)
    h["buckets"][le] = h["buckets"].get(le, 0) + 1


def _hist_percentiles(h: dict, qs=(0.5, 0.9, 0.99)) -> dict:
    """Derived p50/p90/p99 from the power-of-two buckets.

    A value in bucket ``le`` lies in ``(le/2, le]``, so a percentile
    interpolated linearly inside its bucket carries at most a 2x
    (one-bucket-width) error — tight enough to rank latency tails and
    device-load distributions without pre-declared bucket edges.  Results
    clamp to the observed [min, max], so a single-valued histogram reports
    that exact value at every percentile.
    """
    n = h["count"]
    if not n:
        return {f"p{int(q * 100)}": None for q in qs}
    items = sorted(h["buckets"].items())
    out = {}
    for q in qs:
        target = q * n
        cum = 0.0
        val = h["max"]
        for le, c in items:
            if cum + c >= target:
                if le <= 0:
                    val = 0.0
                else:
                    lo = le / 2.0
                    val = lo + (le - lo) * ((target - cum) / c)
                break
            cum += c
        out[f"p{int(q * 100)}"] = min(max(val, h["min"]), h["max"])
    return out


def _hist_dump(h: dict) -> dict:
    """JSON-friendly histogram copy: buckets as sorted [le, count] pairs
    plus ``sum``/``count`` (and the derived ``mean`` and p50/p90/p99) so
    consumers of the OP_METRICS reply compute averages and tails without
    re-deriving from power-of-two bucket midpoints."""
    return {"count": h["count"], "sum": h["sum"],
            "mean": (h["sum"] / h["count"]) if h["count"] else None,
            "min": h["min"], "max": h["max"],
            **_hist_percentiles(h),
            "buckets": sorted([le, n] for le, n in h["buckets"].items())}


def _hist_load(d: dict) -> dict:
    return {"count": d["count"], "sum": d["sum"],
            "min": d["min"], "max": d["max"],
            "buckets": {float(le): n for le, n in d["buckets"]}}


def q_error(est, actual) -> float | None:
    """Cardinality q-error: ``max(est/actual, actual/est)``, the symmetric
    misestimate factor the AQE literature scores planners by (1.0 =
    perfect).  Zeros clamp to 1 row so empty results stay finite — an
    est=1000 that saw 0 rows scores 1000x, not inf.  ``None`` estimate
    (unknown cardinality) returns None: un-scorable, counted separately
    by ``engine.estimate.unknown``."""
    if est is None:
        return None
    e = max(float(est), 1.0)
    a = max(float(actual or 0), 1.0)
    return round(max(e / a, a / e), 4)


# -- per-query context ------------------------------------------------------

_NODE_FIELDS = ("calls", "wall_s", "rows_in", "rows_out", "chunks",
                "padded_rows", "host_syncs", "bytes_in", "bytes_out",
                "wire_bytes")


class QueryMetrics:
    """One query's attribution: node spans, counters, histograms, timers.

    Node spans are keyed by the caller's choice (the executor uses
    ``id(node)`` within one optimized plan) and accumulate across calls —
    a per-chunk re-walk of the scan-dependent subtree adds one call per
    chunk to each node it touches, so span totals ARE the per-node chunk
    and row flow.
    """

    __slots__ = ("qid", "name", "t0", "wall_s", "stats", "counters",
                 "node_spans", "hists", "timers", "mem", "fingerprint",
                 "source_fingerprint", "outcome", "degradations",
                 "decisions", "progress", "trace_id", "_lock")

    def __init__(self, name: str = ""):
        self.qid = next(_qids)
        self.name = name or f"q{self.qid}"
        # end-to-end trace id (utils/blackbox.py query_scope): stamped by
        # the bridge server from the client's v2 frame header, so client
        # spans, server spans, and post-mortem bundles join on one id
        self.trace_id: str = ""
        self.t0 = time.perf_counter()
        self.wall_s: float | None = None
        self.stats: dict = {}
        self.counters: dict[str, int] = {}
        self.node_spans: dict = {}
        self.hists: dict[str, dict] = {}
        self.timers: dict[str, float] = {}
        self.mem: dict = {}  # device-memory telemetry (mem_sample)
        self.fingerprint: str = ""  # plan fingerprint (profile-store key)
        # pre-optimization fingerprint (AQE profile-history key: stable
        # across runs even when warming changes the optimized shape)
        self.source_fingerprint: str = ""
        self.outcome: dict = {}  # status/kind/error (engine/recovery.py)
        self.degradations: list = []  # ladder steps taken (step, cause)
        self.decisions: list = []  # optimizer ledger (plan._decisions)
        # live progress counters, published at chunk boundaries
        self.progress: dict = {"chunks_done": 0, "chunks_total": 0,
                               "rows": 0, "bytes": 0}
        self._lock = threading.Lock()
        with _lock:
            _progress[self.qid] = self

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            _hist_add(self.hists, name, value)

    def add_time(self, name: str, dt: float) -> None:
        with self._lock:
            self.timers[name] = self.timers.get(name, 0.0) + dt

    def _span_record(self, key, label: str) -> dict:
        r = self.node_spans.get(key)
        if r is None:
            r = self.node_spans[key] = dict.fromkeys(_NODE_FIELDS, 0)
            r["wall_s"] = 0.0
            r["label"] = label
        return r

    def node_add(self, key, label: str, **fields) -> None:
        """Accumulate span fields (``_NODE_FIELDS``) onto node ``key``."""
        with self._lock:
            r = self._span_record(key, label)
            for k, v in fields.items():
                r[k] += v

    def node_set(self, key, label: str, **fields) -> None:
        """SET derived span fields on node ``key`` (no accumulation).

        For values that are not running sums — an Exchange's skew ratio,
        straggler share, or per-device row breakdown, computed once from
        the whole exchange — where ``node_add``'s ``+=`` would corrupt.
        Also re-stamps ``label``: the caller passing derived fields knows
        the node's real name, which beats whatever incidental recorder
        (a keyed host_sync) created the record first."""
        with self._lock:
            r = self._span_record(key, label)
            r["label"] = label
            r.update(fields)

    @contextlib.contextmanager
    def node_span(self, key, label: str):
        """Wall-clock span for one execution of node ``key``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.node_add(key, label, calls=1,
                          wall_s=time.perf_counter() - t0)

    def host_sync(self, n: int = 1, key=None, label: str = "") -> None:
        self.count("engine.host_sync", n)
        if key is not None:
            self.node_add(key, label, host_syncs=n)

    def mem_sample(self, snap: dict) -> None:
        """Fold one ``memory.telemetry_snapshot`` into the query's
        device-memory telemetry: last live-bytes + high-water."""
        live = int(snap.get("live_bytes") or 0)
        peak = snap.get("peak_bytes")
        with self._lock:
            m = self.mem
            m["source"] = snap.get("source", "census")
            m["samples"] = m.get("samples", 0) + 1
            m["live_bytes"] = live
            hw = max(m.get("high_water_bytes", 0), live,
                     int(peak) if peak else 0)
            m["high_water_bytes"] = hw

    def note_stats(self, stats: dict) -> None:
        self.stats = dict(stats)

    def degrade(self, step: str, cause: str = "") -> None:
        """Record one degradation-ladder step (engine/recovery.py)."""
        with self._lock:
            self.degradations.append({"step": step, "cause": cause})

    def set_decisions(self, decisions) -> None:
        """Adopt the optimizer's decision ledger (``plan._decisions``)."""
        with self._lock:
            self.decisions = [dict(d) for d in decisions]

    def progress_total(self, chunks: int) -> None:
        """Grow the expected-chunk total (footer metadata, per stream —
        a query with several chunked scans accumulates each reader's
        estimate)."""
        with self._lock:
            self.progress["chunks_total"] += int(chunks)

    def progress_step(self, chunks: int = 0, rows: int = 0,
                      nbytes: int = 0) -> None:
        """Publish one chunk boundary: pure host-side dict increments
        (the caller already holds the row/byte counts from buffer
        metadata), so the execution hot path gains zero device syncs."""
        with self._lock:
            p = self.progress
            p["chunks_done"] += int(chunks)
            p["rows"] += int(rows)
            p["bytes"] += int(nbytes)

    def set_outcome(self, status: str, kind: str = "",
                    error: str = "") -> None:
        """Stamp the query's terminal status (``ok`` | ``error``)."""
        with self._lock:
            self.outcome = {"status": status}
            if kind:
                self.outcome["kind"] = kind
            if error:
                self.outcome["error"] = error[:200]

    def finish(self) -> None:
        if self.wall_s is None:
            self.wall_s = time.perf_counter() - self.t0
        with _lock:
            _progress.pop(self.qid, None)

    def summary(self) -> dict:
        """JSON-ready snapshot (safe to call live or after ``finish``)."""
        with self._lock:
            wall = self.wall_s if self.wall_s is not None \
                else time.perf_counter() - self.t0
            nodes = [{k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in r.items()} for r in self.node_spans.values()]
            out = {"qid": self.qid, "name": self.name,
                   "wall_s": round(wall, 6),
                   "stats": dict(self.stats),
                   "counters": dict(self.counters),
                   "timers": {k: round(v, 6)
                              for k, v in self.timers.items()},
                   "histograms": {k: _hist_dump(h)
                                  for k, h in self.hists.items()},
                   "nodes": nodes}
            if self.fingerprint:
                out["fingerprint"] = self.fingerprint
            if self.source_fingerprint:
                out["source_fingerprint"] = self.source_fingerprint
            if self.trace_id:
                out["trace_id"] = self.trace_id
            if self.mem:
                out["memory"] = dict(self.mem)
            if self.outcome:
                out["outcome"] = dict(self.outcome)
            if self.degradations:
                out["degradations"] = list(self.degradations)
            if self.decisions:
                out["decisions"] = [dict(d) for d in self.decisions]
            return out


def current() -> QueryMetrics | None:
    """The query context bound to this thread (None outside any query)."""
    return getattr(_tls, "q", None)


@contextlib.contextmanager
def query(name: str = ""):
    """Open a query context on this thread; records its summary on exit.

    Yields ``None`` (and collects nothing) when ``SRJT_METRICS=0``.
    """
    if not config.metrics:
        yield None
        return
    qm = QueryMetrics(name)
    prev = current()
    _tls.q = qm
    try:
        yield qm
    finally:
        _tls.q = prev
        qm.finish()
        summary = qm.summary()
        with _lock:
            _recent.append(summary)
        if config.profile_dir:
            # persist one compact profile per query (utils/profile.py);
            # profile IO must never fail the query it describes
            try:
                from . import profile
                profile.write(summary)
            except Exception as e:  # noqa: BLE001 — best-effort telemetry
                from .config import logger
                logger().debug("profile write failed: %s", e)


@contextlib.contextmanager
def maybe_query(name: str = ""):
    """``query(name)`` unless one is already active on this thread.

    Yields the NEW context or ``None`` — never the enclosing one — so
    callers know whether they own the stats/summary hookup.
    """
    if not config.metrics or current() is not None:
        yield None
        return
    with query(name) as qm:
        yield qm


@contextlib.contextmanager
def bind(qm: QueryMetrics | None):
    """Re-enter a captured query context on a helper thread."""
    prev = current()
    _tls.q = qm
    try:
        yield qm
    finally:
        _tls.q = prev


# -- module-level recording -------------------------------------------------

def count(name: str, n: int = 1) -> int:
    """Flat counter tick (always on) + active-query attribution."""
    v = tracing.count(name, n)
    q = current()
    if q is not None:
        q.count(name, n)
    return v


def observe(name: str, value: float) -> None:
    """Record ``value`` into histogram ``name`` (global + active query)."""
    if not config.metrics:
        return
    with _lock:
        _hist_add(_hists, name, value)
    q = current()
    if q is not None:
        q.observe(name, value)


def time_add(name: str, dt: float) -> None:
    """Accumulate a duration gauge (global) + per-query timer."""
    if not config.metrics:
        return
    with _lock:
        _gauges[name] = _gauges.get(name, 0.0) + dt
    q = current()
    if q is not None:
        q.add_time(name, dt)


def gauge_set(name: str, value: float) -> None:
    if not config.metrics:
        return
    with _lock:
        _gauges[name] = value


def gauge_max(name: str, value: float) -> None:
    """Keep the high-water mark of ``name`` (e.g. dispatch-ahead depth)."""
    if not config.metrics:
        return
    with _lock:
        if value > _gauges.get(name, float("-inf")):
            _gauges[name] = value


def host_sync(n: int = 1, key=None, label: str = "") -> None:
    """Record a deliberate device->host sync point (attributed if keyed).

    Also drops a timeline instant event at the sync site — timeline-gated
    independently of SRJT_METRICS, so the Perfetto view marks the engine's
    deliberate syncs even with the metrics layer off — and a flight-
    recorder event (utils/blackbox.py), which survives even with BOTH
    observability layers off."""
    from . import blackbox
    blackbox.record("host_sync", label=label, n=n)
    if config.timeline:
        timeline.instant("engine.host_sync",
                         {"label": label} if label else None)
    if not config.metrics:
        return
    tracing.count("engine.host_sync", n)
    q = current()
    if q is not None:
        q.host_sync(n, key=key, label=label)


def mem_checkpoint(platform: str | None = None) -> None:
    """Sample device memory into the active query + process gauges.

    The executor calls this at query boundaries and chunk boundaries of
    the streaming loops; prefers the runtime allocator's stats (cheap C
    call on TPU/GPU) and falls back to the live-array byte census.  Pure
    host-side accounting — no device sync either way."""
    if not config.metrics:
        return
    from . import memory
    snap = memory.telemetry_snapshot(platform)
    live = int(snap.get("live_bytes") or 0)
    gauge_set("memory.device.live_bytes", live)
    peak = snap.get("peak_bytes")
    gauge_max("memory.device.high_water_bytes",
              int(peak) if peak else live)
    if config.timeline:
        timeline.counter("memory.device.live_bytes", live)
    q = current()
    if q is not None:
        q.mem_sample(snap)


# -- snapshots / test isolation ---------------------------------------------

def histograms_snapshot(prefix: str = "") -> dict:
    with _lock:
        return {k: _hist_dump(h) for k, h in _hists.items()
                if k.startswith(prefix)}


def gauges_snapshot(prefix: str = "") -> dict:
    with _lock:
        return {k: v for k, v in _gauges.items() if k.startswith(prefix)}


def recent_summaries(limit: int | None = None) -> list:
    """Completed-query summaries, oldest first (bounded window)."""
    with _lock:
        out = list(_recent)
    return out if limit is None else out[-limit:]


def progress_snapshot() -> list:
    """One entry per in-flight query, qid order: chunk/row/byte progress
    plus a derived ETA (remaining chunks x the query's own
    ``engine.stream.chunk_latency_s`` p50 — the histogram the streaming
    loops already feed, so the estimate costs the READER a percentile
    walk and the running query nothing).  ``chunks_total`` is the footer
    estimate (0 = no chunked stream opened yet).

    Entries carry a per-trace ``key`` (the trace id, or ``qid:<n>`` for
    untraced queries): under multi-tenancy two concurrent sessions can
    run the SAME plan — same name, same fingerprint — and a consumer
    keying by either would merge their (independent) ETAs.  Every field
    here, ETA included, is derived from the entry's own QueryMetrics, so
    same-fingerprint sessions never contaminate each other; ``key``
    makes that identity explicit for clients."""
    with _lock:
        live = list(_progress.values())
    out = []
    for qm in sorted(live, key=lambda q: q.qid):
        with qm._lock:
            p = dict(qm.progress)
            h = qm.hists.get("engine.stream.chunk_latency_s")
            p50 = _hist_percentiles(h, (0.5,))["p50"] if h else None
            entry = {"qid": qm.qid, "name": qm.name,
                     "key": qm.trace_id or f"qid:{qm.qid}",
                     "fingerprint": qm.fingerprint,
                     "trace_id": qm.trace_id,
                     "wall_s": round(time.perf_counter() - qm.t0, 6),
                     **p}
        remaining = p["chunks_total"] - p["chunks_done"]
        entry["eta_s"] = (round(remaining * p50, 6)
                          if p50 is not None and remaining > 0 else None)
        out.append(entry)
    return out


# -- Prometheus text exposition ----------------------------------------------

def _prom_name(name: str) -> str:
    """Dotted metric name -> exposition-safe name under the srjt_ prefix."""
    safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return f"srjt_{safe}"


def _prom_hist(name: str, h: dict, lines: list) -> None:
    """Render one ``_hist_dump``-shaped histogram: cumulative le buckets
    (power-of-two upper bounds) + the mandatory +Inf, _sum, _count."""
    lines.append(f"# TYPE {name} histogram")
    cum = 0
    for le, n in h.get("buckets", ()):
        cum += n
        lines.append(f'{name}_bucket{{le="{float(le):g}"}} {cum}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {h["count"]}')
    lines.append(f"{name}_sum {float(h['sum']):g}")
    lines.append(f"{name}_count {h['count']}")


def prometheus_text(snap: dict | None = None, prefix: str = "") -> str:
    """The whole counters/gauges/histograms registry in Prometheus text
    exposition format (version 0.0.4) — hand-rolled, no client library.

    ``snap`` accepts a ``snapshot()``-shaped dict (e.g. an OP_METRICS
    reply decoded by ``tools/srjt_export.py``) so a scrape can render a
    remote server's registry; default is this process's live registry.
    Adds ``srjt_queries_in_flight`` and per-query progress gauges from
    the progress registry (local scrapes only — a snapshot dict carries
    no live progress), and SLO burn-rate gauges per source fingerprint
    when objectives are declared (``SRJT_SLO_MS``, utils/blackbox.py) —
    either from the snapshot's ``slo`` block (an OP_METRICS reply) or
    evaluated locally from profile-store history."""
    if snap is None:
        snap = {"counters": tracing.counters_snapshot(prefix),
                "histograms": histograms_snapshot(prefix),
                "gauges": gauges_snapshot(prefix),
                "progress": progress_snapshot()}
        from . import blackbox
        if blackbox.slo_enabled():
            snap["slo"] = blackbox.slo_report()
    lines: list[str] = []
    for k in sorted(snap.get("counters") or {}):
        name = _prom_name(k)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {snap['counters'][k]}")
    for k in sorted(snap.get("gauges") or {}):
        name = _prom_name(k)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {float(snap['gauges'][k]):g}")
    for k in sorted(snap.get("histograms") or {}):
        _prom_hist(_prom_name(k), snap["histograms"][k], lines)
    progress = snap.get("progress")
    if progress is not None:
        lines.append("# TYPE srjt_queries_in_flight gauge")
        lines.append(f"srjt_queries_in_flight {len(progress)}")
        for g in ("chunks_done", "chunks_total", "rows", "bytes"):
            name = f"srjt_query_progress_{g}"
            if progress:
                lines.append(f"# TYPE {name} gauge")
                for e in progress:
                    lines.append(f'{name}{{qid="{e["qid"]}",'
                                 f'name="{e["name"]}"}} {e[g]}')
    slo = snap.get("slo") or {}
    if slo.get("enabled"):
        if slo.get("default_ms") is not None:
            lines.append("# TYPE srjt_slo_default_objective_ms gauge")
            lines.append("srjt_slo_default_objective_ms "
                         f"{float(slo['default_ms']):g}")
        entries = slo.get("entries") or []
        for g in ("objective_ms", "runs", "breaches", "errors",
                  "worst_ms", "burn_rate"):
            if not entries:
                break
            name = f"srjt_slo_{g}"
            lines.append(f"# TYPE {name} gauge")
            for e in entries:
                lines.append(f'{name}{{fingerprint="{e["fingerprint"]}"}} '
                             f"{float(e[g]):g}")
    return "\n".join(lines) + "\n"


def snapshot(prefix: str = "") -> dict:
    """The full export body: counters + histograms + gauges + queries."""
    return {"counters": tracing.counters_snapshot(prefix),
            "histograms": histograms_snapshot(prefix),
            "gauges": gauges_snapshot(prefix),
            "queries": recent_summaries()}


def reset(prefix: str = "") -> None:
    """Zero histograms/gauges under ``prefix`` (tests isolate with this);
    a full reset (empty prefix) also drops the recent-query window."""
    with _lock:
        for k in [k for k in _hists if k.startswith(prefix)]:
            del _hists[k]
        for k in [k for k in _gauges if k.startswith(prefix)]:
            del _gauges[k]
        if not prefix:
            _recent.clear()


def restore(hists: dict | None = None, gauges: dict | None = None,
            prefix: str = "") -> None:
    """Put back a ``histograms_snapshot``/``gauges_snapshot`` pair taken
    before ``reset(prefix)`` (the ``metrics_isolation`` fixture's tail)."""
    with _lock:
        for k in [k for k in _hists if k.startswith(prefix)]:
            del _hists[k]
        for k in [k for k in _gauges if k.startswith(prefix)]:
            del _gauges[k]
        for k, d in (hists or {}).items():
            _hists[k] = _hist_load(d)
        _gauges.update(gauges or {})
