"""Columnar file I/O: the libcudf-I/O role of the stack.

The reference consumes libcudf's Parquet reader (built by
build-libcudf.xml:37-50; the ChunkedParquet north-star op in BASELINE.md)
through JNI.  Here the scan path is native to the engine: footer/metadata
parsing and page decode on the host, decoded buffers handed to the device as
jax arrays, with the chunked reader bounding device-memory per pass the same
way the reference bounds row-conversion batches to 2^31 bytes
(row_conversion.cu:476-511).
"""

from .parquet import (  # noqa: F401
    ParquetChunkedReader,
    ParquetFile,
    read_parquet,
)
from .parquet_writer import write_parquet  # noqa: F401
from .csv import read_csv, write_csv  # noqa: F401
from .orc import ORCChunkedReader, ORCFile, read_orc  # noqa: F401
from .orc_writer import write_orc  # noqa: F401
