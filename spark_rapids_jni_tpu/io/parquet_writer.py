"""Parquet writer: device/host Tables -> standard parquet files.

The write half of the libcudf-I/O role (reference build-libcudf.xml:37-50
builds libcudf's parquet writer; the reference's Spark plugin writes shuffle
and output files through it).  Flat schemas, data page V1, PLAIN encoding,
RLE definition levels for nullable columns, optional snappy compression
(native codec when linked, else uncompressed), min/max/null_count footer
statistics on fixed-width columns — the subset our reader and predicate
pruning consume, and pyarrow-readable (the round-trip tests use pyarrow as
the independent reader oracle).
"""

from __future__ import annotations

import os

import numpy as np

from .. import dtypes as dt
from ..columnar import Table
from .thrift import (T_BINARY, T_I32, T_I64, T_LIST, T_STRUCT,
                     _enc_varint, encode_struct)

_MAGIC = b"PAR1"

# physical types
_PT_BOOLEAN, _PT_INT32, _PT_INT64 = 0, 1, 2
_PT_FLOAT, _PT_DOUBLE, _PT_BYTE_ARRAY = 4, 5, 6

# (physical, converted_type, widen_np) per supported dtype id
_PHYS = {
    dt.TypeId.BOOL8: (_PT_BOOLEAN, None, None),
    dt.TypeId.INT8: (_PT_INT32, 15, np.int32),
    dt.TypeId.INT16: (_PT_INT32, 16, np.int32),
    dt.TypeId.INT32: (_PT_INT32, None, None),
    dt.TypeId.INT64: (_PT_INT64, None, None),
    dt.TypeId.UINT8: (_PT_INT32, 11, np.int32),
    dt.TypeId.UINT16: (_PT_INT32, 12, np.int32),
    dt.TypeId.UINT32: (_PT_INT32, 13, np.int32),
    dt.TypeId.UINT64: (_PT_INT64, 14, np.int64),
    dt.TypeId.FLOAT32: (_PT_FLOAT, None, None),
    dt.TypeId.FLOAT64: (_PT_DOUBLE, None, None),
    dt.TypeId.TIMESTAMP_DAYS: (_PT_INT32, 6, None),
    dt.TypeId.TIMESTAMP_MILLISECONDS: (_PT_INT64, 9, None),
    dt.TypeId.TIMESTAMP_MICROSECONDS: (_PT_INT64, 10, None),
    dt.TypeId.STRING: (_PT_BYTE_ARRAY, 0, None),  # ConvertedType UTF8
    dt.TypeId.DECIMAL32: (_PT_INT32, 5, None),
    dt.TypeId.DECIMAL64: (_PT_INT64, 5, None),
}

from .parquet import _SNAPPY_NATIVE as _SNAPPY  # one codec handle for io/


def _rle_bitpacked_bools(bits: np.ndarray) -> bytes:
    """Definition levels (bit width 1) as one bit-packed hybrid run."""
    return _rle_levels(bits.astype(np.uint8), 1)


def _rle_levels(levels: np.ndarray, bit_width: int) -> bytes:
    """Level stream at ``bit_width`` bits as one bit-packed hybrid run
    (LSB-first within each value, groups of 8 values)."""
    n = len(levels)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, np.uint8)
    padded[:n] = levels.astype(np.uint8)
    # (8*groups, bit_width) LSB-first bit matrix -> packbits little
    bits = (padded[:, None] >> np.arange(bit_width, dtype=np.uint8)) & 1
    packed = np.packbits(bits.reshape(-1), bitorder="little").tobytes()
    header = bytearray()
    _enc_varint(header, (groups << 1) | 1)
    return bytes(header) + packed


def _plain_values(col, dtype: dt.DType, valid) -> tuple[bytes, int]:
    """(PLAIN-encoded non-null values, non-null count)."""
    if dtype.is_string:
        chars = np.asarray(col.data, np.uint8)
        offs = np.asarray(col.offsets, np.int64)
        lens = np.diff(offs)
        keep = np.arange(len(lens)) if valid is None else np.flatnonzero(valid)
        cb = chars.tobytes()
        blob = bytearray()
        for i in keep:
            blob += int(lens[i]).to_bytes(4, "little")
            blob += cb[offs[i]:offs[i + 1]]
        return bytes(blob), len(keep)
    vals = np.asarray(col.data)
    if dtype.id == dt.TypeId.FLOAT64:
        vals = vals.view(np.float64)  # stored as bit patterns
    widen = _PHYS[dtype.id][2]
    if widen is not None:
        vals = vals.astype(widen)
    if valid is not None:
        vals = vals[valid]
    if dtype.id == dt.TypeId.BOOL8:
        return np.packbits(vals.astype(np.uint8), bitorder="little").tobytes(), \
            len(vals)
    return vals.tobytes(), len(vals)


def _stats(col, dtype: dt.DType, valid):
    """(min_bytes, max_bytes, null_count) or (None, None, null_count)."""
    nulls = 0 if valid is None else int(len(valid) - valid.sum())
    if dtype.is_string or dtype.id == dt.TypeId.BOOL8:
        return None, None, nulls
    vals = np.asarray(col.data)
    if dtype.id == dt.TypeId.FLOAT64:
        vals = vals.view(np.float64)
    if valid is not None:
        vals = vals[valid]
    if len(vals) == 0:
        return None, None, nulls
    if vals.dtype.kind == "f" and np.isnan(vals).any():
        # the spec forbids NaN in min/max; stats-trusting readers would
        # mis-prune (NaN compares false) — omit min/max, keep null_count
        return None, None, nulls
    # order in the ORIGINAL dtype (unsigned stays unsigned), then encode the
    # scalars at the physical width (readers decode physical-type bytes)
    widen = _PHYS[dtype.id][2]
    lo, hi = vals.min(), vals.max()
    if widen is not None:
        lo, hi = lo.astype(widen), hi.astype(widen)
    return lo.tobytes(), hi.tobytes(), nulls


def _leaf_element(col, name, nl) -> list:
    if col.dtype.id not in _PHYS:
        raise NotImplementedError(
            f"parquet write for {col.dtype!r} is not supported")
    phys, conv, _ = _PHYS[col.dtype.id]
    fields = [(1, T_I32, phys),
              (3, T_I32, 1 if nl else 0),
              (4, T_BINARY, name)]
    if conv is not None:
        fields.append((6, T_I32, conv))
    if col.dtype.is_decimal:
        # engine scale is the power-of-ten exponent (cudf convention);
        # parquet scale counts digits right of the point
        fields.append((7, T_I32, -col.dtype.scale))
        fields.append((8, T_I32, 9 if col.dtype.id == dt.TypeId.DECIMAL32
                       else 18))
    return fields


def _field_names(struct_fields, name, col):
    fns = (struct_fields or {}).get(name)
    if fns is None:
        return [f"f{fi}" for fi in range(len(col.children))]
    if len(fns) != len(col.children):
        raise ValueError(f"struct_fields[{name!r}] has {len(fns)} names "
                         f"for {len(col.children)} fields")
    return list(fns)


def _schema_elements(table: Table, names, nullable, struct_fields) -> list:
    root = [(4, T_BINARY, "schema"), (5, T_I32, table.num_columns)]
    elements = [root]
    for col, name, nl in zip(table.columns, names, nullable):
        if col.dtype.id == dt.TypeId.STRUCT:
            elements.append([(3, T_I32, 1 if nl else 0),
                             (4, T_BINARY, name),
                             (5, T_I32, len(col.children))])
            fns = _field_names(struct_fields, name, col)
            for fi, child in enumerate(col.children):
                elements.append(_leaf_element(
                    child, fns[fi], child.validity is not None))
            continue
        if col.dtype.id == dt.TypeId.LIST:
            child = col.children[0]
            if child.dtype.id == dt.TypeId.LIST:
                raise NotImplementedError(
                    "parquet write supports one LIST level (the reader "
                    "handles arbitrary depth; deeper writes TBD)")
            # standard 3-level LIST: optional group (LIST) > repeated
            # group list > element
            elements.append([(3, T_I32, 1 if nl else 0),
                             (4, T_BINARY, name),
                             (5, T_I32, 1),
                             (6, T_I32, 3)])          # ConvertedType LIST
            elements.append([(3, T_I32, 2),           # REPEATED
                             (4, T_BINARY, "list"),
                             (5, T_I32, 1)])
            elements.append(_leaf_element(
                child, "element", child.validity is not None))
            continue
        elements.append(_leaf_element(col, name, nl))
    return elements


def write_parquet(table: Table, path, compression: str = "snappy",
                  row_group_size: int = 1 << 20,
                  struct_fields: dict | None = None) -> None:
    """Write a Table to ``path`` as a standard parquet file.

    ``struct_fields`` maps a STRUCT column name to its field-name list —
    the engine's Column carries unnamed children (the DType system mirrors
    the reference's (typeId, scale) pair, RowConversion.java:113-118), so
    without it struct fields are written as f0, f1, ...  A read-modify-
    write round trip can preserve names via
    ``ParquetFile(path).schema[i].fields``."""
    names = list(table.names or
                 [f"c{i}" for i in range(table.num_columns)])
    codec_id = 0
    codec = None
    if compression == "snappy" and _SNAPPY is not None:
        codec_id, codec = 1, _SNAPPY
    elif compression == "gzip":
        import gzip as _gzip

        class _Gz:
            @staticmethod
            def compress(b, asbytes=True):
                return _gzip.compress(b, 6)
        codec_id, codec = 2, _Gz
    elif compression == "zstd":
        import pyarrow as _pa

        class _Zs:
            _c = _pa.Codec("zstd")

            @classmethod
            def compress(cls, b, asbytes=True):
                return cls._c.compress(b, asbytes=True)
        codec_id, codec = 6, _Zs
    elif compression not in (None, "none", "snappy"):
        raise ValueError(f"unsupported compression {compression!r} "
                         "(none, snappy, gzip, zstd)")

    from ..ops.selection import slice_table
    # nullability is a schema-level decision made once on the input table;
    # slicing can materialize an all-true validity, which must not flip a
    # REQUIRED column to writing definition levels
    nullable = [c.validity is not None for c in table.columns]
    field_nullable = {
        (ci, fi): ch.validity is not None
        for ci, c in enumerate(table.columns)
        if c.dtype.id == dt.TypeId.STRUCT
        for fi, ch in enumerate(c.children)}
    # like field_nullable: snapshot LIST element nullability from the
    # INPUT table — slicing materializes an all-true child validity, which
    # must not add a definition level the schema doesn't declare
    list_elem_nullable = {
        ci: c.children[0].validity is not None
        for ci, c in enumerate(table.columns)
        if c.dtype.id == dt.TypeId.LIST}
    out = bytearray(_MAGIC)
    row_groups = []
    n = table.num_rows
    starts = list(range(0, max(n, 1), row_group_size))
    for start in starts:
        stop = min(n, start + row_group_size)
        part = slice_table(table, start, stop - start) \
            if (start, stop) != (0, n) else table
        g_rows = stop - start
        chunks = []
        g_bytes = 0

        # flatten to leaf chunks: a plain column is one leaf at path [name];
        # a STRUCT column is one leaf per field at path [name, f{i}], with
        # 2-level definition levels when the struct itself is nullable; a
        # LIST column is one leaf at [name, "list", "element"] with
        # 3-level def levels and binary rep levels.  Leaf entries:
        # (path, leaf_col, max_def, def_levels, present, rep_levels,
        #  nvalues)
        leaves = []
        for ci, (col, name) in enumerate(zip(part.columns, names)):
            if col.dtype.id == dt.TypeId.LIST:
                child = col.children[0]
                opt_l = 1 if nullable[ci] else 0
                opt_e = 1 if list_elem_nullable[ci] else 0
                md = opt_l + 1 + opt_e
                offs = np.asarray(col.offsets, np.int64)
                lens = np.diff(offs)
                lvalid = (np.ones(g_rows, np.bool_) if col.validity is None
                          else np.asarray(col.validity))
                lens_eff = np.where(lvalid, lens, 0)
                counts = np.maximum(lens_eff, 1)       # 1 entry per empty/null
                nvalues = int(counts.sum())
                ent_start = np.cumsum(counts) - counts
                row_of = np.repeat(np.arange(g_rows), counts)
                first = np.zeros(nvalues, np.bool_)
                first[ent_start] = True
                rep = (~first).astype(np.uint8)
                has_elem = np.repeat(lens_eff > 0, counts)
                within = np.arange(nvalues) - np.repeat(ent_start, counts)
                e_idx = np.repeat(offs[:-1], counts) + within
                evalid_full = (np.asarray(child.validity)
                               if opt_e and child.validity is not None
                               else np.ones(child.size, np.bool_))
                levels = np.zeros(nvalues, np.uint8)
                lv_row = lvalid[row_of]
                levels[lv_row & ~has_elem] = opt_l          # empty list
                e_safe = np.clip(e_idx, 0, max(child.size - 1, 0))
                full = opt_l + 1 + (
                    evalid_full[e_safe] if opt_e else 0)
                levels = np.where(has_elem, full, levels).astype(np.uint8)
                # elements written: those of valid, non-empty rows, non-null
                emask = np.zeros(child.size, np.bool_)
                if nvalues:
                    sel = e_idx[has_elem]
                    emask[sel] = evalid_full[sel]
                leaves.append(([name, "list", "element"], child, md,
                               levels, emask, rep, nvalues))
                continue
            if col.dtype.id == dt.TypeId.STRUCT:
                s_opt = nullable[ci]
                fns = _field_names(struct_fields, name, col)
                svalid = (np.ones(g_rows, np.bool_) if col.validity is None
                          else np.asarray(col.validity))
                for fi, child in enumerate(col.children):
                    f_opt = field_nullable[(ci, fi)]
                    md = (1 if s_opt else 0) + (1 if f_opt else 0)
                    fvalid = (np.asarray(child.validity) if f_opt
                              else np.ones(g_rows, np.bool_))
                    present = svalid & fvalid
                    levels = np.zeros(g_rows, np.uint8)
                    if s_opt:
                        levels += svalid
                    if f_opt:
                        levels += svalid & fvalid
                    leaves.append(([name, fns[fi]], child, md,
                                   levels if md else None,
                                   present if md else None, None, g_rows))
            else:
                if nullable[ci]:
                    valid = (np.ones(g_rows, np.bool_)
                             if col.validity is None
                             else np.asarray(col.validity))
                    leaves.append(([name], col, 1, valid.astype(np.uint8),
                                   valid, None, g_rows))
                else:
                    leaves.append(([name], col, 0, None, None, None, g_rows))

        for cpath, col, md, levels, present, rep, nvalues in leaves:
            dtype = col.dtype
            body = b""
            if rep is not None:  # V1 page: rep levels, then def levels
                rv = _rle_levels(rep, 1)
                body += len(rv).to_bytes(4, "little") + rv
            if md:
                lv = _rle_levels(levels, md.bit_length())
                body += len(lv).to_bytes(4, "little") + lv
            vals, nnon = _plain_values(
                col, dtype, None if present is None else present)
            body += vals
            comp = codec.compress(body, asbytes=True) if codec else body
            if rep is not None:
                # list leaf: parquet-mr/arrow count every entry below
                # max_def as a null at the leaf (null lists, null elements
                # AND empty lists all lack a leaf value — verified against
                # pyarrow's writer on identical data); min/max omitted
                smin, smax, nulls = None, None, int((levels < md).sum())
            else:
                smin, smax, nulls = _stats(
                    col, dtype, None if present is None else present)
            stats_fields = [(3, T_I64, nulls)]
            if smin is not None:
                stats_fields += [(5, T_BINARY, smax), (6, T_BINARY, smin)]
            header = encode_struct([
                (1, T_I32, 0),                      # DATA_PAGE
                (2, T_I32, len(body)),
                (3, T_I32, len(comp)),
                (5, T_STRUCT, [                     # DataPageHeader
                    (1, T_I32, nvalues),
                    (2, T_I32, 0),                  # PLAIN
                    (3, T_I32, 3),                  # def levels RLE
                    (4, T_I32, 3),                  # rep levels RLE
                ]),
            ])
            page_off = len(out)
            out += header
            out += comp
            phys = _PHYS[dtype.id][0]
            meta = [
                (1, T_I32, phys),
                (2, T_LIST, (T_I32, [0, 3])),       # PLAIN, RLE
                (3, T_LIST, (T_BINARY, list(cpath))),
                (4, T_I32, codec_id),
                (5, T_I64, nvalues),
                (6, T_I64, len(header) + len(body)),
                (7, T_I64, len(header) + len(comp)),
                (9, T_I64, page_off),
                (12, T_STRUCT, stats_fields),
            ]
            chunks.append([(2, T_I64, page_off), (3, T_STRUCT, meta)])
            g_bytes += len(header) + len(body)  # spec: uncompressed size
        row_groups.append([
            (1, T_LIST, (T_STRUCT, chunks)),
            (2, T_I64, g_bytes),
            (3, T_I64, g_rows),
        ])
        if n == 0:
            break

    schema = _schema_elements(table, names, nullable, struct_fields)
    footer = encode_struct([
        (1, T_I32, 1),                              # version
        (2, T_LIST, (T_STRUCT, schema)),
        (3, T_I64, n),
        (4, T_LIST, (T_STRUCT, row_groups)),
        (6, T_BINARY, "spark-rapids-jni-tpu"),
    ])
    out += footer
    out += len(footer).to_bytes(4, "little")
    out += _MAGIC
    with open(os.fspath(path), "wb") as f:
        f.write(out)