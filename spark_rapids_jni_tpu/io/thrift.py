"""Minimal Thrift *compact protocol* decoder for Parquet metadata.

Parquet's footer, page headers and column metadata are thrift-compact
structs (parquet-format's parquet.thrift).  The engine only ever *reads*
them, and only by field id, so instead of generating classes we decode any
struct to ``{field_id: value}`` dicts and let io.parquet interpret the ids.
This is the host-side analog of the metadata path the reference gets from
libcudf's parquet reader (build-libcudf.xml:37-50).

Wire grammar implemented (thrift compact protocol spec):
- varint (ULEB128) + zigzag ints
- field header: ``(delta << 4) | compact_type``; delta==0 -> explicit
  zigzag-varint field id; type 0 terminates the struct
- BOOLEAN_TRUE/FALSE carried in the type nibble
- BINARY: varint length + bytes;  DOUBLE: 8-byte little-endian
- LIST/SET header: ``(size << 4) | elem_type``, size==15 -> varint follows
"""

from __future__ import annotations

import struct

# compact-protocol type ids
T_STOP = 0
T_TRUE = 1
T_FALSE = 2
T_BYTE = 3
T_I16 = 4
T_I32 = 5
T_I64 = 6
T_DOUBLE = 7
T_BINARY = 8
T_LIST = 9
T_SET = 10
T_MAP = 11
T_STRUCT = 12


class ThriftReader:
    """Cursor over a buffer of thrift-compact bytes."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    # -- primitives --------------------------------------------------------
    def varint(self) -> int:
        result = 0
        shift = 0
        buf, pos = self.buf, self.pos
        while True:
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        self.pos = pos
        return result

    def zigzag(self) -> int:
        n = self.varint()
        return (n >> 1) ^ -(n & 1)

    def _binary(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("truncated thrift binary")
        self.pos += n
        return out

    def _double(self) -> float:
        (v,) = struct.unpack_from("<d", self.buf, self.pos)
        self.pos += 8
        return v

    # -- containers --------------------------------------------------------
    def _value(self, ctype: int):
        if ctype == T_TRUE:
            return True
        if ctype == T_FALSE:
            return False
        if ctype in (T_BYTE, T_I16, T_I32, T_I64):
            return self.zigzag()
        if ctype == T_DOUBLE:
            return self._double()
        if ctype == T_BINARY:
            return self._binary()
        if ctype in (T_LIST, T_SET):
            return self._list()
        if ctype == T_MAP:
            return self._map()
        if ctype == T_STRUCT:
            return self.struct()
        raise ValueError(f"unsupported thrift compact type {ctype}")

    def _list(self) -> list:
        head = self.buf[self.pos]
        self.pos += 1
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size = self.varint()
        return [self._value(etype) for _ in range(size)]

    def _map(self) -> dict:
        size = self.varint()
        if size == 0:
            return {}
        kv = self.buf[self.pos]
        self.pos += 1
        ktype, vtype = kv >> 4, kv & 0x0F
        return {self._value(ktype): self._value(vtype) for _ in range(size)}

    def struct(self) -> dict:
        """Decode one struct to {field_id: python value}.

        Booleans arrive as True/False; nested structs as dicts; lists as
        lists; binary as bytes.  Unknown fields decode fine (generic).
        """
        out = {}
        last_id = 0
        while True:
            head = self.buf[self.pos]
            self.pos += 1
            ctype = head & 0x0F
            if ctype == T_STOP:
                return out
            delta = head >> 4
            fid = last_id + delta if delta else self.zigzag()
            last_id = fid
            out[fid] = self._value(ctype)


def decode_struct(buf: bytes, pos: int = 0):
    """Decode a struct at ``pos``; returns (fields dict, end position)."""
    r = ThriftReader(buf, pos)
    fields = r.struct()
    return fields, r.pos


# ---------------------------------------------------------------------------
# encoder (the write side of the same wire grammar)
# ---------------------------------------------------------------------------

def _enc_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return


def _enc_zigzag(out: bytearray, n: int) -> None:
    _enc_varint(out, (n << 1) ^ (n >> 63) if n < 0 else n << 1)


def _enc_value(out: bytearray, ttype: int, value) -> None:
    if ttype in (T_BYTE, T_I16, T_I32, T_I64):
        _enc_zigzag(out, int(value))
    elif ttype == T_BINARY:
        data = value.encode() if isinstance(value, str) else bytes(value)
        _enc_varint(out, len(data))
        out.extend(data)
    elif ttype == T_DOUBLE:
        out.extend(struct.pack("<d", value))
    elif ttype == T_LIST:
        etype, items = value
        if len(items) < 15:
            out.append((len(items) << 4) | etype)
        else:
            out.append(0xF0 | etype)
            _enc_varint(out, len(items))
        for it in items:
            _enc_value(out, etype, it)
    elif ttype == T_STRUCT:
        out.extend(encode_struct(value))
    else:
        raise ValueError(f"unsupported thrift encode type {ttype}")


def encode_struct(fields) -> bytes:
    """Encode [(field_id, type, value), ...] (ids ascending) to compact bytes.

    Booleans pass ``T_TRUE`` with a bool value (the value rides in the type
    nibble); lists pass ``(elem_type, [items])``; structs pass nested field
    lists.  The mirror of ``ThriftReader.struct``.
    """
    out = bytearray()
    last_id = 0
    for fid, ttype, value in fields:
        if value is None:
            continue
        wire_type = ttype
        if ttype in (T_TRUE, T_FALSE):
            wire_type = T_TRUE if value else T_FALSE
        delta = fid - last_id
        if 0 < delta <= 15:
            out.append((delta << 4) | wire_type)
        else:
            out.append(wire_type)
            _enc_zigzag(out, fid)
        last_id = fid
        if ttype not in (T_TRUE, T_FALSE):
            _enc_value(out, ttype, value)
    out.append(T_STOP)
    return bytes(out)
