"""Snappy raw-block decompression (Parquet's default codec).

Pure-Python decoder for the snappy *raw* format pyarrow/parquet-mr emit per
page: a varint uncompressed length, then a tag stream of literals and
back-references.  The byte-granular back-references are inherently
sequential, so this is host code operating on page-sized buffers (~1 MiB)
before the decoded columns are handed to the device — the same division of
labor as the reference, whose nvcomp/snappy decode also happens before cudf
column assembly (libcudf parquet reader role, build-libcudf.xml:37-50).

Performance notes: literals and non-overlapping copies are slice copies
into a preallocated bytearray; overlapping copies (run-length patterns) are
materialized by pattern doubling, so even pathological RLE data costs
O(n log n) slice ops, not O(n) python-level byte writes.
"""

from __future__ import annotations


def _uvarint(buf, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def scan_tokens(src) -> tuple:
    """Walk the token *headers* only: ``(n_tokens, literal_only)``.

    The cheap structural probe behind two fast paths: the device decoder
    (ops/parquet_decode.py) skips its pointer-doubling chase when every
    page of a chunk is literal-only, and :func:`decompress_fast` collapses
    a literal-only block to slice copies.  High-entropy data and
    already-dict-encoded columns compress to a handful of large literals,
    so this is a few-iteration loop, not a byte-level walk.

    Never raises on corrupt input — callers probing eligibility want a
    verdict, not an exception; the real decoder reports corruption.
    """
    _, pos = _uvarint(src, 0)
    slen = len(src)
    n_tokens = 0
    literal_only = True
    while pos < slen:
        tag = src[pos]
        pos += 1
        n_tokens += 1
        kind = tag & 3
        if kind == 0:
            length = (tag >> 2) + 1
            if length > 60:
                nbytes = length - 60
                length = int.from_bytes(src[pos:pos + nbytes],
                                        "little") + 1
                pos += nbytes
            pos += length
        else:
            literal_only = False
            pos += (2, 3, 5)[kind - 1] - 1
    return n_tokens, literal_only


def decompress_fast(src: bytes) -> bytes:
    """`decompress` with a zero-parse fast path for literal-only blocks.

    A block whose token scan finds no back-references is just its literals
    concatenated — each token becomes one slice copy (typically ONE for
    page-sized data, since a literal can span 4 GiB).  Anything else falls
    back to the byte-exact sequential decoder.
    """
    n_tokens, literal_only = scan_tokens(src)
    if not literal_only:
        return decompress(src)
    n, pos = _uvarint(src, 0)
    slen = len(src)
    parts = []
    total = 0
    for _ in range(n_tokens):
        tag = src[pos]
        pos += 1
        length = (tag >> 2) + 1
        if length > 60:
            nbytes = length - 60
            length = int.from_bytes(src[pos:pos + nbytes], "little") + 1
            pos += nbytes
        if pos + length > slen:
            raise ValueError("corrupt snappy stream: truncated literal")
        parts.append(src[pos:pos + length])
        pos += length
        total += length
    if total != n:
        raise ValueError(
            f"corrupt snappy stream: wrote {total}, header said {n}")
    return bytes(parts[0]) if len(parts) == 1 else b"".join(parts)


def decompress(src: bytes) -> bytes:
    """Decode one snappy raw block (the whole-page unit Parquet uses)."""
    n, pos = _uvarint(src, 0)
    dst = bytearray(n)
    dpos = 0
    slen = len(src)
    while pos < slen:
        tag = src[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                nbytes = length - 60
                length = int.from_bytes(src[pos:pos + nbytes], "little") + 1
                pos += nbytes
            if pos + length > slen:
                raise ValueError("corrupt snappy stream: truncated literal")
            dst[dpos:dpos + length] = src[pos:pos + length]
            pos += length
            dpos += length
            continue
        if kind == 1:  # copy, 1-byte offset, 4..11 length
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag & 0xE0) << 3) | src[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(src[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(src[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > dpos:
            raise ValueError("corrupt snappy stream: bad copy offset")
        start = dpos - offset
        if offset >= length:
            dst[dpos:dpos + length] = dst[start:start + length]
            dpos += length
        else:
            # overlapping copy: repeat the window by doubling
            pattern = bytes(dst[start:dpos])
            while len(pattern) < length:
                pattern += pattern
            dst[dpos:dpos + length] = pattern[:length]
            dpos += length
    if dpos != n:
        raise ValueError(f"corrupt snappy stream: wrote {dpos}, header said {n}")
    return bytes(dst)
