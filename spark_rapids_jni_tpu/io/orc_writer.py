"""ORC writer: device/host Tables -> standard ORC files.

The write half of the ORC role (SURVEY.md §2.2 "Parquet/ORC I/O"; the
reference's Spark plugin writes ORC output through libcudf's writer).
Emits version 0.12 files with DIRECT (RLEv1) encodings — the simplest
encoding every ORC reader supports — covering the same scalar surface the
reader decodes: ints, floats, bools, strings, dates, timestamps, decimals.
Optional ZLIB chunk compression.  pyarrow/ORC-C++ is the independent reader
oracle in tests (no engine code on the read side of the round trip).
"""

from __future__ import annotations

import zlib

import numpy as np

from .. import dtypes as dt
from ..columnar import Table
from ..ops.selection import gather_column
from .orc import (COMP_NONE, COMP_SNAPPY, COMP_ZLIB, COMP_ZSTD, SK_DATA, SK_LENGTH, SK_PRESENT,
                  SK_SECONDARY, TK_BOOLEAN, TK_BYTE, TK_DATE, TK_DECIMAL,
                  TK_DOUBLE, TK_FLOAT, TK_INT, TK_LIST, TK_LONG, TK_SHORT,
                  TK_STRING, TK_STRUCT, TK_TIMESTAMP, _ORC_EPOCH_S)
from .thrift import _enc_varint  # one LEB128 encoder for the whole io package

_MAGIC = b"ORC"


# ---------------------------------------------------------------------------
# protobuf wire encoding (proto2, write-side twin of orc._pb_fields)


def _pb_varint(out: bytearray, field: int, v: int):
    _enc_varint(out, field << 3)
    _enc_varint(out, int(v))


def _pb_bytes(out: bytearray, field: int, blob: bytes):
    _enc_varint(out, (field << 3) | 2)
    _enc_varint(out, len(blob))
    out += blob


# ---------------------------------------------------------------------------
# run-length encoders (write-side twins of the io.orc decoders)

def _byte_rle(vals: np.ndarray) -> bytes:
    """Byte RLE: constant runs of 3..130, literal groups of 1..128."""
    out = bytearray()
    n = len(vals)
    i = 0
    while i < n:
        run = 1
        while i + run < n and run < 130 and vals[i + run] == vals[i]:
            run += 1
        if run >= 3:
            out.append(run - 3)
            out.append(int(vals[i]))
            i += run
            continue
        lit_start = i
        while i < n and i - lit_start < 128:
            nxt = 1
            while i + nxt < n and nxt < 3 and vals[i + nxt] == vals[i]:
                nxt += 1
            if nxt >= 3:
                break
            i += 1
        cnt = i - lit_start
        out.append(256 - cnt)
        out += bytes(np.asarray(vals[lit_start:i], np.uint8))
    return bytes(out)


def _bool_rle(bits: np.ndarray) -> bytes:
    by = np.packbits(bits.astype(np.uint8))  # MSB-first
    return _byte_rle(by)


def _zigzag_enc(v: int) -> int:
    """Zigzag for arbitrary-precision python ints (ORC signed varints)."""
    return (v << 1) if v >= 0 else ((-v) << 1) - 1


def _int_rle_v1(vals, signed: bool) -> bytes:
    """RLEv1: constant runs (delta 0) of 3..130, literal varints else."""
    out = bytearray()
    vals = [int(v) for v in vals]
    n = len(vals)

    def emit_varint(v: int):
        _enc_varint(out, _zigzag_enc(v) if signed else v & ((1 << 64) - 1))

    i = 0
    while i < n:
        run = 1
        while i + run < n and run < 130 and vals[i + run] == vals[i]:
            run += 1
        if run >= 3:
            out.append(run - 3)
            out.append(0)  # delta 0
            emit_varint(vals[i])
            i += run
            continue
        lit_start = i
        while i < n and i - lit_start < 128:
            nxt = 1
            while i + nxt < n and nxt < 3 and vals[i + nxt] == vals[i]:
                nxt += 1
            if nxt >= 3:
                break
            i += 1
        cnt = i - lit_start
        out.append(256 - cnt)
        for j in range(lit_start, i):
            emit_varint(vals[j])
    return bytes(out)


def _varint_bigint(out: bytearray, v: int):
    """Unbounded zigzag varint (DECIMAL mantissa)."""
    _enc_varint(out, _zigzag_enc(v))


# ---------------------------------------------------------------------------
# per-column stream production

def _orc_type(dtype: dt.DType) -> tuple[int, dict]:
    extra = {}
    tid = dtype.id
    if tid == dt.TypeId.BOOL8:
        return TK_BOOLEAN, extra
    if tid == dt.TypeId.INT8:
        return TK_BYTE, extra
    if tid == dt.TypeId.INT16:
        return TK_SHORT, extra
    if tid == dt.TypeId.INT32:
        return TK_INT, extra
    if tid in (dt.TypeId.INT64, dt.TypeId.UINT32):
        return TK_LONG, extra  # uint32 fits signed LONG losslessly
    if tid == dt.TypeId.UINT64:
        raise NotImplementedError(
            "ORC has no unsigned 64-bit type; values >= 2**63 cannot be "
            "represented losslessly — cast to INT64 or DECIMAL first")
    if tid in (dt.TypeId.UINT8, dt.TypeId.UINT16):
        return TK_SHORT if tid == dt.TypeId.UINT8 else TK_INT, extra
    if tid == dt.TypeId.FLOAT32:
        return TK_FLOAT, extra
    if tid == dt.TypeId.FLOAT64:
        return TK_DOUBLE, extra
    if tid == dt.TypeId.STRING:
        return TK_STRING, extra
    if tid == dt.TypeId.TIMESTAMP_DAYS:
        return TK_DATE, extra
    if tid in (dt.TypeId.TIMESTAMP_SECONDS, dt.TypeId.TIMESTAMP_MILLISECONDS,
               dt.TypeId.TIMESTAMP_MICROSECONDS,
               dt.TypeId.TIMESTAMP_NANOSECONDS):
        return TK_TIMESTAMP, extra
    if dtype.is_decimal:
        if dtype.scale > 0:
            raise NotImplementedError(
                "ORC decimal scale is non-negative; a positive engine scale "
                f"(x10^{dtype.scale} multiplier) cannot be represented — "
                "rescale the column first")
        digits = {dt.TypeId.DECIMAL32: 9, dt.TypeId.DECIMAL64: 18,
                  dt.TypeId.DECIMAL128: 38}[tid]
        extra = {"precision": digits, "scale": -dtype.scale}
        return TK_DECIMAL, extra
    raise NotImplementedError(f"ORC writer does not support {dtype!r}")


_TS_UNIT_NS = {
    dt.TypeId.TIMESTAMP_SECONDS: 1_000_000_000,
    dt.TypeId.TIMESTAMP_MILLISECONDS: 1_000_000,
    dt.TypeId.TIMESTAMP_MICROSECONDS: 1_000,
    dt.TypeId.TIMESTAMP_NANOSECONDS: 1,
}


def _encode_nanos(nanos) -> list:
    """ORC nano encoding: strip trailing decimal zeros, record the count.

    nanos are the *signed* sub-second remainder (the ORC-C++ convention:
    seconds truncate toward zero, remainder keeps the sign); python's
    two's-complement bitwise ops make ``(nb << 3) | zbits`` correct for
    negative values, matching what the C++ writer emits."""
    out = []
    for nv in nanos:
        nv = int(nv)
        if nv == 0:
            out.append(0)
            continue
        a = abs(nv)
        zeros = 0
        while zeros < 7 and a % 10 == 0:
            a //= 10
            zeros += 1
        if zeros >= 2:
            nb = a if nv > 0 else -a
            out.append((nb << 3) | (zeros - 1))
        else:
            out.append(nv << 3)
    return out


def _subtree_size(col) -> int:
    """Number of ORC column ids this column's type subtree occupies."""
    if col.dtype.id == dt.TypeId.LIST:
        return 1 + _subtree_size(col.children[0])
    if col.dtype.id == dt.TypeId.STRUCT:
        return 1 + sum(_subtree_size(c) for c in col.children)
    return 1


def _append_types(types: bytearray, col, next_id: int,
                  field_names=None) -> int:
    """Pre-order Type messages for one column's subtree (matches the id
    assignment `_emit_streams` uses); ``next_id`` is this column's id,
    returns the next free id."""
    d = col.dtype
    tmsg = bytearray()
    if d.id == dt.TypeId.LIST:
        _pb_varint(tmsg, 1, TK_LIST)
        _pb_varint(tmsg, 2, next_id + 1)  # element is the next pre-order id
        _pb_bytes(types, 4, bytes(tmsg))
        return _append_types(types, col.children[0], next_id + 1)
    if d.id == dt.TypeId.STRUCT:
        _pb_varint(tmsg, 1, TK_STRUCT)
        fid = next_id + 1
        for c in col.children:
            _pb_varint(tmsg, 2, fid)
            fid += _subtree_size(c)
        names = field_names or [f"f{i}" for i in range(len(col.children))]
        for nm in names:
            _pb_bytes(tmsg, 3, nm.encode())
        _pb_bytes(types, 4, bytes(tmsg))
        nid = next_id + 1
        for c in col.children:
            nid = _append_types(types, c, nid)
        return nid
    kind, extra = _orc_type(d)
    _pb_varint(tmsg, 1, kind)
    if "precision" in extra:
        _pb_varint(tmsg, 5, extra["precision"])
        _pb_varint(tmsg, 6, extra["scale"])
    _pb_bytes(types, 4, bytes(tmsg))
    return next_id + 1


def _emit_streams(col, cid: int, out: list) -> int:
    """Append (cid, stream_kind, raw) entries for this column subtree in
    pre-order id order; returns the next free column id.

    ORC nesting contract (mirrored from the reader,
    io/orc.py _decode_column TK_LIST/TK_STRUCT): a LIST's LENGTH stream and
    a STRUCT's children carry entries only for PRESENT parent rows, and a
    LIST's element column covers the concatenated elements of present rows.
    """
    d = col.dtype
    valid = None
    if col.validity is not None:
        v = np.asarray(col.validity)
        if not v.all():
            valid = v
    if d.id == dt.TypeId.LIST:
        if valid is not None:
            out.append((cid, SK_PRESENT, _bool_rle(valid)))
        offs = np.asarray(col.offsets, np.int64)
        lens = np.diff(offs)
        child = col.children[0]
        if valid is not None:
            # elements of non-present rows must not reach the child column;
            # vectorized repeat/cumsum index (same pattern as strings.split)
            lens = lens[valid]
            starts = offs[:-1][valid]
            total = int(lens.sum())
            pos = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=pos[1:])
            el_idx = (np.repeat(starts, lens) + np.arange(total)
                      - np.repeat(pos[:-1], lens)).astype(np.int32)
            child = gather_column(child, el_idx)
        out.append((cid, SK_LENGTH, _int_rle_v1(lens, signed=False)))
        return _emit_streams(child, cid + 1, out)
    if d.id == dt.TypeId.STRUCT:
        if valid is not None:
            out.append((cid, SK_PRESENT, _bool_rle(valid)))
        nid = cid + 1
        for c in col.children:
            if valid is not None:
                c = gather_column(c, np.flatnonzero(valid).astype(np.int32))
            nid = _emit_streams(c, nid, out)
        return nid
    for kind, raw in _column_streams(col, d):
        out.append((cid, kind, raw))
    return cid + 1


def _column_streams(col, dtype: dt.DType) -> list[tuple[int, bytes]]:
    """-> [(stream_kind, raw bytes)] for one column over one stripe."""
    streams = []
    valid = None
    if col.validity is not None:
        valid = np.asarray(col.validity)
        if valid.all():
            valid = None
    if valid is not None:
        streams.append((SK_PRESENT, _bool_rle(valid)))

    tid = dtype.id
    if dtype.is_string:
        chars = np.asarray(col.data, np.uint8).tobytes()
        offs = np.asarray(col.offsets, np.int64)
        lens = np.diff(offs)
        if valid is None:
            data = chars
            use_lens = lens
        else:
            keep = np.flatnonzero(valid)
            data = b"".join(chars[offs[i]:offs[i + 1]] for i in keep)
            use_lens = lens[keep]
        streams.append((SK_DATA, data))
        streams.append((SK_LENGTH, _int_rle_v1(use_lens, signed=False)))
        return streams

    vals = np.asarray(col.data)
    if valid is not None and tid != dt.TypeId.DECIMAL128:
        vals = vals[valid]

    if tid == dt.TypeId.BOOL8:
        streams.append((SK_DATA, _bool_rle(vals.astype(np.bool_))))
    elif tid == dt.TypeId.INT8:
        streams.append((SK_DATA, _byte_rle(vals.view(np.uint8))))
    elif tid in (dt.TypeId.INT16, dt.TypeId.INT32, dt.TypeId.INT64,
                 dt.TypeId.UINT8, dt.TypeId.UINT16, dt.TypeId.UINT32,
                 dt.TypeId.UINT64, dt.TypeId.TIMESTAMP_DAYS):
        streams.append((SK_DATA, _int_rle_v1(vals, signed=True)))
    elif tid == dt.TypeId.FLOAT32:
        streams.append((SK_DATA, vals.astype("<f4").tobytes()))
    elif tid == dt.TypeId.FLOAT64:
        streams.append((SK_DATA, vals.view(np.float64).astype("<f8")
                        .tobytes()))
    elif dtype.is_timestamp:
        unit = _TS_UNIT_NS[tid]
        secs, nanos = [], []
        for v in vals:
            t_ns = int(v) * unit
            q, r = divmod(abs(t_ns), 1_000_000_000)  # trunc toward zero
            if t_ns < 0:
                q, r = -q, -r
            secs.append(q - _ORC_EPOCH_S)
            nanos.append(r)
        streams.append((SK_DATA, _int_rle_v1(secs, signed=True)))
        streams.append((SK_SECONDARY, _int_rle_v1(
            _encode_nanos(nanos), signed=False)))
    elif dtype.is_decimal:
        scale = -dtype.scale  # _orc_type rejected positive engine scales
        if tid == dt.TypeId.DECIMAL128:
            limbs = vals.reshape(-1, 2)
            mants = [(int(hi) << 64) | (int(lo) & ((1 << 64) - 1))
                     for lo, hi in limbs]
            if valid is not None:
                mants = [m for m, ok in zip(mants, valid) if ok]
        else:
            mants = [int(v) for v in vals]
        blob = bytearray()
        for m in mants:
            _varint_bigint(blob, m)
        streams.append((SK_DATA, bytes(blob)))
        streams.append((SK_SECONDARY, _int_rle_v1(
            np.full(len(mants), scale, np.int64), signed=True)))
    else:
        raise NotImplementedError(f"ORC writer does not support {dtype!r}")
    return streams


try:
    import pyarrow as _pa
    _SNAPPY_C = _pa.Codec("snappy")  # compressor (decoder lives in io.snappy)
    _ZSTD_C = _pa.Codec("zstd")
except Exception:  # pragma: no cover - pyarrow is baked into this env
    _SNAPPY_C = None
    _ZSTD_C = None


def _compress_stream(raw: bytes, kind: int, block: int) -> bytes:
    if kind == COMP_NONE:
        return raw
    out = bytearray()
    for i in range(0, len(raw), block):
        chunk = raw[i:i + block]
        if kind == COMP_ZLIB:
            comp = zlib.compressobj(6, zlib.DEFLATED, -15)
            cb = comp.compress(chunk) + comp.flush()
        elif kind == COMP_ZSTD:
            cb = _ZSTD_C.compress(chunk).to_pybytes()
        else:  # COMP_SNAPPY
            cb = _SNAPPY_C.compress(chunk).to_pybytes()
        if len(cb) < len(chunk):
            h = len(cb) << 1
            out += bytes([h & 0xFF, (h >> 8) & 0xFF, (h >> 16) & 0xFF])
            out += cb
        else:  # store original
            h = (len(chunk) << 1) | 1
            out += bytes([h & 0xFF, (h >> 8) & 0xFF, (h >> 16) & 0xFF])
            out += chunk
    return bytes(out)


def write_orc(table: Table, path, compression: str = "none",
              stripe_rows: int = 1 << 20,
              struct_fields: dict | None = None):
    """Write a Table as an ORC 0.12 file readable by any ORC reader.

    LIST and STRUCT columns write the standard nested ORC encoding
    (pre-order column ids, LENGTH streams and present-row-filtered
    children).  ``struct_fields`` maps a STRUCT column name to its field
    names (children are unnamed in the engine's Column; default f0, f1...)."""
    kinds = {"none": COMP_NONE, "uncompressed": COMP_NONE,
             "zlib": COMP_ZLIB}
    if _SNAPPY_C is not None:
        kinds["snappy"] = COMP_SNAPPY
    if _ZSTD_C is not None:
        kinds["zstd"] = COMP_ZSTD
    comp = kinds[compression.lower()]
    block = 64 * 1024
    names = [nm or f"c{i}" for i, nm in enumerate(
        table.names or [f"c{i}" for i in range(table.num_columns)])]
    n = table.num_rows

    # types: struct root (id 0) + pre-order subtree per column (LIST and
    # STRUCT columns occupy one id per nested node, like ORC-C++)
    types = bytearray()
    root = bytearray()
    _pb_varint(root, 1, TK_STRUCT)
    cid = 1
    top_ids = []
    for c in table.columns:
        top_ids.append(cid)
        cid += _subtree_size(c)
    total_ids = cid  # including root
    for i in top_ids:
        _pb_varint(root, 2, i)
    for nm in names:
        _pb_bytes(root, 3, nm.encode())
    _pb_bytes(types, 4, bytes(root))  # footer field 4 = repeated Type
    nid = 1
    for c, nm in zip(table.columns, names):
        nid = _append_types(types, c, nid,
                            (struct_fields or {}).get(nm))

    body = bytearray()
    body += _MAGIC  # header
    stripes_meta = []
    for a in range(0, n, stripe_rows):
        b = min(a + stripe_rows, n)
        nrows = b - a
        sliced = [gather_column(c, np.arange(a, b)) if (a, b) != (0, n)
                  else c for c in table.columns]
        offset = len(body)
        sfooter = bytearray()
        data_blobs = []
        entries = []
        for c, top_id in zip(sliced, top_ids):
            _emit_streams(c, top_id, entries)
        for scid, kind, raw in entries:
            blob = _compress_stream(raw, comp, block)
            smsg = bytearray()
            _pb_varint(smsg, 1, kind)
            _pb_varint(smsg, 2, scid)
            _pb_varint(smsg, 3, len(blob))
            _pb_bytes(sfooter, 1, bytes(smsg))
            data_blobs.append(blob)
        for _ in range(total_ids):  # encodings: DIRECT for every id
            emsg = bytearray()
            _pb_varint(emsg, 1, 0)
            _pb_bytes(sfooter, 2, bytes(emsg))
        _pb_bytes(sfooter, 3, b"UTC")  # writer timezone
        data = b"".join(data_blobs)
        sf = _compress_stream(bytes(sfooter), comp, block)
        body += data + sf
        smeta = bytearray()
        _pb_varint(smeta, 1, offset)
        _pb_varint(smeta, 2, 0)            # index length (no row index)
        _pb_varint(smeta, 3, len(data))
        _pb_varint(smeta, 4, len(sf))
        _pb_varint(smeta, 5, nrows)
        stripes_meta.append(bytes(smeta))

    footer = bytearray()
    _pb_varint(footer, 1, 3)               # headerLength = len("ORC")
    _pb_varint(footer, 2, len(body))       # contentLength
    for sm in stripes_meta:
        _pb_bytes(footer, 3, sm)
    footer += types
    _pb_varint(footer, 6, n)               # numberOfRows
    _pb_varint(footer, 8, 0)               # rowIndexStride: none
    fblob = _compress_stream(bytes(footer), comp, block)

    ps = bytearray()
    _pb_varint(ps, 1, len(fblob))          # footerLength
    _pb_varint(ps, 2, comp)                # compression
    _pb_varint(ps, 3, block)               # compressionBlockSize
    _enc_varint(ps, (4 << 3) | 2)          # version: packed [0, 12]
    _enc_varint(ps, 2)
    ps += bytes([0, 12])
    _pb_varint(ps, 5, 0)                   # metadataLength
    _pb_varint(ps, 6, 1)                   # writerVersion
    _pb_bytes(ps, 8000, _MAGIC)            # magic
    if len(ps) > 255:
        raise AssertionError("postscript too long")

    with open(path, "wb") as f:
        f.write(bytes(body) + fblob + bytes(ps) + bytes([len(ps)]))
