"""CSV ingest: delimited text -> device Tables.

Rounds out the libcudf-I/O role (libcudf ships a CSV reader next to
parquet/ORC; the reference consumes it through the cudf Java surface the
jar grafts in — SURVEY §2.2).  Tokenizing is delegated to pandas' C parser
(a linked native parser, the same division of labor as the snappy codec);
the engine owns the schema mapping onto its dtype system, Spark-style null
semantics, and device placement.
"""

from __future__ import annotations

import os

import numpy as np

from .. import dtypes as dt
from ..columnar import Column, Table

def _infer_dtype(np_dtype) -> dt.DType | None:
    try:
        return dt.from_numpy_dtype(np_dtype)  # one mapping for the package
    except (TypeError, KeyError, ValueError):
        return None


def read_csv(path, *, delimiter: str = ",", header: bool = True,
             names: list | None = None, dtypes: dict | None = None,
             na_values=("", "null", "NULL")) -> Table:
    """Read a CSV file into a device Table.

    ``dtypes`` maps column name -> engine DType to force a type; unforced
    columns infer int64 / float64 / bool / string like Spark's CSV schema
    inference.
    """
    import pandas as pd

    # forced integer/bool columns parse through pandas' NULLABLE extension
    # dtypes (plain int dtypes reject NA at the C-parser level; float
    # promotion would corrupt int64 values beyond 2^53)
    def _pd_dtype(v: dt.DType):
        if v.is_string:
            return "str"  # disable inference: preserve the raw text
        if v.id == dt.TypeId.BOOL8:
            return "boolean"  # nullable extension bool
        name = np.dtype(v.storage).name
        if name.startswith(("int", "uint")):
            return name.replace("int", "Int").replace("uInt", "UInt")
        return name

    # nullable extension backend: an int column with NAs stays Int64 (plain
    # numpy inference would promote to float64 and corrupt int64 > 2^53)
    df = pd.read_csv(
        os.fspath(path), sep=delimiter,
        header=0 if header else None, names=names,
        na_values=list(na_values), keep_default_na=True,
        dtype_backend="numpy_nullable",
        dtype={k: _pd_dtype(v) for k, v in (dtypes or {}).items()})
    cols, out_names = [], []
    for name in df.columns:
        ser = df[name]
        out_names.append(str(name))
        forced = (dtypes or {}).get(name)
        is_stringy = (ser.dtype == object or str(ser.dtype) in
                      ("string", "str") or ser.dtype.kind in ("O", "U", "T"))
        if forced is None and is_stringy:
            non_null = [v for v in ser if not pd.isna(v)]
            if non_null and all(isinstance(v, (bool, np.bool_))
                                for v in non_null):
                # bool column with nulls: pandas falls back to object
                cols.append(Column.from_pylist(
                    [None if pd.isna(v) else bool(v) for v in ser],
                    dtype=dt.BOOL8))
                continue
        if (forced is not None and forced.is_string) or \
                (forced is None and is_stringy):
            cols.append(Column.from_pylist(
                [None if pd.isna(v) else str(v) for v in ser]))
            continue
        valid = None
        if ser.isna().any():
            valid = (~ser.isna()).to_numpy()
        if forced is not None:
            arr = ser.to_numpy(dtype=forced.storage,
                               na_value=0 if valid is not None else None)
            dtype = forced
        else:
            # strip the nullable-extension wrapper: "Int64" -> int64 etc.
            base = str(ser.dtype)
            np_name = {"boolean": "bool"}.get(base, base.lower())
            try:
                np_dtype = np.dtype(np_name)
            except TypeError:
                np_dtype = None
            dtype = _infer_dtype(np_dtype) if np_dtype is not None else None
            if dtype is None:
                raise NotImplementedError(
                    f"CSV column {name!r} of dtype {ser.dtype} is unsupported")
            arr = ser.to_numpy(dtype=dtype.storage,
                               na_value=0 if valid is not None else None)
        cols.append(Column.from_numpy(np.asarray(arr, dtype.storage),
                                      validity=valid, dtype=dtype))
    return Table(cols, out_names)

def write_csv(table: Table, path, *, delimiter: str = ",",
              header: bool = True, na_rep: str = "") -> None:
    """Write a Table as delimited text (the libcudf CSV-writer role).

    Values render with Spark-compatible text forms: booleans as
    true/false, decimals with their scale applied, timestamps as raw
    integer ticks (the engine has no session timezone); nulls as
    ``na_rep``.  Quoting: fields containing the delimiter, quotes or
    newlines are double-quoted with embedded quotes doubled (RFC 4180).
    """
    import decimal as _decimal

    def render(v):
        if v is None:
            return na_rep
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, float):
            if v != v:
                # Spark's text form.  CSV cannot distinguish NaN from null
                # without reader options (Spark: nanValue); this package's
                # read_csv also maps it to null — a lossy round trip.
                return "NaN"
            if v == float("inf"):
                return "Infinity"
            if v == float("-inf"):
                return "-Infinity"
            return repr(v)
        if isinstance(v, _decimal.Decimal):
            return format(v, "f")
        s = str(v)
        return s

    def quote(s: str) -> str:
        if any(ch in s for ch in (delimiter, '"', "\n", "\r")):
            return '"' + s.replace('"', '""') + '"'
        return s

    cols = [c.to_pylist() for c in table.columns]
    names = [nm or f"c{i}" for i, nm in enumerate(
        table.names or [f"c{i}" for i in range(table.num_columns)])]
    with open(path, "w", newline="", encoding="utf-8") as f:
        if header:
            f.write(delimiter.join(quote(nm) for nm in names) + "\n")
        for row in zip(*cols) if cols else ():
            f.write(delimiter.join(quote(render(v)) for v in row) + "\n")
