"""ORC scan path: postscript/footer → stripes → device columns.

Completes the libcudf I/O role (SURVEY.md §2.2: "Parquet/ORC I/O",
build-libcudf.xml:37-50) next to io.parquet: entropy decode — protobuf
metadata, RLEv1/v2 runs, compression chunks — runs vectorized on the host
(per *run*, not per value), and decoded buffers land on the device as jax
arrays inside `Column`s.  Stripes are the natural chunk unit, so the
chunked reader bounds device memory per pass the same way the reference
bounds row-conversion batches (row_conversion.cu:476-511).

Supported surface:
- types: BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, STRING, CHAR,
  VARCHAR, BINARY (as LIST<UINT8>), DATE, TIMESTAMP(_INSTANT),
  DECIMAL (≤18 digits → DECIMAL32/64, >18 → DECIMAL128), LIST of the above
- encodings: DIRECT, DIRECT_V2, DICTIONARY, DICTIONARY_V2; integer runs in
  both RLEv1 and RLEv2 (SHORT_REPEAT / DIRECT / PATCHED_BASE / DELTA)
- codecs: NONE, ZLIB (raw deflate), SNAPPY
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..columnar import Column, Table
from . import snappy as _snappy_py

_MAGIC = b"ORC"

# orc_proto CompressionKind
COMP_NONE, COMP_ZLIB, COMP_SNAPPY, COMP_LZO, COMP_LZ4, COMP_ZSTD = range(6)

# orc_proto Type.Kind
(TK_BOOLEAN, TK_BYTE, TK_SHORT, TK_INT, TK_LONG, TK_FLOAT, TK_DOUBLE,
 TK_STRING, TK_BINARY, TK_TIMESTAMP, TK_LIST, TK_MAP, TK_STRUCT, TK_UNION,
 TK_DECIMAL, TK_DATE, TK_VARCHAR, TK_CHAR) = range(18)
TK_TIMESTAMP_INSTANT = 18

# orc_proto Stream.Kind
SK_PRESENT, SK_DATA, SK_LENGTH, SK_DICTIONARY_DATA = 0, 1, 2, 3
SK_SECONDARY, SK_ROW_INDEX = 5, 6

# orc_proto ColumnEncoding.Kind
ENC_DIRECT, ENC_DICTIONARY, ENC_DIRECT_V2, ENC_DICTIONARY_V2 = range(4)

# seconds from the unix epoch to the ORC timestamp epoch (2015-01-01 UTC)
_ORC_EPOCH_S = 1420070400

# ---------------------------------------------------------------------------
# minimal protobuf wire decoder (ORC metadata is proto2; we read by field id,
# mirroring how io.thrift reads parquet's compact-protocol structs)

_uvarint = _snappy_py._uvarint  # one LEB128 decoder for the whole io package


def _pb_fields(buf) -> dict:
    """Decode one message to {field_number: [raw values]}.

    varint fields decode to int; length-delimited to bytes (nested messages
    re-parsed on demand); 64/32-bit to int.
    """
    out: dict = {}
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _uvarint(buf, pos)
        fnum, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _uvarint(buf, pos)
        elif wire == 2:
            ln, pos = _uvarint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 1:
            val = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wire == 5:
            val = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        out.setdefault(fnum, []).append(val)
    return out


def _pb_u(f, n, default=0):
    return f[n][0] if n in f else default


def _pb_packed(f, n) -> list:
    """repeated varint field: packed (one bytes blob) or unpacked."""
    vals = []
    for v in f.get(n, ()):
        if isinstance(v, (bytes, memoryview)):
            pos = 0
            while pos < len(v):
                x, pos = _uvarint(v, pos)
                vals.append(x)
        else:
            vals.append(v)
    return vals


# ---------------------------------------------------------------------------
# compression framing: each stream is a sequence of chunks with a 3-byte
# little-endian header (length << 1 | is_original)

def _decompress_chunk(chunk: bytes, kind: int) -> bytes:
    if kind == COMP_ZLIB:  # raw deflate, no zlib header
        return zlib.decompressobj(-15).decompress(chunk)
    if kind == COMP_SNAPPY:
        # raw-format snappy carries its decompressed length in the preamble;
        # pyarrow's Codec insists on being told, so use the in-repo decoder
        return _snappy_py.decompress(chunk)
    if kind == COMP_ZSTD:
        import pyarrow as _pa
        # stream-decode: pyarrow's one-shot Codec.decompress demands an
        # explicit decompressed size, which ORC chunk framing doesn't carry
        with _pa.input_stream(_pa.BufferReader(chunk),
                              compression="zstd") as st:
            return st.read()
    raise NotImplementedError(
        f"unsupported ORC compression kind {kind} "
        "(NONE, ZLIB, SNAPPY and ZSTD are supported)")


def _decode_stream(raw: bytes, kind: int) -> bytes:
    if kind == COMP_NONE:
        return raw
    out = []
    pos, n = 0, len(raw)
    while pos + 3 <= n:
        h = raw[pos] | (raw[pos + 1] << 8) | (raw[pos + 2] << 16)
        pos += 3
        ln, original = h >> 1, h & 1
        chunk = raw[pos:pos + ln]
        pos += ln
        out.append(bytes(chunk) if original else
                   _decompress_chunk(bytes(chunk), kind))
    return b"".join(out)


# ---------------------------------------------------------------------------
# run-length decoders.  Python touches one iteration per run; values inside
# a run are produced by numpy.

def _byte_rle(buf: bytes, n: int) -> np.ndarray:
    out = np.empty(n + 131, np.uint8)  # headroom: one run may overshoot
    total = pos = 0
    while total < n:
        h = buf[pos]
        pos += 1
        if h < 128:  # run of h+3 copies of the next byte
            run = h + 3
            out[total:total + run] = buf[pos]
            pos += 1
            total += run
        else:  # 256-h literal bytes
            cnt = 256 - h
            out[total:total + cnt] = np.frombuffer(buf, np.uint8, cnt, pos)
            pos += cnt
            total += cnt
    return out[:n]


def _bool_rle(buf: bytes, n: int) -> np.ndarray:
    """Boolean run: byte-RLE bytes expanded to MSB-first bits."""
    nbytes = (n + 7) // 8
    by = _byte_rle(buf, nbytes)
    return np.unpackbits(by)[:n].astype(np.bool_)


def _zigzag(v: np.ndarray) -> np.ndarray:
    u = v.astype(np.uint64)
    return ((u >> np.uint64(1)) ^ (np.uint64(0) - (u & np.uint64(1)))) \
        .view(np.int64)


def _int_rle_v1(buf: bytes, n: int, signed: bool) -> np.ndarray:
    out = np.empty(n + 131, np.int64)
    total = pos = 0
    while total < n:
        h = buf[pos]
        pos += 1
        if h < 128:  # run: length h+3, signed byte delta, varint base
            run = h + 3
            delta = buf[pos] - 256 if buf[pos] > 127 else buf[pos]
            pos += 1
            base, pos = _uvarint(buf, pos)
            if signed:
                base = (base >> 1) ^ -(base & 1)
            # wrap to int64 exactly like the literal path: an unsigned
            # varint base >= 2**63 (e.g. two's-complement negative nanos
            # emitted as a run) must not overflow the int64 assignment
            base = int(np.int64(np.uint64(base & (2**64 - 1))))
            out[total:total + run] = base + delta * np.arange(run, dtype=np.int64)
            total += run
        else:  # 256-h literal varints
            cnt = 256 - h
            for i in range(cnt):
                v, pos = _uvarint(buf, pos)
                if signed:
                    v = (v >> 1) ^ -(v & 1)
                out[total + i] = np.int64(np.uint64(v & (2**64 - 1)))
            total += cnt
    return out[:n]


# RLEv2 5-bit width code → bit width ("fixed bit sizes" table)
_FBS = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]


def _closest_fbs(bits: int) -> int:
    for w in _FBS:
        if w >= bits:
            return w
    return 64


def _unpack_be(buf: bytes, pos: int, count: int, width: int):
    """Big-endian (MSB-first) bit-unpack of `count` values at `width` bits."""
    if width == 0:
        return np.zeros(count, np.uint64), pos
    nbytes = (count * width + 7) // 8
    raw = np.frombuffer(buf, np.uint8, nbytes, pos)
    bits = np.unpackbits(raw)[:count * width].reshape(count, width)
    w = (np.uint64(1) << np.arange(width - 1, -1, -1, dtype=np.uint64))
    vals = (bits.astype(np.uint64) * w).sum(axis=1, dtype=np.uint64)
    return vals, pos + nbytes


def _int_rle_v2(buf: bytes, n: int, signed: bool) -> np.ndarray:
    out = np.empty(n + 512, np.int64)
    total = pos = 0
    while total < n:
        b0 = buf[pos]
        enc = (b0 >> 6) & 3
        if enc == 0:  # SHORT_REPEAT
            width = ((b0 >> 3) & 7) + 1
            run = (b0 & 7) + 3
            pos += 1
            val = int.from_bytes(buf[pos:pos + width], "big")
            pos += width
            if signed:
                val = (val >> 1) ^ -(val & 1)
            out[total:total + run] = np.int64(np.uint64(val & (2**64 - 1)))
            total += run
        elif enc == 1:  # DIRECT
            width = _FBS[(b0 >> 1) & 0x1F]
            run = ((b0 & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_be(buf, pos, run, width)
            if signed:
                vals = _zigzag(vals)
            out[total:total + run] = vals.view(np.int64) if not signed else vals
            total += run
        elif enc == 2:  # PATCHED_BASE
            width = _FBS[(b0 >> 1) & 0x1F]
            run = ((b0 & 1) << 8 | buf[pos + 1]) + 1
            b2, b3 = buf[pos + 2], buf[pos + 3]
            bw = ((b2 >> 5) & 7) + 1          # base width, bytes
            pw = _FBS[b2 & 0x1F]              # patch value width, bits
            pgw = ((b3 >> 5) & 7) + 1         # patch gap width, bits
            pll = b3 & 0x1F                   # patch list length
            pos += 4
            raw_base = int.from_bytes(buf[pos:pos + bw], "big")
            pos += bw
            sign_mask = 1 << (bw * 8 - 1)     # base is sign-magnitude
            base = -(raw_base & (sign_mask - 1)) if raw_base & sign_mask \
                else raw_base
            vals, pos = _unpack_be(buf, pos, run, width)
            if pll:
                cw = _closest_fbs(pgw + pw)
                patches, pos = _unpack_be(buf, pos, pll, cw)
                idx = 0
                pmask = np.uint64((1 << pw) - 1)
                for p in patches:
                    idx += int(p) >> pw
                    vals[idx] |= (p & pmask) << np.uint64(width)
            out[total:total + run] = vals.view(np.int64) + base
            total += run
        else:  # DELTA
            wcode = (b0 >> 1) & 0x1F
            width = 0 if wcode == 0 else _FBS[wcode]
            run = ((b0 & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            base, pos = _uvarint(buf, pos)
            if signed:
                base = (base >> 1) ^ -(base & 1)
            else:
                base = np.int64(np.uint64(base & (2**64 - 1)))
            dbase, pos = _uvarint(buf, pos)
            dbase = (dbase >> 1) ^ -(dbase & 1)  # delta base always signed
            if width == 0:  # fixed-delta run
                out[total:total + run] = \
                    int(base) + int(dbase) * np.arange(run, dtype=np.int64)
            else:
                deltas, pos = _unpack_be(buf, pos, max(run - 2, 0), width)
                seq = np.empty(run, np.int64)
                seq[0] = base
                if run > 1:
                    seq[1] = int(base) + int(dbase)
                    if run > 2:
                        d = deltas.view(np.int64)
                        step = d if dbase >= 0 else -d
                        seq[2:] = seq[1] + np.cumsum(step)
                out[total:total + run] = seq
            total += run
    return out[:n]


def _int_rle(buf, n, signed, v2: bool) -> np.ndarray:
    if n == 0:
        return np.zeros(0, np.int64)
    return _int_rle_v2(buf, n, signed) if v2 else _int_rle_v1(buf, n, signed)


def _rescale_mantissa(m: int, s: int, tgt: int) -> int:
    d = tgt - s
    if d >= 0:
        return m * 10 ** d
    p = 10 ** -d
    q, r = divmod(abs(m), p)
    if r:
        raise ValueError(
            f"ORC decimal value scale {s} does not fit column scale {tgt}")
    return q if m >= 0 else -q


def _varint_bigints(buf: bytes, n: int) -> list:
    """n unbounded zigzag varints (DECIMAL mantissas) as python ints."""
    out = []
    pos = 0
    for _ in range(n):
        result = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        out.append((result >> 1) ^ -(result & 1))
    return out


# ---------------------------------------------------------------------------
# file metadata

@dataclass
class _OrcType:
    kind: int
    subtypes: list
    field_names: list
    precision: int
    scale: int


@dataclass
class _Stripe:
    offset: int
    index_length: int
    data_length: int
    footer_length: int
    num_rows: int


def _map_dtype(t: _OrcType) -> dt.DType:
    if t.kind == TK_BOOLEAN:
        return dt.BOOL8
    if t.kind == TK_BYTE:
        return dt.INT8
    if t.kind == TK_SHORT:
        return dt.INT16
    if t.kind == TK_INT:
        return dt.INT32
    if t.kind == TK_LONG:
        return dt.INT64
    if t.kind == TK_FLOAT:
        return dt.FLOAT32
    if t.kind == TK_DOUBLE:
        return dt.FLOAT64
    if t.kind in (TK_STRING, TK_VARCHAR, TK_CHAR):
        return dt.STRING
    if t.kind == TK_DATE:
        return dt.TIMESTAMP_DAYS
    if t.kind in (TK_TIMESTAMP, TK_TIMESTAMP_INSTANT):
        return dt.TIMESTAMP_NANOSECONDS
    if t.kind == TK_DECIMAL:
        ours = -t.scale  # engine scale is the cudf convention (negated)
        if t.precision <= 9:
            return dt.decimal32(ours)
        if t.precision <= 18:
            return dt.decimal64(ours)
        return dt.decimal128(ours)
    if t.kind == TK_BINARY:
        return dt.DType(dt.TypeId.LIST)
    if t.kind == TK_LIST:
        return dt.DType(dt.TypeId.LIST)
    if t.kind == TK_STRUCT:
        return dt.DType(dt.TypeId.STRUCT)
    raise NotImplementedError(f"unsupported ORC type kind {t.kind}")


class ORCFile:
    """Parsed ORC file: schema + stripe metadata + per-stripe decode."""

    def __init__(self, path):
        self.path = path
        with open(path, "rb") as f:
            f.seek(0, 2)
            fsize = f.tell()
            tail_len = min(fsize, 16 * 1024)
            f.seek(fsize - tail_len)
            tail = f.read(tail_len)
        if fsize < 16:
            raise ValueError("not an ORC file (truncated)")
        ps_len = tail[-1]
        ps = _pb_fields(tail[-1 - ps_len:-1])
        if _pb_u(ps, 8000, b"") not in (b"ORC", b""):
            raise ValueError("not an ORC file (bad postscript magic)")
        self.compression = _pb_u(ps, 2, COMP_NONE)
        self.compression_block = _pb_u(ps, 3, 256 * 1024)
        footer_len = _pb_u(ps, 1)
        meta_len = _pb_u(ps, 5)
        need = 1 + ps_len + footer_len + meta_len
        if need > tail_len:
            with open(path, "rb") as f:
                f.seek(fsize - need)
                tail = f.read(need)
        footer_raw = tail[len(tail) - 1 - ps_len - footer_len:
                          len(tail) - 1 - ps_len]
        meta_raw = tail[len(tail) - 1 - ps_len - footer_len - meta_len:
                        len(tail) - 1 - ps_len - footer_len]
        footer = _pb_fields(_decode_stream(footer_raw, self.compression))
        # Metadata section: per-stripe, per-column statistics (min/max) —
        # the stripe-pruning analog of parquet row-group footer stats
        self._stripe_stats: list = []
        if meta_len:
            meta = _pb_fields(_decode_stream(meta_raw, self.compression))
            for ss in meta.get(1, ()):  # repeated StripeStatistics
                cols = [_pb_fields(cs) for cs in _pb_fields(ss).get(1, ())]
                self._stripe_stats.append(cols)
        self.num_rows = _pb_u(footer, 6)
        self.types = [
            _OrcType(kind=_pb_u(tf, 1), subtypes=_pb_packed(tf, 2),
                     field_names=[bytes(x).decode() for x in tf.get(3, ())],
                     precision=_pb_u(tf, 5), scale=_pb_u(tf, 6))
            for tf in (_pb_fields(t) for t in footer.get(4, ()))
        ]
        self.stripes = [
            _Stripe(offset=_pb_u(sf, 1), index_length=_pb_u(sf, 2),
                    data_length=_pb_u(sf, 3), footer_length=_pb_u(sf, 4),
                    num_rows=_pb_u(sf, 5))
            for sf in (_pb_fields(s) for s in footer.get(3, ()))
        ]
        root = self.types[0] if self.types else None
        if root is None or root.kind != TK_STRUCT:
            raise NotImplementedError("ORC root type must be a struct")
        self.column_names = root.field_names
        self.column_ids = root.subtypes
        self.schema = [(nm, _map_dtype(self.types[cid]))
                       for nm, cid in zip(self.column_names, self.column_ids)]

    @property
    def num_stripes(self) -> int:
        return len(self.stripes)

    def stripe_stat_range(self, stripe: int, column: str):
        """(min, max) for a column over one stripe, or None if absent.

        Int/date stats are zigzag varints; double stats are fixed64 IEEE;
        string stats are raw bytes (returned as str)."""
        if stripe >= len(self._stripe_stats):
            return None
        try:
            cid = self.column_ids[self.column_names.index(column)]
        except ValueError:
            return None
        cols = self._stripe_stats[stripe]
        if cid >= len(cols):
            return None
        cs = cols[cid]

        def zz(v):
            return (v >> 1) ^ -(v & 1)

        if 2 in cs:  # IntStatistics {1 min, 2 max} (sint64)
            f = _pb_fields(cs[2][0])
            if 1 in f and 2 in f:
                return zz(f[1][0]), zz(f[2][0])
        if 3 in cs:  # DoubleStatistics {1 min, 2 max} (fixed64 doubles)
            import struct as _struct
            f = _pb_fields(cs[3][0])
            if 1 in f and 2 in f:
                return (_struct.unpack("<d", int(f[1][0]).to_bytes(8, "little"))[0],
                        _struct.unpack("<d", int(f[2][0]).to_bytes(8, "little"))[0])
        if 4 in cs:  # StringStatistics {1 min, 2 max} (bytes)
            f = _pb_fields(cs[4][0])
            if 1 in f and 2 in f:
                return bytes(f[1][0]).decode(), bytes(f[2][0]).decode()
        if 7 in cs:  # DateStatistics {1 min, 2 max} (sint32 days)
            f = _pb_fields(cs[7][0])
            if 1 in f and 2 in f:
                return zz(f[1][0]), zz(f[2][0])
        return None

    # -- stripe decode -----------------------------------------------------
    def _stripe_streams(self, st: _Stripe):
        """→ ({(column, kind): bytes}, {column: (encoding, dict_size)})"""
        with open(self.path, "rb") as f:
            f.seek(st.offset)
            blob = f.read(st.index_length + st.data_length + st.footer_length)
        sf = _pb_fields(_decode_stream(
            blob[st.index_length + st.data_length:], self.compression))
        streams = []
        for s in sf.get(1, ()):
            fields = _pb_fields(s)
            streams.append((_pb_u(fields, 1), _pb_u(fields, 2),
                            _pb_u(fields, 3)))
        encodings = {}
        for col, e in enumerate(sf.get(2, ())):
            fields = _pb_fields(e)
            encodings[col] = (_pb_u(fields, 1), _pb_u(fields, 2))
        bufs = {}
        off = 0
        for kind, col, length in streams:
            if kind not in (SK_ROW_INDEX, SK_PRESENT, SK_DATA, SK_LENGTH,
                            SK_DICTIONARY_DATA, SK_SECONDARY):
                off += length
                continue
            if kind != SK_ROW_INDEX:
                bufs[(col, kind)] = _decode_stream(
                    blob[off:off + length], self.compression)
            off += length
        return bufs, encodings

    def _decode_column(self, cid: int, bufs, encodings, n: int):
        """Decode column `cid` over `n` rows → Column (host numpy inside)."""
        t = self.types[cid]
        enc, dict_size = encodings.get(cid, (ENC_DIRECT, 0))
        v2 = enc in (ENC_DIRECT_V2, ENC_DICTIONARY_V2)
        present = bufs.get((cid, SK_PRESENT))
        valid = _bool_rle(present, n) if present is not None else None
        nvals = int(valid.sum()) if valid is not None else n
        data = bufs.get((cid, SK_DATA), b"")

        def expand(dense: np.ndarray, fill=0) -> np.ndarray:
            """Scatter per-present values back to row positions."""
            if valid is None:
                return dense
            out = np.full(n, fill, dense.dtype)
            out[valid] = dense
            return out

        k = t.kind
        if k == TK_BOOLEAN:
            vals = _bool_rle(data, nvals).astype(np.uint8)
            return Column.fixed(dt.BOOL8, expand(vals), valid)
        if k in (TK_BYTE,):
            vals = _byte_rle(data, nvals).view(np.int8)
            return Column.fixed(dt.INT8, expand(vals), valid)
        if k in (TK_SHORT, TK_INT, TK_LONG):
            vals = _int_rle(data, nvals, signed=True, v2=v2)
            odt = {TK_SHORT: dt.INT16, TK_INT: dt.INT32, TK_LONG: dt.INT64}[k]
            return Column.fixed(odt, expand(vals).astype(odt.storage), valid)
        if k == TK_FLOAT:
            vals = np.frombuffer(data, "<f4", nvals)
            return Column.fixed(dt.FLOAT32, expand(vals), valid)
        if k == TK_DOUBLE:
            vals = np.frombuffer(data, "<f8", nvals)
            return Column.fixed(dt.FLOAT64, expand(vals), valid)
        if k == TK_DATE:
            vals = _int_rle(data, nvals, signed=True, v2=v2)
            return Column.fixed(dt.TIMESTAMP_DAYS,
                                expand(vals).astype(np.int32), valid)
        if k in (TK_TIMESTAMP, TK_TIMESTAMP_INSTANT):
            secs = _int_rle(data, nvals, signed=True, v2=v2)
            nraw = _int_rle(bufs.get((cid, SK_SECONDARY), b""), nvals,
                            signed=False, v2=v2)
            zeros = (nraw & 7).astype(np.int64)
            nanos = (nraw >> 3) * np.where(zeros != 0, 10 ** (zeros + 1), 1)
            # seconds are the floor relative to the ORC epoch and nanos the
            # positive sub-second remainder (verified against the
            # pyarrow/ORC-C++ oracle incl. pre-2015 and pre-1970 instants)
            total = (secs + _ORC_EPOCH_S) * 1_000_000_000 + nanos
            return Column.fixed(dt.TIMESTAMP_NANOSECONDS, expand(total), valid)
        if k in (TK_STRING, TK_VARCHAR, TK_CHAR):
            if enc in (ENC_DICTIONARY, ENC_DICTIONARY_V2):
                lengths = _int_rle(bufs.get((cid, SK_LENGTH), b""), dict_size,
                                   signed=False, v2=v2)
                dchars = np.frombuffer(
                    bufs.get((cid, SK_DICTIONARY_DATA), b""), np.uint8)
                doffs = np.zeros(dict_size + 1, np.int64)
                np.cumsum(lengths, out=doffs[1:])
                idx = _int_rle(data, nvals, signed=False, v2=v2)
                vlens = lengths[idx] if dict_size else np.zeros(nvals, np.int64)
                row_lens = expand(vlens)
                offsets = np.zeros(n + 1, np.int64)
                np.cumsum(row_lens, out=offsets[1:])
                # vectorized dict materialization: for each output byte, its
                # source index = dict start of its row + offset within the row
                # (cumsum-reset arange, the same pattern as the offsets)
                starts = doffs[idx] if dict_size else np.zeros(nvals, np.int64)
                total_chars = int(vlens.sum())
                pos_in_val = np.arange(total_chars, dtype=np.int64) - \
                    np.repeat(np.concatenate([[0], np.cumsum(vlens)[:-1]]),
                              vlens)
                src = np.repeat(starts, vlens) + pos_in_val
                chars = dchars[src] if total_chars else np.zeros(0, np.uint8)
            else:
                lengths = _int_rle(bufs.get((cid, SK_LENGTH), b""), nvals,
                                   signed=False, v2=v2)
                row_lens = expand(lengths)
                offsets = np.zeros(n + 1, np.int64)
                np.cumsum(row_lens, out=offsets[1:])
                chars = np.frombuffer(data, np.uint8, int(offsets[-1]))
            if offsets[-1] > np.iinfo(np.int32).max:
                raise ValueError("ORC string column exceeds int32 offsets")
            return Column.string(chars, offsets.astype(np.int32), valid)
        if k == TK_DECIMAL:
            mants = _varint_bigints(data, nvals)
            scales = _int_rle(bufs.get((cid, SK_SECONDARY), b""), nvals,
                              signed=True, v2=v2)
            # rescale each value to the column scale — integer math only: a
            # value with more fractional digits than the column scale can
            # only be kept if the extra digits are zero
            tgt = t.scale
            mants = [_rescale_mantissa(m, int(s), tgt) if s != tgt else m
                     for m, s in zip(mants, scales)]
            odt = _map_dtype(t)
            if odt.id == dt.TypeId.DECIMAL128:
                dense = np.array(mants, object)
                if valid is not None:
                    full = np.zeros(n, object)
                    full[valid] = dense
                    dense = full
                return Column.fixed(odt, dense, valid)
            dense = np.array(mants, np.int64)
            return Column.fixed(odt, expand(dense).astype(odt.storage), valid)
        if k == TK_BINARY:
            lengths = _int_rle(bufs.get((cid, SK_LENGTH), b""), nvals,
                               signed=False, v2=v2)
            row_lens = expand(lengths)
            offsets = np.zeros(n + 1, np.int64)
            np.cumsum(row_lens, out=offsets[1:])
            raw = np.frombuffer(data, np.uint8, int(offsets[-1]))
            child = Column.fixed(dt.UINT8, raw)
            return Column.list_(child, offsets.astype(np.int32), valid)
        if k == TK_LIST:
            lengths = _int_rle(bufs.get((cid, SK_LENGTH), b""), nvals,
                               signed=False, v2=v2)
            row_lens = expand(lengths)
            offsets = np.zeros(n + 1, np.int64)
            np.cumsum(row_lens, out=offsets[1:])
            child = self._decode_column(t.subtypes[0], bufs, encodings,
                                        int(offsets[-1]))
            return Column.list_(child, offsets.astype(np.int32), valid)
        if k == TK_STRUCT:
            # ORC struct fields carry one entry per PRESENT struct row;
            # decode each field over nvals rows, then scatter back to the
            # n-row frame (null struct rows -> null field rows)
            kids = [self._decode_column(sub, bufs, encodings, nvals)
                    for sub in t.subtypes]
            if valid is not None:
                from ..ops.selection import gather_column
                idx = np.full(n, -1, np.int32)
                idx[valid] = np.arange(nvals, dtype=np.int32)
                kids = [gather_column(c, jnp.asarray(idx)) for c in kids]
            return Column(dt.DType(dt.TypeId.STRUCT),
                          validity=None if valid is None
                          else jnp.asarray(valid),
                          children=tuple(kids))
        raise NotImplementedError(f"unsupported ORC type kind {k}")

    def _empty_column(self, cid: int) -> Column:
        t = self.types[cid]
        odt = _map_dtype(t)
        if odt.is_string:
            return Column.string(np.zeros(0, np.uint8), np.zeros(1, np.int32))
        if odt.id == dt.TypeId.LIST:
            child = (Column.fixed(dt.UINT8, np.zeros(0, np.uint8))
                     if t.kind == TK_BINARY
                     else self._empty_column(t.subtypes[0]))
            return Column.list_(child, np.zeros(1, np.int32))
        if odt.id == dt.TypeId.DECIMAL128:
            return Column.fixed(odt, np.zeros((0, 2), np.int64))
        if odt.id == dt.TypeId.STRUCT:
            return Column(odt, children=tuple(self._empty_column(s)
                                              for s in t.subtypes))
        return Column.fixed(odt, np.zeros(0, odt.storage))

    def read_stripe(self, i: int, columns=None) -> Table:
        st = self.stripes[i]
        bufs, encodings = self._stripe_streams(st)
        names, cols = [], []
        for nm, cid in zip(self.column_names, self.column_ids):
            if columns is not None and nm not in columns:
                continue
            names.append(nm)
            cols.append(self._decode_column(cid, bufs, encodings,
                                            st.num_rows))
        return Table(cols, names)

    def read(self, columns=None) -> Table:
        parts = [self.read_stripe(i, columns)
                 for i in range(self.num_stripes)]
        if not parts:
            names, cols = [], []
            for nm, cid in zip(self.column_names, self.column_ids):
                if columns is not None and nm not in columns:
                    continue
                names.append(nm)
                cols.append(self._empty_column(cid))
            return Table(cols, names)
        if len(parts) == 1:
            return parts[0]
        names = parts[0].names
        cols = [_concat_columns([p.columns[i] for p in parts])
                for i in range(len(names))]
        return Table(cols, names)


def _concat_columns(parts: list) -> Column:
    """Host-side stripe concat (the scan path is host-bound anyway)."""
    any_valid = any(p.validity is not None for p in parts)
    valid = np.concatenate([p.validity_numpy() for p in parts]) \
        if any_valid else None
    d0 = parts[0].dtype
    if d0.is_string or d0.id == dt.TypeId.LIST:
        offs = [np.asarray(parts[0].offsets, np.int64)]
        base = int(offs[0][-1])
        for p in parts[1:]:
            o = np.asarray(p.offsets, np.int64)
            offs.append(o[1:] + base)
            base += int(o[-1])
        offsets = np.concatenate(offs)
        if offsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("concatenated column exceeds int32 offsets")
        if d0.is_string:
            chars = np.concatenate([np.asarray(p.data) for p in parts])
            return Column.string(chars, offsets.astype(np.int32), valid)
        child = _concat_columns([p.children[0] for p in parts])
        return Column.list_(child, offsets.astype(np.int32), valid)
    data = np.concatenate([np.asarray(p.data) for p in parts])
    return Column(d0, data=jnp.asarray(data),
                  validity=None if valid is None else jnp.asarray(valid))


def read_orc(path, columns=None) -> Table:
    """Read a whole ORC file into a device Table."""
    return ORCFile(path).read(columns)


class ORCChunkedReader:
    """Iterate an ORC file stripe-at-a-time as device Tables.

    Stripes are ORC's native bounded unit (the writer sizes them to
    `stripe_size`), so the per-pass device working set is bounded by file
    layout exactly like ParquetChunkedReader bounds it by byte budget.
    ``predicate=(column, lo, hi)`` prunes whole stripes via the metadata
    section's stripe statistics before any stream decode (the parquet
    footer-stats analog); either bound may be None.
    """

    def __init__(self, path, columns=None, predicate: tuple | None = None):
        self.file = ORCFile(path)
        self.columns = columns
        self.predicate = predicate
        if predicate is not None:
            col, lo, hi = predicate
            if col not in self.file.column_names:
                raise KeyError(f"predicate column {col!r} not in "
                               f"{list(self.file.column_names)}")
            # bound types must be comparable with the column's stat kind
            rng = next((r for r in (self.file.stripe_stat_range(i, col)
                                    for i in range(self.file.num_stripes))
                        if r is not None), None)
            if rng is not None:
                for b in (lo, hi):
                    if b is not None:
                        try:
                            b < rng[0]  # noqa: B015 — comparability probe
                        except TypeError:
                            raise TypeError(
                                f"predicate bound {b!r} is not comparable "
                                f"with {col!r} statistics ({type(rng[0]).__name__})")

    def _pruned(self, i: int) -> bool:
        if self.predicate is None:
            return False
        col, lo, hi = self.predicate
        rng = self.file.stripe_stat_range(i, col)
        if rng is None:
            return False
        smin, smax = rng
        return (hi is not None and smin > hi) or \
               (lo is not None and smax < lo)

    def __iter__(self):
        for i in range(self.file.num_stripes):
            if self._pruned(i):
                continue
            yield self.file.read_stripe(i, self.columns)
