"""Parquet scan path: footer → row groups → device columns, in bounded chunks.

The role the reference fills with libcudf's GPU parquet reader + the
ChunkedParquet north-star op (BASELINE.md configs; build-libcudf.xml:37-50):
get columnar files into device columns without ever materializing more than
a bounded slice.  The TPU split of labor differs from the CUDA one by
design — byte-granular entropy decode (snappy, varints, RLE runs) is hostile
to the MXU/VPU and runs on the host in vectorized numpy, while everything
from dictionary gather onward (the O(rows) work) lands on the device as jax
arrays.  Chunking bounds the *device* working set per pass exactly like the
reference bounds row-conversion batches to 2^31 bytes
(row_conversion.cu:476-511), with the pass budget configurable like the
chunked-reader read limit.

Supported surface (flat schemas — the Spark-SQL scan shape):
- physical types: BOOLEAN, INT32, INT64, INT96 (legacy timestamps), FLOAT,
  DOUBLE, BYTE_ARRAY, FIXED_LEN_BYTE_ARRAY (decimals)
- logical/converted: UTF8→STRING, DATE, TIMESTAMP millis/micros/nanos,
  signed/unsigned int widths, DECIMAL on int32/int64/FLBA (precision ≤ 18)
- encodings: PLAIN, RLE (booleans + levels), PLAIN_DICTIONARY /
  RLE_DICTIONARY, data pages V1 + V2
- codecs: UNCOMPRESSED, SNAPPY
"""

from __future__ import annotations

import mmap
import os
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..columnar import Column, Table
from ..utils import faults, metrics, timeline
from ..utils.errors import retry_call
from . import snappy
from .thrift import decode_struct

_MAGIC = b"PAR1"

# parquet physical types (parquet.thrift Type)
PT_BOOLEAN, PT_INT32, PT_INT64, PT_INT96 = 0, 1, 2, 3
PT_FLOAT, PT_DOUBLE, PT_BYTE_ARRAY, PT_FLBA = 4, 5, 6, 7

# encodings (parquet.thrift Encoding)
ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_RLE_DICTIONARY = 8

# codecs (parquet.thrift CompressionCodec)
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP, CODEC_ZSTD = 0, 1, 2, 6

# page types (parquet.thrift PageType)
PAGE_DATA, PAGE_INDEX, PAGE_DICTIONARY, PAGE_DATA_V2 = 0, 1, 2, 3

_PLAIN_NP = {
    PT_INT32: np.dtype("<i4"),
    PT_INT64: np.dtype("<i8"),
    PT_FLOAT: np.dtype("<f4"),
    PT_DOUBLE: np.dtype("<f8"),
}


_uvarint = snappy._uvarint  # one LEB128 decoder for the whole io package

# Accelerated codec, when a native one is linked (the reference does the
# same with nvcomp inside libcudf); io.snappy stays as the self-contained
# fallback and keeps its own tests.
try:
    import pyarrow as _pa
    _SNAPPY_NATIVE = _pa.Codec("snappy")
except Exception:  # pragma: no cover - pyarrow is baked into this env
    _SNAPPY_NATIVE = None


def _decompress(page: bytes, codec: int, uncompressed_size: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return page
    if codec == CODEC_SNAPPY:
        if _SNAPPY_NATIVE is not None:
            out = _SNAPPY_NATIVE.decompress(
                page, decompressed_size=uncompressed_size).to_pybytes()
        else:
            # literal-only pages (high-entropy / dict-encoded data) collapse
            # to slice copies; anything else hits the byte-exact decoder
            out = snappy.decompress_fast(page)
        if len(out) != uncompressed_size:
            raise ValueError("snappy page size mismatch")
        return out
    if codec == CODEC_GZIP:
        import zlib
        out = zlib.decompress(page, 16 + 15)  # gzip-framed
        if len(out) != uncompressed_size:
            raise ValueError("gzip page size mismatch")
        return out
    if codec == CODEC_ZSTD:
        import pyarrow as _pa
        out = _pa.Codec("zstd").decompress(
            page, decompressed_size=uncompressed_size).to_pybytes()
        if len(out) != uncompressed_size:
            raise ValueError("zstd page size mismatch")
        return out
    raise NotImplementedError(
        f"unsupported parquet codec {codec} "
        "(UNCOMPRESSED, SNAPPY, GZIP and ZSTD are supported)")


def _rle_bitpacked_hybrid(buf, bit_width: int, num_values: int) -> np.ndarray:
    """Decode parquet's RLE/bit-packed hybrid to int32[num_values].

    Bit-packed runs unpack via np.unpackbits (LSB-first groups of 8), RLE
    runs become np.full — both vectorized; python touches one iteration per
    *run*, not per value.
    """
    if bit_width == 0:
        return np.zeros(num_values, np.int32)
    byte_width = (bit_width + 7) // 8
    weights = (np.int64(1) << np.arange(bit_width, dtype=np.int64))
    out = []
    total = 0
    pos = 0
    n = len(buf)
    while total < num_values and pos < n:
        header, pos = _uvarint(buf, pos)
        if header & 1:  # bit-packed run: (header>>1) groups of 8 values
            groups = header >> 1
            nbytes = groups * bit_width
            chunk = np.frombuffer(buf, np.uint8, min(nbytes, n - pos), pos)
            if len(chunk) < nbytes:  # writers may truncate the last group
                chunk = np.concatenate(
                    [chunk, np.zeros(nbytes - len(chunk), np.uint8)])
            pos += nbytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(-1, bit_width).astype(np.int64) @ weights
            out.append(vals.astype(np.int32))
            total += groups * 8
        else:  # RLE run
            count = header >> 1
            val = int.from_bytes(buf[pos:pos + byte_width], "little")
            pos += byte_width
            out.append(np.full(count, val, np.int32))
            total += count
    if not out:
        return np.zeros(num_values, np.int32)
    res = out[0] if len(out) == 1 else np.concatenate(out)
    if len(res) < num_values:
        raise ValueError("truncated RLE/bit-packed run")
    return res[:num_values]


def _parse_byte_array(buf, num_values: int):
    """PLAIN BYTE_ARRAY: [u32 len][bytes]... → (chars u8[], lens i32[])."""
    lens = np.empty(num_values, np.int64)
    pieces = []
    pos = 0
    mv = memoryview(buf)
    for i in range(num_values):
        ln = int.from_bytes(mv[pos:pos + 4], "little")
        lens[i] = ln
        pieces.append(mv[pos + 4:pos + 4 + ln])
        pos += 4 + ln
    chars = np.frombuffer(b"".join(pieces), np.uint8)
    return chars, lens.astype(np.int32)


def _int96_to_ns(raw: np.ndarray) -> np.ndarray:
    """INT96 legacy timestamps: [u64 nanos-of-day][u32 julian day] → epoch ns."""
    nanos = raw[:, :8].copy().view("<u8").reshape(-1).astype(np.int64)
    jday = raw[:, 8:].copy().view("<u4").reshape(-1).astype(np.int64)
    return (jday - 2440588) * 86_400_000_000_000 + nanos


# ---------------------------------------------------------------------------
# metadata interpretation (thrift field ids from parquet-format parquet.thrift)
# ---------------------------------------------------------------------------

@dataclass
class ColumnSchema:
    name: str
    physical: int          # element physical type for LIST columns
    type_length: int
    optional: bool         # element nullability for LIST columns
    dtype: dt.DType        # element dtype for LIST columns
    is_list: bool = False  # standard 3-level LIST<element>
    list_optional: bool = False  # outer list group nullability
    is_struct: bool = False      # flat STRUCT group of leaf fields
    struct_optional: bool = False
    fields: tuple = ()           # STRUCT: leaf ColumnSchemas
    extra_def: int = 0           # def levels contributed by ancestors
                                 # (a leaf inside an optional struct has 1)
    list_levels: tuple = ()      # nested LIST: per-level group optionality,
                                 # outermost first (len >= 2 when nested;
                                 # depth-1 lists keep the legacy fields)

    @property
    def max_def(self) -> int:
        if self.list_levels:
            return sum(1 for o in self.list_levels if o) + \
                len(self.list_levels) + (1 if self.optional else 0)
        if self.is_list:
            return (1 if self.list_optional else 0) + 1 + \
                (1 if self.optional else 0)
        return self.extra_def + (1 if self.optional else 0)

    @property
    def max_rep(self) -> int:
        if self.list_levels:
            return len(self.list_levels)
        return 1 if self.is_list else 0


@dataclass
class ChunkMeta:
    schema: ColumnSchema
    codec: int
    num_values: int
    start_offset: int       # min(data_page_offset, dictionary_page_offset)
    total_compressed: int
    total_uncompressed: int
    statistics: dict | None


@dataclass
class RowGroupMeta:
    num_rows: int
    total_byte_size: int
    chunks: list = field(default_factory=list)   # parallel to file schema


def _interpret_schema_element(elem: dict) -> ColumnSchema | None:
    """SchemaElement fields: 1 type, 2 type_length, 3 repetition, 4 name,
    5 num_children, 6 converted_type, 7 scale, 8 precision, 10 logicalType."""
    name = elem.get(4, b"").decode()
    if elem.get(5):  # group node → handled by the _parse_footer tree walk
        raise NotImplementedError(
            f"nested parquet schemas are not supported (group {name!r})")
    rep = elem.get(3, 0)
    if rep == 2:  # bare REPEATED leaf: legacy 2-level list, not supported
        raise NotImplementedError(
            f"legacy unannotated repeated field {name!r} unsupported")
    phys = elem[1]
    conv = elem.get(6)
    logical = elem.get(10) or {}
    tl = elem.get(2, 0)

    def decimal_dtype():
        scale = elem.get(7, 0)
        precision = elem.get(8, 0)
        if 5 in logical:  # LogicalType.DECIMAL{1:scale, 2:precision}
            scale = logical[5].get(1, scale)
            precision = logical[5].get(2, precision)
        if precision > 18:
            raise NotImplementedError(
                f"decimal precision {precision} > 18 on {name!r}")
        # parquet scale counts digits right of the point; engine scale is the
        # power-of-ten exponent of the stored integer (cudf convention)
        ours = -scale
        return (dt.decimal32(ours) if phys == PT_INT32 and precision <= 9
                else dt.decimal64(ours))

    if phys == PT_BOOLEAN:
        out = dt.BOOL8
    elif phys == PT_INT32:
        if conv == 5 or 5 in logical:
            out = decimal_dtype()
        elif conv == 6 or 6 in logical:  # DATE
            out = dt.TIMESTAMP_DAYS
        elif conv in (15, 16):  # INT_8 / INT_16
            out = dt.INT8 if conv == 15 else dt.INT16
        elif conv in (11, 12, 13):  # UINT_8/16/32
            out = {11: dt.UINT8, 12: dt.UINT16, 13: dt.UINT32}[conv]
        elif 10 in logical:  # LogicalType.INTEGER{1:bitWidth, 2:isSigned}
            bw, signed = logical[10].get(1, 32), logical[10].get(2, True)
            out = {(8, True): dt.INT8, (16, True): dt.INT16,
                   (32, True): dt.INT32, (8, False): dt.UINT8,
                   (16, False): dt.UINT16, (32, False): dt.UINT32}[(bw, signed)]
        else:
            out = dt.INT32
    elif phys == PT_INT64:
        if conv == 5 or 5 in logical:
            out = decimal_dtype()
        elif conv == 9:  # TIMESTAMP_MILLIS
            out = dt.TIMESTAMP_MILLISECONDS
        elif conv == 10:  # TIMESTAMP_MICROS
            out = dt.TIMESTAMP_MICROSECONDS
        elif 8 in logical:  # LogicalType.TIMESTAMP{2: unit{1|2|3: {}}}
            unit = logical[8].get(2, {})
            out = (dt.TIMESTAMP_MILLISECONDS if 1 in unit
                   else dt.TIMESTAMP_NANOSECONDS if 3 in unit
                   else dt.TIMESTAMP_MICROSECONDS)
        elif conv == 14 or (10 in logical and not logical[10].get(2, True)):
            out = dt.UINT64
        else:
            out = dt.INT64
    elif phys == PT_INT96:
        out = dt.TIMESTAMP_NANOSECONDS
    elif phys == PT_FLOAT:
        out = dt.FLOAT32
    elif phys == PT_DOUBLE:
        out = dt.FLOAT64
    elif phys == PT_BYTE_ARRAY:
        out = dt.STRING
    elif phys == PT_FLBA:
        if conv == 5 or 5 in logical:
            out = decimal_dtype()
        else:
            raise NotImplementedError(
                f"FIXED_LEN_BYTE_ARRAY without DECIMAL on {name!r}")
    else:
        raise NotImplementedError(f"parquet physical type {phys}")
    return ColumnSchema(name, phys, tl, rep == 1, out)


def _parse_list_group(elems, i: int) -> tuple[ColumnSchema, int]:
    """Standard 3-level LIST at elems[i]: optional group (LIST) { repeated
    group g { <element> } } → (list ColumnSchema, next index).

    The element may itself be a LIST group (nested lists to any depth);
    per-level group optionality is collected into ``list_levels``."""
    levels = []
    name = elems[i].get(4, b"").decode()
    while True:
        outer = elems[i]
        if outer.get(5) != 1 or i + 2 >= len(elems):
            raise NotImplementedError(f"unsupported LIST shape at {name!r}")
        mid = elems[i + 1]
        if mid.get(3, 0) != 2 or mid.get(5) != 1:
            raise NotImplementedError(
                f"LIST {name!r} without the standard repeated middle group")
        levels.append(outer.get(3, 0) == 1)
        elem = elems[i + 2]
        if not elem.get(5):
            break
        conv, logical = elem.get(6), elem.get(10) or {}
        if not (conv == 3 or 3 in logical):
            raise NotImplementedError(
                f"non-LIST group element under {name!r}")
        i += 2  # descend into the nested LIST group
    es = _interpret_schema_element(elem)
    return ColumnSchema(
        name, es.physical, es.type_length, optional=es.optional,
        dtype=es.dtype, is_list=True, list_optional=levels[0],
        list_levels=tuple(levels) if len(levels) > 1 else ()), i + 3


def _parse_struct_group(elems, i: int) -> tuple[ColumnSchema, int]:
    """Flat STRUCT group at elems[i]: group { <leaf fields> } -> schema.

    Each leaf field carries ``extra_def`` = 1 when the struct itself is
    optional (its definition levels then distinguish struct-null from
    field-null).  Nested groups inside the struct are not supported."""
    outer = elems[i]
    name = outer.get(4, b"").decode()
    if outer.get(3, 0) == 2:
        # legacy 2-level REPEATED group (old Hive/Impala list-of-struct):
        # silently reading it as a flat struct would decode garbage — the
        # repetition levels would never be stripped
        raise NotImplementedError(
            f"legacy repeated group {name!r} (unannotated list) unsupported")
    s_opt = outer.get(3, 0) == 1
    nfields = outer.get(5, 0)
    fields = []
    i += 1
    for _ in range(nfields):
        e = elems[i]
        if e.get(5):
            raise NotImplementedError(
                f"nested group inside struct {name!r} unsupported")
        fs = _interpret_schema_element(e)
        fields.append(ColumnSchema(
            fs.name, fs.physical, fs.type_length, optional=fs.optional,
            dtype=fs.dtype, extra_def=1 if s_opt else 0))
        i += 1
    return ColumnSchema(name, 0, 0, optional=False,
                        dtype=dt.DType(dt.TypeId.STRUCT), is_struct=True,
                        struct_optional=s_opt, fields=tuple(fields)), i


def _parse_footer(meta: dict):
    """FileMetaData: 2 schema, 3 num_rows, 4 row_groups."""
    elems = meta[2]
    root = elems[0]
    schema = []
    i, nchildren = 1, root.get(5, 0)
    for _ in range(nchildren):
        e = elems[i]
        if e.get(5):  # group node: LIST or flat STRUCT
            conv, logical = e.get(6), e.get(10) or {}
            if conv == 3 or 3 in logical:  # ConvertedType/LogicalType LIST
                cs, i = _parse_list_group(elems, i)
                schema.append(cs)
                continue
            cs, i = _parse_struct_group(elems, i)
            schema.append(cs)
            continue
        schema.append(_interpret_schema_element(e))
        i += 1
    by_name = {s.name: i for i, s in enumerate(schema)}
    groups = []
    for rg in meta.get(4, []):
        g = RowGroupMeta(num_rows=rg[3], total_byte_size=rg.get(2, 0),
                         chunks=[None] * len(schema))
        for cc in rg[1]:
            cm = cc[3]  # ColumnMetaData
            path = [p.decode() for p in cm[3]]
            if path[0] not in by_name:
                raise NotImplementedError(f"column path {path} unsupported")
            idx = by_name[path[0]]
            if schema[idx].is_struct:
                if len(path) != 2:
                    raise NotImplementedError(
                        f"column path {path} unsupported")
                fi = [f.name for f in schema[idx].fields].index(path[1])
                if g.chunks[idx] is None:
                    g.chunks[idx] = [None] * len(schema[idx].fields)
                dict_off = cm.get(11)
                data_off = cm[9]
                start = (data_off if dict_off is None
                         else min(dict_off, data_off))
                g.chunks[idx][fi] = ChunkMeta(
                    schema=schema[idx].fields[fi], codec=cm[4],
                    num_values=cm[5], start_offset=start,
                    total_compressed=cm[7], total_uncompressed=cm[6],
                    statistics=cm.get(12))
                continue
            if (len(path) != 1) != schema[idx].is_list:
                raise NotImplementedError(f"column path {path} unsupported")
            dict_off = cm.get(11)
            data_off = cm[9]
            start = data_off if dict_off is None else min(dict_off, data_off)
            g.chunks[idx] = ChunkMeta(
                schema=schema[idx], codec=cm[4], num_values=cm[5],
                start_offset=start, total_compressed=cm[7],
                total_uncompressed=cm.get(6, 0), statistics=cm.get(12))
        if any(c is None for c in g.chunks):
            raise ValueError("row group missing a column chunk")
        groups.append(g)
    return schema, int(meta[3]), groups


# ---------------------------------------------------------------------------
# page + chunk decode (host side)
# ---------------------------------------------------------------------------

@dataclass
class _HostColumn:
    """Decoded chunk in host form, sliceable without touching the device."""
    schema: ColumnSchema
    values: np.ndarray | None      # fixed-width dense values (nulls zeroed)
    chars: np.ndarray | None       # STRING: char buffer (nulls contribute 0 B)
    offsets: np.ndarray | None     # STRING: int32[n+1]
    validity: np.ndarray | None    # bool[n] or None
    child: "_HostColumn | None" = None   # LIST: element chunk
    loffsets: np.ndarray | None = None   # LIST: int32[n+1] row offsets
    children: "list | None" = None       # STRUCT: field chunks

    @property
    def num_rows(self):
        if self.children is not None:
            return self.children[0].num_rows
        if self.loffsets is not None:
            return len(self.loffsets) - 1
        return (len(self.offsets) - 1 if self.offsets is not None
                else len(self.values))

    def nbytes_estimate(self):
        if self.children is not None:
            per = sum(c.nbytes_estimate() for c in self.children)
        elif self.loffsets is not None:
            per = self.child.nbytes_estimate() + self.loffsets.nbytes
        else:
            per = (self.chars.nbytes + self.offsets.nbytes
                   if self.chars is not None else self.values.nbytes)
        if self.validity is not None:
            per += self.validity.nbytes
        return per

    def slice(self, a: int, b: int) -> "_HostColumn":
        if self.children is not None:
            return _HostColumn(self.schema, None, None, None,
                               None if self.validity is None
                               else self.validity[a:b],
                               children=[c.slice(a, b)
                                         for c in self.children])
        if self.loffsets is not None:
            lo = self.loffsets[a:b + 1]
            child = self.child.slice(int(lo[0]), int(lo[-1]))
            return _HostColumn(self.schema, None, None, None,
                               None if self.validity is None
                               else self.validity[a:b],
                               child=child,
                               loffsets=(lo - lo[0]).astype(np.int32))
        if self.offsets is not None:
            offs = self.offsets[a:b + 1]
            chars = self.chars[offs[0]:offs[-1]]
            return _HostColumn(self.schema, None, chars,
                               (offs - offs[0]).astype(np.int32),
                               None if self.validity is None
                               else self.validity[a:b])
        return _HostColumn(self.schema, self.values[a:b], None, None,
                           None if self.validity is None
                           else self.validity[a:b])

    def to_column(self) -> Column:
        s = self.schema
        if self.children is not None:
            return Column(dt.DType(dt.TypeId.STRUCT),
                          validity=None if self.validity is None
                          else jnp.asarray(self.validity),
                          children=tuple(c.to_column()
                                         for c in self.children))
        if self.loffsets is not None:
            return Column.list_(self.child.to_column(), self.loffsets,
                                self.validity)
        if s.dtype.is_string:
            return Column.string(self.chars, self.offsets, self.validity)
        return Column.fixed(s.dtype, self.values, self.validity)


def _decode_plain(schema: ColumnSchema, buf: bytes, nvals: int):
    """PLAIN-encoded values → fixed np array or (chars, lens) for strings."""
    phys = schema.physical
    if phys == PT_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(buf, np.uint8, (nvals + 7) // 8),
                             bitorder="little")
        return bits[:nvals].astype(np.uint8)
    if phys in _PLAIN_NP:
        return np.frombuffer(buf, _PLAIN_NP[phys], nvals)
    if phys == PT_INT96:
        raw = np.frombuffer(buf, np.uint8, nvals * 12).reshape(nvals, 12)
        return _int96_to_ns(raw)
    if phys == PT_BYTE_ARRAY:
        return _parse_byte_array(buf, nvals)
    if phys == PT_FLBA:
        w = schema.type_length
        raw = np.frombuffer(buf, np.uint8, nvals * w).reshape(nvals, w)
        # parquet decimals are big-endian two's-complement
        acc = np.zeros(nvals, np.int64)
        for col in range(w):
            acc = (acc << 8) | raw[:, col]
        if w < 8:  # sign-extend
            sign_bit = np.int64(1) << (8 * w - 1)
            acc = (acc ^ sign_bit) - sign_bit
        return acc
    raise NotImplementedError(f"PLAIN decode for physical type {phys}")


def _gather_dict(schema: ColumnSchema, dict_vals, idx: np.ndarray):
    if schema.physical == PT_BYTE_ARRAY:
        chars, lens = dict_vals
        if idx.size == 0:  # all-null page: nothing to gather
            return np.zeros(0, np.uint8), np.zeros(0, lens.dtype)
        offs = np.zeros(len(lens) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        # vectorized string gather: out[i] spans chars[offs[idx[i]] : +len]
        sel_lens = lens[idx].astype(np.int64)
        total = int(sel_lens.sum())
        out_starts = np.concatenate(([0], np.cumsum(sel_lens)[:-1]))
        pos = (np.arange(total, dtype=np.int64)
               - np.repeat(out_starts, sel_lens)
               + np.repeat(offs[idx], sel_lens))
        return chars[pos], lens[idx]
    return dict_vals[idx]


def _scatter_values(s: ColumnSchema, n: int, vals, mask):
    """Scatter the non-null value stream into ``n`` slots (nulls zeroed).

    ``mask`` (bool[n] or None) marks slots that carry a real value.
    Returns the (values, chars, offsets) triple of a _HostColumn.
    """
    if s.physical == PT_BYTE_ARRAY:
        chars = np.concatenate([v[0] for v in vals]) if vals else \
            np.zeros(0, np.uint8)
        nn_lens = np.concatenate([v[1] for v in vals]) if vals else \
            np.zeros(0, np.int32)
        lens = np.zeros(n, np.int64)
        if mask is None:
            lens[:] = nn_lens
        else:
            lens[mask] = nn_lens
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        if offsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("string chunk exceeds int32 offsets; "
                             "use a smaller row-group size")
        return None, chars, offsets.astype(np.int32)
    storage = s.dtype.storage
    dense = np.zeros(n, storage)
    nn = np.concatenate([np.asarray(v, storage) for v in vals]) if vals \
        else np.zeros(0, storage)
    if mask is None:
        dense[:] = nn
    else:
        dense[mask] = nn
    return dense, None, None


class _ChunkDecoder:
    """Decode one column chunk's page stream into a _HostColumn."""

    def __init__(self, fbuf, meta: ChunkMeta):
        self.fbuf = fbuf
        self.meta = meta
        self.schema = meta.schema
        self.dict_vals = None

    def run(self) -> _HostColumn:
        meta = self.meta
        pos = meta.start_offset
        end = meta.start_offset + meta.total_compressed
        remaining = meta.num_values
        reps, defs, vals = [], [], []
        while remaining > 0 and pos < end:
            header, pos = decode_struct(self.fbuf, pos)
            ptype = header[1]
            comp = header[3]
            page = bytes(self.fbuf[pos:pos + comp])
            pos += comp
            if ptype == PAGE_DICTIONARY:
                data = _decompress(page, meta.codec, header[2])
                nd = header[7][1]  # DictionaryPageHeader.num_values
                self.dict_vals = _decode_plain(self.schema, data, nd)
            elif ptype == PAGE_DATA:
                r, d, v, nv = self._data_page_v1(page, header)
                reps.append(r)
                defs.append(d)
                vals.append(v)
                remaining -= nv
            elif ptype == PAGE_DATA_V2:
                r, d, v, nv = self._data_page_v2(page, header)
                reps.append(r)
                defs.append(d)
                vals.append(v)
                remaining -= nv
            elif ptype == PAGE_INDEX:
                continue
            else:
                raise NotImplementedError(f"page type {ptype}")
        # struct assembly (in _decode_group) reads the raw def stream to
        # recover struct-level nullity from any one field's levels; only
        # struct members (extra_def > 0) pay for the extra copy
        self.def_stream = (np.concatenate([d for d in defs])
                           if self.schema.extra_def and defs
                           and defs[0] is not None else None)
        if self.schema.list_levels:
            return self._assemble_list_nested(reps, defs, vals)
        if self.schema.is_list:
            return self._assemble_list(reps, defs, vals)
        return self._assemble(defs, vals)

    # DataPageHeader: 1 num_values, 2 encoding, 3 def-level enc, 4 rep enc
    def _data_page_v1(self, page: bytes, header: dict):
        data = _decompress(page, self.meta.codec, header[2])
        ph = header[5]
        nv = ph[1]
        enc = ph[2]
        pos = 0
        r = None
        if self.schema.max_rep:
            if ph.get(4, ENC_RLE) != ENC_RLE:
                raise NotImplementedError("non-RLE repetition levels")
            ln = int.from_bytes(data[0:4], "little")
            r = _rle_bitpacked_hybrid(data[4:4 + ln],
                                      self.schema.max_rep.bit_length(), nv)
            pos = 4 + ln
        d = None
        md = self.schema.max_def
        if md:
            if ph.get(3, ENC_RLE) != ENC_RLE:
                raise NotImplementedError("non-RLE definition levels")
            ln = int.from_bytes(data[pos:pos + 4], "little")
            d = _rle_bitpacked_hybrid(data[pos + 4:pos + 4 + ln],
                                      md.bit_length(), nv)
            pos += 4 + ln
        nnon = nv if d is None else int((d == md).sum())
        v = self._values(data[pos:], enc, nnon)
        return r, d, v, nv

    # DataPageHeaderV2: 1 num_values, 2 num_nulls, 3 num_rows, 4 encoding,
    # 5 def-levels byte len, 6 rep-levels byte len, 7 is_compressed
    def _data_page_v2(self, page: bytes, header: dict):
        ph = header[8]
        nv, nnulls, enc = ph[1], ph[2], ph[4]
        dlen, rlen = ph.get(5, 0), ph.get(6, 0)
        # V2 layout: repetition levels first, then definition levels
        r = None
        if self.schema.max_rep:
            r = _rle_bitpacked_hybrid(page[0:rlen],
                                      self.schema.max_rep.bit_length(), nv)
        d = None
        md = self.schema.max_def
        if md:
            d = _rle_bitpacked_hybrid(page[rlen:rlen + dlen],
                                      md.bit_length(), nv)
        body = page[dlen + rlen:]
        if ph.get(7, True):
            body = _decompress(body, self.meta.codec,
                               header[2] - dlen - rlen)
        nnon = (nv - nnulls) if d is None else int((d == md).sum())
        v = self._values(body, enc, nnon)
        return r, d, v, nv

    def _values(self, data: bytes, enc: int, nnon: int):
        if enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            if self.dict_vals is None:
                raise ValueError("dictionary-encoded page before dictionary")
            bw = data[0]
            idx = _rle_bitpacked_hybrid(data[1:], bw, nnon)
            return _gather_dict(self.schema, self.dict_vals, idx)
        if enc == ENC_PLAIN:
            return _decode_plain(self.schema, data, nnon)
        if enc == ENC_RLE and self.schema.physical == PT_BOOLEAN:
            ln = int.from_bytes(data[0:4], "little")
            return _rle_bitpacked_hybrid(data[4:4 + ln], 1, nnon) \
                .astype(np.uint8)
        raise NotImplementedError(f"value encoding {enc}")

    def _assemble(self, defs, vals) -> _HostColumn:
        s = self.schema
        md = s.max_def
        nrows = sum((len(d) if d is not None else
                     (len(v[1]) if isinstance(v, tuple) else len(v)))
                    for d, v in zip(defs, vals))
        if all(d is None for d in defs):
            valid = None
        else:
            valid = np.concatenate(
                [d == md if d is not None else
                 np.ones(len(v[1]) if isinstance(v, tuple) else len(v),
                         np.bool_)
                 for d, v in zip(defs, vals)])
        values, chars, offsets = _scatter_values(s, nrows, vals, valid)
        return _HostColumn(s, values, chars, offsets, valid)

    def _assemble_list(self, reps, defs, vals) -> _HostColumn:
        """Reconstruct LIST<element> rows from rep/def level streams.

        Level semantics for the standard 3-level shape (max_def = md):
        rep 0 starts a row; def >= elem-slot level means an element slot
        exists (null element iff def < md); lower defs encode an empty list
        or a null row.
        """
        s = self.schema
        md = s.max_def
        slot_def = md - (1 if s.optional else 0)
        rep = np.concatenate([r for r in reps]) if reps else \
            np.zeros(0, np.int32)
        deff = np.concatenate([d for d in defs]) if defs else \
            np.zeros(0, np.int32)
        starts = np.flatnonzero(rep == 0)
        nrows = len(starts)
        row_valid = None
        if s.list_optional:
            row_valid = deff[starts] >= 1
            if bool(row_valid.all()):
                row_valid = None
        slot = deff >= slot_def
        cum = np.concatenate(([0], np.cumsum(slot.astype(np.int64))))
        seg_end = np.concatenate((starts[1:], [len(rep)])) if nrows else \
            np.zeros(0, np.int64)
        lengths = cum[seg_end] - cum[starts]
        loffsets = np.zeros(nrows + 1, np.int64)
        np.cumsum(lengths, out=loffsets[1:])
        if loffsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("list chunk exceeds int32 offsets; "
                             "use a smaller row-group size")
        nslots = int(loffsets[-1])
        elem_valid = None
        if s.optional:
            elem_valid = (deff == md)[slot]
            if bool(elem_valid.all()):
                elem_valid = None
        ecs = ColumnSchema(s.name + ".element", s.physical, s.type_length,
                           optional=s.optional, dtype=s.dtype)
        values, chars, offsets = _scatter_values(s, nslots, vals, elem_valid)
        child = _HostColumn(ecs, values, chars, offsets, elem_valid)
        return _HostColumn(s, None, None, None, row_valid, child=child,
                           loffsets=loffsets.astype(np.int32))

    def _assemble_list_nested(self, reps, defs, vals) -> _HostColumn:
        """Arbitrary-depth LIST reconstruction from rep/def level streams.

        Level math (generalizing the 3-level case above): with per-level
        group optionality o_1..o_D, C_k = sum_{j<=k}(1 + o_j) is the
        definition level at which an element SLOT exists at depth k; the
        level-k list hanging at a depth-(k-1) slot is null iff
        def < C_{k-1} + o_k, and every event with rep < k opens a level-k
        segment (dead segments — whose first def < C_{k-1} — belong to no
        parent slot and are dropped)."""
        s = self.schema
        o = [1 if x else 0 for x in s.list_levels]
        depth = len(o)
        C = [0]
        for ok in o:
            C.append(C[-1] + 1 + ok)
        md = s.max_def
        rep = np.concatenate([r for r in reps]) if reps else \
            np.zeros(0, np.int32)
        deff = np.concatenate([d for d in defs]) if defs else \
            np.zeros(0, np.int32)
        nev = len(rep)
        top = prev = None
        for k in range(1, depth + 1):
            seg = np.flatnonzero(rep < k)
            first_def = deff[seg]
            keep = first_def >= C[k - 1]       # parent slot exists
            # a NEW level-k element starts only where rep <= k (deeper rep
            # values continue an existing slot at this level)
            slot = (rep <= k) & (deff >= C[k])
            cs = np.concatenate(([0], np.cumsum(slot, dtype=np.int64)))
            seg_end = np.concatenate((seg[1:], [nev])) if len(seg) else \
                np.zeros(0, np.int64)
            lens = (cs[seg_end] - cs[seg])[keep]
            valid_k = (first_def >= C[k - 1] + o[k - 1])[keep]
            loff = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=loff[1:])
            if loff[-1] > np.iinfo(np.int32).max:
                raise ValueError("nested list chunk exceeds int32 offsets")
            lcs = ColumnSchema(s.name + ".list" * (k - 1), s.physical,
                               s.type_length, optional=s.optional,
                               dtype=s.dtype, is_list=True,
                               list_optional=bool(o[k - 1]))
            hc = _HostColumn(lcs, None, None, None,
                             None if bool(valid_k.all()) else valid_k,
                             loffsets=loff.astype(np.int32))
            if prev is None:
                top = hc
            else:
                prev.child = hc
            prev = hc
        slot_leaf = deff >= C[depth]
        nslots = int(slot_leaf.sum())
        elem_valid = None
        if s.optional:
            elem_valid = (deff == md)[slot_leaf]
            if bool(elem_valid.all()):
                elem_valid = None
        ecs = ColumnSchema(s.name + ".element", s.physical, s.type_length,
                           optional=s.optional, dtype=s.dtype)
        values, chars, offsets = _scatter_values(s, nslots, vals, elem_valid)
        prev.child = _HostColumn(ecs, values, chars, offsets, elem_valid)
        return top


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

# Parsed-footer cache: the streaming path opens the same file more than
# once (the chunked reader for data, the executor's empty-stream fallback
# for schema), and repeated scans of one file are the NDS norm — parse the
# footer ONCE per (file identity, version).  The cached value is pure
# metadata (schema + ChunkMeta offsets), safely shared across mmaps; the
# key's mtime/size pin it to the exact file version.  ``io.footer_parses``
# counts actual parses so tests can prove one parse per file.
_FOOTER_CACHE: dict = {}
_FOOTER_CACHE_MAX = 64
_footer_lock = __import__("threading").Lock()


class ParquetFile:
    """Metadata handle over one parquet file; decodes row groups on demand."""

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        # mmap, not read(): host memory stays proportional to the pages a
        # pass actually touches, which is what ParquetChunkedReader promises
        with open(self.path, "rb") as f:
            buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        if buf[:4] != _MAGIC or buf[-4:] != _MAGIC:
            raise ValueError(f"{self.path}: not a parquet file")
        self._buf = buf
        key = None
        try:
            st = os.stat(self.path)
            key = (os.path.realpath(self.path), st.st_mtime_ns, st.st_size)
        except OSError:
            pass
        with _footer_lock:
            cached = _FOOTER_CACHE.get(key) if key is not None else None
        if cached is None:
            flen = int.from_bytes(buf[-8:-4], "little")
            meta, _ = decode_struct(buf[-8 - flen:-8])
            metrics.count("io.footer_parses")
            cached = _parse_footer(meta)
            if key is not None:
                with _footer_lock:
                    if len(_FOOTER_CACHE) >= _FOOTER_CACHE_MAX:
                        _FOOTER_CACHE.pop(next(iter(_FOOTER_CACHE)))
                    _FOOTER_CACHE[key] = cached
        self.schema, self.num_rows, self.row_groups = cached
        self.names = [s.name for s in self.schema]

    @property
    def num_row_groups(self) -> int:
        return len(self.row_groups)

    def _column_indices(self, columns):
        if columns is None:
            return list(range(len(self.schema)))
        return [self.names.index(c) for c in columns]

    def _decode_group(self, gi: int, columns=None) -> list[_HostColumn]:
        g = self.row_groups[gi]
        out = []
        for i in self._column_indices(columns):
            s = self.schema[i]
            if s.is_struct:
                kids, svalid = [], None
                for ck in g.chunks[i]:
                    dec = _ChunkDecoder(self._buf, ck)
                    kids.append(dec.run())
                    if (svalid is None and s.struct_optional
                            and dec.def_stream is not None):
                        svalid = dec.def_stream >= 1
                if svalid is not None and bool(svalid.all()):
                    svalid = None
                out.append(_HostColumn(s, None, None, None, svalid,
                                       children=kids))
            else:
                out.append(_ChunkDecoder(self._buf, g.chunks[i]).run())
        return out

    def group_stats(self, gi: int, column: str):
        """(min, max, null_count) from row-group statistics, or None.

        Drives scan-level row-group pruning (the predicate-pushdown role of
        the reference's chunked reader).  Only fixed-width stats decode.
        """
        idx = self.names.index(column)
        if self.schema[idx].is_struct:
            return None
        ck = self.row_groups[gi].chunks[idx]
        st = ck.statistics
        if not st:
            return None
        lo = st.get(6, st.get(2))
        hi = st.get(5, st.get(1))
        if lo is None or hi is None or ck.schema.physical not in _PLAIN_NP:
            return None
        if ck.schema.dtype.is_decimal:
            # stats carry the unscaled integer; predicates are user-space
            return None
        npdt = _PLAIN_NP[ck.schema.physical]
        if ck.schema.dtype.storage.kind == "u":
            npdt = np.dtype(f"<u{npdt.itemsize}")
        return (np.frombuffer(lo, npdt, 1)[0].item(),
                np.frombuffer(hi, npdt, 1)[0].item(),
                st.get(3))

    def read_row_group(self, gi: int, columns=None) -> Table:
        cols = self._decode_group(gi, columns)
        return Table([h.to_column() for h in cols],
                     [h.schema.name for h in cols])

    def empty_table(self, columns=None) -> Table:
        """Zero-row Table with this file's schema (engine empty-scan result)."""
        empty = [_empty_host(self.schema[i])
                 for i in self._column_indices(columns)]
        return Table([h.to_column() for h in empty],
                     [h.schema.name for h in empty])

    def read(self, columns=None, staged: bool | None = None) -> Table:
        """Read into a device Table.

        The staged path (ONE packed device transfer + a jitted on-device
        unpack, io/staging.py — the GDS role) is the DEFAULT scan->device
        route for fixed-width schemas: ``staged=None`` takes it whenever
        its unpack program is already compiled for this (schema, row
        bucket), and otherwise ships per-column now while compiling the
        staged program on a background thread, so the next scan (the NDS
        repeated-scan pattern) is single-transfer.  ``staged=True`` forces
        the staged path (paying a first-touch compile), ``staged=False``
        forces per-column transfers."""
        idxs = self._column_indices(columns)
        eligible = (self.num_row_groups >= 1 and
                    all(self.schema[i].dtype is not None and
                        self.schema[i].dtype.is_fixed_width and
                        self.schema[i].dtype.id != dt.TypeId.DECIMAL128 and
                        not self.schema[i].is_list and
                        not self.schema[i].is_struct for i in idxs))
        if staged and not eligible:
            staged = False  # explicit request, ineligible schema
        if eligible and staged is not False:
            from .staging import plan_ready, warm_plan_async
            hosts = self._decode_all_groups(columns)
            merged = hosts[0] if len(hosts) == 1 else \
                [_concat_host([g[i] for g in hosts])
                 for i in range(len(hosts[0]))]
            specs = [(h.schema.name, h.schema.dtype, h.values, h.validity)
                     for h in merged]
            if staged or plan_ready(specs):
                from .staging import stage_fixed_table
                return stage_fixed_table(specs)
            warm_plan_async(specs)  # single-transfer from the next scan on
            return Table([h.to_column() for h in merged],
                         [h.schema.name for h in merged])
        hosts = self._decode_all_groups(columns)
        if not hosts:  # valid file, zero row groups (empty partition)
            empty = [_empty_host(self.schema[i])
                     for i in self._column_indices(columns)]
            return Table([h.to_column() for h in empty],
                         [h.schema.name for h in empty])
        if len(hosts) == 1:
            return Table([h.to_column() for h in hosts[0]],
                         [h.schema.name for h in hosts[0]])
        merged = [_concat_host([g[i] for g in hosts])
                  for i in range(len(hosts[0]))]
        return Table([h.to_column() for h in merged],
                     [h.schema.name for h in merged])

    def _decode_all_groups(self, columns=None) -> list:
        """All row groups decoded host-side; >1 group fans out on a thread
        pool (numpy decode kernels drop the GIL — libcudf's reader decodes
        row groups concurrently on-device for the same reason)."""
        if self.num_row_groups > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=min(
                    self.num_row_groups, os.cpu_count() or 4)) as ex:
                return list(ex.map(
                    lambda gi: self._decode_group(gi, columns),
                    range(self.num_row_groups)))
        return [self._decode_group(gi, columns)
                for gi in range(self.num_row_groups)]


def _empty_host(s: ColumnSchema) -> _HostColumn:
    if s.is_struct:
        return _HostColumn(s, None, None, None, None,
                           children=[_empty_host(f) for f in s.fields])
    if s.is_list:
        ecs = ColumnSchema(s.name + ".element", s.physical, s.type_length,
                           optional=s.optional, dtype=s.dtype)
        return _HostColumn(s, None, None, None, None,
                           child=_empty_host(ecs),
                           loffsets=np.zeros(1, np.int32))
    if s.dtype.is_string:
        return _HostColumn(s, None, np.zeros(0, np.uint8),
                           np.zeros(1, np.int32), None)
    return _HostColumn(s, np.zeros(0, s.dtype.storage), None, None, None)


def _concat_host(parts: list[_HostColumn]) -> _HostColumn:
    s = parts[0].schema
    has_valid = any(p.validity is not None for p in parts)
    valid = np.concatenate(
        [p.validity if p.validity is not None
         else np.ones(p.num_rows, np.bool_) for p in parts]) \
        if has_valid else None
    if s.is_struct:
        kids = [_concat_host([p.children[i] for p in parts])
                for i in range(len(s.fields))]
        return _HostColumn(s, None, None, None, valid, children=kids)
    if s.is_list:
        offs = [parts[0].loffsets.astype(np.int64)]
        base = int(parts[0].loffsets[-1])
        for p in parts[1:]:
            offs.append(p.loffsets[1:].astype(np.int64) + base)
            base += int(p.loffsets[-1])
        loffsets = np.concatenate(offs)
        if loffsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("concatenated list column exceeds int32 offsets")
        child = _concat_host([p.child for p in parts])
        return _HostColumn(s, None, None, None, valid, child=child,
                           loffsets=loffsets.astype(np.int32))
    if s.dtype.is_string:
        chars = np.concatenate([p.chars for p in parts])
        offs = [parts[0].offsets.astype(np.int64)]
        base = int(parts[0].offsets[-1])
        for p in parts[1:]:
            offs.append(p.offsets[1:].astype(np.int64) + base)
            base += int(p.offsets[-1])
        offsets = np.concatenate(offs)
        if offsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("concatenated string column exceeds int32 offsets")
        return _HostColumn(s, None, chars, offsets.astype(np.int32), valid)
    return _HostColumn(s, np.concatenate([p.values for p in parts]),
                       None, None, valid)


def read_parquet(path, columns=None, staged: bool | None = None) -> Table:
    """Read a whole parquet file into a device Table.

    Fixed-width schemas default to the staged single-transfer path with
    first-touch fallback (see ParquetFile.read); ``staged=True``: force it —
    see ParquetFile.read."""
    return ParquetFile(path).read(columns, staged=staged)


# ---------------------------------------------------------------------------
# device-decode page planning (SRJT_DEVICE_DECODE)
# ---------------------------------------------------------------------------

from ..utils.errors import TransientError as _TransientError  # noqa: E402


class TruncatedPageError(_TransientError, OSError):
    """A page header or body runs past its chunk/file bounds.

    Typed ``io_error`` (transient OSError): storage-layer truncation is
    indistinguishable from a torn read, so the bounded retry ladder gets a
    chance before the failure propagates."""


class DevicePageChunk:
    """One row group's raw compressed pages, packed as host numpy planes.

    The device-decode wire unit: ``to_device()`` ships the planes (the
    *compressed* page bytes plus the tiny per-page count sidecars) and
    ops/parquet_decode.decode_table turns them into columns on-device.
    Built host-side — in the prefetch producer thread when the pipeline is
    double-buffered — so only the transfer + decode land on the consumer's
    critical path.
    """

    __slots__ = ("gi", "geom", "planes", "nrows", "comp_bytes", "unc_bytes")

    def __init__(self, gi, geom, planes, nrows, comp_bytes, unc_bytes):
        self.gi = gi
        self.geom = geom
        self.planes = planes          # {col: {plane: np.ndarray}}
        self.nrows = nrows
        self.comp_bytes = comp_bytes  # padded plane bytes (the link cost)
        self.unc_bytes = unc_bytes    # what the host path's transfer ships

    def to_device(self) -> dict:
        """Transfer the planes; returns the jnp pytree decode_table eats."""
        faults.check("parquet.device_decode")
        metrics.count("io.device_decode.chunks")
        metrics.count("io.device_decode.link_bytes", int(self.comp_bytes))
        metrics.count("io.device_decode.uncompressed_bytes",
                      int(self.unc_bytes))
        return {name: {k: jnp.asarray(v) for k, v in planes.items()}
                for name, planes in self.planes.items()}


def _walk_pages(fbuf, meta: ChunkMeta):
    """Host page-header walk of one column chunk (payloads untouched).

    Returns ``(data_pages, dict_page, encoding)`` with data_pages =
    [(body_off, comp_len, unc_len, num_values)], dict_page the same tuple
    shape with num_values = dictionary size, and encoding the chunk's data
    encoding class ("plain" | "dict") — or ``(None, None, reason)`` when an
    encoding/page shape needs the host decoder.  Truncation raises the
    typed :class:`TruncatedPageError`.
    """
    pos = meta.start_offset
    end = pos + meta.total_compressed
    remaining = meta.num_values
    flen = len(fbuf)
    data_pages, dict_page, encs = [], None, set()
    while remaining > 0 and pos < end:
        try:
            header, body = decode_struct(fbuf, pos)
        except Exception as e:
            raise TruncatedPageError(
                f"{meta.schema.name}: page header at {pos} unreadable") \
                from e
        comp = header.get(3)
        if comp is None or body + comp > end or body + comp > flen:
            raise TruncatedPageError(
                f"{meta.schema.name}: page body at {body} overruns chunk")
        ptype = header[1]
        if ptype == PAGE_DICTIONARY:
            dict_page = (body, comp, header[2], header[7][1])
        elif ptype == PAGE_DATA:
            ph = header[5]
            if ph.get(3, ENC_RLE) != ENC_RLE:
                return None, None, "level_encoding"
            enc = ph[2]
            if enc == ENC_PLAIN:
                encs.add("plain")
            elif enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
                encs.add("dict")
            else:
                return None, None, "value_encoding"
            data_pages.append((body, comp, header[2], ph[1]))
            remaining -= ph[1]
        elif ptype == PAGE_DATA_V2:
            return None, None, "v2_pages"
        elif ptype != PAGE_INDEX:
            return None, None, "page_type"
        pos = body + comp
    if len(encs) != 1:
        return None, None, ("no_pages" if not encs else "mixed_encoding")
    encoding = encs.pop()
    if encoding == "dict" and dict_page is None:
        return None, None, "no_dictionary"
    return data_pages, dict_page, encoding


def _device_eligible_schema(s: ColumnSchema):
    """Fallback reason for schema shapes the device decoder won't take,
    or None when eligible (flat fixed-width, at most one def level)."""
    if s.is_struct or s.is_list or s.list_levels or s.extra_def:
        return "nested"
    if s.max_rep:
        return "repeated"
    if s.max_def > 1:
        return "multi_def"
    if s.physical == PT_BOOLEAN:
        return None
    if s.physical not in _PLAIN_NP or s.dtype.is_string:
        return "physical_type"
    if np.dtype(s.dtype.storage).itemsize != _PLAIN_NP[s.physical].itemsize:
        return "narrowed_type"  # e.g. INT32 physical read as int16
    return None


def plan_device_group(pf: ParquetFile, gi: int, columns=None,
                      limit: int | None = None):
    """Plan one row group for device decode: ``(DevicePageChunk, None)`` or
    ``(None, reason)`` when the group re-plans to the host decoder.

    Pure host metadata work: footer eligibility, a page-header walk
    (io/thrift.py), a snappy token scan per page (header bytes only), and
    numpy plane packing.  No page payload is decoded here.
    """
    from ..ops import parquet_decode as pqd
    g = pf.row_groups[gi]
    idxs = pf._column_indices(columns)
    for i in idxs:
        reason = _device_eligible_schema(pf.schema[i])
        if reason is None and g.chunks[i].codec not in (CODEC_SNAPPY,
                                                        CODEC_UNCOMPRESSED):
            reason = "codec"
        if reason is not None:
            return None, reason
    if limit is not None:
        total_unc = sum(int(g.chunks[i].total_uncompressed or 0)
                        for i in idxs)
        if total_unc > limit:
            # one group must stay one chunk on the device path (pages are
            # not row-sliceable without decode); oversized groups keep the
            # host path's budgeted slicing
            return None, "oversized_group"
    nrows = int(g.num_rows)
    rb = pqd.bucket(max(nrows, 1), 1024)
    fbuf = pf._buf
    cols, planes = [], {}
    comp_bytes = unc_bytes = 0
    for i in idxs:
        meta = g.chunks[i]
        s = meta.schema
        data_pages, dict_page, enc = _walk_pages(fbuf, meta)
        if data_pages is None:
            return None, enc
        np_, cmax, umax, vmax = len(data_pages), 0, 0, 0
        rows_seen = 0
        for _, c, u, nv in data_pages:
            cmax, umax, vmax = max(cmax, c), max(umax, u), max(vmax, nv)
            rows_seen += nv
        if rows_seen != nrows:
            return None, "row_count"
        if dict_page is not None:
            cmax = max(cmax, dict_page[1])
            umax = max(umax, dict_page[2])
        pcount = pqd.bucket(max(np_, 1), 1)
        cb, ub = pqd.bucket(cmax), pqd.bucket(umax)
        vb = pqd.bucket(vmax)
        db = pqd.bucket(dict_page[3]) if enc == "dict" else pqd.MIN_BUCKET
        has_copies, tmax = False, 1
        if meta.codec == CODEC_SNAPPY:
            view = memoryview(fbuf)
            bodies = list(data_pages) + \
                ([dict_page] if dict_page is not None else [])
            for off, c, _, _ in bodies:
                ntok, lit_only = snappy.scan_tokens(view[off:off + c])
                tmax = max(tmax, ntok)
                if not lit_only:
                    has_copies = True
        comp = np.zeros((pcount + 1, cb), np.uint8)
        clen = np.zeros(pcount + 1, np.int32)
        ulen = np.zeros(pcount + 1, np.int32)
        nv_arr = np.zeros(pcount + 1, np.int32)
        if dict_page is not None:
            off, c, u, nd = dict_page
            comp[0, :c] = np.frombuffer(fbuf, np.uint8, c, off)
            clen[0], ulen[0], nv_arr[0] = c, u, nd
        for k, (off, c, u, nv) in enumerate(data_pages):
            comp[k + 1, :c] = np.frombuffer(fbuf, np.uint8, c, off)
            clen[k + 1], ulen[k + 1], nv_arr[k + 1] = c, u, nv
        cols.append(pqd.ColumnGeom(
            name=s.name, dtype=s.dtype, physical=s.physical,
            codec=meta.codec, encoding=enc, max_def=s.max_def,
            has_copies=has_copies, npages=pcount, cb=cb, ub=ub, vb=vb,
            db=db, tb=pqd.bucket(tmax, 16)))
        # row -> (page, slot) is NOT shipped: the kernel derives it from
        # the nv cumsum, so the link carries only pages + page counts
        planes[s.name] = {"comp": comp, "clen": clen, "ulen": ulen,
                          "nv": nv_arr}
        comp_bytes += comp.nbytes + clen.nbytes + ulen.nbytes \
            + nv_arr.nbytes
        unc_bytes += int(meta.total_uncompressed or 0)
    geom = pqd.ChunkGeom(columns=tuple(cols), rb=rb)
    return DevicePageChunk(gi, geom, planes, nrows, comp_bytes,
                           unc_bytes), None


class ParquetChunkedReader:
    """Iterate a parquet file as device Tables bounded by a byte budget.

    TPU analog of the reference's chunked-parquet north star (BASELINE.md):
    ``pass_read_limit`` bounds the decoded bytes per emitted Table so the
    device working set stays fixed no matter the file size.  Row groups
    decode host-side one at a time and are sliced to the budget before any
    device transfer.

        for tbl in ParquetChunkedReader(p, pass_read_limit=64 << 20):
            ... # tbl.num_rows * row_bytes ≤ pass_read_limit

    ``predicate=(column, lo, hi)`` prunes whole row groups via footer
    statistics before any page decode.
    """

    def __init__(self, path, pass_read_limit: int = 64 << 20, columns=None,
                 predicate: tuple | None = None, prefetch: int = 0,
                 cancel=None):
        self.file = ParquetFile(path)
        self.limit = int(pass_read_limit)
        self.columns = columns
        self.predicate = predicate
        self.prefetch = int(prefetch)
        # cooperative cancellation (utils.errors.CancelToken, duck-typed):
        # checked per row group and polled by the prefetch producer so a
        # cancelled/expired query releases its reader thread promptly
        self.cancel = cancel
        # pruning observability: the engine's executor reports these through
        # its execution stats to prove predicate pushdown engaged
        self.groups_pruned = 0
        self.groups_read = 0
        # live prefetch generators: a consumer loop that raises mid-stream
        # never closes its iterator, which would leave the producer thread
        # parked on the bounded queue until GC; ``close()`` reaps them
        self._active: list = []
        if self.limit <= 0:
            raise ValueError("pass_read_limit must be positive")

    def close(self) -> None:
        """Stop any live prefetch producer threads (idempotent).

        Closing the tracked generator raises GeneratorExit at its yield
        point, running ``_prefetched``'s finally: stop event, queue drain,
        thread join.  Streamed executions call this in a finally; ``with
        ParquetChunkedReader(...) as r`` does it automatically."""
        while self._active:
            self._active.pop().close()

    def __enter__(self) -> "ParquetChunkedReader":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def footer_chunk_estimate(self) -> int:
        """Expected chunk count from footer metadata alone — no page
        decode, no IO beyond the already-parsed footer.  Per non-pruned
        row group: at least one chunk, plus one per ``pass_read_limit``
        of the group's footer ``total_byte_size`` (the same
        uncompressed-bytes scale the real slicer budgets with).  The
        executor publishes this as the query's live-progress
        ``chunks_total``; it is an estimate, not a promise."""
        total = 0
        for gi in range(self.file.num_row_groups):
            if self._group_pruned(gi):
                continue
            nbytes = int(self.file.row_groups[gi].total_byte_size or 0)
            total += max(1, -(-nbytes // self.limit))
        return total

    def _group_pruned(self, gi: int) -> bool:
        if self.predicate is None:
            return False
        col, lo, hi = self.predicate
        st = self.file.group_stats(gi, col)
        if st is None:
            return False
        gmin, gmax, _ = st
        return (hi is not None and gmin > hi) or \
               (lo is not None and gmax < lo)

    def _chunks(self):
        from ..utils.config import config
        from ..utils.memory import MemoryScope
        # the live-buffer census walks every live jax.Array, so per-batch
        # checkpoints only run when the observability is actually wanted
        if not config.mem_debug:
            yield from self._chunks_raw()
            return
        with MemoryScope("parquet_chunked") as scope:
            for tbl in self._chunks_raw():
                yield tbl
                # RMM-role checkpoint: refresh the working-set high-water
                # mark at the batch boundary
                scope.checkpoint()

    def _decode_group_checked(self, gi: int):
        faults.check("parquet.chunk")
        return self.file._decode_group(gi, self.columns)

    def _host_slices_group(self, gi: int):
        """Budget-bounded host-side slices of ONE row group."""
        # transient decode failures (flaky storage) retry per row
        # group, bounded by SRJT_RETRY_MAX with backoff
        hosts = retry_call(
            lambda gi=gi: self._decode_group_checked(gi),
            "parquet.chunk", cancel=self.cancel)
        nrows = hosts[0].num_rows
        if nrows == 0:
            return
        total = sum(h.nbytes_estimate() for h in hosts)
        metrics.count("io.parquet.bytes_decoded", int(total))
        per_row = max(1, total // max(nrows, 1))
        step = max(1, self.limit // per_row)
        for a in range(0, nrows, step):
            b = min(a + step, nrows)
            yield [h.slice(a, b) for h in hosts]

    def _host_slices(self):
        """Budget-bounded host-side chunk slices, pre device transfer."""
        for gi in range(self.file.num_row_groups):
            if self.cancel is not None:
                self.cancel.check()
            if self._group_pruned(gi):
                self.groups_pruned += 1
                continue
            self.groups_read += 1
            yield from self._host_slices_group(gi)

    def _chunks_raw(self):
        for sl in self._host_slices():
            metrics.count("io.parquet.chunks")
            metrics.observe("io.parquet.chunk_rows", sl[0].num_rows)
            yield Table([h.to_column() for h in sl],
                        [h.schema.name for h in sl])

    def _staged_chunks(self):
        """(Table, n_rows) chunks on the packed-transfer path.

        Fixed-width chunks ship as ONE staged transfer kept PADDED to the
        power-of-two row bucket (io/staging.py): every same-schema chunk
        lands in the same shape class, so the engine's fused segments
        compile once and mask rows >= n_rows.  Ineligible schemas
        (strings, lists, structs, DECIMAL128) fall back to per-column
        transfers at natural size (n_rows == num_rows)."""
        for sl in self._host_slices():
            yield self._stage_one(sl)

    def _stage_one(self, sl):
        """One host slice -> (padded Table, n_rows) on the staged path."""
        from .staging import stage_fixed_table
        nrows = sl[0].num_rows
        metrics.count("io.parquet.chunks")
        metrics.observe("io.parquet.chunk_rows", nrows)
        if all(h.values is not None and
               h.schema.dtype.id != dt.TypeId.DECIMAL128 for h in sl):
            specs = [(h.schema.name, h.schema.dtype, h.values,
                      h.validity) for h in sl]
            return stage_fixed_table(specs, padded=True)
        return (Table([h.to_column() for h in sl],
                      [h.schema.name for h in sl]), nrows)

    def _device_stream(self):
        """Mixed device/host chunk stream for SRJT_DEVICE_DECODE.

        Yields ``("dev", DevicePageChunk, None)`` for groups the device
        decoder takes (planes packed host-side, payloads NOT decoded) and
        ``("host", (Table, n_rows), reason)`` for per-group fallbacks —
        the executor records the ledgered ``scan:device_decode`` decision
        either way.  Group order is preserved, so results match the host
        path row-for-row.
        """
        for gi in range(self.file.num_row_groups):
            if self.cancel is not None:
                self.cancel.check()
            if self._group_pruned(gi):
                self.groups_pruned += 1
                continue
            self.groups_read += 1
            if int(self.file.row_groups[gi].num_rows) == 0:
                continue
            chunk, reason = plan_device_group(
                self.file, gi, self.columns, self.limit)
            if chunk is not None:
                metrics.count("io.parquet.chunks")
                metrics.observe("io.parquet.chunk_rows", chunk.nrows)
                yield ("dev", chunk, None)
            else:
                metrics.count("io.device_decode.fallbacks")
                for sl in self._host_slices_group(gi):
                    yield ("host", self._stage_one(sl), reason)

    def iter_device(self, prefetch: int | None = None):
        """Iterate the device-decode stream, optionally double-buffered.

        Same pipeline shape as :meth:`iter_staged` — with depth >= 1 the
        producer thread does the page-header walk and plane packing (or the
        host decode, for fallback groups) for chunk k+1 while the consumer
        transfers/decodes chunk k on device."""
        depth = self.prefetch if prefetch is None else int(prefetch)
        gen = self._device_stream()
        if depth <= 0:
            yield from gen
        else:
            yield from self._tracked(_prefetched(gen, depth, self.cancel))

    def iter_staged(self, prefetch: int | None = None):
        """Iterate ``(padded Table, n_rows)`` chunks, double-buffered.

        The chunk-pipeline entry point: with depth >= 1 a producer thread
        host-decodes AND stages (pack + device_put + unpack dispatch)
        chunk k+1 while the consumer computes on chunk k — the decode and
        transfer halves of the scan hide behind device compute.  Depth
        defaults to the reader's ``prefetch``; 0 means serial."""
        depth = self.prefetch if prefetch is None else int(prefetch)
        gen = self._staged_chunks()
        if depth <= 0:
            yield from gen
        else:
            yield from self._tracked(_prefetched(gen, depth, self.cancel))

    def __iter__(self):
        if self.prefetch <= 0:
            yield from self._chunks()
            return
        yield from self._tracked(_prefetched(self._chunks(), self.prefetch,
                                             self.cancel))

    def _tracked(self, pf):
        """Register a prefetch generator for ``close()`` while it runs."""
        self._active.append(pf)
        try:
            yield from pf
        finally:
            try:
                self._active.remove(pf)
            except ValueError:
                pass  # close() already reaped it


_reap_warned = False


def _prefetched(gen, depth: int, cancel=None):
    """Pipeline overlap (the per-thread-stream analog, SURVEY §2.3 "PP"):
    a worker thread produces item i+1..i+depth while the caller consumes
    item i.  jax dispatch is already async on the consumer side; this
    overlaps the HOST half (page decode, decompress, staging pack) with
    it.  The queue bound keeps at most ``depth`` items of extra memory in
    flight."""
    import queue
    import threading
    import time

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    DONE, FAIL = object(), object()
    # the producer thread must attribute its decode/stall metrics to the
    # query that opened the stream (thread-locals don't cross threads)
    qm = metrics.current()
    timed = metrics.enabled()
    # cross-thread flow arrows: producer's staging of chunk n links to the
    # consumer's dispatch of chunk n by id.  Both sides count the same
    # in-order sequence, so fid_base + n matches without threading ids
    # through the queue items.
    tl = timeline.enabled()
    fid_base = timeline.new_flow_base() if tl else 0

    def put(item) -> bool:  # False once the consumer abandoned us
        t0 = time.perf_counter() if timed else 0.0
        while not stop.is_set():
            if cancel is not None and cancel.should_stop():
                return False  # stuck query: release the reader thread
            try:
                q.put(item, timeout=0.1)
            except queue.Full:
                continue
            if timed:
                # time blocked on a full queue: the producer ran AHEAD of
                # the consumer (healthy pipeline; idle below is the stall
                # that costs wall time)
                metrics.time_add("io.parquet.prefetch.producer_stall_s",
                                 time.perf_counter() - t0)
            return True
        return False

    def put_ctrl(item) -> None:
        # DONE/FAIL sentinels must always land (the consumer blocks on
        # q.get until one arrives) — only consumer abandonment (stop)
        # releases this loop, never cancellation
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def producer():
        with metrics.bind(qm):
            try:
                if tl:
                    it = iter(gen)
                    n = 0
                    while True:
                        # span covers the host decode + staging pull for
                        # chunk n; the flow tail starts inside it so the
                        # arrow binds to the producer slice
                        with timeline.span("io.parquet.produce_chunk",
                                           {"chunk": n}):
                            faults.check("parquet.prefetch")
                            try:
                                item = next(it)
                            except StopIteration:
                                break
                            timeline.flow_start("io.parquet.chunk",
                                                fid_base + n)
                        if not put(item):
                            if not stop.is_set() and cancel is not None:
                                cancel.check()  # -> typed error via FAIL
                            return
                        n += 1
                else:
                    it = iter(gen)
                    while True:
                        faults.check("parquet.prefetch")
                        try:
                            item = next(it)
                        except StopIteration:
                            break
                        if not put(item):
                            if not stop.is_set() and cancel is not None:
                                cancel.check()  # -> typed error via FAIL
                            return
                put_ctrl(DONE)
            except BaseException as e:  # surface decode errors to consumer
                put_ctrl((FAIL, e))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    k = 0
    try:
        while True:
            t0 = time.perf_counter() if timed else 0.0
            item = q.get()
            if timed:
                # consumer blocked waiting on host decode: the bubble the
                # double-buffered pipeline exists to hide
                metrics.time_add("io.parquet.prefetch.consumer_idle_s",
                                 time.perf_counter() - t0)
            if item is DONE:
                break
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] is FAIL:
                raise item[1]
            if tl:
                # the arrow head: chunk k leaves the queue for dispatch on
                # the consumer thread (binds to the enclosing engine slice)
                with timeline.span("io.parquet.consume_chunk",
                                   {"chunk": k}):
                    timeline.flow_finish("io.parquet.chunk", fid_base + k)
                k += 1
            yield item
    finally:
        # early abandonment (LIMIT queries, consumer errors) must not
        # leave the producer pinned on the bounded queue
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5)
        if t.is_alive():
            # the producer outlived the reap window: count it (the chaos
            # soak asserts zero) and warn once rather than silently leak
            metrics.count("io.prefetch.reap_timeouts")
            global _reap_warned
            if not _reap_warned:
                _reap_warned = True
                from ..utils.config import logger
                logger().warning(
                    "prefetch producer thread failed to stop within 5s "
                    "(leaked; counted as io.prefetch.reap_timeouts)")
