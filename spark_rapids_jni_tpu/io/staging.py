"""Single-transfer device staging for fixed-width column sets.

The GDS role (reference CMakeLists.txt:176-199 — cuFile exists to keep the
storage->device path off the bounce-buffer critical path).  On tunneled
devices the host->device link is RTT-dominated (hundreds of ms per
dispatch, single-digit MB/s): six column transfers cost five avoidable
round trips.  So the scan path packs EVERY column buffer (values and
validity) into ONE contiguous uint32 host buffer, ships it in a single
``device_put``, and slices/bitcasts each column back out on device — the
unpack is one fused XLA program whose cost is noise next to the link.

Measured (r4): per-group per-column puts reached 14% of the link rate;
the staged single put removes the extra round trips entirely.

Word-level unpacking mirrors the row-conversion wire tricks
(ops/row_conversion.py): 8-byte types rebuild from u32 pairs via the same
``bitcast_convert_type`` the wire path uses (proven on TPU, where only
<=32-bit bitcasts exist), sub-word types extract lanes by shifts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..columnar import Column, Table
from ..utils import faults


def _pad4(b: bytes) -> bytes:
    r = len(b) % 4
    return b if r == 0 else b + b"\0" * (4 - r)


@functools.partial(jax.jit, static_argnums=(1,))
def _unpack(words: jnp.ndarray, plan: tuple):
    """One fused unpack of the staged u32 buffer into per-column arrays.

    ``plan``: per entry (kind, word_off, word_len, n) with kind one of
    'w8' (8-byte scalars), 'w4', 'w2', 'w1'.
    """
    outs = []
    for kind, off, wlen, n in plan:
        w = jax.lax.dynamic_slice(words, (off,), (wlen,))
        if kind == "w8":
            pairs = w.reshape(n, 2)
            outs.append(jax.lax.bitcast_convert_type(pairs, jnp.int64))
        elif kind == "w4":
            outs.append(w)
        elif kind == "w2":
            half = jnp.stack([w & jnp.uint32(0xFFFF),
                              w >> jnp.uint32(16)], axis=1)
            outs.append(half.reshape(-1)[:n].astype(jnp.uint16))
        else:  # w1
            lanes = jnp.stack([(w >> jnp.uint32(8 * j)) & jnp.uint32(0xFF)
                               for j in range(4)], axis=1)
            outs.append(lanes.reshape(-1)[:n].astype(jnp.uint8))
    return tuple(outs)


def _bucket(n: int) -> int:
    """Next power of two >= n: the staged unpack compiles once per
    (schema, row bucket), not once per exact file size — scanning many
    same-schema files of nearby sizes reuses one compiled program."""
    b = 1024
    while b < n:
        b *= 2
    return b


def _plan_for(specs):
    """The (plan, total_words) ``stage_fixed_table`` will use for these
    specs — computed WITHOUT packing, so callers can ask whether the
    unpack program is already compiled before paying for the pack."""
    plan = []
    off = 0
    n_rows = len(specs[0][2]) if specs else 0
    padded = _bucket(n_rows)

    def push(itemsize, kind):
        nonlocal off
        wlen = padded * itemsize // 4 if itemsize >= 4 else \
            (padded * itemsize + 3) // 4
        plan.append((kind, off, wlen, padded))
        off += wlen

    for name, dtype, values, validity in specs:
        size = np.dtype(dtype.storage).itemsize if not dtype.is_decimal \
            else dtype.itemsize
        kind = {8: "w8", 4: "w4", 2: "w2", 1: "w1"}[size]
        push(size, kind)
        if validity is not None:
            push(1, "w1")
    return tuple(plan), off


_ready_plans: set = set()
_warming: set = set()
_failed_plans: set = set()
_plans_lock = __import__("threading").Lock()


def plan_ready(specs) -> bool:
    """True when the staged unpack for these specs is already compiled —
    the first-touch gate: a cold scan should not stall on a (remote)
    compile when per-column transfers can ship now."""
    plan, total = _plan_for(specs)
    with _plans_lock:
        return (plan, total) in _ready_plans


def warm_plan_async(specs) -> None:
    """Compile the staged unpack for these specs on a background thread so
    the NEXT scan of this (schema, row-bucket) takes the single-transfer
    path.  Idempotent; never blocks the caller."""
    import threading
    plan, total = _plan_for(specs)
    key = (plan, total)
    with _plans_lock:
        if key in _ready_plans or key in _warming or key in _failed_plans:
            return
        _warming.add(key)

    def work():
        try:
            # Invoke the live jitted callable on a dummy buffer: this is what
            # populates jax.jit's DISPATCH cache for (shape, plan).  The
            # previous .lower().compile() built a throwaway AOT executable —
            # the next stage_fixed_table still paid the full trace+compile,
            # defeating the warm.
            out = _unpack(jnp.zeros((total,), jnp.uint32), plan)
            jax.block_until_ready(out)
            with _plans_lock:
                _ready_plans.add(key)
        except Exception as e:  # noqa: BLE001 — backend may reject the plan
            # memoize the failure: re-spawning a doomed multi-second compile
            # on every scan would burn CPU forever with zero diagnostics
            with _plans_lock:
                _failed_plans.add(key)
            from ..utils.config import logger
            logger().warning("staged unpack compile failed (%d cols); "
                             "scans stay per-column: %s: %s",
                             len(specs), type(e).__name__, e)
        finally:
            with _plans_lock:
                _warming.discard(key)

    threading.Thread(target=work, daemon=True).start()


def stage_fixed_table(specs, padded: bool = False):
    """``specs``: list of (name, dtype, values_np, validity_np_or_None) for
    fixed-width dtypes only.  One host pack, ONE device transfer, one fused
    device unpack; returns the device Table.

    Rows are padded host-side to a power-of-two bucket so the jitted
    unpack's shapes (and hence its compile) are shared across file sizes;
    outputs are sliced back to the true row count on device.

    ``padded=True`` keeps the bucket-padded form and returns
    ``(Table, n_rows)`` instead: pad rows carry zeroed values and False
    validity.  This is the chunk-pipeline form — every same-schema chunk
    shares ONE shape class, so fused plan segments (engine/segment.py)
    compile once and mask rows ``>= n_rows`` instead of slicing."""
    faults.check("staging.transfer")
    blob = bytearray()
    plan = []
    posts = []  # (name, dtype, has_valid, n)
    n_rows = len(specs[0][2]) if specs else 0
    bucket = _bucket(n_rows)

    def push(arr: np.ndarray, kind: str):
        arr = np.ascontiguousarray(arr)
        if len(arr) < bucket:
            arr = np.concatenate(
                [arr, np.zeros(bucket - len(arr), arr.dtype)])
        off = len(blob) // 4
        b = _pad4(arr.tobytes())
        blob.extend(b)
        plan.append((kind, off, len(b) // 4, bucket))

    for name, dtype, values, validity in specs:
        size = np.dtype(dtype.storage).itemsize if not dtype.is_decimal \
            else dtype.itemsize
        if dtype.id == dt.TypeId.DECIMAL128:
            raise TypeError("DECIMAL128 staging unsupported; use the "
                            "column-at-a-time path")
        kind = {8: "w8", 4: "w4", 2: "w2", 1: "w1"}[size]
        push(values, kind)
        if validity is not None:
            push(np.asarray(validity, np.uint8), "w1")
        posts.append((name, dtype, validity is not None, len(values)))

    words = jnp.asarray(np.frombuffer(bytes(blob), np.uint32))  # ONE put
    arrays = _unpack(words, tuple(plan))
    with _plans_lock:
        _ready_plans.add((tuple(plan), len(blob) // 4))
    cols, names = [], []
    ai = 0
    for name, dtype, has_valid, n in posts:
        data = arrays[ai] if padded else arrays[ai][:n]
        ai += 1
        storage = jnp.dtype(dtype.device_storage)
        if data.dtype != storage:
            if data.dtype.itemsize == storage.itemsize:
                data = jax.lax.bitcast_convert_type(data, storage)
            else:
                data = data.astype(storage)
        valid = None
        if has_valid:
            v = arrays[ai]
            valid = (v if padded else v[:n]).astype(jnp.bool_)
            ai += 1
        cols.append(Column(dtype, data=data, validity=valid))
        names.append(name)
    out = Table(cols, names)
    return (out, n_rows) if padded else out
