"""Parallel layer: device meshes, hash-partition shuffle, distributed plans.

The single biggest net-new component vs the reference (SURVEY.md §2.3): the
reference defers all cross-worker exchange to Spark's shuffle at L6, only
*preparing* row blobs for it (RowConversion.java:28-31).  Here the exchange is
first-class: row blobs ride ``jax.lax.all_to_all`` over the ICI mesh inside
``shard_map``, so a whole shuffle+aggregate plan compiles to one XLA program.
"""

from .mesh import make_mesh, shard_table  # noqa: F401
from .shuffle import (  # noqa: F401
    partition_ids,
    shuffle_chunks_pipelined,
    shuffle_table_padded,
)
from .spill import shuffle_table_spilled  # noqa: F401
from .distributed import (  # noqa: F401
    distributed_groupby, distributed_join, distributed_window,
    distributed_cross_join)
from .stringplane import explode_strings, reassemble_strings  # noqa: F401
