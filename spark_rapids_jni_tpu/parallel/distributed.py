"""Distributed query plans: shuffle-then-aggregate, shuffle-then-join.

The two classic Spark exchange plans, each expressed as ONE jittable XLA
program over the mesh:

- GROUP BY (GpuHashAggregate + GpuShuffleExchange):
      local groupby_padded -> row-blob all_to_all -> final groupby_padded
- equi-join (GpuShuffledHashJoin / SortMergeJoin, BASELINE configs[3]):
      both sides hash-partition over all_to_all (co-partitioning)
      -> shard-local padded sorted-probe join (ops.join.inner_join_padded)

Everything stays in HBM; the exchanges ride ICI.  Outputs are padded per
shard (static shapes) with live-row masks; ``distributed_groupby`` /
``distributed_join`` compact at the host boundary, the ``build_*``
constructors return the pure shard_map programs for pjit pipelines (the
dryrun/benchmark entries).  STRING columns cross the mesh in padded-bucket
form (stringplane.explode_strings).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
try:  # moved to the jax namespace in 0.5; experimental before that
    from jax import shard_map
except ImportError:  # pragma: no cover - jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        # the replication-check kwarg was renamed check_rep -> check_vma
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_old(f, **kw)

from ..columnar import Column, Table
from ..dtypes import DType, TypeId, INT64, FLOAT64
from ..ops.aggregate import groupby_padded
from ..ops.row_conversion import fixed_width_layout, _build_planes, \
    _from_planes
from .mesh import ROW_AXIS, axis_size
from ..utils.tracing import traced
from .shuffle import (partition_ids, partition_ids_specs, key_specs_for,
                      cap_bucket, cap_bucket_fine, exchange_planes,
                      partition_counts)

# (partial op emitted by the local pass, final re-aggregation op)
_REAGG = {"sum": "sum", "count": "sum", "count_all": "sum",
          "min": "min", "max": "max", "sumsq": "sum", "fsum": "sum"}


def _expand_aggs(aggs):
    """mean decomposes into (sum, count) partials + a final divide;
    var/std into (fsum, sumsq, count) partials + a final moment combine."""
    partial_specs = []   # (col_ref, op) for the local pass
    final_plan = []      # ("direct", i, op) | ("mean", si, ci)
    for ref, op in aggs:  # | ("var"/"std", si, qi, ci)
        if op == "mean":
            si = len(partial_specs)
            partial_specs.append((ref, "sum"))
            ci = len(partial_specs)
            partial_specs.append((ref, "count"))
            final_plan.append(("mean", si, ci))
        elif op in ("var", "std"):
            si = len(partial_specs)
            partial_specs.append((ref, "fsum"))
            qi = len(partial_specs)
            partial_specs.append((ref, "sumsq"))
            ci = len(partial_specs)
            partial_specs.append((ref, "count"))
            final_plan.append((op, si, qi, ci))
        else:
            if op not in _REAGG:
                raise ValueError(
                    f"aggregation {op!r} is not supported in the "
                    "distributed groupby (no partial/re-aggregation "
                    f"decomposition); supported: "
                    f"{sorted(_REAGG) + ['mean', 'var', 'std']}")
            i = len(partial_specs)
            partial_specs.append((ref, op))
            final_plan.append(("direct", i, _REAGG[op]))
    return partial_specs, final_plan


def _padded_table(out_keys, out_aggs, key_names):
    cols, names = [], []
    for spec, nm in zip(out_keys, key_names):
        if spec[0] == "string":
            # internal invariant, not a user-facing limit: the public entry
            # points (distributed_groupby/distributed_join) explode STRING
            # columns into fixed-width (len, word...) columns before building
            # this program (stringplane.explode_strings), so no string spec
            # can reach the exchange
            raise AssertionError(
                "string key reached the distributed exchange unexploded; "
                "use distributed_groupby/distributed_join (they explode "
                "strings via stringplane), or explode_strings() first")
        _, dtype, data, valid = spec
        cols.append(Column(dtype, data=data, validity=valid))
        names.append(nm if isinstance(nm, str) else f"key{nm}")
    for i, c in enumerate(out_aggs):
        cols.append(c)
        names.append(f"agg{i}")
    return Table(cols, names)


@functools.lru_cache(maxsize=64)
def build_distributed_groupby(mesh: Mesh, schema: tuple, names: tuple,
                              key_names: tuple, aggs: tuple,
                              capacity: int, axis: str = ROW_AXIS,
                              masked: bool = False,
                              key_specs: tuple | None = None):
    """Compile-once distributed GROUP BY for a fixed schema.

    Returns fn(datas, masks[, n_valid]) -> (key+agg padded buffers, live
    mask, ngroups per shard, overflow) operating on row-sharded column
    buffers.

    With ``masked=True`` the function takes a traced scalar ``n_valid`` (the
    original, pre-padding global row count) so ONE compiled program serves
    any row count at a fixed padded shape.  Rows at global index >= n_valid
    are pad_to_multiple null rows and are masked out of the local partial
    pass — without this they would form a spurious null-key group and
    corrupt genuine null-key aggregates.
    """
    ndev = axis_size(mesh, axis)
    partial_specs, final_plan = _expand_aggs(aggs)
    # var/std moment partials are computed over globally mean-shifted values
    # (variance is shift-invariant; without the shift the (Σx², Σx) combine
    # cancels catastrophically when |mean| >> std, e.g. timestamp columns)
    shift_idx = set()
    for plan in final_plan:
        if plan[0] in ("var", "std"):
            shift_idx.update((plan[1], plan[2]))

    def shard_fn(datas, masks, n_valid=None):
        shard_tbl = Table([Column(dt, data=d, validity=m)
                           for dt, d, m in zip(schema, datas, masks)],
                          list(names))
        n_local = shard_tbl.num_rows
        if n_valid is None:
            row_mask = None
        else:
            # shards are contiguous row ranges: shard i owns global rows
            # [i * n_local, (i+1) * n_local)
            shard_idx = jax.lax.axis_index(axis).astype(jnp.int64)
            global_row = shard_idx * n_local + jnp.arange(n_local,
                                                          dtype=jnp.int64)
            row_mask = global_row < n_valid
        specs = list(partial_specs)
        if shift_idx:
            from ..ops.aggregate import _float64_vals
            live = row_mask if row_mask is not None \
                else jnp.ones((n_local,), jnp.bool_)
            shifted = {}
            for i in shift_idx:
                ref = partial_specs[i][0]
                if ref not in shifted:
                    c = shard_tbl.column(ref)
                    vf = _float64_vals(c, c.data)
                    ok = c.valid_mask() & live
                    gs = jax.lax.psum(jnp.sum(jnp.where(ok, vf, 0.0)), axis)
                    gc = jax.lax.psum(jnp.sum(ok.astype(jnp.int64)), axis)
                    gm = gs / jnp.maximum(gc, 1).astype(jnp.float64)
                    shifted[ref] = Column.fixed(FLOAT64, vf - gm,
                                                validity=c.validity)
                specs[i] = (shifted[ref], partial_specs[i][1])
        # 1. local partial aggregation (padded to shard rows)
        out_keys, out_aggs, ng_local = groupby_padded(
            shard_tbl, list(key_names), specs, row_mask=row_mask)
        live_local = jnp.arange(n_local, dtype=jnp.int32) < ng_local

        partial_tbl = _padded_table(out_keys, out_aggs, key_names)
        playout = fixed_width_layout(partial_tbl.dtypes())
        pdatas = tuple(c.data for c in partial_tbl.columns)
        pmasks = tuple(c.validity for c in partial_tbl.columns)

        # 2. exchange partial groups by key hash (word planes over ICI);
        # string keys partition by Spark UTF8String murmur3 over their
        # exploded words (partition_ids_specs)
        if key_specs is not None:
            dest = partition_ids_specs(list(partial_tbl.columns),
                                       key_specs, ndev)
        else:
            key_cols = [partial_tbl.column(i) for i in range(len(key_names))]
            dest = partition_ids(Table(key_cols), ndev)
        planes = _build_planes(playout, pdatas, pmasks)
        planes_in, mask_in, overflow = exchange_planes(
            planes, dest, live_local, ndev, capacity, axis)

        # 3. final aggregation over received partials
        rdatas, rmasks = _from_planes(playout, planes_in)
        rtbl = Table([Column(dt, data=d, validity=m) for dt, d, m in
                      zip(playout.schema, rdatas, rmasks)],
                     list(partial_tbl.names))
        final_specs = []
        for plan in final_plan:
            if plan[0] == "mean":
                final_specs.append((f"agg{plan[1]}", "sum"))
                final_specs.append((f"agg{plan[2]}", "sum"))
            elif plan[0] in ("var", "std"):
                final_specs.append((f"agg{plan[1]}", "sum"))
                final_specs.append((f"agg{plan[2]}", "sum"))
                final_specs.append((f"agg{plan[3]}", "sum"))
            else:
                final_specs.append((f"agg{plan[1]}", plan[2]))
        fkeys, faggs, ng = groupby_padded(rtbl, list(key_names), final_specs,
                                          row_mask=mask_in)

        # 4. assemble outputs; resolve means
        out_cols = []
        fi = 0
        for plan in final_plan:
            if plan[0] == "mean":
                s, c = faggs[fi], faggs[fi + 1]
                fi += 2
                sv = s.float_values() if s.dtype.id == TypeId.FLOAT64 \
                    else s.data.astype(jnp.float64)
                m = sv / jnp.maximum(c.data, 1).astype(jnp.float64)
                valid = (c.data > 0) if s.validity is None \
                    else (s.validity & (c.data > 0))
                out_cols.append(Column.fixed(FLOAT64, m, validity=valid))
            elif plan[0] in ("var", "std"):
                s, q, c = faggs[fi], faggs[fi + 1], faggs[fi + 2]
                fi += 3
                sv = s.float_values()
                qv = q.float_values()
                nf = jnp.maximum(c.data, 1).astype(jnp.float64)
                var = jnp.maximum(
                    (qv - sv * sv / nf) / jnp.maximum(nf - 1.0, 1.0), 0.0)
                data = jnp.sqrt(var) if plan[0] == "std" else var
                out_cols.append(Column.fixed(FLOAT64, data,
                                             validity=c.data > 1))
            else:
                out_cols.append(faggs[fi])
                fi += 1
        # arrays only across the shard_map boundary (dtypes are static,
        # reconstructed by the caller from the plan)
        key_data = tuple(spec[2] for spec in fkeys)
        key_valid = tuple(spec[3] for spec in fkeys)
        agg_data = tuple(c.data for c in out_cols)
        agg_valid = tuple(c.valid_mask() for c in out_cols)
        live_out = jnp.arange(ndev * capacity, dtype=jnp.int32) < ng
        return (key_data, key_valid, agg_data, agg_valid, live_out,
                jnp.reshape(ng, (1,)), jax.lax.psum(overflow, axis))

    spec = P(axis)
    if masked:
        return jax.jit(shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, spec, P()),
            out_specs=(spec, spec, spec, spec, spec, spec, P()),
            check_vma=False))
    return jax.jit(shard_map(
        lambda datas, masks: shard_fn(datas, masks), mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec, spec, spec, spec, spec, P()),
        check_vma=False))


# ---------------------------------------------------------------------------
# distributed SortMergeJoin: co-partition by key hash, join locally per shard
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def build_distributed_join(mesh: Mesh, lschema: tuple, lnames: tuple,
                           rschema: tuple, rnames: tuple,
                           on_left: tuple, on_right: tuple, how: str,
                           lcap: int, rcap: int, jcap: int,
                           axis: str = ROW_AXIS,
                           lkey_specs: tuple | None = None,
                           rkey_specs: tuple | None = None):
    """Compile-once distributed equi-join for fixed schemas.

    The physical plan Spark runs as GpuShuffledHashJoin/SortMergeJoin
    (BASELINE configs[3]) as ONE jitted shard_map program: both sides
    hash-partition by join key over ICI all_to_all (co-partitioning), then
    each shard joins its partitions locally with the padded sorted-probe
    join (ops.join.inner_join_padded).  Returns fn(ldatas, lmasks, rdatas,
    rmasks) -> (lsel, rsel, live, rvalid, counts, overflows) where lsel/rsel
    index the *exchanged* padded shard tables whose buffers are also
    returned; the host wrapper assembles and compacts.
    """
    from ..ops.join import inner_join_padded
    ndev = axis_size(mesh, axis)
    llayout = fixed_width_layout(list(lschema))
    rlayout = fixed_width_layout(list(rschema))

    def exchange(layout, names, schema, datas, masks, key_names, cap,
                 kspecs):
        tbl = Table([Column(dt_, data=d, validity=m)
                     for dt_, d, m in zip(schema, datas, masks)], list(names))
        if kspecs is not None:
            dest = partition_ids_specs(list(tbl.columns), kspecs, ndev)
        else:
            keys = [tbl.column(k) for k in key_names]
            dest = partition_ids(Table(keys), ndev)
        planes = _build_planes(layout, datas, masks)
        planes_in, live_in, overflow = exchange_planes(
            planes, dest, None, ndev, cap, axis)
        d_in, m_in = _from_planes(layout, list(planes_in))
        tbl_in = Table([Column(dt_, data=d, validity=m)
                        for dt_, d, m in zip(layout.schema, d_in, m_in)],
                       list(names))
        return tbl_in, live_in, overflow

    def shard_fn(ldatas, lmasks, rdatas, rmasks):
        ltbl, llive, lovf = exchange(llayout, lnames, lschema, ldatas,
                                     lmasks, on_left, lcap, lkey_specs)
        rtbl, rlive, rovf = exchange(rlayout, rnames, rschema, rdatas,
                                     rmasks, on_right, rcap, rkey_specs)
        # pack=False: the host wrapper compacts by mask, so the
        # front-packing compaction sort would be pure waste
        li, ri, jlive, npairs, jovf = inner_join_padded(
            ltbl, rtbl, list(on_left), list(on_right), jcap,
            left_live=llive, right_live=rlive, pack=False)

        if how in ("inner", "left", "right", "full"):
            nl = ndev * lcap
            nr = ndev * rcap
            lvalid = jnp.ones(jlive.shape, jnp.bool_)
            rvalid = jlive
            live = jlive
            # matched masks over the ORIGINAL pair arrays, before any
            # outer-extension concatenation below changes their length
            matched_l = jnp.zeros((nl,), jnp.bool_)
            matched_r = jnp.zeros((nr,), jnp.bool_)
            if jcap:
                matched_l = matched_l.at[li].max(jlive)
                matched_r = matched_r.at[ri].max(jlive)
            if how in ("left", "full"):
                li = jnp.concatenate([li, jnp.arange(nl, dtype=jnp.int32)])
                ri = jnp.concatenate([ri, jnp.zeros((nl,), jnp.int32)])
                lvalid = jnp.concatenate([lvalid, jnp.ones((nl,), jnp.bool_)])
                rvalid = jnp.concatenate([rvalid, jnp.zeros((nl,), jnp.bool_)])
                live = jnp.concatenate(
                    [live, llive & jnp.logical_not(matched_l)])
            if how in ("right", "full"):
                li = jnp.concatenate([li, jnp.zeros((nr,), jnp.int32)])
                ri = jnp.concatenate([ri, jnp.arange(nr, dtype=jnp.int32)])
                lvalid = jnp.concatenate([lvalid, jnp.zeros((nr,), jnp.bool_)])
                rvalid = jnp.concatenate([rvalid, jnp.ones((nr,), jnp.bool_)])
                live = jnp.concatenate(
                    [live, rlive & jnp.logical_not(matched_r)])
            lsel = tuple(jnp.take(c.data, li, axis=0) for c in ltbl.columns)
            lselv = tuple(jnp.take(c.valid_mask(), li) & lvalid
                          for c in ltbl.columns)
            rsel = tuple(jnp.take(c.data, ri, axis=0) for c in rtbl.columns)
            rselv = tuple(jnp.take(c.valid_mask(), ri) & rvalid
                          for c in rtbl.columns)
            if how in ("right", "full"):
                # coalesce key columns shard-side: rows missing on the left
                # (right-extra rows) take the right side's key value, so the
                # host wrapper's drop-right-keys projection stays correct
                lsel, lselv = list(lsel), list(lselv)
                for lk_name, rk_name in zip(on_left, on_right):
                    i = list(lnames).index(lk_name)
                    j = list(rnames).index(rk_name)
                    rkey = jnp.take(rtbl.columns[j].data, ri, axis=0)
                    lmask = lvalid.reshape(
                        lvalid.shape + (1,) * (rkey.ndim - 1))
                    lsel[i] = jnp.where(lmask, lsel[i], rkey)
                    lselv[i] = jnp.where(
                        lvalid, lselv[i],
                        jnp.take(rtbl.columns[j].valid_mask(), ri) & rvalid)
                lsel, lselv = tuple(lsel), tuple(lselv)
            nrows = jnp.sum(live.astype(jnp.int32))
            return (lsel, lselv, rsel, rselv, live, jnp.reshape(nrows, (1,)),
                    jax.lax.psum(lovf + rovf, axis),
                    jax.lax.psum(jovf, axis))

        # semi / anti: left rows with (no) matching key on the co-partition
        nl = ndev * lcap
        matched = jnp.zeros((nl,), jnp.bool_)
        if jcap:
            matched = matched.at[li].max(jlive)
        keep = llive & (matched if how == "semi" else jnp.logical_not(matched))
        lsel = tuple(c.data for c in ltbl.columns)
        lselv = tuple(c.valid_mask() for c in ltbl.columns)
        nrows = jnp.sum(keep.astype(jnp.int32))
        return (lsel, lselv, (), (), keep, jnp.reshape(nrows, (1,)),
                jax.lax.psum(lovf + rovf, axis), jax.lax.psum(jovf, axis))

    spec = P(axis)
    return jax.jit(shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec, spec, spec, P(), P()),
        check_vma=False))


@traced("distributed_join")
def distributed_join(left: Table, right: Table, mesh: Mesh, on_left,
                     on_right=None, how: str = "inner",
                     capacity: int | None = None,
                     join_capacity: int | None = None,
                     suffixes=("", "_r"), axis: str = ROW_AXIS) -> Table:
    """Distributed equi-join (inner/left/right/full/semi/anti); compacts to a
    host Table.

    Both sides are hash-partitioned on the join keys over the mesh, then
    joined shard-locally — the 8-chip shuffle + SortMergeJoin plan of
    BASELINE configs[3].  Outer rows (left/right/full) are shard-local
    correct because co-partitioning puts every occurrence of a key on one
    shard.  STRING columns travel in padded-bucket form; string JOIN KEYS
    are exploded at one common bucket width across both sides (the word
    count is part of the key identity — different widths would partition
    the same string to different shards).  ``capacity`` bounds rows
    received per (source, dest) pair per side; ``join_capacity`` bounds
    candidate pairs per shard.  Overflow raises with the counts, never
    silently drops.
    """
    from .mesh import pad_to_multiple, shard_table
    from .stringplane import explode_strings, reassemble_strings
    from ..ops.strings_common import string_width_bucket
    on_right = list(on_right or on_left)
    on_left = list(on_left)
    ndev = axis_size(mesh, axis)

    def _key_width(t, k):
        c = t.column(k)
        return string_width_bucket(c) if c.dtype.is_string else None

    lov, rov = {}, {}
    for lk, rk in zip(on_left, on_right):
        wl, wr = _key_width(left, lk), _key_width(right, rk)
        if wl is not None or wr is not None:
            w = max(wl or 0, wr or 0)
            lov[lk], rov[rk] = w, w

    def prep(t, keys, overrides):
        plan = None
        if any(c.dtype.is_string for c in t.columns):
            t, plan = explode_strings(t, width_overrides=overrides)
            keys = plan.exploded_keys(keys)
        if t.num_rows % ndev:
            t, _ = pad_to_multiple(t, ndev)
            # padded rows are all-null: null keys never match (SQL equi-join)
        t = shard_table(t, mesh, axis)
        return t, keys, plan

    lt, lkeys, lplan = prep(left, on_left, lov)
    rt, rkeys, rplan = prep(right, on_right, rov)
    if len(lkeys) != len(rkeys):
        raise TypeError(
            f"join key shapes disagree after explosion: {lkeys} vs {rkeys} "
            "(string keys must pair with string keys)")
    # Spark-exact partitioning: string keys hash their UTF-8 bytes, and
    # CO-PARTITIONING demands the two sides agree — the byte hash does by
    # construction (the exploded-representation hash only agreed because
    # widths were forced equal)
    lkey_specs = key_specs_for(lt, on_left, lplan)
    rkey_specs = key_specs_for(rt, on_right, rplan)
    auto_cap = capacity is None
    auto_jcap = join_capacity is None
    if auto_cap:
        # two-phase exchange: counts are exact for joins (no pre-agg dedup);
        # each side sized independently (builder takes lcap/rcap)
        lcounts = partition_counts(lt, mesh, lkeys, axis,
                                   key_specs=lkey_specs)
        rcounts = partition_counts(rt, mesh, rkeys, axis,
                                   key_specs=rkey_specs)
        lcap = cap_bucket(int(lcounts.max()))
        rcap = cap_bucket(int(rcounts.max()))
        if auto_jcap:
            # candidate pairs per shard start at (received left + received
            # right) rows — exact for FK-style joins, and the overflow
            # retry below right-sizes heavy-duplicate keys.  Fine buckets:
            # jcap is the largest sort in the program, so 2x pow2 padding
            # is real work.
            recv = int(lcounts.sum(axis=0).max() + rcounts.sum(axis=0).max())
            join_capacity = cap_bucket_fine(recv)
    else:
        lcap = rcap = capacity
    if auto_jcap and join_capacity is None:
        join_capacity = 2 * ndev * max(lcap, rcap)

    lnames = tuple(lt.names or [f"l{i}" for i in range(lt.num_columns)])
    rnames = tuple(rt.names or [f"r{i}" for i in range(rt.num_columns)])
    largs = (tuple(c.data for c in lt.columns),
             tuple(c.validity for c in lt.columns))
    rargs = (tuple(c.data for c in rt.columns),
             tuple(c.validity for c in rt.columns))
    # Join cardinality is data-dependent; the counted overflows say exactly
    # how much was missing, so auto-sized capacities retry right-sized
    # (explicitly passed capacities are contracts and raise instead).
    for _attempt in range(8):
        fn = build_distributed_join(
            mesh, tuple(lt.dtypes()), lnames, tuple(rt.dtypes()), rnames,
            tuple(lkeys), tuple(rkeys), how, lcap, rcap,
            join_capacity, axis, lkey_specs, rkey_specs)
        (lsel, lselv, rsel, rselv, live, _n, xovf, jovf) = fn(
            *largs, *rargs)
        if int(xovf) > 0:
            # structurally unreachable with counts-based sizing; kept as a
            # defense-in-depth invariant for explicitly passed capacities
            if not auto_cap:
                raise RuntimeError(
                    f"distributed_join exchange overflow ({int(xovf)} rows); "
                    f"rerun with larger capacity (got {lcap}/{rcap})")
            lcap = 2 * lcap + (int(xovf) + ndev - 1) // ndev
            rcap = 2 * rcap + (int(xovf) + ndev - 1) // ndev
            if auto_jcap:
                join_capacity = 2 * ndev * max(lcap, rcap)
            continue
        if int(jovf) > 0:
            if not auto_jcap:
                raise RuntimeError(
                    f"distributed_join pair overflow ({int(jovf)} candidate "
                    f"pairs); rerun with larger join_capacity "
                    f"(got {join_capacity})")
            join_capacity = join_capacity + int(jovf) + 63 & ~63
            continue
        break
    else:
        raise RuntimeError("distributed_join failed to size its exchange")

    live_np = np.asarray(live)
    def compact(specs, valids, schema, names):
        cols = []
        for dt_, d, v in zip(schema, specs, valids):
            dn = np.asarray(d)[live_np]
            vn = np.asarray(v)[live_np]
            cols.append(Column(dt_, data=jnp.asarray(dn),
                               validity=None if vn.all() else jnp.asarray(vn)))
        return Table(cols, list(names))

    ltab = compact(lsel, lselv, lt.dtypes(), lnames)
    if lplan is not None:
        ltab = reassemble_strings(ltab, lplan)
    if how in ("semi", "anti"):
        return ltab
    rtab = compact(rsel, rselv, rt.dtypes(), rnames)
    if rplan is not None:
        rtab = reassemble_strings(rtab, rplan)
    # drop right key columns; suffix collisions (cudf/Spark projection shape)
    keep = [i for i, nm in enumerate(rtab.names) if nm not in on_right]
    lout_names = list(ltab.names)
    out_cols = list(ltab.columns)
    out_names = lout_names[:]
    for i in keep:
        nm = rtab.names[i]
        out_cols.append(rtab.columns[i])
        out_names.append(nm + (suffixes[1] if nm in lout_names else ""))
    return Table(out_cols, out_names)


@functools.lru_cache(maxsize=8)
def build_distributed_cross(mesh: Mesh, axis: str = ROW_AXIS):
    """Compile-once distributed cross join: left row-sharded, right
    replicated (the Spark BroadcastNestedLoopJoin/CartesianProduct plan
    shape — no exchange at all; each shard pairs its left rows with the
    full right side)."""
    def shard_fn(ldatas, lmasks, llive, rdatas, rmasks):
        nl = ldatas[0].shape[0]
        nr = rdatas[0].shape[0]
        li = jnp.repeat(jnp.arange(nl, dtype=jnp.int32), nr)
        ri = jnp.tile(jnp.arange(nr, dtype=jnp.int32), nl)
        def sel(datas, masks, idx):
            d = tuple(jnp.take(x, idx, axis=0) for x in datas)
            v = tuple(jnp.ones(idx.shape, jnp.bool_) if m is None
                      else jnp.take(m, idx) for m in masks)
            return d, v
        lsel, lselv = sel(ldatas, lmasks, li)
        rsel, rselv = sel(rdatas, rmasks, ri)
        live = jnp.take(llive, li)
        return lsel, lselv, rsel, rselv, live
    spec = P(axis)
    return jax.jit(shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec, spec, spec, P(), P()),
        out_specs=(spec, spec, spec, spec, spec), check_vma=False))


@traced("distributed_cross_join")
def distributed_cross_join(left: Table, right: Table, mesh: Mesh,
                           suffixes=("", "_r"), axis: str = ROW_AXIS) -> Table:
    """Distributed Cartesian product; compacts to a host Table.

    Left is row-sharded over the mesh, right is replicated to every shard
    (no collective traffic).  Output row order is shard-major and otherwise
    unspecified, as in Spark."""
    from .mesh import pad_to_multiple, shard_table
    from .stringplane import explode_strings, reassemble_strings
    ndev = axis_size(mesh, axis)
    lt, lplan = (explode_strings(left)
                 if any(c.dtype.is_string for c in left.columns)
                 else (left, None))
    rt, rplan = (explode_strings(right)
                 if any(c.dtype.is_string for c in right.columns)
                 else (right, None))
    n_orig = lt.num_rows
    if lt.num_rows % ndev:
        lt, n_orig = pad_to_multiple(lt, ndev)
    llive = jnp.arange(lt.num_rows, dtype=jnp.int64) < n_orig
    lt = shard_table(lt, mesh, axis)
    llive = jax.device_put(
        llive, jax.sharding.NamedSharding(mesh, P(axis)))
    fn = build_distributed_cross(mesh, axis)
    lsel, lselv, rsel, rselv, live = fn(
        tuple(c.data for c in lt.columns),
        tuple(c.validity for c in lt.columns), llive,
        tuple(c.data for c in rt.columns),
        tuple(c.validity for c in rt.columns))
    live_np = np.asarray(live)

    def compact(specs, valids, schema, names):
        cols = []
        for dt_, d, v in zip(schema, specs, valids):
            dn = np.asarray(d)[live_np]
            vn = np.asarray(v)[live_np]
            cols.append(Column(dt_, data=jnp.asarray(dn),
                               validity=None if vn.all() else jnp.asarray(vn)))
        return Table(cols, list(names))

    lnames = list(lt.names or [f"l{i}" for i in range(lt.num_columns)])
    rnames = list(rt.names or [f"r{i}" for i in range(rt.num_columns)])
    ltab = compact(lsel, lselv, lt.dtypes(), lnames)
    rtab = compact(rsel, rselv, rt.dtypes(), rnames)
    if lplan is not None:
        ltab = reassemble_strings(ltab, lplan)
    if rplan is not None:
        rtab = reassemble_strings(rtab, rplan)
    out_cols = list(ltab.columns)
    out_names = list(ltab.names)
    for nm, c in zip(rtab.names, rtab.columns):
        out_cols.append(c)
        out_names.append(nm + (suffixes[1] if nm in ltab.names else ""))
    return Table(out_cols, out_names)


@functools.lru_cache(maxsize=64)
def build_distributed_window(mesh: Mesh, schema: tuple, names_in: tuple,
                             partition_by: tuple, order_by: tuple,
                             nspecs: tuple, axis: str = ROW_AXIS):
    """Compile-once per-shard window program (jitted shard_map), keyed on
    the static plan like make_shuffle / build_distributed_groupby."""
    from ..ops.window import window as _window

    def order_key(tbl, k):
        if isinstance(k, tuple):  # (name, ascending)
            from ..ops.order import SortKey
            return SortKey(tbl.column(k[0]), ascending=k[1])
        return k

    def _win_shard(datas, masks, okm):
        tbl = Table([Column(dt_, data=d, validity=m)
                     for dt_, d, m in zip(schema, datas, masks)],
                    list(names_in))
        out = _window(tbl, list(partition_by),
                      [order_key(tbl, k) for k in order_by],
                      [tuple(s) for s in nspecs], live=okm)
        new = out.columns[tbl.num_columns:]
        return (tuple(c.data for c in new),
                tuple(c.valid_mask() for c in new))

    return jax.jit(shard_map(
        _win_shard, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis)), check_vma=False))


@traced("distributed_window")
def distributed_window(table: Table, mesh: Mesh, partition_by: list,
                       order_by: list, specs: list, names: list | None = None,
                       axis: str = ROW_AXIS) -> Table:
    """Distributed window functions: co-partition by the partition keys over
    the mesh, then run ops.window shard-locally (exact — a window never
    crosses partitions, and a partition never crosses shards).

    Key lists must be column names (SortKey descending wrappers are applied
    shard-side for ``order_by`` via (name, False) tuples).  Returns a
    compacted host Table (row order unspecified, as in Spark).
    """
    from .mesh import pad_to_multiple, shard_table
    from .shuffle import shuffle_table_padded
    ndev = axis_size(mesh, axis)
    t = table
    live = None
    if t.num_rows % ndev:
        t, n_orig = pad_to_multiple(t, ndev)
        live = jnp.arange(t.num_rows, dtype=jnp.int64) < n_orig
    st = shard_table(t, mesh, axis)
    shuffled, ok, overflow = shuffle_table_padded(
        st, mesh, list(partition_by), axis=axis, live=live)
    if int(overflow):
        raise RuntimeError(f"window shuffle overflow: {int(overflow)} rows")

    names_in = tuple(shuffled.names or
                     [f"c{i}" for i in range(shuffled.num_columns)])
    schema = tuple(shuffled.dtypes())
    nspecs = tuple(tuple(s) for s in specs)
    win_fn = build_distributed_window(mesh, schema, names_in,
                                      tuple(partition_by), tuple(order_by),
                                      nspecs, axis)
    datas = tuple(c.data for c in shuffled.columns)
    masks = tuple(c.validity for c in shuffled.columns)
    wdata, wvalid = win_fn(datas, masks, ok)

    keep = np.flatnonzero(np.asarray(ok))
    out_cols = [Column(c.dtype,
                       data=jnp.asarray(np.asarray(c.data)[keep]),
                       validity=None if c.validity is None else
                       jnp.asarray(np.asarray(c.validity)[keep]))
                for c in shuffled.columns]
    from ..ops.window import default_window_names, window_out_dtype
    wcols = []
    for wi, (ref, op, *rest) in enumerate(nspecs):
        d = np.asarray(wdata[wi])[keep]
        v = np.asarray(wvalid[wi])[keep]
        dtype = window_out_dtype(
            None if ref is None else shuffled.column(ref).dtype, op)
        wcols.append(Column(dtype, data=jnp.asarray(d),
                            validity=jnp.asarray(v)))
    wnames = list(names) if names is not None \
        else default_window_names(nspecs)
    return Table(out_cols + wcols, list(names_in) + wnames)


def agg_out_dtype(col_dtype: DType, op: str) -> DType:
    """Result dtype of an aggregation (mirrors ops.aggregate._agg_column)."""
    if op in ("count", "count_all"):
        return INT64
    if op in ("mean", "var", "std", "sumsq", "fsum"):
        return FLOAT64
    if op in ("min", "max"):
        return col_dtype
    if op == "sum":
        if col_dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            return FLOAT64
        return col_dtype if col_dtype.is_decimal else INT64
    raise ValueError(op)


@traced("distributed_groupby")
def distributed_groupby(table: Table, mesh: Mesh, key_names: list,
                        aggs: list, capacity: int | None = None,
                        axis: str = ROW_AXIS,
                        n_valid_rows: int | None = None) -> Table:
    """GROUP BY over a row-sharded table; compacts to a host-side Table.

    Non-mesh-divisible tables are padded internally with masked null rows.
    Callers who pre-padded with ``pad_to_multiple`` must pass the original
    row count as ``n_valid_rows`` so padding rows don't aggregate as data.

    STRING columns (keys or counted values) ride the mesh in padded-bucket
    form (stringplane.explode_strings): exploded before sharding, grouped as
    (length, byte-word) multi-keys, reassembled on the way out.
    """
    from .mesh import pad_to_multiple, shard_table
    ndev = axis_size(mesh, axis)

    orig_keys = list(key_names)
    orig_aggs = list(aggs)
    plan = None
    if any(c.dtype.is_string for c in table.columns):
        from .stringplane import explode_strings, reassemble_strings, \
            StringPlan
        table, plan = explode_strings(table)
        spec_of = dict(zip(plan.names, plan.specs))
        key_names = plan.exploded_keys(orig_keys)
        aggs = []
        for ref, op in orig_aggs:
            if spec_of.get(ref, ("fixed",))[0] == "string":
                if op not in ("count", "count_all"):
                    raise TypeError(
                        "string value aggregation not supported; "
                        "dictionary-encode first (ops.dictionary)")
                aggs.append((f"{ref}#len", op))  # same validity as the string
            else:
                aggs.append((ref, op))
    if table.num_rows % ndev:
        if n_valid_rows is not None:
            raise ValueError("table rows not mesh-divisible; pad first or "
                             "let distributed_groupby pad (omit n_valid_rows)")
        table, n_valid_rows = pad_to_multiple(table, ndev)
        table = shard_table(table, mesh, axis)
    elif plan is not None:
        # strings couldn't shard before explosion; place the exploded
        # fixed-width buffers on the mesh now
        table = shard_table(table, mesh, axis)
    # Spark-exact partition hashing (string keys by UTF8 murmur3): specs
    # over the full exploded table for the counts pass, and over the
    # partial-group table (keys lead its columns) for the exchange
    tbl_specs = key_specs_for(table, orig_keys, plan)
    kcols = Table([table.column(k) for k in key_names], list(key_names))
    partial_specs = key_specs_for(kcols, orig_keys, plan)
    if capacity is None:
        # two-phase exchange: raw-row partition counts upper-bound the
        # partial-group rows each shard sends (local agg only dedups)
        counts = partition_counts(table, mesh, list(key_names), axis,
                                  n_valid_rows=n_valid_rows,
                                  key_specs=tbl_specs)
        shard_rows = table.num_rows // ndev
        capacity = min(cap_bucket(int(counts.max())),
                       cap_bucket(shard_rows))
    fn = build_distributed_groupby(
        mesh, tuple(table.dtypes()),
        tuple(table.names or [f"c{i}" for i in range(table.num_columns)]),
        tuple(key_names), tuple(aggs), capacity, axis,
        masked=n_valid_rows is not None, key_specs=partial_specs)
    datas = tuple(c.data for c in table.columns)
    masks = tuple(c.validity for c in table.columns)
    if n_valid_rows is not None:
        (key_data, key_valid, agg_data, agg_valid, live, _ng,
         overflow) = fn(datas, masks, jnp.int64(n_valid_rows))
    else:
        (key_data, key_valid, agg_data, agg_valid, live, _ng,
         overflow) = fn(datas, masks)
    if int(overflow) > 0:
        raise RuntimeError(
            f"shuffle capacity overflow ({int(overflow)} rows); rerun with "
            f"larger capacity (got {capacity})")

    live_np = np.asarray(live)
    key_dtypes = [table.column(k).dtype for k in key_names]
    agg_dtypes = [agg_out_dtype(table.column(ref).dtype, op)
                  for ref, op in aggs]
    cols = []
    agg_out_names = [f"{op}_{ref}" for ref, op in orig_aggs]
    names = list(key_names) + agg_out_names
    for dtype, data, valid in zip(
            key_dtypes + agg_dtypes,
            list(key_data) + list(agg_data),
            list(key_valid) + list(agg_valid)):
        d = np.asarray(data)[live_np]
        v = np.asarray(valid)[live_np]
        cols.append(Column(dtype, data=jnp.asarray(d),
                           validity=None if v.all() else jnp.asarray(v)))
    result = Table(cols, names)
    if plan is not None:
        # fold exploded key columns back into strings
        out_specs = tuple([spec_of[k] for k in orig_keys]
                          + [("fixed",)] * len(orig_aggs))
        out_plan = StringPlan(tuple(orig_keys + agg_out_names), out_specs)
        result = reassemble_strings(result, out_plan)
    return result
