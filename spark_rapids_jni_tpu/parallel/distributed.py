"""Distributed query plans: shuffle-then-aggregate, shuffle-then-join.

The classic Spark physical plan for GROUP BY — partial aggregation, hash
exchange, final aggregation (what spark-rapids runs as GpuHashAggregate +
GpuShuffleExchange) — expressed as ONE jittable XLA program over the mesh:

    local groupby_padded  ->  row-blob all_to_all  ->  final groupby_padded

Everything stays in HBM; the exchange rides ICI.  Outputs are padded per
shard (static shapes) with a live-row mask; ``distributed_groupby`` compacts
at the host boundary, ``distributed_groupby_padded`` is the pure function for
pjit pipelines (the dryrun/benchmark entry).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..columnar import Column, Table
from ..dtypes import DType, TypeId, INT64, FLOAT64
from ..ops.aggregate import groupby_padded
from ..ops.row_conversion import fixed_width_layout, _to_row_words, \
    _from_row_words
from .mesh import ROW_AXIS
from .shuffle import partition_ids, _bucket_scatter

# (partial op emitted by the local pass, final re-aggregation op)
_REAGG = {"sum": "sum", "count": "sum", "count_all": "sum",
          "min": "min", "max": "max"}


def _expand_aggs(aggs):
    """mean decomposes into (sum, count) partials + a final divide."""
    partial_specs = []   # (col_ref, op) for the local pass
    final_plan = []      # ("direct", partial_idx, final_op) | ("mean", si, ci)
    for ref, op in aggs:
        if op == "mean":
            si = len(partial_specs)
            partial_specs.append((ref, "sum"))
            ci = len(partial_specs)
            partial_specs.append((ref, "count"))
            final_plan.append(("mean", si, ci))
        else:
            i = len(partial_specs)
            partial_specs.append((ref, op))
            final_plan.append(("direct", i, _REAGG[op]))
    return partial_specs, final_plan


def _padded_table(out_keys, out_aggs, key_names):
    cols, names = [], []
    for spec, nm in zip(out_keys, key_names):
        if spec[0] == "string":
            raise TypeError("string keys not supported in the distributed "
                            "path yet (dictionary-encode first)")
        _, dtype, data, valid = spec
        cols.append(Column(dtype, data=data, validity=valid))
        names.append(nm if isinstance(nm, str) else f"key{nm}")
    for i, c in enumerate(out_aggs):
        cols.append(c)
        names.append(f"agg{i}")
    return Table(cols, names)


def build_distributed_groupby(mesh: Mesh, schema: tuple, names: tuple,
                              key_names: tuple, aggs: tuple,
                              capacity: int, axis: str = ROW_AXIS,
                              masked: bool = False):
    """Compile-once distributed GROUP BY for a fixed schema.

    Returns fn(datas, masks[, n_valid]) -> (key+agg padded buffers, live
    mask, ngroups per shard, overflow) operating on row-sharded column
    buffers.

    With ``masked=True`` the function takes a traced scalar ``n_valid`` (the
    original, pre-padding global row count) so ONE compiled program serves
    any row count at a fixed padded shape.  Rows at global index >= n_valid
    are pad_to_multiple null rows and are masked out of the local partial
    pass — without this they would form a spurious null-key group and
    corrupt genuine null-key aggregates.
    """
    ndev = mesh.shape[axis]
    partial_specs, final_plan = _expand_aggs(aggs)

    def shard_fn(datas, masks, n_valid=None):
        shard_tbl = Table([Column(dt, data=d, validity=m)
                           for dt, d, m in zip(schema, datas, masks)],
                          list(names))
        n_local = shard_tbl.num_rows
        if n_valid is None:
            row_mask = None
        else:
            # shards are contiguous row ranges: shard i owns global rows
            # [i * n_local, (i+1) * n_local)
            shard_idx = jax.lax.axis_index(axis).astype(jnp.int64)
            global_row = shard_idx * n_local + jnp.arange(n_local,
                                                          dtype=jnp.int64)
            row_mask = global_row < n_valid
        # 1. local partial aggregation (padded to shard rows)
        out_keys, out_aggs, ng_local = groupby_padded(
            shard_tbl, list(key_names), list(partial_specs),
            row_mask=row_mask)
        live_local = jnp.arange(n_local, dtype=jnp.int32) < ng_local

        partial_tbl = _padded_table(out_keys, out_aggs, key_names)
        playout = fixed_width_layout(partial_tbl.dtypes())
        pdatas = tuple(c.data for c in partial_tbl.columns)
        pmasks = tuple(c.validity for c in partial_tbl.columns)

        # 2. exchange partial groups by key hash (row blobs over ICI)
        key_cols = [partial_tbl.column(i) for i in range(len(key_names))]
        dest = partition_ids(Table(key_cols), ndev)
        rows = _to_row_words(playout, pdatas, pmasks)
        send, ok, overflow = _bucket_scatter(rows, dest, live_local, ndev,
                                             capacity)
        recv = jax.lax.all_to_all(send, axis, 0, 0)
        rok = jax.lax.all_to_all(ok, axis, 0, 0)
        rows_in = recv.reshape(ndev * capacity, rows.shape[1])
        mask_in = rok.reshape(ndev * capacity)

        # 3. final aggregation over received partials
        rdatas, rmasks = _from_row_words(playout, rows_in)
        rtbl = Table([Column(dt, data=d, validity=m) for dt, d, m in
                      zip(playout.schema, rdatas, rmasks)],
                     list(partial_tbl.names))
        final_specs = []
        for plan in final_plan:
            if plan[0] == "mean":
                final_specs.append((f"agg{plan[1]}", "sum"))
                final_specs.append((f"agg{plan[2]}", "sum"))
            else:
                final_specs.append((f"agg{plan[1]}", plan[2]))
        fkeys, faggs, ng = groupby_padded(rtbl, list(key_names), final_specs,
                                          row_mask=mask_in)

        # 4. assemble outputs; resolve means
        out_cols = []
        fi = 0
        for plan in final_plan:
            if plan[0] == "mean":
                s, c = faggs[fi], faggs[fi + 1]
                fi += 2
                sv = s.float_values() if s.dtype.id == TypeId.FLOAT64 \
                    else s.data.astype(jnp.float64)
                m = sv / jnp.maximum(c.data, 1).astype(jnp.float64)
                valid = (c.data > 0) if s.validity is None \
                    else (s.validity & (c.data > 0))
                out_cols.append(Column.fixed(FLOAT64, m, validity=valid))
            else:
                out_cols.append(faggs[fi])
                fi += 1
        # arrays only across the shard_map boundary (dtypes are static,
        # reconstructed by the caller from the plan)
        key_data = tuple(spec[2] for spec in fkeys)
        key_valid = tuple(spec[3] for spec in fkeys)
        agg_data = tuple(c.data for c in out_cols)
        agg_valid = tuple(c.valid_mask() for c in out_cols)
        live_out = jnp.arange(ndev * capacity, dtype=jnp.int32) < ng
        return (key_data, key_valid, agg_data, agg_valid, live_out,
                jnp.reshape(ng, (1,)), jax.lax.psum(overflow, axis))

    spec = P(axis)
    if masked:
        return shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, spec, P()),
            out_specs=(spec, spec, spec, spec, spec, spec, P()),
            check_vma=False)
    return shard_map(
        lambda datas, masks: shard_fn(datas, masks), mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec, spec, spec, spec, spec, P()),
        check_vma=False)


def agg_out_dtype(col_dtype: DType, op: str) -> DType:
    """Result dtype of an aggregation (mirrors ops.aggregate._agg_column)."""
    if op in ("count", "count_all"):
        return INT64
    if op == "mean":
        return FLOAT64
    if op in ("min", "max"):
        return col_dtype
    if op == "sum":
        if col_dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            return FLOAT64
        return col_dtype if col_dtype.is_decimal else INT64
    raise ValueError(op)


def distributed_groupby(table: Table, mesh: Mesh, key_names: list,
                        aggs: list, capacity: int | None = None,
                        axis: str = ROW_AXIS,
                        n_valid_rows: int | None = None) -> Table:
    """GROUP BY over a row-sharded table; compacts to a host-side Table.

    Non-mesh-divisible tables are padded internally with masked null rows.
    Callers who pre-padded with ``pad_to_multiple`` must pass the original
    row count as ``n_valid_rows`` so padding rows don't aggregate as data.
    """
    from .mesh import pad_to_multiple, shard_table
    ndev = mesh.shape[axis]
    if table.num_rows % ndev:
        if n_valid_rows is not None:
            raise ValueError("table rows not mesh-divisible; pad first or "
                             "let distributed_groupby pad (omit n_valid_rows)")
        table, n_valid_rows = pad_to_multiple(table, ndev)
        table = shard_table(table, mesh, axis)
    if capacity is None:
        capacity = table.num_rows // ndev
    fn = build_distributed_groupby(
        mesh, tuple(table.dtypes()),
        tuple(table.names or [f"c{i}" for i in range(table.num_columns)]),
        tuple(key_names), tuple(aggs), capacity, axis,
        masked=n_valid_rows is not None)
    datas = tuple(c.data for c in table.columns)
    masks = tuple(c.validity for c in table.columns)
    if n_valid_rows is not None:
        (key_data, key_valid, agg_data, agg_valid, live, _ng,
         overflow) = jax.jit(fn)(datas, masks, jnp.int64(n_valid_rows))
    else:
        (key_data, key_valid, agg_data, agg_valid, live, _ng,
         overflow) = jax.jit(fn)(datas, masks)
    if int(overflow) > 0:
        raise RuntimeError(
            f"shuffle capacity overflow ({int(overflow)} rows); rerun with "
            f"larger capacity (got {capacity})")

    live_np = np.asarray(live)
    key_dtypes = [table.column(k).dtype for k in key_names]
    agg_dtypes = [agg_out_dtype(table.column(ref).dtype, op)
                  for ref, op in aggs]
    cols = []
    names = list(key_names) + [f"{op}_{ref}" for ref, op in aggs]
    for dtype, data, valid in zip(
            key_dtypes + agg_dtypes,
            list(key_data) + list(agg_data),
            list(key_valid) + list(agg_valid)):
        d = np.asarray(data)[live_np]
        v = np.asarray(valid)[live_np]
        cols.append(Column(dtype, data=jnp.asarray(d),
                           validity=None if v.all() else jnp.asarray(v)))
    return Table(cols, names)
