"""Spill-capable shuffle: exchanges larger than HBM, in bounded passes.

The storage half of the GDS role (reference CMakeLists.txt:176-199 builds
cufilejni so SPILL and shuffle files move storage<->device without bounce
buffers): when the two-phase counts say the received payload would blow an
HBM budget, the exchange runs as MULTIPLE passes over within-destination
rank windows.  Each pass is the ordinary jitted shuffle program
(parallel/shuffle.py) at a small per-pass capacity with a row mask
selecting its window — dead rows are never sent — and each pass's received
rows leave the device immediately: into host arrays, or numpy memmaps
under ``spill_dir`` when even host RAM is too small.  Row identity and
order are deterministic (pass-major, then destination order), so
downstream consumers can stream chunk-at-a-time (the Spark shuffle-file
reader pattern) or materialize.

Fixed-width columns only (the wire planes the exchange moves); STRING
columns should be dictionary-encoded (ops/dictionary) or exploded
(parallel/stringplane) by the caller — at spill scale a padded-bucket
string plane is exactly the buffer you do not want twice in memory.
"""

from __future__ import annotations

import functools
import itertools
import os
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
try:  # moved to the jax namespace in 0.5; experimental before that
    from jax import shard_map
except ImportError:  # pragma: no cover - jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        # the replication-check kwarg was renamed check_rep -> check_vma
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_old(f, **kw)

from ..columnar import Column, Table
from ..ops.row_conversion import fixed_width_layout, _from_planes
from .mesh import ROW_AXIS, axis_size
from .shuffle import (cap_bucket, key_specs_for, make_shuffle,
                      partition_counts, _spec_columns, partition_ids_specs)
from ..utils import faults, metrics, timeline
from ..utils.errors import retry_call
from ..utils.tracing import traced


@functools.lru_cache(maxsize=32)
def make_dest_ranks(mesh: Mesh, key_specs: tuple, axis: str = ROW_AXIS):
    """Per-shard program: (datas, masks, n_valid) -> (rank within dest,
    live mask).

    One stable 2-operand sort per shard, same formulation as the bucket
    pack; computed ONCE so every spill pass reuses the ranks instead of
    re-sorting.  Rows at global index >= n_valid are pad rows
    (pad_to_multiple): they get live=False and never enter a pass window.
    """
    ndev = axis_size(mesh, axis)

    def shard_fn(datas, masks, n_valid):
        cols = _spec_columns(key_specs, datas, masks)
        dest = partition_ids_specs(cols, key_specs, ndev)
        n = dest.shape[0]
        shard_idx = jax.lax.axis_index(axis).astype(jnp.int64)
        gid = shard_idx * n + jnp.arange(n, dtype=jnp.int64)
        live = gid < n_valid
        dest = jnp.where(live, dest, jnp.int32(ndev))  # pads rank last
        idx = jnp.arange(n, dtype=jnp.int32)
        sd, si = jax.lax.sort((dest, idx), num_keys=1, is_stable=True)
        first = jnp.concatenate([jnp.ones((1,), jnp.bool_), sd[1:] != sd[:-1]])
        run_start = jax.lax.cummax(jnp.where(first, idx, jnp.int32(-1)))
        srank = idx - run_start
        _, rank = jax.lax.sort((si, srank), num_keys=1, is_stable=True)
        return rank, live

    spec = P(axis)
    return jax.jit(shard_map(shard_fn, mesh=mesh, in_specs=(spec, spec, P()),
                             out_specs=(spec, spec), check_vma=False))


_SPILL_SEQ = itertools.count(1)


def _unlink_quiet(path):
    try:
        os.unlink(path)
    except OSError:
        pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


def sweep_orphans(spill_dir: str) -> int:
    """Unlink spill files left by dead processes; returns the count.

    The happy path reclaims via ``weakref.finalize`` on the memmap, but a
    crashed query never runs its finalizers — its ``spill-<pid>-...npy``
    files survive in ``spill_dir`` forever.  Names carry the owning pid,
    so liveness is one ``kill(pid, 0)`` probe; our own files and those of
    live processes are never touched.
    """
    try:
        names = os.listdir(spill_dir)
    except OSError:
        return 0
    me = os.getpid()
    reaped = 0
    for name in names:
        if not (name.startswith("spill-") and name.endswith(".npy")):
            continue
        parts = name.split("-")
        try:
            pid = int(parts[1])
        except (IndexError, ValueError):
            continue
        if pid == me or _pid_alive(pid):
            continue
        path = os.path.join(spill_dir, name)
        try:
            os.unlink(path)
            reaped += 1
        except OSError:
            continue
    if reaped:
        metrics.count("parallel.spill.orphans_reaped", reaped)
        from ..utils.config import logger
        logger().warning("reaped %d orphaned spill file(s) in %s",
                         reaped, spill_dir)
    return reaped


def _spill_buffers(schema, total_rows, spill_dir):
    """Per-column output buffers: RAM numpy, or memmaps under spill_dir."""
    from ..dtypes import TypeId
    datas, valids = [], []
    for i, dtp in enumerate(schema):
        npdt = np.dtype(dtp.device_storage)
        shape = (total_rows, 2) if dtp.id == TypeId.DECIMAL128 \
            else (total_rows,)
        if spill_dir is None:
            datas.append(np.empty(shape, npdt))
        else:
            # unique per call: a fixed name would silently overwrite the
            # buffers backing a still-live earlier spill result
            mm = np.lib.format.open_memmap(
                os.path.join(spill_dir,
                             f"spill-{os.getpid()}-{next(_SPILL_SEQ)}"
                             f"-col{i}.npy"),
                mode="w+", dtype=npdt, shape=shape)
            # reclaim disk when the buffer dies: unlink-while-mapped is
            # safe on POSIX (views keep the base memmap alive)
            weakref.finalize(mm, _unlink_quiet, mm.filename)
            datas.append(mm)
        valids.append(np.ones(total_rows, np.bool_))
    return datas, valids


@traced("shuffle_table_spilled")
def shuffle_table_spilled(table: Table, mesh: Mesh, keys: list,
                          hbm_budget_bytes: int,
                          spill_dir: str | None = None,
                          axis: str = ROW_AXIS,
                          key_specs: tuple | None = None):
    """Shuffle by key hash with the device working set bounded by
    ``hbm_budget_bytes``; returns a HOST-resident compacted Table (numpy
    buffers, or memmaps under ``spill_dir``, unlinked automatically when
    the result is garbage-collected).

    Row placement is identical to ``shuffle_table_padded`` (Spark
    HashPartitioning); output rows appear pass-major, destination-shard
    order within a pass — deterministic, so streamed consumers can
    re-group.  The result's buffers are HOST arrays (jnp lifts them back
    to the device lazily if an op touches them — re-loading spilled data
    is the consumer's explicit choice, as with Spark shuffle files).
    """
    if any(c.dtype.is_string for c in table.columns):
        raise TypeError(
            "spilled shuffle is fixed-width only; dictionary-encode "
            "(ops/dictionary) or explode (parallel/stringplane) first")
    from .mesh import pad_to_multiple, shard_table
    if spill_dir is not None:
        sweep_orphans(spill_dir)
    ndev = axis_size(mesh, axis)
    n_valid = table.num_rows
    if table.num_rows % ndev:
        # pad internally with masked null rows (never sent, never output)
        table, n_valid = pad_to_multiple(table, ndev)
    st = shard_table(table, mesh, axis)
    layout = fixed_width_layout(st.dtypes())
    if key_specs is None:
        key_specs = key_specs_for(st, keys, None)

    counts = partition_counts(st, mesh, list(keys), axis,
                              n_valid_rows=n_valid, key_specs=key_specs)
    max_cap = int(counts.max())          # the one-shot capacity
    row_bytes = layout.row_size
    # per-pass capacity from the budget: a pass holds the received block
    # (ndev*ndev*cap*row_bytes of planes) plus the send block of the same
    # size in flight
    budget_rows = max(32, int(hbm_budget_bytes // (2 * ndev * ndev *
                                                   row_bytes)))
    # round DOWN to a power of two: rounding up could double the pass's
    # device block and bust the budget — the one thing this path promises
    cap_slice = 1 << (budget_rows.bit_length() - 1)
    cap_slice = min(cap_slice, cap_bucket(max(max_cap, 1)))
    npasses = max(1, -(-max_cap // cap_slice))

    ranks_fn = make_dest_ranks(mesh, key_specs, axis)
    datas = tuple(c.data for c in st.columns)
    masks = tuple(c.validity for c in st.columns)
    rank, live = ranks_fn(datas, masks, jnp.int64(n_valid))

    total = int(np.asarray(counts).sum())
    out_datas, out_valids = _spill_buffers(st.dtypes(), total, spill_dir)
    buffer_bytes = sum(d.nbytes for d in out_datas) + \
        sum(v.nbytes for v in out_valids)
    metrics.count("parallel.spill.spills")
    metrics.count("parallel.spill.passes", npasses)
    metrics.gauge_max("parallel.spill.buffer_bytes", buffer_bytes)
    metrics.observe("parallel.spill.pass_capacity_rows", cap_slice)
    fn = make_shuffle(mesh, layout, key_specs, cap_slice, axis)
    written = 0

    def run_pass(p, window):
        # writes land at offsets fixed by the pre-pass ``written``, so a
        # transient failure replays the whole pass idempotently
        faults.check("spill.write")
        planes_in, ok, ovf = fn(datas, masks, window)
        if int(ovf):
            raise RuntimeError(
                f"spill pass {p} overflow ({int(ovf)} rows)"
                " — counts pass disagrees with payload")
        d_in, m_in = _from_planes(layout, list(planes_in))
        okn = np.asarray(ok)
        keep = np.flatnonzero(okn)
        nlive = keep.shape[0]
        for ci, (d, m) in enumerate(zip(d_in, m_in)):
            dn = np.asarray(d)
            out_datas[ci][written:written + nlive] = dn[keep] if \
                dn.ndim == 1 else dn[keep].reshape(nlive, *dn.shape[1:])
            out_valids[ci][written:written + nlive] = \
                np.asarray(m)[keep]
        return nlive

    for p in range(npasses):
        lo, hi = p * cap_slice, (p + 1) * cap_slice
        window = (rank >= lo) & (rank < hi) & live
        with timeline.span("parallel.spill.pass",
                           {"pass": p, "capacity": int(cap_slice)}):
            nlive = retry_call(lambda: run_pass(p, window), "spill.write")
            written += nlive
            metrics.count("parallel.spill.bytes_spilled",
                          nlive * (row_bytes + len(out_valids)))
        metrics.mem_checkpoint()
    assert written == total, (written, total)

    cols = []
    for dtp, d, v in zip(st.dtypes(), out_datas, out_valids):
        cols.append(Column(dtp, data=d,  # host-resident: that's the point
                           validity=None if v.all() else v))
    return Table(cols, st.names)
