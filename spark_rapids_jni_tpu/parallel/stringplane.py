"""Strings in the distributed data plane: padded-bucket explosion.

XLA collectives want static shapes; Arrow STRING columns (char buffer +
n+1 offsets) have neither a per-row width nor a row-shardable layout.  The
padded-bucket design (SURVEY.md §7 hard part #2): before a table enters the
mesh, every STRING column *explodes* into fixed-width columns —

    s  ->  s#len : INT32   (byte length, carries the validity)
           s#w0.. : UINT32 (the padded byte matrix, 4 bytes per word,
                            zero beyond the row's length)

— which shard, ride row blobs through all_to_all, group, and join like any
other fixed-width columns.  Zero padding + the length column make
multi-key equality over (len, words...) exactly string equality, so a
GROUP BY or join on an exploded string key needs no special casing
anywhere downstream.  ``reassemble`` inverts the transform at the host
boundary.

The bucket width is the global max length rounded to a power-of-two
(strings_common.pad_width_bucket), fixed at explode time — every shard
compiles one program regardless of local maxima.  The alternative encoding
for high-cardinality keys is ops/dictionary.dictionary_encode (INT32 codes
+ replicated dictionary); both coexist: dictionaries when values repeat,
padded buckets when payload bytes must physically move.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..dtypes import INT32, UINT32
from ..ops.strings_common import to_padded_bytes, from_padded_bytes

LEN_SUFFIX = "#len"
WORD_SUFFIX = "#w"


@dataclass(frozen=True)
class StringPlan:
    """Static recipe mapping original columns <-> exploded fixed columns."""

    names: tuple  # original column names
    specs: tuple  # per column: ("fixed",) | ("string", nwords)

    @property
    def has_strings(self) -> bool:
        return any(s[0] == "string" for s in self.specs)

    def exploded_names(self) -> list:
        out = []
        for nm, spec in zip(self.names, self.specs):
            if spec[0] == "fixed":
                out.append(nm)
            else:
                out.append(f"{nm}{LEN_SUFFIX}")
                out.extend(f"{nm}{WORD_SUFFIX}{i}" for i in range(spec[1]))
        return out

    def exploded_keys(self, key_names) -> list:
        """Map key column names to their exploded column names."""
        spec_of = dict(zip(self.names, self.specs))
        out = []
        for k in key_names:
            spec = spec_of[k]
            if spec[0] == "fixed":
                out.append(k)
            else:
                out.append(f"{k}{LEN_SUFFIX}")
                out.extend(f"{k}{WORD_SUFFIX}{i}" for i in range(spec[1]))
        return out


def explode_strings(table: Table, width_overrides: dict | None = None
                    ) -> tuple[Table, StringPlan]:
    """Replace every STRING column with its fixed-width padded-bucket form.

    Host-boundary op (the bucket width is a global data-dependent static);
    everything downstream of it is jit-able.

    ``width_overrides`` maps column name -> minimum byte width.  Join paths
    use it to force BOTH sides of a join key to one bucket width: the word
    count is part of the multi-key identity, so sides exploded at different
    widths would hash (and partition) the same string differently.
    """
    names = tuple(table.names or [f"c{i}" for i in range(table.num_columns)])
    cols, out_names, specs = [], [], []
    for nm, c in zip(names, table.columns):
        if not c.dtype.is_string:
            cols.append(c)
            out_names.append(nm)
            specs.append(("fixed",))
            continue
        mat, lengths = to_padded_bytes(
            c, width=(width_overrides or {}).get(nm))
        n, w = mat.shape
        nwords = max((w + 3) // 4, 1)
        if w < nwords * 4:
            mat = jnp.pad(mat, ((0, 0), (0, nwords * 4 - w)))
        # null rows must not carry stray bytes into group/join equality
        if c.validity is not None:
            mat = jnp.where(c.validity[:, None], mat, jnp.uint8(0))
            lengths = jnp.where(c.validity, lengths, 0)
        words = jax.lax.bitcast_convert_type(
            mat.reshape(n, nwords, 4), jnp.uint32)  # (n, nwords) LE
        cols.append(Column(INT32, data=lengths.astype(jnp.int32),
                           validity=c.validity))
        out_names.append(f"{nm}{LEN_SUFFIX}")
        for i in range(nwords):
            cols.append(Column(UINT32, data=words[:, i], validity=c.validity))
            out_names.append(f"{nm}{WORD_SUFFIX}{i}")
        specs.append(("string", nwords))
    return Table(cols, out_names), StringPlan(names, tuple(specs))


def reassemble_strings(table: Table, plan: StringPlan) -> Table:
    """Invert explode_strings (host boundary: Arrow re-materialization)."""
    import numpy as np
    cols, idx = [], 0
    for nm, spec in zip(plan.names, plan.specs):
        if spec[0] == "fixed":
            cols.append(table.columns[idx])
            idx += 1
            continue
        nwords = spec[1]
        len_col = table.columns[idx]
        word_cols = table.columns[idx + 1:idx + 1 + nwords]
        idx += 1 + nwords
        words = jnp.stack([c.data for c in word_cols], axis=1)
        mat = jax.lax.bitcast_convert_type(
            words, jnp.uint8).reshape(words.shape[0], nwords * 4)
        valid = len_col.validity
        lengths = np.asarray(len_col.data)
        if valid is not None:
            lengths = np.where(np.asarray(valid), lengths, 0)
        has_null = valid is not None and not bool(valid.all())
        cols.append(from_padded_bytes(np.asarray(mat), lengths,
                                      valid if has_null else None))
    return Table(cols, list(plan.names))
