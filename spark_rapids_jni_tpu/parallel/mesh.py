"""Mesh construction and table sharding.

Data parallelism in the reference is Spark's task-per-partition with one GPU
per executor bound by ``auto_set_device`` (reference RowConversionJni.cpp:30).
The TPU-native form: one global mesh, every column a ``jax.Array`` sharded on
the row axis, XLA inserting ICI collectives (SURVEY.md §2.3 DP row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar import Column, Table

ROW_AXIS = "shard"
DCN_AXIS = "dcn"


def make_mesh(n_devices: int | None = None, axis: str = ROW_AXIS) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def make_multislice_mesh(n_slices: int, chips_per_slice: int,
                         dcn_axis: str = DCN_AXIS,
                         ici_axis: str = ROW_AXIS) -> Mesh:
    """(n_slices, chips_per_slice) mesh: the multi-host/multi-slice layout.

    Row data shards over BOTH axes (pass ``axis=(dcn_axis, ici_axis)`` to
    the distributed entry points); XLA routes the per-slice legs of each
    collective over ICI and the cross-slice legs over DCN — the multi-host
    scaling story the reference delegates to Spark+NCCL at L6 (SURVEY.md
    §2.3 last row).  Device order: ``jax.devices()`` is contiguous per
    slice/host, so the major mesh axis is the slice boundary."""
    devs = jax.devices()
    need = n_slices * chips_per_slice
    if len(devs) < need:
        raise ValueError(f"mesh wants {need} devices, have {len(devs)}")
    return Mesh(np.array(devs[:need]).reshape(n_slices, chips_per_slice),
                (dcn_axis, ici_axis))


def axis_size(mesh: Mesh, axis) -> int:
    """Total shard count over one axis name or a tuple of axis names."""
    if isinstance(axis, tuple):
        return int(np.prod([mesh.shape[a] for a in axis]))
    return mesh.shape[axis]


def pad_to_multiple(table: Table, multiple: int) -> tuple[Table, int]:
    """Pad row count to a mesh-divisible size with null rows; returns original n.

    The SQL analog of the reference's 32-row batch alignment
    (row_conversion.cu:477-479): shards must be equal-sized for pjit.
    """
    n = table.num_rows
    target = (n + multiple - 1) // multiple * multiple
    if target == n:
        return table, n
    pad = target - n
    cols = []
    for c in table.columns:
        if c.dtype.is_string:
            raise TypeError("pad_to_multiple: shard STRING columns via "
                            "dictionary encoding first")
        data = jnp.concatenate([c.data, jnp.zeros((pad,), c.data.dtype)])
        valid = jnp.concatenate([c.valid_mask(), jnp.zeros((pad,), jnp.bool_)])
        cols.append(Column(c.dtype, data=data, validity=valid))
    return Table(cols, table.names), n


def shard_table(table: Table, mesh: Mesh, axis: str = ROW_AXIS) -> Table:
    """Place every column buffer row-sharded over the mesh axis."""
    sharding = NamedSharding(mesh, P(axis))
    cols = []
    for c in table.columns:
        if c.dtype.is_string:
            raise TypeError("shard_table: STRING columns don't row-shard "
                            "(offsets are n+1); dictionary-encode first")
        data = jax.device_put(c.data, sharding)
        valid = None if c.validity is None else \
            jax.device_put(c.validity, sharding)
        cols.append(Column(c.dtype, data=data, validity=valid))
    return Table(cols, table.names)


def broadcast_table(table: Table, mesh: Mesh) -> Table:
    """Replicate every column buffer to all mesh devices (the broadcast
    Exchange: the build side of a broadcast-hash join).

    One fully-replicated ``device_put`` per buffer is the wire move —
    ``nbytes x (ndev - 1)`` over the interconnect.  The returned Table
    holds the FIRST device's local replica of each buffer (a committed
    single-device array), not the multi-device replicated array: mixing
    committed arrays from different device sets inside one jitted program
    raises, and downstream per-device compute only ever needs its local
    copy.  Strings replicate fine — offsets aren't row-sharded here.
    """
    sharding = NamedSharding(mesh, P())

    def rep(a):
        if a is None:
            return None
        return jax.device_put(a, sharding).addressable_shards[0].data

    def rep_col(c: Column) -> Column:
        return Column(c.dtype, data=rep(c.data), validity=rep(c.validity),
                      offsets=rep(c.offsets),
                      children=tuple(rep_col(k) for k in c.children))

    return Table([rep_col(c) for c in table.columns], table.names)
