"""Hash-partition shuffle: row blobs over ICI all-to-all.

The heart of the exchange layer (BASELINE.json north_star: "hash-partition
shuffle ... as ICI all-to-all across a pod").  Architecture mirrors the
reference's split of labor — RowConversion packs rows, the shuffle moves
them (RowConversion.java:28-31 documents row blobs as the hand-off format to
Spark's shuffle) — except both halves now live in one jitted XLA program:

    per shard:  dest = pmod(murmur3(keys), ndev)          (Spark partitioning)
                word planes (ops/row_conversion._build_planes)
                sort-based bucket pack into (nw, ndev, capacity) planes
    exchange:   one dense lax.all_to_all block over the mesh axis (ICI)
    per shard:  received padded word planes + row mask (+ overflow count)

Static shapes everywhere: each source shard may send at most ``capacity``
rows to each destination.  Capacity comes from a TWO-PHASE exchange (SURVEY
§7 hard part #3): phase 1 is a counts-only pass (hash + bincount + an
ndev-vector all_gather), phase 2 the payload all_to_all compiled at the
counts-derived capacity (power-of-two bucketed so compiled programs are
reused).  Overflow is still counted as a defense-in-depth invariant, but
with counts-based sizing it is structurally zero.  (The reference's analog
of this bound: the 2^31-byte batch ceiling it splits output to —
row_conversion.cu:476-511 — except ours is measured, not guessed.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:  # moved to the jax namespace in 0.5; experimental before that
    from jax import shard_map
except ImportError:  # pragma: no cover - jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        # the replication-check kwarg was renamed check_rep -> check_vma
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_old(f, **kw)

from ..columnar import Column, Table
from ..ops.hash import murmur3_hash
from ..ops.row_conversion import (RowLayout, _build_planes,
                                  _from_planes)
from .mesh import ROW_AXIS, axis_size
from .stringplane import explode_strings, reassemble_strings
from ..utils import faults, metrics, timeline
from ..utils.tracing import traced


def partition_ids(key_table: Table, num_partitions: int) -> jnp.ndarray:
    """Spark HashPartitioning: pmod(murmur3_hash(keys, 42), n)."""
    h = murmur3_hash(key_table).data  # int32
    m = h % jnp.int32(num_partitions)
    return jnp.where(m < 0, m + jnp.int32(num_partitions), m)


def partition_ids_specs(cols, key_specs, num_partitions: int) -> jnp.ndarray:
    """Spark HashPartitioning over possibly-EXPLODED key columns.

    ``key_specs`` (static, per original key): ("fixed", idx, dtype) or
    ("string", len_idx, (word_idx, ...)) into ``cols``.  String keys hash
    their UTF-8 bytes (Spark UTF8String murmur3) reconstructed from the
    exploded (length, words) group — wire-exact partition placement, the
    interop half of keeping the row-blob format bit-exact
    (RowConversion.java:28-48).
    """
    from ..ops.hash import murmur3_hash_specs
    hs = tuple(("fixed", s[1]) if s[0] == "fixed" else s for s in key_specs)
    h = jax.lax.bitcast_convert_type(
        murmur3_hash_specs(cols, hs), jnp.int32)
    m = h % jnp.int32(num_partitions)
    return jnp.where(m < 0, m + jnp.int32(num_partitions), m)


def key_specs_for(table: Table, keys, plan) -> tuple:
    """Static key specs for ``partition_ids_specs`` over a possibly-exploded
    table: ``keys`` are the ORIGINAL key names (or indices when nothing was
    exploded), ``plan`` the StringPlan (or None)."""
    from .stringplane import LEN_SUFFIX, WORD_SUFFIX
    spec_of = dict(zip(plan.names, plan.specs)) if plan is not None else {}
    names = list(table.names or [f"c{i}" for i in range(table.num_columns)])
    out = []
    for k in keys:
        s = spec_of.get(k, ("fixed",)) if isinstance(k, str) else ("fixed",)
        if s[0] == "string":
            li = names.index(f"{k}{LEN_SUFFIX}")
            out.append(("string", li,
                        tuple(names.index(f"{k}{WORD_SUFFIX}{i}")
                              for i in range(s[1]))))
        else:
            i = names.index(k) if isinstance(k, str) else int(k)
            out.append(("fixed", i, table.columns[i].dtype))
    return tuple(out)


def _spec_columns(key_specs, datas, masks):
    """Columns referenced by ``key_specs``, built from raw shard buffers
    (positions not referenced stay None)."""
    from ..dtypes import INT32 as _I32DT, UINT32 as _U32DT
    cols = [None] * len(datas)

    def put(i, dtype):
        if cols[i] is None:
            cols[i] = Column(dtype, data=datas[i],
                             validity=None if masks[i] is None else masks[i])

    for s in key_specs:
        if s[0] == "fixed":
            put(s[1], s[2])
        else:
            put(s[1], _I32DT)
            for i in s[2]:
                put(i, _U32DT)
    return cols


def _bucket_pack_planes(planes, dest: jnp.ndarray, row_mask, ndev: int,
                        capacity: int):
    """Scatter-free bucket pack: rows into per-destination slots.

    Sort-carried rather than scatter-based (docs/PERF.md: TPU scatters
    serialize), but the payload planes are never sorted: ONE stable
    2-operand sort of (dest, row-index) groups the row *indices* by
    destination, a one-hot reduction counts rows per destination, and the
    (ndev, capacity) send grid fills by GATHER — slot (d, r) reads sorted
    position start[d] + r.  Each u32 plane moves exactly once (the gather)
    instead of riding two (nw+2)-operand sorts of n + ndev*capacity
    elements, which dominated the exchange cost.

    ``planes`` is the word-major row decomposition (nw dense u32[n]
    vectors — never the lane-padded (n, nw) matrix).  Returns (send_planes
    [(ndev, capacity) u32 per word], ok (ndev, capacity) bool, overflow
    scalar = live rows that didn't fit their destination bucket).
    """
    n = dest.shape[0]
    if n == 0:
        ok = jnp.zeros((ndev, capacity), jnp.bool_)
        send = [jnp.zeros((ndev, capacity), p.dtype) for p in planes]
        return send, ok, jnp.int32(0)
    if row_mask is not None:
        dest = jnp.where(row_mask, dest, jnp.int32(ndev))
    idx = jnp.arange(n, dtype=jnp.int32)
    sd, si = jax.lax.sort((dest, idx), num_keys=1, is_stable=True)
    # rows per destination from the sorted runs: ndev binary-search queries
    # over sd (ndev-independent in n — a one-hot reduction would be
    # Theta(ndev*n) at pod scale, a bincount scatter-add would serialize
    # on TPU)
    d = jnp.arange(ndev, dtype=jnp.int32)
    start = jnp.searchsorted(sd, d, side="left").astype(jnp.int32)
    cnt = jnp.searchsorted(sd, d, side="right").astype(jnp.int32) - start
    r = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    src = start[:, None] + r                       # (ndev, capacity)
    ok = r < jnp.minimum(cnt, capacity)[:, None]
    rows = jnp.take(si, jnp.clip(src, 0, max(n - 1, 0)).reshape(-1))
    okf = ok.reshape(-1)
    send = [jnp.where(okf, jnp.take(p, rows), jnp.zeros((), p.dtype))
            .reshape(ndev, capacity) for p in planes]
    overflow = jnp.sum(jnp.maximum(cnt - capacity, 0))
    return send, ok, overflow


def device_load_stats(dest_rows) -> dict:
    """Skew/straggler attribution from per-destination row counts.

    ``dest_rows`` is any sequence of rows landing on each device (one
    entry per device).  Skew is max/mean destination load — 1.0 is a
    perfectly balanced exchange, ndev is everything-on-one-device; the
    straggler share (max - mean)/max is the fraction of the slowest
    device's work the mesh sits idle for (the all_to_all completes at the
    pace of its fullest destination).  Shared by the shuffle counts pass
    and the executor's Exchange attribution so both report identically.
    """
    import numpy as np
    rows = np.asarray(dest_rows, dtype=np.int64).reshape(-1)
    ndev = max(1, rows.size)
    total = int(rows.sum()) if rows.size else 0
    mean = total / ndev
    mx = int(rows.max()) if rows.size else 0
    skew = (mx / mean) if mean > 0 else 1.0
    straggler = ((mx - mean) / mx) if mx > 0 else 0.0
    return {"dev_rows": [int(r) for r in rows],
            "total_rows": total,
            "max_dev_rows": mx,
            "mean_dev_rows": round(mean, 3),
            "skew": round(float(skew), 6),
            "straggler_share": round(float(straggler), 6)}


def cap_bucket(count: int) -> int:
    """Round a counts-derived capacity up to a power-of-two bucket (>=32).

    Buckets bound the number of distinct compiled programs the two-phase
    exchange can create (capacity is a static shape).
    """
    cap = 32
    while cap < count:
        cap *= 2
    return cap


def cap_bucket_fine(count: int) -> int:
    """Round up to a quarter-power-of-two bucket (1, 1.25, 1.5, 1.75 x 2^k).

    For the big data-dependent capacities (join pair counts) the 2x
    worst-case padding of ``cap_bucket`` is real sort work; quarter buckets
    cap padding waste at 25% for at most 4x the distinct compiled programs.
    """
    cap = 32
    while cap < count:
        cap *= 2
    if cap >= 128:
        for frac in (4, 5, 6, 7):
            fine = cap // 8 * frac
            if fine >= count:
                return fine
    return cap


@functools.lru_cache(maxsize=64)
def make_partition_counts(mesh: Mesh, key_specs: tuple,
                          axis: str = ROW_AXIS, masked: bool = False):
    """Phase 1 of the two-phase exchange: per-(src, dest) row counts.

    SURVEY.md §7 hard part #3 (ragged all-to-all with static shapes): rather
    than guessing a capacity and retrying on overflow, a cheap counts pass
    (hash + bincount + all_gather of an ndev-vector — no payload movement)
    sizes the payload exchange exactly.  ``key_specs`` comes from
    ``key_specs_for`` (Spark-exact hashing incl. exploded string keys).
    Returns fn(datas, masks[, n_valid]) -> int32[ndev, ndev] with row s =
    counts shard s sends to each dest.
    """
    ndev = axis_size(mesh, axis)

    def shard_fn(datas, masks, n_valid=None):
        cols = _spec_columns(key_specs, datas, masks)
        dest = partition_ids_specs(cols, key_specs, ndev)
        if n_valid is not None:
            n_local = dest.shape[0]
            shard_idx = jax.lax.axis_index(axis).astype(jnp.int64)
            gid = shard_idx * n_local + jnp.arange(n_local, dtype=jnp.int64)
            dest = jnp.where(gid < n_valid, dest, jnp.int32(ndev))
        counts = jnp.zeros((ndev,), jnp.int32).at[dest].add(1, mode="drop")
        return counts[None]

    spec = P(axis)
    if masked:
        return jax.jit(shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, spec, P()),
            out_specs=spec, check_vma=False))
    return jax.jit(shard_map(
        lambda d, m: shard_fn(d, m), mesh=mesh,
        in_specs=(spec, spec), out_specs=spec, check_vma=False))


def partition_counts(table: Table, mesh: Mesh, keys: list,
                     axis: str = ROW_AXIS, n_valid_rows=None,
                     key_specs: tuple | None = None):
    """Host wrapper over ``make_partition_counts`` for a sharded table.

    The returned array has reached the host — a deliberate sync the engine
    Exchange paths label via ``metrics.host_sync("exchange-counts-sizing")``
    at THEIR call sites (here would also tag the distributed.py/spill.py
    callers, whose syncs ``verify.sync_budget`` does not model).
    """
    import numpy as np
    if key_specs is None:
        key_specs = key_specs_for(table, keys, None)
    fn = make_partition_counts(mesh, key_specs, axis,
                               masked=n_valid_rows is not None)
    datas = tuple(c.data for c in table.columns)
    masks = tuple(c.validity for c in table.columns)
    out = fn(datas, masks, jnp.int64(n_valid_rows)) \
        if n_valid_rows is not None else fn(datas, masks)
    return np.asarray(out)


def exchange_planes(planes, dest, row_mask, ndev: int, capacity: int,
                    axis: str):
    """Bucket-pack word planes and move them over ICI as ONE dense block.

    The single exchange primitive shared by the raw shuffle and the
    distributed groupby/join plans: pack -> stack (nw, ndev, cap) ->
    all_to_all(split/concat axis 1) -> per-word receive planes.  Returns
    (planes_in tuple of u32[ndev*capacity], row mask, overflow scalar).
    """
    send, ok, overflow = _bucket_pack_planes(planes, dest, row_mask, ndev,
                                             capacity)
    block = jnp.stack(send, axis=0)
    recv = jax.lax.all_to_all(block, axis, 1, 1)
    rok = jax.lax.all_to_all(ok, axis, 0, 0)
    planes_in = tuple(recv[w].reshape(ndev * capacity)
                      for w in range(len(planes)))
    return planes_in, rok.reshape(ndev * capacity), overflow


@functools.lru_cache(maxsize=64)
def make_shuffle(mesh: Mesh, layout: RowLayout, key_specs: tuple,
                 capacity: int, axis: str = ROW_AXIS,
                 donate: bool = False, split: tuple | None = None):
    """Build the jitted shard_map shuffle for a fixed schema.

    Returns fn(datas, masks, row_mask) -> (planes_in, ok, overflow): the
    received word planes (tuple of u32[ndev*capacity] per row word — feed
    ``_from_planes``), the live-row mask, and the global overflow count.
    ``key_specs`` from ``key_specs_for`` — string keys partition by Spark
    UTF8String murmur3 over their exploded words.

    ``donate=True`` donates the input buffers to XLA (donate_argnums — the
    async-dispatch/donation half of the reference's per-thread-stream
    overlap, SURVEY §2.3 "PP"): the send buffers reuse the table's HBM, so
    a shuffle's working set is ~1x instead of 2x.  Callers must not touch
    the donated table afterwards.

    ``split`` = ``(hot_dests, salt)`` is the AQE skew-split secondary
    assignment (engine/adaptive.py): rows hashed to a hot destination are
    re-dealt round-robin across ALL devices by a salted per-shard running
    index.  The deal bounds each destination's share of a shard's hot rows
    at ceil(hot/ndev) — unlike a salted re-hash, an adversarial single-key
    distribution cannot overflow a counts-projected capacity.  Placement
    stops being key-deterministic for hot rows, so only consumers that
    merge the full exchange output (or re-combine per key afterwards) may
    ask for it.  Static (part of the compile cache key), like capacity.
    """
    ndev = axis_size(mesh, axis)

    def shard_fn(datas, masks, row_mask):
        cols = _spec_columns(key_specs, datas, masks)
        dest = partition_ids_specs(cols, key_specs, ndev)
        if split is not None:
            hot, salt = split
            is_hot = dest == jnp.int32(hot[0])
            for h in hot[1:]:
                is_hot = is_hot | (dest == jnp.int32(h))
            if row_mask is not None:
                # dead (pad) rows must not advance the deal: the capacity
                # projection counted live rows only
                is_hot = is_hot & row_mask
            # stagger the deal start by source shard: shards with few hot
            # rows would otherwise ALL open at dest salt and re-concentrate
            # what the split is meant to spread.  The per-(src, dest) bound
            # is rotation-invariant — still at most ceil(hot_s / ndev)
            shard_idx = jax.lax.axis_index(axis).astype(jnp.int32)
            hot_idx = jnp.cumsum(is_hot.astype(jnp.int32)) - 1
            dest = jnp.where(
                is_hot,
                (jnp.int32(salt) + shard_idx + hot_idx) % jnp.int32(ndev),
                dest)
        planes = _build_planes(layout, datas, masks)
        planes_in, rok, overflow = exchange_planes(planes, dest, row_mask,
                                                   ndev, capacity, axis)
        return planes_in, rok, jax.lax.psum(overflow, axis)

    spec = P(axis)
    return jax.jit(shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, P()),
        check_vma=False,
    ), donate_argnums=(0, 1) if donate else ())


@traced("shuffle_table_padded")
def shuffle_table_padded(table: Table, mesh: Mesh, keys: list,
                         capacity: int | None = None,
                         axis: str = ROW_AXIS, donate: bool = False,
                         live=None, key_specs: tuple | None = None,
                         split: tuple | None = None):
    """Shuffle a row-sharded table by key hash.

    Returns (padded Table [ndev * ndev * capacity global rows], row mask
    Column-less bool array, overflow scalar).  Rows land on the partition
    owning pmod(murmur3(keys), ndev); padding rows have mask False.

    ``live``: optional bool row mask — dead rows (e.g. pad_to_multiple
    padding) are never sent.

    STRING columns (keys or payloads) cross the exchange in padded-bucket
    form (stringplane): exploded to fixed-width, shuffled inside the row
    blobs, reassembled on the way out.  String-key partitioning is Spark's
    UTF8String murmur3 over the original bytes (reconstructed on device
    from the exploded words — ``partition_ids_specs``), so partition
    placement interoperates with Spark's HashPartitioning wire-exactly.

    ``key_specs``: pre-computed ``key_specs_for`` result for callers whose
    table is ALREADY exploded (the engine exchange explodes once globally
    so every chunk shares one layout) — overrides the local computation so
    string keys still hash Spark-exactly.

    ``split``: AQE skew-split ``(hot_dests, salt)`` — see ``make_shuffle``.
    The internal counts pass sizes capacity for the UNSPLIT placement, so
    splitting callers must pass the projected capacity explicitly.
    """
    from ..ops.row_conversion import fixed_width_layout
    if split is not None and capacity is None:
        raise ValueError("split requires an explicitly projected capacity")
    plan = None
    if any(c.dtype.is_string for c in table.columns):
        names0 = table.names or [f"c{i}" for i in range(table.num_columns)]
        keys = [k if isinstance(k, str) else names0[int(k)] for k in keys]
        table, plan = explode_strings(table)
        from .mesh import shard_table
        table = shard_table(table, mesh, axis)  # strings couldn't shard before
    layout = fixed_width_layout(table.dtypes())
    ndev = axis_size(mesh, axis)
    if key_specs is None:
        key_specs = key_specs_for(table, keys, plan)
    if capacity is None:
        # two-phase exchange: counts pass sizes the payload pass exactly.
        # The counts fetch is a DELIBERATE host sync (they must reach the
        # host to become phase 2's static capacity) — whitelisted in
        # engine/verify.SYNC_WHITELIST; the AST lint holds the label honest
        counts_mat = partition_counts(table, mesh, list(keys), axis,
                                      key_specs=key_specs)
        capacity = cap_bucket(int(counts_mat.max()))
        metrics.host_sync(label="exchange-counts-sizing")
        if metrics.enabled():
            # the counts matrix is already on host — per-device skew
            # attribution costs nothing extra (no added syncs)
            st = device_load_stats(counts_mat.sum(axis=0))
            metrics.gauge_set("parallel.shuffle.skew", st["skew"])
            metrics.gauge_set("parallel.shuffle.max_dev_rows",
                              st["max_dev_rows"])
            for r in st["dev_rows"]:
                metrics.observe("parallel.shuffle.dev_rows", r)
    fn = make_shuffle(mesh, layout, key_specs, capacity, axis, donate,
                      split)
    # exchange observability: every slot of the padded all_to_all crosses
    # the interconnect whether live or not, so slots x row_size IS the
    # wire traffic (the padding_efficiency ratio bench.py reports)
    metrics.count("parallel.shuffle.exchanges")
    metrics.count("parallel.shuffle.exchange_bytes",
                  ndev * ndev * capacity * layout.row_size)
    metrics.observe("parallel.shuffle.capacity_rows", capacity)
    datas = tuple(c.data for c in table.columns)
    masks = tuple(c.validity for c in table.columns)
    with timeline.span("parallel.shuffle.exchange",
                       {"capacity": int(capacity),
                        "wire_bytes": int(ndev * ndev * capacity *
                                          layout.row_size)}):
        planes_in, ok, overflow = fn(datas, masks, live)
    datas_out, masks_out = _from_planes(layout, list(planes_in))
    cols = [Column(dt, data=d, validity=m)
            for dt, d, m in zip(layout.schema, datas_out, masks_out)]
    out = Table(cols, table.names)
    if plan is not None:
        out = reassemble_strings(out, plan)
    return out, ok, overflow


def shuffle_chunks_pipelined(chunks, mesh: Mesh, keys: list,
                             capacity: int | None = None, depth: int = 1,
                             axis: str = ROW_AXIS, donate: bool = False,
                             key_specs: tuple | None = None,
                             split: tuple | None = None):
    """Exchange a stream of table chunks with dispatch-ahead overlap.

    The engine's double-buffered chunk pipeline applied to the shuffle
    exchange: the all_to_all for chunk k+1 is DISPATCHED before chunk k is
    yielded, so while the consumer's join/merge of chunk k runs (device
    compute plus its host-side compaction sync), the next exchange is
    already in the device queue — jax's async dispatch provides the
    overlap; this generator just keeps up to ``depth`` exchanges in front
    of the consumer.  ``depth=1`` is classic double buffering; ``depth=0``
    degenerates to the serial exchange-then-merge loop.

    ``chunks`` yields row-sharded Tables (or ``(Table, live_mask)`` pairs,
    same contract as ``shuffle_table_padded``).  Pass ``capacity`` sized
    from global counts so ONE compiled shuffle program serves the whole
    stream; with ``capacity=None`` each chunk runs its own counts pass
    (still correct, but differently-filled chunks may compile more than
    one program).  ``donate=True`` passes through to ``make_shuffle``'s
    buffer donation: each chunk's send buffers reuse its table's HBM (1x
    working set) — callers must not touch a chunk after yielding it.
    ``key_specs`` passes through to ``shuffle_table_padded`` for streams of
    already-exploded chunks (Spark-exact string-key placement).  ``split``
    passes through the AQE skew-split assignment (requires ``capacity``).

    Yields ``(padded Table, ok mask, overflow)`` per chunk, in order.
    """
    from collections import deque
    inflight: deque = deque()
    for item in chunks:
        tbl, live = item if isinstance(item, tuple) else (item, None)
        faults.check("exchange.dispatch")
        out = shuffle_table_padded(tbl, mesh, list(keys), capacity=capacity,
                                   axis=axis, donate=donate, live=live,
                                   key_specs=key_specs, split=split)
        inflight.append(out)
        # dispatch-ahead depth: how many exchanges sit in the device queue
        # in front of the consumer (the pipeline's high-water mark)
        metrics.gauge_max("parallel.shuffle.dispatch_ahead", len(inflight))
        if len(inflight) > max(0, int(depth)):
            yield inflight.popleft()
    while inflight:
        yield inflight.popleft()
