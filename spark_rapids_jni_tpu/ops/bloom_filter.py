"""BloomFilter: Spark BloomFilterImpl-compatible build/probe.

TPU-native rebuild of the reference's BloomFilter component (BASELINE.json
north-star set; CUDA side appears post-snapshot as bloom_filter.cu backing
Spark 3.3+ runtime filter pushdown: BloomFilterAggregate on the build side,
BloomFilterMightContain on the probe side).

Spark's BloomFilterImpl (double hashing, sign-folded):

    h1 = Murmur3_x86_32.hashLong(item, seed=0)
    h2 = Murmur3_x86_32.hashLong(item, seed=h1)
    for i in 1..k:  pos = fold(h1 + i*h2) % num_bits ; set bit pos
    fold(x) = ~x if x < 0 else x

The filter state is a device bool[num_bits] array (scatter-friendly form);
``spark_serialize``/``spark_deserialize`` convert to/from Spark's exact wire
bytes (V1 header + big-endian longs of the BitArray) so filters interchange
with JVM executors.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..dtypes import BOOL8, TypeId
from .hash import _murmur_long, _U32

_I32 = jnp.int32


def optimal_num_bits(expected_items: int, fpp: float = 0.03) -> int:
    """Spark BloomFilter.optimalNumOfBits."""
    return max(8, int(-expected_items * np.log(fpp) / (np.log(2) ** 2)))


def optimal_num_hashes(expected_items: int, num_bits: int) -> int:
    """Spark BloomFilter.optimalNumOfHashFunctions."""
    return max(1, int(round(num_bits / max(expected_items, 1) * np.log(2))))


def _item_u64(col: Column) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(lo, hi) u32 words of the long item + per-row validity."""
    if not (col.dtype.is_integral or col.dtype.is_timestamp
            or col.dtype.is_decimal or col.dtype.id == TypeId.BOOL8):
        raise TypeError(f"bloom filter items must be long-typed, got {col.dtype!r}")
    v = col.data.astype(jnp.int64)
    pair = jax.lax.bitcast_convert_type(v, jnp.uint32)
    return pair[..., 0], pair[..., 1], col.valid_mask()


def _positions(col: Column, num_hashes: int, num_bits: int):
    """[n, num_hashes] int32 bit positions per item (Spark double hashing)."""
    lo, hi, valid = _item_u64(col)
    h1 = _murmur_long(lo, hi, _U32(0))
    h2 = _murmur_long(lo, hi, h1)
    h1s = jax.lax.bitcast_convert_type(h1, jnp.int32)
    h2s = jax.lax.bitcast_convert_type(h2, jnp.int32)
    pos = []
    for i in range(1, num_hashes + 1):
        combined = h1s + jnp.int32(i) * h2s  # wraps like Java int
        combined = jnp.where(combined < 0, ~combined, combined)
        pos.append(combined % jnp.int32(num_bits))
    return jnp.stack(pos, axis=1), valid


def bloom_build(col: Column, num_bits: int, num_hashes: int) -> jnp.ndarray:
    """Aggregate a long column into a bool[num_bits] filter (null items skipped)."""
    pos, valid = _positions(col, num_hashes, num_bits)
    bits = jnp.zeros((num_bits,), jnp.bool_)
    pos = jnp.where(valid[:, None], pos, num_bits)  # nulls scatter out of range
    return bits.at[pos.reshape(-1)].set(True, mode="drop")


def bloom_merge(filters: list[jnp.ndarray]) -> jnp.ndarray:
    """OR-combine filters built with identical (num_bits, num_hashes)."""
    out = filters[0]
    for f in filters[1:]:
        out = out | f
    return out


def bloom_might_contain(bits: jnp.ndarray, col: Column,
                        num_hashes: int) -> Column:
    """BOOL8 probe column; null items probe to null (Spark MightContain)."""
    num_bits = bits.shape[0]
    pos, valid = _positions(col, num_hashes, num_bits)
    hit = jnp.take(bits, pos, axis=0).all(axis=1)
    return Column(BOOL8, data=hit.astype(jnp.uint8),
                  validity=None if col.validity is None else valid)


# -- Spark wire format ------------------------------------------------------

def spark_serialize(bits: np.ndarray, num_hashes: int) -> bytes:
    """Spark BloomFilterImpl.writeTo: V1, numHashFunctions, numWords, BE longs.

    BitArray layout: bit i lives at words[i >> 6], bit position (i & 63)
    counting from the long's LSB; longs serialize big-endian (DataOutputStream).
    """
    bits = np.asarray(bits).astype(bool)
    num_bits = bits.shape[0]
    nwords = (num_bits + 63) // 64
    padded = np.zeros(nwords * 64, bool)
    padded[:num_bits] = bits
    words = np.packbits(padded.reshape(nwords, 64), axis=1,
                        bitorder="little").view(np.uint64).reshape(nwords)
    head = np.array([1, num_hashes, nwords], ">i4").tobytes()
    return head + words.astype(">u8").tobytes()


def spark_deserialize(buf: bytes) -> tuple[np.ndarray, int]:
    """(bool bit array, num_hashes) from Spark BloomFilterImpl bytes."""
    head = np.frombuffer(buf[:12], ">i4")
    version, num_hashes, nwords = int(head[0]), int(head[1]), int(head[2])
    if version != 1:
        raise ValueError(f"unsupported bloom filter version {version}")
    words = np.frombuffer(buf[12:12 + nwords * 8], ">u8")
    bits = np.unpackbits(words.astype("<u8").view(np.uint8),
                         bitorder="little")  # LSB-first within each long
    return bits.astype(bool), num_hashes
