"""Column expression ops: null-propagating arithmetic/comparison/logical.

The libcudf binary/unary-op role (SURVEY.md §2.2 "algorithms"): Spark
projects expressions over columns before/after the relational ops.  Rules
follow Spark SQL:

- null in → null out (except null-safe equality and AND/OR short-circuit
  truth tables);
- float comparisons use Spark's NaN ordering, not IEEE: NaN == NaN is
  true (also under ``<=>``) and NaN sorts greater than any other double;
- integer division/modulo by zero → null (Spark returns null, not error);
- FLOAT64 columns store bit patterns (dtypes.device_storage), so float
  arithmetic round-trips through utils.floatbits;
- comparisons return BOOL8 columns.

Everything is elementwise and jit-safe (fixed shapes, no host syncs).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..dtypes import BOOL8, DType, FLOAT64, INT64, TypeId
from ..utils.tracing import traced


def _vals(col: Column) -> jnp.ndarray:
    """Computation view of a column's data (floats as hardware floats)."""
    if col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        return col.float_values()
    if col.dtype.id == TypeId.BOOL8:
        return col.data.astype(jnp.bool_)
    return col.data


def _both_valid(a: Column, b: Column):
    if a.validity is None and b.validity is None:
        return None
    return a.valid_mask() & b.valid_mask()


def _result(dtype: DType, data, valid) -> Column:
    if dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        return Column.fixed(dtype, data, validity=valid)
    if dtype.id == TypeId.BOOL8:
        return Column(BOOL8, data=data.astype(jnp.uint8), validity=valid)
    return Column(dtype, data=data.astype(jnp.dtype(dtype.device_storage)),
                  validity=valid)


def _numeric_out_dtype(a: DType, b: DType) -> DType:
    if TypeId.FLOAT64 in (a.id, b.id) or TypeId.FLOAT32 in (a.id, b.id):
        return FLOAT64
    return INT64


def _arith(a: Column, b: Column, fn, out_dtype=None) -> Column:
    av, bv = _vals(a), _vals(b)
    out = out_dtype or _numeric_out_dtype(a.dtype, b.dtype)
    if out.id == TypeId.FLOAT64:
        av = av.astype(jnp.float64)
        bv = bv.astype(jnp.float64)
    return _result(out, fn(av, bv), _both_valid(a, b))


@traced("binary_op")
def add(a: Column, b: Column) -> Column:
    return _arith(a, b, jnp.add)


@traced("binary_op")
def subtract(a: Column, b: Column) -> Column:
    return _arith(a, b, jnp.subtract)


@traced("binary_op")
def multiply(a: Column, b: Column) -> Column:
    return _arith(a, b, jnp.multiply)


@traced("binary_op")
def true_divide(a: Column, b: Column) -> Column:
    """Spark ``/``: always double; x/0 is null (not inf) for nonzero x."""
    av = _vals(a).astype(jnp.float64)
    bv = _vals(b).astype(jnp.float64)
    zero = bv == 0.0
    safe = jnp.where(zero, 1.0, bv)
    valid = _both_valid(a, b)
    valid = ~zero if valid is None else (valid & ~zero)
    return _result(FLOAT64, av / safe, valid)


@traced("binary_op")
def floor_div(a: Column, b: Column) -> Column:
    """Spark ``div``: integral quotient; by-zero is null."""
    av = _vals(a).astype(jnp.int64)
    bv = _vals(b).astype(jnp.int64)
    zero = bv == 0
    safe = jnp.where(zero, jnp.int64(1), bv)
    # Spark div truncates toward zero (Java semantics), unlike // (floor)
    q = (jnp.abs(av) // jnp.abs(safe)) * jnp.sign(av) * jnp.sign(safe)
    valid = _both_valid(a, b)
    valid = ~zero if valid is None else (valid & ~zero)
    return _result(INT64, q, valid)


@traced("binary_op")
def modulo(a: Column, b: Column) -> Column:
    """Spark ``%``: sign follows the dividend (Java), by-zero is null."""
    av = _vals(a).astype(jnp.int64)
    bv = _vals(b).astype(jnp.int64)
    zero = bv == 0
    safe = jnp.where(zero, jnp.int64(1), bv)
    r = jnp.sign(av) * (jnp.abs(av) % jnp.abs(safe))
    valid = _both_valid(a, b)
    valid = ~zero if valid is None else (valid & ~zero)
    return _result(INT64, r, valid)


def _is_float(a: Column, b: Column) -> bool:
    return a.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64) or \
        b.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64)


def _nan_eq(av, bv):
    """Spark equality over doubles: NaN == NaN is true.

    IEEE ``==`` is already false whenever either side is NaN, so Spark's
    table is the IEEE result plus the both-NaN case."""
    return jnp.equal(av, bv) | (jnp.isnan(av) & jnp.isnan(bv))


def _nan_lt(av, bv):
    """Spark ordering over doubles: NaN is greater than everything else."""
    return jnp.less(av, bv) | (jnp.isnan(bv) & ~jnp.isnan(av))


def _compare(a: Column, b: Column, fn, nan_fn=None) -> Column:
    av, bv = _vals(a), _vals(b)
    if _is_float(a, b):
        av = av.astype(jnp.float64)
        bv = bv.astype(jnp.float64)
        if nan_fn is not None:
            fn = nan_fn
    return _result(BOOL8, fn(av, bv), _both_valid(a, b))


@traced("binary_op")
def eq(a: Column, b: Column) -> Column:
    return _compare(a, b, jnp.equal, _nan_eq)


@traced("binary_op")
def ne(a: Column, b: Column) -> Column:
    return _compare(a, b, jnp.not_equal, lambda x, y: ~_nan_eq(x, y))


@traced("binary_op")
def lt(a: Column, b: Column) -> Column:
    return _compare(a, b, jnp.less, _nan_lt)


@traced("binary_op")
def le(a: Column, b: Column) -> Column:
    # a <= b: IEEE result, plus "b is NaN" (NaN is the maximum, and equals
    # itself, so any a satisfies a <= NaN)
    return _compare(a, b, jnp.less_equal,
                    lambda x, y: jnp.less_equal(x, y) | jnp.isnan(y))


@traced("binary_op")
def gt(a: Column, b: Column) -> Column:
    return _compare(a, b, jnp.greater, lambda x, y: _nan_lt(y, x))


@traced("binary_op")
def ge(a: Column, b: Column) -> Column:
    return _compare(a, b, jnp.greater_equal,
                    lambda x, y: jnp.greater_equal(x, y) | jnp.isnan(x))


@traced("binary_op")
def eq_null_safe(a: Column, b: Column) -> Column:
    """Spark ``<=>``: nulls compare equal; never returns null."""
    av, bv = _vals(a), _vals(b)
    if _is_float(a, b):
        same_v = _nan_eq(av.astype(jnp.float64), bv.astype(jnp.float64))
    else:
        same_v = jnp.equal(av, bv)
    va, vb = a.valid_mask(), b.valid_mask()
    same = same_v & va & vb
    both_null = ~va & ~vb
    return Column(BOOL8, data=(same | both_null).astype(jnp.uint8))


@traced("binary_op")
def logical_and(a: Column, b: Column) -> Column:
    """SQL three-valued AND: false dominates null."""
    av = _vals(a).astype(jnp.bool_)
    bv = _vals(b).astype(jnp.bool_)
    va, vb = a.valid_mask(), b.valid_mask()
    false_a = va & ~av
    false_b = vb & ~bv
    out = av & bv
    valid = (va & vb) | false_a | false_b
    return Column(BOOL8, data=out.astype(jnp.uint8), validity=valid)


@traced("binary_op")
def logical_or(a: Column, b: Column) -> Column:
    """SQL three-valued OR: true dominates null."""
    av = _vals(a).astype(jnp.bool_)
    bv = _vals(b).astype(jnp.bool_)
    va, vb = a.valid_mask(), b.valid_mask()
    true_a = va & av
    true_b = vb & bv
    out = av | bv
    valid = (va & vb) | true_a | true_b
    return Column(BOOL8, data=out.astype(jnp.uint8), validity=valid)


@traced("unary_op")
def logical_not(a: Column) -> Column:
    av = _vals(a).astype(jnp.bool_)
    return Column(BOOL8, data=(~av).astype(jnp.uint8), validity=a.validity)


@traced("unary_op")
def negate(a: Column) -> Column:
    return _result(a.dtype, -_vals(a), a.validity)


@traced("unary_op")
def abs_(a: Column) -> Column:
    return _result(a.dtype, jnp.abs(_vals(a)), a.validity)


@traced("unary_op")
def round_(a: Column, scale: int = 0) -> Column:
    """Spark ``round(col, scale)``: HALF_UP (away from zero).

    Floats stay FLOAT64; integral inputs round at negative scales (tens,
    hundreds, ...) and pass through otherwise.  Integral results that
    would exceed int64 saturate at the largest representable multiple of
    the rounding unit; ``scale <= -19`` exceeds int64 entirely and
    raises.

    Known divergence (documented, like the reference plugin's float-round
    caveats): doubles round via v * 10^scale then HALF_UP, while Spark goes
    through BigDecimal.valueOf(double) — the SHORTEST decimal
    representation.  Values whose scaled product falls on the other side
    of .5 from their shortest-repr digit string can differ in the last
    digit (none of the classic 2.675/0.285/1.005 cases do)."""
    if a.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        v = a.float_values().astype(jnp.float64)
        p = 10.0 ** scale
        s = v * p
        r = jnp.where(s >= 0, jnp.floor(s + 0.5), jnp.ceil(s - 0.5))
        return Column.fixed(FLOAT64, r / p, validity=a.validity)
    if scale >= 0:
        return a
    if scale <= -19:
        raise ValueError("round scale <= -19 exceeds the int64 range")
    q = 10 ** (-scale)
    v = a.data.astype(jnp.int64)
    # overflow-free HALF_UP: floor-div + remainder comparison (the
    # _div_half_up '+ q//2' form wraps at the int64 extremes)
    qj = jnp.int64(q)
    b = jnp.floor_divide(v, qj)
    r = v - b * qj                       # in [0, q)
    up = jnp.where(v >= 0, 2 * r >= qj, 2 * (qj - r) < qj)
    m = b + up.astype(jnp.int64)
    lim = (2**63 - 1) // q
    out = jnp.clip(m, -lim, lim) * qj
    return _result(INT64, out, a.validity)


def _float_to_long(a: Column, fn) -> Column:
    from ..dtypes import INT64 as _I64D
    from .cast import cast
    v = fn(a.float_values().astype(jnp.float64))
    # reuse cast()'s saturating double->long rules (NaN -> 0, +/-inf and
    # out-of-range saturate) instead of a raw astype that wraps
    return cast(Column.fixed(FLOAT64, v, validity=a.validity), _I64D)


@traced("unary_op")
def floor_(a: Column) -> Column:
    """Spark ``floor(double) -> long``; integral inputs pass through."""
    if a.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        return _float_to_long(a, jnp.floor)
    return a


@traced("unary_op")
def ceil_(a: Column) -> Column:
    """Spark ``ceil(double) -> long``; integral inputs pass through."""
    if a.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        return _float_to_long(a, jnp.ceil)
    return a


@traced("unary_op")
def is_null(a: Column) -> Column:
    return Column(BOOL8, data=(~a.valid_mask()).astype(jnp.uint8))


@traced("unary_op")
def is_not_null(a: Column) -> Column:
    return Column(BOOL8, data=a.valid_mask().astype(jnp.uint8))


@traced("unary_op")
def coalesce(*cols: Column) -> Column:
    """First non-null value per row across the arguments (same dtype)."""
    if not cols:
        raise ValueError("coalesce needs at least one column")
    out_v = _vals(cols[0])
    out_ok = cols[0].valid_mask()
    for c in cols[1:]:
        cv = _vals(c)
        take = ~out_ok & c.valid_mask()
        out_v = jnp.where(take, cv.astype(out_v.dtype), out_v)
        out_ok = out_ok | c.valid_mask()
    return _result(cols[0].dtype, out_v, out_ok)
