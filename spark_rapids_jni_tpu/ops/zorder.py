"""ZOrder: bit interleaving for multi-dimensional clustering.

TPU-native rebuild of the reference's ZOrder component (BASELINE.json
north-star set; CUDA side appears post-snapshot as src/main/cpp/src/zorder.cu
backing Delta/Databricks OPTIMIZE ZORDER BY through spark-rapids'
``interleaveBits``).  Semantics: for k integer columns of width w bits, output
row r is a k*w-bit big-endian byte string where output bit t (MSB-first)
carries bit (w-1 - t//k) of column (t % k) — identical to the Java/CUDA
``interleave_bits``.

Everything is shifts/masks on the VPU; the output is a LIST<INT8> column of
fixed k*w/8-byte rows (offsets are an arithmetic sequence, like the row-blob
columns from RowConversion).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..dtypes import INT8, TypeId

_WIDTH_OK = {1, 2, 4, 8}


def interleave_bits(table: Table) -> Column:
    """Interleave the bits of equal-width integer columns, MSB-first.

    All columns must share one storage width (cudf interleave_bits requires
    equal element widths).  Null values interleave their data bytes as-is
    (the reference kernel reads the data buffer unconditionally).
    """
    cols = list(table.columns)
    if not cols:
        raise ValueError("interleave_bits needs at least one column")
    widths = {c.dtype.itemsize for c in cols}
    if len(widths) != 1 or cols[0].dtype.itemsize not in _WIDTH_OK:
        raise TypeError(f"columns must share one integer width, got {widths}")
    for c in cols:
        if not (c.dtype.is_integral or c.dtype.is_timestamp
                or c.dtype.id == TypeId.BOOL8 or c.dtype.is_decimal):
            raise TypeError(f"non-integer column in interleave_bits: {c.dtype!r}")
    w = cols[0].dtype.itemsize * 8
    k = len(cols)
    n = cols[0].size

    # work in u64 lanes (exact for every width on TPU's emulated u64)
    vals = [c.data.astype(jnp.int64).astype(jnp.uint64)
            if c.dtype.itemsize == 8 else
            c.data.astype(jnp.uint64) if c.dtype.storage.kind == "u" else
            jax.lax.bitcast_convert_type(
                c.data.astype(jnp.int64), jnp.uint64)
            for c in cols]

    total_bits = k * w
    nbytes = total_bits // 8
    out_bytes = []
    for byte_i in range(nbytes):
        acc = jnp.zeros((n,), jnp.uint32)
        for j in range(8):
            t = byte_i * 8 + j            # output bit index, MSB-first
            col = t % k
            bit = w - 1 - t // k          # source bit, MSB-first per column
            b = ((vals[col] >> jnp.uint64(bit)) & jnp.uint64(1)).astype(jnp.uint32)
            acc = acc | (b << jnp.uint32(7 - j))
        out_bytes.append(acc.astype(jnp.uint8))
    data = jnp.stack(out_bytes, axis=1).reshape(-1)
    offsets = jnp.arange(n + 1, dtype=jnp.int32) * nbytes
    return Column.list_(Column.fixed(INT8, data), offsets)
