"""Row selection: gather (string-aware), boolean-mask filter, sorting, slicing.

The cudf primitives the reference's op layer builds on (gather with NULLIFY
out-of-bounds policy, apply_boolean_mask, sorted_order/gather) re-expressed
for XLA.  Fixed-width gathers are pure device ops; producing a *compacted*
STRING column requires the new char-buffer size, which is data-dependent, so
string compaction happens at the host boundary (XLA static shapes).  Inside
jit pipelines strings travel as padded matrices instead (strings_common).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from .order import SortKey, sort_indices
from .strings_common import to_padded_bytes, from_padded_bytes


def gather_column(col: Column, indices, indices_valid=None) -> Column:
    """Row gather with cudf NULLIFY semantics; supports STRING columns."""
    if not col.dtype.is_string:
        return col.gather(indices, indices_valid)
    indices = jnp.asarray(indices)
    mat, lengths = to_padded_bytes(col)
    n = mat.shape[0]
    ok = (indices >= 0) & (indices < n)
    safe = jnp.clip(indices, 0, max(n - 1, 0))
    gmat = jnp.take(mat, safe, axis=0)
    glen = jnp.where(ok, jnp.take(lengths, safe), 0)
    valid = ok
    if col.validity is not None:
        valid = valid & jnp.take(col.validity, safe)
    if indices_valid is not None:
        valid = valid & indices_valid
    return from_padded_bytes(gmat, glen, valid)


def gather_table(table: Table, indices, indices_valid=None) -> Table:
    return Table([gather_column(c, indices, indices_valid)
                  for c in table.columns], table.names)


def apply_boolean_mask(table: Table, mask) -> Table:
    """Keep rows where mask is True (null mask entries drop the row, like
    Spark filter).  Output size is data-dependent -> host boundary."""
    if isinstance(mask, Column):
        m = np.asarray(mask.data).astype(bool) & mask.validity_numpy()
    else:
        m = np.asarray(mask).astype(bool)
    idx = jnp.asarray(np.flatnonzero(m), jnp.int32)
    return gather_table(table, idx)


def sort_table(table: Table, keys: list[SortKey]) -> Table:
    """cudf sorted_order + gather as one call."""
    order = sort_indices(keys)
    return gather_table(table, order)


def slice_table(table: Table, start: int, length: int) -> Table:
    """Row range [start, start+length) clamped to the table (cudf::slice)."""
    start = max(0, min(start, table.num_rows))
    length = max(0, min(length, table.num_rows - start))
    idx = jnp.arange(start, start + length, dtype=jnp.int32)
    return gather_table(table, idx)
