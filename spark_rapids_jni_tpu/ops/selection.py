"""Row selection: gather (string-aware), boolean-mask filter, sorting, slicing.

The cudf primitives the reference's op layer builds on (gather with NULLIFY
out-of-bounds policy, apply_boolean_mask, sorted_order/gather) re-expressed
for XLA.  Fixed-width gathers are pure device ops; producing a *compacted*
STRING column requires the new char-buffer size, which is data-dependent, so
string compaction happens at the host boundary (XLA static shapes).  Inside
jit pipelines strings travel as padded matrices instead (strings_common).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..dtypes import TypeId
from .order import SortKey, sort_indices
from .strings_common import to_padded_bytes, from_padded_bytes
from ..utils.tracing import traced


def nonzero_indices(mask: jnp.ndarray, count: int | None = None) -> jnp.ndarray:
    """Device-side ``flatnonzero``: int32 indices of True entries, in order.

    The compaction primitive every data-dependent-size op shares.  A stable
    argsort moves True rows to the front without leaving the device; only the
    *count* touches the host (one scalar sync — the same place cudf returns
    its gather-map size).  Pass a *static* ``count`` (e.g. the full length,
    or a capacity bound) to stay fully on-device inside jit; the slice size
    must be trace-time constant.
    """
    order = jnp.argsort(jnp.logical_not(mask).astype(jnp.uint8), stable=True)
    if count is None:
        count = int(jnp.sum(mask))
    return order[:count].astype(jnp.int32)


def gather_column(col: Column, indices, indices_valid=None) -> Column:
    """Row gather with cudf NULLIFY semantics; supports STRING columns."""
    if not col.dtype.is_string:
        return col.gather(indices, indices_valid)
    indices = jnp.asarray(indices)
    if col.size == 0:
        n_out = indices.shape[0]
        return Column.string(jnp.zeros((0,), jnp.uint8),
                             jnp.zeros((n_out + 1,), jnp.int32),
                             validity=jnp.zeros((n_out,), jnp.bool_))
    mat, lengths = to_padded_bytes(col)
    n = mat.shape[0]
    ok = (indices >= 0) & (indices < n)
    safe = jnp.clip(indices, 0, max(n - 1, 0))
    gmat = jnp.take(mat, safe, axis=0)
    glen = jnp.where(ok, jnp.take(lengths, safe), 0)
    valid = ok
    if col.validity is not None:
        valid = valid & jnp.take(col.validity, safe)
    if indices_valid is not None:
        valid = valid & indices_valid
    return from_padded_bytes(gmat, glen, valid)


def gather_table(table: Table, indices, indices_valid=None) -> Table:
    return Table([gather_column(c, indices, indices_valid)
                  for c in table.columns], table.names)


def _filter_mask(mask) -> jnp.ndarray:
    """bool[n] keep-mask; null mask entries drop the row (Spark filter)."""
    if isinstance(mask, Column):
        return (mask.data != 0) & mask.valid_mask()
    return jnp.asarray(mask).astype(jnp.bool_)


@traced("apply_boolean_mask")
def apply_boolean_mask(table: Table, mask) -> Table:
    """Keep rows where mask is True.  Compaction runs on device; only the
    surviving-row *count* syncs to the host (output shape)."""
    m = _filter_mask(mask)
    return gather_table(table, nonzero_indices(m))


def apply_boolean_mask_padded(table: Table, mask):
    """Jit-able filter: rows reordered live-first at full length.

    Returns (padded Table, live row mask, live count) — the static-shape
    form pjit pipelines compose (pair with groupby_padded's row_mask /
    shuffle's ok mask); compact at the host boundary only when materializing.
    """
    m = _filter_mask(mask)
    n = table.num_rows
    order = nonzero_indices(m, count=n)
    count = jnp.sum(m.astype(jnp.int32))
    live = jnp.arange(n, dtype=jnp.int32) < count
    return gather_table(table, order, indices_valid=live), live, count


@traced("sort_table")
def sort_table(table: Table, keys: list[SortKey]) -> Table:
    """cudf sorted_order + gather as one call."""
    order = sort_indices(keys)
    return gather_table(table, order)


def concat_tables(tables: list[Table]) -> Table:
    """Vertical concatenation of same-schema Tables (cudf concatenate).

    Host-boundary op: output length is the sum of inputs, so this runs
    outside jit (like compaction).  STRING/LIST offsets are rebased; the
    result lands back on the device.
    """
    if not tables:
        raise ValueError("concat_tables needs at least one table")
    if len(tables) == 1:
        return tables[0]
    first = tables[0]
    for t in tables[1:]:
        if t.num_columns != first.num_columns or any(
                not _schema_matches(a, b)
                for a, b in zip(first.columns, t.columns)):
            raise TypeError("concat_tables requires identical schemas "
                            "(including nested child types)")
    cols = [_concat_columns([t.columns[i] for t in tables])
            for i in range(first.num_columns)]
    return Table(cols, first.names)


def _schema_matches(a: Column, b: Column) -> bool:
    if a.dtype != b.dtype or len(a.children) != len(b.children):
        return False
    return all(_schema_matches(ca, cb)
               for ca, cb in zip(a.children, b.children))


def _concat_columns(parts: list[Column]) -> Column:
    d0 = parts[0].dtype
    any_valid = any(p.validity is not None for p in parts)
    valid = np.concatenate([p.validity_numpy() for p in parts]) \
        if any_valid else None
    if d0.id == TypeId.STRUCT:
        kids = tuple(_concat_columns([p.children[i] for p in parts])
                     for i in range(len(parts[0].children)))
        return Column(d0, validity=None if valid is None
                      else jnp.asarray(valid), children=kids)
    if d0.is_string or d0.id == TypeId.LIST:
        offs = [np.asarray(parts[0].offsets, np.int64)]
        base = int(offs[0][-1])
        for p in parts[1:]:
            o = np.asarray(p.offsets, np.int64)
            offs.append(o[1:] + base)
            base += int(o[-1])
        offsets = np.concatenate(offs)
        if offsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("concatenated column exceeds int32 offsets")
        if d0.is_string:
            chars = np.concatenate([np.asarray(p.data) for p in parts])
            return Column.string(chars, offsets.astype(np.int32), valid)
        child = _concat_columns([p.children[0] for p in parts])
        return Column.list_(child, offsets.astype(np.int32), valid)
    data = np.concatenate([np.asarray(p.data) for p in parts])
    return Column(d0, data=jnp.asarray(data),
                  validity=None if valid is None else jnp.asarray(valid))


def distinct(table: Table, subset: list | None = None) -> Table:
    """Spark dropDuplicates: keep the first row of each key group.

    Returns FULL rows (all columns), deduplicated over ``subset`` (default:
    all columns).  Null keys compare equal (one null group).  Host-boundary
    op: the surviving-row count is data-dependent."""
    from .order import encode_keys, rows_differ_from_prev
    key_cols = list(table.columns) if subset is None else \
        [table.column(k) for k in subset]
    sk = [SortKey(c) for c in key_cols]
    order = sort_indices(sk)
    bounds = rows_differ_from_prev(encode_keys(sk), order)
    # stable sort → the boundary row of each group is its earliest input row
    keep = np.sort(np.asarray(order)[np.asarray(bounds)])
    return gather_table(table, jnp.asarray(keep.astype(np.int32)))


def slice_table(table: Table, start: int, length: int) -> Table:
    """Row range [start, start+length) clamped to the table (cudf::slice)."""
    start = max(0, min(start, table.num_rows))
    length = max(0, min(length, table.num_rows - start))
    idx = jnp.arange(start, start + length, dtype=jnp.int32)
    return gather_table(table, idx)
