"""Window functions: partitioned, ordered analytics over rows.

The libcudf rolling/window role (SURVEY.md §2.2 "algorithms"; Spark plans
these as WindowExec over GpuWindow).  Same TPU shape as the groupby
(docs/PERF.md "sorts over scatters"): ONE multi-operand sort by
(partition, order) keys carries every input column; ranks and running
aggregates are cumulative/segmented scans; results ride a second
payload-carrying sort back to input row order — no gathers, no scatters.

Supported window ops (Spark names):
- ``row_number``                        1-based position in the partition
- ``rank`` / ``dense_rank``             ties share a rank
- ``percent_rank`` / ``cume_dist``      relative rank / cumulative share
- ``ntile`` (buckets k)                 Spark bucket assignment
- ``lag`` / ``lead`` (offset k)         null outside the partition
- ``first_value`` / ``last_value``      over the default frame: partition
  head / end of the current peer run
- ``sum`` / ``min`` / ``max`` / ``count`` / ``mean``
  running aggregates over Spark's default frame: RANGE UNBOUNDED
  PRECEDING .. CURRENT ROW — rows tied on the order keys (peers) share
  the frame value; with no order keys the frame is the whole partition
- ``rolling_sum`` / ``rolling_count`` / ``rolling_mean`` (window w):
  ROWS BETWEEN w-1 PRECEDING AND CURRENT ROW, via prefix differences
  (cudf::rolling_window's bounded-ROWS shape)

All jit-safe: fixed shapes, no host syncs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..dtypes import FLOAT64, INT64, TypeId
from .aggregate import (_float64_vals, _seg_last_valid, _seg_scan,
                        _shift_down)
from .order import SortKey, encode_keys
from ..utils.tracing import traced


def _shift_up(arr, shift: int, fill):
    """arr shifted so row i sees row i+shift (back-filled)."""
    pad = jnp.full((shift,) + arr.shape[1:], fill, arr.dtype)
    return jnp.concatenate([arr[shift:], pad], axis=0)


def window_out_dtype(col_dtype, op: str):
    """Result dtype of a window op (shared with parallel.distributed)."""
    if op in ("row_number", "rank", "dense_rank", "count", "ntile"):
        return INT64
    if op in ("lag", "lead", "min", "max", "first_value", "last_value"):
        return col_dtype
    if op in ("mean", "percent_rank", "cume_dist"):
        return FLOAT64
    if op in ("sum", "rolling_sum"):
        if col_dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            return FLOAT64
        return col_dtype if col_dtype.is_decimal else INT64
    if op == "rolling_count":
        return INT64
    if op == "rolling_mean":
        return FLOAT64
    raise ValueError(f"unknown window op {op!r}")


def default_window_names(specs) -> list:
    """Default (de-duplicated) output names (shared with distributed)."""
    names, seen = [], {}
    for spec in specs:
        ref, op, *_ = spec
        nm = op if ref is None or not isinstance(ref, str) else f"{op}_{ref}"
        if nm in seen:
            seen[nm] += 1
            nm = f"{nm}_{seen[nm]}"
        else:
            seen[nm] = 1
        names.append(nm)
    return names


def _running(op: str, col: Column, sval, svalid, seg, peer_fill):
    """Running aggregate over the ordered partition frame.

    Spark's default frame with ORDER BY is RANGE UNBOUNDED PRECEDING ..
    CURRENT ROW: peer rows (ties on the order keys) share the frame, so
    every prefix value is forward-filled from the END of its peer run via
    ``peer_fill``.  With no ORDER BY the whole partition is one peer run
    and this degenerates to the partition total — also Spark's default.
    """
    if op == "count":
        m = svalid.astype(jnp.int64)
        cnt = peer_fill(_seg_scan(m, seg, jnp.add, jnp.zeros((), jnp.int64)),
                        jnp.int64(0))
        return Column(INT64, data=cnt)
    if op in ("sum", "mean"):
        if col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            vf = _float64_vals(col, sval)
        else:
            vf = sval.astype(jnp.int64)  # decimal: unscaled; int: widened
        zero = jnp.zeros((), vf.dtype)
        m = jnp.where(svalid, vf, zero)
        s = peer_fill(_seg_scan(m, seg, jnp.add, zero), zero)
        cnt = peer_fill(_seg_scan(svalid.astype(jnp.int64), seg, jnp.add,
                                  jnp.zeros((), jnp.int64)), jnp.int64(0))
        if op == "mean":
            mean = s.astype(jnp.float64) / jnp.maximum(cnt, 1).astype(
                jnp.float64)
            if col.dtype.is_decimal:
                mean = mean * (10.0 ** col.dtype.scale)
            return Column.fixed(FLOAT64, mean, validity=cnt > 0)
        if col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            return Column.fixed(FLOAT64, s, validity=cnt > 0)
        out = col.dtype if col.dtype.is_decimal else INT64
        return Column(out, data=s, validity=cnt > 0)
    if op in ("min", "max"):
        if col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            from . import order as _order
            enc = _order._fixed_to_u64(Column(col.dtype, data=sval))
            ident = jnp.uint64(2**64 - 1) if op == "min" else jnp.uint64(0)
            enc = jnp.where(svalid, enc, ident)
            combine = jnp.minimum if op == "min" else jnp.maximum
            red = peer_fill(_seg_scan(enc, seg, combine, ident), ident)
            cnt = peer_fill(_seg_scan(svalid.astype(jnp.int64), seg, jnp.add,
                                      jnp.zeros((), jnp.int64)), jnp.int64(0))
            data = _order.decode_minmax_bits(red, col.dtype)
            return Column(col.dtype, data=data, validity=cnt > 0)
        if jnp.issubdtype(sval.dtype, jnp.integer):
            info = jnp.iinfo(sval.dtype)
            ident = jnp.asarray(info.max if op == "min" else info.min,
                                sval.dtype)
        else:
            ident = jnp.asarray(jnp.inf if op == "min" else -jnp.inf,
                                sval.dtype)
        m = jnp.where(svalid, sval, ident)
        combine = jnp.minimum if op == "min" else jnp.maximum
        red = peer_fill(_seg_scan(m, seg, combine, ident), ident)
        cnt = peer_fill(_seg_scan(svalid.astype(jnp.int64), seg, jnp.add,
                                  jnp.zeros((), jnp.int64)), jnp.int64(0))
        return Column(col.dtype, data=red, validity=cnt > 0)
    raise ValueError(f"unknown window aggregate {op!r}")


@traced("window")
def window(table: Table, partition_by: list, order_by: list,
           specs: list[tuple], names: list | None = None,
           live=None) -> Table:
    """Append window columns; rows keep their input order.

    ``specs``: list of (column_or_None, op) or (column, op, k) for lag/lead.
    ``order_by`` entries may be column names or SortKey for descending.
    ``live``: optional bool[n] row mask for padded pipelines (post-shuffle
    shards) — dead rows form their own trailing partition and produce
    garbage outputs the caller must mask; live rows never see them.
    """
    n = table.num_rows
    pkeys = [SortKey(table.column(k)) if not isinstance(k, SortKey) else k
             for k in partition_by]
    okeys = [SortKey(table.column(k)) if not isinstance(k, SortKey) else k
             for k in order_by]
    pwords = encode_keys(pkeys)
    if live is not None:
        # dead rows sort last and never share a partition with live rows
        pwords = [jnp.logical_not(live).astype(jnp.uint64)] + pwords
    owords = encode_keys(okeys)
    nw_p, nw_o = len(pwords), len(owords)

    # distinct value columns ride the sort once each
    distinct_cols: list[Column] = []
    slot_of: dict[int, int] = {}
    resolved = []
    for spec in specs:
        ref, op, *rest = spec
        col = None
        if ref is None:
            if op == "count":  # count(*): peers share the frame (RANGE)
                op = "count_star"
            elif op not in ("row_number", "rank", "dense_rank",
                            "percent_rank", "cume_dist", "ntile"):
                raise ValueError(
                    f"window op {op!r} needs a value column (got None)")
        else:
            col = ref if isinstance(ref, Column) else table.column(ref)
            if col.dtype.is_string:
                raise TypeError("string value columns are not supported in "
                                "window aggregates")
            if col.data is None or col.data.ndim != 1:
                raise TypeError(
                    f"window value column must be 1-D fixed-width; "
                    f"{col.dtype!r} is not (DECIMAL128 limb pairs and "
                    "nested columns cannot ride the sort payload)")
            if id(col) not in slot_of:
                slot_of[id(col)] = len(distinct_cols)
                distinct_cols.append(col)
        k = int(rest[0]) if rest else 1
        if op == "ntile" and k < 1:
            raise ValueError(f"NTILE bucket count must be >= 1, got {k}")
        if op.startswith("rolling_") and k < 1:
            raise ValueError(f"rolling window size must be >= 1, got {k}")
        if op in ("lag", "lead") and k < 0:  # Spark: lag(-k) == lead(k)
            op = "lead" if op == "lag" else "lag"
            k = -k
        resolved.append((col, op, k))

    payloads = [jnp.arange(n, dtype=jnp.int32)]  # original row index
    for c in distinct_cols:
        payloads.append(c.data)
        payloads.append(c.valid_mask().astype(jnp.uint8))
    sorted_all = jax.lax.sort(tuple(pwords) + tuple(owords) + tuple(payloads),
                              num_keys=nw_p + nw_o, is_stable=True)
    spwords = sorted_all[:nw_p]
    sowords = sorted_all[nw_p:nw_p + nw_o]
    sp = sorted_all[nw_p + nw_o:]
    row_idx_sorted = sp[0]
    sdata, svalid = [], []
    for i in range(len(distinct_cols)):
        sdata.append(sp[1 + 2 * i])
        svalid.append(sp[2 + 2 * i].astype(jnp.bool_))

    first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    pbounds = first
    for w in spwords:
        pbounds = pbounds | jnp.concatenate([first[:1], w[1:] != w[:-1]])
    seg = jnp.cumsum(pbounds.astype(jnp.int32)) - 1
    obounds = pbounds
    for w in sowords:
        obounds = obounds | jnp.concatenate([first[:1], w[1:] != w[:-1]])

    idx = jnp.arange(n, dtype=jnp.int64)
    seg_start = _seg_scan(idx, seg, lambda cur, prev: prev, jnp.int64(0))
    row_number = (idx - seg_start + 1)

    # RANGE-frame fill: running values are shared across order-key peers by
    # taking each peer run's END value (backward nearest-valid fill =
    # forward nearest-valid fill on the reversed arrays — still gather-free)
    is_end = jnp.concatenate([obounds[1:], jnp.ones((1,), jnp.bool_)])

    def peer_fill(arr, ident):
        rev = jnp.where(is_end, arr, ident)[::-1]
        filled = _seg_last_valid(rev, is_end[::-1], seg[::-1])
        return filled[::-1]

    # partition size: every row adopts its partition's last row_number
    part_size = None

    def _part_size():
        nonlocal part_size
        if part_size is None:
            last = jnp.concatenate([pbounds[1:], jnp.ones((1,), jnp.bool_)])
            rev = jnp.where(last, row_number, jnp.int64(0))[::-1]
            part_size = _seg_last_valid(rev, last[::-1], seg[::-1])[::-1]
        return part_size

    rank_cache = None

    def _rank():
        nonlocal rank_cache
        if rank_cache is None:
            rn_at_change = jnp.where(obounds, row_number, jnp.int64(0))
            rank_cache = _seg_scan(rn_at_change, seg, jnp.maximum,
                                   jnp.int64(0))
        return rank_cache

    out_sorted = []
    for col, op, k in resolved:
        if op == "row_number":
            out_sorted.append((INT64, row_number, None))
        elif op == "count_star":
            out_sorted.append((INT64, peer_fill(row_number, jnp.int64(0)),
                               None))
        elif op == "percent_rank":
            ps = _part_size().astype(jnp.float64)
            pr = (_rank() - 1).astype(jnp.float64) / jnp.maximum(ps - 1.0,
                                                                 1.0)
            out_sorted.append((FLOAT64, Column.fixed(FLOAT64, pr).data,
                               None))
        elif op == "cume_dist":
            cd = peer_fill(row_number, jnp.int64(0)).astype(jnp.float64) \
                / _part_size().astype(jnp.float64)
            out_sorted.append((FLOAT64, Column.fixed(FLOAT64, cd).data,
                               None))
        elif op == "ntile":
            # Spark NTile: first (n % k) buckets get ceil(n/k) rows
            ps = _part_size()
            kk = jnp.int64(k)
            base = ps // kk
            rem = ps % kk
            rn0 = row_number - 1
            big = (base + 1) * rem  # rows covered by the larger buckets
            tile = jnp.where(
                rn0 < big,
                rn0 // jnp.maximum(base + 1, 1),
                rem + (rn0 - big) // jnp.maximum(base, 1))
            out_sorted.append((INT64, tile + 1, None))
        elif op == "rank":
            out_sorted.append((INT64, _rank(), None))
        elif op == "dense_rank":
            d = jnp.cumsum(obounds.astype(jnp.int64))
            d_start = _seg_scan(d, seg, lambda cur, prev: prev, jnp.int64(0))
            out_sorted.append((INT64, d - d_start + 1, None))
        elif op in ("lag", "lead"):
            slot = slot_of[id(col)]
            sval, sv = sdata[slot], svalid[slot]
            if k == 0:
                shifted, shv, sseg = sval, sv, seg
            elif k >= n:  # entire partition out of range → all null
                shifted = jnp.zeros_like(sval)
                shv = jnp.zeros((n,), jnp.bool_)
                sseg = jnp.full((n,), -1, jnp.int32)
            elif op == "lag":
                shifted = _shift_down(sval, k, jnp.zeros((), sval.dtype))
                shv = _shift_down(sv, k, jnp.zeros((), jnp.bool_))
                sseg = _shift_down(seg, k, jnp.int32(-1))
            else:
                shifted = _shift_up(sval, k, jnp.zeros((), sval.dtype))
                shv = _shift_up(sv, k, jnp.zeros((), jnp.bool_))
                sseg = _shift_up(seg, k, jnp.int32(-1))
            ok = (sseg == seg) & shv
            out_sorted.append((col.dtype, shifted, ok))
        elif op in ("first_value", "last_value"):
            # Spark default frame (RANGE UNBOUNDED PRECEDING..CURRENT ROW):
            # first_value is the partition's first row's value; last_value
            # is the value at the END of the current peer run
            slot = slot_of[id(col)]
            sval, sv = sdata[slot], svalid[slot]
            if op == "first_value":
                fv = _seg_scan(sval, seg, lambda cur, prev: prev,
                               jnp.zeros((), sval.dtype))
                fvv = _seg_scan(sv, seg, lambda cur, prev: prev,
                                jnp.zeros((), jnp.bool_))
            else:
                fv = peer_fill(sval, jnp.zeros((), sval.dtype))
                fvv = peer_fill(sv, jnp.zeros((), jnp.bool_))
            out_sorted.append((col.dtype, fv, fvv))
        elif op in ("rolling_sum", "rolling_count", "rolling_mean"):
            # ROWS-frame bounded window via prefix differences: the sum over
            # [i-k+1, i] is ps[i] - ps[i-k], with rows from another segment
            # contributing their prefix AT the segment boundary... which is
            # exactly what subtracting the shifted-from-other-segment prefix
            # would get wrong — so shift both the prefix and its segment id
            # and fall back to the segment-start prefix when i-k crosses it.
            slot = slot_of[id(col)]
            sval, sv = sdata[slot], svalid[slot]
            is_float = col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64)
            vf = _float64_vals(col, sval) if is_float \
                else sval.astype(jnp.int64)
            zero = jnp.zeros((), vf.dtype)
            kk = min(k, n)

            def windowed(contrib, ident):
                """Σ contrib over the last kk rows of the same segment, via
                per-segment inclusive prefixes (prefix before a segment
                start is identically 0, so the boundary base is just 0)."""
                ps_ = _seg_scan(contrib, seg, jnp.add, ident)
                if kk == 0:
                    return ps_
                pk = _shift_down(ps_, kk, ident)
                sk = _shift_down(seg, kk, jnp.int32(-1))
                return ps_ - jnp.where(sk == seg, pk,
                                       jnp.zeros((), ps_.dtype))

            if is_float:
                # isolate non-finite values so a NaN/Inf only affects the
                # windows that actually contain it (prefix differences would
                # otherwise poison every later window: NaN - NaN = NaN)
                finite = jnp.isfinite(vf)
                rsum = windowed(jnp.where(sv & finite, vf, zero), zero)
                nan_w = windowed((sv & jnp.isnan(vf)).astype(jnp.int64),
                                 jnp.int64(0))
                pinf_w = windowed((sv & jnp.isposinf(vf)).astype(jnp.int64),
                                  jnp.int64(0))
                ninf_w = windowed((sv & jnp.isneginf(vf)).astype(jnp.int64),
                                  jnp.int64(0))
                rsum = jnp.where(pinf_w > 0, jnp.inf, rsum)
                rsum = jnp.where(ninf_w > 0, -jnp.inf, rsum)
                rsum = jnp.where((nan_w > 0) | ((pinf_w > 0) & (ninf_w > 0)),
                                 jnp.nan, rsum)
            else:
                rsum = windowed(jnp.where(sv, vf, zero), zero)
            rcnt = windowed(sv.astype(jnp.int64), jnp.int64(0))
            if op == "rolling_count":
                out_sorted.append((INT64, rcnt, None))
            elif op == "rolling_sum":
                if col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
                    c_ = Column.fixed(FLOAT64, rsum, validity=rcnt > 0)
                    out_sorted.append((FLOAT64, c_.data, rcnt > 0))
                else:
                    out = col.dtype if col.dtype.is_decimal else INT64
                    out_sorted.append((out, rsum, rcnt > 0))
            else:
                mean = rsum.astype(jnp.float64) / jnp.maximum(
                    rcnt, 1).astype(jnp.float64)
                if col.dtype.is_decimal:
                    mean = mean * (10.0 ** col.dtype.scale)
                c_ = Column.fixed(FLOAT64, mean, validity=rcnt > 0)
                out_sorted.append((FLOAT64, c_.data, rcnt > 0))
        else:
            slot = slot_of[id(col)]
            c = _running(op, col, sdata[slot], svalid[slot], seg, peer_fill)
            out_sorted.append((c.dtype, c.data,
                               c.valid_mask() if c.validity is not None
                               else None))

    # ride ONE sort back to input row order (sorts over scatters)
    back_payloads = []
    for dtype, data, valid in out_sorted:
        back_payloads.append(data)
        back_payloads.append((jnp.ones((n,), jnp.bool_) if valid is None
                              else valid).astype(jnp.uint8))
    unsorted = jax.lax.sort((row_idx_sorted,) + tuple(back_payloads),
                            num_keys=1, is_stable=True)[1:]
    out_cols = []
    for i, (dtype, _, valid) in enumerate(out_sorted):
        data = unsorted[2 * i]
        v = unsorted[2 * i + 1].astype(jnp.bool_)
        out_cols.append(Column(dtype, data=data,
                               validity=None if valid is None else v))

    out_names = list(names) if names is not None \
        else default_window_names(specs)
    return Table(list(table.columns) + out_cols,
                 list(table.names or [f"c{i}" for i in
                                      range(table.num_columns)]) + out_names)
