"""CastStrings: Spark-semantics string <-> numeric/decimal/bool casts.

TPU-native rebuild of the reference's CastStrings component (named in
BASELINE.json's north-star op set; CUDA side appears post-snapshot as
src/main/cpp/src/cast_string.cu).  Behavior follows Spark's CAST:

- string -> int/long/short/byte: trim, optional sign, digits, optionally a
  fraction that is validated but truncated (Spark's UTF8String.toLong accepts
  "123.456" -> 123); anything else, or overflow, yields null (or raises when
  ``ansi=True``, matching Spark ANSI mode).
- string -> float/double: optional sign, digits with fraction and exponent,
  case-insensitive "inf"/"infinity"/"nan" keywords, optional trailing d/f
  suffix (Java parseDouble semantics).  Values may differ from the JVM by
  ~1 ulp on >17-digit inputs — same caveat the cudf implementation documents.
- string -> decimal(scale): exact integer parsing with HALF_UP rounding to the
  target scale (cudf convention: negative scale = fractional digits), null on
  overflow of the storage type.
- int/bool -> string; string -> bool with Spark's accepted literal sets.

Everything runs as one `lax.scan` state machine over the padded byte matrix —
a data-parallel reformulation of the per-thread character loops a CUDA
implementation uses; every row advances through the same per-character step on
the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import DType, TypeId, BOOL8, STRING
from .strings_common import to_padded_bytes, from_padded_bytes
from ..utils.tracing import traced

_U64 = jnp.uint64
_I32 = jnp.int32

# u64 mantissa capacity: accumulating another digit is safe below this
_ACC_CAP = _U64((2**64 - 1 - 9) // 10)

_POW10_U64 = jnp.asarray([10**k for k in range(20)], jnp.uint64)
# f64 powers of ten, exact-to-double-rounding, index k -> 10^(k-350)
_POW10_F64 = jnp.asarray(
    np.array([float(f"1e{k}") for k in range(-350, 351)]),  # strtod: correctly
    jnp.float64)                                            # rounded, inf/0 at ends


def _trim_bounds(mat, lengths):
    """Spark trims leading/trailing ASCII control+space (UTF8String.trim)."""
    n, w = mat.shape
    pos = jnp.arange(w, dtype=_I32)[None, :]
    in_str = pos < lengths[:, None]
    is_ws = (mat <= 32) | ~in_str
    non_ws = ~is_ws
    any_non = non_ws.any(axis=1)
    start = jnp.argmax(non_ws, axis=1).astype(_I32)
    end = (w - jnp.argmax(non_ws[:, ::-1], axis=1)).astype(_I32)
    start = jnp.where(any_non, start, 0)
    end = jnp.where(any_non, end, 0)
    return start, end


# parser states
_S_START, _S_INT, _S_FRAC, _S_EXP0, _S_EXP, _S_BAD = range(6)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _parse_number(mat, lengths, allow_frac: bool, allow_exp: bool,
                  accumulate_frac: bool, allow_suffix: bool = False):
    """Data-parallel numeric-literal state machine.

    Returns per-row arrays: neg, digits (u64 mantissa, int [+frac] digits),
    frac_kept, dropped_int, exp (signed), has_digits, syntax_ok, overflow.
    """
    n, w = mat.shape
    start, end = _trim_bounds(mat, lengths)

    if allow_suffix:
        # Java parseDouble accepts a trailing d/D/f/F suffix after the number
        last = jnp.take_along_axis(
            mat, jnp.clip(end - 1, 0, w - 1)[:, None], axis=1)[:, 0]
        has_suffix = ((last == ord('d')) | (last == ord('D'))
                      | (last == ord('f')) | (last == ord('F'))) & (end - start > 1)
        end = jnp.where(has_suffix, end - 1, end)

    zeros_i = jnp.zeros((n,), _I32)
    carry = dict(
        state=jnp.full((n,), _S_START, _I32),
        neg=jnp.zeros((n,), jnp.bool_),
        digits=jnp.zeros((n,), _U64),
        ndigits=zeros_i, frac_kept=zeros_i, dropped_int=zeros_i,
        exp=zeros_i, exp_digits=zeros_i, exp_neg=jnp.zeros((n,), jnp.bool_),
    )

    def step(c, xs):
        ch, p = xs
        active = (p >= start) & (p < end)
        st = c["state"]
        d = ch.astype(_I32) - ord('0')
        is_digit = (d >= 0) & (d <= 9)
        is_sign = (ch == ord('+')) | (ch == ord('-'))
        is_dot = ch == ord('.')
        is_e = (ch == ord('e')) | (ch == ord('E'))
        at_start = p == start

        # mantissa accumulation (int digits always; frac digits optionally)
        acc_int = active & is_digit & ((st == _S_START) | (st == _S_INT))
        acc_frac = active & is_digit & (st == _S_FRAC) & accumulate_frac
        acc = acc_int | acc_frac
        can = c["digits"] <= _ACC_CAP
        new_digits = jnp.where(
            acc & can, c["digits"] * _U64(10) + d.astype(_U64), c["digits"])
        # dropped int digits shift the magnitude; dropped frac digits only
        # lose precision
        dropped_int = c["dropped_int"] + jnp.where(acc_int & ~can, 1, 0)
        frac_kept = c["frac_kept"] + jnp.where(acc_frac & can, 1, 0)
        ndigits = c["ndigits"] + jnp.where(
            active & is_digit & (st != _S_EXP0) & (st != _S_EXP), 1, 0)

        # exponent accumulation (cap well past any meaningful range)
        acc_exp = active & is_digit & ((st == _S_EXP0) | (st == _S_EXP))
        new_exp = jnp.where(acc_exp, jnp.minimum(c["exp"] * 10 + d, 99999),
                            c["exp"])
        exp_digits = c["exp_digits"] + jnp.where(acc_exp, 1, 0)

        neg = jnp.where(active & at_start & (ch == ord('-')), True, c["neg"])
        exp_neg = jnp.where(active & (st == _S_EXP0) & (ch == ord('-')),
                            True, c["exp_neg"])

        # state transitions
        nxt = jnp.where(is_digit, jnp.where(
            (st == _S_START) | (st == _S_INT), _S_INT, jnp.where(
                st == _S_FRAC, _S_FRAC, jnp.where(
                    (st == _S_EXP0) | (st == _S_EXP), _S_EXP, _S_BAD))),
            _S_BAD)
        nxt = jnp.where(is_sign & at_start & (st == _S_START), _S_START, nxt)
        nxt = jnp.where(is_sign & (st == _S_EXP0) & ~at_start, _S_EXP, nxt)
        if allow_frac:
            nxt = jnp.where(
                is_dot & ((st == _S_START) | (st == _S_INT)), _S_FRAC, nxt)
        if allow_exp:
            nxt = jnp.where(
                is_e & ((st == _S_INT) | (st == _S_FRAC)) & (c["ndigits"] > 0),
                _S_EXP0, nxt)
        nxt = jnp.where(st == _S_BAD, _S_BAD, nxt)
        state = jnp.where(active, nxt, st)

        return dict(state=state, neg=neg, digits=new_digits, ndigits=ndigits,
                    frac_kept=frac_kept, dropped_int=dropped_int, exp=new_exp,
                    exp_digits=exp_digits, exp_neg=exp_neg), None

    pos = jnp.arange(w, dtype=_I32)
    carry, _ = jax.lax.scan(step, carry, (mat.T, pos))

    st = carry["state"]
    syntax_ok = ((st == _S_INT) | (st == _S_FRAC) | (st == _S_EXP)) \
        & (carry["ndigits"] > 0) & (end > start)
    # "1e+" / "1e-" reach _S_EXP via the sign without any exponent digit
    syntax_ok = syntax_ok & ~((st == _S_EXP) & (carry["exp_digits"] == 0))
    exp = jnp.where(carry["exp_neg"], -carry["exp"], carry["exp"])
    return dict(neg=carry["neg"], digits=carry["digits"],
                frac_kept=carry["frac_kept"], dropped_int=carry["dropped_int"],
                exp=exp, ndigits=carry["ndigits"], syntax_ok=syntax_ok,
                overflow=carry["dropped_int"] > 0)


_INT_BOUNDS = {
    TypeId.INT8: 2**7, TypeId.INT16: 2**15, TypeId.INT32: 2**31,
    TypeId.INT64: 2**63,
}


def _null_out(col: Column, ok):
    return ok if col.validity is None else (ok & col.validity)


@traced("cast.to_integer")
def cast_to_integer(col: Column, dtype: DType, ansi: bool = False) -> Column:
    """string -> byte/short/int/long with Spark CAST semantics."""
    if dtype.id not in _INT_BOUNDS:
        raise TypeError(f"not an integer target: {dtype!r}")
    mat, lengths = to_padded_bytes(col)
    p = _parse_number(mat, lengths, True, False, False)
    bound = _INT_BOUNDS[dtype.id]
    limit = jnp.where(p["neg"], _U64(bound), _U64(bound - 1))
    ok = p["syntax_ok"] & ~p["overflow"] & (p["digits"] <= limit)
    mag = jnp.minimum(p["digits"], limit)  # clamp so the cast below is defined
    signed = jnp.where(p["neg"],
                       (~mag + _U64(1)).astype(jnp.int64),
                       mag.astype(jnp.int64))
    valid = _null_out(col, ok)
    if ansi:
        bad = bool((~ok & (col.valid_mask())).any())
        if bad:
            raise ValueError(f"invalid input for CAST to {dtype!r} in ANSI mode")
    return Column(dtype, data=signed.astype(dtype.jnp_dtype), validity=valid)


def _keyword_match(mat, start, end, word: bytes):
    """Case-insensitive match of the trimmed region against a keyword."""
    n, w = mat.shape
    length = end - start
    m = length == len(word)
    for i, ch in enumerate(word):
        pos = jnp.clip(start + i, 0, w - 1)
        c = jnp.take_along_axis(mat, pos[:, None], axis=1)[:, 0]
        lower = jnp.where((c >= 65) & (c <= 90), c + 32, c)
        m = m & (lower == ch)
    return m


@traced("cast.to_float")
def cast_to_float(col: Column, dtype: DType, ansi: bool = False) -> Column:
    """string -> float/double with Spark CAST semantics."""
    if dtype.id not in (TypeId.FLOAT32, TypeId.FLOAT64):
        raise TypeError(f"not a float target: {dtype!r}")
    mat, lengths = to_padded_bytes(col)
    start, end = _trim_bounds(mat, lengths)
    p = _parse_number(mat, lengths, True, True, True, True)

    # value = digits * 10^(exp + dropped_int - frac_kept)
    eff = p["exp"] + p["dropped_int"] - p["frac_kept"]
    eff = jnp.clip(eff, -350, 350)
    scale = jnp.take(_POW10_F64, (eff + 350).astype(_I32))
    mag = p["digits"].astype(jnp.float64) * scale
    val = jnp.where(p["neg"], -mag, mag)

    # keywords (after optional sign)
    first = jnp.take_along_axis(
        mat, jnp.clip(start, 0, mat.shape[1] - 1)[:, None], axis=1)[:, 0]
    has_sign = (first == ord('+')) | (first == ord('-'))
    kw_start = jnp.where(has_sign, start + 1, start)
    kw_neg = first == ord('-')
    is_inf = (_keyword_match(mat, kw_start, end, b"inf")
              | _keyword_match(mat, kw_start, end, b"infinity"))
    is_nan = _keyword_match(mat, kw_start, end, b"nan")  # sign allowed, ignored
    val = jnp.where(is_inf, jnp.where(kw_neg, -jnp.inf, jnp.inf), val)
    val = jnp.where(is_nan, jnp.nan, val)

    ok = p["syntax_ok"] | is_inf | is_nan
    valid = _null_out(col, ok)
    if ansi and bool((~ok & col.valid_mask()).any()):
        raise ValueError(f"invalid input for CAST to {dtype!r} in ANSI mode")
    if dtype.id == TypeId.FLOAT32:
        return Column(dtype, data=val.astype(jnp.float32), validity=valid)
    return Column.fixed(dtype, val, validity=valid)  # FLOAT64 stores bits


@traced("cast.to_decimal")
def cast_to_decimal(col: Column, dtype: DType, ansi: bool = False) -> Column:
    """string -> decimal32/64 at the target scale, HALF_UP rounding.

    cudf scale convention (dtypes.py): stored integer = value * 10^(-scale).
    """
    if not dtype.is_decimal:
        raise TypeError(f"not a decimal target: {dtype!r}")
    mat, lengths = to_padded_bytes(col)
    p = _parse_number(mat, lengths, True, True, True)

    # unscaled = digits * 10^shift, shift = -scale - frac_kept + exp + dropped
    shift = (-dtype.scale) - p["frac_kept"] + p["exp"] + p["dropped_int"]
    up = jnp.clip(shift, 0, 19)
    down = jnp.clip(-shift, 0, 19)
    mul = jnp.take(_POW10_U64, up.astype(_I32))
    div = jnp.take(_POW10_U64, down.astype(_I32))
    # overflow if digits * mul wraps: digits > max/mul
    umax = _U64(2**64 - 1)
    mul_ovf = (shift > 0) & (p["digits"] > umax // mul)
    scaled_up = p["digits"] * jnp.where(mul_ovf, _U64(1), mul)
    q = scaled_up // div
    r = scaled_up % div
    # HALF_UP without u64 overflow: r*2 >= div  <=>  r >= div - r  (r < div)
    q = q + jnp.where((shift < 0) & (r >= div - r), _U64(1), _U64(0))
    q = jnp.where((shift > 19) & (p["digits"] > _U64(0)), umax, q)  # overflow

    q = jnp.where(shift < -19, _U64(0), q)  # rounds to zero well below scale

    store_max = _U64(2**31 - 1) if dtype.id == TypeId.DECIMAL32 else _U64(2**63 - 1)
    store_min_mag = store_max + _U64(1)
    limit = jnp.where(p["neg"], store_min_mag, store_max)
    ok = p["syntax_ok"] & ~mul_ovf & ~p["overflow"] & (q <= limit)
    mag = jnp.minimum(q, limit)
    signed = jnp.where(p["neg"], (~mag + _U64(1)).astype(jnp.int64),
                       mag.astype(jnp.int64))
    valid = _null_out(col, ok)
    if ansi and bool((~ok & col.valid_mask()).any()):
        raise ValueError(f"invalid input for CAST to {dtype!r} in ANSI mode")
    return Column(dtype, data=signed.astype(dtype.jnp_dtype), validity=valid)


_TRUE_LITS = (b"t", b"true", b"y", b"yes", b"1")
_FALSE_LITS = (b"f", b"false", b"n", b"no", b"0")


@traced("cast.to_bool")
def cast_to_bool(col: Column, ansi: bool = False) -> Column:
    """string -> boolean with Spark's accepted literal sets."""
    mat, lengths = to_padded_bytes(col)
    start, end = _trim_bounds(mat, lengths)
    is_true = functools.reduce(
        jnp.bitwise_or, (_keyword_match(mat, start, end, lit) for lit in _TRUE_LITS))
    is_false = functools.reduce(
        jnp.bitwise_or, (_keyword_match(mat, start, end, lit) for lit in _FALSE_LITS))
    ok = is_true | is_false
    valid = _null_out(col, ok)
    if ansi and bool((~ok & col.valid_mask()).any()):
        raise ValueError("invalid input for CAST to BOOLEAN in ANSI mode")
    return Column(BOOL8, data=is_true.astype(jnp.uint8), validity=valid)


@functools.partial(jax.jit, static_argnums=1)
def _int_to_digit_matrix(vals: jnp.ndarray, width: int):
    """(u8[n, width] char matrix, lengths) rendering of int64 values."""
    neg = vals < 0
    u = vals.astype(jnp.uint64)  # wraps mod 2^64
    mag = jnp.where(neg, _U64(0) - u, u)  # correct incl. INT64_MIN
    # digits most-significant-first over a static 20-slot window
    ndig = jnp.ones(vals.shape, _I32)
    for k in range(1, 20):
        ndig = jnp.where(mag >= jnp.take(_POW10_U64, k), k + 1, ndig)
    total = ndig + neg.astype(_I32)
    out = jnp.zeros(vals.shape + (width,), jnp.uint8)
    for i in range(min(width, 21)):
        # position i holds digit index (total-1-i) counting from least significant
        di = total - 1 - i
        p10 = jnp.take(_POW10_U64, jnp.clip(di, 0, 19).astype(_I32))
        digit = (mag // p10) % _U64(10)
        ch = jnp.where((i == 0) & neg, jnp.uint8(ord('-')),
                       digit.astype(jnp.uint8) + jnp.uint8(ord('0')))
        out = out.at[:, i].set(jnp.where(i < total, ch, jnp.uint8(0)))
    return out, total


@traced("cast.from_integer")
def cast_from_integer(col: Column) -> Column:
    """byte/short/int/long/decimal-unscaled -> string (Spark CAST)."""
    if not col.dtype.is_integral and not col.dtype.is_decimal \
            and col.dtype.id != TypeId.BOOL8:
        raise TypeError(f"expected integral column, got {col.dtype!r}")
    if col.dtype.id == TypeId.BOOL8:
        # device select between the two literal byte rows (was a host loop)
        tmat = jnp.asarray(np.frombuffer(b"true\0", dtype=np.uint8))
        fmat = jnp.asarray(np.frombuffer(b"false", dtype=np.uint8))
        truth = jnp.asarray(col.data) != 0
        mat = jnp.where(truth[:, None], tmat[None, :], fmat[None, :])
        lengths = jnp.where(truth, 4, 5).astype(jnp.int32)
        return from_padded_bytes(mat, lengths, col.validity)
    vals = jnp.asarray(col.data).astype(jnp.int64)
    mat, lengths = _int_to_digit_matrix(vals, 21)
    return from_padded_bytes(mat, lengths, col.validity)
