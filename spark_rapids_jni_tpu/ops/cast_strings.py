"""CastStrings: Spark-semantics string <-> numeric/decimal/bool casts.

TPU-native rebuild of the reference's CastStrings component (named in
BASELINE.json's north-star op set; CUDA side appears post-snapshot as
src/main/cpp/src/cast_string.cu).  Behavior follows Spark's CAST:

- string -> int/long/short/byte: trim, optional sign, digits, optionally a
  fraction that is validated but truncated (Spark's UTF8String.toLong accepts
  "123.456" -> 123); anything else, or overflow, yields null (or raises when
  ``ansi=True``, matching Spark ANSI mode).
- string -> float/double: optional sign, digits with fraction and exponent,
  case-insensitive "inf"/"infinity"/"nan" keywords, optional trailing d/f
  suffix (Java parseDouble semantics).  Values may differ from the JVM by
  ~1 ulp on >17-digit inputs — same caveat the cudf implementation documents.
- string -> decimal(scale): exact integer parsing with HALF_UP rounding to the
  target scale (cudf convention: negative scale = fractional digits), null on
  overflow of the storage type.
- int/bool -> string; string -> bool with Spark's accepted literal sets.

Everything runs as one `lax.scan` state machine over the padded byte matrix —
a data-parallel reformulation of the per-thread character loops a CUDA
implementation uses; every row advances through the same per-character step on
the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import DType, TypeId, BOOL8, STRING
from .strings_common import to_padded_bytes, from_padded_bytes
from ..utils.tracing import traced

_U64 = jnp.uint64
_I32 = jnp.int32

# u64 mantissa capacity: accumulating another digit is safe below this
_ACC_CAP = _U64((2**64 - 1 - 9) // 10)

_POW10_U64 = jnp.asarray([10**k for k in range(20)], jnp.uint64)
# f64 powers of ten, exact-to-double-rounding, index k -> 10^(k-350).
# The _NP tables are the source of truth and NEVER touch a device: on
# TPU, pushing f64 constants through the emulated backend and pulling
# them back CORRUPTS them (low bits + flushed subnormals).
_POW10_F64_NP = np.array([float(f"1e{k}") for k in range(-350, 351)])
_POW10_F64 = jnp.asarray(_POW10_F64_NP, jnp.float64)


def _pow10_err_table():
    """Exact residual (10^k - float(10^k)) per table entry, as float64 —
    the correction term that lets cast_from_float evaluate decimal-vs-
    binary deltas in double-double precision."""
    from fractions import Fraction
    errs = []
    for k in range(-350, 351):
        t = float(f"1e{k}")
        if t == 0.0 or np.isinf(t):
            errs.append(0.0)
            continue
        errs.append(float(Fraction(10) ** k - Fraction(t)))
    return np.array(errs)


_POW10_F64_ERR_NP = np.asarray(_pow10_err_table())
_POW10_F64_ERR = jnp.asarray(_POW10_F64_ERR_NP, jnp.float64)

# exact f64 powers of two, index e -> 2^(e-1100) (0 below the subnormal
# floor, inf above the exponent cap); jnp.ldexp is NOT usable on TPU (it
# lowers through a 64-bit bitcast, which the backend lacks)
_POW2_F64_NP = np.array(
    [0.0 if e < -1074 else (np.inf if e > 1023 else float(2.0 ** e))
     for e in range(-1100, 1101)])
_POW2_F64 = jnp.asarray(_POW2_F64_NP, jnp.float64)


def _pow2(e):
    return jnp.take(_POW2_F64, jnp.clip(e + 1100, 0, 2200))


@functools.lru_cache(maxsize=1)
def _f64_exact() -> bool:
    """Does the default backend's float64 arithmetic round correctly?

    TPU emulates f64 in software and its multiply is NOT correctly
    rounded, which would silently break the exact half-ulp reasoning in
    the shortest-digits search; when this probe fails, the search runs in
    host numpy instead (these formatting casts materialize Arrow strings
    at the host boundary anyway)."""
    rng = np.random.default_rng(0)
    xs = rng.standard_normal(128)
    ys = rng.standard_normal(128) * np.power(
        10.0, rng.integers(-18, 18, 128).astype(np.float64))
    try:
        mul = np.asarray(jnp.asarray(xs) * jnp.asarray(ys))
        add = np.asarray(jnp.asarray(xs) + jnp.asarray(ys))
    except Exception:
        return False
    return bool((mul == xs * ys).all() and (add == xs + ys).all())


def _trim_bounds(mat, lengths):
    """Spark trims leading/trailing ASCII control+space (UTF8String.trim)."""
    n, w = mat.shape
    pos = jnp.arange(w, dtype=_I32)[None, :]
    in_str = pos < lengths[:, None]
    is_ws = (mat <= 32) | ~in_str
    non_ws = ~is_ws
    any_non = non_ws.any(axis=1)
    start = jnp.argmax(non_ws, axis=1).astype(_I32)
    end = (w - jnp.argmax(non_ws[:, ::-1], axis=1)).astype(_I32)
    start = jnp.where(any_non, start, 0)
    end = jnp.where(any_non, end, 0)
    return start, end


# parser states
_S_START, _S_INT, _S_FRAC, _S_EXP0, _S_EXP, _S_BAD = range(6)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _parse_number(mat, lengths, allow_frac: bool, allow_exp: bool,
                  accumulate_frac: bool, allow_suffix: bool = False):
    """Data-parallel numeric-literal state machine.

    Returns per-row arrays: neg, digits (u64 mantissa, int [+frac] digits),
    frac_kept, dropped_int, exp (signed), has_digits, syntax_ok, overflow.
    """
    n, w = mat.shape
    start, end = _trim_bounds(mat, lengths)

    if allow_suffix:
        # Java parseDouble accepts a trailing d/D/f/F suffix after the number
        last = jnp.take_along_axis(
            mat, jnp.clip(end - 1, 0, w - 1)[:, None], axis=1)[:, 0]
        has_suffix = ((last == ord('d')) | (last == ord('D'))
                      | (last == ord('f')) | (last == ord('F'))) & (end - start > 1)
        end = jnp.where(has_suffix, end - 1, end)

    zeros_i = jnp.zeros((n,), _I32)
    carry = dict(
        state=jnp.full((n,), _S_START, _I32),
        neg=jnp.zeros((n,), jnp.bool_),
        digits=jnp.zeros((n,), _U64),
        ndigits=zeros_i, frac_kept=zeros_i, dropped_int=zeros_i,
        exp=zeros_i, exp_digits=zeros_i, exp_neg=jnp.zeros((n,), jnp.bool_),
    )

    def step(c, xs):
        ch, p = xs
        active = (p >= start) & (p < end)
        st = c["state"]
        d = ch.astype(_I32) - ord('0')
        is_digit = (d >= 0) & (d <= 9)
        is_sign = (ch == ord('+')) | (ch == ord('-'))
        is_dot = ch == ord('.')
        is_e = (ch == ord('e')) | (ch == ord('E'))
        at_start = p == start

        # mantissa accumulation (int digits always; frac digits optionally)
        acc_int = active & is_digit & ((st == _S_START) | (st == _S_INT))
        acc_frac = active & is_digit & (st == _S_FRAC) & accumulate_frac
        acc = acc_int | acc_frac
        can = c["digits"] <= _ACC_CAP
        new_digits = jnp.where(
            acc & can, c["digits"] * _U64(10) + d.astype(_U64), c["digits"])
        # dropped int digits shift the magnitude; dropped frac digits only
        # lose precision
        dropped_int = c["dropped_int"] + jnp.where(acc_int & ~can, 1, 0)
        frac_kept = c["frac_kept"] + jnp.where(acc_frac & can, 1, 0)
        ndigits = c["ndigits"] + jnp.where(
            active & is_digit & (st != _S_EXP0) & (st != _S_EXP), 1, 0)

        # exponent accumulation (cap well past any meaningful range)
        acc_exp = active & is_digit & ((st == _S_EXP0) | (st == _S_EXP))
        new_exp = jnp.where(acc_exp, jnp.minimum(c["exp"] * 10 + d, 99999),
                            c["exp"])
        exp_digits = c["exp_digits"] + jnp.where(acc_exp, 1, 0)

        neg = jnp.where(active & at_start & (ch == ord('-')), True, c["neg"])
        exp_neg = jnp.where(active & (st == _S_EXP0) & (ch == ord('-')),
                            True, c["exp_neg"])

        # state transitions
        nxt = jnp.where(is_digit, jnp.where(
            (st == _S_START) | (st == _S_INT), _S_INT, jnp.where(
                st == _S_FRAC, _S_FRAC, jnp.where(
                    (st == _S_EXP0) | (st == _S_EXP), _S_EXP, _S_BAD))),
            _S_BAD)
        nxt = jnp.where(is_sign & at_start & (st == _S_START), _S_START, nxt)
        nxt = jnp.where(is_sign & (st == _S_EXP0) & ~at_start, _S_EXP, nxt)
        if allow_frac:
            nxt = jnp.where(
                is_dot & ((st == _S_START) | (st == _S_INT)), _S_FRAC, nxt)
        if allow_exp:
            nxt = jnp.where(
                is_e & ((st == _S_INT) | (st == _S_FRAC)) & (c["ndigits"] > 0),
                _S_EXP0, nxt)
        nxt = jnp.where(st == _S_BAD, _S_BAD, nxt)
        state = jnp.where(active, nxt, st)

        return dict(state=state, neg=neg, digits=new_digits, ndigits=ndigits,
                    frac_kept=frac_kept, dropped_int=dropped_int, exp=new_exp,
                    exp_digits=exp_digits, exp_neg=exp_neg), None

    pos = jnp.arange(w, dtype=_I32)
    carry, _ = jax.lax.scan(step, carry, (mat.T, pos))

    st = carry["state"]
    syntax_ok = ((st == _S_INT) | (st == _S_FRAC) | (st == _S_EXP)) \
        & (carry["ndigits"] > 0) & (end > start)
    # "1e+" / "1e-" reach _S_EXP via the sign without any exponent digit
    syntax_ok = syntax_ok & ~((st == _S_EXP) & (carry["exp_digits"] == 0))
    exp = jnp.where(carry["exp_neg"], -carry["exp"], carry["exp"])
    return dict(neg=carry["neg"], digits=carry["digits"],
                frac_kept=carry["frac_kept"], dropped_int=carry["dropped_int"],
                exp=exp, ndigits=carry["ndigits"], syntax_ok=syntax_ok,
                overflow=carry["dropped_int"] > 0)


_INT_BOUNDS = {
    TypeId.INT8: 2**7, TypeId.INT16: 2**15, TypeId.INT32: 2**31,
    TypeId.INT64: 2**63,
}


def _null_out(col: Column, ok):
    return ok if col.validity is None else (ok & col.validity)


@traced("cast.to_integer")
def cast_to_integer(col: Column, dtype: DType, ansi: bool = False) -> Column:
    """string -> byte/short/int/long with Spark CAST semantics."""
    if dtype.id not in _INT_BOUNDS:
        raise TypeError(f"not an integer target: {dtype!r}")
    mat, lengths = to_padded_bytes(col)
    p = _parse_number(mat, lengths, True, False, False)
    bound = _INT_BOUNDS[dtype.id]
    limit = jnp.where(p["neg"], _U64(bound), _U64(bound - 1))
    ok = p["syntax_ok"] & ~p["overflow"] & (p["digits"] <= limit)
    mag = jnp.minimum(p["digits"], limit)  # clamp so the cast below is defined
    signed = jnp.where(p["neg"],
                       (~mag + _U64(1)).astype(jnp.int64),
                       mag.astype(jnp.int64))
    valid = _null_out(col, ok)
    if ansi:
        bad = bool((~ok & (col.valid_mask())).any())
        if bad:
            raise ValueError(f"invalid input for CAST to {dtype!r} in ANSI mode")
    return Column(dtype, data=signed.astype(dtype.jnp_dtype), validity=valid)


def _keyword_match(mat, start, end, word: bytes):
    """Case-insensitive match of the trimmed region against a keyword."""
    n, w = mat.shape
    length = end - start
    m = length == len(word)
    for i, ch in enumerate(word):
        pos = jnp.clip(start + i, 0, w - 1)
        c = jnp.take_along_axis(mat, pos[:, None], axis=1)[:, 0]
        lower = jnp.where((c >= 65) & (c <= 90), c + 32, c)
        m = m & (lower == ch)
    return m


@traced("cast.to_float")
def cast_to_float(col: Column, dtype: DType, ansi: bool = False) -> Column:
    """string -> float/double with Spark CAST semantics."""
    if dtype.id not in (TypeId.FLOAT32, TypeId.FLOAT64):
        raise TypeError(f"not a float target: {dtype!r}")
    mat, lengths = to_padded_bytes(col)
    start, end = _trim_bounds(mat, lengths)
    p = _parse_number(mat, lengths, True, True, True, True)

    # value = digits * 10^(exp + dropped_int - frac_kept)
    eff = p["exp"] + p["dropped_int"] - p["frac_kept"]
    eff = jnp.clip(eff, -350, 350)
    scale = jnp.take(_POW10_F64, (eff + 350).astype(_I32))
    mag = p["digits"].astype(jnp.float64) * scale
    val = jnp.where(p["neg"], -mag, mag)

    # keywords (after optional sign)
    first = jnp.take_along_axis(
        mat, jnp.clip(start, 0, mat.shape[1] - 1)[:, None], axis=1)[:, 0]
    has_sign = (first == ord('+')) | (first == ord('-'))
    kw_start = jnp.where(has_sign, start + 1, start)
    kw_neg = first == ord('-')
    is_inf = (_keyword_match(mat, kw_start, end, b"inf")
              | _keyword_match(mat, kw_start, end, b"infinity"))
    is_nan = _keyword_match(mat, kw_start, end, b"nan")  # sign allowed, ignored
    val = jnp.where(is_inf, jnp.where(kw_neg, -jnp.inf, jnp.inf), val)
    val = jnp.where(is_nan, jnp.nan, val)

    ok = p["syntax_ok"] | is_inf | is_nan
    valid = _null_out(col, ok)
    if ansi and bool((~ok & col.valid_mask()).any()):
        raise ValueError(f"invalid input for CAST to {dtype!r} in ANSI mode")
    if dtype.id == TypeId.FLOAT32:
        return Column(dtype, data=val.astype(jnp.float32), validity=valid)
    return Column.fixed(dtype, val, validity=valid)  # FLOAT64 stores bits


@traced("cast.to_decimal")
def cast_to_decimal(col: Column, dtype: DType, ansi: bool = False) -> Column:
    """string -> decimal32/64 at the target scale, HALF_UP rounding.

    cudf scale convention (dtypes.py): stored integer = value * 10^(-scale).
    """
    if not dtype.is_decimal:
        raise TypeError(f"not a decimal target: {dtype!r}")
    mat, lengths = to_padded_bytes(col)
    p = _parse_number(mat, lengths, True, True, True)

    # unscaled = digits * 10^shift, shift = -scale - frac_kept + exp + dropped
    shift = (-dtype.scale) - p["frac_kept"] + p["exp"] + p["dropped_int"]
    up = jnp.clip(shift, 0, 19)
    down = jnp.clip(-shift, 0, 19)
    mul = jnp.take(_POW10_U64, up.astype(_I32))
    div = jnp.take(_POW10_U64, down.astype(_I32))
    # overflow if digits * mul wraps: digits > max/mul
    umax = _U64(2**64 - 1)
    mul_ovf = (shift > 0) & (p["digits"] > umax // mul)
    scaled_up = p["digits"] * jnp.where(mul_ovf, _U64(1), mul)
    q = scaled_up // div
    r = scaled_up % div
    # HALF_UP without u64 overflow: r*2 >= div  <=>  r >= div - r  (r < div)
    q = q + jnp.where((shift < 0) & (r >= div - r), _U64(1), _U64(0))
    q = jnp.where((shift > 19) & (p["digits"] > _U64(0)), umax, q)  # overflow

    q = jnp.where(shift < -19, _U64(0), q)  # rounds to zero well below scale

    store_max = _U64(2**31 - 1) if dtype.id == TypeId.DECIMAL32 else _U64(2**63 - 1)
    store_min_mag = store_max + _U64(1)
    limit = jnp.where(p["neg"], store_min_mag, store_max)
    ok = p["syntax_ok"] & ~mul_ovf & ~p["overflow"] & (q <= limit)
    mag = jnp.minimum(q, limit)
    signed = jnp.where(p["neg"], (~mag + _U64(1)).astype(jnp.int64),
                       mag.astype(jnp.int64))
    valid = _null_out(col, ok)
    if ansi and bool((~ok & col.valid_mask()).any()):
        raise ValueError(f"invalid input for CAST to {dtype!r} in ANSI mode")
    return Column(dtype, data=signed.astype(dtype.jnp_dtype), validity=valid)


_TRUE_LITS = (b"t", b"true", b"y", b"yes", b"1")
_FALSE_LITS = (b"f", b"false", b"n", b"no", b"0")


@traced("cast.to_bool")
def cast_to_bool(col: Column, ansi: bool = False) -> Column:
    """string -> boolean with Spark's accepted literal sets."""
    mat, lengths = to_padded_bytes(col)
    start, end = _trim_bounds(mat, lengths)
    is_true = functools.reduce(
        jnp.bitwise_or, (_keyword_match(mat, start, end, lit) for lit in _TRUE_LITS))
    is_false = functools.reduce(
        jnp.bitwise_or, (_keyword_match(mat, start, end, lit) for lit in _FALSE_LITS))
    ok = is_true | is_false
    valid = _null_out(col, ok)
    if ansi and bool((~ok & col.valid_mask()).any()):
        raise ValueError("invalid input for CAST to BOOLEAN in ANSI mode")
    return Column(BOOL8, data=is_true.astype(jnp.uint8), validity=valid)


@functools.partial(jax.jit, static_argnums=1)
def _int_to_digit_matrix(vals: jnp.ndarray, width: int):
    """(u8[n, width] char matrix, lengths) rendering of int64 values."""
    neg = vals < 0
    u = vals.astype(jnp.uint64)  # wraps mod 2^64
    mag = jnp.where(neg, _U64(0) - u, u)  # correct incl. INT64_MIN
    # digits most-significant-first over a static 20-slot window
    ndig = jnp.ones(vals.shape, _I32)
    for k in range(1, 20):
        ndig = jnp.where(mag >= jnp.take(_POW10_U64, k), k + 1, ndig)
    total = ndig + neg.astype(_I32)
    out = jnp.zeros(vals.shape + (width,), jnp.uint8)
    for i in range(min(width, 21)):
        # position i holds digit index (total-1-i) counting from least significant
        di = total - 1 - i
        p10 = jnp.take(_POW10_U64, jnp.clip(di, 0, 19).astype(_I32))
        digit = (mag // p10) % _U64(10)
        ch = jnp.where((i == 0) & neg, jnp.uint8(ord('-')),
                       digit.astype(jnp.uint8) + jnp.uint8(ord('0')))
        out = out.at[:, i].set(jnp.where(i < total, ch, jnp.uint8(0)))
    return out, total


@traced("cast.from_integer")
def cast_from_integer(col: Column) -> Column:
    """byte/short/int/long/decimal-unscaled -> string (Spark CAST)."""
    if not col.dtype.is_integral and not col.dtype.is_decimal \
            and col.dtype.id != TypeId.BOOL8:
        raise TypeError(f"expected integral column, got {col.dtype!r}")
    if col.dtype.id == TypeId.BOOL8:
        # device select between the two literal byte rows (was a host loop)
        tmat = jnp.asarray(np.frombuffer(b"true\0", dtype=np.uint8))
        fmat = jnp.asarray(np.frombuffer(b"false", dtype=np.uint8))
        truth = jnp.asarray(col.data) != 0
        mat = jnp.where(truth[:, None], tmat[None, :], fmat[None, :])
        lengths = jnp.where(truth, 4, 5).astype(jnp.int32)
        return from_padded_bytes(mat, lengths, col.validity)
    vals = jnp.asarray(col.data).astype(jnp.int64)
    mat, lengths = _int_to_digit_matrix(vals, 21)
    return from_padded_bytes(mat, lengths, col.validity)


# ---------------------------------------------------------------------------
# device formatting casts (X -> STRING), VERDICT r4 missing #6
# ---------------------------------------------------------------------------

def _render_signed(body_char_at, body_len, neg, width: int):
    """Assemble a char matrix from a per-position body renderer plus a sign.

    ``body_char_at(i)`` gives the unsigned body's char at position i (from
    the left); negatives shift the body right one slot for '-'.  Static
    loop over ``width`` positions — pure elementwise device code.
    """
    n = body_len.shape[0]
    out = jnp.zeros((n, width), jnp.uint8)
    for i in range(width):
        ch = jnp.where(i < body_len, body_char_at(i), jnp.uint8(0))
        out = out.at[:, i].set(ch)
    shifted = jnp.concatenate(
        [jnp.full((n, 1), np.uint8(ord("-"))), out[:, :-1]], axis=1)
    mat = jnp.where(neg[:, None], shifted, out)
    return mat, body_len + neg.astype(_I32)


def _decimal_body(digit_at, ndig, frac: int):
    """Body renderer for a decimal magnitude: ``digit_at(j)`` is the digit
    at index j counting from LEAST significant; ``frac`` (static) fraction
    digits render as ``0.00x``-style zero-padded tails."""
    show = jnp.maximum(ndig, frac + 1)
    dot = 1 if frac > 0 else 0
    int_digits = show - frac

    def char_at(i):
        j_int = show - 1 - i                       # before the dot
        j_frac = show - 1 - (i - dot)              # after the dot
        j = jnp.where(i < int_digits, j_int, j_frac)
        d = digit_at(jnp.clip(j, 0, None))
        ch = d.astype(jnp.uint8) + jnp.uint8(ord("0"))
        if dot:
            ch = jnp.where(i == int_digits, jnp.uint8(ord(".")), ch)
        return ch

    return char_at, show + dot


def _mag_digits64(mag_u64):
    """(digit_at, ndig) for uint64 magnitudes."""
    ndig = jnp.ones(mag_u64.shape, _I32)
    for k in range(1, 20):
        ndig = jnp.where(mag_u64 >= jnp.take(_POW10_U64, k), k + 1, ndig)

    def digit_at(j):
        p10 = jnp.take(_POW10_U64, jnp.clip(j, 0, 19).astype(_I32))
        d = ((mag_u64 // p10) % _U64(10)).astype(jnp.uint8)
        return jnp.where(j > 19, jnp.uint8(0), d)  # beyond u64's 20 digits

    return digit_at, ndig


_CHUNK = 10**9  # 128-bit magnitudes decompose into five 9-digit chunks


def _u128_chunks(lo_u64, hi_u64):
    """uint128 (lo, hi) -> five base-1e9 chunks, most significant first
    (utils.int128.divmod_small owns the limb long division)."""
    from ..utils.int128 import divmod_small
    chunks = []
    for _ in range(5):
        lo_u64, hi_u64, r = divmod_small(lo_u64, hi_u64, _CHUNK)
        chunks.append(r)  # least significant chunk this round
    return chunks[::-1]  # most significant first


def _mag_digits128(lo_u64, hi_u64):
    """(digit_at, ndig) for uint128 magnitudes (max 39 digits)."""
    chunks = _u128_chunks(lo_u64, hi_u64)  # [c0..c4], c0 most significant
    # first nonzero chunk wins: scan most-significant-first, keep the first
    found = jnp.zeros(lo_u64.shape, jnp.bool_)
    ndig = jnp.ones(lo_u64.shape, _I32)
    for k, c in enumerate(chunks):
        cd = jnp.ones(lo_u64.shape, _I32)
        for t in range(1, 10):
            cd = jnp.where(c >= jnp.take(_POW10_U64, t), t + 1, cd)
        hit = (c > 0) & (~found)
        ndig = jnp.where(hit, (4 - k) * 9 + cd, ndig)
        found = found | (c > 0)

    def digit_at(j):
        # j//9 selects the chunk from the least-significant end; j is a
        # TRACED array here, so gather the stacked chunks
        stack = jnp.stack(chunks[::-1], axis=0)  # [c4..c0] least-sig first
        ci = jnp.clip(j // 9, 0, 4).astype(_I32)
        c = jnp.take_along_axis(stack, ci[None, :], axis=0)[0]
        p10 = jnp.take(_POW10_U64, (j % 9).astype(_I32))
        d = ((c // p10) % _U64(10)).astype(jnp.uint8)
        return jnp.where(j >= 45, jnp.uint8(0), d)

    return digit_at, ndig


def _decimal128_parts(col: Column):
    """(lo_u64, hi_u64 magnitude limbs, neg) from int64[n, 2] limb pairs
    (utils.int128.split_sign owns the negate-with-carry)."""
    from ..utils.int128 import split_sign
    return split_sign(col.data[:, 0], col.data[:, 1])


@traced("cast.from_decimal")
def cast_from_decimal(col: Column) -> Column:
    """DECIMAL32/64/128 -> STRING with Spark formatting: the unscaled value
    at the type's scale, zero-padded fractions (``0.005``), trailing zeros
    kept (scale is part of the type)."""
    if not col.dtype.is_decimal:
        raise TypeError(f"expected decimal column, got {col.dtype!r}")
    scale = col.dtype.scale
    frac = max(-scale, 0)
    if col.dtype.id == TypeId.DECIMAL128:
        lo, hi, neg = _decimal128_parts(col)
        digit_at, ndig = _mag_digits128(lo, hi)
        is_zero = (lo | hi) == 0
        max_digits = 39
    else:
        vals = col.data.astype(jnp.int64)
        neg = vals < 0
        u = vals.astype(jnp.uint64)
        mag = jnp.where(neg, _U64(0) - u, u)
        digit_at, ndig = _mag_digits64(mag)
        is_zero = mag == 0
        max_digits = 19
    if scale > 0:  # value = unscaled * 10^scale: trailing zeros
        base_digit_at = digit_at

        def digit_at(j):  # noqa: F811 — shifted view of the same digits
            return jnp.where(j < scale, jnp.uint8(0),
                             base_digit_at(jnp.maximum(j - scale, 0)))

        # zero stays "0": trailing type-scale zeros apply to values only
        ndig = jnp.where(is_zero, 1, ndig + scale)
    char_at, body_len = _decimal_body(digit_at, ndig, frac)
    width = max_digits + max(scale, 0) + frac + 3
    mat, lengths = _render_signed(char_at, body_len, neg, width)
    return from_padded_bytes(mat, lengths, col.validity)


_NAN_LIT = np.frombuffer(b"NaN", np.uint8)
_INF_LIT = np.frombuffer(b"Infinity", np.uint8)


def _shortest_digits(col: Column):
    """Shortest round-tripping decimal digits of a float column.

    Returns (m, p, e10, neg, nanm, infm, zerom): per row the mantissa
    digits as int64 (p digits), the decimal exponent (value ~
    m * 10^(e10-p+1)), the sign, and the special masks.  The backbone of
    BOTH the Java-style string rendering (cast_from_float) and Spark's
    float -> decimal casts (BigDecimal.valueOf goes through the shortest
    STRING, so the decimal must be built from these digits, not from the
    exact binary expansion).

    Backend dispatch: the search's half-ulp reasoning requires CORRECTLY
    ROUNDED float64 +/-/* (Veltkamp two-products).  Where the backend has
    it (CPU), the search runs on device; where f64 is sloppy software
    emulation (TPU — see ``_f64_exact``), it runs in host numpy, which is
    where these formatting casts materialize their Arrow strings anyway.
    """
    if col.dtype.id not in (TypeId.FLOAT32, TypeId.FLOAT64):
        raise TypeError(f"expected float column, got {col.dtype!r}")
    is32 = col.dtype.id == TypeId.FLOAT32
    if _f64_exact():
        v = col.float_values().astype(jnp.float64)
        if is32:
            bits = jax.lax.bitcast_convert_type(
                jnp.asarray(col.data, jnp.float32), jnp.int32)
        else:
            bits = jnp.asarray(col.data)  # FLOAT64 stores bit patterns
        return _shortest_digits_xp(jnp, v, bits, is32)
    if is32:
        host = np.asarray(col.data).astype(np.float32)
        return _shortest_digits_xp(np, host.astype(np.float64),
                                   host.view(np.int32), is32)
    bits_np = np.asarray(col.data)
    return _shortest_digits_xp(np, bits_np.view(np.float64), bits_np, is32)


def _shortest_digits_xp(xp, v, bits, is32: bool):
    """The search itself, over ``xp`` in {jnp, np} (identical APIs for
    everything used here; bit manipulation arrives pre-bitcast)."""
    maxp = 9 if is32 else 17
    n = v.shape[0]
    a = xp.abs(v)
    nanm = xp.isnan(v)
    infm = xp.isinf(v)
    zerom = a == 0.0
    neg = (bits < 0) & (~nanm)  # sign bit is the MSB of the bit pattern
    safe_a = xp.where(nanm | infm | zerom, 1.0, a)

    # powers of ten/two come from strtod-exact host tables, never
    # xp.power (not correctly rounded even on CPU for some libms)
    def t10(e):
        return xp.take(_POW10_F64_NP, xp.clip(e + 350, 0, 700))

    def t10err(e):
        return xp.take(_POW10_F64_ERR_NP, xp.clip(e + 350, 0, 700))

    # decimal exponent estimate + guarded corrections (log10 is inexact
    # at boundaries; table entries underflow to 0 below 1e-323, so a zero
    # power must never drive a correction)
    e10 = xp.floor(xp.log10(safe_a)).astype(xp.int32)
    for _ in range(2):
        pe = t10(e10)
        e10 = xp.where((pe > 0) & (safe_a < pe), e10 - 1, e10)
    for _ in range(2):
        pe = t10(e10 + 1)
        e10 = xp.where((pe > 0) & (safe_a >= pe), e10 + 1, e10)
    e10 = e10.astype(xp.int32)

    def pow10_mul(x, k):
        # x * 10^k with k possibly beyond double's exponent range: split
        # into two in-range factors
        k1 = xp.clip(k, -300, 300)
        return x * t10(k1) * t10(k - k1)

    # Rigorous acceptance predicate: the decimal m*10^k parses back to
    # exactly this float iff |m*10^k - a| < ulp(a)/2.  The delta is
    # evaluated in double-double precision (Veltkamp two-product — no FMA
    # needed), with the exact residual of each table power, and the
    # half-ulp comes from the BIT PATTERN, so a float-rounded
    # reconstruction can never accept a decimal that strtod would snap to
    # a neighboring double (the flaw of a recon == a test).
    def two_prod(x, y):
        c = xp.float64((1 << 27) + 1)
        prod = x * y
        xh = x * c - (x * c - x)
        xl = x - xh
        yh = y * c - (y * c - y)
        yl = y - yh
        err = ((xh * yh - prod) + xh * yl + xl * yh) + xl * yl
        return prod, err

    def dd_delta(m, k, aa):
        # m*10^k - aa, with m < 2^57 split into exact f64 halves
        mh = xp.floor_divide(m, xp.int64(1 << 26)).astype(xp.float64) \
            * xp.float64(1 << 26)
        ml = (m & xp.int64((1 << 26) - 1)).astype(xp.float64)
        t = t10(k)
        p1, er1 = two_prod(mh, t)
        p2, er2 = two_prod(ml, t)
        return ((p1 - aa) + p2) + (er1 + er2 + (mh + ml) * t10err(k))

    if is32:
        be = ((bits >> 23) & 0xFF).astype(xp.int32)
        half_ulp = xp.take(_POW2_F64_NP, xp.clip(be - 151 + 1100, 0, 2200))
    else:
        be = ((bits >> 52) & 0x7FF).astype(xp.int32)
        half_ulp = xp.take(_POW2_F64_NP, xp.clip(be - 1076 + 1100, 0, 2200))
    margin = half_ulp * 0.99999

    best_p = xp.full((n,), maxp, xp.int32)
    best_m = xp.zeros((n,), xp.int64)
    best_e = e10
    found = xp.zeros((n,), bool)
    for p in range(1, maxp + 1):
        k = e10 - (p - 1)
        t = t10(k)
        deep = t <= 0.0  # table underflow (|value| ~< 1e-305): best-effort
        m0 = xp.round(pow10_mul(safe_a, -k)).astype(xp.int64)
        # one Newton step in mantissa units absorbs pow10_mul's rounding
        adj = xp.where(deep, 0.0,
                       xp.round(dd_delta(m0, k, safe_a) /
                                xp.where(t > 0, t, 1.0))).astype(xp.int64)
        m1 = m0 - adj
        # of the three candidates, take the acceptable one with the
        # SMALLEST delta — Java prints the decimal nearest the value when
        # several p-digit decimals round-trip
        sel_ok = xp.zeros((n,), bool)
        sel_d = xp.full((n,), np.inf, xp.float64)
        sel_m = xp.zeros((n,), xp.int64)
        sel_bump = xp.zeros((n,), bool)
        for c in (-1, 0, 1):
            mc = m1 + c
            bump = mc >= xp.int64(10 ** p)  # "9.99" rounds up to "10.0"
            mcb = xp.where(bump, mc // 10, mc)
            kc = xp.where(bump, k + 1, k)
            lo_ok = mcb >= xp.int64(10 ** (p - 1)) if p > 1 else mcb >= 1
            in_range = lo_ok & (mcb < xp.int64(10 ** p))
            dabs = xp.abs(dd_delta(mcb, kc, safe_a))
            okd = dabs < margin
            okr = pow10_mul(mcb.astype(xp.float64), kc) == safe_a
            ok = in_range & xp.where(deep, okr, okd)
            better = ok & (dabs < sel_d)
            sel_m = xp.where(better, mcb, sel_m)
            sel_bump = xp.where(better, bump, sel_bump)
            sel_d = xp.where(better, dabs, sel_d)
            sel_ok = sel_ok | ok
        hit = sel_ok & (~found)
        best_p = xp.where(hit, p, best_p)
        best_m = xp.where(hit, sel_m, best_m)
        best_e = xp.where(hit, xp.where(sel_bump, e10 + 1, e10), best_e)
        found = found | sel_ok
    # nothing accepted (half-ulp ties, deep-subnormal scales): max precision
    m17 = xp.round(pow10_mul(safe_a, -(e10 - (maxp - 1)))).astype(xp.int64)
    bump = m17 >= xp.int64(10 ** maxp)
    best_m = xp.where(found, best_m, xp.where(bump, m17 // 10, m17))
    best_e = xp.where(found, best_e, xp.where(bump, e10 + 1, e10))
    p_ = xp.where(found, best_p, maxp)
    m_, e_ = best_m, best_e
    # Java prints the shortest mantissa: strip trailing zeros
    for _ in range(maxp - 1):
        can = (m_ % 10 == 0) & (p_ > 1)
        m_ = xp.where(can, m_ // 10, m_)
        p_ = xp.where(can, p_ - 1, p_)
    return m_, p_, e_, neg, nanm, infm, zerom


@traced("cast.from_float")
def cast_from_float(col: Column) -> Column:
    """FLOAT32/64 -> STRING following Java Double/Float.toString: plain
    decimal in [1e-3, 1e7), otherwise ``d.dddE±x`` scientific; the digit
    count is the shortest that round-trips (searched 1..17 / 1..9,
    verified against the half-ulp interval in double-double arithmetic).

    Documented divergence (the reference plugin documents the same class
    of difference behind spark.rapids.sql.castFloatToString.enabled):
    half-ulp TIES and values below ~1e-305 (power-table underflow) may
    print one more digit than Java — never a wrong value; every printed
    string still parses back to the same float.  XLA flushes subnormals,
    so sub-1e-308 doubles print "0.0" (the engine computes them as 0)."""
    m_, p_, e_, neg, nanm, infm, zerom = _shortest_digits(col)
    n = m_.shape[0]

    def mdigit(j):  # mantissa digit j from least significant
        p10 = jnp.take(_POW10_U64, jnp.clip(j, 0, 19).astype(_I32))
        d = ((m_.astype(jnp.uint64) // p10) % _U64(10)).astype(jnp.uint8)
        return jnp.where((j < 0) | (j > 19), jnp.uint8(0), d)

    sci = (e_ >= 7) | (e_ < -3)
    W = 28
    zero8 = jnp.uint8(ord("0"))

    # scientific body: [d][.][frac...][E][-][exp digits]
    ae = jnp.abs(e_)
    elen = 1 + (ae >= 10).astype(_I32) + (ae >= 100).astype(_I32)
    esign = (e_ < 0).astype(_I32)
    fp_sci = jnp.maximum(p_ - 1, 1)
    len_sci = 2 + fp_sci + 1 + esign + elen

    def sci_char(i):
        ch = jnp.full((n,), zero8)
        ch = jnp.where(i == 0, mdigit(p_ - 1) + zero8, ch)
        if i == 1:
            return jnp.full((n,), np.uint8(ord(".")))
        if i >= 2:
            t = i - 2
            fr = jnp.where(p_ == 1, zero8, mdigit(p_ - 2 - t) + zero8)
            ch = jnp.where(t < fp_sci, fr, ch)
            epos = 2 + fp_sci
            ch = jnp.where(i == epos, jnp.uint8(ord("E")), ch)
            kk = i - epos - 1
            ch = jnp.where((kk == 0) & (esign == 1) & (i > epos),
                           jnp.uint8(ord("-")), ch)
            ed = kk - esign  # exponent digit position from the left
            digs = (ae.astype(jnp.int64) //
                    jnp.take(_POW10_U64, jnp.clip(
                        elen - 1 - ed, 0, 19).astype(_I32)).astype(jnp.int64)
                    ) % 10
            ch = jnp.where((i > epos) & (ed >= 0) & (ed < elen),
                           digs.astype(jnp.uint8) + zero8, ch)
        return ch

    # plain body: [int digits][.][frac digits]
    ilen = jnp.where(e_ >= 0, e_ + 1, 1)
    zlead = jnp.maximum(-e_ - 1, 0)  # zeros after "0." for e10 < 0
    fplain = jnp.where(e_ >= 0, jnp.maximum(p_ - (e_ + 1), 1), zlead + p_)
    len_plain = ilen + 1 + fplain

    def plain_char(i):
        # integer part
        jint = p_ - 1 - i
        ich = jnp.where(e_ >= 0,
                        jnp.where(jint >= 0, mdigit(jint) + zero8, zero8),
                        zero8)
        ch = ich
        # dot
        ch = jnp.where(i == ilen, jnp.uint8(ord(".")), ch)
        # fraction
        t = i - ilen - 1
        jfrac_pos = p_ - 1 - (ilen + t)           # e10 >= 0
        jfrac_neg = p_ - 1 - (t - zlead)          # e10 < 0
        fch = jnp.where(
            e_ >= 0,
            jnp.where(jfrac_pos >= 0, mdigit(jfrac_pos) + zero8, zero8),
            jnp.where(t < zlead, zero8, mdigit(jfrac_neg) + zero8))
        return jnp.where(i > ilen, fch, ch)

    body_len = jnp.where(sci, len_sci, len_plain)
    mat, lengths = _render_signed(
        lambda i: jnp.where(sci, sci_char(i), plain_char(i)),
        body_len, neg, W)

    # specials overlay: NaN / Infinity / -Infinity / 0.0 / -0.0
    nanmat = jnp.zeros((W,), jnp.uint8).at[:3].set(jnp.asarray(_NAN_LIT))
    infmat = jnp.zeros((W,), jnp.uint8).at[:8].set(jnp.asarray(_INF_LIT))
    infneg = jnp.zeros((W,), jnp.uint8).at[0].set(
        np.uint8(ord("-"))).at[1:9].set(jnp.asarray(_INF_LIT))
    zmat = jnp.zeros((W,), jnp.uint8).at[0].set(zero8).at[1].set(
        np.uint8(ord("."))).at[2].set(zero8)
    zneg = jnp.zeros((W,), jnp.uint8).at[0].set(np.uint8(ord("-"))) \
        .at[1].set(zero8).at[2].set(np.uint8(ord("."))).at[3].set(zero8)
    mat = jnp.where(nanm[:, None], nanmat[None, :], mat)
    lengths = jnp.where(nanm, 3, lengths)
    mat = jnp.where((infm & ~neg)[:, None], infmat[None, :], mat)
    lengths = jnp.where(infm & ~neg, 8, lengths)
    mat = jnp.where((infm & neg)[:, None], infneg[None, :], mat)
    lengths = jnp.where(infm & neg, 9, lengths)
    mat = jnp.where((zerom & ~neg)[:, None], zmat[None, :], mat)
    lengths = jnp.where(zerom & ~neg, 3, lengths)
    mat = jnp.where((zerom & neg)[:, None], zneg[None, :], mat)
    lengths = jnp.where(zerom & neg, 4, lengths)
    return from_padded_bytes(mat, lengths, col.validity)


@traced("cast.from_datetime")
def cast_from_datetime(col: Column) -> Column:
    """DATE/TIMESTAMP -> STRING with Spark CAST formatting:
    ``yyyy-MM-dd`` for dates, ``yyyy-MM-dd HH:mm:ss[.ffffff]`` for
    timestamps (fraction only when nonzero, trailing zeros stripped —
    Spark's TimestampFormatter.getFractionFormatter behavior)."""
    from .datetime import _days_and_secs, _civil
    if not (col.dtype.is_timestamp or col.dtype.id == TypeId.TIMESTAMP_DAYS):
        raise TypeError(f"expected date/timestamp column, got {col.dtype!r}")
    is_date = col.dtype.id == TypeId.TIMESTAMP_DAYS
    days, secs = _days_and_secs(col)
    y, mo, d = _civil(days)
    n = days.shape[0]
    zero8 = jnp.uint8(ord("0"))

    # sub-second micros (unit-dependent); _days_and_secs floors to seconds
    unit = {TypeId.TIMESTAMP_SECONDS: 1,
            TypeId.TIMESTAMP_MILLISECONDS: 10**3,
            TypeId.TIMESTAMP_MICROSECONDS: 10**6,
            TypeId.TIMESTAMP_NANOSECONDS: 10**9}.get(col.dtype.id, 1)
    if unit > 1:
        per_day = jnp.int64(86_400 * unit)
        v = col.data.astype(jnp.int64)
        tod = v - jnp.floor_divide(v, per_day) * per_day  # [0, per_day)
        sub = tod % jnp.int64(unit)
        micros = (sub * (10**6 // unit)).astype(jnp.int64) if unit <= 10**6 \
            else jnp.floor_divide(sub, unit // 10**6)
    else:
        micros = jnp.zeros((n,), jnp.int64)

    # fraction length: micros rendered to 6 digits, trailing zeros
    # stripped — 6 minus the largest power of ten dividing micros
    flen = jnp.full((n,), 6, _I32)
    for t in range(1, 7):
        flen = jnp.where(micros % jnp.int64(10 ** t) == 0, 6 - t, flen)
    flen = jnp.where(micros == 0, 0, flen)

    def two(x):  # 2-digit zero-padded pair of columns
        return ((x // 10).astype(jnp.uint8) + zero8,
                (x % 10).astype(jnp.uint8) + zero8)

    cols = []
    yy = y.astype(jnp.int64)
    neg_y = yy < 0
    ay = jnp.abs(yy)
    # years render 4-digit zero-padded (Spark/proleptic), widening up to
    # 12 digits — TIMESTAMP_SECONDS over int64 reaches 12-digit years, and
    # truncating high digits would print a silently wrong date
    _YW = 12
    ylen = jnp.full(ay.shape, 4, _I32)
    for t in range(5, _YW + 1):
        ylen = jnp.where(ay >= jnp.int64(10 ** (t - 1)), t, ylen)
    W = _YW + 6 + (0 if is_date else 16)
    out = jnp.zeros((n, W), jnp.uint8)
    # year digits right-aligned in the window, then shifted out below
    ypos0 = _YW - ylen  # start of year digits in the fixed window
    for i in range(_YW):
        j = ylen - 1 - (i - ypos0)
        p10 = jnp.take(_POW10_U64, jnp.clip(j, 0, 19).astype(_I32))
        dch = ((ay.astype(jnp.uint64) // p10) % _U64(10)).astype(
            jnp.uint8) + zero8
        out = out.at[:, i].set(jnp.where(i >= ypos0, dch, jnp.uint8(0)))
    rest = [np.uint8(ord("-")), *two(mo), np.uint8(ord("-")), *two(d)]
    if not is_date:
        hh = (secs // 3600).astype(jnp.int64)
        mi = ((secs // 60) % 60).astype(jnp.int64)
        ss = (secs % 60).astype(jnp.int64)
        rest += [np.uint8(ord(" ")), *two(hh), np.uint8(ord(":")), *two(mi),
                 np.uint8(ord(":")), *two(ss), np.uint8(ord("."))]
        for k in range(6):
            p10 = jnp.int64(10 ** (5 - k))
            rest.append(((micros // p10) % 10).astype(jnp.uint8) + zero8)
    for i, ch in enumerate(rest):
        colv = jnp.broadcast_to(jnp.asarray(ch, jnp.uint8), (n,)) \
            if np.isscalar(ch) or getattr(ch, "shape", ()) == () else ch
        out = out.at[:, _YW + i].set(colv)
    # compact the year's left padding: shift rows left by ypos0 slots
    # (ylen in 4..12 -> ypos0 in 8..0), then trim the tail: dates end
    # after "-MM-dd"; timestamps keep ".f..." only when the fraction is
    # nonzero, trailing zeros stripped
    if is_date:
        blen = ylen + 6
    else:
        blen = ylen + 15 + jnp.where(flen > 0, flen + 1, 0)
    final = out
    for shift in range(1, _YW - 3):
        shifted = jnp.concatenate(
            [out[:, shift:], jnp.zeros((n, shift), jnp.uint8)], axis=1)
        final = jnp.where((ypos0 == shift)[:, None], shifted, final)
    # negative years: prepend '-'
    mat, lengths = _render_signed(
        lambda i: final[:, i] if i < W else jnp.zeros((n,), jnp.uint8),
        blen, neg_y, W + 1)
    return from_padded_bytes(mat, lengths, col.validity)
