"""General column casts (the cudf::cast role, SURVEY.md §2.2 "algorithms").

Spark non-ANSI cast semantics over the engine's dtype system:

- integral -> integral: two's-complement narrowing (Java semantics);
- float -> integral: truncate toward zero, NaN -> 0, +/-inf and
  out-of-range saturate to the target min/max (JVM double-to-long rules);
- integral/bool -> float and float widths: value conversion;
- numeric <-> BOOL8: zero is false, nonzero is true; bool -> 0/1;
- timestamps: unit rescale (truncating toward negative infinity on
  downscale, Spark's instant semantics); DATE <-> timestamp via day
  boundaries;
- decimals: scale change by powers of ten — values that cannot be
  represented exactly at the target scale, or that overflow the target
  width, become null (Spark's non-ANSI overflow-to-null);
- STRING directions delegate to ops.cast_strings (the reference's
  CastStrings component).

FLOAT64 columns store IEEE bit patterns device-side (dtypes.device_storage);
casts go through ``float_values()``/``Column.fixed`` so the bit-pattern
convention never leaks.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..dtypes import DType, TypeId
from ..utils.tracing import traced

_TS_UNIT = {
    TypeId.TIMESTAMP_SECONDS: 10**9,
    TypeId.TIMESTAMP_MILLISECONDS: 10**6,
    TypeId.TIMESTAMP_MICROSECONDS: 10**3,
    TypeId.TIMESTAMP_NANOSECONDS: 1,
}

_INT_IDS = (TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
            TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64)


def _num_values(col: Column) -> jnp.ndarray:
    if col.dtype.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        return col.float_values()
    if col.dtype.id == TypeId.BOOL8:
        return col.data.astype(jnp.int64)
    return col.data


@traced("cast")
def cast(col: Column, to: DType, ansi: bool = False) -> Column:
    """Cast a column to ``to`` with Spark non-ANSI semantics (see module
    docstring); ``ansi=True`` is accepted for the string directions that
    support it (delegated to ops.cast_strings)."""
    f = col.dtype
    if f == to:
        return col

    # ---- string directions: the CastStrings component owns these
    if f.is_string:
        from . import cast_strings as cs
        if to.id in _INT_IDS:
            return cs.cast_to_integer(col, to, ansi=ansi)
        if to.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            return cs.cast_to_float(col, to, ansi=ansi)
        if to.is_decimal:
            return cs.cast_to_decimal(col, to, ansi=ansi)
        if to.id == TypeId.BOOL8:
            return cs.cast_to_bool(col, ansi=ansi)
        raise NotImplementedError(f"cast STRING -> {to!r}")
    if to.is_string:
        from . import cast_strings as cs
        if f.id in _INT_IDS or f.id == TypeId.BOOL8:
            return cs.cast_from_integer(col)
        if f.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            return cs.cast_from_float(col)
        if f.is_decimal:
            return cs.cast_from_decimal(col)
        if f.is_timestamp or f.id == TypeId.TIMESTAMP_DAYS:
            return cs.cast_from_datetime(col)
        raise NotImplementedError(f"cast {f!r} -> STRING")

    # ---- timestamps
    if f.is_timestamp and to.is_timestamp:
        if TypeId.TIMESTAMP_DAYS in (f.id, to.id):
            # per-unit day length, with NO nanosecond intermediate: a ns
            # intermediate wraps int64 outside ~1677..2262 while the
            # day/second/ms/us ranges themselves are fine
            if f.id == TypeId.TIMESTAMP_DAYS:
                day_units = 86_400 * (10**9 // _TS_UNIT[to.id])
                out = col.data.astype(jnp.int64) * jnp.int64(day_units)
            else:
                day_units = 86_400 * (10**9 // _TS_UNIT[f.id])
                out = jnp.floor_divide(
                    col.data.astype(jnp.int64),
                    jnp.int64(day_units)).astype(jnp.int32)
            return Column.fixed(to, out, validity=col.validity)
        uf, ut = _TS_UNIT[f.id], _TS_UNIT[to.id]
        v = col.data.astype(jnp.int64)
        out = v * (uf // ut) if uf >= ut else jnp.floor_divide(v, ut // uf)
        return Column.fixed(to, out, validity=col.validity)

    # ---- decimals: rescale with overflow/precision-loss -> null
    if f.is_decimal or to.is_decimal:
        return _cast_decimal(col, to)

    # ---- numeric / bool
    if to.id == TypeId.BOOL8:
        v = _num_values(col)
        return Column.fixed(to, (v != 0), validity=col.validity)
    if to.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        v = _num_values(col).astype(
            jnp.float32 if to.id == TypeId.FLOAT32 else jnp.float64)
        return Column.fixed(to, v, validity=col.validity)
    if to.id in _INT_IDS:
        import numpy as np
        tdt = jnp.dtype(to.storage)
        if f.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            v = col.float_values().astype(jnp.float64)
            info = jnp.iinfo(tdt)
            # JVM double->integral: NaN -> 0, truncate toward zero,
            # out-of-range saturates EXACTLY to min/max.  float(info.max)
            # rounds up to 2**(bits-?) for 64-bit targets (astype would
            # wrap), so saturate with explicit selects on
            # safely-representable bounds before the convert.
            t = jnp.where(jnp.isnan(v), 0.0, jnp.trunc(v))
            # for 64-bit targets float(info.max) rounds UP to the exact
            # power of two (2**63 signed, 2**64 unsigned): a clean edge
            edge = float(info.max)
            hi = float(np.nextafter(np.float64(edge), 0.0)) \
                if tdt.itemsize == 8 else edge
            lo = float(info.min)
            over = t >= edge if tdt.itemsize == 8 else t > edge
            under = t < lo
            safe = jnp.clip(t, lo, hi).astype(tdt)
            out = jnp.where(over, jnp.array(info.max, tdt),
                            jnp.where(under, jnp.array(info.min, tdt),
                                      safe))
            return Column.fixed(to, out, validity=col.validity)
        v = _num_values(col)
        # two's-complement narrowing (Java semantics): wrap via the
        # unsigned view of the target width
        bits = tdt.itemsize * 8
        if bits < 64:
            wrapped = v.astype(jnp.int64) & jnp.int64((1 << bits) - 1)
            if tdt.kind == "i":
                sign = jnp.int64(1 << (bits - 1))
                wrapped = (wrapped ^ sign) - sign
        else:
            wrapped = v.astype(jnp.int64)
        return Column.fixed(to, wrapped.astype(tdt), validity=col.validity)
    raise NotImplementedError(f"cast {f!r} -> {to!r}")


def _div_half_up(iv: jnp.ndarray, q) -> jnp.ndarray:
    """Integer divide rounding half away from zero (Spark HALF_UP)."""
    a = jnp.abs(iv)
    m = (a + q // 2) // q
    return jnp.where(iv >= 0, m, -m)


def _cast_decimal128(col: Column, to: DType) -> Column:
    """Casts where either side is DECIMAL128: 128-bit limb arithmetic on
    device (utils/int128 — the cudf fixed_point<__int128> role), Spark
    non-ANSI overflow-to-null semantics throughout."""
    from ..utils import int128 as i128
    f = col.dtype
    valid = col.valid_mask()
    fs = f.scale if f.is_decimal else 0
    ts = to.scale if to.is_decimal else 0

    if f.id == TypeId.DECIMAL128:
        lo, hi, neg = i128.split_sign(col.data[:, 0], col.data[:, 1])
        ok = jnp.ones(neg.shape, jnp.bool_)
    elif f.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        # Spark's float -> decimal goes through BigDecimal.valueOf, i.e.
        # the SHORTEST decimal string of the double — so the unscaled
        # value must come from the shortest digits (cast_from_float's
        # machinery), rescaled EXACTLY in 128-bit integers, not from the
        # value's full binary expansion
        from .cast_strings import _shortest_digits
        m, p, e, neg, nanm, infm, zerom = _shortest_digits(col)
        lo, hi = i128.from_u64(m.astype(jnp.uint64))
        k = e - (p - 1) - ts
        ok = ~(nanm | infm) & (k <= 41)  # 10^41 overflows 2^127
        lo, hi, ovf = i128.mul_pow10_dyn(
            lo, hi, jnp.clip(k, 0, 41), 41)
        ok = ok & (~ovf)
        lo, hi = i128.div_pow10_dyn(
            lo, hi, jnp.clip(-k, 0, 20), 20, half_up=True)
        zlo = jnp.zeros(lo.shape, jnp.uint64)
        lo = jnp.where(zerom, zlo, lo)
        hi = jnp.where(zerom, zlo, hi)
        neg = neg & (~zerom)
        fs = ts  # already at target scale
    else:
        iv = _num_values(col).astype(jnp.int64)
        neg = iv < 0
        u = iv.astype(jnp.uint64)
        mag = jnp.where(neg, jnp.uint64(0) - u, u)
        lo, hi = i128.from_u64(mag)
        ok = jnp.ones(neg.shape, jnp.bool_)

    # value-preserving targets need no limb rescale: the scale factor
    # applies in float space (float) or cancels (bool nonzero test)
    if to.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        mf = i128.to_f64(lo, hi) * (10.0 ** fs)
        vf = jnp.where(neg, -mf, mf)
        return Column.fixed(to, vf.astype(
            jnp.float32 if to.id == TypeId.FLOAT32 else jnp.float64),
            validity=col.validity)
    if to.id == TypeId.BOOL8:
        return Column.fixed(to, (lo | hi) != 0, validity=col.validity)

    diff = fs - ts
    if not to.is_decimal:
        diff = fs  # rescale all the way to integer units
    if diff > 0:
        lo, hi, ovf = i128.mul_pow10(lo, hi, diff)
        ok = ok & (~ovf)
    elif diff < 0:
        # decimal targets round HALF_UP (Spark); integral targets truncate
        lo, hi, _ = i128.div_pow10(lo, hi, -diff, half_up=to.is_decimal)

    if to.id == TypeId.DECIMAL128:
        ok = ok & i128.fits_bits(lo, hi, 127)
        slo, shi = i128.apply_sign(lo, hi, neg)
        data = jnp.stack([jnp.where(ok, slo, 0),
                          jnp.where(ok, shi, 0)], axis=1)
        return Column(to, data=data, validity=valid & ok)
    if to.is_decimal:
        bound = 2**31 - 1 if to.id == TypeId.DECIMAL32 else 2**62
        ok = ok & i128.le_u64(lo, hi, bound)
        slo, _ = i128.apply_sign(lo, hi, neg)
        out = jnp.where(ok, slo, 0).astype(jnp.dtype(to.storage))
        return Column(to, data=out, validity=valid & ok)
    # integral targets: must fit int64 after the rescale, then narrow
    ok = ok & i128.le_u64(lo, hi, 2**63)  # magnitude; 2^63 only when neg
    ok = ok & ((lo < jnp.uint64(2**63)) | neg)
    slo, _ = i128.apply_sign(lo, hi, neg)
    return cast(Column.fixed(DType(TypeId.INT64),
                             jnp.where(ok, slo, 0),
                             validity=valid & ok), to)


def _cast_decimal(col: Column, to: DType) -> Column:
    f = col.dtype
    if f.id == TypeId.DECIMAL128 or to.id == TypeId.DECIMAL128:
        return _cast_decimal128(col, to)
    fs = f.scale if f.is_decimal else 0
    ts = to.scale if to.is_decimal else 0
    valid = col.valid_mask()
    if f.is_decimal and not to.is_decimal:
        if to.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            # decimal -> float: value = mantissa * 10^fs
            v = col.data.astype(jnp.float64) * (10.0 ** fs)
            return Column.fixed(to, v.astype(
                jnp.float32 if to.id == TypeId.FLOAT32 else jnp.float64),
                validity=col.validity)
        iv = col.data.astype(jnp.int64)
        if fs >= 0:
            mul = jnp.int64(10 ** fs)
            out = iv * mul
            valid = valid & ((out // mul) == iv)  # upscale overflow -> null
        else:
            q = jnp.int64(10 ** (-fs))
            out = jnp.where(iv >= 0, iv // q, -((-iv) // q))  # trunc to 0
        return cast(Column.fixed(DType(TypeId.INT64), out,
                                 validity=valid), to)
    width_max = jnp.int64(2**31 - 1) if to.id == TypeId.DECIMAL32 \
        else jnp.int64(2**62)
    if not f.is_decimal:
        # numeric -> decimal: mantissa = value * 10^-ts (HALF_UP), null on
        # target-width overflow (Spark non-ANSI overflow-to-null)
        if f.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            v = col.float_values().astype(jnp.float64)
            scaled = v * (10.0 ** (-ts))
            # HALF_UP (away from zero), matching _div_half_up and Spark
            m = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5),
                          jnp.ceil(scaled - 0.5))
            ok = jnp.isfinite(v) & (jnp.abs(m) <= width_max.astype(
                jnp.float64))
            return Column.fixed(
                to, jnp.where(ok, m, 0.0).astype(jnp.int64).astype(
                    jnp.dtype(to.storage)),
                validity=valid & ok)
        iv = _num_values(col).astype(jnp.int64)
        if ts <= 0:
            mul = jnp.int64(10 ** (-ts))
            m = iv * mul
            ok = ((m // mul) == iv) & (jnp.abs(m) <= width_max)
            return Column.fixed(to, m.astype(jnp.dtype(to.storage)),
                                validity=valid & ok)
        q = jnp.int64(10 ** ts)
        m = _div_half_up(iv, q)  # Spark rounds HALF_UP to coarser scales
        ok = jnp.abs(m) <= width_max
        return Column.fixed(to, m.astype(jnp.dtype(to.storage)),
                            validity=valid & ok)
    # decimal -> decimal rescale
    diff = fs - ts
    iv = col.data.astype(jnp.int64)
    if diff >= 0:
        mul = jnp.int64(10 ** diff)
        m = iv * mul
        ok = (m // mul) == iv
    else:
        m = _div_half_up(iv, jnp.int64(10 ** (-diff)))
        ok = jnp.ones(m.shape, jnp.bool_)  # rounding, not exactness
    width_ok = jnp.abs(m) <= width_max
    return Column.fixed(to, m.astype(jnp.dtype(to.storage)),
                        validity=valid & ok & width_ok)
