"""Shared string-column device representation helpers.

STRING columns live in Arrow layout (uint8 char buffer + int32 offsets —
columnar/column.py).  XLA wants static shapes, so string *compute* (hashing,
casting, regex) runs over a padded byte matrix ``u8[n, width]`` produced here.
``width`` is a trace-static padding bucket (next power of two of the longest
row) so recompilation only happens when the longest string crosses a bucket
boundary, not on every batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column


def pad_width_bucket(max_len: int, minimum: int = 4) -> int:
    """Static padding bucket: next power of two >= max(max_len, minimum)."""
    w = minimum
    while w < max_len:
        w *= 2
    return w


def string_width_bucket(col) -> int:
    """The padded-bytes bucket width ``to_padded_bytes`` would pick for a
    STRING column — the ONE place that rule lives, so join paths that must
    force a common width across two sides (stringplane explosion) cannot
    drift from the matrix layout."""
    lens = np.diff(np.asarray(col.offsets))
    return pad_width_bucket(int(lens.max()) if lens.size else 0)


@functools.partial(jax.jit, static_argnums=2)
def _gather_matrix(chars: jnp.ndarray, offsets: jnp.ndarray, width: int):
    starts = offsets[:-1]
    lengths = (offsets[1:] - starts).astype(jnp.int32)
    idx = starts[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    mat = jnp.take(chars, idx, mode="clip")
    mask = jnp.arange(width, dtype=jnp.int32)[None, :] < lengths[:, None]
    return jnp.where(mask, mat, jnp.uint8(0)), lengths


def to_padded_bytes(col: Column, width: int | None = None):
    """(u8[n, width] zero-padded byte matrix, int32[n] lengths) for a STRING column."""
    if not col.dtype.is_string:
        raise TypeError(f"expected STRING column, got {col.dtype!r}")
    offsets = jnp.asarray(col.offsets, jnp.int32)
    if width is None:
        width = string_width_bucket(col)
    chars = col.data if col.data is not None and col.data.shape[0] else \
        jnp.zeros((1,), jnp.uint8)
    return _gather_matrix(jnp.asarray(chars, jnp.uint8), offsets, int(width))


def from_padded_bytes(mat: jnp.ndarray, lengths: jnp.ndarray,
                      validity=None) -> Column:
    """Rebuild an Arrow-layout STRING column from a padded byte matrix.

    Host-side compaction (np): fine at API boundaries; jit pipelines keep the
    matrix form.
    """
    mat = np.asarray(mat)
    lengths = np.asarray(lengths).astype(np.int64)
    n = mat.shape[0]
    offsets64 = np.zeros(n + 1, np.int64)
    np.cumsum(lengths, out=offsets64[1:])
    if offsets64[-1] > np.iinfo(np.int32).max:
        # cudf raises on string offset overflow; a silent int32 wrap here
        # would corrupt the Arrow offsets
        raise OverflowError(
            f"string column char buffer is {int(offsets64[-1])} bytes; "
            f"Arrow int32 offsets cap at 2^31-1")
    offsets = offsets64.astype(np.int32)
    keep = np.arange(mat.shape[1])[None, :] < lengths[:, None]
    chars = mat[keep]  # row-major boolean extraction == concatenated rows
    return Column.string(chars, offsets, validity)
