"""RowConversion: columnar Table <-> packed row-major blobs (LIST<INT8>).

TPU-native re-design of the reference op (reference
src/main/cpp/src/row_conversion.cu, Java API RowConversion.java):

- Wire format is IDENTICAL to the reference so blobs interoperate with
  UnsafeRow-style CPU consumers: C-struct natural alignment per column in
  schema order, one validity bit per column in bytes appended at the row tail,
  row padded to a 64-bit multiple (reference row_conversion.cu:432-456
  ``compute_fixed_width_layout``; layout documented in RowConversion.java:50-99).
- Output is split into batches so no batch exceeds 2^31-1 bytes, with batch row
  counts a multiple of 32 (reference row_conversion.cu:476-511 keeps int32 list
  offsets valid and validity words batch-local).
- Fixed-width types only, like the reference at this snapshot
  (row_conversion.cu:515,573 CUDF_FAIL on non-fixed-width).

The kernel design is TPU-first rather than a translation of the CUDA kernels:
where the reference stages per-block shared-memory tiles and does warp-ballot
validity packing (row_conversion.cu:75-108,158-165,255-272), we express the
whole conversion as a dense uint32 *row-word matrix* ``u32[rows, row_size/4]``
built from per-column bitcasts/shifts — XLA fuses the whole thing into one
elementwise pass over HBM, and every operation is 32-bit (the VPU lane width;
64-bit float bitcasts do not exist on TPU — see utils/floatbits.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..dtypes import DType, TypeId, INT8, UINT8

# Reference parity: per-batch byte ceiling from cudf's int32 list offsets
# (row_conversion.cu:384-386) and 32-row batch alignment (:477-479).
MAX_BATCH_BYTES = (1 << 31) - 1
BATCH_ROW_ALIGN = 32


@dataclass(frozen=True)
class RowLayout:
    """Host-side packed-row layout plan (one per schema).

    Mirrors the reference's ``compute_fixed_width_layout``
    (row_conversion.cu:432-456): natural alignment per column, validity bytes
    at the tail, 64-bit row padding.
    """

    schema: tuple[DType, ...]
    offsets: tuple[int, ...]  # byte offset of each column's value in the row
    validity_offset: int      # first validity byte
    row_size: int             # padded total bytes per row

    @property
    def num_validity_bytes(self) -> int:
        return (len(self.schema) + 7) // 8


def fixed_width_layout(schema: Sequence[DType]) -> RowLayout:
    schema = tuple(schema)
    for dt in schema:
        if not dt.is_fixed_width:
            # parity with CUDF_FAIL "only fixed-width types" (row_conversion.cu:515)
            raise TypeError(f"row conversion requires fixed-width types, got {dt!r}")
    off = 0
    offsets = []
    for dt in schema:
        size = dt.itemsize
        off = (off + size - 1) // size * size  # natural C alignment
        offsets.append(off)
        off += size
    validity_offset = off
    off += (len(schema) + 7) // 8
    row_size = (off + 7) // 8 * 8  # 64-bit row padding (row_conversion.cu:86)
    return RowLayout(schema, tuple(offsets), validity_offset, row_size)


# ---------------------------------------------------------------------------
# kernels (jitted per (layout, n) via trace caching)
# ---------------------------------------------------------------------------

def _col_to_u32_parts(dtype: DType, data: jnp.ndarray) -> list[tuple[int, jnp.ndarray]]:
    """Decompose one column into (byte_width, uint32-extended value) parts.

    8-byte types yield two parts (lo, hi); smaller types one part whose value
    occupies the low ``byte_width`` bytes of the uint32.
    """
    size = dtype.itemsize
    if size == 8:
        # FLOAT64 included: its device buffer already holds IEEE bit patterns
        # as int64 (dtypes.device_storage), so every 8-byte type is an integer
        # bitcast — exact on TPU, where 64-bit float bitcasts don't exist
        pair = jax.lax.bitcast_convert_type(data, jnp.uint32)  # (n, 2) LE
        return [(4, pair[..., 0]), (4, pair[..., 1])]
    if size == 4:
        return [(4, jax.lax.bitcast_convert_type(data, jnp.uint32))]
    if size == 2:
        u16 = jax.lax.bitcast_convert_type(data, jnp.uint16)
        return [(2, u16.astype(jnp.uint32))]
    u8 = jax.lax.bitcast_convert_type(data, jnp.uint8)
    return [(1, u8.astype(jnp.uint32))]


def _to_row_words(layout: RowLayout, datas: Sequence[jnp.ndarray],
                  masks: Sequence[Optional[jnp.ndarray]]) -> jnp.ndarray:
    """Pack columns into the row-word matrix ``u32[n, row_size // 4]``."""
    nwords = layout.row_size // 4
    n = datas[0].shape[0] if datas else 0
    # word index -> list of uint32 contributions (pre-shifted into place)
    contribs: dict[int, list[jnp.ndarray]] = {}

    def place(byte_off: int, width: int, value_u32: jnp.ndarray):
        w, b = divmod(byte_off, 4)
        assert b + width <= 4, "parts never straddle words (natural alignment)"
        v = value_u32 if b == 0 else value_u32 << jnp.uint32(8 * b)
        contribs.setdefault(w, []).append(v)

    for dt, off, data in zip(layout.schema, layout.offsets, datas):
        for i, (width, part) in enumerate(_col_to_u32_parts(dt, data)):
            place(off + 4 * i, width, part)

    # validity bytes: bit i%8 of byte i//8 set when column i's row is valid
    # (wire layout per RowConversion.java:90-97; reference packs these bits with
    # atomics/ballots — here each byte is a sum of shifted bool lanes)
    for byte_idx in range(layout.num_validity_bytes):
        byte = jnp.zeros((n,), jnp.uint32)
        for bit in range(8):
            i = byte_idx * 8 + bit
            if i >= len(layout.schema):
                break
            m = masks[i]
            lane = (jnp.ones((n,), jnp.uint32) if m is None
                    else m.astype(jnp.uint32))
            byte = byte | (lane << jnp.uint32(bit))
        place(layout.validity_offset + byte_idx, 1, byte)

    words = []
    zero = jnp.zeros((n,), jnp.uint32)
    for w in range(nwords):
        parts = contribs.get(w)
        words.append(functools.reduce(jnp.bitwise_or, parts) if parts else zero)
    return jnp.stack(words, axis=1)


def _from_row_words(layout: RowLayout, words: jnp.ndarray):
    """Unpack ``u32[n, nwords]`` into (datas, masks) per the layout."""
    datas, masks = [], []

    def word_at(byte_off: int) -> jnp.ndarray:
        return words[:, byte_off // 4]

    def subword(byte_off: int, width: int) -> jnp.ndarray:
        w, b = divmod(byte_off, 4)
        v = words[:, w]
        if b:
            v = v >> jnp.uint32(8 * b)
        if width < 4:
            v = v & jnp.uint32((1 << (8 * width)) - 1)
        return v

    for dt, off in zip(layout.schema, layout.offsets):
        size = dt.itemsize
        if size == 8:
            pair = jnp.stack([word_at(off), word_at(off + 4)], axis=-1)
            data = jax.lax.bitcast_convert_type(pair, jnp.int64)
            if dt.id != TypeId.FLOAT64:  # FLOAT64 keeps its bit-pattern buffer
                data = data.astype(dt.jnp_dtype)
        elif size == 4:
            data = jax.lax.bitcast_convert_type(word_at(off), dt.jnp_dtype)
        elif size == 2:
            u16 = subword(off, 2).astype(jnp.uint16)
            data = jax.lax.bitcast_convert_type(u16, dt.jnp_dtype)
        else:
            u8 = subword(off, 1).astype(jnp.uint8)
            data = u8 if dt.jnp_dtype == jnp.uint8 else \
                jax.lax.bitcast_convert_type(u8, dt.jnp_dtype)
        datas.append(data)

    for i in range(len(layout.schema)):
        byte = subword(layout.validity_offset + i // 8, 1)
        masks.append(((byte >> jnp.uint32(i % 8)) & jnp.uint32(1)).astype(jnp.bool_))
    return datas, masks


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_bytes(layout: RowLayout, datas, masks) -> jnp.ndarray:
    """u8[n * row_size] packed rows for one batch (jitted per layout/shape)."""
    words = _to_row_words(layout, datas, masks)
    by = jax.lax.bitcast_convert_type(words, jnp.uint8)  # (n, nwords, 4) LE
    return by.reshape(-1)


@functools.partial(jax.jit, static_argnums=0)
def _from_rows_bytes(layout: RowLayout, data_u8: jnp.ndarray):
    n = data_u8.shape[0] // layout.row_size
    grouped = data_u8.reshape(n, layout.row_size // 4, 4)
    words = jax.lax.bitcast_convert_type(grouped, jnp.uint32)
    return _from_row_words(layout, words)


# ---------------------------------------------------------------------------
# public API (mirrors RowConversion.java:101-121)
# ---------------------------------------------------------------------------

def convert_to_rows(table: Table, max_batch_bytes: int = MAX_BATCH_BYTES) -> list[Column]:
    """Columnar table -> list of LIST<INT8> row-blob columns.

    Analog of ``RowConversion.convertToRows`` (RowConversion.java:101-108).
    Returns multiple columns when the packed output would exceed
    ``max_batch_bytes`` (reference row_conversion.cu:476-511); batch row counts
    are a multiple of 32 except possibly the last.
    """
    layout = fixed_width_layout(table.dtypes())
    n = table.num_rows
    rows_per_batch = max(1, max_batch_bytes // layout.row_size)
    if rows_per_batch < n:
        if layout.row_size * BATCH_ROW_ALIGN > max_batch_bytes:
            # a 32-row-aligned batch would exceed the cap (and for the default
            # cap, overflow the int32 LIST offsets the format protects)
            raise ValueError(
                f"row size {layout.row_size} too large: a {BATCH_ROW_ALIGN}"
                f"-row aligned batch exceeds max_batch_bytes={max_batch_bytes}")
        rows_per_batch = rows_per_batch // BATCH_ROW_ALIGN * BATCH_ROW_ALIGN
    out = []
    start = 0
    while start < n or (n == 0 and not out):
        stop = min(n, start + rows_per_batch)
        datas = tuple(c.data[start:stop] for c in table.columns)
        masks = tuple(None if c.validity is None else c.validity[start:stop]
                      for c in table.columns)
        data_u8 = _to_rows_bytes(layout, datas, masks)
        nb = stop - start
        offsets = jnp.arange(nb + 1, dtype=jnp.int32) * layout.row_size
        out.append(Column.list_(Column.fixed(INT8, data_u8), offsets))
        start = stop
        if n == 0:
            break
    return out


def convert_from_rows(rows: Column, schema: Sequence[DType]) -> Table:
    """LIST<INT8> row blobs -> columnar table.

    Analog of ``RowConversion.convertFromRows`` (RowConversion.java:110-121);
    ``schema`` plays the role of the flattened (type-id, scale) pairs the Java
    layer marshals (RowConversion.java:113-118).
    """
    if rows.dtype.id != TypeId.LIST or not rows.children:
        raise TypeError("expected a LIST<INT8> row-blob column")
    child = rows.children[0]
    if child.dtype not in (INT8, UINT8):
        # parity with the INT8/UINT8 child guard (row_conversion.cu:525-528)
        raise TypeError(f"row blobs must be LIST<INT8>, child is {child.dtype!r}")
    layout = fixed_width_layout(schema)
    offs = np.asarray(rows.offsets)
    n = offs.shape[0] - 1
    widths = np.diff(offs)
    if n and not (widths == layout.row_size).all():
        # parity with the size cross-check (row_conversion.cu:537-542)
        raise ValueError(
            f"row width mismatch: blobs have {set(widths.tolist())} bytes/row, "
            f"schema packs to {layout.row_size}")
    data_u8 = jnp.asarray(child.data, jnp.uint8)
    datas, masks = _from_rows_bytes(layout, data_u8)
    cols = [Column(dt, data=d, validity=m)
            for dt, d, m in zip(layout.schema, datas, masks)]
    return Table(cols)
