"""RowConversion: columnar Table <-> packed row-major blobs (LIST<INT8>).

TPU-native re-design of the reference op (reference
src/main/cpp/src/row_conversion.cu, Java API RowConversion.java):

- Wire format is IDENTICAL to the reference so blobs interoperate with
  UnsafeRow-style CPU consumers: C-struct natural alignment per column in
  schema order, one validity bit per column in bytes appended at the row tail,
  row padded to a 64-bit multiple (reference row_conversion.cu:432-456
  ``compute_fixed_width_layout``; layout documented in RowConversion.java:50-99).
- Output is split into batches so no batch exceeds 2^31-1 bytes, with batch row
  counts a multiple of 32 (reference row_conversion.cu:476-511 keeps int32 list
  offsets valid and validity words batch-local).
- Fixed-width types only, like the reference at this snapshot
  (row_conversion.cu:515,573 CUDF_FAIL on non-fixed-width).

The kernel design is TPU-first rather than a translation of the CUDA kernels:
where the reference stages per-block shared-memory tiles and does warp-ballot
validity packing (row_conversion.cu:75-108,158-165,255-272), we express the
whole conversion as a dense uint32 *row-word matrix* ``u32[rows, row_size/4]``
built from per-column bitcasts/shifts — XLA fuses the whole thing into one
elementwise pass over HBM, and every operation is 32-bit (the VPU lane width;
64-bit float bitcasts do not exist on TPU — see utils/floatbits.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, PackedByteColumn, Table
from ..dtypes import DType, TypeId, INT8, UINT8
from ..utils.tracing import traced

# Reference parity: per-batch byte ceiling from cudf's int32 list offsets
# (row_conversion.cu:384-386) and 32-row batch alignment (:477-479).
MAX_BATCH_BYTES = (1 << 31) - 1
BATCH_ROW_ALIGN = 32


@dataclass(frozen=True)
class RowLayout:
    """Host-side packed-row layout plan (one per schema).

    Mirrors the reference's ``compute_fixed_width_layout``
    (row_conversion.cu:432-456): natural alignment per column, validity bytes
    at the tail, 64-bit row padding.
    """

    schema: tuple[DType, ...]
    offsets: tuple[int, ...]  # byte offset of each column's value in the row
    validity_offset: int      # first validity byte
    row_size: int             # padded total bytes per row

    @property
    def num_validity_bytes(self) -> int:
        return (len(self.schema) + 7) // 8


def fixed_width_layout(schema: Sequence[DType]) -> RowLayout:
    schema = tuple(schema)
    for dt in schema:
        if not dt.is_fixed_width:
            # parity with CUDF_FAIL "only fixed-width types" (row_conversion.cu:515)
            raise TypeError(f"row conversion requires fixed-width types, got {dt!r}")
    off = 0
    offsets = []
    for dt in schema:
        size = dt.itemsize
        off = (off + size - 1) // size * size  # natural C alignment
        offsets.append(off)
        off += size
    validity_offset = off
    off += (len(schema) + 7) // 8
    row_size = (off + 7) // 8 * 8  # 64-bit row padding (row_conversion.cu:86)
    return RowLayout(schema, tuple(offsets), validity_offset, row_size)


# ---------------------------------------------------------------------------
# kernels (jitted per (layout, n) via trace caching)
# ---------------------------------------------------------------------------

def _col_to_u32_parts(dtype: DType, data: jnp.ndarray) -> list[tuple[int, jnp.ndarray]]:
    """Decompose one column into (byte_width, uint32-extended value) parts.

    8-byte types yield two parts (lo, hi); smaller types one part whose value
    occupies the low ``byte_width`` bytes of the uint32.
    """
    size = dtype.itemsize
    if size == 16:
        # DECIMAL128: int64[n, 2] limb pairs -> four LE words
        quad = jax.lax.bitcast_convert_type(data, jnp.uint32)  # (n, 2, 2)
        return [(4, quad[..., 0, 0]), (4, quad[..., 0, 1]),
                (4, quad[..., 1, 0]), (4, quad[..., 1, 1])]
    if size == 8:
        # FLOAT64 included: its device buffer already holds IEEE bit patterns
        # as int64 (dtypes.device_storage), so every 8-byte type is an integer
        # bitcast — exact on TPU, where 64-bit float bitcasts don't exist
        pair = jax.lax.bitcast_convert_type(data, jnp.uint32)  # (n, 2) LE
        return [(4, pair[..., 0]), (4, pair[..., 1])]
    if size == 4:
        return [(4, jax.lax.bitcast_convert_type(data, jnp.uint32))]
    if size == 2:
        u16 = jax.lax.bitcast_convert_type(data, jnp.uint16)
        return [(2, u16.astype(jnp.uint32))]
    u8 = jax.lax.bitcast_convert_type(data, jnp.uint8)
    return [(1, u8.astype(jnp.uint32))]


def _build_planes(layout: RowLayout, datas: Sequence[jnp.ndarray],
                  masks: Sequence[Optional[jnp.ndarray]]) -> list[jnp.ndarray]:
    """One dense ``u32[n]`` *plane* per row word (word-major decomposition).

    Planes stay in the TPU's natural dense 1-D layout — the key to the fast
    wire path (see ``_to_rows_wire``): all per-column shifts/ors fuse into one
    elementwise pass, and no intermediate ever has a sub-128 minor dimension
    that XLA would pad to full lane width.
    """
    nwords = layout.row_size // 4
    n = datas[0].shape[0] if datas else 0
    # word index -> list of uint32 contributions (pre-shifted into place)
    contribs: dict[int, list[jnp.ndarray]] = {}

    def place(byte_off: int, width: int, value_u32: jnp.ndarray):
        w, b = divmod(byte_off, 4)
        assert b + width <= 4, "parts never straddle words (natural alignment)"
        v = value_u32 if b == 0 else value_u32 << jnp.uint32(8 * b)
        contribs.setdefault(w, []).append(v)

    for dt, off, data in zip(layout.schema, layout.offsets, datas):
        for i, (width, part) in enumerate(_col_to_u32_parts(dt, data)):
            place(off + 4 * i, width, part)

    # validity bytes: bit i%8 of byte i//8 set when column i's row is valid
    # (wire layout per RowConversion.java:90-97; reference packs these bits with
    # atomics/ballots — here each byte is a sum of shifted bool lanes)
    for byte_idx in range(layout.num_validity_bytes):
        byte = jnp.zeros((n,), jnp.uint32)
        for bit in range(8):
            i = byte_idx * 8 + bit
            if i >= len(layout.schema):
                break
            m = masks[i]
            lane = (jnp.ones((n,), jnp.uint32) if m is None
                    else m.astype(jnp.uint32))
            byte = byte | (lane << jnp.uint32(bit))
        place(layout.validity_offset + byte_idx, 1, byte)

    zero = jnp.zeros((n,), jnp.uint32)
    return [functools.reduce(jnp.bitwise_or, contribs[w])
            if w in contribs else zero for w in range(nwords)]


def _to_row_words(layout: RowLayout, datas: Sequence[jnp.ndarray],
                  masks: Sequence[Optional[jnp.ndarray]]) -> jnp.ndarray:
    """Pack columns into the row-word matrix ``u32[n, row_size // 4]``.

    The (n, nwords) matrix is the *shuffle* representation (row-granular
    gathers); for bulk wire output prefer ``_to_rows_wire`` which avoids this
    shape's lane padding entirely.
    """
    return jnp.stack(_build_planes(layout, datas, masks), axis=1)


# Row-group width of the wire formulation: 32 rows of nwords words become one
# (32*nwords)-lane output row, keeping every minor dimension >= 128 lanes for
# typical row sizes so nothing is lane-padded.  This is the TPU analog of the
# reference's staged shared-memory coalescing (row_conversion.cu:75-108,
# 278-300): instead of staging tiles in shared memory for int64-coalesced
# writes, group rows so XLA's natural (8,128) tiling IS the coalesced layout.
WIRE_GROUP = 32


@functools.lru_cache(maxsize=None)
def _wire_perm(nwords: int):
    """Lane permutation taking w-major concat order to row-major wire order.

    After concatenating the 32-row reshapes of each plane, lane w*32+i holds
    word w of group-row i; the wire wants lane i*nwords+w.
    """
    perm = np.empty(WIRE_GROUP * nwords, np.int32)
    for w in range(nwords):
        for i in range(WIRE_GROUP):
            perm[i * nwords + w] = w * WIRE_GROUP + i
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size, dtype=np.int32)
    return perm, inv


def _to_rows_wire(layout: RowLayout, datas, masks) -> jnp.ndarray:
    """Fast path: packed wire image as dense ``u32[n * row_size // 4]``.

    The bytes of this array (little-endian) are exactly the packed rows.  The
    pipeline is planes -> 32-row-group concat -> constant lane permutation;
    measured ~2x the naive (n, nwords) stack on TPU because no step touches a
    lane-padded layout (the (n, nwords) matrix pads nwords -> 128 lanes, a
    ~10x write amplification for typical row sizes).
    """
    nwords = layout.row_size // 4
    planes = _build_planes(layout, datas, masks)
    n = datas[0].shape[0] if datas else 0
    ngroups = -(-n // WIRE_GROUP) if n else 0
    padded = ngroups * WIRE_GROUP
    if padded != n:
        planes = [jnp.concatenate(
            [p, jnp.zeros((padded - n,), jnp.uint32)]) for p in planes]
    if ngroups == 0:
        return jnp.zeros((0,), jnp.uint32)
    perm, _ = _wire_perm(nwords)
    grouped = jnp.concatenate(
        [p.reshape(ngroups, WIRE_GROUP) for p in planes], axis=1)
    wire = grouped[:, jnp.asarray(perm)].reshape(-1)
    return wire if padded == n else wire[:n * nwords]


def _from_wire(layout: RowLayout, wire: jnp.ndarray, n: int):
    """Inverse of ``_to_rows_wire``: dense u32 wire image -> planes list."""
    nwords = layout.row_size // 4
    ngroups = -(-n // WIRE_GROUP) if n else 0
    padded = ngroups * WIRE_GROUP
    if padded != n:
        wire = jnp.concatenate(
            [wire, jnp.zeros((padded - n) * nwords, jnp.uint32)])
    if ngroups == 0:
        zero = jnp.zeros((0,), jnp.uint32)
        return [zero for _ in range(nwords)]
    _, inv = _wire_perm(nwords)
    grouped = wire.reshape(ngroups, WIRE_GROUP * nwords)[:, jnp.asarray(inv)]
    return [grouped[:, w * WIRE_GROUP:(w + 1) * WIRE_GROUP].reshape(-1)[:n]
            for w in range(nwords)]


def _from_planes(layout: RowLayout, planes: list):
    """Unpack per-word planes (``u32[n]`` each) into (datas, masks)."""
    datas, masks = [], []

    def word_at(byte_off: int) -> jnp.ndarray:
        return planes[byte_off // 4]

    def subword(byte_off: int, width: int) -> jnp.ndarray:
        w, b = divmod(byte_off, 4)
        v = planes[w]
        if b:
            v = v >> jnp.uint32(8 * b)
        if width < 4:
            v = v & jnp.uint32((1 << (8 * width)) - 1)
        return v

    for dt, off in zip(layout.schema, layout.offsets):
        size = dt.itemsize
        if size == 16:  # DECIMAL128 -> int64[n, 2] limb pairs
            quad = jnp.stack([jnp.stack([word_at(off), word_at(off + 4)], -1),
                              jnp.stack([word_at(off + 8), word_at(off + 12)],
                                        -1)], axis=-2)
            data = jax.lax.bitcast_convert_type(quad, jnp.int64)
        elif size == 8:
            pair = jnp.stack([word_at(off), word_at(off + 4)], axis=-1)
            data = jax.lax.bitcast_convert_type(pair, jnp.int64)
            if dt.id != TypeId.FLOAT64:  # FLOAT64 keeps its bit-pattern buffer
                data = data.astype(dt.jnp_dtype)
        elif size == 4:
            data = jax.lax.bitcast_convert_type(word_at(off), dt.jnp_dtype)
        elif size == 2:
            u16 = subword(off, 2).astype(jnp.uint16)
            data = jax.lax.bitcast_convert_type(u16, dt.jnp_dtype)
        else:
            u8 = subword(off, 1).astype(jnp.uint8)
            data = u8 if dt.jnp_dtype == jnp.uint8 else \
                jax.lax.bitcast_convert_type(u8, dt.jnp_dtype)
        datas.append(data)

    for i in range(len(layout.schema)):
        byte = subword(layout.validity_offset + i // 8, 1)
        masks.append(((byte >> jnp.uint32(i % 8)) & jnp.uint32(1)).astype(jnp.bool_))
    return datas, masks


def _from_row_words(layout: RowLayout, words: jnp.ndarray):
    """Unpack ``u32[n, nwords]`` (shuffle representation) into (datas, masks)."""
    return _from_planes(layout, [words[:, w]
                                 for w in range(layout.row_size // 4)])


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_wire_jit(layout: RowLayout, datas, masks) -> jnp.ndarray:
    return _to_rows_wire(layout, datas, masks)


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_bytes(layout: RowLayout, datas, masks) -> jnp.ndarray:
    """u8[n * row_size] packed rows for one batch (jitted per layout/shape)."""
    wire = _to_rows_wire(layout, datas, masks)
    return jax.lax.bitcast_convert_type(wire, jnp.uint8).reshape(-1)  # LE


@functools.partial(jax.jit, static_argnums=0)
def _from_rows_bytes(layout: RowLayout, data_u8: jnp.ndarray):
    n = data_u8.shape[0] // layout.row_size
    grouped = data_u8.reshape(-1, 4)
    wire = jax.lax.bitcast_convert_type(grouped, jnp.uint32)
    return _from_planes(layout, _from_wire(layout, wire, n))


@functools.partial(jax.jit, static_argnums=(0, 2))
def _from_rows_wire_jit(layout: RowLayout, wire_u32: jnp.ndarray, n: int):
    return _from_planes(layout, _from_wire(layout, wire_u32, n))


# ---------------------------------------------------------------------------
# public API (mirrors RowConversion.java:101-121)
# ---------------------------------------------------------------------------

@traced("convert_to_rows")
def convert_to_rows(table: Table, max_batch_bytes: int = MAX_BATCH_BYTES) -> list[Column]:
    """Columnar table -> list of LIST<INT8> row-blob columns.

    Analog of ``RowConversion.convertToRows`` (RowConversion.java:101-108).
    Returns multiple columns when the packed output would exceed
    ``max_batch_bytes`` (reference row_conversion.cu:476-511); batch row counts
    are a multiple of 32 except possibly the last.
    """
    layout = fixed_width_layout(table.dtypes())
    n = table.num_rows
    rows_per_batch = max(1, max_batch_bytes // layout.row_size)
    if rows_per_batch < n:
        if layout.row_size * BATCH_ROW_ALIGN > max_batch_bytes:
            # a 32-row-aligned batch would exceed the cap (and for the default
            # cap, overflow the int32 LIST offsets the format protects)
            raise ValueError(
                f"row size {layout.row_size} too large: a {BATCH_ROW_ALIGN}"
                f"-row aligned batch exceeds max_batch_bytes={max_batch_bytes}")
        rows_per_batch = rows_per_batch // BATCH_ROW_ALIGN * BATCH_ROW_ALIGN
    out = []
    start = 0
    while start < n or (n == 0 and not out):
        stop = min(n, start + rows_per_batch)
        datas = tuple(c.data[start:stop] for c in table.columns)
        masks = tuple(None if c.validity is None else c.validity[start:stop]
                      for c in table.columns)
        wire = _to_rows_wire_jit(layout, datas, masks)
        nb = stop - start
        offsets = jnp.arange(nb + 1, dtype=jnp.int32) * layout.row_size
        out.append(Column.list_(PackedByteColumn(INT8, data=wire), offsets))
        start = stop
        if n == 0:
            break
    return out


@traced("convert_from_rows")
def convert_from_rows(rows: Column, schema: Sequence[DType]) -> Table:
    """LIST<INT8> row blobs -> columnar table.

    Analog of ``RowConversion.convertFromRows`` (RowConversion.java:110-121);
    ``schema`` plays the role of the flattened (type-id, scale) pairs the Java
    layer marshals (RowConversion.java:113-118).
    """
    if rows.dtype.id != TypeId.LIST or not rows.children:
        raise TypeError("expected a LIST<INT8> row-blob column")
    child = rows.children[0]
    if child.dtype not in (INT8, UINT8):
        # parity with the INT8/UINT8 child guard (row_conversion.cu:525-528)
        raise TypeError(f"row blobs must be LIST<INT8>, child is {child.dtype!r}")
    layout = fixed_width_layout(schema)
    offs = np.asarray(rows.offsets)
    n = offs.shape[0] - 1
    widths = np.diff(offs)
    if n and not (widths == layout.row_size).all():
        # parity with the size cross-check (row_conversion.cu:537-542)
        raise ValueError(
            f"row width mismatch: blobs have {set(widths.tolist())} bytes/row, "
            f"schema packs to {layout.row_size}")
    if child.data.dtype == jnp.uint32:  # packed-word blob (convert_to_rows)
        datas, masks = _from_rows_wire_jit(layout, child.data, n)
    else:
        datas, masks = _from_rows_bytes(layout, jnp.asarray(child.data,
                                                            jnp.uint8))
    cols = [Column(dt, data=d, validity=m)
            for dt, d, m in zip(layout.schema, datas, masks)]
    return Table(cols)
