"""RowConversion: columnar Table <-> packed row-major blobs (LIST<INT8>).

TPU-native re-design of the reference op (reference
src/main/cpp/src/row_conversion.cu, Java API RowConversion.java):

- Wire format is IDENTICAL to the reference so blobs interoperate with
  UnsafeRow-style CPU consumers: C-struct natural alignment per column in
  schema order, one validity bit per column in bytes appended at the row tail,
  row padded to a 64-bit multiple (reference row_conversion.cu:432-456
  ``compute_fixed_width_layout``; layout documented in RowConversion.java:50-99).
- Output is split into batches so no batch exceeds 2^31-1 bytes, with batch row
  counts a multiple of 32 (reference row_conversion.cu:476-511 keeps int32 list
  offsets valid and validity words batch-local).
- Fixed-width types only, like the reference at this snapshot
  (row_conversion.cu:515,573 CUDF_FAIL on non-fixed-width).

The kernel design is TPU-first rather than a translation of the CUDA kernels:
where the reference stages per-block shared-memory tiles and does warp-ballot
validity packing (row_conversion.cu:75-108,158-165,255-272), we express the
whole conversion as a dense uint32 *row-word matrix* ``u32[rows, row_size/4]``
built from per-column bitcasts/shifts — XLA fuses the whole thing into one
elementwise pass over HBM, and every operation is 32-bit (the VPU lane width;
64-bit float bitcasts do not exist on TPU — see utils/floatbits.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, PackedByteColumn, Table
from ..dtypes import DType, TypeId, INT8, UINT8
from ..utils.tracing import traced

# Reference parity: per-batch byte ceiling from cudf's int32 list offsets
# (row_conversion.cu:384-386) and 32-row batch alignment (:477-479).
MAX_BATCH_BYTES = (1 << 31) - 1
BATCH_ROW_ALIGN = 32


@dataclass(frozen=True)
class RowLayout:
    """Host-side packed-row layout plan (one per schema).

    Mirrors the reference's ``compute_fixed_width_layout``
    (row_conversion.cu:432-456): natural alignment per column, validity bytes
    at the tail, 64-bit row padding.
    """

    schema: tuple[DType, ...]
    offsets: tuple[int, ...]  # byte offset of each column's value in the row
    validity_offset: int      # first validity byte
    row_size: int             # padded total bytes per row

    @property
    def num_validity_bytes(self) -> int:
        return (len(self.schema) + 7) // 8


def fixed_width_layout(schema: Sequence[DType]) -> RowLayout:
    schema = tuple(schema)
    for dt in schema:
        if not dt.is_fixed_width:
            # parity with CUDF_FAIL "only fixed-width types" (row_conversion.cu:515)
            raise TypeError(f"row conversion requires fixed-width types, got {dt!r}")
    off = 0
    offsets = []
    for dt in schema:
        size = dt.itemsize
        off = (off + size - 1) // size * size  # natural C alignment
        offsets.append(off)
        off += size
    validity_offset = off
    off += (len(schema) + 7) // 8
    row_size = (off + 7) // 8 * 8  # 64-bit row padding (row_conversion.cu:86)
    return RowLayout(schema, tuple(offsets), validity_offset, row_size)


# ---------------------------------------------------------------------------
# kernels (jitted per (layout, n) via trace caching)
# ---------------------------------------------------------------------------

def _col_to_u32_parts(dtype: DType, data: jnp.ndarray) -> list[tuple[int, jnp.ndarray]]:
    """Decompose one column into (byte_width, uint32-extended value) parts.

    8-byte types yield two parts (lo, hi); smaller types one part whose value
    occupies the low ``byte_width`` bytes of the uint32.
    """
    size = dtype.itemsize
    if size == 16:
        # DECIMAL128: int64[n, 2] limb pairs -> four LE words
        quad = jax.lax.bitcast_convert_type(data, jnp.uint32)  # (n, 2, 2)
        return [(4, quad[..., 0, 0]), (4, quad[..., 0, 1]),
                (4, quad[..., 1, 0]), (4, quad[..., 1, 1])]
    if size == 8:
        # FLOAT64 included: its device buffer already holds IEEE bit patterns
        # as int64 (dtypes.device_storage), so every 8-byte type is an integer
        # bitcast — exact on TPU, where 64-bit float bitcasts don't exist
        pair = jax.lax.bitcast_convert_type(data, jnp.uint32)  # (n, 2) LE
        return [(4, pair[..., 0]), (4, pair[..., 1])]
    if size == 4:
        return [(4, jax.lax.bitcast_convert_type(data, jnp.uint32))]
    if size == 2:
        u16 = jax.lax.bitcast_convert_type(data, jnp.uint16)
        return [(2, u16.astype(jnp.uint32))]
    u8 = jax.lax.bitcast_convert_type(data, jnp.uint8)
    return [(1, u8.astype(jnp.uint32))]


def _build_planes(layout: RowLayout, datas: Sequence[jnp.ndarray],
                  masks: Sequence[Optional[jnp.ndarray]],
                  extra_parts=None, n: Optional[int] = None
                  ) -> list[jnp.ndarray]:
    """One dense ``u32[n]`` *plane* per row word (word-major decomposition).

    Planes stay in the TPU's natural dense 1-D layout — the key to the fast
    wire path (see ``_to_rows_wire``): all per-column shifts/ors fuse into one
    elementwise pass, and no intermediate ever has a sub-128 minor dimension
    that XLA would pad to full lane width.

    ``extra_parts``: optional {column index: [(byte_width, u32 part), ...]}
    overriding the value decomposition for columns whose device buffer is
    not the wire value (the variable-width path injects (offset, length)
    slot words for STRING columns here).
    """
    nwords = layout.row_size // 4
    if n is None:
        # derive the row count from any present buffer — an all-string
        # schema has None at every datas position, so check extra_parts too
        for d in datas:
            if d is not None:
                n = d.shape[0]
                break
        else:
            for parts in (extra_parts or {}).values():
                if parts:
                    n = parts[0][1].shape[0]
                    break
            else:
                n = 0
    # word index -> list of uint32 contributions (pre-shifted into place)
    contribs: dict[int, list[jnp.ndarray]] = {}

    def place(byte_off: int, width: int, value_u32: jnp.ndarray):
        w, b = divmod(byte_off, 4)
        assert b + width <= 4, "parts never straddle words (natural alignment)"
        v = value_u32 if b == 0 else value_u32 << jnp.uint32(8 * b)
        contribs.setdefault(w, []).append(v)

    for ci, (dt, off, data) in enumerate(zip(layout.schema, layout.offsets,
                                             datas)):
        parts = (extra_parts[ci] if extra_parts and ci in extra_parts
                 else _col_to_u32_parts(dt, data))
        for i, (width, part) in enumerate(parts):
            place(off + 4 * i, width, part)

    # validity bytes: bit i%8 of byte i//8 set when column i's row is valid
    # (wire layout per RowConversion.java:90-97; reference packs these bits with
    # atomics/ballots — here each byte is a sum of shifted bool lanes)
    for byte_idx in range(layout.num_validity_bytes):
        byte = jnp.zeros((n,), jnp.uint32)
        for bit in range(8):
            i = byte_idx * 8 + bit
            if i >= len(layout.schema):
                break
            m = masks[i]
            lane = (jnp.ones((n,), jnp.uint32) if m is None
                    else m.astype(jnp.uint32))
            byte = byte | (lane << jnp.uint32(bit))
        place(layout.validity_offset + byte_idx, 1, byte)

    zero = jnp.zeros((n,), jnp.uint32)
    return [functools.reduce(jnp.bitwise_or, contribs[w])
            if w in contribs else zero for w in range(nwords)]


def _to_row_words(layout: RowLayout, datas: Sequence[jnp.ndarray],
                  masks: Sequence[Optional[jnp.ndarray]]) -> jnp.ndarray:
    """Pack columns into the row-word matrix ``u32[n, row_size // 4]``.

    The (n, nwords) matrix is the *shuffle* representation (row-granular
    gathers); for bulk wire output prefer ``_to_rows_wire`` which avoids this
    shape's lane padding entirely.
    """
    return jnp.stack(_build_planes(layout, datas, masks), axis=1)


# Row-group width of the wire formulation: 32 rows of nwords words become one
# (32*nwords)-lane output row, keeping every minor dimension >= 128 lanes for
# typical row sizes so nothing is lane-padded.  This is the TPU analog of the
# reference's staged shared-memory coalescing (row_conversion.cu:75-108,
# 278-300): instead of staging tiles in shared memory for int64-coalesced
# writes, group rows so XLA's natural (8,128) tiling IS the coalesced layout.
WIRE_GROUP = 32


@functools.lru_cache(maxsize=None)
def _wire_perm(nwords: int):
    """Lane permutation taking w-major concat order to row-major wire order.

    After concatenating the 32-row reshapes of each plane, lane w*32+i holds
    word w of group-row i; the wire wants lane i*nwords+w.
    """
    perm = np.empty(WIRE_GROUP * nwords, np.int32)
    for w in range(nwords):
        for i in range(WIRE_GROUP):
            perm[i * nwords + w] = w * WIRE_GROUP + i
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size, dtype=np.int32)
    return perm, inv


def _to_rows_wire(layout: RowLayout, datas, masks) -> jnp.ndarray:
    """Fast path: packed wire image as dense ``u32[n * row_size // 4]``.

    The bytes of this array (little-endian) are exactly the packed rows.  The
    pipeline is planes -> 32-row-group concat -> constant lane permutation;
    measured ~2x the naive (n, nwords) stack on TPU because no step touches a
    lane-padded layout (the (n, nwords) matrix pads nwords -> 128 lanes, a
    ~10x write amplification for typical row sizes).
    """
    nwords = layout.row_size // 4
    planes = _build_planes(layout, datas, masks)
    n = datas[0].shape[0] if datas else 0
    ngroups = -(-n // WIRE_GROUP) if n else 0
    padded = ngroups * WIRE_GROUP
    if padded != n:
        planes = [jnp.concatenate(
            [p, jnp.zeros((padded - n,), jnp.uint32)]) for p in planes]
    if ngroups == 0:
        return jnp.zeros((0,), jnp.uint32)
    from . import pallas_kernels as pk
    if pk.available():
        # single-pass VMEM interleave (Mosaic): planes stream through VMEM
        # once and HBM sees only dense full-lane reads/writes — attacks the
        # lane-permutation bottleneck named in docs/PERF.md.  Probe-gated:
        # deployments without Mosaic (e.g. tunneled remote-compile) take
        # the pure-XLA path below.
        wire = pk.interleave_planes(planes)
        return wire if padded == n else wire[:n * nwords]
    perm, _ = _wire_perm(nwords)
    grouped = jnp.concatenate(
        [p.reshape(ngroups, WIRE_GROUP) for p in planes], axis=1)
    wire = grouped[:, jnp.asarray(perm)].reshape(-1)
    return wire if padded == n else wire[:n * nwords]


def _from_wire(layout: RowLayout, wire: jnp.ndarray, n: int):
    """Inverse of ``_to_rows_wire``: dense u32 wire image -> planes list."""
    nwords = layout.row_size // 4
    ngroups = -(-n // WIRE_GROUP) if n else 0
    padded = ngroups * WIRE_GROUP
    if padded != n:
        wire = jnp.concatenate(
            [wire, jnp.zeros((padded - n) * nwords, jnp.uint32)])
    if ngroups == 0:
        zero = jnp.zeros((0,), jnp.uint32)
        return [zero for _ in range(nwords)]
    from . import pallas_kernels as pk
    if pk.available():
        planes = pk.deinterleave_wire(wire, nwords)
        return [p[:n] for p in planes]
    _, inv = _wire_perm(nwords)
    grouped = wire.reshape(ngroups, WIRE_GROUP * nwords)[:, jnp.asarray(inv)]
    return [grouped[:, w * WIRE_GROUP:(w + 1) * WIRE_GROUP].reshape(-1)[:n]
            for w in range(nwords)]


def _from_planes(layout: RowLayout, planes: list):
    """Unpack per-word planes (``u32[n]`` each) into (datas, masks)."""
    datas, masks = [], []

    def word_at(byte_off: int) -> jnp.ndarray:
        return planes[byte_off // 4]

    def subword(byte_off: int, width: int) -> jnp.ndarray:
        w, b = divmod(byte_off, 4)
        v = planes[w]
        if b:
            v = v >> jnp.uint32(8 * b)
        if width < 4:
            v = v & jnp.uint32((1 << (8 * width)) - 1)
        return v

    for dt, off in zip(layout.schema, layout.offsets):
        size = dt.itemsize
        if size == 16:  # DECIMAL128 -> int64[n, 2] limb pairs
            quad = jnp.stack([jnp.stack([word_at(off), word_at(off + 4)], -1),
                              jnp.stack([word_at(off + 8), word_at(off + 12)],
                                        -1)], axis=-2)
            data = jax.lax.bitcast_convert_type(quad, jnp.int64)
        elif size == 8:
            pair = jnp.stack([word_at(off), word_at(off + 4)], axis=-1)
            data = jax.lax.bitcast_convert_type(pair, jnp.int64)
            if dt.id != TypeId.FLOAT64:  # FLOAT64 keeps its bit-pattern buffer
                data = data.astype(dt.jnp_dtype)
        elif size == 4:
            data = jax.lax.bitcast_convert_type(word_at(off), dt.jnp_dtype)
        elif size == 2:
            u16 = subword(off, 2).astype(jnp.uint16)
            data = jax.lax.bitcast_convert_type(u16, dt.jnp_dtype)
        else:
            u8 = subword(off, 1).astype(jnp.uint8)
            data = u8 if dt.jnp_dtype == jnp.uint8 else \
                jax.lax.bitcast_convert_type(u8, dt.jnp_dtype)
        datas.append(data)

    for i in range(len(layout.schema)):
        byte = subword(layout.validity_offset + i // 8, 1)
        masks.append(((byte >> jnp.uint32(i % 8)) & jnp.uint32(1)).astype(jnp.bool_))
    return datas, masks


def _from_row_words(layout: RowLayout, words: jnp.ndarray):
    """Unpack ``u32[n, nwords]`` (shuffle representation) into (datas, masks)."""
    return _from_planes(layout, [words[:, w]
                                 for w in range(layout.row_size // 4)])


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_wire_jit(layout: RowLayout, datas, masks) -> jnp.ndarray:
    return _to_rows_wire(layout, datas, masks)


@functools.partial(jax.jit, static_argnums=0)
def _to_rows_bytes(layout: RowLayout, datas, masks) -> jnp.ndarray:
    """u8[n * row_size] packed rows for one batch (jitted per layout/shape)."""
    wire = _to_rows_wire(layout, datas, masks)
    return jax.lax.bitcast_convert_type(wire, jnp.uint8).reshape(-1)  # LE


@functools.partial(jax.jit, static_argnums=0)
def _from_rows_bytes(layout: RowLayout, data_u8: jnp.ndarray):
    n = data_u8.shape[0] // layout.row_size
    grouped = data_u8.reshape(-1, 4)
    wire = jax.lax.bitcast_convert_type(grouped, jnp.uint32)
    return _from_planes(layout, _from_wire(layout, wire, n))


@functools.partial(jax.jit, static_argnums=(0, 2))
def _from_rows_wire_jit(layout: RowLayout, wire_u32: jnp.ndarray, n: int):
    return _from_planes(layout, _from_wire(layout, wire_u32, n))


# ---------------------------------------------------------------------------
# variable-width (STRING) rows
# ---------------------------------------------------------------------------
#
# The reference snapshot punts on variable width (row_conversion.cu:515,573
# CUDF_FAIL "only fixed-width types"), but its build machinery exists to feed
# Spark's UnsafeRow consumers, so the variable-width contract here follows
# UnsafeRow conventions grafted onto the documented fixed-width layout
# (RowConversion.java:50-99):
#
#   | fixed region | validity bytes | pad to 8 | variable region | (8-aligned)
#
# - STRING columns occupy an 8-byte naturally-aligned slot in the fixed
#   region: u32 LE byte offset FROM ROW START to the field's bytes, then
#   u32 LE byte length.
# - validity bytes exactly as the fixed-width contract (bit i%8 of byte
#   i//8 per column i).
# - the variable region starts at align8(validity end); fields appear in
#   column order, each padded to an 8-byte multiple with zero bytes
#   (UnsafeRow's roundUpTo8 convention), so every row size is 8-aligned.
# - NULL strings write length 0 at the offset the field would occupy and
#   contribute no variable bytes.


@dataclass(frozen=True)
class VarRowLayout:
    """Layout plan for rows with STRING columns.

    ``base`` plans the fixed region (slots + validity + pad); its
    ``row_size`` is the variable region's start offset.
    """

    base: RowLayout
    string_idx: tuple[int, ...]


def variable_width_layout(schema: Sequence[DType]) -> VarRowLayout:
    schema = tuple(schema)
    off = 0
    offsets = []
    for dt in schema:
        size = 8 if dt.is_string else dt.itemsize
        if not (dt.is_string or dt.is_fixed_width):
            raise TypeError(
                f"row conversion supports fixed-width and STRING, got {dt!r}")
        off = (off + size - 1) // size * size
        offsets.append(off)
        off += size
    validity_offset = off
    off += (len(schema) + 7) // 8
    var_start = (off + 7) // 8 * 8
    base = RowLayout(schema, tuple(offsets), validity_offset, var_start)
    return VarRowLayout(base, tuple(i for i, dt in enumerate(schema)
                                    if dt.is_string))


# (An owner-fill merge formulation — two sorts + flat gathers, the pattern
# in ops/join.py:_expand_pairs — was measured ~3x slower than the single
# (slot, value) wire sort below and removed; see docs/PERF.md r5 notes.)


def _string_words(col: Column, width: int):
    """(u32[n * width//4] flat LE word matrix, int32[n] effective lengths).

    ``width`` must be an 8-byte multiple; nulls get length 0 (they write no
    variable bytes — see the contract above).
    """
    from .strings_common import to_padded_bytes
    mat, lengths = to_padded_bytes(col, width=width)
    if col.validity is not None:
        lengths = jnp.where(col.validity, lengths, 0)
        mat = jnp.where(col.validity[:, None], mat, jnp.uint8(0))
    words = jax.lax.bitcast_convert_type(
        mat.reshape(mat.shape[0], width // 4, 4), jnp.uint32)
    return words.reshape(-1), lengths


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _to_rows_wire_var(vlayout: VarRowLayout, swidths: tuple, total_words: int,
                      datas, masks, smat_words, slens, row_off4):
    """Variable-width wire image as dense ``u32[total_words]``.

    ``datas`` has None at string positions; ``smat_words``/``slens`` are the
    flat padded word matrices + effective lengths per string column (order
    of ``vlayout.string_idx``); ``row_off4`` the per-row word offsets.

    TPU formulation: every candidate output word lives in a dense
    (n, base_words + sum(swidths)/4) lane grid built ELEMENTWISE (fixed
    planes + per-column padded string words), each lane's destination wire
    slot is also elementwise, and ONE stable 2-operand (slot, value) sort
    delivers the wire image as its first ``total_words`` entries.  Ragged
    interleave is inherently data-dependent movement — on TPU that costs
    one sort; this shape does it with no gathers, no scatter, no unsort
    pass (compare _run_owner_fill, which needs two sorts plus flat
    gathers and measures ~3x slower here).
    """
    base = vlayout.base
    base_words = base.row_size // 4
    n = row_off4.shape[0]
    # per-field padded word counts and per-row exclusive cumsum across cols
    pw = [((l + 7) // 8 * 2).astype(jnp.int32) for l in slens]
    cumb = []
    acc = jnp.zeros((n,), jnp.int32)
    for w in pw:
        cumb.append(acc)
        acc = acc + w
    # slot words for each string column: byte offset from row start + length
    extra = {}
    for k, idx in enumerate(vlayout.string_idx):
        off_bytes = (base.row_size + 4 * cumb[k]).astype(jnp.uint32)
        extra[idx] = [(4, off_bytes), (4, slens[k].astype(jnp.uint32))]
    planes = _build_planes(base, datas, masks, extra_parts=extra, n=n)

    dead = jnp.int32(total_words)
    keys = [row_off4 + w for w in range(base_words)]
    vals = list(planes)
    var_base = row_off4 + base_words
    for k, (words, wbytes) in enumerate(zip(smat_words, swidths)):
        w4 = wbytes // 4
        mat = words.reshape(n, w4)
        col_base = var_base + cumb[k]
        for w in range(w4):
            live = w < pw[k]
            keys.append(jnp.where(live, col_base + w, dead))
            vals.append(mat[:, w])
    key = jnp.concatenate(keys)
    val = jnp.concatenate(vals)
    _, sval = jax.lax.sort((key, val), num_keys=1, is_stable=False)
    return sval[:total_words]


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _from_rows_var(vlayout: VarRowLayout, swidths: tuple, n: int,
                   wire_u32, row_off4):
    """Inverse: wire words + row offsets -> (fixed datas, masks, string
    (byte-matrix, length) pairs).  Pure flat gathers (row starts are known,
    so no owner-fill is needed on this side)."""
    base = vlayout.base
    base_words = base.row_size // 4
    W = wire_u32.shape[0]
    idx = row_off4[:, None] + jnp.arange(base_words, dtype=jnp.int32)[None, :]
    mat = jnp.take(wire_u32, jnp.clip(idx, 0, max(W - 1, 0)).reshape(-1))
    planes = [mat.reshape(n, base_words)[:, w] for w in range(base_words)]

    def subword(byte_off, width):
        w, b = divmod(byte_off, 4)
        v = planes[w]
        if b:
            v = v >> jnp.uint32(8 * b)
        if width < 4:
            v = v & jnp.uint32((1 << (8 * width)) - 1)
        return v

    wire_u8 = jax.lax.bitcast_convert_type(wire_u32, jnp.uint8).reshape(-1)
    datas = []
    strings = []
    sk = 0
    for ci, (dt, off) in enumerate(zip(base.schema, base.offsets)):
        if dt.is_string:
            foff = planes[off // 4]
            flen = planes[off // 4 + 1].astype(jnp.int32)
            width = swidths[sk]
            sk += 1
            byte0 = (row_off4 * 4 + foff.astype(jnp.int32))
            bidx = byte0[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
            smat = jnp.take(wire_u8,
                            jnp.clip(bidx, 0, max(W * 4 - 1, 0)).reshape(-1)
                            ).reshape(n, width)
            keep = jnp.arange(width, dtype=jnp.int32)[None, :] < flen[:, None]
            strings.append((jnp.where(keep, smat, jnp.uint8(0)), flen))
            datas.append(None)
            continue
        size = dt.itemsize
        if size == 16:
            quad = jnp.stack(
                [jnp.stack([planes[off // 4], planes[off // 4 + 1]], -1),
                 jnp.stack([planes[off // 4 + 2], planes[off // 4 + 3]], -1)],
                axis=-2)
            data = jax.lax.bitcast_convert_type(quad, jnp.int64)
        elif size == 8:
            pair = jnp.stack([planes[off // 4], planes[off // 4 + 1]], -1)
            data = jax.lax.bitcast_convert_type(pair, jnp.int64)
            if dt.id != TypeId.FLOAT64:
                data = data.astype(dt.jnp_dtype)
        elif size == 4:
            data = jax.lax.bitcast_convert_type(planes[off // 4],
                                                dt.jnp_dtype)
        elif size == 2:
            u16 = subword(off, 2).astype(jnp.uint16)
            data = jax.lax.bitcast_convert_type(u16, dt.jnp_dtype)
        else:
            u8 = subword(off, 1).astype(jnp.uint8)
            data = u8 if dt.jnp_dtype == jnp.uint8 else \
                jax.lax.bitcast_convert_type(u8, dt.jnp_dtype)
        datas.append(data)

    masks = []
    for i in range(len(base.schema)):
        byte = subword(base.validity_offset + i // 8, 1)
        masks.append(((byte >> jnp.uint32(i % 8)) & jnp.uint32(1))
                     .astype(jnp.bool_))
    return datas, masks, strings


@functools.partial(jax.jit, static_argnums=0)
def _var_probe(vlayout: VarRowLayout, soffs, svalids):
    """ONE device program -> [max_len per string col ..., total bytes].

    The only data-dependent statics of the variable-width conversion, so
    the host pays a single scalar-vector fetch before launching the fused
    kernel (a tunneled deployment pays ~100ms per sync)."""
    outs = []
    total = jnp.int64(0)
    for offs, valid in zip(soffs, svalids):
        ln = (offs[1:] - offs[:-1]).astype(jnp.int32)
        if valid is not None:
            ln = jnp.where(valid, ln, 0)
        outs.append(jnp.max(ln) if ln.shape[0] else jnp.int32(0))
        total = total + jnp.sum((ln.astype(jnp.int64) + 7) // 8 * 8)
    n = soffs[0].shape[0] - 1 if soffs else 0
    total = total + vlayout.base.row_size * n
    return jnp.stack([o.astype(jnp.int64) for o in outs] + [total])


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _to_rows_var_fused(vlayout: VarRowLayout, swidths: tuple,
                       total_words: int, datas, masks, soffs, schars):
    """Single-batch fused program: string padded matrices, row offsets and
    the wire sort in ONE compilation — no eager dispatch chatter."""
    smat_words = []
    slens = []
    n = soffs[0].shape[0] - 1 if soffs else (
        datas[0].shape[0] if datas and datas[0] is not None else 0)
    row_sizes = jnp.full((n,), vlayout.base.row_size, jnp.int64)
    for k, (offs, chars) in enumerate(zip(soffs, schars)):
        w = swidths[k]
        starts = offs[:-1]
        lengths = (offs[1:] - starts).astype(jnp.int32)
        valid = masks[vlayout.string_idx[k]]
        if valid is not None:
            lengths = jnp.where(valid, lengths, 0)
        idx = starts[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
        mat = jnp.take(chars, idx, mode="clip")
        keep = jnp.arange(w, dtype=jnp.int32)[None, :] < lengths[:, None]
        mat = jnp.where(keep, mat, jnp.uint8(0))
        words = jax.lax.bitcast_convert_type(
            mat.reshape(n, w // 4, 4), jnp.uint32)
        smat_words.append(words.reshape(-1))
        slens.append(lengths)
        row_sizes = row_sizes + ((lengths.astype(jnp.int64) + 7) // 8 * 8)
    row_ends = jnp.cumsum(row_sizes)
    row_off4 = ((row_ends - row_sizes) // 4).astype(jnp.int32)
    wire = _to_rows_wire_var(vlayout, swidths, total_words, datas, masks,
                             tuple(smat_words), tuple(slens), row_off4)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               row_ends.astype(jnp.int32)])
    return wire, offsets


def _convert_to_rows_var(table: Table, max_batch_bytes: int) -> list[Column]:
    """Host wrapper for the variable-width path.

    All per-row math (lengths, row sizes, offsets) stays ON DEVICE — host
    syncs are scalars only (total bytes, max string length).  On tunneled
    deployments a host round trip of an n-sized array costs more than the
    whole kernel.
    """
    vlayout = variable_width_layout(table.dtypes())
    base = vlayout.base
    n = table.num_rows
    scols = [table.columns[i] for i in vlayout.string_idx]
    soffs = tuple(jnp.asarray(c.offsets, jnp.int32) for c in scols)
    svalids = tuple(c.validity for c in scols)
    schars = tuple(jnp.asarray(c.data, jnp.uint8)
                   if c.data is not None and c.data.shape[0]
                   else jnp.zeros((1,), jnp.uint8) for c in scols)
    probe = np.asarray(_var_probe(vlayout, soffs, svalids))  # one fetch
    # align8 widths (not pow2 buckets): every lane of the padded matrix
    # rides the wire sort, so slack lanes are real sort work
    swidths = tuple(max(8, (int(mx) + 7) // 8 * 8) for mx in probe[:-1])
    total_bytes = int(probe[-1]) if n else 0

    datas = tuple(None if dt.is_string else c.data
                  for dt, c in zip(base.schema, table.columns))
    masks = tuple(c.validity for c in table.columns)

    if total_bytes <= max_batch_bytes:  # common case: ONE fused program
        wire, offsets = _to_rows_var_fused(vlayout, swidths,
                                           total_bytes // 4, datas, masks,
                                           soffs, schars)
        return [Column.list_(PackedByteColumn(INT8, data=wire), offsets)]

    smat_words = []
    slens = []
    row_sizes = jnp.full((n,), base.row_size, jnp.int64)
    for c, w in zip(scols, swidths):
        words, lengths = _string_words(c, w)
        smat_words.append(words)
        slens.append(lengths)
        row_sizes = row_sizes + ((lengths.astype(jnp.int64) + 7) // 8 * 8)
    row_ends = jnp.cumsum(row_sizes)

    def emit(start, stop, total_words, row_off4, ends):
        bdatas = tuple(None if d is None else d[start:stop] for d in datas)
        bmasks = tuple(None if m is None else m[start:stop] for m in masks)
        bwords = tuple(words.reshape(-1, w // 4)[start:stop].reshape(-1)
                       for w, words in zip(swidths, smat_words))
        blens = tuple(l[start:stop] for l in slens)
        wire = _to_rows_wire_var(vlayout, tuple(swidths), total_words,
                                 bdatas, bmasks, bwords, blens, row_off4)
        offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   ends.astype(jnp.int32)])
        return Column.list_(PackedByteColumn(INT8, data=wire), offsets)

    # multi-batch: row boundary planning needs the size vector on the host
    ends_np = np.asarray(row_ends)
    sizes_np = np.diff(np.concatenate([[0], ends_np]))
    if int(sizes_np.max()) > max_batch_bytes:
        raise ValueError(
            f"a single row packs to {int(sizes_np.max())} bytes, above "
            f"max_batch_bytes={max_batch_bytes}")
    out = []
    start = 0
    while start < n:
        # batch greedily by bytes, 32-row aligned when at least one whole
        # group fits (reference row_conversion.cu:476-511); searchsorted
        # gives >= start+1 because every single row fits max_batch_bytes
        base_off = int(ends_np[start - 1]) if start else 0
        stop = int(np.searchsorted(ends_np, base_off + max_batch_bytes,
                                   side="right"))
        if stop < n:
            fit = stop - start
            if fit >= BATCH_ROW_ALIGN:
                # at least one whole aligned group fits the byte budget:
                # align the cut down — the HARD contract middle batches keep
                stop = start + fit // BATCH_ROW_ALIGN * BATCH_ROW_ALIGN
            else:
                # fewer than 32 rows fit: greedy maximality of searchsorted
                # means one aligned group genuinely exceeds max_batch_bytes,
                # the single case the contract exempts — enforce that this
                # is why the cut is unaligned
                group_end = min(start + BATCH_ROW_ALIGN, n)
                assert int(ends_np[group_end - 1]) - base_off \
                    > max_batch_bytes, "unaligned middle batch despite a " \
                    "fitting aligned group"
        total_words = int(ends_np[stop - 1] - base_off) // 4
        row_off4 = ((row_ends[start:stop] - row_sizes[start:stop]
                     - base_off) // 4).astype(jnp.int32)
        out.append(emit(start, stop, total_words, row_off4,
                        row_ends[start:stop] - base_off))
        start = stop
    return out


@functools.partial(jax.jit, static_argnums=0)
def _from_rows_probe(vlayout: VarRowLayout, wire, row_off4):
    """Max string length per string column, stacked — one fetch."""
    base = vlayout.base
    outs = []
    for idx in vlayout.string_idx:
        slot_word = base.offsets[idx] // 4 + 1
        lens = jnp.take(wire, jnp.clip(row_off4 + slot_word, 0,
                                       max(wire.shape[0] - 1, 0)))
        outs.append(jnp.max(lens).astype(jnp.int64))
    return jnp.stack(outs)


def _convert_from_rows_var(rows: Column, schema: Sequence[DType]) -> Table:
    from .strings_common import from_padded_bytes
    vlayout = variable_width_layout(schema)
    base = vlayout.base
    child = rows.children[0]
    offs = jnp.asarray(rows.offsets, jnp.int64)
    n = offs.shape[0] - 1
    sizes = offs[1:] - offs[:-1]
    if n and int(jnp.sum(((sizes < base.row_size) |
                          (sizes % 8 != 0)).astype(jnp.int32))):
        raise ValueError(
            f"variable-width row blobs must be 8-byte aligned and at least "
            f"the fixed region ({base.row_size} B)")
    if child.data.dtype == jnp.uint32:
        wire = child.data
    else:
        wire = jax.lax.bitcast_convert_type(
            jnp.asarray(child.data, jnp.uint8).reshape(-1, 4), jnp.uint32)
    row_off4 = (offs[:-1] // 4).astype(jnp.int32)

    # ONE host sync sizes every padded string matrix (trace-stable align8
    # buckets) — the mirror of _var_probe on the to-rows side; per-column
    # fetches would pay one tunnel round trip each
    if n and vlayout.string_idx:
        maxes = np.asarray(_from_rows_probe(vlayout, wire, row_off4))
        swidths = [max(8, (int(mx) + 7) // 8 * 8) for mx in maxes]
    else:
        swidths = [8] * len(vlayout.string_idx)

    datas, masks, strings = _from_rows_var(vlayout, tuple(swidths), n,
                                           wire, row_off4)
    cols = []
    sk = 0
    for dt, d, m in zip(base.schema, datas, masks):
        if dt.is_string:
            smat, slen = strings[sk]
            sk += 1
            cols.append(from_padded_bytes(smat, slen, validity=m))
        else:
            cols.append(Column(dt, data=d, validity=m))
    return Table(cols)


# ---------------------------------------------------------------------------
# public API (mirrors RowConversion.java:101-121)
# ---------------------------------------------------------------------------

@traced("convert_to_rows")
def convert_to_rows(table: Table, max_batch_bytes: int = MAX_BATCH_BYTES) -> list[Column]:
    """Columnar table -> list of LIST<INT8> row-blob columns.

    Analog of ``RowConversion.convertToRows`` (RowConversion.java:101-108).
    Returns multiple columns when the packed output would exceed
    ``max_batch_bytes`` (reference row_conversion.cu:476-511).  Batch row
    counts are a multiple of 32 except possibly the last — a hard contract
    on both paths.  The fixed-width path raises when even one 32-row group
    exceeds ``max_batch_bytes``; the variable-width (STRING) path cuts a
    middle batch unaligned ONLY in that same oversized-group case (whenever
    at least one aligned group fits the budget, the cut is aligned).

    STRING columns produce variable-width rows under the UnsafeRow-style
    contract documented above ``VarRowLayout`` (the reference snapshot
    CUDF_FAILs here, row_conversion.cu:515).
    """
    if any(dt.is_string for dt in table.dtypes()):
        return _convert_to_rows_var(table, max_batch_bytes)
    layout = fixed_width_layout(table.dtypes())
    n = table.num_rows
    rows_per_batch = max(1, max_batch_bytes // layout.row_size)
    if rows_per_batch < n:
        if layout.row_size * BATCH_ROW_ALIGN > max_batch_bytes:
            # a 32-row-aligned batch would exceed the cap (and for the default
            # cap, overflow the int32 LIST offsets the format protects)
            raise ValueError(
                f"row size {layout.row_size} too large: a {BATCH_ROW_ALIGN}"
                f"-row aligned batch exceeds max_batch_bytes={max_batch_bytes}")
        rows_per_batch = rows_per_batch // BATCH_ROW_ALIGN * BATCH_ROW_ALIGN
    out = []
    start = 0
    while start < n or (n == 0 and not out):
        stop = min(n, start + rows_per_batch)
        datas = tuple(c.data[start:stop] for c in table.columns)
        masks = tuple(None if c.validity is None else c.validity[start:stop]
                      for c in table.columns)
        wire = _to_rows_wire_jit(layout, datas, masks)
        nb = stop - start
        offsets = jnp.arange(nb + 1, dtype=jnp.int32) * layout.row_size
        out.append(Column.list_(PackedByteColumn(INT8, data=wire), offsets))
        start = stop
        if n == 0:
            break
    return out


@traced("convert_from_rows")
def convert_from_rows(rows: Column, schema: Sequence[DType]) -> Table:
    """LIST<INT8> row blobs -> columnar table.

    Analog of ``RowConversion.convertFromRows`` (RowConversion.java:110-121);
    ``schema`` plays the role of the flattened (type-id, scale) pairs the Java
    layer marshals (RowConversion.java:113-118).
    """
    if rows.dtype.id != TypeId.LIST or not rows.children:
        raise TypeError("expected a LIST<INT8> row-blob column")
    child = rows.children[0]
    if child.dtype not in (INT8, UINT8):
        # parity with the INT8/UINT8 child guard (row_conversion.cu:525-528)
        raise TypeError(f"row blobs must be LIST<INT8>, child is {child.dtype!r}")
    if any(dt.is_string for dt in schema):
        return _convert_from_rows_var(rows, schema)
    layout = fixed_width_layout(schema)
    offs = np.asarray(rows.offsets)
    n = offs.shape[0] - 1
    widths = np.diff(offs)
    if n and not (widths == layout.row_size).all():
        # parity with the size cross-check (row_conversion.cu:537-542)
        raise ValueError(
            f"row width mismatch: blobs have {set(widths.tolist())} bytes/row, "
            f"schema packs to {layout.row_size}")
    if child.data.dtype == jnp.uint32:  # packed-word blob (convert_to_rows)
        datas, masks = _from_rows_wire_jit(layout, child.data, n)
    else:
        datas, masks = _from_rows_bytes(layout, jnp.asarray(child.data,
                                                            jnp.uint8))
    cols = [Column(dt, data=d, validity=m)
            for dt, d, m in zip(layout.schema, datas, masks)]
    return Table(cols)
