"""String ops over Arrow-layout STRING columns.

The compute form is the padded byte matrix (strings_common.py); results are
BOOL8/INT32 columns (predicates) or new STRING columns.  Character semantics
follow Spark: ``length``/``substring`` count UTF-8 characters, not bytes.

These are the building blocks the reference's RegexRewrite component lowers
regexes onto (startsWith/endsWith/contains — see regex_rewrite.py) plus the
string functions NDS queries need.  Predicates are fully jit-able; ops that
produce new STRING columns compact through the host at the API boundary
(XLA needs static shapes; inside fused pipelines keep the matrix form).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import INT32, BOOL8
from .strings_common import to_padded_bytes, from_padded_bytes

_I32 = jnp.int32


def _prop_valid(col: Column, extra=None):
    v = col.validity
    if extra is not None:
        v = extra if v is None else (v & extra)
    return v


def byte_length(col: Column) -> Column:
    """Byte length per row (jit-able straight off the offsets)."""
    offsets = jnp.asarray(col.offsets, _I32)
    return Column(INT32, data=offsets[1:] - offsets[:-1],
                  validity=_prop_valid(col))


def char_length(col: Column) -> Column:
    """Spark ``length()``: UTF-8 character count (continuation bytes excluded)."""
    mat, lengths = to_padded_bytes(col)
    in_str = jnp.arange(mat.shape[1], dtype=_I32)[None, :] < lengths[:, None]
    starts = ((mat & jnp.uint8(0xC0)) != jnp.uint8(0x80)) & in_str
    return Column(INT32, data=starts.sum(axis=1, dtype=_I32),
                  validity=_prop_valid(col))


def upper(col: Column) -> Column:
    """ASCII uppercase (multi-byte code points pass through unchanged)."""
    mat, lengths = to_padded_bytes(col)
    out = jnp.where((mat >= 97) & (mat <= 122), mat - 32, mat)
    return from_padded_bytes(out, lengths, _prop_valid(col))


def lower(col: Column) -> Column:
    """ASCII lowercase (multi-byte code points pass through unchanged)."""
    mat, lengths = to_padded_bytes(col)
    out = jnp.where((mat >= 65) & (mat <= 90), mat + 32, mat)
    return from_padded_bytes(out, lengths, _prop_valid(col))


# ---------------------------------------------------------------------------
# literal search predicates (the RegexRewrite lowering targets)
# ---------------------------------------------------------------------------

def _literal(pat) -> bytes:
    return pat.encode() if isinstance(pat, str) else bytes(pat)


@functools.partial(jax.jit, static_argnums=2)
def _match_positions(mat, lengths, pat: bytes):
    """bool[n, W]: window at shift s equals ``pat`` and fits in the row."""
    n, w = mat.shape
    if len(pat) == 0:
        fits = jnp.arange(w, dtype=_I32)[None, :] <= lengths[:, None]
        return fits
    padded = jnp.pad(mat, ((0, 0), (0, len(pat))))
    eq = jnp.ones((n, w), jnp.bool_)
    for i, b in enumerate(pat):
        eq = eq & (padded[:, i:i + w] == jnp.uint8(b))
    fits = (jnp.arange(w, dtype=_I32)[None, :]
            <= (lengths[:, None] - len(pat)))
    return eq & fits


def starts_with(col: Column, pat) -> Column:
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    hit = _match_positions(mat, lengths, pat)[:, 0] if mat.shape[1] else \
        jnp.zeros((len(col),), jnp.bool_)
    if len(pat) == 0:
        hit = jnp.ones((len(col),), jnp.bool_)
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def ends_with(col: Column, pat) -> Column:
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    if len(pat) == 0:
        hit = jnp.ones((len(col),), jnp.bool_)
    else:
        pos = _match_positions(mat, lengths, pat)
        tailpos = jnp.clip(lengths - len(pat), 0, mat.shape[1] - 1)
        hit = jnp.take_along_axis(pos, tailpos[:, None], axis=1)[:, 0]
        hit = hit & (lengths >= len(pat))
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def contains(col: Column, pat) -> Column:
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    if len(pat) == 0:
        hit = jnp.ones((len(col),), jnp.bool_)
    else:
        hit = _match_positions(mat, lengths, pat).any(axis=1)
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def equal(col: Column, other) -> Column:
    """Elementwise ``==`` against a python string or another STRING column.

    The kernel the interpreted Filter path lowers ``==``/``!=`` predicates
    over STRING columns onto (executor._eval_expr) — raw ``col.data`` is a
    chars buffer, so the generic jnp comparison is meaningless for strings.
    """
    mat, lengths = to_padded_bytes(col)
    if isinstance(other, Column):
        omat, olengths = to_padded_bytes(other)
        w = max(mat.shape[1], omat.shape[1])
        if mat.shape[1] < w:
            mat = jnp.pad(mat, ((0, 0), (0, w - mat.shape[1])))
        if omat.shape[1] < w:
            omat = jnp.pad(omat, ((0, 0), (0, w - omat.shape[1])))
        in_str = jnp.arange(w, dtype=_I32)[None, :] < lengths[:, None]
        hit = (lengths == olengths) & \
            jnp.where(in_str, mat == omat, True).all(axis=1)
        return Column(BOOL8, data=hit.astype(jnp.uint8),
                      validity=_prop_valid(col, other.validity))
    pat = _literal(other)
    if len(pat) == 0:
        hit = lengths == 0
    elif len(pat) > mat.shape[1]:
        hit = jnp.zeros((len(col),), jnp.bool_)
    else:
        target = jnp.asarray(np.frombuffer(pat, np.uint8))
        hit = (lengths == len(pat)) & \
            (mat[:, :len(pat)] == target).all(axis=1)
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def find(col: Column, pat) -> Column:
    """First byte index of ``pat`` per row, -1 when absent (cudf find())."""
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    pos = _match_positions(mat, lengths, pat)
    first = jnp.argmax(pos, axis=1).astype(_I32)
    found = pos.any(axis=1)
    idx = jnp.where(found, first, _I32(-1))
    if len(pat) == 0:
        idx = jnp.zeros((len(col),), _I32)
    return Column(INT32, data=idx, validity=_prop_valid(col))


# ---------------------------------------------------------------------------
# substring (character-based, Spark semantics)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2, 3))
def _substring_matrix(mat, lengths, start: int, length: int | None):
    n, w = mat.shape
    in_str = jnp.arange(w, dtype=_I32)[None, :] < lengths[:, None]
    is_start = ((mat & jnp.uint8(0xC0)) != jnp.uint8(0x80)) & in_str
    nchars = is_start.sum(axis=1, dtype=_I32)
    # byte offset of each character: scatter byte positions into char slots
    char_no = jnp.cumsum(is_start, axis=1, dtype=_I32) - 1
    char_no = jnp.where(is_start, char_no, w)  # park non-starts in a spare slot
    bytepos = jnp.broadcast_to(jnp.arange(w, dtype=_I32)[None, :], (n, w))
    rows = jnp.broadcast_to(jnp.arange(n, dtype=_I32)[:, None], (n, w))
    char_byte = jnp.full((n, w + 1), 0, _I32)
    char_byte = char_byte.at[rows, char_no].set(bytepos, mode="drop")
    # char index c >= nchars maps to the row's byte length
    cidx = jnp.arange(w + 1, dtype=_I32)[None, :]
    char_byte = jnp.where(cidx >= nchars[:, None], lengths[:, None], char_byte)

    # Spark substring: 1-based, 0 treated as 1, negative counts from the end
    if start > 0:
        first_char = jnp.full((n,), start - 1, _I32)
    elif start == 0:
        first_char = jnp.zeros((n,), _I32)
    else:
        first_char = jnp.maximum(nchars + start, 0)
    first_char = jnp.minimum(first_char, nchars)
    if length is None:
        last_char = nchars
    else:
        last_char = jnp.minimum(first_char + max(length, 0), nchars)

    sb = jnp.take_along_axis(char_byte, first_char[:, None], axis=1)[:, 0]
    eb = jnp.take_along_axis(char_byte, last_char[:, None], axis=1)[:, 0]
    out_len = eb - sb
    idx = sb[:, None] + jnp.arange(w, dtype=_I32)[None, :]
    gathered = jnp.take_along_axis(
        jnp.pad(mat, ((0, 0), (0, 1))), jnp.clip(idx, 0, w), axis=1)
    keep = jnp.arange(w, dtype=_I32)[None, :] < out_len[:, None]
    return jnp.where(keep, gathered, jnp.uint8(0)), out_len


def substring(col: Column, start: int, length: int | None = None) -> Column:
    """Spark ``substring(str, pos[, len])`` — character-based."""
    mat, lengths = to_padded_bytes(col)
    out, out_len = _substring_matrix(mat, lengths, int(start),
                                     None if length is None else int(length))
    return from_padded_bytes(out, out_len, _prop_valid(col))


def concat_padded(mats, lens, valids=None):
    """Jit-able Spark ``concat`` over padded byte matrices.

    Each input row scatters at its running start offset into an output of
    static width sum(w_k); dead lanes route to an out-of-bounds column and
    drop.  Returns (u8[n, W] matrix, lengths, valid) — null if any input
    row is null.
    """
    n = mats[0].shape[0]
    W = int(sum(m.shape[1] for m in mats))
    out = jnp.zeros((n, W), jnp.uint8)
    pos = jnp.zeros((n,), _I32)
    rows = jnp.arange(n, dtype=_I32)[:, None]
    for m, l in zip(mats, lens):
        w = m.shape[1]
        lane = jnp.arange(w, dtype=_I32)
        tgt = pos[:, None] + lane[None, :]
        tgt = jnp.where(lane[None, :] < l[:, None], tgt, W)  # dead -> drop
        out = out.at[jnp.broadcast_to(rows, (n, w)), tgt].set(m, mode="drop")
        pos = pos + l.astype(_I32)
    valid = None
    if valids is not None:
        for v in valids:
            if v is not None:
                valid = v if valid is None else (valid & v)
    return out, pos, valid


def concat(*cols: Column) -> Column:
    """Spark ``concat``: null if any input is null.  The scatter runs on
    device (concat_padded); only the Arrow materialization is host-side."""
    mats, lens, valids = [], [], []
    for c in cols:
        m, l = to_padded_bytes(c)
        mats.append(m)
        lens.append(l)
        valids.append(c.validity)
    out, out_len, valid = concat_padded(mats, lens, valids)
    if valid is not None and bool(valid.all()):
        valid = None
    return from_padded_bytes(out, out_len, valid)


# ---------------------------------------------------------------------------
# replace / split: greedy non-overlapping literal matches, vectorized
# ---------------------------------------------------------------------------


def _greedy_matches(pos, L: int):
    """Left-to-right non-overlapping selection of candidate starts.

    ``pos`` is bool[n, w] candidate match starts; a start is active iff no
    active start began within the previous L-1 bytes (Spark/cudf replace
    semantics).  One lax.scan over the width, vectorized across rows."""
    if L <= 1:
        return pos
    n, w = pos.shape

    def step(cool, x):
        can = (cool == 0) & x
        cool = jnp.where(can, _I32(L - 1),
                         jnp.maximum(cool - 1, 0))
        return cool, can

    _, act = jax.lax.scan(step, jnp.zeros((n,), _I32), pos.T)
    return act.T


@functools.partial(jax.jit, static_argnums=(2, 3))
def _replace_matrix(mat, lengths, pat: bytes, rep: bytes):
    """(out matrix, out lengths) for literal replace-all."""
    n, w = mat.shape
    L, R = len(pat), len(rep)
    act = _greedy_matches(_match_positions(mat, lengths, pat), L)
    c = jnp.cumsum(act, axis=1, dtype=_I32)          # inclusive active count
    count = c[:, -1] if w else jnp.zeros((n,), _I32)
    # covered[j]: byte j belongs to a match  (an active start in (j-L, j])
    cpad = jnp.pad(c, ((0, 0), (L, 0)))
    covered = (c - cpad[:, :w]) > 0
    # prior_ended[j]: matches fully before byte j  (starts at p <= j - L)
    prior = cpad[:, :w]
    W = w + (w // max(L, 1)) * max(R - L, 0)
    out = jnp.zeros((n, W), jnp.uint8)
    rows = jnp.arange(n, dtype=_I32)[:, None]
    j = jnp.arange(w, dtype=_I32)[None, :]
    in_str = j < lengths[:, None]
    # pass 1: keep bytes outside matches, shifted by earlier size deltas
    tgt = j + prior * (R - L)
    tgt = jnp.where(in_str & ~covered, tgt, W)       # dead lanes drop
    out = out.at[jnp.broadcast_to(rows, (n, w)),
                 jnp.clip(tgt, 0, W)].set(mat, mode="drop")
    # pass 2: write the replacement at each active start's shifted position
    start_out = j + (c - 1) * (R - L)
    for r, b in enumerate(rep):
        tr = jnp.where(act, start_out + r, W)
        out = out.at[jnp.broadcast_to(rows, (n, w)),
                     jnp.clip(tr, 0, W)].set(jnp.uint8(b), mode="drop")
    out_len = lengths + count * (R - L)
    return out, out_len


def replace(col: Column, search, replacement) -> Column:
    """Spark ``replace(str, search, replace)``: all non-overlapping literal
    occurrences, left to right.  Empty search returns the input unchanged
    (Spark semantics)."""
    pat = _literal(search)
    rep = _literal(replacement)
    if len(pat) == 0:
        return col
    mat, lengths = to_padded_bytes(col)
    out, out_len = _replace_matrix(mat, lengths, pat, rep)
    return from_padded_bytes(out, out_len, _prop_valid(col))


@functools.partial(jax.jit, static_argnums=2)
def _delim_layout(mat, lengths, delim: bytes):
    """(active starts, inclusive count cumsum, total count) for a delimiter."""
    act = _greedy_matches(_match_positions(mat, lengths, delim), len(delim))
    c = jnp.cumsum(act, axis=1, dtype=_I32)
    total = c[:, -1] if mat.shape[1] else jnp.zeros(mat.shape[:1], _I32)
    return act, c, total


def split_part(col: Column, delim, index: int) -> Column:
    """Spark ``split_part(str, delim, partNum)``: 1-based; negative counts
    from the end; 0 is an error.  Out-of-range parts are empty strings;
    the delimiter is a literal."""
    d = _literal(delim)
    if len(d) == 0 or index == 0:
        raise ValueError("split_part needs a non-empty delimiter and a "
                         "non-zero part number (negative counts from "
                         "the end)")
    mat, lengths = to_padded_bytes(col)
    n, w = mat.shape
    act, c, total = _delim_layout(mat, lengths, d)
    # 0-based part number per row; rows have total+1 parts
    if index > 0:
        k = jnp.full((n,), index - 1, _I32)
    else:
        k = total + 1 + index  # may go negative -> out of range
    j = jnp.arange(w, dtype=_I32)[None, :]
    # start byte of part k: 0, or end of the k-th delimiter; end byte:
    # start of the (k+1)-th delimiter or row length
    def nth_start(m):
        """Byte position of the (m+1)-th active delimiter per row."""
        hit = act & (c == m[:, None] + 1)
        anyhit = hit.any(axis=1)
        p = jnp.argmax(hit, axis=1).astype(_I32)
        return jnp.where(anyhit, p, lengths), anyhit
    p, prev_ok = nth_start(k - 1)
    sb = jnp.where(k > 0, jnp.where(prev_ok, p + len(d), lengths),
                   jnp.int32(0))
    ok = (k == 0) | (prev_ok & (k > 0))
    e, e_ok = nth_start(k)
    eb = jnp.where(e_ok, e, lengths)
    have = ok & (k >= 0) & (sb <= lengths)
    out_len = jnp.where(have, jnp.maximum(eb - sb, 0), 0)
    idx = sb[:, None] + j
    gathered = jnp.take_along_axis(
        jnp.pad(mat, ((0, 0), (0, 1))), jnp.clip(idx, 0, w), axis=1)
    keep = j < out_len[:, None]
    return from_padded_bytes(jnp.where(keep, gathered, jnp.uint8(0)),
                             out_len, _prop_valid(col))


def split(col: Column, delim) -> Column:
    """Spark ``split(str, delim)`` with a literal delimiter -> LIST<STRING>.

    Match positions and part boundaries are computed on device; the ragged
    LIST<STRING> materialization happens at the host boundary like every
    other ragged producer in the engine."""
    d = _literal(delim)
    if len(d) == 0:
        raise ValueError("split needs a non-empty delimiter")
    mat, lengths = to_padded_bytes(col)
    n, w = mat.shape
    act, c, total = _delim_layout(mat, lengths, d)
    act_np = np.asarray(act)
    len_np = np.asarray(lengths).astype(np.int64)
    mat_np = np.asarray(mat)
    total_np = np.asarray(total).astype(np.int64)
    nparts_row = total_np + 1
    if col.validity is not None:
        # null rows get EMPTY list ranges (the engine-wide Arrow
        # convention), not a phantom one-part list; their delimiter hits
        # and lengths are zeroed so starts_d stays aligned with the
        # non-first parts below
        vnp = np.asarray(col.validity)
        nparts_row[~vnp] = 0
        act_np = act_np.copy()
        act_np[~vnp] = False
        len_np = len_np.copy()
        len_np[~vnp] = 0
    loffsets = np.zeros(n + 1, np.int64)
    np.cumsum(nparts_row, out=loffsets[1:])
    # vectorized part boundaries: delimiter starts (row-major order) split
    # each row into parts; a part's bytes are [prev_end, start), the last
    # part ends at the row length.  No per-part Python loop.
    rows_d, starts_d = np.nonzero(act_np)        # in row-major order
    nparts = int(loffsets[-1])
    part_row = np.repeat(np.arange(n), nparts_row)
    nonempty = nparts_row > 0                    # null rows have no parts
    first = np.zeros(nparts, np.bool_)
    first[loffsets[:-1][nonempty]] = True
    part_start = np.zeros(nparts, np.int64)
    part_end = np.empty(nparts, np.int64)
    # parts after a delimiter start at delim_pos + len(d); each row's
    # non-first parts align with its delimiters in order
    part_start[~first] = starts_d + len(d)
    part_end[:] = len_np[part_row]
    # non-last parts end at their delimiter's position
    last = np.zeros(nparts, np.bool_)
    last[loffsets[1:][nonempty] - 1] = True
    part_end[~last] = starts_d
    plens = np.maximum(part_end - part_start, 0)
    offsets = np.zeros(nparts + 1, np.int64)
    np.cumsum(plens, out=offsets[1:])
    if offsets[-1] > np.iinfo(np.int32).max:
        raise OverflowError("split output exceeds int32 char offsets")
    # one fancy-indexed gather for all part bytes
    byte_row = np.repeat(part_row, plens)
    byte_col = np.repeat(part_start, plens) + \
        np.arange(int(offsets[-1])) - np.repeat(offsets[:-1], plens)
    chars = mat_np[byte_row, byte_col] if byte_row.size else \
        np.zeros(0, np.uint8)
    child = Column.string(jnp.asarray(chars), offsets.astype(np.int32))
    return Column.list_(child, loffsets.astype(np.int32),
                        validity=_prop_valid(col))


# ---------------------------------------------------------------------------
# trim / pad
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _trim_matrix(mat, lengths, trimset: bytes, left: bool, right: bool):
    n, w = mat.shape
    j = jnp.arange(w, dtype=_I32)[None, :]
    in_str = j < lengths[:, None]
    is_t = jnp.zeros((n, w), jnp.bool_)
    for b in trimset:
        is_t = is_t | (mat == jnp.uint8(b))
    is_t = is_t & in_str
    if left:
        lead = jnp.cumprod(is_t, axis=1, dtype=jnp.int32).sum(
            axis=1, dtype=_I32)
    else:
        lead = jnp.zeros((n,), _I32)
    if right:
        tail_t = is_t | ~in_str  # padding counts as trimmable from the right
        trail = jnp.cumprod(tail_t[:, ::-1], axis=1, dtype=jnp.int32).sum(
            axis=1, dtype=_I32) - (w - lengths)
        trail = jnp.maximum(trail, 0)
    else:
        trail = jnp.zeros((n,), _I32)
    out_len = jnp.maximum(lengths - lead - trail, 0)
    idx = lead[:, None] + j
    gathered = jnp.take_along_axis(
        jnp.pad(mat, ((0, 0), (0, 1))), jnp.clip(idx, 0, w), axis=1)
    keep = j < out_len[:, None]
    return jnp.where(keep, gathered, jnp.uint8(0)), out_len


def _trim(col: Column, chars, left: bool, right: bool) -> Column:
    if chars == "" or (isinstance(chars, (bytes, bytearray))
                       and len(chars) == 0):
        return col  # Spark: TRIM('' FROM s) is a no-op
    trimset = chars.encode() if isinstance(chars, str) else \
        b" " if chars is None else bytes(chars)
    if any(b >= 0x80 for b in trimset):
        # the match is byte-wise; a multi-byte trim character would strip
        # individual UTF-8 bytes and corrupt the row
        raise ValueError("only ASCII trim characters are supported")
    mat, lengths = to_padded_bytes(col)
    out, out_len = _trim_matrix(mat, lengths, trimset, left, right)
    return from_padded_bytes(out, out_len, _prop_valid(col))


def trim(col: Column, chars: str | None = None) -> Column:
    """Spark ``trim``: strip leading+trailing characters (default space).

    The trim set must be ASCII (byte-wise matching); an empty trim set is a
    no-op as in Spark."""
    return _trim(col, chars, True, True)


def ltrim(col: Column, chars: str | None = None) -> Column:
    return _trim(col, chars, True, False)


def rtrim(col: Column, chars: str | None = None) -> Column:
    return _trim(col, chars, False, True)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _pad_matrix(mat, lengths, width: int, pad: bytes, left: bool):
    n, w = mat.shape
    j = jnp.arange(w, dtype=_I32)[None, :]
    in_str = j < lengths[:, None]
    starts = ((mat & jnp.uint8(0xC0)) != jnp.uint8(0x80)) & in_str
    nchars = starts.sum(axis=1, dtype=_I32)
    pad_count = jnp.clip(width - nchars, 0, width)
    lane = jnp.arange(width, dtype=_I32)
    cyc = np.frombuffer(bytes(pad[i % len(pad)] for i in range(width)),
                        np.uint8) if width else np.zeros(0, np.uint8)
    padmat = jnp.where(lane[None, :] < pad_count[:, None],
                       jnp.asarray(cyc)[None, :], jnp.uint8(0))
    tmat, tlen = _substring_matrix(mat, lengths, 1, width)  # <= width chars
    if left:
        out, out_len, _ = concat_padded([padmat, tmat], [pad_count, tlen])
    else:
        out, out_len, _ = concat_padded([tmat, padmat], [tlen, pad_count])
    return out, out_len


def _pad(col: Column, width: int, pad: str, left: bool) -> Column:
    pb = pad.encode()
    if not pb:
        raise ValueError("pad string must be non-empty")
    if any(b >= 0x80 for b in pb):
        raise ValueError("only ASCII pad strings are supported")
    mat, lengths = to_padded_bytes(col)
    out, out_len = _pad_matrix(mat, lengths, int(width), pb, left)
    return from_padded_bytes(out, out_len, _prop_valid(col))


def lpad(col: Column, width: int, pad: str = " ") -> Column:
    """Spark ``lpad``: left-pad (cycling ``pad``) to ``width`` characters;
    longer strings truncate to the first ``width`` characters."""
    return _pad(col, width, pad, True)


def rpad(col: Column, width: int, pad: str = " ") -> Column:
    return _pad(col, width, pad, False)


# ---------------------------------------------------------------------------
# SQL LIKE (%, _) — dynamic-programming match over the byte matrix
# ---------------------------------------------------------------------------

def _parse_like(pattern: str, escape: str = "\\"):
    toks = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            toks.append(("lit", pattern[i + 1].encode()))
            i += 2
        elif ch == "%":
            toks.append(("any", None))
            i += 1
        elif ch == "_":
            toks.append(("one", None))
            i += 1
        else:
            toks.append(("lit", ch.encode()))
            i += 1
    return tuple(toks)


def like(col: Column, pattern: str, escape: str = "\\") -> Column:
    """SQL LIKE — NFA over byte positions, one vectorized step per token.

    Note: ``_`` matches one *byte* here; multi-byte UTF-8 characters under
    ``_`` are a known divergence (cudf's like is byte-based too).
    """
    toks = _parse_like(pattern, escape)
    mat, lengths = to_padded_bytes(col)
    n, w = mat.shape
    # reach[i, j] — pattern prefix consumed exactly j bytes of row i
    reach = (jnp.arange(w + 1, dtype=_I32)[None, :] == 0)
    reach = jnp.broadcast_to(reach, (n, w + 1))
    inb = jnp.arange(w, dtype=_I32)[None, :] < lengths[:, None]
    for kind, lit in toks:
        if kind == "lit":
            for b in lit:  # multi-byte UTF-8 pattern chars consume per byte
                step = reach[:, :-1] & (mat == jnp.uint8(b)) & inb
                reach = jnp.pad(step, ((0, 0), (1, 0)))
        elif kind == "one":
            step = reach[:, :-1] & inb
            reach = jnp.pad(step, ((0, 0), (1, 0)))
        else:  # '%' — consume any number of bytes: prefix-or to the right
            reach = jax.lax.associative_scan(jnp.logical_or, reach, axis=1)
    hit = jnp.take_along_axis(reach, lengths[:, None], axis=1)[:, 0]
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))
