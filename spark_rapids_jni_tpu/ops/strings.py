"""String ops over Arrow-layout STRING columns.

The compute form is the padded byte matrix (strings_common.py); results are
BOOL8/INT32 columns (predicates) or new STRING columns.  Character semantics
follow Spark: ``length``/``substring`` count UTF-8 characters, not bytes.

These are the building blocks the reference's RegexRewrite component lowers
regexes onto (startsWith/endsWith/contains — see regex_rewrite.py) plus the
string functions NDS queries need.  Predicates are fully jit-able; ops that
produce new STRING columns compact through the host at the API boundary
(XLA needs static shapes; inside fused pipelines keep the matrix form).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import INT32, BOOL8
from .strings_common import to_padded_bytes, from_padded_bytes

_I32 = jnp.int32


def _prop_valid(col: Column, extra=None):
    v = col.validity
    if extra is not None:
        v = extra if v is None else (v & extra)
    return v


def byte_length(col: Column) -> Column:
    """Byte length per row (jit-able straight off the offsets)."""
    offsets = jnp.asarray(col.offsets, _I32)
    return Column(INT32, data=offsets[1:] - offsets[:-1],
                  validity=_prop_valid(col))


def char_length(col: Column) -> Column:
    """Spark ``length()``: UTF-8 character count (continuation bytes excluded)."""
    mat, lengths = to_padded_bytes(col)
    in_str = jnp.arange(mat.shape[1], dtype=_I32)[None, :] < lengths[:, None]
    starts = ((mat & jnp.uint8(0xC0)) != jnp.uint8(0x80)) & in_str
    return Column(INT32, data=starts.sum(axis=1, dtype=_I32),
                  validity=_prop_valid(col))


def upper(col: Column) -> Column:
    """ASCII uppercase (multi-byte code points pass through unchanged)."""
    mat, lengths = to_padded_bytes(col)
    out = jnp.where((mat >= 97) & (mat <= 122), mat - 32, mat)
    return from_padded_bytes(out, lengths, _prop_valid(col))


def lower(col: Column) -> Column:
    """ASCII lowercase (multi-byte code points pass through unchanged)."""
    mat, lengths = to_padded_bytes(col)
    out = jnp.where((mat >= 65) & (mat <= 90), mat + 32, mat)
    return from_padded_bytes(out, lengths, _prop_valid(col))


# ---------------------------------------------------------------------------
# literal search predicates (the RegexRewrite lowering targets)
# ---------------------------------------------------------------------------

def _literal(pat) -> bytes:
    return pat.encode() if isinstance(pat, str) else bytes(pat)


@functools.partial(jax.jit, static_argnums=2)
def _match_positions(mat, lengths, pat: bytes):
    """bool[n, W]: window at shift s equals ``pat`` and fits in the row."""
    n, w = mat.shape
    if len(pat) == 0:
        fits = jnp.arange(w, dtype=_I32)[None, :] <= lengths[:, None]
        return fits
    padded = jnp.pad(mat, ((0, 0), (0, len(pat))))
    eq = jnp.ones((n, w), jnp.bool_)
    for i, b in enumerate(pat):
        eq = eq & (padded[:, i:i + w] == jnp.uint8(b))
    fits = (jnp.arange(w, dtype=_I32)[None, :]
            <= (lengths[:, None] - len(pat)))
    return eq & fits


def starts_with(col: Column, pat) -> Column:
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    hit = _match_positions(mat, lengths, pat)[:, 0] if mat.shape[1] else \
        jnp.zeros((len(col),), jnp.bool_)
    if len(pat) == 0:
        hit = jnp.ones((len(col),), jnp.bool_)
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def ends_with(col: Column, pat) -> Column:
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    if len(pat) == 0:
        hit = jnp.ones((len(col),), jnp.bool_)
    else:
        pos = _match_positions(mat, lengths, pat)
        tailpos = jnp.clip(lengths - len(pat), 0, mat.shape[1] - 1)
        hit = jnp.take_along_axis(pos, tailpos[:, None], axis=1)[:, 0]
        hit = hit & (lengths >= len(pat))
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def contains(col: Column, pat) -> Column:
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    if len(pat) == 0:
        hit = jnp.ones((len(col),), jnp.bool_)
    else:
        hit = _match_positions(mat, lengths, pat).any(axis=1)
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))


def find(col: Column, pat) -> Column:
    """First byte index of ``pat`` per row, -1 when absent (cudf find())."""
    pat = _literal(pat)
    mat, lengths = to_padded_bytes(col)
    pos = _match_positions(mat, lengths, pat)
    first = jnp.argmax(pos, axis=1).astype(_I32)
    found = pos.any(axis=1)
    idx = jnp.where(found, first, _I32(-1))
    if len(pat) == 0:
        idx = jnp.zeros((len(col),), _I32)
    return Column(INT32, data=idx, validity=_prop_valid(col))


# ---------------------------------------------------------------------------
# substring (character-based, Spark semantics)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2, 3))
def _substring_matrix(mat, lengths, start: int, length: int | None):
    n, w = mat.shape
    in_str = jnp.arange(w, dtype=_I32)[None, :] < lengths[:, None]
    is_start = ((mat & jnp.uint8(0xC0)) != jnp.uint8(0x80)) & in_str
    nchars = is_start.sum(axis=1, dtype=_I32)
    # byte offset of each character: scatter byte positions into char slots
    char_no = jnp.cumsum(is_start, axis=1, dtype=_I32) - 1
    char_no = jnp.where(is_start, char_no, w)  # park non-starts in a spare slot
    bytepos = jnp.broadcast_to(jnp.arange(w, dtype=_I32)[None, :], (n, w))
    rows = jnp.broadcast_to(jnp.arange(n, dtype=_I32)[:, None], (n, w))
    char_byte = jnp.full((n, w + 1), 0, _I32)
    char_byte = char_byte.at[rows, char_no].set(bytepos, mode="drop")
    # char index c >= nchars maps to the row's byte length
    cidx = jnp.arange(w + 1, dtype=_I32)[None, :]
    char_byte = jnp.where(cidx >= nchars[:, None], lengths[:, None], char_byte)

    # Spark substring: 1-based, 0 treated as 1, negative counts from the end
    if start > 0:
        first_char = jnp.full((n,), start - 1, _I32)
    elif start == 0:
        first_char = jnp.zeros((n,), _I32)
    else:
        first_char = jnp.maximum(nchars + start, 0)
    first_char = jnp.minimum(first_char, nchars)
    if length is None:
        last_char = nchars
    else:
        last_char = jnp.minimum(first_char + max(length, 0), nchars)

    sb = jnp.take_along_axis(char_byte, first_char[:, None], axis=1)[:, 0]
    eb = jnp.take_along_axis(char_byte, last_char[:, None], axis=1)[:, 0]
    out_len = eb - sb
    idx = sb[:, None] + jnp.arange(w, dtype=_I32)[None, :]
    gathered = jnp.take_along_axis(
        jnp.pad(mat, ((0, 0), (0, 1))), jnp.clip(idx, 0, w), axis=1)
    keep = jnp.arange(w, dtype=_I32)[None, :] < out_len[:, None]
    return jnp.where(keep, gathered, jnp.uint8(0)), out_len


def substring(col: Column, start: int, length: int | None = None) -> Column:
    """Spark ``substring(str, pos[, len])`` — character-based."""
    mat, lengths = to_padded_bytes(col)
    out, out_len = _substring_matrix(mat, lengths, int(start),
                                     None if length is None else int(length))
    return from_padded_bytes(out, out_len, _prop_valid(col))


def concat_padded(mats, lens, valids=None):
    """Jit-able Spark ``concat`` over padded byte matrices.

    Each input row scatters at its running start offset into an output of
    static width sum(w_k); dead lanes route to an out-of-bounds column and
    drop.  Returns (u8[n, W] matrix, lengths, valid) — null if any input
    row is null.
    """
    n = mats[0].shape[0]
    W = int(sum(m.shape[1] for m in mats))
    out = jnp.zeros((n, W), jnp.uint8)
    pos = jnp.zeros((n,), _I32)
    rows = jnp.arange(n, dtype=_I32)[:, None]
    for m, l in zip(mats, lens):
        w = m.shape[1]
        lane = jnp.arange(w, dtype=_I32)
        tgt = pos[:, None] + lane[None, :]
        tgt = jnp.where(lane[None, :] < l[:, None], tgt, W)  # dead -> drop
        out = out.at[jnp.broadcast_to(rows, (n, w)), tgt].set(m, mode="drop")
        pos = pos + l.astype(_I32)
    valid = None
    if valids is not None:
        for v in valids:
            if v is not None:
                valid = v if valid is None else (valid & v)
    return out, pos, valid


def concat(*cols: Column) -> Column:
    """Spark ``concat``: null if any input is null.  The scatter runs on
    device (concat_padded); only the Arrow materialization is host-side."""
    mats, lens, valids = [], [], []
    for c in cols:
        m, l = to_padded_bytes(c)
        mats.append(m)
        lens.append(l)
        valids.append(c.validity)
    out, out_len, valid = concat_padded(mats, lens, valids)
    if valid is not None and bool(valid.all()):
        valid = None
    return from_padded_bytes(out, out_len, valid)


# ---------------------------------------------------------------------------
# SQL LIKE (%, _) — dynamic-programming match over the byte matrix
# ---------------------------------------------------------------------------

def _parse_like(pattern: str, escape: str = "\\"):
    toks = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            toks.append(("lit", pattern[i + 1].encode()))
            i += 2
        elif ch == "%":
            toks.append(("any", None))
            i += 1
        elif ch == "_":
            toks.append(("one", None))
            i += 1
        else:
            toks.append(("lit", ch.encode()))
            i += 1
    return tuple(toks)


def like(col: Column, pattern: str, escape: str = "\\") -> Column:
    """SQL LIKE — NFA over byte positions, one vectorized step per token.

    Note: ``_`` matches one *byte* here; multi-byte UTF-8 characters under
    ``_`` are a known divergence (cudf's like is byte-based too).
    """
    toks = _parse_like(pattern, escape)
    mat, lengths = to_padded_bytes(col)
    n, w = mat.shape
    # reach[i, j] — pattern prefix consumed exactly j bytes of row i
    reach = (jnp.arange(w + 1, dtype=_I32)[None, :] == 0)
    reach = jnp.broadcast_to(reach, (n, w + 1))
    inb = jnp.arange(w, dtype=_I32)[None, :] < lengths[:, None]
    for kind, lit in toks:
        if kind == "lit":
            for b in lit:  # multi-byte UTF-8 pattern chars consume per byte
                step = reach[:, :-1] & (mat == jnp.uint8(b)) & inb
                reach = jnp.pad(step, ((0, 0), (1, 0)))
        elif kind == "one":
            step = reach[:, :-1] & inb
            reach = jnp.pad(step, ((0, 0), (1, 0)))
        else:  # '%' — consume any number of bytes: prefix-or to the right
            reach = jax.lax.associative_scan(jnp.logical_or, reach, axis=1)
    hit = jnp.take_along_axis(reach, lengths[:, None], axis=1)[:, 0]
    return Column(BOOL8, data=hit.astype(jnp.uint8), validity=_prop_valid(col))
