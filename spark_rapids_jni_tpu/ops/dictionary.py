"""Dictionary encoding: STRING column <-> (int32 codes, distinct-value dict).

TPU-native analog of cudf's DICTIONARY32 columns (dtypes.py TypeId mirrors
the id) — the form string *keys* take to cross the device mesh: codes are
plain INT32 rows that shard/shuffle/aggregate like any fixed-width column,
while the dictionary (small, distinct values only) replicates host-side.
Spark's GpuShuffle does the same densification for high-cardinality string
keys; Parquet stores most string columns dictionary-encoded already.

Encoding is sort-based like the groupby (ops/aggregate.py): lexsort the
order-preserving key words, segment at value boundaries, code = segment id.
Codes are ordinal — c1 < c2 iff value1 < value2 — so ORDER BY on codes
equals ORDER BY on the strings (a property cudf dictionaries share).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column
from ..dtypes import INT32
from .order import SortKey, encode_keys, rows_differ_from_prev
from .selection import nonzero_indices, gather_column

_I32 = jnp.int32


def dictionary_encode(col: Column):
    """(codes: INT32 Column, dictionary: Column of distinct non-null values).

    Null rows get a null code (validity carries over); the dictionary holds
    only non-null distinct values in ascending order.  Works for any
    sortable column type; the headline use is STRING.
    """
    n = col.size
    if n == 0:
        return (Column(INT32, data=jnp.zeros((0,), _I32),
                       validity=col.validity), gather_column(col, jnp.zeros((0,), _I32)))
    words = encode_keys([SortKey(col)])  # null flag word first when nullable
    order = jnp.lexsort(tuple(reversed(words)))
    bounds = rows_differ_from_prev(words, order)
    seg = jnp.cumsum(bounds.astype(_I32)) - 1
    seg_of_row = jnp.zeros((n,), _I32).at[order].set(seg)

    has_nulls = col.validity is not None and bool(
        jnp.logical_not(col.validity).any())
    if has_nulls:
        # nulls sort first (asc default) as segment 0: shift codes down and
        # exclude the null segment from the dictionary
        codes = seg_of_row - 1
        rep_positions = nonzero_indices(bounds)[1:]
    else:
        codes = seg_of_row
        rep_positions = nonzero_indices(bounds)
    reps = jnp.take(order, rep_positions).astype(_I32)
    dictionary = gather_column(col, reps)
    # dictionary rows are non-null by construction
    dictionary = dictionary.with_validity(None)
    return Column(INT32, data=codes, validity=col.validity), dictionary


def dictionary_decode(codes: Column, dictionary: Column) -> Column:
    """Inverse of dictionary_encode: gather dictionary rows by code."""
    idx = jnp.asarray(codes.data, _I32)
    return gather_column(dictionary, idx, indices_valid=codes.validity)
