"""RegexRewrite: lower simple regex patterns onto literal string predicates.

TPU-native equivalent of the reference's RegexRewrite component (named in
BASELINE.json's north-star set; Java side appears post-snapshot as
RegexRewriteUtils).  Its job in spark-rapids is to recognize regex patterns
that are really literal prefix/suffix/contains tests and dispatch them to fast
non-regex kernels instead of a regex engine.  We implement the same contract:

    rewrite(pattern)            -> ("startswith"|"endswith"|"contains"|"equals",
                                    literal) or None
    regex_matches(col, pattern) -> BOOL8 column, raising ValueError for
                                   patterns outside the rewritable subset
                                   (a general TPU regex engine is out of scope,
                                   exactly as it is for the reference kernels).

Recognized shapes (anchors + literal + unbounded wildcards only):
    ^lit$   -> equals        ^lit / ^lit.*  -> startswith
    lit$ / .*lit$ -> endswith    lit / .*lit.* -> contains
Escaped metacharacters (\\.) inside the literal are unescaped.
"""

from __future__ import annotations

from ..columnar import Column
from ..dtypes import BOOL8
from . import strings as _s

_META = set(".^$*+?()[]{}|\\")


def _scan_literal(pattern: str, i: int) -> tuple[str, int]:
    """Longest literal run starting at i; handles backslash escapes."""
    out = []
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern) and pattern[i + 1] in _META:
            out.append(pattern[i + 1])
            i += 2
        elif ch in _META:
            break
        else:
            out.append(ch)
            i += 1
    return "".join(out), i


def rewrite(pattern: str):
    """Classify ``pattern``; return (kind, literal) or None if not rewritable."""
    i, n = 0, len(pattern)
    anchored_start = i < n and pattern[i] == "^"
    if anchored_start:
        i += 1
    if pattern.startswith(".*", i):
        i += 2
        anchored_start = False  # ^.*lit == .*lit
    lit, i = _scan_literal(pattern, i)
    trailing_any = False
    if pattern.startswith(".*", i):
        i += 2
        trailing_any = True
    anchored_end = i < n and pattern[i] == "$"
    if anchored_end:
        i += 1
        if trailing_any:
            anchored_end = False  # lit.*$ == lit.*
            trailing_any = True
    if i != n or not lit:
        return None
    if anchored_start and anchored_end:
        return ("equals", lit)
    if anchored_start:
        return ("startswith", lit)
    if anchored_end:
        return ("endswith", lit)
    return ("contains", lit)


def regex_matches(col: Column, pattern: str,
                  fallback: bool = True) -> Column:
    """RLIKE: the rewrite table's fast literal kernels when the pattern
    lowers (the reference component's whole contract), else a host-side
    regex escape hatch so NDS predicates outside the subset still run —
    the analog of the plugin falling back to CPU for unsupported exprs.
    ``fallback=False`` restores the strict reference behavior (raise)."""
    rw = rewrite(pattern)
    if rw is None:
        if not fallback:
            raise ValueError(
                f"pattern {pattern!r} is outside the rewritable subset "
                "(literal prefix/suffix/contains/equals)")
        # the host loop is O(rows) Python + a device round-trip per call —
        # a silent 1000x cliff; name the pattern so it's diagnosable, and
        # count it so fleet-wide fallback volume is measurable (the log
        # line alone vanishes in aggregation)
        from ..utils import tracing
        from ..utils.config import logger
        tracing.count("ops.regex.host_fallback")
        tracing.count(f"ops.regex.host_fallback.pattern.{pattern}")
        logger().warning(
            "regex_matches pattern %r is outside the rewritable subset; "
            "falling back to the per-row host loop over %d rows",
            pattern, col.size)
        return _regex_matches_host(col, pattern)
    kind, lit = rw
    if kind == "startswith":
        return _s.starts_with(col, lit)
    if kind == "endswith":
        return _s.ends_with(col, lit)
    if kind == "contains":
        return _s.contains(col, lit)
    sw = _s.starts_with(col, lit)
    ln = _s.byte_length(col)
    import jax.numpy as jnp
    eq = (sw.data != 0) & (ln.data == len(lit.encode()))
    return Column(BOOL8, data=eq.astype(jnp.uint8), validity=sw.validity)


def _regex_matches_host(col: Column, pattern: str) -> Column:
    """Host-side RLIKE fallback (python `re` over the Arrow buffers).

    Java regex and python `re` agree on the common NDS predicate shapes
    (alternation, classes, quantifiers, anchors); exotic Java-only syntax
    (possessive quantifiers, \\p{javaX}) still raises, loudly, from `re`.
    RLIKE is an unanchored find(), matching Spark semantics.
    """
    import re
    import numpy as np
    import jax.numpy as jnp
    # re.ASCII: Java regex classes (\d \w \s \b) are ASCII by default —
    # python defaults to Unicode classes, which would silently match e.g.
    # Arabic-Indic digits that Spark's engine rejects
    rx = re.compile(pattern, re.ASCII)
    offs = np.asarray(col.offsets, np.int64)
    chars = (np.asarray(col.data, np.uint8).tobytes()
             if col.data is not None else b"")
    n = offs.shape[0] - 1
    hit = np.zeros(n, np.bool_)
    valid = (np.ones(n, np.bool_) if col.validity is None
             else np.asarray(col.validity))
    for i in range(n):
        if valid[i]:
            s = chars[offs[i]:offs[i + 1]].decode("utf-8", "surrogatepass")
            hit[i] = rx.search(s) is not None
    return Column(BOOL8, data=jnp.asarray(hit.astype(np.uint8)),
                  validity=None if col.validity is None else col.validity)
