"""RegexRewrite: lower simple regex patterns onto literal string predicates.

TPU-native equivalent of the reference's RegexRewrite component (named in
BASELINE.json's north-star set; Java side appears post-snapshot as
RegexRewriteUtils).  Its job in spark-rapids is to recognize regex patterns
that are really literal prefix/suffix/contains tests and dispatch them to fast
non-regex kernels instead of a regex engine.  We implement the same contract:

    rewrite(pattern)            -> ("startswith"|"endswith"|"contains"|"equals",
                                    literal) or None
    regex_matches(col, pattern) -> BOOL8 column, raising ValueError for
                                   patterns outside the rewritable subset
                                   (a general TPU regex engine is out of scope,
                                   exactly as it is for the reference kernels).

Recognized shapes (anchors + literal + unbounded wildcards only):
    ^lit$   -> equals        ^lit / ^lit.*  -> startswith
    lit$ / .*lit$ -> endswith    lit / .*lit.* -> contains
Escaped metacharacters (\\.) inside the literal are unescaped.
"""

from __future__ import annotations

from ..columnar import Column
from ..dtypes import BOOL8
from . import strings as _s

_META = set(".^$*+?()[]{}|\\")


def _scan_literal(pattern: str, i: int) -> tuple[str, int]:
    """Longest literal run starting at i; handles backslash escapes."""
    out = []
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern) and pattern[i + 1] in _META:
            out.append(pattern[i + 1])
            i += 2
        elif ch in _META:
            break
        else:
            out.append(ch)
            i += 1
    return "".join(out), i


def rewrite(pattern: str):
    """Classify ``pattern``; return (kind, literal) or None if not rewritable."""
    i, n = 0, len(pattern)
    anchored_start = i < n and pattern[i] == "^"
    if anchored_start:
        i += 1
    if pattern.startswith(".*", i):
        i += 2
        anchored_start = False  # ^.*lit == .*lit
    lit, i = _scan_literal(pattern, i)
    trailing_any = False
    if pattern.startswith(".*", i):
        i += 2
        trailing_any = True
    anchored_end = i < n and pattern[i] == "$"
    if anchored_end:
        i += 1
        if trailing_any:
            anchored_end = False  # lit.*$ == lit.*
            trailing_any = True
    if i != n or not lit:
        return None
    if anchored_start and anchored_end:
        return ("equals", lit)
    if anchored_start:
        return ("startswith", lit)
    if anchored_end:
        return ("endswith", lit)
    return ("contains", lit)


def regex_matches(col: Column, pattern: str) -> Column:
    """RLIKE via the rewrite table; raises for unsupported patterns."""
    rw = rewrite(pattern)
    if rw is None:
        raise ValueError(
            f"pattern {pattern!r} is outside the rewritable subset "
            "(literal prefix/suffix/contains/equals)")
    kind, lit = rw
    if kind == "startswith":
        return _s.starts_with(col, lit)
    if kind == "endswith":
        return _s.ends_with(col, lit)
    if kind == "contains":
        return _s.contains(col, lit)
    sw = _s.starts_with(col, lit)
    ln = _s.byte_length(col)
    import jax.numpy as jnp
    eq = (sw.data != 0) & (ln.data == len(lit.encode()))
    return Column(BOOL8, data=eq.astype(jnp.uint8), validity=sw.validity)
