"""Device-side Parquet page decode (`SRJT_DEVICE_DECODE`).

The host scan path (io/parquet.py `_ChunkDecoder`) decompresses and decodes
pages in pure Python/numpy, then ships *uncompressed* bytes over the link;
staging (io/staging.py) hides the transfer but not the decode.  This module
moves the inner loops into jitted kernels so the link carries the
*compressed* page bytes and decode runs on-device, overlapped with compute
by the existing double-buffered prefetch pipeline:

- **snappy** raw-block decompression as a two-pass token scan: pass 1 is a
  (vmapped) sequential walk over the token *headers* only — a few dozen
  iterations per page, each O(1) — scattering per-token (dest, literal-src,
  copy-offset) marks; pass 2 is fully parallel over output bytes: a
  ``cummax`` recovers each byte's owning token and a pointer-doubling chase
  resolves back-reference chains (literal bytes are fixed points).  Pages
  whose token scan found no back-references (``has_copies=False``, the
  common case for high-entropy and dict-encoded data) skip the chase
  entirely — the gather is one ``take_along_axis``.
- **RLE/bit-packed hybrid** decode (def levels, dictionary indices) with the
  same shape: sequential run-header walk, then parallel per-slot extraction
  from a ``cummax`` over run marks.
- **PLAIN** fixed-width decode as a byte gather + word assembly (the
  two-stage u8 -> u32 -> int64 rebuild staging already proves on TPU, where
  only <=32-bit bitcasts exist), and **dictionary gather** through the
  decoded dictionary page.

Word assembly optionally runs as a Pallas VMEM kernel
(`pallas_kernels.available()` + a Mosaic probe of this kernel shape); the
pure-XLA shift assembly is the always-correct fallback and the CPU test
path (``interpret=True``).

Wire format: each column chunk ships as padded ``uint8`` *page planes* —
``comp[P+1, CB]`` (row 0 = dictionary page or zeros, rows 1..P = data
pages) plus the tiny ``clen/ulen/nv[P+1]`` per-page byte/value counts.
That is ALL that crosses the link: the global row -> (page, slot) map is
derived in-kernel from a ``cumsum`` over ``nv`` (shipping it as i32
tables would cost 8 B/row/col — more than compressed int64 data).  All
dimensions are power-of-two buckets recorded in the static
:class:`ChunkGeom`, so one jitted program serves every chunk of the same
(schema, geometry) class.  Everything here is pure traced code: zero host
syncs, zero callbacks — `verify.py` lints the jaxpr.

Unsupported shapes (nesting, v2 pages, non-RLE levels, codecs beyond
snappy/uncompressed, strings) never reach this module: io/parquet.py's
`plan_device_group` routes them to the host decoder with a ledgered
fallback reason.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..dtypes import DType, TypeId

#: floor for the per-page byte/value buckets (lane-width aligned so the
#: Pallas word-assembly blocks always divide evenly)
MIN_BUCKET = 128


def bucket(n: int, floor: int = MIN_BUCKET) -> int:
    """Next power of two >= max(n, floor) — the geometry-class quantizer."""
    b = int(floor)
    while b < n:
        b *= 2
    return b


# -- static geometry (the jit cache key) ------------------------------------

@dataclass(frozen=True)
class ColumnGeom:
    """Static decode geometry for one column chunk.

    ``encoding`` is the *data-page* value encoding class: ``"plain"`` or
    ``"dict"`` (PLAIN_DICTIONARY / RLE_DICTIONARY).  ``has_copies`` is the
    host token-scan's verdict on the snappy streams: False means every page
    is literal-only and the device decompressor skips the pointer chase.
    Buckets: ``cb``/``ub`` compressed/uncompressed page bytes, ``vb`` values
    per page, ``db`` dictionary entries, ``tb`` snappy tokens per page (the
    pass-1 walk's compact carry size); ``npages`` is the (pow2) data-page
    count.
    """

    name: str
    dtype: DType
    physical: int
    codec: int
    encoding: str
    max_def: int
    has_copies: bool
    npages: int
    cb: int
    ub: int
    vb: int
    db: int
    tb: int = 64


@dataclass(frozen=True)
class ChunkGeom:
    """Static geometry for a whole row-group chunk: per-column geometry
    plus the shared row-table bucket ``rb``."""

    columns: tuple
    rb: int

    def column(self, name: str) -> ColumnGeom:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)


_I32 = jnp.int32
_U32 = jnp.uint32


def _i32(x):
    return x.astype(_I32)


# -- snappy: two-pass token-scan decompression ------------------------------

def _snappy_pass1(comp, clen, ulen, ub: int, tb: int):
    """Sequential token-header walk for ONE page (vmapped by the caller).

    Walks the token *headers* only, carrying a COMPACT per-token table of
    static bucket ``tb`` (the host token scan's count), never the
    output-sized planes: under vmap every loop iteration pays a masked
    select over the carried state, so the carry must stay tokens-sized —
    with byte-sized carries the walk's memory traffic dwarfs the actual
    decompression.  The table then scatters ONCE into the per-output-byte
    planes the parallel pass consumes: ``mark[ub]`` (token start position,
    -1 elsewhere), ``lsrc[ub]`` (literal source byte offset in ``comp``),
    ``coff[ub]`` (back-reference offset; 0 marks a literal).
    """
    cb = comp.shape[0]

    def rd(pos):
        return _i32(comp[jnp.clip(pos, 0, cb - 1)])

    # uvarint preamble (uncompressed length): skip 1-5 bytes
    b = [rd(jnp.int32(k)) for k in range(5)]
    c = [bk >> 7 for bk in b]
    hdr = 1 + c[0] + c[0] * c[1] + c[0] * c[1] * c[2] \
        + c[0] * c[1] * c[2] * c[3]

    def cond(st):
        s, d, k = st[0], st[1], st[2]
        # k < tb is a safety bound only: the host scan sized tb to the
        # real token count, so a correct stream never trips it
        return (s < clen) & (d < ulen) & (k < tb)

    def body(st):
        s, d, k, dk, ls, co = st
        tag = rd(s)
        kind = tag & 3
        lcode = tag >> 2
        # literal: 1-4 extra LE length bytes when lcode >= 60
        nlb = jnp.clip(lcode - 59, 0, 4)
        e = [rd(s + 1 + k) for k in range(4)]
        extra = e[0] | e[1] << 8 | e[2] << 16 | e[3] << 24
        emask = jnp.where(nlb >= 4, jnp.int32(-1),
                          (jnp.int32(1) << (8 * jnp.minimum(nlb, 3))) - 1)
        lit_len = jnp.where(lcode < 60, lcode + 1, (extra & emask) + 1)
        lit_start = s + 1 + nlb
        # copies
        n1, n2, n3, n4 = e  # bytes after the tag
        len1 = ((tag >> 2) & 7) + 4
        off1 = ((tag & 0xE0) << 3) | n1
        off2 = n1 | n2 << 8
        off3 = n1 | n2 << 8 | n3 << 16 | n4 << 24
        cp_len = jnp.where(kind == 1, len1, lcode + 1)
        cp_off = jnp.where(kind == 1, off1,
                           jnp.where(kind == 2, off2, off3))
        cp_off = jnp.maximum(cp_off, 1)  # 0 is the literal marker
        cp_adv = jnp.where(kind == 1, 2, jnp.where(kind == 2, 3, 5))

        is_lit = kind == 0
        tok_len = jnp.where(is_lit, lit_len, cp_len)
        dk = dk.at[k].set(d)
        ls = ls.at[k].set(jnp.where(is_lit, lit_start, 0))
        co = co.at[k].set(jnp.where(is_lit, 0, cp_off))
        s = s + jnp.where(is_lit, 1 + nlb + lit_len, cp_adv)
        return s, d + tok_len, k + 1, dk, ls, co

    # unused slots keep destination ub: out of bounds -> scatter-dropped
    init = (hdr, jnp.int32(0), jnp.int32(0),
            jnp.full((tb,), ub, _I32), jnp.zeros((tb,), _I32),
            jnp.zeros((tb,), _I32))
    _, _, _, dk, ls, co = jax.lax.while_loop(cond, body, init)
    mark = jnp.full((ub,), -1, _I32).at[dk].set(dk, mode="drop")
    lsrc = jnp.zeros((ub,), _I32).at[dk].set(ls, mode="drop")
    coff = jnp.zeros((ub,), _I32).at[dk].set(co, mode="drop")
    return mark, lsrc, coff


def _snappy_decompress(comp, clen, ulen, ub: int, has_copies: bool,
                       tb: int):
    """``comp[R, CB]`` snappy pages -> ``u8[R, UB]`` uncompressed planes."""
    r, cb = comp.shape
    mark, lsrc, coff = jax.vmap(_snappy_pass1,
                                in_axes=(0, 0, 0, None, None))(
        comp, clen, ulen, ub, tb)
    iota = jnp.arange(ub, dtype=_I32)[None, :]
    tid = jax.lax.cummax(mark, axis=1)
    tidc = jnp.clip(tid, 0, ub - 1)
    lit = jnp.take_along_axis(lsrc, tidc, axis=1)
    off = jnp.take_along_axis(coff, tidc, axis=1)
    if has_copies:
        # pointer-doubling chase: literal positions are fixed points, copy
        # positions point strictly backwards, so log2(ub) rounds resolve
        # every chain (incl. overlapping RLE-style copies)
        ptr = jnp.where(off == 0, iota, jnp.clip(iota - off, 0, ub - 1))
        ptr = jnp.broadcast_to(ptr, (r, ub))
        for _ in range(int(ub).bit_length()):
            ptr = jnp.take_along_axis(ptr, ptr, axis=1)
        src = jnp.take_along_axis(lit, ptr, axis=1) + \
            (ptr - jnp.take_along_axis(tidc, ptr, axis=1))
    else:
        src = lit + (iota - tidc)
    out = jnp.take_along_axis(comp, jnp.clip(src, 0, cb - 1), axis=1)
    return jnp.where(iota < ulen[:, None], out, jnp.uint8(0))


def _decompress(comp, clen, ulen, g: ColumnGeom):
    """Codec dispatch (static): ``u8[R, CB]`` pages -> ``u8[R, UB]``."""
    from ..io.parquet import CODEC_SNAPPY, CODEC_UNCOMPRESSED
    if g.codec == CODEC_SNAPPY:
        return _snappy_decompress(comp, clen, ulen, g.ub, g.has_copies,
                                  g.tb)
    if g.codec == CODEC_UNCOMPRESSED:
        if g.cb >= g.ub:
            return comp[:, :g.ub]
        return jnp.pad(comp, ((0, 0), (0, g.ub - g.cb)))
    raise ValueError(f"device decode: unsupported codec {g.codec}")


# -- RLE / bit-packed hybrid ------------------------------------------------

def _hybrid_pass1(data, start, end, bw, n, vb: int):
    """Sequential run-header walk for ONE hybrid stream (vmapped).

    Returns scatter planes over value slots: ``mark[vb]`` (run start slot),
    ``pk[vb]`` (bit-packed run?), ``bb[vb]`` (bit offset of the run's packed
    payload), ``rv[vb]`` (the RLE run value).
    """
    ub = data.shape[0]

    def rd(pos):
        return _i32(data[jnp.clip(pos, 0, ub - 1)])

    def cond(st):
        s, v = st[0], st[1]
        return (s < end) & (v < n)

    def body(st):
        s, v, mark, pk, bb, rv = st
        b = [rd(s + k) for k in range(5)]
        c = [bk >> 7 for bk in b]
        seg = [bk & 0x7F for bk in b]
        h = seg[0] \
            + c[0] * (seg[1] << 7) \
            + c[0] * c[1] * (seg[2] << 14) \
            + c[0] * c[1] * c[2] * (seg[3] << 21) \
            + c[0] * c[1] * c[2] * c[3] * (seg[4] << 28)
        hlen = 1 + c[0] + c[0] * c[1] + c[0] * c[1] * c[2] \
            + c[0] * c[1] * c[2] * c[3]
        dp = s + hlen
        packed = (h & 1) == 1
        groups = h >> 1
        bwb = (bw + 7) >> 3  # RLE value byte width
        d = [rd(dp + k) for k in range(4)]
        raw = (d[0] | d[1] << 8 | d[2] << 16 | d[3] << 24).astype(_U32)
        vmask = jnp.where(bwb >= 4, _U32(0xFFFFFFFF),
                          (_U32(1) << _U32(8 * jnp.minimum(bwb, 3))) - 1)
        cnt = jnp.where(packed, groups * 8, groups)
        cnt = jnp.maximum(cnt, 1)  # corrupt zero-count header: still advance
        adv = jnp.where(packed, groups * bw, bwb)
        vc = jnp.clip(v, 0, vb - 1)
        mark = mark.at[vc].set(v)
        pk = pk.at[vc].set(packed)
        bb = bb.at[vc].set(dp * 8)
        rv = rv.at[vc].set(raw & vmask)
        return dp + adv, v + cnt, mark, pk, bb, rv

    init = (start, jnp.int32(0),
            jnp.full((vb,), -1, _I32), jnp.zeros((vb,), jnp.bool_),
            jnp.zeros((vb,), _I32), jnp.zeros((vb,), _U32))
    _, _, mark, pk, bb, rv = jax.lax.while_loop(cond, body, init)
    return mark, pk, bb, rv


def _rle_hybrid(data, start, end, bw, n, vb: int):
    """RLE/bit-packed hybrid streams -> ``u32[R, vb]`` values.

    ``data[R, UB]`` uncompressed page planes; ``start``/``end`` byte ranges
    and ``bw`` bit widths are per-row (dynamic — for dictionary indices the
    width byte itself lives in the page payload); ``n`` values per row.
    """
    r, ub = data.shape
    mark, pk, bb, rv = jax.vmap(_hybrid_pass1,
                                in_axes=(0, 0, 0, 0, 0, None))(
        data, start, end, bw, n, vb)
    rid = jax.lax.cummax(mark, axis=1)
    ridc = jnp.clip(rid, 0, vb - 1)
    pk2 = jnp.take_along_axis(pk, ridc, axis=1)
    bb2 = jnp.take_along_axis(bb, ridc, axis=1)
    rv2 = jnp.take_along_axis(rv, ridc, axis=1)
    iota = jnp.arange(vb, dtype=_I32)[None, :]
    bit = bb2 + (iota - ridc) * bw[:, None]
    byte0 = bit >> 3
    sh = (bit & 7).astype(_U32)
    by = [jnp.take_along_axis(
        data, jnp.clip(byte0 + k, 0, ub - 1), axis=1).astype(_U32)
        for k in range(5)]
    lo = by[0] | by[1] << 8 | by[2] << 16 | by[3] << 24
    # straddle byte: (hi << (32 - sh)) is undefined at sh == 0, so compute
    # the shift mod 32 and select it away
    hi = jnp.where(sh == 0, _U32(0), by[4] << ((_U32(32) - sh) & _U32(31)))
    bwm = jnp.where(bw >= 32, _U32(0xFFFFFFFF),
                    (_U32(1) << jnp.minimum(bw, 31).astype(_U32)) - 1)
    val = ((lo >> sh) | hi) & bwm[:, None]
    val = jnp.where(pk2, val, rv2)
    return jnp.where(iota < n[:, None], val, _U32(0))


# -- PLAIN fixed-width gather + word assembly -------------------------------

def _asm_kernel(b_ref, o_ref):
    """u8 (blk, 512) byte block -> u32 (blk, 128) word block in VMEM."""
    x = b_ref[:].astype(jnp.uint32).reshape(o_ref.shape[0], -1, 4)
    o_ref[:] = (x[..., 0] | x[..., 1] << 8 | x[..., 2] << 16
                | x[..., 3] << 24)


def _asm_call(nblocks: int, interpret: bool):
    from jax.experimental import pallas as pl
    return pl.pallas_call(
        _asm_kernel, grid=(nblocks,),
        in_specs=[pl.BlockSpec((1, 512), lambda r: (r, 0))],
        out_specs=pl.BlockSpec((1, 128), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, 128), jnp.uint32),
        interpret=interpret)


@functools.lru_cache(maxsize=1)
def _asm_available() -> bool:
    """Probe whether Mosaic compiles the byte->word assembly kernel.

    `pallas_kernels.available()` proves gridded pallas_call works at all;
    this probes THIS kernel's u8 load + reshape shape, eagerly (see
    pallas_kernels.available for why ensure_compile_time_eval)."""
    from . import pallas_kernels
    if not pallas_kernels.available():
        return False
    try:
        with jax.ensure_compile_time_eval():
            out = _asm_call(2, False)(jnp.zeros((2, 512), jnp.uint8))
            np.asarray(out)
        return True
    except Exception:
        return False


def assemble_u32(b, *, interpret: bool = False, force_pallas: bool = False):
    """``u8[..., 4]`` little-endian byte groups -> ``u32[...]``.

    Pallas VMEM kernel when available (or forced for interpreter tests),
    pure-XLA shift assembly otherwise.  The Pallas path needs the flattened
    byte count to divide 512 — guaranteed by the pow2 buckets (>= 128
    values x 4 bytes)."""
    total = int(np.prod(b.shape))
    if (force_pallas or _asm_available()) and total % 512 == 0:
        flat = b.reshape(-1, 512)
        out = _asm_call(flat.shape[0], interpret)(flat)
        return out.reshape(b.shape[:-1])
    x = b.astype(_U32)
    return x[..., 0] | x[..., 1] << 8 | x[..., 2] << 16 | x[..., 3] << 24


def _plain_gather(unc, voff, nn, dtype: DType, *, interpret: bool = False):
    """PLAIN-encoded values: byte gather at per-slot offsets + assembly.

    ``unc[R, UB]`` page planes, ``voff[R]`` value-section starts, ``nn[R,V]``
    per-slot value ordinals (-1 on null slots — clipped, caller masks).
    Returns ``[R, V]`` in the dtype's device storage.
    """
    r, ub = unc.shape
    nnc = jnp.clip(nn, 0, None)
    if dtype.id == TypeId.BOOL8:
        byte = jnp.take_along_axis(
            unc, jnp.clip(voff[:, None] + (nnc >> 3), 0, ub - 1), axis=1)
        return ((byte.astype(_U32) >> (nnc & 7).astype(_U32))
                & _U32(1)).astype(jnp.uint8)
    size = np.dtype(dtype.storage).itemsize
    base = voff[:, None] + nnc * size
    offs = base[:, :, None] + jnp.arange(size, dtype=_I32)
    flat = jnp.clip(offs.reshape(r, -1), 0, ub - 1)
    b = jnp.take_along_axis(unc, flat, axis=1).reshape(r, -1, size)
    if size == 4:
        w = assemble_u32(b, interpret=interpret)
        if dtype.id == TypeId.FLOAT32:
            return jax.lax.bitcast_convert_type(w, jnp.float32)
        return jax.lax.bitcast_convert_type(w, jnp.dtype(dtype.storage))
    # size == 8: rebuild from u32 pairs (staging's TPU-proven idiom —
    # only <= 32-bit bitcasts exist there).  FLOAT64 device storage IS the
    # int64 bit pattern (dtypes.device_storage), so this is the final form.
    lo = assemble_u32(b[..., :4], interpret=interpret)
    hi = assemble_u32(b[..., 4:], interpret=interpret)
    pairs = jnp.stack([lo, hi], axis=-1)
    return jax.lax.bitcast_convert_type(pairs, jnp.int64)


# -- column decode ----------------------------------------------------------

def _le32(unc, at: int):
    """u32 little-endian read at static byte offset ``at`` of each row."""
    return (_i32(unc[:, at]) | _i32(unc[:, at + 1]) << 8
            | _i32(unc[:, at + 2]) << 16 | _i32(unc[:, at + 3]) << 24)


def _decode_column(p: dict, g: ColumnGeom, rb: int, *,
                   interpret: bool = False):
    """One column chunk's planes -> (data[rb], validity[rb] | None)."""
    if g.encoding == "plain":
        # PLAIN never reads the dict row -- skip decompressing plane 0
        unc = None
        dunc = _decompress(p["comp"][1:], p["clen"][1:], p["ulen"][1:], g)
    else:
        unc = _decompress(p["comp"], p["clen"], p["ulen"], g)  # [P+1, UB]
        dunc = unc[1:]
    ulen_d = p["ulen"][1:]
    nv_d = p["nv"][1:]
    npages, vb = g.npages, g.vb
    iota_v = jnp.arange(vb, dtype=_I32)[None, :]

    if g.max_def > 0:
        # v1 page layout: [u32 def-len][def RLE hybrid][values] — the
        # length prefix lives INSIDE the (de)compressed body, so the value
        # offset is dynamic per page
        dlen = _le32(dunc, 0)
        voff = 4 + dlen
        lv = _rle_hybrid(dunc, jnp.full((npages,), 4, _I32), voff,
                         jnp.ones((npages,), _I32), nv_d, vb)
        valid = (lv == _U32(g.max_def)) & (iota_v < nv_d[:, None])
        nn = jnp.cumsum(valid, axis=1, dtype=_I32) - 1
        nnon = nn[:, -1] + 1
    else:
        voff = jnp.zeros((npages,), _I32)
        valid = iota_v < nv_d[:, None]
        nn = jnp.broadcast_to(iota_v, (npages, vb))
        nnon = nv_d

    if g.encoding == "plain":
        dense = _plain_gather(dunc, voff, nn, g.dtype, interpret=interpret)
    else:  # dictionary: decode the dict page, then gather through indices
        dvals = _plain_gather(
            unc[:1], jnp.zeros((1,), _I32),
            jnp.arange(g.db, dtype=_I32)[None, :], g.dtype,
            interpret=interpret)[0]
        nd = p["nv"][0]
        dvals = jnp.where(jnp.arange(g.db, dtype=_I32) < nd, dvals,
                          jnp.zeros((), dvals.dtype))
        bw = _i32(jnp.take_along_axis(
            dunc, jnp.clip(voff, 0, g.ub - 1)[:, None], axis=1)[:, 0])
        idx = _rle_hybrid(dunc, voff + 1, ulen_d, bw, nnon, vb)
        slot = jnp.take_along_axis(idx, jnp.clip(nn, 0, vb - 1),
                                   axis=1).astype(_I32)
        dense = dvals[jnp.clip(slot, 0, g.db - 1)]

    zero = jnp.zeros((), dense.dtype)
    dense = jnp.where(valid, dense, zero)

    # global row -> (page, slot) map, derived on-device from the per-page
    # value counts: shipping it as i32 tables would cost 8 B/row/col —
    # more than the int64 data itself once compressed
    nvc = jnp.cumsum(nv_d, dtype=_I32)  # rows at/under each page
    start = nvc - nv_d                  # first global row of each page
    iota_r = jnp.arange(rb, dtype=_I32)
    rp = jnp.sum(iota_r[None, :] >= nvc[:, None], axis=0, dtype=_I32)
    inrow = iota_r < nvc[-1]  # rows past the chunk are bucket pad
    rpc = jnp.clip(rp, 0, npages - 1)
    ric = jnp.clip(iota_r - start[rpc], 0, vb - 1)
    data = jnp.where(inrow, dense[rpc, ric], zero)
    if g.max_def > 0:
        return data, valid[rpc, ric] & inrow
    return data, None


def decode_table(planes: dict, geom: ChunkGeom, *,
                 interpret: bool = False) -> Table:
    """Page planes -> bucket-padded device Table (pure traced code).

    Mirrors the staged host chunk contract (io/staging.py padded=True):
    rows are padded to the ``rb`` bucket with zeroed values and False
    validity; a column carries validity iff its schema has a def level.
    """
    cols, names = [], []
    for g in geom.columns:
        data, validity = _decode_column(planes[g.name], g, geom.rb,
                                        interpret=interpret)
        storage = jnp.dtype(g.dtype.device_storage)
        if data.dtype != storage:  # e.g. unsigned storage: same-width bits
            data = jax.lax.bitcast_convert_type(data, storage)
        cols.append(Column(g.dtype, data=data, validity=validity))
        names.append(g.name)
    return Table(cols, names)


def probe_table(geom: ChunkGeom) -> Table:
    """A 1-row host-materialized Table with the decode output's schema —
    the executor's segment-eligibility probe (stream_runtime_eligible
    inspects dtypes/validity, and one row dodges the empty-agg veto)."""
    cols, names = [], []
    for g in geom.columns:
        data = jnp.zeros((1,), jnp.dtype(g.dtype.device_storage))
        validity = jnp.ones((1,), jnp.bool_) if g.max_def > 0 else None
        cols.append(Column(g.dtype, data=data, validity=validity))
        names.append(g.name)
    return Table(cols, names)


def zero_planes(geom: ChunkGeom) -> dict:
    """All-zero planes matching ``geom`` — abstract inputs for jaxpr lint
    and shape probing (a zero page decodes to zero rows: the token walk's
    loop condition fails immediately)."""
    out = {}
    for g in geom.columns:
        out[g.name] = {
            "comp": jnp.zeros((g.npages + 1, g.cb), jnp.uint8),
            "clen": jnp.zeros((g.npages + 1,), _I32),
            "ulen": jnp.zeros((g.npages + 1,), _I32),
            "nv": jnp.zeros((g.npages + 1,), _I32),
        }
    return out
