"""Order-preserving u64 key encodings for sort / merge / group-compare.

Replaces cudf's row-comparator machinery with something XLA likes: every key
column encodes to one or more uint64 arrays whose unsigned order equals the
column's SQL order.  Multi-column ordering is then a plain ``jnp.lexsort``
(radix sort on the VPU) instead of a per-row comparison lambda — comparator
control flow doesn't vectorize on TPU, monotone integer keys do.

Encodings:
- signed ints / timestamps / decimals: bits XOR sign-flip (order-preserving
  bijection into u64)
- unsigned ints / bool: zero-extend
- FLOAT32/64: IEEE total-order transform on the bit pattern (negative floats
  reverse); NaNs sort above +inf like cudf/Spark, and since FLOAT64 columns
  store raw bit patterns (dtypes.device_storage) this is *exact* on TPU
- strings: bytes packed big-endian into ceil(W/8) u64 words (u64 compare ==
  byte-lexicographic compare), plus the length as a tiebreaker so prefixes
  sort first
- nulls: an extra leading flag key; Spark default is NULLS FIRST for ASC and
  NULLS LAST for DESC, which falls out of flag inversion

Descending order = bitwise NOT of every key word.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..columnar import Column
from ..dtypes import TypeId
from .strings_common import to_padded_bytes

_U64 = jnp.uint64
_SIGN64 = _U64(1) << _U64(63)


@dataclass(frozen=True)
class SortKey:
    col: object          # Column
    ascending: bool = True
    nulls_first: bool | None = None  # None -> Spark default (first iff asc)

    @property
    def effective_nulls_first(self) -> bool:
        return self.ascending if self.nulls_first is None else self.nulls_first


def normalize_f64_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """Spark float normalization on bit patterns: -0.0 -> 0.0, NaN -> qNaN.

    Applied before ordering/equality so grouping and joins treat -0.0 = 0.0
    and all NaNs as one value (Spark NormalizeFloatingNumbers semantics)."""
    bits = jnp.where(bits == _SIGN64, _U64(0), bits)
    is_nan = ((bits & _U64(0x7FF0000000000000)) == _U64(0x7FF0000000000000)) \
        & ((bits & _U64(0x000FFFFFFFFFFFFF)) != _U64(0))
    return jnp.where(is_nan, _U64(0x7FF8000000000000), bits)


def normalize_f32_bits(bits32: jnp.ndarray) -> jnp.ndarray:
    u = jnp.uint32
    bits32 = jnp.where(bits32 == u(0x80000000), u(0), bits32)
    is_nan = ((bits32 & u(0x7F800000)) == u(0x7F800000)) \
        & ((bits32 & u(0x007FFFFF)) != u(0))
    return jnp.where(is_nan, u(0x7FC00000), bits32)


def _fixed_to_u64(col: Column) -> jnp.ndarray:
    tid = col.dtype.id
    data = col.data
    if tid == TypeId.FLOAT64:
        bits = normalize_f64_bits(data.astype(_U64))  # stored bit patterns
        neg = (bits & _SIGN64) != _U64(0)
        return jnp.where(neg, ~bits, bits | _SIGN64)
    if tid == TypeId.FLOAT32:
        bits32 = normalize_f32_bits(jax.lax.bitcast_convert_type(
            jnp.asarray(data, jnp.float32), jnp.uint32))
        bits = bits32.astype(_U64)
        neg = (bits & _U64(0x80000000)) != _U64(0)
        key32 = jnp.where(neg, ~bits & _U64(0xFFFFFFFF), bits | _U64(0x80000000))
        return key32
    if tid == TypeId.BOOL8:
        return (data != 0).astype(_U64)
    if col.dtype.storage.kind == "u":
        return data.astype(_U64)
    # signed integral family (ints, timestamps, durations, decimal unscaled)
    return data.astype(jnp.int64).astype(_U64) ^ _SIGN64


def encode_key(key: SortKey) -> list[jnp.ndarray]:
    """Primary-first list of u64 key words for one sort key."""
    col: Column = key.col
    words: list[jnp.ndarray] = []
    if col.dtype.is_string:
        mat, lengths = to_padded_bytes(col)
        n, w = mat.shape
        nwords = max((w + 7) // 8, 1)
        if w < nwords * 8:
            mat = jnp.pad(mat, ((0, 0), (0, nwords * 8 - w)))
        m = mat.reshape(n, nwords, 8).astype(_U64)
        for c in range(nwords):
            word = m[:, c, 0]
            for b in range(1, 8):
                word = (word << _U64(8)) | m[:, c, b]  # big-endian packing
            words.append(word)
        words.append(lengths.astype(_U64))  # prefix-first tiebreak
    else:
        words.append(_fixed_to_u64(col))
    if not key.ascending:
        words = [~wd for wd in words]
    if col.validity is not None:
        # neutralize value words on null rows: whatever bytes the buffer holds
        # there must not split the null group (SQL: all nulls compare equal in
        # GROUP BY) or order rows within the null block
        words = [jnp.where(col.validity, wd, _U64(0)) for wd in words]
        flag = col.validity.astype(_U64)  # valid=1: nulls first
        if not key.effective_nulls_first:
            flag = _U64(1) - flag
        words.insert(0, flag)
    return words


def decode_minmax_bits(red: jnp.ndarray, dtype) -> jnp.ndarray:
    """Invert ``_fixed_to_u64``'s float total-order transform.

    ``red`` is a reduced (min/max) encoding word; returns the float column
    data in its device-storage form (FLOAT64 -> int64 bit patterns).
    """
    from ..dtypes import TypeId
    if dtype.id == TypeId.FLOAT64:
        sign = (red & (jnp.uint64(1) << jnp.uint64(63))) != 0
        bits = jnp.where(sign, red ^ (jnp.uint64(1) << jnp.uint64(63)), ~red)
        return bits.astype(jnp.int64)
    sign = (red & jnp.uint64(0x80000000)) != 0
    bits32 = jnp.where(sign, red ^ jnp.uint64(0x80000000),
                       ~red & jnp.uint64(0xFFFFFFFF))
    return jax.lax.bitcast_convert_type(bits32.astype(jnp.uint32),
                                        jnp.float32)


def encode_keys(keys: list[SortKey]) -> list[jnp.ndarray]:
    """Primary-first flat u64 word list for a multi-column ordering."""
    out: list[jnp.ndarray] = []
    for k in keys:
        out.extend(encode_key(k))
    return out


def sort_indices(keys: list[SortKey], stable: bool = True) -> jnp.ndarray:
    """Row permutation realizing the requested ordering (stable)."""
    words = encode_keys(keys)
    # lexsort treats the LAST key as primary
    return jnp.lexsort(tuple(reversed(words)))


def rows_differ_from_prev(words: list[jnp.ndarray],
                          order: jnp.ndarray) -> jnp.ndarray:
    """bool[n]: sorted row i differs from row i-1 on any key word (row 0 True).

    The group-boundary primitive for sort-based aggregation; nulls compare
    equal to nulls here (the flag word is part of ``words``), matching SQL
    GROUP BY null semantics.
    """
    n = order.shape[0]
    if n == 0:  # no rows, no boundaries (``.at[0]`` would be OOB)
        return jnp.zeros((0,), jnp.bool_)
    first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    diff = first
    for wd in words:
        s = jnp.take(wd, order)
        diff = diff | jnp.concatenate([first[:1], s[1:] != s[:-1]])
    return diff
