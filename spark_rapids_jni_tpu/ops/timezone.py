"""TimeZoneDB: timezone-aware timestamp conversion from device transition tables.

TPU-native rebuild of the reference's GpuTimeZoneDB component (BASELINE.json
north-star set; Java/CUDA side appears post-snapshot as GpuTimeZoneDB.java —
it loads each zone's transition rules into a device table once, then kernels
binary-search per row).  Same design here:

- host side: parse the system TZif database (/usr/share/zoneinfo, the same
  IANA data the JVM uses) into (transition instants, utc offsets) int64
  arrays, cached per zone;
- device side: ``searchsorted`` into the transition instants picks each row's
  offset — the vectorized form of the reference's per-thread binary search.

Semantics match Spark's from_utc_timestamp/to_utc_timestamp: local->UTC
resolves gaps/overlaps by using the offset in force *before* the wall-clock
transition point (Java's earlier-offset rule for overlaps).  All four
timestamp precisions are supported (SECONDS/MILLIS/MICROS/NANOS).

Rule-based zones stay correct past the TZif enumeration horizon (2037): the
trailing POSIX TZ footer string (v2+) is parsed and its DST rules expanded
through ``EXPAND_THROUGH_YEAR``, matching what the JVM's ZoneRulesProvider
computes from the same rules.
"""

from __future__ import annotations

import datetime
import functools
import re
import struct

import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import TypeId

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo", "/etc/zoneinfo")

MICROS = 1_000_000
_SENTINEL = np.iinfo(np.int64).min // 2  # below any representable micros

# How far past the TZif table the POSIX footer rules are expanded.  2200
# covers any timestamp a NANOS column can represent (int64 nanos max out in
# 2262) at ~2 transitions/year of table size.
EXPAND_THROUGH_YEAR = 2200

# ticks per second for each supported precision
_TICKS = {
    TypeId.TIMESTAMP_SECONDS: 1,
    TypeId.TIMESTAMP_MILLISECONDS: 1_000,
    TypeId.TIMESTAMP_MICROSECONDS: 1_000_000,
    TypeId.TIMESTAMP_NANOSECONDS: 1_000_000_000,
}


def _read_tzif(name: str) -> bytes:
    if "/" in name and name.startswith("/"):
        path_candidates = [name]
    else:
        path_candidates = [f"{p}/{name}" for p in _TZPATHS]
    for p in path_candidates:
        try:
            with open(p, "rb") as f:
                return f.read()
        except OSError:
            continue
    raise ValueError(f"unknown timezone {name!r}")


# --- POSIX TZ footer (TZif v2+ trailing rule string) -----------------------

_POSIX_NAME = r"(?:[A-Za-z]{3,}|<[A-Za-z0-9+\-]{3,}>)"
_POSIX_OFF = r"([+-]?\d{1,2}(?::\d{1,2}(?::\d{1,2})?)?)"


def _parse_posix_offset(s: str) -> int:
    """POSIX offset (west-positive, local + offset = UTC) -> seconds."""
    sign = -1 if s.startswith("-") else 1
    parts = s.lstrip("+-").split(":")
    sec = int(parts[0]) * 3600
    if len(parts) > 1:
        sec += int(parts[1]) * 60
    if len(parts) > 2:
        sec += int(parts[2])
    return sign * sec


def _parse_posix_time(s: str | None) -> int:
    """Transition time-of-day (may be negative or >24h, TZ extension)."""
    if not s:
        return 2 * 3600
    sign = -1 if s.startswith("-") else 1
    parts = s.lstrip("+-").split(":")
    sec = int(parts[0]) * 3600
    if len(parts) > 1:
        sec += int(parts[1]) * 60
    if len(parts) > 2:
        sec += int(parts[2])
    return sign * sec


def _rule_day(year: int, rule: str) -> datetime.date:
    """Resolve an Mm.w.d / Jn / n date rule for one year."""
    if rule.startswith("M"):
        m, w, d = (int(x) for x in rule[1:].split("."))
        # d-th weekday (0=Sunday) of week w (5 = last) in month m
        first = datetime.date(year, m, 1)
        want_wd = d % 7  # python: Monday=0 ... convert below
        # python weekday(): Mon=0..Sun=6; POSIX: Sun=0..Sat=6
        first_wd = (first.weekday() + 1) % 7
        day1 = 1 + (want_wd - first_wd) % 7
        day = day1 + (w - 1) * 7
        # clamp week 5 = last occurrence
        while True:
            try:
                out = datetime.date(year, m, day)
                return out
            except ValueError:
                day -= 7
    if rule.startswith("J"):  # 1..365, Feb 29 never counted
        n = int(rule[1:])
        d = datetime.date(year, 1, 1) + datetime.timedelta(days=n - 1)
        if (datetime.date(year, 3, 1) - datetime.date(year, 1, 1)).days == 60 \
                and n >= 60:  # leap year, day >= Mar 1
            d += datetime.timedelta(days=1)
        return d
    n = int(rule)  # 0..365, leap day counted
    return datetime.date(year, 1, 1) + datetime.timedelta(days=n)


def _parse_posix_tz(footer: str):
    """Parse a POSIX TZ string -> (std_off, dst_off, start_rule, end_rule).

    Offsets are utoff seconds (east-positive, the TZif convention — POSIX
    signs are inverted).  Returns None for rules this implementation cannot
    expand; constant-offset strings return (std, None, None, None).
    """
    m = re.match(
        rf"^{_POSIX_NAME}{_POSIX_OFF}"
        rf"(?:({_POSIX_NAME})(?:{_POSIX_OFF})?"
        rf"(?:,([^,/]+)(?:/([^,]+))?,([^,/]+)(?:/([^,]+))?)?)?$",
        footer.strip())
    if not m:
        return None
    std_posix = _parse_posix_offset(m.group(1))
    std = -std_posix  # POSIX west-positive -> utoff east-positive
    if not m.group(2):
        return (std, None, None, None)
    dst = -_parse_posix_offset(m.group(3)) if m.group(3) else std + 3600
    if not m.group(4):
        # DST name without rules: POSIX default rules (US); rare in TZif
        start = ("M3.2.0", 2 * 3600)
        end = ("M11.1.0", 2 * 3600)
        return (std, dst, start, end)
    start = (m.group(4), _parse_posix_time(m.group(5)))
    end = (m.group(6), _parse_posix_time(m.group(7)))
    return (std, dst, start, end)


_EPOCH = datetime.date(1970, 1, 1)


def _expand_posix(footer: str, from_instant: int):
    """Generate (instants, offsets) seconds-UTC from the footer rules for
    all transitions strictly after ``from_instant`` through
    EXPAND_THROUGH_YEAR.  Empty arrays when the footer is constant-offset
    or unparseable."""
    parsed = _parse_posix_tz(footer)
    if not parsed or parsed[1] is None:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    std, dst, (start_rule, start_tod), (end_rule, end_tod) = parsed
    year0 = max(1970, datetime.datetime.fromtimestamp(
        max(from_instant, 0), datetime.timezone.utc).year)
    inst, offs = [], []
    for year in range(year0, EXPAND_THROUGH_YEAR + 1):
        sd = _rule_day(year, start_rule)
        ed = _rule_day(year, end_rule)
        # start time is wall clock under std offset; end under dst offset
        s_utc = (sd - _EPOCH).days * 86400 + start_tod - std
        e_utc = (ed - _EPOCH).days * 86400 + end_tod - dst
        for t, o in sorted([(s_utc, dst), (e_utc, std)]):
            if t > from_instant:
                inst.append(t)
                offs.append(o)
    return np.array(inst, np.int64), np.array(offs, np.int64)


@functools.lru_cache(maxsize=None)
def load_transitions(name: str) -> tuple[np.ndarray, np.ndarray]:
    """(instants int64[T] seconds-UTC, offsets int64[T] seconds) for a zone.

    ``offsets[i]`` is in force from ``instants[i]`` (inclusive) to
    ``instants[i+1]``; ``instants[0]`` is -inf sentinel carrying the earliest
    known offset.  Enumerated TZif transitions are extended by the expanded
    POSIX footer rules (post-2037 correctness for rule-based zones).
    """
    raw = _read_tzif(name)
    if raw[:4] != b"TZif":
        raise ValueError(f"{name!r}: not a TZif file")
    version = raw[4:5]

    def parse_block(buf, off, time_size, time_fmt):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt) = \
            struct.unpack(">6I", buf[off + 20:off + 44])
        p = off + 44
        times = np.frombuffer(buf, dtype=time_fmt, count=timecnt, offset=p)
        p += timecnt * time_size
        idx = np.frombuffer(buf, dtype=np.uint8, count=timecnt, offset=p)
        p += timecnt
        ttinfo = []
        for i in range(typecnt):
            utoff, isdst, abbrind = struct.unpack(
                ">iBB", buf[p + 6 * i:p + 6 * i + 6])
            ttinfo.append(utoff)
        p += 6 * typecnt + charcnt + leapcnt * (time_size + 4)
        p += isstdcnt + isutcnt
        return times.astype(np.int64), idx, np.array(ttinfo, np.int64), p

    footer = ""
    if version >= b"2":
        # skip the v1 block, parse the 64-bit v2 block
        _, _, _, end_v1 = parse_block(raw, 0, 4, ">i4")
        times, idx, offsets_by_type, end_v2 = parse_block(raw, end_v1, 8,
                                                          ">i8")
        # trailing newline-enclosed POSIX TZ string (RFC 9636 §3.3)
        tail = raw[end_v2:].decode("ascii", "replace")
        if tail.startswith("\n"):
            footer = tail[1:].split("\n", 1)[0]
    else:
        times, idx, offsets_by_type, _ = parse_block(raw, 0, 4, ">i4")

    if offsets_by_type.size == 0:
        raise ValueError(f"{name!r}: no time types")
    first = offsets_by_type[0]
    if times.size:
        instants = np.concatenate([[_SENTINEL], times]).astype(np.int64)
        offs = np.concatenate([[first], offsets_by_type[idx]]).astype(np.int64)
    else:
        instants = np.array([_SENTINEL], np.int64)
        offs = np.array([first], np.int64)
    if footer:
        last = int(instants[-1]) if instants.size > 1 else 0
        ext_i, ext_o = _expand_posix(footer, last)
        if ext_i.size:
            instants = np.concatenate([instants, ext_i])
            offs = np.concatenate([offs, ext_o])
    return instants, offs


@functools.lru_cache(maxsize=None)
def _device_tables(name: str, ticks: int = MICROS):
    instants, offs = load_transitions(name)
    # Scale only the real transitions: the -2^62 sentinel times 10^6 is a
    # multiple of 2^64 and wraps to 0, unsorting the table and breaking
    # searchsorted.  The sentinel stays pre-scaled (it is already below any
    # representable tick value).
    scaled = np.concatenate([[_SENTINEL], instants[1:] * ticks])
    return jnp.asarray(scaled), jnp.asarray(offs * ticks)


@functools.lru_cache(maxsize=None)
def _device_wall_tables(name: str, ticks: int = MICROS):
    """Cached (wall-clock transition instants, offsets) for a zone.

    ``wall[i]`` is the local wall-clock tick at which ``offs[i]`` takes
    effect; sentinel stays pre-scaled (see _device_tables on int64 wrap).
    """
    instants, offs = load_transitions(name)
    wall = np.concatenate([[_SENTINEL],
                           instants[1:] * ticks + offs[1:] * ticks])
    return jnp.asarray(wall), jnp.asarray(offs * ticks)


def _check_ts(col: Column) -> int:
    """Validate the column is a timestamp; return its ticks/second."""
    ticks = _TICKS.get(col.dtype.id)
    if ticks is None:
        raise TypeError(f"expected a TIMESTAMP column, got {col.dtype!r}")
    return ticks


def utc_to_local(col: Column, zone: str) -> Column:
    """Spark from_utc_timestamp: shift a UTC instant to the zone's wall clock."""
    ticks = _check_ts(col)
    instants, offs = _device_tables(zone, ticks)
    idx = jnp.clip(jnp.searchsorted(instants, col.data, side="right") - 1,
                   0, None)  # pre-sentinel timestamps take the earliest offset
    out = col.data + jnp.take(offs, idx)
    return Column(col.dtype, data=out, validity=col.validity)


def local_to_utc(col: Column, zone: str) -> Column:
    """Spark to_utc_timestamp: interpret wall-clock micros in the zone.

    Gap/overlap resolution: the offset in force before the wall-clock
    transition wins (Java earlier-offset rule).
    """
    ticks = _check_ts(col)
    wall_dev, offs_dev = _device_wall_tables(zone, ticks)
    idx = jnp.searchsorted(wall_dev, col.data, side="right") - 1
    idx = jnp.clip(idx, 0, wall_dev.shape[0] - 1)
    out = col.data - jnp.take(offs_dev, idx)
    return Column(col.dtype, data=out, validity=col.validity)
