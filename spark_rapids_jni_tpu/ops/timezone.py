"""TimeZoneDB: timezone-aware timestamp conversion from device transition tables.

TPU-native rebuild of the reference's GpuTimeZoneDB component (BASELINE.json
north-star set; Java/CUDA side appears post-snapshot as GpuTimeZoneDB.java —
it loads each zone's transition rules into a device table once, then kernels
binary-search per row).  Same design here:

- host side: parse the system TZif database (/usr/share/zoneinfo, the same
  IANA data the JVM uses) into (transition instants, utc offsets) int64
  arrays, cached per zone;
- device side: ``searchsorted`` into the transition instants picks each row's
  offset — the vectorized form of the reference's per-thread binary search.

Semantics match Spark's from_utc_timestamp/to_utc_timestamp: timestamps are
micros since epoch; local->UTC resolves gaps/overlaps by using the offset in
force *before* the wall-clock transition point (Java's earlier-offset rule
for overlaps).  Transitions cover what the TZif tables enumerate (through
2037 for rule-based zones; the trailing POSIX TZ string is not expanded —
post-2037 rule-based conversions reuse the last known offset).
"""

from __future__ import annotations

import functools
import struct

import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import TypeId

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo", "/etc/zoneinfo")

MICROS = 1_000_000
_SENTINEL = np.iinfo(np.int64).min // 2  # below any representable micros


def _read_tzif(name: str) -> bytes:
    if "/" in name and name.startswith("/"):
        path_candidates = [name]
    else:
        path_candidates = [f"{p}/{name}" for p in _TZPATHS]
    for p in path_candidates:
        try:
            with open(p, "rb") as f:
                return f.read()
        except OSError:
            continue
    raise ValueError(f"unknown timezone {name!r}")


@functools.lru_cache(maxsize=None)
def load_transitions(name: str) -> tuple[np.ndarray, np.ndarray]:
    """(instants int64[T] seconds-UTC, offsets int64[T] seconds) for a zone.

    ``offsets[i]`` is in force from ``instants[i]`` (inclusive) to
    ``instants[i+1]``; ``instants[0]`` is -inf sentinel carrying the earliest
    known offset.
    """
    raw = _read_tzif(name)
    if raw[:4] != b"TZif":
        raise ValueError(f"{name!r}: not a TZif file")
    version = raw[4:5]

    def parse_block(buf, off, time_size, time_fmt):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt) = \
            struct.unpack(">6I", buf[off + 20:off + 44])
        p = off + 44
        times = np.frombuffer(buf, dtype=time_fmt, count=timecnt, offset=p)
        p += timecnt * time_size
        idx = np.frombuffer(buf, dtype=np.uint8, count=timecnt, offset=p)
        p += timecnt
        ttinfo = []
        for i in range(typecnt):
            utoff, isdst, abbrind = struct.unpack(
                ">iBB", buf[p + 6 * i:p + 6 * i + 6])
            ttinfo.append(utoff)
        p += 6 * typecnt + charcnt + leapcnt * (time_size + 4)
        p += isstdcnt + isutcnt
        return times.astype(np.int64), idx, np.array(ttinfo, np.int64), p

    if version >= b"2":
        # skip the v1 block, parse the 64-bit v2 block
        _, _, _, end_v1 = parse_block(raw, 0, 4, ">i4")
        times, idx, offsets_by_type, _ = parse_block(raw, end_v1, 8, ">i8")
    else:
        times, idx, offsets_by_type, _ = parse_block(raw, 0, 4, ">i4")

    if offsets_by_type.size == 0:
        raise ValueError(f"{name!r}: no time types")
    first = offsets_by_type[0]
    if times.size:
        instants = np.concatenate([[_SENTINEL], times]).astype(np.int64)
        offs = np.concatenate([[first], offsets_by_type[idx]]).astype(np.int64)
    else:
        instants = np.array([_SENTINEL], np.int64)
        offs = np.array([first], np.int64)
    return instants, offs


@functools.lru_cache(maxsize=None)
def _device_tables(name: str):
    instants, offs = load_transitions(name)
    # Scale only the real transitions: the -2^62 sentinel times 10^6 is a
    # multiple of 2^64 and wraps to 0, unsorting the table and breaking
    # searchsorted.  The sentinel stays pre-scaled (it is already below any
    # micros value).
    scaled = np.concatenate([[_SENTINEL], instants[1:] * MICROS])
    return jnp.asarray(scaled), jnp.asarray(offs * MICROS)


@functools.lru_cache(maxsize=None)
def _device_wall_tables(name: str):
    """Cached (wall-clock transition instants, offsets) in micros for a zone.

    ``wall[i]`` is the local wall-clock micros at which ``offs[i]`` takes
    effect; sentinel stays pre-scaled (see _device_tables on int64 wrap).
    """
    instants, offs = load_transitions(name)
    wall = np.concatenate([[_SENTINEL], instants[1:] * MICROS + offs[1:] * MICROS])
    return jnp.asarray(wall), jnp.asarray(offs * MICROS)


def _check_ts(col: Column):
    if col.dtype.id != TypeId.TIMESTAMP_MICROSECONDS:
        raise TypeError(
            f"expected TIMESTAMP_MICROSECONDS, got {col.dtype!r}")


def utc_to_local(col: Column, zone: str) -> Column:
    """Spark from_utc_timestamp: shift a UTC instant to the zone's wall clock."""
    _check_ts(col)
    instants, offs = _device_tables(zone)
    idx = jnp.clip(jnp.searchsorted(instants, col.data, side="right") - 1,
                   0, None)  # pre-sentinel timestamps take the earliest offset
    out = col.data + jnp.take(offs, idx)
    return Column(col.dtype, data=out, validity=col.validity)


def local_to_utc(col: Column, zone: str) -> Column:
    """Spark to_utc_timestamp: interpret wall-clock micros in the zone.

    Gap/overlap resolution: the offset in force before the wall-clock
    transition wins (Java earlier-offset rule).
    """
    _check_ts(col)
    wall_dev, offs_dev = _device_wall_tables(zone)
    idx = jnp.searchsorted(wall_dev, col.data, side="right") - 1
    idx = jnp.clip(idx, 0, wall_dev.shape[0] - 1)
    out = col.data - jnp.take(offs_dev, idx)
    return Column(col.dtype, data=out, validity=col.validity)
