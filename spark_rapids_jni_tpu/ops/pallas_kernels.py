"""VMEM-tiled Pallas kernels for the row-conversion hot path.

TPU analog of the reference's staged shared-memory kernels
(reference src/main/cpp/src/row_conversion.cu:75-108, 278-300): the CUDA
version stages rows in dynamic shared memory so global-memory transactions
are int64-coalesced; here a Pallas kernel stages plane blocks in VMEM and
performs the 32-row-group interleave on-chip, so HBM sees only dense,
full-lane reads and writes.

Availability: Mosaic compilation is not available on every deployment (the
remote-compile path of tunneled devices rejects Pallas kernels); callers must
check ``available()`` and fall back to the pure-XLA wire path in
``ops.row_conversion`` (concat + constant lane permutation).  The kernels are
correctness-tested in interpreter mode on CPU either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_GROUP = 32  # rows per wire group, = row_conversion.WIRE_GROUP


def _interleave_kernel(p_ref, o_ref):
    """(nwords, B) plane block -> (B//32, 32*nwords) wire block in VMEM."""
    b = p_ref.shape[1]
    o_ref[:] = p_ref[:].T.reshape(b // _GROUP, _GROUP * p_ref.shape[0])


def _deinterleave_kernel(w_ref, o_ref):
    """(B//32, 32*nwords) wire block -> (nwords, B) plane block in VMEM."""
    nw = o_ref.shape[0]
    b = o_ref.shape[1]
    o_ref[:] = w_ref[:].reshape(b, nw).T


def _pallas_call(nwords: int, n: int, block_rows: int, forward: bool,
                 interpret: bool):
    from jax.experimental import pallas as pl

    grid = (n // block_rows,)
    plane_spec = pl.BlockSpec((nwords, block_rows), lambda r: (0, r))
    wire_spec = pl.BlockSpec((block_rows // _GROUP, _GROUP * nwords),
                             lambda r: (r, 0))
    if forward:
        in_specs, out_specs = [plane_spec], wire_spec
        out_shape = jax.ShapeDtypeStruct((n // _GROUP, _GROUP * nwords),
                                         jnp.uint32)
        body = _interleave_kernel
    else:
        in_specs, out_specs = [wire_spec], plane_spec
        out_shape = jax.ShapeDtypeStruct((nwords, n), jnp.uint32)
        body = _deinterleave_kernel
    return pl.pallas_call(body, grid=grid, in_specs=in_specs,
                          out_specs=out_specs, out_shape=out_shape,
                          interpret=interpret)


# Mosaic tiling: the plane block (nwords, B) needs B % 128 == 0 (lane dim)
# and the wire block (B // 32, 32 * nwords) needs B // 32 % 8 == 0, so
# blocks step in units of 256 rows; inputs are padded up to a block multiple.
_BLOCK_ALIGN = 256


def _pick_block_rows(n: int, nwords: int) -> int:
    # VMEM budget ~ 2 blocks in flight * 2 (in+out) * 4B * nwords * block
    target = max(_BLOCK_ALIGN,
                 (2 << 20) // max(nwords * 4, 1)
                 // _BLOCK_ALIGN * _BLOCK_ALIGN)
    return min(-(-n // _BLOCK_ALIGN) * _BLOCK_ALIGN, target)


def interleave_planes(planes, *, interpret: bool = False) -> jnp.ndarray:
    """Stack of word planes ``[u32[n]] * nwords`` -> wire ``u32[n*nwords]``.

    Requires n % 32 == 0 (callers pad, like the 32-row batch alignment the
    wire format already guarantees — reference row_conversion.cu:477-479).
    """
    nwords = len(planes)
    n = planes[0].shape[0]
    if n % _GROUP:
        raise ValueError(f"n={n} not a multiple of {_GROUP}")
    block = _pick_block_rows(n, nwords)
    padded = -(-n // block) * block
    mat = jnp.stack(planes, axis=0)  # (nwords, n) — dense concat
    if padded != n:  # pad fuses into the stack producer
        mat = jnp.pad(mat, ((0, 0), (0, padded - n)))
    out = _pallas_call(nwords, padded, block, True, interpret)(mat)
    return out.reshape(-1)[:n * nwords]


def deinterleave_wire(wire: jnp.ndarray, nwords: int, *,
                      interpret: bool = False) -> list[jnp.ndarray]:
    """Wire ``u32[n*nwords]`` -> word planes ``[u32[n]] * nwords``."""
    n = wire.shape[0] // nwords
    if n % _GROUP:
        raise ValueError(f"n={n} not a multiple of {_GROUP}")
    block = _pick_block_rows(n, nwords)
    padded = -(-n // block) * block
    w2 = wire.reshape(n // _GROUP, _GROUP * nwords)
    if padded != n:
        w2 = jnp.pad(w2, ((0, (padded - n) // _GROUP), (0, 0)))
    mat = _pallas_call(nwords, padded, block, False, interpret)(w2)
    return [mat[w, :n] for w in range(nwords)]


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """Probe whether Mosaic can compile on this backend (cached).

    The probe is a REAL gridded interleave (12 words x 2 grid blocks), not a
    toy single-block kernel: deployments exist (axon remote-compile, r4)
    where a trivial no-grid kernel compiles but every gridded pallas_call is
    rejected by the compile helper — a single-block probe would report
    available and then fail on first real use.

    The probe must run EAGERLY even when first consulted inside a jit
    trace (``ensure_compile_time_eval``): otherwise the probe kernel is
    staged into the CALLER's program instead of compiling here, the
    Mosaic rejection surfaces at the caller's lowering — outside this
    try — and a backend with no Pallas support reports available."""
    try:
        with jax.ensure_compile_time_eval():
            n = 2 * _BLOCK_ALIGN
            mat = jnp.zeros((12, n), jnp.uint32)
            # force block_rows = _BLOCK_ALIGN so the grid is genuinely 2
            # blocks (interleave_planes would auto-pick one block at this
            # size)
            out = _pallas_call(12, n, _BLOCK_ALIGN, True, False)(mat)
            np.asarray(out)
        return True
    except Exception:
        return False
