"""GroupBy aggregation: sort-based segmented reduction.

The TPU-native answer to cudf's hash_groupby (what the reference's Spark plans
call HashAggregate — BASELINE.json configs[2]).  A hash table with open
addressing is a pointer-chasing structure XLA can't vectorize; sorting by the
group keys and running segmented reductions is the same O(n log n) work
expressed as radix sort + scans, which map perfectly onto the VPU:

    1. order  = lexsort(key encodings)          (ops/order.py)
    2. bounds = sorted row != previous row      (rows_differ_from_prev)
    3. seg_id = cumsum(bounds) - 1
    4. each aggregation = jax.ops.segment_<op>(values[order], seg_id)

``groupby_padded`` is the fully jit-able core: output padded to n rows with a
group-count scalar (static shapes for pjit pipelines — the distributed
partial-aggregation path).  ``groupby`` compacts at the host boundary.

Null semantics match Spark: null keys form their own group (nulls equal in
GROUP BY); null values are excluded from sum/min/max/mean/count(col), while
count(*) counts rows.  sum/mean over FLOAT64 use the hardware float
approximation (float_values); min/max over FLOAT64 run on the total-order bit
encoding and are exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..dtypes import DType, TypeId, INT64, FLOAT64
from .order import SortKey, encode_keys, rows_differ_from_prev
from .selection import gather_table
from . import order as _order
from ..utils.tracing import traced

AGGS = ("sum", "min", "max", "mean", "count", "count_all", "var", "std",
        "sumsq", "fsum", "first", "last", "collect_list")

# ops the sort-carried fast path implements; first/last need positional
# selection and collect_list is ragged (host-compacted in ``groupby``)
_FAST_OPS = frozenset(AGGS) - {"first", "last", "collect_list"}


# ---------------------------------------------------------------------------
# fast path: sort-carried aggregation (no gathers, no scatters)
#
# Profiling on TPU (docs/PERF.md methodology): XLA's segment_sum lowers to a
# serialized scatter (~165 ms for 2M rows) and a random 2M-row gather costs
# ~28 ms, while a multi-operand lax.sort is ~5 ms and a cumsum ~2.5 ms.  So
# the fast path never gathers or scatters: value columns ride the key sort
# as payload operands, sums come from prefix-sum differences at segment
# starts, min/max from a doubling segmented scan, and group compaction is a
# second payload-carrying sort keyed by segment id.  This is the TPU shape
# of the reference's hash aggregation (BASELINE configs[2]): measured ~19.6x
# the scatter-based formulation on a 2M-row 100k-group aggregation.
# ---------------------------------------------------------------------------

def _shift_down(arr, shift: int, fill):
    """arr shifted so row i sees row i-shift (front-filled), gather-free."""
    pad = jnp.full((shift,) + arr.shape[1:], fill, arr.dtype)
    return jnp.concatenate([pad, arr[:-shift]], axis=0)


def _seg_scan(vals, seg, op, identity):
    """Running ``op`` from each segment's start, via log2(n) doubling passes."""
    n = vals.shape[0]
    shift = 1
    while shift < n:
        pv = _shift_down(vals, shift, identity)
        ps = _shift_down(seg, shift, jnp.int32(-1))
        vals = jnp.where(ps == seg, op(vals, pv), vals)
        shift *= 2
    return vals


def _seg_first_valid(vals, has, seg):
    """Forward-fill each segment's first VALID value (doubling passes).

    Rows before any valid value keep their own payload; callers mask those
    rows out anyway.  Gather-free, like _seg_scan."""
    n = vals.shape[0]
    shift = 1
    while shift < n:
        pv = _shift_down(vals, shift, jnp.zeros((), vals.dtype))
        ph = _shift_down(has, shift, jnp.zeros((), jnp.bool_))
        ps = _shift_down(seg, shift, jnp.int32(-1))
        same = ps == seg
        take_prev = same & ph  # an earlier valid value wins
        vals = jnp.where(take_prev, pv, vals)
        has = jnp.where(same, has | ph, has)
        shift *= 2
    return vals


def _seg_last_valid(vals, has, seg):
    """Forward-fill the NEAREST preceding VALID value per row (doubling).

    Unlike _seg_first_valid (earliest valid wins — the whole segment sees
    one value), this keeps the latest: a row only adopts an earlier value
    while it has none yet.  Rows before any valid keep their payload."""
    n = vals.shape[0]
    shift = 1
    while shift < n:
        pv = _shift_down(vals, shift, jnp.zeros((), vals.dtype))
        ph = _shift_down(has, shift, jnp.zeros((), jnp.bool_))
        ps = _shift_down(seg, shift, jnp.int32(-1))
        same = ps == seg
        take_prev = same & ph & jnp.logical_not(has)
        vals = jnp.where(take_prev, pv, vals)
        has = jnp.where(same, has | ph, has)
        shift *= 2
    return vals


def _fast_eligible(key_cols, agg_cols) -> bool:
    for c in key_cols + agg_cols:
        if c.data is None or c.dtype.is_string or c.data.ndim != 1:
            return False
    return True


def _sum_dtype_and_vals(col: Column, sval, svalid):
    """Widened contribution vector + (output dtype, is_float) per Spark."""
    tid = col.dtype.id
    if tid == TypeId.FLOAT64:
        vals = Column(col.dtype, data=sval).float_values()
        return vals, FLOAT64, True
    if tid == TypeId.FLOAT32:
        return jnp.asarray(sval, jnp.float64), FLOAT64, True
    out = col.dtype if col.dtype.is_decimal else INT64
    return sval.astype(jnp.int64), out, False


def _float64_vals(col: Column, sval) -> jnp.ndarray:
    """float64 value vector (Spark casts var/std inputs to double)."""
    tid = col.dtype.id
    if tid == TypeId.FLOAT64:
        return Column(col.dtype, data=sval).float_values()
    if col.dtype.is_decimal:
        return sval.astype(jnp.float64) * (10.0 ** col.dtype.scale)
    return jnp.asarray(sval, jnp.float64)


def _fast_groupby_padded(key_cols, agg_specs, row_mask):
    """(out_keys specs, out_aggs Columns, ngroups) — see groupby_padded."""
    n = key_cols[0].data.shape[0]
    words = encode_keys([SortKey(c) for c in key_cols])
    if row_mask is not None:
        words = [(~row_mask).astype(jnp.uint64)] + words
    nw = len(words)

    # distinct agg-input columns ride the sort once each
    distinct: list[Column] = []
    col_slot: dict[int, int] = {}
    for col, op in agg_specs:
        if col is not None and id(col) not in col_slot:
            col_slot[id(col)] = len(distinct)
            distinct.append(col)

    # non-nullable columns skip the validity payload (no point carrying a
    # constant all-ones byte vector through the sort)
    payloads = []
    for c in key_cols + distinct:
        payloads.append(c.data)
        if c.validity is not None:
            payloads.append(c.validity.astype(jnp.uint8))
    sorted_ops = jax.lax.sort(tuple(words) + tuple(payloads), num_keys=nw,
                              is_stable=True)
    swords = sorted_ops[:nw]
    sp = sorted_ops[nw:]
    ones = jnp.ones((n,), jnp.bool_)
    sdata, svalid_list = [], []
    pi = 0
    for c in key_cols + distinct:
        sdata.append(sp[pi])
        pi += 1
        if c.validity is not None:
            svalid_list.append(sp[pi].astype(jnp.bool_))
            pi += 1
        else:
            svalid_list.append(ones)
    skey_data = sdata[:len(key_cols)]
    skey_valid = svalid_list[:len(key_cols)]
    sval_of = sdata[len(key_cols):]
    svalid_of = svalid_list[len(key_cols):]

    first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    bounds = first
    for w in swords:
        bounds = bounds | jnp.concatenate([first[:1], w[1:] != w[:-1]])
    seg = jnp.cumsum(bounds.astype(jnp.int32)) - 1
    live_sorted = None if row_mask is None else (swords[0] == 0)
    if row_mask is None:
        ngroups = seg[-1] + 1
    else:
        ngroups = jnp.sum((bounds & live_sorted).astype(jnp.int32))

    live_b = bounds if live_sorted is None else (bounds & live_sorted)
    start_key = jnp.where(live_b, seg, jnp.int32(n))
    is_end = jnp.concatenate([bounds[1:], jnp.ones((1,), jnp.bool_)])
    live_e = is_end if live_sorted is None else (is_end & live_sorted)
    end_key = jnp.where(live_e, seg, jnp.int32(n))

    # prefix-before vectors (psb trick) for every sum-like aggregation; the
    # compacted psb of group g+1 minus group g's IS the segment total —
    # exact for integers; floats use the scan path below instead
    start_payloads: list = list(skey_data) + [m.astype(jnp.uint8)
                                              for m in skey_valid]
    end_payloads: list = []
    plans = []  # (op, col_slot, start_slots/end_slots info ...)

    idx = jnp.arange(n, dtype=jnp.int32)
    count_cache: dict = {}

    def add_start_payload(arr):
        start_payloads.append(arr)
        return len(start_payloads) - 1

    def add_end_payload(arr):
        end_payloads.append(arr)
        return len(end_payloads) - 1

    for col, op in agg_specs:
        if op == "count_all":
            m = jnp.ones((n,), jnp.int64) if live_sorted is None else \
                live_sorted.astype(jnp.int64)
            ps = jnp.cumsum(m)
            grand = ps[-1]
            plans.append(("psb", None, add_start_payload(ps - m), grand,
                          INT64, None))
            continue
        slot = col_slot[id(col)]
        sval, svalid = sval_of[slot], svalid_of[slot]
        if live_sorted is not None:
            svalid = svalid & live_sorted
        if slot in count_cache:
            count_slot, cgrand = count_cache[slot]
        else:
            cm = svalid.astype(jnp.int64)
            cps = jnp.cumsum(cm)
            count_slot = add_start_payload(cps - cm)
            cgrand = cps[-1]
            count_cache[slot] = (count_slot, cgrand)
        if op == "count":
            plans.append(("psb", None, count_slot, cgrand, INT64, None))
            continue
        if op in ("sum", "mean"):
            vals, out_dtype, is_float = _sum_dtype_and_vals(col, sval, svalid)
            if is_float:
                zero = jnp.zeros((), vals.dtype)
                m = jnp.where(svalid, vals, zero)
                scanned = _seg_scan(m, seg, jnp.add, zero)
                plans.append((op + "_scan", col, add_end_payload(scanned),
                              (count_slot, cgrand), out_dtype, None))
            else:
                zero = jnp.zeros((), vals.dtype)
                m = jnp.where(svalid, vals, zero)
                ps = jnp.cumsum(m)
                plans.append((op + "_psb", col, add_start_payload(ps - m),
                              (count_slot, cgrand, ps[-1]), out_dtype, None))
            continue
        if op in ("var", "std", "sumsq", "fsum"):
            vf = _float64_vals(col, sval)
            zero = jnp.zeros((), jnp.float64)
            if op in ("var", "std"):
                # shift by each segment's first VALID value before
                # accumulating moments (variance is shift-invariant; the
                # naive two-moment formula cancels catastrophically when
                # |mean| >> std).  Null-slot payloads are arbitrary (NaN,
                # garbage), so the pivot must come from a valid row.
                pivot = _seg_first_valid(jnp.where(svalid, vf, zero),
                                         svalid, seg)
                vf = jnp.where(svalid, vf - pivot, zero)
            m = jnp.where(svalid, vf, zero)
            s_slot = add_end_payload(_seg_scan(m, seg, jnp.add, zero))
            q_slot = add_end_payload(_seg_scan(m * m, seg, jnp.add, zero))
            plans.append(("var_scan", col, (s_slot, q_slot),
                          (count_slot, cgrand, op), FLOAT64, None))
            continue
        if op in ("min", "max"):
            tid = col.dtype.id
            if tid in (TypeId.FLOAT32, TypeId.FLOAT64):
                enc = _order._fixed_to_u64(Column(col.dtype, data=sval))
                ident = jnp.uint64(2**64 - 1) if op == "min" else jnp.uint64(0)
                enc = jnp.where(svalid, enc, ident)
                combine = jnp.minimum if op == "min" else jnp.maximum
                scanned = _seg_scan(enc, seg, combine, ident)
                plans.append(("minmax_enc", col, add_end_payload(scanned),
                              (count_slot, cgrand, op), col.dtype, None))
            else:
                if jnp.issubdtype(sval.dtype, jnp.integer):
                    info = jnp.iinfo(sval.dtype)
                    ident = jnp.asarray(info.max if op == "min" else info.min,
                                        sval.dtype)
                else:
                    ident = jnp.asarray(jnp.inf if op == "min" else -jnp.inf,
                                        sval.dtype)
                m = jnp.where(svalid, sval, ident)
                combine = jnp.minimum if op == "min" else jnp.maximum
                scanned = _seg_scan(m, seg, combine, ident)
                plans.append(("minmax", col, add_end_payload(scanned),
                              (count_slot, cgrand, op), col.dtype, None))
            continue
        raise ValueError(f"unknown aggregation {op!r}; expected one of {AGGS}")

    comp_s = jax.lax.sort((start_key,) + tuple(start_payloads), num_keys=1,
                          is_stable=True)[1:]
    if end_payloads:
        comp_e = jax.lax.sort((end_key,) + tuple(end_payloads), num_keys=1,
                              is_stable=True)[1:]

    nkeys = len(key_cols)
    out_keys = []
    for i, c in enumerate(key_cols):
        out_keys.append(("fixed", c.dtype, comp_s[i],
                         comp_s[nkeys + i].astype(jnp.bool_)))

    def psb_total(slot, grand):
        psb = comp_s[slot]
        nxt = jnp.concatenate([psb[1:], psb[-1:]])
        return jnp.where(idx == ngroups - 1, grand, nxt) - psb

    out_aggs = []
    for kind, col, slot, extra, out_dtype, _ in plans:
        if kind == "psb":
            out_aggs.append(Column(INT64, data=psb_total(slot, extra)))
            continue
        if kind in ("sum_psb", "mean_psb"):
            count_slot, cgrand, grand = extra
            counts = psb_total(count_slot, cgrand)
            s = psb_total(slot, grand)
            has_any = counts > 0
            if kind == "mean_psb":
                m = s.astype(jnp.float64) / jnp.maximum(counts, 1).astype(
                    jnp.float64)
                if col.dtype.is_decimal:
                    m = m * (10.0 ** col.dtype.scale)
                out_aggs.append(Column.fixed(FLOAT64, m, validity=has_any))
            else:
                out_aggs.append(Column(out_dtype, data=s, validity=has_any))
            continue
        if kind in ("sum_scan", "mean_scan"):
            count_slot, cgrand = extra
            counts = psb_total(count_slot, cgrand)
            has_any = counts > 0
            s = comp_e[slot]
            if kind == "mean_scan":
                m = s / jnp.maximum(counts, 1).astype(jnp.float64)
                out_aggs.append(Column.fixed(FLOAT64, m, validity=has_any))
            else:
                out_aggs.append(Column.fixed(FLOAT64, s, validity=has_any))
            continue
        if kind == "var_scan":
            s_slot, q_slot = slot
            count_slot, cgrand, op = extra
            counts = psb_total(count_slot, cgrand)
            s = comp_e[s_slot]
            q = comp_e[q_slot]
            if op in ("sumsq", "fsum"):
                out_aggs.append(Column.fixed(
                    FLOAT64, q if op == "sumsq" else s,
                    validity=counts > 0))
                continue
            nf = counts.astype(jnp.float64)
            var = (q - s * s / jnp.maximum(nf, 1.0)) / \
                jnp.maximum(nf - 1.0, 1.0)
            var = jnp.maximum(var, 0.0)  # clamp catastrophic cancellation
            data = jnp.sqrt(var) if op == "std" else var
            out_aggs.append(Column.fixed(FLOAT64, data, validity=counts > 1))
            continue
        if kind == "minmax":
            count_slot, cgrand, op = extra
            counts = psb_total(count_slot, cgrand)
            out_aggs.append(Column(out_dtype, data=comp_e[slot],
                                   validity=counts > 0))
            continue
        if kind == "minmax_enc":
            count_slot, cgrand, op = extra
            counts = psb_total(count_slot, cgrand)
            data = _order.decode_minmax_bits(comp_e[slot], out_dtype)
            out_aggs.append(Column(out_dtype, data=data,
                                   validity=counts > 0))
            continue
    return out_keys, out_aggs, ngroups


def _seg_ids(keys: list[SortKey], row_mask=None):
    """Sort+segment the rows; masked-out rows sort last as dead groups.

    With ``row_mask`` (padded pipelines, e.g. post-shuffle), the returned
    ``ngroups`` counts only live groups — dead rows sort after every live row
    via a primary mask word, so live groups occupy seg ids [0, ngroups).
    """
    words = encode_keys(keys)
    if row_mask is not None:
        words = [(~row_mask).astype(jnp.uint64)] + words  # live rows first
    order = jnp.lexsort(tuple(reversed(words)))
    bounds = rows_differ_from_prev(words, order)
    seg = jnp.cumsum(bounds.astype(jnp.int32)) - 1
    if order.shape[0] == 0:
        return order, seg, jnp.int32(0)
    if row_mask is None:
        ngroups = seg[-1] + 1
    else:
        live_sorted = jnp.take(row_mask, order)
        ngroups = jnp.sum((bounds & live_sorted).astype(jnp.int32))
    return order, seg, ngroups


def _segment_reduce(op: str, vals, seg, num_segments: int, valid=None):
    if valid is None:
        valid = jnp.ones(vals.shape[:1], jnp.bool_)
    if op == "sum":
        z = jnp.zeros((), vals.dtype)
        contrib = jnp.where(valid, vals, z)
        return jax.ops.segment_sum(contrib, seg, num_segments)
    if op == "min":
        big = jnp.iinfo(vals.dtype).max if jnp.issubdtype(vals.dtype, jnp.integer) \
            else jnp.inf
        contrib = jnp.where(valid, vals, jnp.asarray(big, vals.dtype))
        return jax.ops.segment_min(contrib, seg, num_segments)
    if op == "max":
        small = jnp.iinfo(vals.dtype).min if jnp.issubdtype(vals.dtype, jnp.integer) \
            else -jnp.inf
        contrib = jnp.where(valid, vals, jnp.asarray(small, vals.dtype))
        return jax.ops.segment_max(contrib, seg, num_segments)
    raise ValueError(op)


def _agg_column(col: Column, op: str, order, seg, num_segments: int,
                live_sorted=None):
    """One aggregation over sorted rows.

    ``live_sorted``: sorted-order live-row mask for padded pipelines; the
    single place dead rows are excluded from every op, count_all included.
    """
    if op == "count_all":
        live = jnp.ones(order.shape, jnp.int64) if live_sorted is None \
            else live_sorted.astype(jnp.int64)
        return Column(INT64, data=jax.ops.segment_sum(live, seg, num_segments))

    sval = None if col.data is None else jnp.take(col.data, order, axis=0)
    svalid = jnp.take(col.valid_mask(), order)
    if live_sorted is not None:
        svalid = svalid & live_sorted
    counts = jax.ops.segment_sum(svalid.astype(jnp.int64), seg, num_segments)

    if op == "count":
        return Column(INT64, data=counts)

    has_any = counts > 0
    tid = col.dtype.id
    if op in ("sum", "mean"):
        if tid == TypeId.FLOAT64:
            vals = Column(col.dtype, data=sval).float_values()
        elif tid == TypeId.FLOAT32:
            vals = jnp.asarray(sval, jnp.float64)
        elif col.dtype.is_decimal:
            vals = sval.astype(jnp.int64)  # unscaled sum keeps the scale
        else:
            vals = sval.astype(jnp.int64)  # Spark widens integral sums to long
        s = _segment_reduce("sum", vals, seg, num_segments, svalid)
        if op == "mean":
            m = s.astype(jnp.float64) / jnp.maximum(counts, 1).astype(jnp.float64)
            if col.dtype.is_decimal:
                m = m * (10.0 ** col.dtype.scale)
            return Column.fixed(FLOAT64, m, validity=has_any)
        if tid in (TypeId.FLOAT32, TypeId.FLOAT64):
            return Column.fixed(FLOAT64, s, validity=has_any)
        out_dtype = col.dtype if col.dtype.is_decimal else INT64
        return Column(out_dtype, data=s, validity=has_any)

    if op in ("first", "last"):
        # Spark first/last (ignoreNulls=False): the value at the group's
        # first/last live row in input order (the key sort is stable)
        n = sval.shape[0]
        idxv = jnp.arange(n, dtype=jnp.int32)
        live = jnp.ones((n,), jnp.bool_) if live_sorted is None \
            else live_sorted
        if op == "first":
            pos = jax.ops.segment_min(jnp.where(live, idxv, n),
                                      seg, num_segments)
        else:
            pos = jax.ops.segment_max(jnp.where(live, idxv, -1),
                                      seg, num_segments)
        has_row = (pos >= 0) & (pos < n)
        pos_c = jnp.clip(pos, 0, max(n - 1, 0))
        data = jnp.take(sval, pos_c, axis=0)
        valid = jnp.take(col.valid_mask(), jnp.take(order, pos_c)) & has_row
        return Column(col.dtype, data=data, validity=valid)

    if op in ("var", "std", "sumsq", "fsum"):
        vf = _float64_vals(col, sval)
        if op in ("var", "std"):
            # shift by the segment's first VALID value (variance is
            # shift-invariant; the naive formula cancels when |mean| >> std;
            # null-slot payloads are arbitrary and must not leak in)
            n_ = vf.shape[0]
            first_idx = jax.ops.segment_min(
                jnp.where(svalid, jnp.arange(n_, dtype=jnp.int32),
                          jnp.int32(n_)), seg, num_segments)
            pivot = jnp.take(jnp.where(svalid, vf, 0.0),
                             jnp.clip(first_idx, 0, max(n_ - 1, 0)))
            vf = jnp.where(svalid, vf - jnp.take(pivot, seg), 0.0)
        s = _segment_reduce("sum", vf, seg, num_segments, svalid)
        q = _segment_reduce("sum", vf * vf, seg, num_segments, svalid)
        if op in ("sumsq", "fsum"):
            return Column.fixed(FLOAT64, q if op == "sumsq" else s,
                                validity=has_any)
        nf = counts.astype(jnp.float64)
        var = (q - s * s / jnp.maximum(nf, 1.0)) / jnp.maximum(nf - 1.0, 1.0)
        var = jnp.maximum(var, 0.0)
        data = jnp.sqrt(var) if op == "std" else var
        return Column.fixed(FLOAT64, data, validity=counts > 1)

    if op in ("min", "max"):
        if tid in (TypeId.FLOAT32, TypeId.FLOAT64):
            # exact on the total-order encoding, decode via gather of argmin
            enc = _order._fixed_to_u64(Column(col.dtype, data=sval))
            enc = jnp.where(svalid, enc,
                            jnp.where(op == "min", jnp.uint64(2**64 - 1),
                                      jnp.uint64(0)))
            red = _segment_reduce(op, enc.astype(jnp.uint64), seg, num_segments)
            data = _order.decode_minmax_bits(red, col.dtype)
            return Column(col.dtype, data=data, validity=has_any)
        red = _segment_reduce(op, sval, seg, num_segments, svalid)
        return Column(col.dtype, data=red, validity=has_any)

    if op == "collect_list":
        raise ValueError("collect_list output is ragged; it is only "
                         "available through ops.aggregate.groupby")
    raise ValueError(f"unknown aggregation {op!r}; expected one of {AGGS}")


@traced("groupby_padded")
def groupby_padded(table: Table, key_names: list, aggs: list[tuple],
                   keys_cols: list | None = None, row_mask=None):
    """Jit-able core: (key_table_padded, agg_table_padded, ngroups).

    Outputs have n rows; rows >= ngroups are padding.  Strings in VALUE
    position are unsupported (as in cudf hash aggregations).
    """
    key_cols = keys_cols if keys_cols is not None else \
        [table.column(k) for k in key_names]

    resolved = []
    for col_ref, op in aggs:
        col = col_ref if isinstance(col_ref, Column) else \
            (None if op == "count_all" else table.column(col_ref))
        resolved.append((col, op))
    agg_inputs = [c for c, _ in resolved if c is not None]
    if key_cols and key_cols[0].data is not None \
            and key_cols[0].data.shape[0] > 0 \
            and _fast_eligible(key_cols, agg_inputs) \
            and all(op in _FAST_OPS for _, op in resolved):
        return _fast_groupby_padded(key_cols, resolved, row_mask)

    skeys = [SortKey(c) for c in key_cols]
    order, seg, ngroups = _seg_ids(skeys, row_mask)
    n = order.shape[0]

    first_row_of_seg = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32), seg, n)  # n-padded
    out_keys = []
    for c in key_cols:
        if c.dtype.is_string:
            from .strings_common import to_padded_bytes
            mat, lengths = to_padded_bytes(c)
            srt = jnp.take(order, jnp.clip(first_row_of_seg, 0, n - 1))
            gm = jnp.take(mat, srt, axis=0)
            gl = jnp.take(lengths, srt)
            out_keys.append(("string", gm, gl,
                             jnp.take(c.valid_mask(), srt)))
        else:
            srt = jnp.take(order, jnp.clip(first_row_of_seg, 0, n - 1))
            data = jnp.take(c.data, srt, axis=0)
            valid = jnp.take(c.valid_mask(), srt)
            out_keys.append(("fixed", c.dtype, data, valid))

    live_sorted = None if row_mask is None else jnp.take(row_mask, order)
    out_aggs = []
    for col, op in resolved:
        if col is None:  # count_all carries no input column
            out_aggs.append(_agg_column(None, op, order, seg, n, live_sorted))
            continue
        if col.dtype.is_string and op not in ("count", "count_all"):
            raise TypeError("string value aggregation not supported")
        if col.dtype.is_string and op == "count":
            # no fixed-width buffer to gather; count validity directly
            svalid = jnp.take(col.valid_mask(), order)
            if live_sorted is not None:
                svalid = svalid & live_sorted
            out_aggs.append(Column(INT64, data=jax.ops.segment_sum(
                svalid.astype(jnp.int64), seg, n)))
        else:
            out_aggs.append(_agg_column(col, op, order, seg, n, live_sorted))
    return out_keys, out_aggs, ngroups


@functools.partial(jax.jit, static_argnums=(1, 2))
def _groupby_compiled(table: Table, key_names: tuple, aggs: tuple):
    """Fixed-width groupby_padded as ONE compiled program (key specs are
    static; Columns are pytrees, so outputs cross the jit boundary whole)."""
    out_keys, out_aggs, ngroups = groupby_padded(table, list(key_names),
                                                 list(aggs))
    key_cols = [Column(spec[1], data=spec[2], validity=spec[3])
                for spec in out_keys]  # eligibility guarantees "fixed"
    return key_cols, out_aggs, ngroups


def _host_key_segments(table: Table, key_names: list, value_col=None):
    """(order, key_bounds, pair_bounds) of the host-side key lexsort.

    The alignment contract the ragged-agg wrappers rely on: the base
    groupby's group order is ascending in the encoded key words, and so is
    this lexsort — group i of the base is segment i here.  ``key_bounds``
    marks each group's first sorted row; with ``value_col`` the sort is
    over (keys, value) and ``pair_bounds`` additionally marks each
    distinct (key, value) run (else None).  Keys encode exactly once."""
    key_cols = [table.column(k) for k in key_names]
    kwords = [np.asarray(w) for w in
              encode_keys([SortKey(c) for c in key_cols])]
    vwords = [] if value_col is None else \
        [np.asarray(w) for w in encode_keys([SortKey(value_col)])]
    order = np.lexsort(tuple(reversed(kwords + vwords)))
    n = len(order)

    def bounds_of(words):
        b = np.ones(n, np.bool_)
        if n:
            b[1:] = np.zeros(n - 1, np.bool_)
            for w in words:
                sw = w[order]
                b[1:] |= sw[1:] != sw[:-1]
        return b

    kb = bounds_of(kwords)
    pb = None if value_col is None else (kb | bounds_of(vwords))
    return order, kb, pb


def _assemble_special_aggs(base: Table, nkeys: int, aggs: list,
                           names: list | None, is_special, build) -> Table:
    """Interleave base scalar-agg columns with specially-built columns in
    the caller's agg order (shared epilogue of the ragged-agg wrappers)."""
    out_cols = list(base.columns[:nkeys])
    oi = nkeys
    for ref, op in aggs:
        if is_special(op):
            out_cols.append(build(ref))
        else:
            out_cols.append(base.columns[oi])
            oi += 1
    agg_names = names or [
        f"{op}_{ref if isinstance(ref, str) else i}"
        for i, (ref, op) in enumerate(aggs)]
    return Table(out_cols, list(base.names[:nkeys]) + list(agg_names))


def _groupby_with_collect(table: Table, key_names: list, aggs: list,
                          names: list | None) -> Table:
    """groupby with collect_list aggs: ragged output, host-compacted.

    Scalar aggs run through the normal device path; the list columns are
    built host-side over the same sorted-key segmentation
    (_host_key_segments), so group order matches.  Spark semantics: null
    elements are dropped; empty groups give [] not null.
    """
    others = [(r, op) for r, op in aggs if op != "collect_list"]
    base = groupby(table, key_names, others) if others else \
        groupby(table, key_names, [(key_names[0], "count_all")])
    nkeys = len(key_names)
    order, bounds, _ = _host_key_segments(table, key_names)
    n = len(order)
    starts = np.flatnonzero(bounds)

    def collect(ref) -> Column:
        col = ref if isinstance(ref, Column) else table.column(ref)
        valid = col.validity_numpy()[order]
        if col.dtype.is_string:
            vals = col.to_pylist()
            groups = [[vals[r] for r in order[a:b] if vals[r] is not None]
                      for a, b in zip(starts, np.append(starts[1:], n))]
            flat = [v for g in groups for v in g]
            child = Column.from_pylist(flat, dtype=col.dtype)
        else:
            vals = col.to_numpy()[order]
            groups = [vals[a:b][valid[a:b]]
                      for a, b in zip(starts, np.append(starts[1:], n))]
            child = Column.from_numpy(
                np.concatenate(groups) if groups else
                np.zeros(0, col.dtype.storage), dtype=col.dtype)
        lens = np.fromiter((len(g) for g in groups), np.int64, len(starts))
        offsets = np.zeros(len(starts) + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        if offsets[-1] > np.iinfo(np.int32).max:
            raise ValueError("collect_list output exceeds int32 offsets")
        return Column.list_(child, offsets.astype(np.int32))

    return _assemble_special_aggs(base, nkeys, aggs, names,
                                  lambda op: op == "collect_list", collect)


def _groupby_with_nunique(table: Table, key_names: list, aggs: list,
                          names: list | None) -> Table:
    """groupby with count(DISTINCT col) aggs (Spark nunique).

    Alignment via _host_key_segments: a lexsort over (keys, value)
    segments identically to the base groupby's group order, so each
    group's distinct-valid-value count lands at its base row.  Spark
    semantics: null values are not counted; an all-null group counts 0.
    """
    others = [(r, op) for r, op in aggs
              if op not in ("nunique", "count_distinct")]
    base = groupby(table, key_names, others) if others else \
        groupby(table, key_names, [(key_names[0], "count_all")])
    nkeys = len(key_names)
    ngroups = base.num_rows

    def nunique(ref) -> Column:
        col = table.column(ref)
        order, kb, pb = _host_key_segments(table, key_names, value_col=col)
        if len(order) == 0:
            return Column.fixed(INT64, np.zeros(0, np.int64))
        gid = np.cumsum(kb) - 1
        valid = col.validity_numpy()[order]
        take = pb & valid  # first row of each distinct non-null value
        cnt = np.bincount(gid[take], minlength=ngroups).astype(np.int64)
        return Column.fixed(INT64, cnt)

    return _assemble_special_aggs(
        base, nkeys, aggs, names,
        lambda op: op in ("nunique", "count_distinct"), nunique)


@traced("groupby")
def groupby(table: Table, key_names: list, aggs: list[tuple],
            names: list | None = None) -> Table:
    """GROUP BY key_names with aggregations [(column, op), ...] -> compact Table.

    op in {sum, min, max, mean, count, count_all, var, std, sumsq, fsum,
    first, last, collect_list} (the AGGS tuple) plus nunique /
    count_distinct (Spark count(DISTINCT col): null values not counted).
    var/std are sample (ddof=1) moments; first/last follow Spark's
    ignoreNulls=False positional semantics; collect_list drops null
    elements and returns a LIST column (host-compacted — ragged output
    can't stay padded).
    """
    # One compiled program instead of eager per-op dispatch: on remote
    # devices each eager op costs a full round trip, which turned this host
    # wrapper into minutes of latency.  Jit requires hashable static specs
    # and fixed-width columns (string keys size their padded matrices on
    # the host).
    if any(op in ("nunique", "count_distinct") for _, op in aggs):
        return _groupby_with_nunique(table, key_names, aggs, names)
    if any(op == "collect_list" for _, op in aggs):
        return _groupby_with_collect(table, key_names, aggs, names)
    jitable = all(isinstance(k, str) for k in key_names) and \
        all(isinstance(r, str) for r, _ in aggs) and \
        all(op in _FAST_OPS for _, op in aggs)
    if jitable:
        try:
            key_cols = [table.column(k) for k in key_names]
            agg_cols = [table.column(r) for r, op in aggs
                        if op != "count_all"]
            jitable = table.num_rows > 0 and \
                _fast_eligible(key_cols, agg_cols)
        except (KeyError, ValueError):
            jitable = False
    if jitable:
        out_key_cols, out_aggs, ngroups = _groupby_compiled(
            table, tuple(key_names), tuple((r, op) for r, op in aggs))
        out_keys = [("fixed", c.dtype, c.data, c.valid_mask())
                    for c in out_key_cols]
    else:
        out_keys, out_aggs, ngroups = groupby_padded(table, key_names, aggs)
    ng = int(ngroups)
    cols = []
    for spec in out_keys:
        if spec[0] == "string":
            _, gm, gl, gv = spec
            gm, gl, gv = (np.asarray(gm)[:ng], np.asarray(gl)[:ng],
                          np.asarray(gv)[:ng])
            from .strings_common import from_padded_bytes
            has_null = not gv.all()
            cols.append(from_padded_bytes(gm, gl, gv if has_null else None))
        else:
            _, dtype, data, valid = spec
            v = np.asarray(valid)[:ng]
            cols.append(Column(dtype, data=jnp.asarray(np.asarray(data)[:ng]),
                               validity=jnp.asarray(v) if not v.all() else None))
    for c in out_aggs:
        data = jnp.asarray(np.asarray(c.data)[:ng])
        valid = None if c.validity is None else \
            jnp.asarray(np.asarray(c.validity)[:ng])
        cols.append(Column(c.dtype, data=data, validity=valid))
    key_names_out = [k if isinstance(k, str) else f"key{i}"
                     for i, k in enumerate(key_names)]
    agg_names = names or [
        f"{op}_{ref if isinstance(ref, str) else i}"
        for i, (ref, op) in enumerate(aggs)]
    return Table(cols, key_names_out + list(agg_names))
