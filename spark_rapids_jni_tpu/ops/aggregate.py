"""GroupBy aggregation: sort-based segmented reduction.

The TPU-native answer to cudf's hash_groupby (what the reference's Spark plans
call HashAggregate — BASELINE.json configs[2]).  A hash table with open
addressing is a pointer-chasing structure XLA can't vectorize; sorting by the
group keys and running segmented reductions is the same O(n log n) work
expressed as radix sort + scans, which map perfectly onto the VPU:

    1. order  = lexsort(key encodings)          (ops/order.py)
    2. bounds = sorted row != previous row      (rows_differ_from_prev)
    3. seg_id = cumsum(bounds) - 1
    4. each aggregation = jax.ops.segment_<op>(values[order], seg_id)

``groupby_padded`` is the fully jit-able core: output padded to n rows with a
group-count scalar (static shapes for pjit pipelines — the distributed
partial-aggregation path).  ``groupby`` compacts at the host boundary.

Null semantics match Spark: null keys form their own group (nulls equal in
GROUP BY); null values are excluded from sum/min/max/mean/count(col), while
count(*) counts rows.  sum/mean over FLOAT64 use the hardware float
approximation (float_values); min/max over FLOAT64 run on the total-order bit
encoding and are exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..dtypes import DType, TypeId, INT64, FLOAT64
from .order import SortKey, encode_keys, rows_differ_from_prev
from .selection import gather_table
from . import order as _order
from ..utils.tracing import traced

AGGS = ("sum", "min", "max", "mean", "count", "count_all")


def _seg_ids(keys: list[SortKey], row_mask=None):
    """Sort+segment the rows; masked-out rows sort last as dead groups.

    With ``row_mask`` (padded pipelines, e.g. post-shuffle), the returned
    ``ngroups`` counts only live groups — dead rows sort after every live row
    via a primary mask word, so live groups occupy seg ids [0, ngroups).
    """
    words = encode_keys(keys)
    if row_mask is not None:
        words = [(~row_mask).astype(jnp.uint64)] + words  # live rows first
    order = jnp.lexsort(tuple(reversed(words)))
    bounds = rows_differ_from_prev(words, order)
    seg = jnp.cumsum(bounds.astype(jnp.int32)) - 1
    if order.shape[0] == 0:
        return order, seg, jnp.int32(0)
    if row_mask is None:
        ngroups = seg[-1] + 1
    else:
        live_sorted = jnp.take(row_mask, order)
        ngroups = jnp.sum((bounds & live_sorted).astype(jnp.int32))
    return order, seg, ngroups


def _segment_reduce(op: str, vals, seg, num_segments: int, valid=None):
    if valid is None:
        valid = jnp.ones(vals.shape[:1], jnp.bool_)
    if op == "sum":
        z = jnp.zeros((), vals.dtype)
        contrib = jnp.where(valid, vals, z)
        return jax.ops.segment_sum(contrib, seg, num_segments)
    if op == "min":
        big = jnp.iinfo(vals.dtype).max if jnp.issubdtype(vals.dtype, jnp.integer) \
            else jnp.inf
        contrib = jnp.where(valid, vals, jnp.asarray(big, vals.dtype))
        return jax.ops.segment_min(contrib, seg, num_segments)
    if op == "max":
        small = jnp.iinfo(vals.dtype).min if jnp.issubdtype(vals.dtype, jnp.integer) \
            else -jnp.inf
        contrib = jnp.where(valid, vals, jnp.asarray(small, vals.dtype))
        return jax.ops.segment_max(contrib, seg, num_segments)
    raise ValueError(op)


def _agg_column(col: Column, op: str, order, seg, num_segments: int,
                live_sorted=None):
    """One aggregation over sorted rows.

    ``live_sorted``: sorted-order live-row mask for padded pipelines; the
    single place dead rows are excluded from every op, count_all included.
    """
    if op == "count_all":
        live = jnp.ones(order.shape, jnp.int64) if live_sorted is None \
            else live_sorted.astype(jnp.int64)
        return Column(INT64, data=jax.ops.segment_sum(live, seg, num_segments))

    sval = None if col.data is None else jnp.take(col.data, order, axis=0)
    svalid = jnp.take(col.valid_mask(), order)
    if live_sorted is not None:
        svalid = svalid & live_sorted
    counts = jax.ops.segment_sum(svalid.astype(jnp.int64), seg, num_segments)

    if op == "count":
        return Column(INT64, data=counts)

    has_any = counts > 0
    tid = col.dtype.id
    if op in ("sum", "mean"):
        if tid == TypeId.FLOAT64:
            vals = Column(col.dtype, data=sval).float_values()
        elif tid == TypeId.FLOAT32:
            vals = jnp.asarray(sval, jnp.float64)
        elif col.dtype.is_decimal:
            vals = sval.astype(jnp.int64)  # unscaled sum keeps the scale
        else:
            vals = sval.astype(jnp.int64)  # Spark widens integral sums to long
        s = _segment_reduce("sum", vals, seg, num_segments, svalid)
        if op == "mean":
            m = s.astype(jnp.float64) / jnp.maximum(counts, 1).astype(jnp.float64)
            if col.dtype.is_decimal:
                m = m * (10.0 ** col.dtype.scale)
            return Column.fixed(FLOAT64, m, validity=has_any)
        if tid in (TypeId.FLOAT32, TypeId.FLOAT64):
            return Column.fixed(FLOAT64, s, validity=has_any)
        out_dtype = col.dtype if col.dtype.is_decimal else INT64
        return Column(out_dtype, data=s, validity=has_any)

    if op in ("min", "max"):
        if tid in (TypeId.FLOAT32, TypeId.FLOAT64):
            # exact on the total-order encoding, decode via gather of argmin
            enc = _order._fixed_to_u64(Column(col.dtype, data=sval))
            enc = jnp.where(svalid, enc,
                            jnp.where(op == "min", jnp.uint64(2**64 - 1),
                                      jnp.uint64(0)))
            red = _segment_reduce(op, enc.astype(jnp.uint64), seg, num_segments)
            # invert the order transform
            if tid == TypeId.FLOAT64:
                sign = (red & (jnp.uint64(1) << jnp.uint64(63))) != 0
                bits = jnp.where(sign, red ^ (jnp.uint64(1) << jnp.uint64(63)),
                                 ~red)
                data = bits.astype(jnp.int64)
                return Column(col.dtype, data=data, validity=has_any)
            sign = (red & jnp.uint64(0x80000000)) != 0
            bits32 = jnp.where(sign, red ^ jnp.uint64(0x80000000),
                               ~red & jnp.uint64(0xFFFFFFFF))
            data = jax.lax.bitcast_convert_type(
                bits32.astype(jnp.uint32), jnp.float32)
            return Column(col.dtype, data=data, validity=has_any)
        red = _segment_reduce(op, sval, seg, num_segments, svalid)
        return Column(col.dtype, data=red, validity=has_any)

    raise ValueError(f"unknown aggregation {op!r}; expected one of {AGGS}")


@traced("groupby_padded")
def groupby_padded(table: Table, key_names: list, aggs: list[tuple],
                   keys_cols: list | None = None, row_mask=None):
    """Jit-able core: (key_table_padded, agg_table_padded, ngroups).

    Outputs have n rows; rows >= ngroups are padding.  Strings in VALUE
    position are unsupported (as in cudf hash aggregations).
    """
    key_cols = keys_cols if keys_cols is not None else \
        [table.column(k) for k in key_names]
    skeys = [SortKey(c) for c in key_cols]
    order, seg, ngroups = _seg_ids(skeys, row_mask)
    n = order.shape[0]

    first_row_of_seg = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32), seg, n)  # n-padded
    out_keys = []
    for c in key_cols:
        if c.dtype.is_string:
            from .strings_common import to_padded_bytes
            mat, lengths = to_padded_bytes(c)
            srt = jnp.take(order, jnp.clip(first_row_of_seg, 0, n - 1))
            gm = jnp.take(mat, srt, axis=0)
            gl = jnp.take(lengths, srt)
            out_keys.append(("string", gm, gl,
                             jnp.take(c.valid_mask(), srt)))
        else:
            srt = jnp.take(order, jnp.clip(first_row_of_seg, 0, n - 1))
            data = jnp.take(c.data, srt, axis=0)
            valid = jnp.take(c.valid_mask(), srt)
            out_keys.append(("fixed", c.dtype, data, valid))

    live_sorted = None if row_mask is None else jnp.take(row_mask, order)
    out_aggs = []
    for col_ref, op in aggs:
        col = table.column(col_ref) if not isinstance(col_ref, Column) else col_ref
        if col.dtype.is_string and op not in ("count", "count_all"):
            raise TypeError("string value aggregation not supported")
        if col.dtype.is_string and op == "count":
            # no fixed-width buffer to gather; count validity directly
            svalid = jnp.take(col.valid_mask(), order)
            if live_sorted is not None:
                svalid = svalid & live_sorted
            out_aggs.append(Column(INT64, data=jax.ops.segment_sum(
                svalid.astype(jnp.int64), seg, n)))
        else:
            out_aggs.append(_agg_column(col, op, order, seg, n, live_sorted))
    return out_keys, out_aggs, ngroups


@traced("groupby")
def groupby(table: Table, key_names: list, aggs: list[tuple],
            names: list | None = None) -> Table:
    """GROUP BY key_names with aggregations [(column, op), ...] -> compact Table.

    op in {sum, min, max, mean, count, count_all}.
    """
    out_keys, out_aggs, ngroups = groupby_padded(table, key_names, aggs)
    ng = int(ngroups)
    cols = []
    for spec in out_keys:
        if spec[0] == "string":
            _, gm, gl, gv = spec
            gm, gl, gv = (np.asarray(gm)[:ng], np.asarray(gl)[:ng],
                          np.asarray(gv)[:ng])
            from .strings_common import from_padded_bytes
            has_null = not gv.all()
            cols.append(from_padded_bytes(gm, gl, gv if has_null else None))
        else:
            _, dtype, data, valid = spec
            v = np.asarray(valid)[:ng]
            cols.append(Column(dtype, data=jnp.asarray(np.asarray(data)[:ng]),
                               validity=jnp.asarray(v) if not v.all() else None))
    for c in out_aggs:
        data = jnp.asarray(np.asarray(c.data)[:ng])
        valid = None if c.validity is None else \
            jnp.asarray(np.asarray(c.validity)[:ng])
        cols.append(Column(c.dtype, data=data, validity=valid))
    key_names_out = [k if isinstance(k, str) else f"key{i}"
                     for i, k in enumerate(key_names)]
    agg_names = names or [
        f"{op}_{ref if isinstance(ref, str) else i}"
        for i, (ref, op) in enumerate(aggs)]
    return Table(cols, key_names_out + list(agg_names))
